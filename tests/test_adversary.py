"""Tier-3 adversary tests: attacks from the reference's spam suite
(gossipsub_spam_test.go) and the sybil squatter (gossipsub_test.go:1777-1811),
expressed as injected behavior vectors per survey §7 stage 6.

Attack injection model: per-round adversary actions (IHAVE spam, GRAFT
flood) are written into the attacker's control outboxes between steps —
the vectorized analogue of the reference's `newMockGS` raw-wire fakes
(gossipsub_spam_test.go:765-813). Standing behavior (never forwarding data)
is the static `adversary_no_forward` vector of `make_gossipsub_step`.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net

M = 32  # msg slots (single bitset word)


def p7_score_params():
    """P7-focused params: behaviour penalty bites immediately, the rest
    benign (P3/P3b off so only the attack moves the score)."""
    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.9,
    )
    return PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )


def build(n=20, d=6, seed=0, score=True, score_params=None, params=None,
          heartbeat_every=1, no_forward=None):
    topo = graph.random_connect(n, d, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    p = params or GossipSubParams()
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0,
        publish_threshold=-4.0,
        graylist_threshold=-8.0,
        accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(p, thr, score_enabled=score,
                                heartbeat_every=heartbeat_every)
    sp = (score_params or p7_score_params()) if score else None
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               adversary_no_forward=no_forward)
    return topo, net, cfg, st, step


def edge_to(topo, j, target):
    """Neighbor-slot index k such that nbr[j, k] == target (or None)."""
    for k in range(topo.max_degree):
        if topo.nbr_ok[j, k] and topo.nbr[j, k] == target:
            return k
    return None


def pub(o, t=0, valid=True, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    pv = np.zeros(p, bool)
    po[0], pt[0], pv[0] = o, t, valid
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def run(step, st, k):
    a = no_publish()
    for _ in range(k):
        st = step(st, *a)
    return st


def inject_ihave(st, attacker, slot):
    """Attacker advertises message `slot` on all its edges this round
    (the IHAVE-spam move, gossipsub_spam_test.go:290)."""
    ih = np.zeros(np.asarray(st.ihave_out).shape, np.uint32)
    ih[attacker, :, slot // 32] = np.uint32(1 << (slot % 32))
    return st.replace(ihave_out=jnp.asarray(ih))


def inject_graft(st, attacker, k_edge):
    """Attacker sends GRAFT on edge k_edge for topic slot 0 this round
    (the GRAFT-flood move, gossipsub_spam_test.go:365)."""
    g = np.asarray(st.graft_out).copy()
    g[attacker, 0, k_edge] = True
    return st.replace(graft_out=jnp.asarray(g))


def withheld_publish(st, step, attacker):
    """Attacker originates a valid message it will never forward; returns
    (state, slot) with the message resident only at the attacker."""
    st = step(st, *pub(attacker))
    origin = np.asarray(st.core.msgs.origin)
    slots = np.where(origin == attacker)[0]
    assert len(slots) == 1
    return st, int(slots[0])


# ---------------------------------------------------------------------------
# IHAVE spam: flood-protection caps (handleIHave gossipsub.go:624-633)


def test_ihave_spam_batch_cap():
    """A spammer IHAVEing every round gets at most MaxIHaveMessages IWANT
    batches per heartbeat period (gossipsub.go:624-628)."""
    params = dataclasses.replace(GossipSubParams(), max_ihave_messages=3)
    topo, net, cfg, st, step = build(
        score=False, params=params, heartbeat_every=8,
        no_forward=np.arange(20) == 5,
    )
    attacker = 5
    st = run(step, st, 8)  # one full period of mesh warmup
    st, slot = withheld_publish(st, step, attacker)

    victims = [topo.nbr[attacker, k] for k in range(topo.max_degree)
               if topo.nbr_ok[attacker, k]]
    asks_per_victim = {v: 0 for v in victims}
    for _ in range(16):  # two heartbeat periods of spam
        st = inject_ihave(st, attacker, slot)
        st = step(st, *no_publish())
        iw = np.asarray(st.iwant_out)
        for v in victims:
            k = edge_to(topo, v, attacker)
            if iw[v, k].any():
                asks_per_victim[v] += 1

    # per period the ask count is capped at max_ihave_messages; two periods
    assert max(asks_per_victim.values()) >= 2  # the attack does elicit asks
    assert max(asks_per_victim.values()) <= 2 * 3


def test_ihave_spam_ask_budget():
    """MaxIHaveLength also caps total mids asked per period
    (gossipsub.go:630-633,655-658)."""
    params = dataclasses.replace(
        GossipSubParams(), max_ihave_messages=100, max_ihave_length=2
    )
    topo, net, cfg, st, step = build(
        score=False, params=params, heartbeat_every=8,
        no_forward=np.arange(20) == 5,
    )
    attacker = 5
    st = run(step, st, 8)
    st, slot = withheld_publish(st, step, attacker)

    victims = [topo.nbr[attacker, k] for k in range(topo.max_degree)
               if topo.nbr_ok[attacker, k]]
    asks = {v: 0 for v in victims}
    for _ in range(8):  # within one heartbeat period
        st = inject_ihave(st, attacker, slot)
        st = step(st, *no_publish())
        iw = np.asarray(st.iwant_out)
        for v in victims:
            k = edge_to(topo, v, attacker)
            if iw[v, k].any():
                asks[v] += 1
    assert max(asks.values()) <= 2


# ---------------------------------------------------------------------------
# IWANT promise break -> P7 (gossip_tracer.go + gossipsub.go:1578-1583)


def test_promise_break_applies_p7_and_prunes():
    adv = np.arange(20) == 4
    topo, net, cfg, st, step = build(no_forward=adv, seed=2)
    attacker = 4
    st = run(step, st, 8)
    st, slot = withheld_publish(st, step, attacker)

    for _ in range(12):
        st = inject_ihave(st, attacker, slot)
        st = step(st, *no_publish())

    bp = np.asarray(st.score.bp)
    scores = np.asarray(st.scores)
    mesh = np.asarray(st.mesh[:, 0, :])
    hits = 0
    for j in range(net.n_peers):
        k = edge_to(topo, j, attacker)
        if k is None:
            continue
        hits += 1
        # the victim accumulated broken-promise behaviour penalty ...
        assert bp[j, k] > 0, (j, k)
        # ... P7 made its score of the attacker negative ...
        assert scores[j, k] < 0, (j, k, scores[j, k])
        # ... and the heartbeat dropped the attacker from its mesh
        assert not mesh[j, k]
    assert hits > 0
    assert int(st.mesh[attacker].sum()) == 0


def test_fulfilled_promise_no_penalty():
    """An honest gossiper that serves its IWANTs accrues no P7: promises
    are fulfilled on delivery (gossip_tracer.go DeliverMessage)."""
    topo, net, cfg, st, step = build(seed=3)
    st = run(step, st, 8)
    origin = 2
    st = step(st, *pub(origin))
    st = run(step, st, 10)  # gossip + IWANT + service all complete
    assert float(np.asarray(st.score.bp).max()) == 0.0
    # and the message actually reached everyone
    have = np.asarray(bitset.unpack(st.core.dlv.have, M))
    slot = int(np.where(np.asarray(st.core.msgs.origin) == origin)[0][0])
    assert have[:, slot].all()


# ---------------------------------------------------------------------------
# GRAFT flood during backoff (handleGraft gossipsub.go:753-770)


def test_graft_during_backoff_penalized():
    adv = np.arange(20) == 6
    # gentle P7 weight: with -10 the very first offense graylists the
    # attacker and later GRAFTs are dropped at ingress (also correct, but
    # here we want to watch the flood accumulate)
    sp = dataclasses.replace(p7_score_params(), behaviour_penalty_weight=-0.1)
    topo, net, cfg, st, step = build(no_forward=adv, seed=4, score_params=sp)
    attacker = 6
    victim = None
    for k in range(topo.max_degree):
        if topo.nbr_ok[attacker, k]:
            victim = int(topo.nbr[attacker, k])
            k_av = k
            break
    k_va = edge_to(topo, victim, attacker)
    st = run(step, st, 4)

    # the victim recently pruned the attacker: standing backoff
    tick = int(st.core.tick)
    be = np.asarray(st.backoff_expire).copy()
    bpres = np.asarray(st.backoff_present).copy()
    be[victim, 0, k_va] = tick + cfg.prune_backoff_ticks
    bpres[victim, 0, k_va] = True
    mesh = np.asarray(st.mesh).copy()
    mesh[victim, 0, k_va] = False
    mesh[attacker, 0, k_av] = False
    st = st.replace(
        backoff_expire=jnp.asarray(be),
        backoff_present=jnp.asarray(bpres),
        mesh=jnp.asarray(mesh),
    )

    for _ in range(6):
        st = inject_graft(st, attacker, k_av)
        st = step(st, *no_publish())

    bp = np.asarray(st.score.bp)
    scores = np.asarray(st.scores)
    # each offending GRAFT inside the flood threshold counts twice
    # (gossipsub.go:760-768): 6 grafts, decay 0.9 => well above 6
    assert bp[victim, k_va] > 6.0, bp[victim, k_va]
    assert scores[victim, k_va] < 0
    # and none of them got the attacker into the mesh; backoff refreshed
    assert not bool(st.mesh[victim, 0, k_va])
    assert int(np.asarray(st.backoff_expire)[victim, 0, k_va]) >= tick + cfg.prune_backoff_ticks


# ---------------------------------------------------------------------------
# sybil squatters: grafted-but-silent peers starve the mesh -> P3 deficit
# (score.go:292-298) -> pruned; the honest overlay keeps delivering
# (gossipsub_test.go:1777-1811 TestGossipsubAttackSpamSquatter analogue)


def test_sybil_squatters_pruned_and_delivery_survives():
    n, d = 40, 10
    squatters = np.arange(n) >= 32  # 8 sybils
    # P3 tuned to the traffic volume (as the reference requires of its
    # users): threshold well below the per-edge delivery rate so honest
    # mesh members clear it, activation long enough to accumulate credit
    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=0.5,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_decay=0.9,
        mesh_message_deliveries_cap=20.0,
        mesh_message_deliveries_threshold=0.5,
        mesh_message_deliveries_window=2.0,
        mesh_message_deliveries_activation=8.0,
        mesh_failure_penalty_weight=-1.0,
        mesh_failure_penalty_decay=0.9,
    )
    sp = PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )
    topo, net, cfg, st, step = build(
        n=n, d=d, seed=6, score_params=sp, no_forward=squatters
    )
    st = run(step, st, 6)

    rng = np.random.default_rng(0)
    for i in range(40):
        po = rng.integers(0, 32, size=4).astype(np.int32)  # 4 msgs/round
        pt = np.zeros(4, np.int32)
        pv = np.ones(4, bool)
        st = step(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))

    scores = np.asarray(st.scores)
    mesh = np.asarray(st.mesh[:, 0, :])
    # honest peers scored their squatter mesh-neighbors negative (P3
    # deficit^2 after activation) and pruned every one of them
    squat_edges = 0
    for j in range(32):
        for k in range(topo.max_degree):
            if topo.nbr_ok[j, k] and squatters[topo.nbr[j, k]]:
                squat_edges += 1
                assert not mesh[j, k], (j, k, scores[j, k])
    assert squat_edges > 0
    # P3b sticky mesh-failure penalty recorded on pruned squatter edges
    assert float(np.asarray(st.score.mfp).max()) > 0
    # the honest overlay still delivers end-to-end
    st = step(st, *pub(1))
    st = run(step, st, 8)
    slot = int(np.where(np.asarray(st.core.msgs.origin) == 1)[0][-1])
    have = np.asarray(bitset.unpack(st.core.dlv.have, M))
    assert have[:32, slot].all(), "honest delivery must survive the sybils"


# ---------------------------------------------------------------------------
# IWANT flood: the retransmission cap (handleIWant gossipsub.go:695-707,
# the `iwantEverything` greedy client, gossipsub_test.go:2009)


def test_iwant_flood_served_at_most_retransmission_cap():
    topo, net, cfg, st, step = build(n=12, d=5, seed=3, score=False)
    # victim publishes; the message sits in its mcache window
    victim = 0
    attacker = int(topo.nbr[victim][topo.nbr_ok[victim]][0])
    k_att = edge_to(topo, attacker, victim)  # attacker's edge toward victim
    st, slot = withheld_publish(st, step, victim)
    # use a long history so the window doesn't expire before the cap bites
    word, bit = slot // 32, np.uint32(1 << (slot % 32))

    served = 0
    for _ in range(cfg.gossip_retransmission + 3):
        # attacker re-requests the message from the victim every round
        # (raw-wire greedy client), and pretends it never received it
        iw = np.zeros(np.asarray(st.iwant_out).shape, np.uint32)
        iw[attacker, k_att, word] = bit
        have = np.asarray(st.core.dlv.have).copy()
        have[attacker, word] &= ~bit
        st = st.replace(
            iwant_out=jnp.asarray(iw),
            core=st.core.replace(dlv=st.core.dlv.replace(have=jnp.asarray(have))),
        )
        st = step(st, *no_publish())
        # the bit was cleared before the step, so holding it now means the
        # victim served this round's request
        if np.asarray(st.core.dlv.have)[attacker, word] & bit:
            served += 1

    assert served == cfg.gossip_retransmission, (
        served, cfg.gossip_retransmission)


# ---------------------------------------------------------------------------
# GRAFT for an unknown topic: silently ignored (spam hardening,
# handleGraft gossipsub.go:727-733 — no mesh change, no PRUNE, no
# backoff, no penalty; TestGossipsubAttackGRAFTNonExistentTopic,
# gossipsub_spam_test.go:290)


def test_graft_unknown_topic_ignored():
    n = 16
    topo = graph.random_connect(n, 5, seed=3)
    mask = np.zeros((n, 2), bool)
    mask[:, 0] = True          # everyone joins topic 0
    attacker = 1
    mask[attacker, 1] = True   # ONLY the attacker knows topic 1
    subs = graph.subscribe_mask(mask)
    net = Net.build(topo, subs)
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0,
        graylist_threshold=-8.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(GossipSubParams(), thr, score_enabled=True)
    sp = p7_score_params()
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=3)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    st = run(step, st, 10)

    s1 = int(subs.slot_of[attacker, 1])
    assert s1 >= 0
    pre_backoff = np.asarray(st.backoff_present).copy()
    pre_scores = np.asarray(st.scores).copy()

    for _ in range(5):
        # GRAFT topic 1 toward every neighbor — none of them joined it
        g = np.zeros(np.asarray(st.graft_out).shape, bool)
        g[attacker, s1, :] = True
        st = st.replace(graft_out=jnp.asarray(g))
        st = step(st, *no_publish())

    # no victim meshed the attacker on a slot it doesn't have, no backoff
    # was created anywhere, and nobody's opinion of anyone moved
    post_backoff = np.asarray(st.backoff_present)
    assert (post_backoff == pre_backoff).all(), "unknown-topic GRAFT must not create backoff"
    post_scores = np.asarray(st.scores)
    assert np.array_equal(post_scores, pre_scores), "unknown-topic GRAFT must not move scores"
    # attacker's own mesh for topic 1 stays empty (nobody to graft)
    assert int(np.asarray(st.mesh)[attacker, s1].sum()) == 0


# =========================================================================
# The adversary PLANE (chaos/adversary.py, docs/DESIGN.md §13): scheduled
# vectorized attacker populations driving the same behaviors as engine
# hooks — masked variants of the step math — rather than between-step
# host injection. Elision-when-off is bit-exact on every engine; the
# behaviors reproduce the manual-injection outcomes above end to end.

import jax

from go_libp2p_pubsub_tpu import checkpoint
from go_libp2p_pubsub_tpu.chaos import adversary as adversary_mod
from go_libp2p_pubsub_tpu.chaos.adversary import Adversary, AttackScenario
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
    make_gossipsub_phase_step,
)
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.state import SimState
from go_libp2p_pubsub_tpu.trace.events import EV

import pytest


def _assert_trees_equal(a, b, what="", skip_events_entry=None):
    la, paths = jax.tree_util.tree_leaves(a), \
        jax.tree_util.tree_flatten_with_path(a)[0]
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb), f"{what}: leaf count differs"
    for (path, xa), xb in zip(paths, lb):
        name = jax.tree_util.keystr(path)
        if jnp.issubdtype(getattr(xa, "dtype", None), jax.dtypes.prng_key):
            xa, xb = jax.random.key_data(xa), jax.random.key_data(xb)
        xa, xb = np.asarray(xa), np.asarray(xb)
        if skip_events_entry is not None and name.endswith(".events"):
            xa = np.delete(xa, skip_events_entry)
            xb = np.delete(xb, skip_events_entry)
        assert np.array_equal(xa, xb), f"{what}{name} differs"


def _schedule(rounds, seed=0, n=32, m=32, width=4):
    rng = np.random.default_rng(seed)
    po = rng.integers(0, n, size=(rounds, width)).astype(np.int32)
    pt = np.zeros((rounds, width), np.int32)
    pv = np.ones((rounds, width), bool)
    return po, pt, pv


def _off_population(n):
    """Two distinct all-off shapes: no sybils, and sybils with every
    behavior empty — both must resolve to None (full elision)."""
    return (
        Adversary(n, np.zeros(n, bool), behaviors=("drop_forward",
                                                   "lie_ihave")),
        Adversary(n, np.arange(n) < 4, behaviors=()),
    )


def test_adversary_resolve_elides_off_populations():
    for off in _off_population(16):
        assert adversary_mod.resolve(off) is None
    live = Adversary(16, np.arange(16) < 4)
    assert adversary_mod.resolve(live) is live
    with pytest.raises(adversary_mod.AdversaryError):
        Adversary(16, np.arange(16) < 4, behaviors=("no_such_attack",))
    with pytest.raises(adversary_mod.AdversaryError):
        # behavior masks cannot extend the faction
        Adversary(16, np.arange(16) < 4,
                  masks={"drop_forward": np.arange(16) >= 4})
    with pytest.raises(adversary_mod.AdversaryError):
        # censorship needs its target set
        Adversary(16, np.arange(16) < 4, behaviors=("censor",))


def test_attack_scenario_build_deterministic_and_hashed():
    sc = AttackScenario(n_peers=24, sybil_fraction=0.25,
                        behaviors=("drop_forward", "graft_spam"),
                        onset=5, ramp_rounds=6, seed=3)
    a, b = sc.build(), sc.build()
    assert np.array_equal(a.is_sybil, b.is_sybil)
    assert np.array_equal(a.onset, b.onset)
    assert a.is_sybil.sum() == 6  # top 25% of the id space
    idx = np.nonzero(a.is_sybil)[0]
    assert (a.onset[idx] >= 5).all() and (a.onset[idx] < 11).all()
    assert sc.scenario_hash() == sc.scenario_hash()
    sc2 = dataclasses.replace(sc, onset=6)
    assert sc.scenario_hash() != sc2.scenario_hash()
    assert sc.events()[0][1] == "AttackOnset"


def test_attack_scenario_surround_targets_fraction():
    topo = graph.random_connect(32, 6, seed=7)
    net = Net.build(topo, graph.subscribe_all(32, 1))
    sc = AttackScenario(n_peers=32, targets=(0, 1), surround_targets=True,
                        surround_fraction=0.5,
                        behaviors=("drop_forward", "graft_spam"), seed=7)
    adv = sc.build(net)
    nbr, ok = np.asarray(net.nbr), np.asarray(net.nbr_ok)
    neighborhood = set()
    for t in (0, 1):
        neighborhood.update(np.unique(nbr[t][ok[t]]).tolist())
    sybs = set(np.nonzero(adv.is_sybil)[0].tolist())
    assert sybs and sybs <= neighborhood
    assert not adv.is_sybil[0] and not adv.is_sybil[1]  # victims stay honest
    # graft spam is restricted to edges toward the victim set
    assert adv.graft_targets is not None
    with pytest.raises(adversary_mod.AdversaryError):
        sc.build()  # needs the topology


def _adv_build(n=32, seed=1, m=32):
    topo = graph.random_connect(n, 5, seed=seed)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0,
        graylist_threshold=-8.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(GossipSubParams(), thr, score_enabled=False)
    return topo, net, cfg


def test_adversary_off_bitexact_per_round():
    n = 32
    _topo, net, cfg = _adv_build(n)
    po, pt, pv = _schedule(8, seed=5, n=n)
    offs = (None,) + _off_population(n)
    outs = []
    for adv in offs:
        st = GossipSubState.init(net, M, cfg, seed=5)
        step = make_gossipsub_step(cfg, net, adversary=adv)
        for i in range(8):
            st = step(st, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                      jnp.asarray(pv[i]))
        outs.append(st)
    _assert_trees_equal(outs[0], outs[1], "off-per-round/")
    _assert_trees_equal(outs[0], outs[2], "off-per-round-empty/")


@pytest.mark.slow
@pytest.mark.parametrize("r", [4, 8])
def test_adversary_off_bitexact_phase_stacked(r):
    """Adversary-off elision on the phase engine's stacked coalesced
    wire path (cfg.wire_coalesced default) — bit-exact vs a build that
    never saw the parameter (the chaos-plane phase elision pattern)."""
    n = 32
    _topo, net, cfg = _adv_build(n)
    rounds = 2 * r
    po, pt, pv = _schedule(rounds, seed=5, n=n)
    outs = []
    for adv in (None, _off_population(n)[0]):
        st = GossipSubState.init(net, M, cfg, seed=5)
        pstep = make_gossipsub_phase_step(cfg, net, r, adversary=adv)
        for p in range(rounds // r):
            sl = slice(p * r, (p + 1) * r)
            st = pstep(st, jnp.asarray(po[sl]), jnp.asarray(pt[sl]),
                       jnp.asarray(pv[sl]), do_heartbeat=True)
        outs.append(st)
    _assert_trees_equal(outs[0], outs[1], f"off-phase-r{r}/")


def test_adversary_off_bitexact_floodsub_randomsub():
    n = 32
    _topo, net, _cfg = _adv_build(n)
    po, pt, pv = _schedule(6, seed=6, n=n)
    outs = []
    for adv in (None,) + _off_population(n):
        st = SimState.init(n, M, seed=2, k=net.max_degree)
        for i in range(6):
            st = floodsub_step(net, st, jnp.asarray(po[i]),
                               jnp.asarray(pt[i]), jnp.asarray(pv[i]),
                               adversary=adv)
        outs.append(st)
    _assert_trees_equal(outs[0], outs[1], "off-flood/")
    _assert_trees_equal(outs[0], outs[2], "off-flood-empty/")
    outs = []
    for adv in (None, _off_population(n)[0]):
        st = SimState.init(n, M, seed=3, k=net.max_degree)
        step = make_randomsub_step(net, adversary=adv)
        for i in range(6):
            st = step(st, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                      jnp.asarray(pv[i]))
        outs.append(st)
    _assert_trees_equal(outs[0], outs[1], "off-randomsub/")


def test_attacked_phase_r1_matches_per_round():
    """Under an active multi-behavior attack, the r=1 phase engine and
    the per-round engine agree bit-for-bit on EVERY leaf except the
    EV.ADV_DROP entry (documented engine-approximate attribution: the
    per-round engines count receiver-side after their gates, the phase
    engine sender-side before them)."""
    n = 32
    _topo, net, cfg = _adv_build(n)
    po, pt, pv = _schedule(8, seed=4, n=n)
    adv = AttackScenario(
        n_peers=n, sybil_fraction=0.25, onset=2,
        behaviors=("drop_forward", "lie_ihave", "graft_spam"),
    ).build()
    st1 = GossipSubState.init(net, M, cfg, seed=4)
    s1 = make_gossipsub_step(cfg, net, adversary=adv)
    for i in range(8):
        st1 = s1(st1, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                 jnp.asarray(pv[i]))
    st2 = GossipSubState.init(net, M, cfg, seed=4)
    s2 = make_gossipsub_phase_step(cfg, net, 1, adversary=adv)
    for i in range(8):
        st2 = s2(st2, jnp.asarray(po[i][None]), jnp.asarray(pt[i][None]),
                 jnp.asarray(pv[i][None]), do_heartbeat=True)
    assert int(st1.core.events[EV.ADV_DROP]) > 0
    _assert_trees_equal(st1, st2, "attacked-r1/",
                        skip_events_entry=int(EV.ADV_DROP))


def test_drop_forward_schedule_window():
    """The ADV_DROP counter (and hence the masking) moves ONLY inside
    the [onset, stop) activity window — and the run resumes honest
    forwarding after stop."""
    n = 24
    topo = graph.random_connect(n, 5, seed=2)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds())
    adv = Adversary(n, np.arange(n) < 6, behaviors=("drop_forward",),
                    onset=4, stop=8)
    st = GossipSubState.init(net, M, cfg, seed=2)
    step = make_gossipsub_step(cfg, net, adversary=adv)
    po, pt, pv = _schedule(14, seed=2, n=n)
    drops = []
    for i in range(14):
        st = step(st, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                  jnp.asarray(pv[i]))
        drops.append(int(st.core.events[EV.ADV_DROP]))
    deltas = np.diff([0] + drops)
    assert (deltas[:4] == 0).all(), deltas
    assert deltas[4:8].sum() > 0, deltas
    assert (deltas[9:] == 0).all(), deltas  # round 8 may still count the
    # outbox written at tick 7 — activity is evaluated at transmit time,
    # so from round 9 on nothing moves


def test_lie_ihave_engine_driven_breaks_promises():
    """The in-engine lie-in-IHAVE behavior reproduces the manual
    inject_ihave outcome: victims IWANT, the attacker never serves,
    promises break, P7 accrues, scores of the attacker go negative."""
    n = 24
    topo = graph.random_connect(n, 6, seed=9)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0,
        graylist_threshold=-8.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(GossipSubParams(), thr, score_enabled=True)
    sp = p7_score_params()
    attacker = 5
    adv = Adversary(n, np.arange(n) == attacker,
                    behaviors=("drop_forward", "lie_ihave"))
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=9)
    step = make_gossipsub_step(cfg, net, score_params=sp, adversary=adv)
    st = run(step, st, 6)
    # the attacker originates messages it will never forward (drop),
    # then lies about them every heartbeat: victims IWANT, nothing is
    # served, promises break (the manual withheld_publish + inject_
    # ihave sequence, engine-driven)
    for i in range(4):
        st = step(st, *pub(attacker))
        st = run(step, st, 5)
    assert int(st.core.events[EV.ADV_IHAVE_LIE]) > 0
    bp = np.asarray(st.score.bp)
    scores = np.asarray(st.scores)
    hits = 0
    for j in range(n):
        k = edge_to(topo, j, attacker)
        if k is None or j == attacker:
            continue
        if bp[j, k] > 0:
            hits += 1
            assert scores[j, k] < 0, (j, k, scores[j, k])
    # the one-promise-per-edge model adopts lazily, so not every victim
    # edge need accrue, but the neighborhood must catch the liar
    assert hits >= 2, (hits, bp.max())


def test_graft_spam_engine_driven_penalized_backoffless():
    n = 24
    topo = graph.random_connect(n, 5, seed=11)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0,
        graylist_threshold=-8.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(GossipSubParams(D=3, Dlo=2, Dhi=4,
                                                Dscore=2, Dout=1),
                                thr, score_enabled=True)
    sp = dataclasses.replace(p7_score_params(),
                             behaviour_penalty_weight=-1.0)
    attacker = 7
    mask = np.arange(n) == attacker
    adv = Adversary(n, mask, behaviors=("drop_forward", "graft_spam"))
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=11)
    step = make_gossipsub_step(cfg, net, score_params=sp, adversary=adv)
    st = run(step, st, 30)
    assert int(st.core.events[EV.ADV_GRAFT_SPAM]) > 0
    # the attacker keeps NO backoff bookkeeping (raw-wire fake)
    assert not bool(np.asarray(st.backoff_present)[attacker].any())
    assert int(np.asarray(st.backoff_expire)[attacker].max()) == 0
    # victims that pruned the spammer keep being grafted at and
    # penalize the flood (P7 accrues somewhere in the neighborhood)
    bp = np.asarray(st.score.bp)
    vic = [edge_to(topo, j, attacker) for j in range(n)]
    accr = [bp[j, k] for j, k in enumerate(vic) if k is not None
            and j != attacker]
    assert max(accr) > 0.0


def test_self_promo_pins_sybil_faction_scores():
    n = 24
    topo = graph.random_connect(n, 5, seed=13)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0,
        graylist_threshold=-8.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(GossipSubParams(), thr, score_enabled=True)
    sp = p7_score_params()
    mask = np.arange(n) >= 18
    adv = Adversary(n, mask, behaviors=("drop_forward", "self_promo"),
                    promo_score=7.5)
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=13)
    step = make_gossipsub_step(cfg, net, score_params=sp, adversary=adv)
    st = run(step, st, 10)
    scores = np.asarray(st.scores)
    nbr = np.clip(np.asarray(net.nbr), 0, None)
    ok = np.asarray(net.nbr_ok)
    syb_syb = ok & mask[nbr] & mask[:, None]
    if syb_syb.any():
        assert np.allclose(scores[syb_syb], 7.5)
    # honest opinions of sybils are NOT pinned (the defense untouched)
    att_edges = ok & mask[nbr] & ~mask[:, None]
    assert not np.allclose(scores[att_edges], 7.5)


def test_censor_masks_only_target_messages():
    n = 20
    topo = graph.random_connect(n, 5, seed=15)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds())
    censored_origin = 3
    targets = np.arange(n) == censored_origin
    mask = (np.arange(n) >= 14)
    adv = Adversary(n, mask, behaviors=("censor",), censor_origins=targets)
    st = GossipSubState.init(net, M, cfg, seed=15)
    step = make_gossipsub_step(cfg, net, adversary=adv)
    st = run(step, st, 6)
    # unit check at the mask level: only the censored origin's slots
    # are removed, only on attacker edges
    consts = adversary_mod.AdversaryConsts(adv, net)
    plane = jnp.full((n, net.max_degree, 1), 0xFFFFFFFF, jnp.uint32)
    st = step(st, *pub(censored_origin))
    st = step(st, *pub(0))
    masked, removed = consts.mask_transmit_nbr(st.core.tick, plane,
                                               st.core.msgs)
    cw = np.asarray(consts.censor_words(st.core.msgs))
    origin = np.asarray(st.core.msgs.origin)
    slots = np.where(origin == censored_origin)[0]
    assert len(slots) >= 1
    for s_ in slots:
        assert cw[s_ // 32] & np.uint32(1 << (s_ % 32))
    s0 = int(np.where(origin == 0)[0][0])
    assert not (cw[s0 // 32] & np.uint32(1 << (s0 % 32)))
    rem = np.asarray(removed)[..., 0]
    att_nbr = np.asarray(consts.active_nbr("censor", st.core.tick))
    assert (rem[~att_nbr] == 0).all()
    assert (rem[att_nbr] == cw[0]).all()
    # the run delivers non-censored traffic and counts the withheld bits
    st = run(step, st, 8)
    assert int(st.core.events[EV.ADV_DROP]) > 0
    have = np.asarray(bitset.unpack(st.core.dlv.have, M))
    assert have[:, s0].all(), "non-censored message must fully deliver"


def test_checkpoint_attacked_resume_bitexact(tmp_path):
    """Checkpoint round trip with the adversary plane armed: format v6
    UNCHANGED (the plane is stateless — activity is a pure function of
    the checkpointed tick and the static planes), and a resumed run
    reproduces the uninterrupted run's attack stream, scores, and
    invariant verdicts bit-for-bit."""
    n = 24
    topo = graph.random_connect(n, 5, seed=21)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0,
        graylist_threshold=-8.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(GossipSubParams(), thr, score_enabled=True)
    sp = p7_score_params()
    adv = AttackScenario(
        n_peers=n, sybil_fraction=0.25, onset=4, ramp_rounds=4,
        behaviors=("drop_forward", "lie_ihave", "graft_spam"), seed=21,
    ).build()
    po, pt, pv = _schedule(12, seed=21, n=n)

    def steps(st, step, lo, hi):
        for i in range(lo, hi):
            st = step(st, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                      jnp.asarray(pv[i]))
        return st

    step = make_gossipsub_step(cfg, net, score_params=sp, adversary=adv)
    full = steps(GossipSubState.init(net, M, cfg, score_params=sp, seed=21),
                 step, 0, 12)

    st = steps(GossipSubState.init(net, M, cfg, score_params=sp, seed=21),
               step, 0, 6)
    path = str(tmp_path / "attacked.npz")
    checkpoint.save(path, st)
    with np.load(path) as data:  # no version bump: v6, pytree-generic
        assert int(data["__version__"]) == 6
    template = GossipSubState.init(net, M, cfg, score_params=sp, seed=21)
    resumed = checkpoint.restore(path, template)
    resumed = steps(resumed, step, 6, 12)
    _assert_trees_equal(full, resumed, "attacked-resume/")

    # identical invariant verdicts on both final states
    from go_libp2p_pubsub_tpu.oracle import invariants as oracle_inv

    checker, names = oracle_inv.make_checker("gossipsub", net, cfg)
    due = oracle_inv.due_vector()
    va = np.asarray(checker(full, full.core.events, due))
    vb = np.asarray(checker(resumed, resumed.core.events, due))
    assert np.array_equal(va, vb)
    assert va.all(), list(zip(names, va.tolist()))


def test_invariants_hold_under_attack_small():
    """A quick all-behaviors attacked run with the PR-7 oracle checker:
    every applicable safety property holds at every check (the
    attack-smoke acceptance, tier-1 sized)."""
    n = 32
    topo = graph.random_connect(n, 5, seed=23)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0,
        graylist_threshold=-8.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(GossipSubParams(D=3, Dlo=2, Dhi=4,
                                                Dscore=2, Dout=1),
                                thr, score_enabled=True)
    sp = p7_score_params()
    adv = AttackScenario(
        n_peers=n, sybil_fraction=0.25, onset=4,
        behaviors=("drop_forward", "lie_ihave", "graft_spam",
                   "self_promo"), seed=23,
    ).build()
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=23)
    step = make_gossipsub_step(cfg, net, score_params=sp, adversary=adv)
    po, pt, pv = _schedule(24, seed=23, n=n)

    from go_libp2p_pubsub_tpu.oracle import invariants as oracle_inv

    checker, names = oracle_inv.make_checker("gossipsub", net, cfg)
    due = oracle_inv.due_vector()
    prev = jnp.copy(st.core.events)
    for i in range(24):
        st = step(st, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                  jnp.asarray(pv[i]))
        if (i + 1) % 4 == 0:
            ok = np.asarray(checker(st, prev, due))
            assert ok.all(), [nm for nm, o in zip(names, ok) if not o]
            prev = jnp.copy(st.core.events)
