"""Tier-3 adversary tests: attacks from the reference's spam suite
(gossipsub_spam_test.go) and the sybil squatter (gossipsub_test.go:1777-1811),
expressed as injected behavior vectors per survey §7 stage 6.

Attack injection model: per-round adversary actions (IHAVE spam, GRAFT
flood) are written into the attacker's control outboxes between steps —
the vectorized analogue of the reference's `newMockGS` raw-wire fakes
(gossipsub_spam_test.go:765-813). Standing behavior (never forwarding data)
is the static `adversary_no_forward` vector of `make_gossipsub_step`.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net

M = 32  # msg slots (single bitset word)


def p7_score_params():
    """P7-focused params: behaviour penalty bites immediately, the rest
    benign (P3/P3b off so only the attack moves the score)."""
    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.9,
    )
    return PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )


def build(n=20, d=6, seed=0, score=True, score_params=None, params=None,
          heartbeat_every=1, no_forward=None):
    topo = graph.random_connect(n, d, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    p = params or GossipSubParams()
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0,
        publish_threshold=-4.0,
        graylist_threshold=-8.0,
        accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(p, thr, score_enabled=score,
                                heartbeat_every=heartbeat_every)
    sp = (score_params or p7_score_params()) if score else None
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               adversary_no_forward=no_forward)
    return topo, net, cfg, st, step


def edge_to(topo, j, target):
    """Neighbor-slot index k such that nbr[j, k] == target (or None)."""
    for k in range(topo.max_degree):
        if topo.nbr_ok[j, k] and topo.nbr[j, k] == target:
            return k
    return None


def pub(o, t=0, valid=True, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    pv = np.zeros(p, bool)
    po[0], pt[0], pv[0] = o, t, valid
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def run(step, st, k):
    a = no_publish()
    for _ in range(k):
        st = step(st, *a)
    return st


def inject_ihave(st, attacker, slot):
    """Attacker advertises message `slot` on all its edges this round
    (the IHAVE-spam move, gossipsub_spam_test.go:290)."""
    ih = np.zeros(np.asarray(st.ihave_out).shape, np.uint32)
    ih[attacker, :, slot // 32] = np.uint32(1 << (slot % 32))
    return st.replace(ihave_out=jnp.asarray(ih))


def inject_graft(st, attacker, k_edge):
    """Attacker sends GRAFT on edge k_edge for topic slot 0 this round
    (the GRAFT-flood move, gossipsub_spam_test.go:365)."""
    g = np.asarray(st.graft_out).copy()
    g[attacker, 0, k_edge] = True
    return st.replace(graft_out=jnp.asarray(g))


def withheld_publish(st, step, attacker):
    """Attacker originates a valid message it will never forward; returns
    (state, slot) with the message resident only at the attacker."""
    st = step(st, *pub(attacker))
    origin = np.asarray(st.core.msgs.origin)
    slots = np.where(origin == attacker)[0]
    assert len(slots) == 1
    return st, int(slots[0])


# ---------------------------------------------------------------------------
# IHAVE spam: flood-protection caps (handleIHave gossipsub.go:624-633)


def test_ihave_spam_batch_cap():
    """A spammer IHAVEing every round gets at most MaxIHaveMessages IWANT
    batches per heartbeat period (gossipsub.go:624-628)."""
    params = dataclasses.replace(GossipSubParams(), max_ihave_messages=3)
    topo, net, cfg, st, step = build(
        score=False, params=params, heartbeat_every=8,
        no_forward=np.arange(20) == 5,
    )
    attacker = 5
    st = run(step, st, 8)  # one full period of mesh warmup
    st, slot = withheld_publish(st, step, attacker)

    victims = [topo.nbr[attacker, k] for k in range(topo.max_degree)
               if topo.nbr_ok[attacker, k]]
    asks_per_victim = {v: 0 for v in victims}
    for _ in range(16):  # two heartbeat periods of spam
        st = inject_ihave(st, attacker, slot)
        st = step(st, *no_publish())
        iw = np.asarray(st.iwant_out)
        for v in victims:
            k = edge_to(topo, v, attacker)
            if iw[v, k].any():
                asks_per_victim[v] += 1

    # per period the ask count is capped at max_ihave_messages; two periods
    assert max(asks_per_victim.values()) >= 2  # the attack does elicit asks
    assert max(asks_per_victim.values()) <= 2 * 3


def test_ihave_spam_ask_budget():
    """MaxIHaveLength also caps total mids asked per period
    (gossipsub.go:630-633,655-658)."""
    params = dataclasses.replace(
        GossipSubParams(), max_ihave_messages=100, max_ihave_length=2
    )
    topo, net, cfg, st, step = build(
        score=False, params=params, heartbeat_every=8,
        no_forward=np.arange(20) == 5,
    )
    attacker = 5
    st = run(step, st, 8)
    st, slot = withheld_publish(st, step, attacker)

    victims = [topo.nbr[attacker, k] for k in range(topo.max_degree)
               if topo.nbr_ok[attacker, k]]
    asks = {v: 0 for v in victims}
    for _ in range(8):  # within one heartbeat period
        st = inject_ihave(st, attacker, slot)
        st = step(st, *no_publish())
        iw = np.asarray(st.iwant_out)
        for v in victims:
            k = edge_to(topo, v, attacker)
            if iw[v, k].any():
                asks[v] += 1
    assert max(asks.values()) <= 2


# ---------------------------------------------------------------------------
# IWANT promise break -> P7 (gossip_tracer.go + gossipsub.go:1578-1583)


def test_promise_break_applies_p7_and_prunes():
    adv = np.arange(20) == 4
    topo, net, cfg, st, step = build(no_forward=adv, seed=2)
    attacker = 4
    st = run(step, st, 8)
    st, slot = withheld_publish(st, step, attacker)

    for _ in range(12):
        st = inject_ihave(st, attacker, slot)
        st = step(st, *no_publish())

    bp = np.asarray(st.score.bp)
    scores = np.asarray(st.scores)
    mesh = np.asarray(st.mesh[:, 0, :])
    hits = 0
    for j in range(net.n_peers):
        k = edge_to(topo, j, attacker)
        if k is None:
            continue
        hits += 1
        # the victim accumulated broken-promise behaviour penalty ...
        assert bp[j, k] > 0, (j, k)
        # ... P7 made its score of the attacker negative ...
        assert scores[j, k] < 0, (j, k, scores[j, k])
        # ... and the heartbeat dropped the attacker from its mesh
        assert not mesh[j, k]
    assert hits > 0
    assert int(st.mesh[attacker].sum()) == 0


def test_fulfilled_promise_no_penalty():
    """An honest gossiper that serves its IWANTs accrues no P7: promises
    are fulfilled on delivery (gossip_tracer.go DeliverMessage)."""
    topo, net, cfg, st, step = build(seed=3)
    st = run(step, st, 8)
    origin = 2
    st = step(st, *pub(origin))
    st = run(step, st, 10)  # gossip + IWANT + service all complete
    assert float(np.asarray(st.score.bp).max()) == 0.0
    # and the message actually reached everyone
    have = np.asarray(bitset.unpack(st.core.dlv.have, M))
    slot = int(np.where(np.asarray(st.core.msgs.origin) == origin)[0][0])
    assert have[:, slot].all()


# ---------------------------------------------------------------------------
# GRAFT flood during backoff (handleGraft gossipsub.go:753-770)


def test_graft_during_backoff_penalized():
    adv = np.arange(20) == 6
    # gentle P7 weight: with -10 the very first offense graylists the
    # attacker and later GRAFTs are dropped at ingress (also correct, but
    # here we want to watch the flood accumulate)
    sp = dataclasses.replace(p7_score_params(), behaviour_penalty_weight=-0.1)
    topo, net, cfg, st, step = build(no_forward=adv, seed=4, score_params=sp)
    attacker = 6
    victim = None
    for k in range(topo.max_degree):
        if topo.nbr_ok[attacker, k]:
            victim = int(topo.nbr[attacker, k])
            k_av = k
            break
    k_va = edge_to(topo, victim, attacker)
    st = run(step, st, 4)

    # the victim recently pruned the attacker: standing backoff
    tick = int(st.core.tick)
    be = np.asarray(st.backoff_expire).copy()
    bpres = np.asarray(st.backoff_present).copy()
    be[victim, 0, k_va] = tick + cfg.prune_backoff_ticks
    bpres[victim, 0, k_va] = True
    mesh = np.asarray(st.mesh).copy()
    mesh[victim, 0, k_va] = False
    mesh[attacker, 0, k_av] = False
    st = st.replace(
        backoff_expire=jnp.asarray(be),
        backoff_present=jnp.asarray(bpres),
        mesh=jnp.asarray(mesh),
    )

    for _ in range(6):
        st = inject_graft(st, attacker, k_av)
        st = step(st, *no_publish())

    bp = np.asarray(st.score.bp)
    scores = np.asarray(st.scores)
    # each offending GRAFT inside the flood threshold counts twice
    # (gossipsub.go:760-768): 6 grafts, decay 0.9 => well above 6
    assert bp[victim, k_va] > 6.0, bp[victim, k_va]
    assert scores[victim, k_va] < 0
    # and none of them got the attacker into the mesh; backoff refreshed
    assert not bool(st.mesh[victim, 0, k_va])
    assert int(np.asarray(st.backoff_expire)[victim, 0, k_va]) >= tick + cfg.prune_backoff_ticks


# ---------------------------------------------------------------------------
# sybil squatters: grafted-but-silent peers starve the mesh -> P3 deficit
# (score.go:292-298) -> pruned; the honest overlay keeps delivering
# (gossipsub_test.go:1777-1811 TestGossipsubAttackSpamSquatter analogue)


def test_sybil_squatters_pruned_and_delivery_survives():
    n, d = 40, 10
    squatters = np.arange(n) >= 32  # 8 sybils
    # P3 tuned to the traffic volume (as the reference requires of its
    # users): threshold well below the per-edge delivery rate so honest
    # mesh members clear it, activation long enough to accumulate credit
    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=0.5,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_decay=0.9,
        mesh_message_deliveries_cap=20.0,
        mesh_message_deliveries_threshold=0.5,
        mesh_message_deliveries_window=2.0,
        mesh_message_deliveries_activation=8.0,
        mesh_failure_penalty_weight=-1.0,
        mesh_failure_penalty_decay=0.9,
    )
    sp = PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )
    topo, net, cfg, st, step = build(
        n=n, d=d, seed=6, score_params=sp, no_forward=squatters
    )
    st = run(step, st, 6)

    rng = np.random.default_rng(0)
    for i in range(40):
        po = rng.integers(0, 32, size=4).astype(np.int32)  # 4 msgs/round
        pt = np.zeros(4, np.int32)
        pv = np.ones(4, bool)
        st = step(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))

    scores = np.asarray(st.scores)
    mesh = np.asarray(st.mesh[:, 0, :])
    # honest peers scored their squatter mesh-neighbors negative (P3
    # deficit^2 after activation) and pruned every one of them
    squat_edges = 0
    for j in range(32):
        for k in range(topo.max_degree):
            if topo.nbr_ok[j, k] and squatters[topo.nbr[j, k]]:
                squat_edges += 1
                assert not mesh[j, k], (j, k, scores[j, k])
    assert squat_edges > 0
    # P3b sticky mesh-failure penalty recorded on pruned squatter edges
    assert float(np.asarray(st.score.mfp).max()) > 0
    # the honest overlay still delivers end-to-end
    st = step(st, *pub(1))
    st = run(step, st, 8)
    slot = int(np.where(np.asarray(st.core.msgs.origin) == 1)[0][-1])
    have = np.asarray(bitset.unpack(st.core.dlv.have, M))
    assert have[:32, slot].all(), "honest delivery must survive the sybils"


# ---------------------------------------------------------------------------
# IWANT flood: the retransmission cap (handleIWant gossipsub.go:695-707,
# the `iwantEverything` greedy client, gossipsub_test.go:2009)


def test_iwant_flood_served_at_most_retransmission_cap():
    topo, net, cfg, st, step = build(n=12, d=5, seed=3, score=False)
    # victim publishes; the message sits in its mcache window
    victim = 0
    attacker = int(topo.nbr[victim][topo.nbr_ok[victim]][0])
    k_att = edge_to(topo, attacker, victim)  # attacker's edge toward victim
    st, slot = withheld_publish(st, step, victim)
    # use a long history so the window doesn't expire before the cap bites
    word, bit = slot // 32, np.uint32(1 << (slot % 32))

    served = 0
    for _ in range(cfg.gossip_retransmission + 3):
        # attacker re-requests the message from the victim every round
        # (raw-wire greedy client), and pretends it never received it
        iw = np.zeros(np.asarray(st.iwant_out).shape, np.uint32)
        iw[attacker, k_att, word] = bit
        have = np.asarray(st.core.dlv.have).copy()
        have[attacker, word] &= ~bit
        st = st.replace(
            iwant_out=jnp.asarray(iw),
            core=st.core.replace(dlv=st.core.dlv.replace(have=jnp.asarray(have))),
        )
        st = step(st, *no_publish())
        # the bit was cleared before the step, so holding it now means the
        # victim served this round's request
        if np.asarray(st.core.dlv.have)[attacker, word] & bit:
            served += 1

    assert served == cfg.gossip_retransmission, (
        served, cfg.gossip_retransmission)


# ---------------------------------------------------------------------------
# GRAFT for an unknown topic: silently ignored (spam hardening,
# handleGraft gossipsub.go:727-733 — no mesh change, no PRUNE, no
# backoff, no penalty; TestGossipsubAttackGRAFTNonExistentTopic,
# gossipsub_spam_test.go:290)


def test_graft_unknown_topic_ignored():
    n = 16
    topo = graph.random_connect(n, 5, seed=3)
    mask = np.zeros((n, 2), bool)
    mask[:, 0] = True          # everyone joins topic 0
    attacker = 1
    mask[attacker, 1] = True   # ONLY the attacker knows topic 1
    subs = graph.subscribe_mask(mask)
    net = Net.build(topo, subs)
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0,
        graylist_threshold=-8.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(GossipSubParams(), thr, score_enabled=True)
    sp = p7_score_params()
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=3)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    st = run(step, st, 10)

    s1 = int(subs.slot_of[attacker, 1])
    assert s1 >= 0
    pre_backoff = np.asarray(st.backoff_present).copy()
    pre_scores = np.asarray(st.scores).copy()

    for _ in range(5):
        # GRAFT topic 1 toward every neighbor — none of them joined it
        g = np.zeros(np.asarray(st.graft_out).shape, bool)
        g[attacker, s1, :] = True
        st = st.replace(graft_out=jnp.asarray(g))
        st = step(st, *no_publish())

    # no victim meshed the attacker on a slot it doesn't have, no backoff
    # was created anywhere, and nobody's opinion of anyone moved
    post_backoff = np.asarray(st.backoff_present)
    assert (post_backoff == pre_backoff).all(), "unknown-topic GRAFT must not create backoff"
    post_scores = np.asarray(st.scores)
    assert np.array_equal(post_scores, pre_scores), "unknown-topic GRAFT must not move scores"
    # attacker's own mesh for topic 1 stays empty (nobody to graft)
    assert int(np.asarray(st.mesh)[attacker, s1].sum()) == 0
