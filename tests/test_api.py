"""Host API tests — the reference's tier-2 integration style
(floodsub_test.go getNetHosts/connect/assertReceive) driven through the
Network/Node/Topic/Subscription surface."""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import api
from go_libp2p_pubsub_tpu.config import default_peer_score_params
from go_libp2p_pubsub_tpu.subscription_filter import AllowlistSubscriptionFilter


def _basic_net(router="gossipsub", n=10, **kw):
    net = api.Network(router=router, **kw)
    nodes = net.add_nodes(n)
    net.dense_connect(d=5, seed=1)
    return net, nodes


def test_basic_delivery_gossipsub():
    net, nodes = _basic_net()
    topics = [nd.join("news") for nd in nodes]
    subs = [t.subscribe() for t in topics]
    net.start()
    mid = topics[0].publish(b"msg-0")
    assert isinstance(mid, bytes) and len(mid) > 8
    net.run(6)  # mesh forms at tick0 heartbeat; then propagation
    got = [s.next() for s in subs]
    # everyone (publisher included) got exactly the published message
    assert all(m is not None and m.data == b"msg-0" for m in got)
    assert all(m.topic == "news" for m in got)
    assert all(s.next() is None for s in subs)
    # signature travels with the message
    assert got[1].HasField("signature")
    assert getattr(got[1], "from") == nodes[0].peer_id


def test_basic_delivery_floodsub():
    net, nodes = _basic_net(router="floodsub")
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    nodes[3].topics["t"].publish(b"flood")
    net.run(5)
    assert all(s.next() is not None for s in subs)


def test_basic_delivery_randomsub():
    net, nodes = _basic_net(router="randomsub", n=12)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    nodes[0].topics["t"].publish(b"rnd")
    net.run(6)
    delivered = sum(1 for s in subs if s.next() is not None)
    assert delivered >= 10  # sqrt-fanout flood reaches (nearly) everyone


def test_multi_topic_isolation():
    net = api.Network()
    nodes = net.add_nodes(8)
    net.connect_all()
    t_a = [nd.join("a") for nd in nodes[:4]]
    t_b = [nd.join("b") for nd in nodes[4:]]
    sub_a = [t.subscribe() for t in t_a]
    sub_b = [t.subscribe() for t in t_b]
    net.start()
    t_a[0].publish(b"for-a")
    net.run(5)
    assert all(s.next().data == b"for-a" for s in sub_a)
    assert all(s.next() is None for s in sub_b)


def test_validator_rejects_propagation():
    net, nodes = _basic_net(n=8)
    subs = [nd.join("t").subscribe() for nd in nodes]
    nodes[0].register_topic_validator(
        "t", lambda pid, m: not m.data.startswith(b"spam"), inline=True
    )
    net.start()
    with pytest.raises(api.ValidationError):
        nodes[1].topics["t"].publish(b"spam-1")  # local reject errors out
    net.run(4)
    assert all(s.next() is None for s in subs[2:])


def test_validator_throttle():
    net, nodes = _basic_net(n=4, validate_throttle=2)
    t = [nd.join("t") for nd in nodes]
    nodes[0].register_topic_validator("t", lambda pid, m: True)  # async
    net.start()
    t[0].publish(b"a")
    t[0].publish(b"b")
    with pytest.raises(api.ValidationError):
        t[0].publish(b"c")  # global throttle exhausted
    net.run(1)  # budget resets per run
    t[0].publish(b"d")


def test_subscription_filter_blocks_join():
    net = api.Network()
    a = net.add_node(sub_filter=AllowlistSubscriptionFilter(["ok"]))
    a.join("ok")
    with pytest.raises(api.APIError):
        a.join("not-ok")


def test_relay_forwards_without_delivery():
    # line: 0 -1- 2, node 1 relays but doesn't subscribe
    net = api.Network()
    nodes = net.add_nodes(3)
    net.connect(nodes[0], nodes[1])
    net.connect(nodes[1], nodes[2])
    t0 = nodes[0].join("t")
    t1 = nodes[1].join("t")
    t2 = nodes[2].join("t")
    cancel = t1.relay()
    sub2 = t2.subscribe()
    net.start()
    t0.publish(b"through")
    net.run(4)
    assert sub2.next().data == b"through"
    cancel()
    assert t1._relays == 0


def test_event_handler_churn():
    net, nodes = _basic_net(n=6)
    topics = [nd.join("t") for nd in nodes]
    h = topics[0].event_handler()
    net.start()
    # initial membership replay: everyone else is already joined
    seen = set()
    while (ev := h.next_event()) is not None:
        kind, pid = ev
        assert kind == api.PEER_JOIN
        seen.add(pid)
    assert seen == {nd.peer_id for nd in nodes[1:]}
    nodes[3].disconnect()
    net.run(1)
    assert h.next_event() == (api.PEER_LEAVE, nodes[3].peer_id)
    nodes[3].reconnect()
    net.run(1)
    assert h.next_event() == (api.PEER_JOIN, nodes[3].peer_id)


def test_blacklist_disconnects():
    net, nodes = _basic_net(n=6)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    net.run(2)  # let the mesh form
    nodes[0].blacklist_peer(nodes[5].peer_id)
    net.run(1)
    nodes[5].topics["t"].publish(b"from-banned")
    net.run(4)
    # the blacklisted peer is cut off: nobody else receives its message
    assert all(subs[i].next() is None for i in range(5))


def test_subscription_buffer_drops():
    net = api.Network(max_publishes_per_round=64)
    nodes = net.add_nodes(2)
    net.connect(nodes[0], nodes[1])
    t0 = nodes[0].join("t")
    sub = nodes[1].join("t").subscribe(buffer=4)
    net.start()
    for i in range(10):
        t0.publish(b"m%d" % i)
    net.run(4)
    assert len(sub._q) == 4
    assert sub.dropped == 6


def test_peer_scores_surface():
    sp = default_peer_score_params(1)
    net, nodes = _basic_net(n=6, score_params=sp)
    [nd.join("t") for nd in nodes]
    net.start()
    net.run(3)
    scores = nodes[0].peer_scores()
    assert scores  # neighbors present
    assert all(isinstance(k, bytes) for k in scores)


def test_traced_network(tmp_path):
    from go_libp2p_pubsub_tpu.trace import sinks

    path = str(tmp_path / "api.json")
    net = api.Network(trace_sinks=[sinks.JSONTracer(path)])
    nodes = net.add_nodes(5)
    net.connect_all()
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    nodes[0].topics["t"].publish(b"x")
    net.run(4)
    net.stop()
    evs = list(sinks.read_json_trace(path))
    kinds = {e.type for e in evs}
    from go_libp2p_pubsub_tpu.pb import trace_pb2

    assert trace_pb2.TraceEvent.PUBLISH_MESSAGE in kinds
    assert trace_pb2.TraceEvent.DELIVER_MESSAGE in kinds


def test_peer_score_snapshots_detailed():
    # WithPeerScoreInspectDetailed parity: per-topic counters behind the score
    from go_libp2p_pubsub_tpu import api

    net = api.Network(score_params=default_peer_score_params(1))
    nodes = net.add_nodes(10)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.dense_connect(d=4, seed=1)
    net.start()
    nodes[0].topics["t"].publish(b"x")
    net.run(6)
    snaps = nodes[1].peer_score_snapshots()
    assert snaps, "expected neighbor snapshots"
    for pid, snap in snaps.items():
        assert isinstance(snap.score, float)
        assert "t" in snap.topics
        ts = snap.topics["t"]
        assert ts.time_in_mesh >= 0
        assert ts.first_message_deliveries >= 0.0
        assert snap.ip_colocation_factor >= 0.0
    # somewhere in the network a first delivery must have been credited
    all_snaps = [s for nd in nodes for s in nd.peer_score_snapshots().values()]
    assert any(s.topics["t"].first_message_deliveries > 0 for s in all_snaps)
    # scores agree with the simple inspection map
    simple = nodes[1].peer_scores()
    for pid, snap in snaps.items():
        assert abs(simple[pid] - snap.score) < 1e-6


def test_slow_heartbeat_warning(caplog):
    # gossipsub.go:1305-1312: warn when a tick's wall time exceeds 10% of
    # the heartbeat interval — force it with a tiny interval
    import dataclasses
    import logging

    from go_libp2p_pubsub_tpu import api
    from go_libp2p_pubsub_tpu.config import GossipSubParams

    params = dataclasses.replace(GossipSubParams(), heartbeat_interval=1e-4)
    net = api.Network(params=params)
    nodes = net.add_nodes(4)
    for nd in nodes:
        nd.join("t")
    net.connect_all()
    net.start()
    net.run(1)  # first round is exempt (jit compile)
    with caplog.at_level(logging.WARNING, logger="go_libp2p_pubsub_tpu"):
        net.run(1)
    assert any("slow heartbeat" in r.message for r in caplog.records)


def test_network_rounds_per_phase():
    """The phase engine through the L6 API: publishes land per sub-round,
    deliveries drain at phase boundaries, full coverage."""
    from go_libp2p_pubsub_tpu import api as api_mod

    net = api_mod.Network(rounds_per_phase=4)
    nodes = net.add_nodes(24)
    net.dense_connect(d=6, seed=5)
    subs = [nd.join("x").subscribe() for nd in nodes]
    net.start()
    net.run(8)  # 2 phases of mesh formation
    for i in range(5):
        nodes[i].topics["x"].publish(b"p%d" % i)
    net.run(12)
    got = [sum(1 for _ in s) for s in subs]
    assert all(g == 5 for g in got), got
    import pytest as _pytest

    with _pytest.raises(api_mod.APIError, match="multiple of the phase"):
        net.run(3)


def test_network_phase_mode_no_delivery_loss_under_slot_pressure():
    """Publish far more messages than msg_slots through a long phase: the
    per-phase admission cap must prevent within-phase recycling from
    wiping receipts before the boundary drain (round-4 review repro:
    128 pubs at r=16 delivered only 32 without the cap)."""
    from go_libp2p_pubsub_tpu import api as api_mod

    net = api_mod.Network(rounds_per_phase=16, msg_slots=64)
    nodes = net.add_nodes(24)
    net.dense_connect(d=6, seed=7)
    subs = [nd.join("x").subscribe(buffer=256) for nd in nodes]
    net.start()
    net.run(16)
    for i in range(128):
        nodes[i % 24].topics["x"].publish(b"m%d" % i)
    net.run(16 * 8)
    got = [sum(1 for _ in s) for s in subs]
    assert all(g == 128 for g in got), got


def test_network_phase_mode_runtime_leave():
    """Runtime leave() in phase mode drives the transition through a full
    publish-free phase (round-4 review repro: TypeError before)."""
    from go_libp2p_pubsub_tpu import api as api_mod

    net = api_mod.Network(rounds_per_phase=4)
    nodes = net.add_nodes(16)
    net.dense_connect(d=5, seed=9)
    topics = [nd.join("x") for nd in nodes]
    net.start()
    net.run(8)
    topics[0].close()  # leave
    net.run(8)
    subs = [nd.topics["x"].subscribe() for nd in nodes[1:]]
    nodes[1].topics["x"].publish(b"after-leave")
    net.run(12)
    assert all(sum(1 for _ in s) == 1 for s in subs)


def test_network_phase_cold_start_publish():
    """Publishing immediately after start() in phase mode delivers to the
    whole network: start() runs a formation prelude (one publish-free
    phase) so the first user phase sees a formed mesh — the reference's
    immediate-Join behavior (gossipsub.go:1015-1064), with no warmup
    contract pushed onto the caller (round-4 review missing item 3)."""
    from go_libp2p_pubsub_tpu import api as api_mod

    net = api_mod.Network(rounds_per_phase=8)
    nodes = net.add_nodes(24)
    net.dense_connect(d=6, seed=5)
    subs = [nd.join("x").subscribe() for nd in nodes]
    net.start()
    for i in range(3):
        nodes[i].topics["x"].publish(b"cold%d" % i)
    net.run(8)  # ONE phase, no warmup
    got = [sum(1 for _ in s) for s in subs]
    assert all(g == 3 for g in got), got


def test_run_periodic_checkpoint_resume_exact(tmp_path):
    """run(checkpoint_every=k, checkpoint_path=p) auto-snapshots the
    device state; an identically-built Network that load_checkpoint()s
    the snapshot and runs the remaining rounds lands on EXACTLY the
    uninterrupted run's device state — the PRNG key and tick ride the
    snapshot, so the continued random (and chaos-fault) stream is the
    uninterrupted one."""
    import jax
    import jax.numpy as jnp

    path = str(tmp_path / "auto.npz")

    def build():
        net = api.Network(router="gossipsub", seed=11)
        nodes = net.add_nodes(10)
        net.dense_connect(d=5, seed=2)
        topics = [nd.join("t") for nd in nodes]
        net.start()
        return net, topics

    # uninterrupted: 10 rounds (publish up front), snapshots every 4
    net1, topics1 = build()
    topics1[0].publish(b"payload")
    net1.run(4, checkpoint_every=4, checkpoint_path=path)
    mid_tick = int(net1.state.core.tick)
    net1.run(6)
    final1 = net1.state

    # crashed host: fresh identically-built network resumes the snapshot
    net2, _ = build()
    net2.load_checkpoint(path)
    assert int(net2.state.core.tick) == mid_tick
    net2.run(6)
    final2 = net2.state

    la = jax.tree_util.tree_leaves(final1)
    lb = jax.tree_util.tree_leaves(final2)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_run_checkpoint_arg_validation(tmp_path):
    net, _ = _basic_net(n=4)
    net.start()
    with pytest.raises(api.APIError):
        net.run(1, checkpoint_every=2)  # path missing
    with pytest.raises(api.APIError):
        net.run(1, checkpoint_every=0, checkpoint_path=str(tmp_path / "x"))


def test_run_checkpoint_retention_store_resume(tmp_path):
    """run(keep_last=, keep_every=) grows the single-path overwrite into
    the supervised loop's rolling store: multiple retained snapshots
    under a manifest, corrupted-latest fallback, and load_checkpoint()
    accepting the store DIRECTORY — resuming bit-exact."""
    import jax
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.serve import CheckpointStore, truncate_file

    store_dir = str(tmp_path / "store")

    def build():
        net = api.Network(router="gossipsub", seed=13)
        nodes = net.add_nodes(10)
        net.dense_connect(d=5, seed=3)
        topics = [nd.join("t") for nd in nodes]
        net.start()
        return net, topics

    net1, topics1 = build()
    topics1[0].publish(b"payload")
    net1.run(8, checkpoint_every=2, checkpoint_path=store_dir,
             keep_last=2, keep_every=2)
    entries = CheckpointStore(store_dir).entries()
    assert len(entries) >= 2  # a rolling store, not one overwritten file
    ticks = [e["tick"] for e in entries]
    assert ticks == sorted(ticks)
    mid_tick = ticks[-1]
    net1.run(4)
    final1 = net1.state

    net2, _ = build()
    net2.load_checkpoint(store_dir)
    assert int(net2.state.core.tick) == mid_tick
    net2.run(4 + 8 - mid_tick)
    la = jax.tree_util.tree_leaves(final1)
    lb = jax.tree_util.tree_leaves(net2.state)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    # damaged latest: load_checkpoint falls back to the previous entry
    latest = CheckpointStore(store_dir).latest()
    truncate_file(str(tmp_path / "store" / latest["file"]))
    net3, _ = build()
    net3.load_checkpoint(store_dir)
    assert int(net3.state.core.tick) < mid_tick


def test_run_checkpoint_retention_validation(tmp_path):
    net, _ = _basic_net(n=4)
    net.start()
    with pytest.raises(api.APIError):
        net.run(1, checkpoint_every=1,
                checkpoint_path=str(tmp_path / "s"), keep_last=0)
    with pytest.raises(api.APIError):
        net.run(1, checkpoint_every=1,
                checkpoint_path=str(tmp_path / "s"), keep_every=-1)
