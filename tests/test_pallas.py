"""Fused Pallas delivery kernel (ops/pallas_delivery.py): exact parity with
the generic XLA delivery_round on banded topologies, in interpret mode (no
TPU needed)."""

import numpy as np
import jax.numpy as jnp

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.models import common
from go_libp2p_pubsub_tpu.ops.bitset import edge_eq_words
from go_libp2p_pubsub_tpu.state import Delivery, MsgTable, Net


def _random_state(n, m, k, rng):
    w = (m + 31) // 32
    mask_m = (1 << m) - 1  # keep invalid high bits clear

    def words(shape):
        raw = rng.integers(0, 2**32, size=shape + (w,), dtype=np.uint64)
        flat = raw.astype(np.uint32)
        # clear padding bits of the last word
        if m % 32:
            flat[..., -1] &= np.uint32((1 << (m % 32)) - 1)
        return jnp.asarray(flat)

    dlv = Delivery(
        have=words((n,)),
        fwd=words((n,)),
        first_round=jnp.asarray(rng.integers(-1, 5, size=(n, m)).astype(np.int32)),
        fe_words=edge_eq_words(
            jnp.asarray(rng.integers(-1, k, size=(n, m)).astype(np.int8)), k
        ),
    )
    msgs = MsgTable(
        topic=jnp.asarray(rng.integers(0, 2, size=(m,)).astype(np.int32)),
        origin=jnp.asarray(rng.integers(-1, n, size=(m,)).astype(np.int32)),
        birth=jnp.zeros((m,), jnp.int32),
        valid=jnp.asarray(rng.random(m) < 0.8),
        ignored=jnp.zeros((m,), bool),
        cursor=jnp.int32(0),
    )
    edge_mask = words((n, k))
    return dlv, msgs, edge_mask


def test_pallas_delivery_matches_xla():
    n, m, d = 64, 40, 4
    topo = graph.ring_lattice(n, d=d)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    assert net.band_off is not None
    k = net.max_degree

    rng = np.random.default_rng(11)
    # block=16 -> a 4-block grid, exercising the wrapped halo views and
    # cross-block slicing (not just the degenerate single-block case)
    for trial, block in enumerate([None, 16, 32]):
        dlv, msgs, edge_mask = _random_state(n, m, k, rng)
        tick = jnp.int32(3 + trial)

        dlv_x, info_x = common.delivery_round(net, msgs, dlv, edge_mask, tick)
        dlv_p, info_p = common._delivery_round_pallas(
            net, msgs, dlv, edge_mask, tick, block=block, interpret=True
        )

        for name in ("have", "fwd", "first_round", "first_edge"):
            a, b = np.asarray(getattr(dlv_x, name)), np.asarray(getattr(dlv_p, name))
            assert (a == b).all(), f"{name} diverged (block {block})"
        assert (np.asarray(info_x.trans) == np.asarray(info_p.trans)).all()
        assert (np.asarray(info_x.new_words) == np.asarray(info_p.new_words)).all()
        for c in ("n_rpc", "n_deliver", "n_reject", "n_duplicate"):
            assert int(getattr(info_x, c)) == int(getattr(info_p, c)), c


def test_pallas_delivery_partial_liveness():
    # dead edges (nbr_ok=False) must carry nothing on the pallas path too
    n, m, d = 32, 33, 3
    topo = graph.ring_lattice(n, d=d)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    rng = np.random.default_rng(5)
    live = rng.random((n, net.max_degree)) < 0.6
    net_l = net.replace(nbr_ok=jnp.asarray(live))

    dlv, msgs, edge_mask = _random_state(n, m, net.max_degree, rng)
    dlv_x, info_x = common.delivery_round(net_l, msgs, dlv, edge_mask, jnp.int32(2))
    dlv_p, info_p = common._delivery_round_pallas(
        net_l, msgs, dlv, edge_mask, jnp.int32(2), interpret=True
    )
    assert (np.asarray(info_x.trans) == np.asarray(info_p.trans)).all()
    assert (np.asarray(dlv_x.have) == np.asarray(dlv_p.have)).all()
    assert (np.asarray(dlv_x.first_edge) == np.asarray(dlv_p.first_edge)).all()
