"""Custom protocol matching (gossipsub_feat.go:11-36 feature function +
the WithProtocolMatchFn seam, gossipsub_matchfn_test.go): embedders
register custom protocol ids with declared feature sets, or a match
function admitting versioned variants; the router treats the speakers
by their features."""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import api
from go_libp2p_pubsub_tpu.protocol import (
    DEFAULT_FEATURES,
    FEATURE_MESH,
    FEATURE_PX,
    ProtocolMatcher,
    ProtocolError,
    prefix_match,
)


def test_matcher_defaults_and_levels():
    m = ProtocolMatcher()
    assert m.level("/floodsub/1.0.0") == 0
    assert m.level("/meshsub/1.0.0") == 1
    assert m.level("/meshsub/1.1.0") == 2
    assert m.supports("/meshsub/1.0.0", FEATURE_MESH)
    assert not m.supports("/meshsub/1.0.0", FEATURE_PX)
    with pytest.raises(ProtocolError):
        m.level("/unknown/9.9.9")


def test_matcher_custom_table_and_match_fn():
    m = ProtocolMatcher(
        features={"/my-app/gossip/2.0.0": FEATURE_MESH | FEATURE_PX},
        match_fn=prefix_match("/meshsub/1.1.0"),
    )
    assert m.level("/my-app/gossip/2.0.0") == 2
    # the matchfn shape from gossipsub_matchfn_test.go: a versioned
    # variant negotiates as its base protocol
    assert m.level("/meshsub/1.1.0-beta2") == 2
    with pytest.raises(ProtocolError):
        m.level("/meshsub/0.9.0")  # prefix doesn't match


def test_px_without_mesh_rejected():
    with pytest.raises(ProtocolError):
        ProtocolMatcher(features={"/bad/1.0.0": FEATURE_PX})


def test_mixed_custom_and_floodsub_network_delivers():
    """A network mixing a custom mesh protocol, a matchfn-admitted
    meshsub variant, and plain floodsub peers: the mesh forms among the
    mesh-capable speakers and every subscriber still gets every message
    (the floodsub interop edges of gossipsub.go:973-978)."""
    net = api.Network(
        protocol_matcher=ProtocolMatcher(
            features={"/my-app/gossip/2.0.0": FEATURE_MESH},
            match_fn=prefix_match("/meshsub/1.1.0"),
        ),
        seed=3,
    )
    nodes = []
    for i in range(18):
        proto = (
            "/my-app/gossip/2.0.0" if i % 3 == 0
            else "/meshsub/1.1.0-custom" if i % 3 == 1
            else "/floodsub/1.0.0"
        )
        nodes.append(net.add_node(protocol=proto))
    net.dense_connect(d=6, seed=1)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    for _ in range(12):
        net.run(1)
    nodes[0].topics["t"].publish(b"a")
    nodes[2].topics["t"].publish(b"b")  # floodsub origin
    net.run(8)
    got = [sum(1 for _ in s) for s in subs]
    assert all(g == 2 for g in got), got

    # floodsub speakers never enter anyone's mesh; mesh-capable peers do
    mesh = np.asarray(net.state.mesh)  # [N,S,K]
    nbr = np.asarray(net.net.nbr)
    fs = {i for i in range(18) if i % 3 == 2}
    in_mesh_peers = set()
    for i in range(18):
        for k in np.flatnonzero(mesh[i].any(axis=0)):
            in_mesh_peers.add(int(nbr[i, k]))
    assert not (in_mesh_peers & fs), in_mesh_peers & fs
    assert in_mesh_peers  # and the custom-protocol mesh actually formed


def test_unknown_protocol_fails_fast_at_add_node():
    net = api.Network()
    with pytest.raises(ProtocolError):
        net.add_node(protocol="/my-app/gossip/2.0.0")
