"""Runtime Join/Leave after start() (pubsub.go:1146-1218, topic.go:135-199;
Leave sends PRUNE+backoff, gossipsub.go:1066-1082): the API rebuilds the
subscription constants and recompiles the step, carrying protocol state
across with a per-node topic-slot remap."""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import api


def _scored_params():
    from go_libp2p_pubsub_tpu.config import PeerScoreParams, TopicScoreParams

    tp = TopicScoreParams(
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
        first_message_deliveries_decay=0.9999,
    )
    return PeerScoreParams(
        topics={0: tp, 1: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )


def test_join_after_start_receives_messages():
    net = api.Network()
    nodes = net.add_nodes(16)
    net.dense_connect(d=5, seed=1)
    for nd in nodes[:15]:
        nd.join("t")
    net.start()
    net.run(3)  # mesh forms among the first 15

    late = nodes[15]
    sub = late.join("t").subscribe()
    net.run(6)  # announce visible; heartbeat grafts the newcomer
    nodes[0].topics["t"].publish(b"after-join")
    net.run(6)
    got = [m for m in sub]
    assert len(got) == 1 and got[0].data == b"after-join"


@pytest.mark.slow
def test_leave_after_start_stops_delivery_and_prunes():
    net = api.Network()
    nodes = net.add_nodes(12)
    net.dense_connect(d=5, seed=2)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    net.run(4)

    leaver = nodes[7]
    leaver_pid = leaver.identity.peer_id
    leaver.leave("t")
    # the leaver is out of every remaining mesh row once its PRUNE lands
    s = int(np.asarray(net.net.slot_of)[0, net.topic_ids["t"]])
    mesh = np.asarray(net.state.mesh)
    nbr = np.asarray(net.net.nbr)
    for i in range(12):
        if i == 7:
            continue
        row = mesh[i, int(np.asarray(net.net.slot_of)[i, net.topic_ids["t"]])]
        peers = nbr[i][row]
        assert 7 not in peers.tolist(), f"node {i} still meshes the leaver"

    nodes[0].topics["t"].publish(b"post-leave")
    net.run(6)
    assert all(sum(1 for _ in s) == 1 for i, s in enumerate(subs) if i != 7)
    assert sum(1 for _ in subs[7]) == 0
    assert "t" not in leaver.topics


@pytest.mark.slow
def test_rejoin_forms_mesh_again():
    net = api.Network()
    nodes = net.add_nodes(10)
    net.dense_connect(d=4, seed=3)
    for nd in nodes:
        nd.join("t")
    net.start()
    net.run(3)
    nodes[3].leave("t")
    net.run(2)
    sub = nodes[3].join("t").subscribe()
    net.run(65)  # ride out the PRUNE backoff (60 ticks) + regraft
    nodes[0].topics["t"].publish(b"welcome-back")
    net.run(5)
    assert sum(1 for _ in sub) == 1


@pytest.mark.slow
def test_scored_state_survives_resubscribe():
    """Counters for the untouched topic must carry across the rebuild."""
    net = api.Network(score_params=_scored_params())
    nodes = net.add_nodes(12)
    net.dense_connect(d=5, seed=4)
    for nd in nodes:
        nd.join("a")
        nd.join("b")
    net.start()
    for r in range(6):
        nodes[r % 12].topics["a"].publish(b"x%d" % r)
        net.run(1)
    fmd_before = float(np.asarray(net.state.score.fmd).sum())
    assert fmd_before > 0
    nodes[11].leave("b")
    fmd_after = float(np.asarray(net.state.score.fmd).sum())
    # topic-a counters survive the remap (only node 11's topic-b slot
    # drops; the leave's transition round may accrue further deliveries,
    # so carry-over means no loss)
    assert fmd_after >= fmd_before * (1 - 1e-6)
    # and the sim still runs + delivers on both topics
    suba = nodes[5].topics["a"].subscribe()
    nodes[0].topics["a"].publish(b"still-works")
    net.run(5)
    assert sum(1 for _ in suba) == 1


def test_join_new_topic_after_start_still_raises():
    net = api.Network()
    net.add_nodes(4)
    net.connect_all()
    net.nodes[0].join("exists")
    net.start()
    with pytest.raises(api.APIError):
        net.nodes[1].join("brand-new")


def test_get_topics_and_list_peers():
    net = api.Network()
    nodes = net.add_nodes(6)
    net.connect_all()
    for nd in nodes[:4]:
        nd.join("a")
    nodes[0].join("b")
    net.start()
    assert nodes[0].get_topics() == ["a", "b"]
    assert nodes[5].get_topics() == []
    peers = nodes[0].list_peers("a")
    want = sorted(nd.identity.peer_id for nd in nodes[1:4])
    assert peers == want
    assert nodes[0].list_peers("nope") == []


@pytest.mark.slow
def test_set_score_params_live():
    from go_libp2p_pubsub_tpu.config import TopicScoreParams

    net = api.Network(score_params=_scored_params())
    nodes = net.add_nodes(8)
    net.dense_connect(d=4, seed=5)
    for nd in nodes:
        nd.join("a")
        nd.join("b")
    net.start()
    net.run(3)
    # live update: crank topic-a's P1 weight; counters carry, step recompiles
    nodes[0].topics["a"].set_score_params(
        TopicScoreParams(topic_weight=2.0, time_in_mesh_weight=0.5,
                         mesh_message_deliveries_weight=0.0,
                         mesh_failure_penalty_weight=0.0)
    )
    sub = nodes[3].topics["a"].subscribe()
    nodes[0].topics["a"].publish(b"post-update")
    net.run(5)
    assert sum(1 for _ in sub) == 1
    scores = nodes[0].peer_scores()
    assert any(v > 0 for v in scores.values())  # P1 now credits time in mesh


def test_set_score_params_requires_scoring():
    import pytest

    from go_libp2p_pubsub_tpu.config import TopicScoreParams

    net = api.Network()
    net.add_nodes(2)
    net.connect_all()
    t = net.nodes[0].join("x")
    with pytest.raises(api.APIError):
        t.set_score_params(TopicScoreParams())


def test_floodsub_runtime_join_and_leave():
    net = api.Network(router="floodsub")
    nodes = net.add_nodes(8)
    net.connect_all()
    for nd in nodes[:7]:
        nd.join("t")
    net.start()
    net.run(2)
    sub = nodes[7].join("t").subscribe()
    nodes[0].topics["t"].publish(b"flood")
    net.run(4)
    assert sum(1 for _ in sub) == 1
    nodes[7].leave("t")
    nodes[0].topics["t"].publish(b"again")
    net.run(4)
    assert sum(1 for _ in sub) == 0  # left: no delivery


def test_randomsub_runtime_join():
    net = api.Network(router="randomsub")
    nodes = net.add_nodes(10)
    net.connect_all()
    for nd in nodes[:9]:
        nd.join("t")
    net.start()
    net.run(2)
    sub = nodes[9].join("t").subscribe()
    got = 0
    for _ in range(6):  # randomsub fanout is probabilistic; retry publishes
        nodes[0].topics["t"].publish(b"r")
        net.run(4)
        got += sum(1 for _ in sub)
        if got:
            break
    assert got >= 1


@pytest.mark.slow
def test_resubscribe_with_tags_and_traces(tmp_path):
    """The TagTracer connmgr state and the TraceSession's net views must
    survive a runtime leave (slot remap + session refresh)."""
    from go_libp2p_pubsub_tpu.pb import trace_pb2
    from go_libp2p_pubsub_tpu.trace import sinks

    path = str(tmp_path / "resub.json")
    net = api.Network(track_tags=True, trace_sinks=[sinks.JSONTracer(path)])
    nodes = net.add_nodes(10)
    net.dense_connect(d=4, seed=6)
    for nd in nodes:
        nd.join("a")
        nd.join("b")
    net.start()
    for r in range(5):
        nodes[r % 10].topics["a"].publish(b"x%d" % r)
        net.run(1)
    tags_before = int(net.tag_tracer.cm.tags.sum())
    assert tags_before > 0
    nodes[9].leave("b")
    # all tags are topic-a tags (only topic a saw traffic), and only node
    # 9's topic-b slot dropped — the remap carries every tag across; the
    # leave's transition round may bump further deliveries on top
    assert int(net.tag_tracer.cm.tags.sum()) >= tags_before
    # the traced session keeps observing consistently after the rebuild
    suba = nodes[2].topics["a"].subscribe()
    nodes[0].topics["a"].publish(b"post")
    net.run(5)
    assert sum(1 for _ in suba) == 1
    net.stop()
    evs = list(sinks.read_json_trace(path))
    assert any(e.type == trace_pb2.TraceEvent.DELIVER_MESSAGE for e in evs)
