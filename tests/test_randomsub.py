"""RandomSub tests (randomsub_test.go analogues): probabilistic flooding
reaches (nearly) everyone with sqrt-fanout traffic well below flood."""

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net, SimState
from go_libp2p_pubsub_tpu.trace.events import EV


def _pub(o, t, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    pv = np.zeros(p, bool)
    po[0], pt[0], pv[0] = o, t, True
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def _none(p=4):
    z = jnp.full((p,), -1, jnp.int32)
    return z, z, jnp.zeros((p,), bool)


def test_randomsub_propagates():
    n = 150
    topo = graph.random_connect(n, 20, seed=1)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    st = SimState.init(n, 32, seed=0, k=net.max_degree)
    step = make_randomsub_step(net)
    st = step(st, *_pub(0, 0))
    for _ in range(12):
        st = step(st, *_none())
    have = np.asarray(bitset.unpack(st.dlv.have, 32))[:, 0]
    # probabilistic: sqrt-fanout should reach essentially everyone
    assert have.mean() > 0.97


def test_randomsub_cheaper_than_flood():
    n = 100
    topo = graph.random_connect(n, 25, seed=2)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)

    st_r = SimState.init(n, 32, seed=0, k=net.max_degree)
    step_r = make_randomsub_step(net)
    st_r = step_r(st_r, *_pub(0, 0))
    for _ in range(12):
        st_r = step_r(st_r, *_none())

    st_f = SimState.init(n, 32, seed=0, k=net.max_degree)
    st_f = floodsub_step(net, st_f, *_pub(0, 0))
    for _ in range(12):
        st_f = floodsub_step(net, st_f, *_none())

    rpc_r = int(np.asarray(st_r.events)[EV.SEND_RPC])
    rpc_f = int(np.asarray(st_f.events)[EV.SEND_RPC])
    assert rpc_r < rpc_f * 0.6, (rpc_r, rpc_f)


def test_randomsub_fanout_bound():
    # each sender transmits to at most max(D, ceil(sqrt(size))) peers/round
    n = 64
    topo = graph.connect_all(n)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    st = SimState.init(n, 16, seed=0, k=net.max_degree)
    step = make_randomsub_step(net)
    st = step(st, *_pub(0, 0))
    st = step(st, *_none())
    ev = np.asarray(st.events)
    # the publish round sends to exactly max(6, ceil(sqrt(64)))=8 peers
    assert ev[EV.SEND_RPC] <= 8 + 1
    assert ev[EV.DELIVER_MESSAGE] >= 6


def test_floodsub_peers_always_receive():
    # randomsub.go:107-116: floodsub-only peers are not subject to the
    # random draw — every publish reaches them (if subscribed + adjacent)
    n = 24
    topo = graph.ring_lattice(n, d=6)
    subs = graph.subscribe_all(n, 1)
    protocol = np.full(n, 2, np.int8)
    fs = [3, 9, 17]
    protocol[fs] = 0  # floodsub-only speakers
    net = Net.build(topo, subs, protocol=protocol)
    st = SimState.init(n, 32, seed=0, k=net.max_degree)
    step = make_randomsub_step(net, d=2)  # small d so the draw is sparse

    for r in range(6):
        st = step(st, *_pub((5 * r + 1) % n, 0))
        st = step(st, *_none())
    have = np.asarray(bitset.unpack(st.dlv.have, 32))
    # every floodsub peer adjacent to any holder of a message eventually
    # has it: with always-forward they receive on first contact; just check
    # they received at least as many messages as the network median
    counts = have.sum(axis=1)
    assert all(counts[f] >= np.median(counts) for f in fs), (
        counts[fs], np.median(counts))


def test_floodsub_sender_floods_all_neighbors():
    # a /floodsub/1.0.0 speaker runs floodsub semantics: its messages go to
    # every subscribed neighbor in one hop, not a random subset
    n = 40
    topo = graph.ring_lattice(n, d=8)  # degree 16 >> randomsub target
    subs = graph.subscribe_all(n, 1)
    protocol = np.full(n, 2, np.int8)
    protocol[7] = 0
    net = Net.build(topo, subs, protocol=protocol)
    st = SimState.init(n, 32, seed=3, k=net.max_degree)
    step = make_randomsub_step(net, d=2)
    st = step(st, *_pub(7, 0))
    st = step(st, *_none())
    have = np.asarray(bitset.unpack(st.dlv.have, 32))
    nbrs = np.asarray(topo.nbr)[7][np.asarray(topo.nbr_ok)[7]]
    # after one delivery round every neighbor of 7 must hold the message
    assert have[nbrs, 0].all()
