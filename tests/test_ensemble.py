"""Ensemble-plane tests (docs/DESIGN.md §10).

The contracts pinned here, per the round-10 acceptance criteria:

  * **S=1 parity** — the batched step is bit-exact against the
    unbatched step on FULL state trees for all four engines, incl. the
    phase engine at r ∈ {1, 8} on the stacked coalesced wire path;
  * **sim-i parity** — sim ``i`` of an S>1 batched run reproduces the
    unbatched run built with ``fold_in(sim_key, i)`` bit-exactly
    (under the ambient threefry PRNG — ensemble/batch.py documents the
    unsafe_rbg caveat);
  * **stream independence** — two sims' Gilbert–Elliott chaos streams,
    i.i.d. flap streams, and sampler streams all differ under the
    fold_in derivation;
  * **per-sim scenario inputs** — a [S, ...] ``link_deny`` runs S
    DIFFERENT scenarios in one program;
  * **checkpointing** — a batched state round-trips through the npz
    backend unchanged (no version bump: the v6 format is pytree-
    generic) and each unbatched sim slice remains v6-compatible;
  * **one compile** — the runner's cache sentinel reads exactly 1 for
    a multi-round batched run;
  * **stats** — the device cross-sim reductions agree with the
    host-side chaos.metrics versions per sim.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, ensemble, graph
from go_libp2p_pubsub_tpu.chaos import ChaosConfig, delivery_stats
from go_libp2p_pubsub_tpu.chaos import faults as chaos_faults
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.ensemble import stats as estats
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
    make_gossipsub_phase_step,
)
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.state import Net, SimState
from go_libp2p_pubsub_tpu.trace.drain import batched_counter_events

N = 48
M = 64
ROUNDS = 6


def _keyless(tree):
    def unkey(x):
        if checkpoint.is_prng_key(x):
            return jax.random.key_data(x)
        return x

    return jax.tree_util.tree_map(unkey, tree)


def assert_trees_bitexact(got, want, context=""):
    flat_g, _ = jax.tree_util.tree_flatten_with_path(_keyless(got))
    flat_w, _ = jax.tree_util.tree_flatten_with_path(_keyless(want))
    assert len(flat_g) == len(flat_w)
    for (path, a), (_, b) in zip(flat_g, flat_w):
        assert a.dtype == b.dtype and a.shape == b.shape, (
            f"{context}{jax.tree_util.keystr(path)}: aval mismatch"
        )
        assert bool(jnp.array_equal(a, b)), (
            f"{context}{jax.tree_util.keystr(path)}: values differ"
        )


def _net(n=N, seed=0):
    topo = graph.random_connect(n, d=4, seed=seed)
    return Net.build(topo, graph.subscribe_all(n, 1))


def _schedule(n, rounds, seed=0, width=4):
    rng = np.random.default_rng(seed)
    po = rng.integers(0, n, size=(rounds, width)).astype(np.int32)
    po[rounds // 2:] = -1  # publish half the run, deliver the rest
    pt = np.zeros((rounds, width), np.int32)
    pv = np.ones((rounds, width), bool)
    return po, pt, pv


def _score_params():
    return PeerScoreParams(topics={0: TopicScoreParams()},
                           skip_app_specific=True)


def _gossip_cfg(chaos=None, heartbeat_every=1):
    return GossipSubConfig.build(
        GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1),
        PeerScoreThresholds(), score_enabled=True, chaos=chaos,
        heartbeat_every=heartbeat_every,
    )


# ---------------------------------------------------------------------------
# S=1 parity: batched == unbatched, full state trees, all engines


def _run_unbatched(step, st, po, pt, pv, net=None, **kw):
    for i in range(po.shape[0]):
        args = (jnp.asarray(po[i]), jnp.asarray(pt[i]), jnp.asarray(pv[i]))
        st = (step(net, st, *args, **kw) if net is not None
              else step(st, *args, **kw))
    return st


def _run_batched(ens, states, po, pt, pv, s, heartbeat=None):
    def margs(i):
        return (ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
                ensemble.tile(pv[i], s))

    run = ensemble.run_rounds(ens, states, margs, po.shape[0],
                              heartbeat_fn=heartbeat)
    return run


def test_s1_parity_floodsub():
    net = _net()
    po, pt, pv = _schedule(N, ROUNDS)
    cc = ChaosConfig(loss_rate=0.3)

    # fresh init per run: the jitted steps DONATE their state buffers,
    # so a tree that has been through one run is dead (same seed ->
    # identical init, including the key)
    def init():
        return SimState.init(N, M, seed=2, k=net.max_degree)

    st0 = init()  # key source + the batched seed state (never donated)
    ref = _run_unbatched(floodsub_step, init(), po, pt, pv, net=net,
                         chaos=cc)
    # the S=1 state derives sim key 0 — the unbatched reference must
    # too (the parity contract is per derived key)
    st1 = ensemble.with_sim_key(init(), st0.key, 0)
    ref1 = _run_unbatched(floodsub_step, st1, po, pt, pv, net=net, chaos=cc)
    ens = ensemble.lift_floodsub(net, chaos=cc)
    run = _run_batched(ens, ensemble.batch_states(st0, 1), po, pt, pv, 1)
    assert run.compiles == 1
    assert_trees_bitexact(ensemble.unbatch(run.states, 0), ref1,
                          "floodsub S=1 ")
    # sanity: the derived-key run is a DIFFERENT stream from the raw one
    assert not bool(jnp.array_equal(ref.key, ref1.key))


def test_s1_parity_randomsub():
    net = _net(seed=3)
    po, pt, pv = _schedule(N, ROUNDS, seed=3)
    step = make_randomsub_step(net)
    st0 = SimState.init(N, M, seed=4, k=net.max_degree)
    # the reference run gets its own init (donation kills the tree)
    ref = _run_unbatched(
        step,
        ensemble.with_sim_key(SimState.init(N, M, seed=4,
                                            k=net.max_degree),
                              st0.key, 0),
        po, pt, pv)
    ens = ensemble.lift_step(step)
    run = _run_batched(ens, ensemble.batch_states(st0, 1), po, pt, pv, 1)
    assert_trees_bitexact(ensemble.unbatch(run.states, 0), ref,
                          "randomsub S=1 ")


def test_s1_parity_gossipsub_per_round():
    net = _net(seed=5)
    po, pt, pv = _schedule(N, ROUNDS, seed=5)
    sp = _score_params()
    cfg = _gossip_cfg(chaos=ChaosConfig(generator="ge", ge_p_down=0.2,
                                        ge_p_up=0.4))
    st0 = GossipSubState.init(net, M, cfg, score_params=sp, seed=6)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    ref = _run_unbatched(
        step,
        ensemble.with_sim_key(
            GossipSubState.init(net, M, cfg, score_params=sp, seed=6),
            st0.core.key, 0),
        po, pt, pv)
    ens = ensemble.lift_step(step)
    run = _run_batched(ens, ensemble.batch_states(st0, 1), po, pt, pv, 1)
    assert_trees_bitexact(ensemble.unbatch(run.states, 0), ref,
                          "gossipsub S=1 ")


# heavy compile: the r=8 case rides the slow tier with the other big
# phase parity suites (tests/test_phase_stacked.py policy)
@pytest.mark.parametrize(
    "r", [1, pytest.param(8, marks=pytest.mark.slow)])
def test_s1_parity_phase_stacked_wire(r):
    net = _net(seed=7)
    n_phases = 2
    po, pt, pv = _schedule(N, n_phases * r, seed=7)
    po3 = po.reshape(n_phases, r, -1)
    pt3 = pt.reshape(n_phases, r, -1)
    pv3 = pv.reshape(n_phases, r, -1)
    sp = _score_params()
    cfg = _gossip_cfg(heartbeat_every=max(r, 1))
    assert cfg.wire_coalesced  # the stacked coalesced path is the default
    st0 = GossipSubState.init(net, M, cfg, score_params=sp, seed=8)
    step = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
    ref = ensemble.with_sim_key(
        GossipSubState.init(net, M, cfg, score_params=sp, seed=8),
        st0.core.key, 0)
    for p in range(n_phases):
        ref = step(ref, jnp.asarray(po3[p]), jnp.asarray(pt3[p]),
                   jnp.asarray(pv3[p]), do_heartbeat=True)
    ens = ensemble.lift_step(step)

    def margs(p):
        return (ensemble.tile(po3[p], 1), ensemble.tile(pt3[p], 1),
                ensemble.tile(pv3[p], 1))

    run = ensemble.run_rounds(ens, ensemble.batch_states(st0, 1), margs,
                              n_phases, rounds_per_phase=r,
                              heartbeat_fn=lambda p: True)
    assert run.compiles == 1
    assert_trees_bitexact(ensemble.unbatch(run.states, 0), ref,
                          f"phase r={r} S=1 ")


# ---------------------------------------------------------------------------
# sim-i parity at S>1 + stream independence


def test_sim_parity_and_independence_batched():
    net = _net(seed=9)
    po, pt, pv = _schedule(N, ROUNDS, seed=9)
    cc = ChaosConfig(generator="ge", ge_p_down=0.25, ge_p_up=0.4)
    sp = _score_params()
    cfg = _gossip_cfg(chaos=cc)
    st0 = GossipSubState.init(net, M, cfg, score_params=sp, seed=10)
    base_key = st0.core.key
    step = make_gossipsub_step(cfg, net, score_params=sp)
    ens = ensemble.lift_step(step)
    s = 3
    run = _run_batched(ens, ensemble.batch_states(st0, s), po, pt, pv, s)
    assert run.compiles == 1
    # every sim bit-identical to its single-sim run under the derived key
    for i in range(s):
        ref = _run_unbatched(
            step,
            ensemble.with_sim_key(
                GossipSubState.init(net, M, cfg, score_params=sp, seed=10),
                base_key, i),
            po, pt, pv)
        assert_trees_bitexact(ensemble.unbatch(run.states, i), ref,
                              f"sim {i} ")
    # GE chaos chains (and hence fault histories) differ between sims
    ge = np.asarray(run.states.core.chaos.ge_bad)
    assert not np.array_equal(ge[0], ge[1])
    # the delivery planes differ too (sampler + fault independence)
    fr = np.asarray(run.states.core.dlv.first_round)
    assert not np.array_equal(fr[0], fr[1])


def test_fault_hash_streams_independent_per_sim():
    # the chaos counter-mode hash is keyed on the sim key, so fold_in
    # derivation alone must separate the streams — no engine in the loop
    net = _net(seed=11)
    key = jax.random.key(0)
    k0 = jax.random.fold_in(key, 0)
    k1 = jax.random.fold_in(key, 1)
    s0, s1 = chaos_faults.chaos_seed(k0), chaos_faults.chaos_seed(k1)
    assert int(s0) != int(s1)
    m0 = chaos_faults.iid_link_down(s0, net.nbr, jnp.int32(3), 0.5)
    m1 = chaos_faults.iid_link_down(s1, net.nbr, jnp.int32(3), 0.5)
    assert not bool(jnp.array_equal(m0, m1))
    # and sim 0's stream is the BASE run's stream under the same key
    # (what makes batched-vs-unbatched chaos bit-exact in the parity
    # tests above)
    assert int(chaos_faults.chaos_seed(k0)) == int(s0)


def test_sampler_streams_independent_per_sim():
    # randomsub's per-round fanout draw comes from fold_in(st.key, tick)
    # — per-sim keys must decorrelate it
    net = _net(seed=12)
    po, pt, pv = _schedule(N, ROUNDS, seed=12)
    step = make_randomsub_step(net)
    st0 = SimState.init(N, M, seed=13, k=net.max_degree)
    ens = ensemble.lift_step(step)
    run = _run_batched(ens, ensemble.batch_states(st0, 2), po, pt, pv, 2)
    fr = np.asarray(run.states.dlv.first_round)
    assert not np.array_equal(fr[0], fr[1])


def test_per_sim_scenario_inputs():
    # one program, S different scenarios: sim 0 has every link denied
    # (nothing can deliver), sim 1 a lossless wire
    net = _net(seed=14)
    po, pt, pv = _schedule(N, ROUNDS, seed=14)
    cc = ChaosConfig(scheduled=True)
    st0 = SimState.init(N, M, seed=15, k=net.max_degree)
    ens = ensemble.lift_floodsub(net, chaos=cc)
    deny = np.stack([np.ones(net.nbr.shape, bool),
                     np.zeros(net.nbr.shape, bool)])

    def margs(i):
        return (ensemble.tile(po[i], 2), ensemble.tile(pt[i], 2),
                ensemble.tile(pv[i], 2), jnp.asarray(deny))

    run = ensemble.run_rounds(ens, ensemble.batch_states(st0, 2), margs,
                              ROUNDS)
    fr = np.asarray(run.states.dlv.first_round)
    origin_free = fr.copy()
    # non-origin receipts only: origins stamp their own publishes
    for sim in range(2):
        o = np.asarray(run.states.msgs.origin[sim])
        live = o >= 0
        origin_free[sim][np.clip(o, 0, N - 1)[live],
                         np.nonzero(live)[0]] = -1
    assert (origin_free[0] < 0).all()       # total outage: no deliveries
    assert (origin_free[1] >= 0).any()      # lossless: traffic flowed


# ---------------------------------------------------------------------------
# sharding composition (conftest forces 8 virtual CPU devices)


@pytest.mark.parametrize("axis", ["sims", "peers"])
def test_shard_ensemble_state_parity(axis):
    # the two documented layouts (docs/DESIGN.md §10): sims sharded
    # across devices (S/D whole sims each, no steady-state collectives)
    # or the peer dim sharded as the unbatched state is. Placement must
    # not change a single bit vs the unplaced batched run.
    from go_libp2p_pubsub_tpu.parallel.sharding import make_mesh

    if jax.device_count() < 2:
        pytest.skip("needs a multi-device (virtual CPU) harness")
    net = _net(seed=27)
    po, pt, pv = _schedule(N, ROUNDS, seed=27)
    s = 8  # divisible by the 8 virtual devices (and N=48 by 8 for peers)
    st0 = SimState.init(N, M, seed=28, k=net.max_degree)
    ens = ensemble.lift_floodsub(net)
    gold = _run_batched(ens, ensemble.batch_states(st0, s), po, pt, pv, s)
    placed = ensemble.shard_ensemble_state(
        ensemble.batch_states(
            SimState.init(N, M, seed=28, k=net.max_degree), s),
        make_mesh(), N, axis=axis)
    run = _run_batched(ens, placed, po, pt, pv, s)
    assert_trees_bitexact(run.states, gold.states, f"{axis}-sharded ")


# ---------------------------------------------------------------------------
# checkpointing


def test_checkpoint_batched_roundtrip_no_version_bump(tmp_path):
    # the npz format is pytree-generic, so a batched tree checkpoints
    # as-is — same v6 format, no bump
    assert checkpoint._FORMAT_VERSION == 6
    net = _net(seed=16)
    po, pt, pv = _schedule(N, ROUNDS, seed=16)
    cc = ChaosConfig(generator="ge", ge_p_down=0.3, ge_p_up=0.5)
    st0 = SimState.init(N, M, seed=17, k=net.max_degree, chaos_ge=True)
    ens = ensemble.lift_floodsub(net, chaos=cc)
    run = _run_batched(ens, ensemble.batch_states(st0, 2), po, pt, pv, 2)
    path = str(tmp_path / "batched.npz")
    checkpoint.save(path, run.states)
    template = ensemble.batch_states(
        SimState.init(N, M, seed=17, k=net.max_degree, chaos_ge=True), 2)
    restored = checkpoint.restore(path, template)
    assert_trees_bitexact(restored, run.states, "batched roundtrip ")
    # resume parity: continuing the restored ensemble == uninterrupted
    po2, pt2, pv2 = _schedule(N, 3, seed=18)
    cont = _run_batched(ens, restored, po2, pt2, pv2, 2)
    gold = _run_batched(ens, run.states, po2, pt2, pv2, 2)
    assert_trees_bitexact(cont.states, gold.states, "batched resume ")


def test_checkpoint_per_sim_slice_v6_compatible(tmp_path):
    # an unbatched sim slice is a plain v6 state: it must round-trip
    # against an UNBATCHED template (the per-sim compatibility pin)
    net = _net(seed=19)
    po, pt, pv = _schedule(N, ROUNDS, seed=19)
    st0 = SimState.init(N, M, seed=20, k=net.max_degree)
    ens = ensemble.lift_floodsub(net)
    run = _run_batched(ens, ensemble.batch_states(st0, 2), po, pt, pv, 2)
    sim1 = ensemble.unbatch(run.states, 1)
    path = str(tmp_path / "sim1.npz")
    checkpoint.save(path, sim1)
    template = SimState.init(N, M, seed=20, k=net.max_degree)
    restored = checkpoint.restore(path, template)
    assert_trees_bitexact(restored, sim1, "per-sim slice ")
    # and a batched checkpoint must REFUSE an unbatched template with
    # the pytree-path mismatch message, not load garbage
    bpath = str(tmp_path / "batched.npz")
    checkpoint.save(bpath, run.states)
    with pytest.raises(ValueError, match="shape"):
        checkpoint.restore(bpath, template)


# ---------------------------------------------------------------------------
# stats


def test_sim_delivery_ratios_match_host_metrics():
    net = _net(seed=21)
    po, pt, pv = _schedule(N, ROUNDS, seed=21)
    cc = ChaosConfig(loss_rate=0.4)
    st0 = SimState.init(N, M, seed=22, k=net.max_degree)
    ens = ensemble.lift_floodsub(net, chaos=cc)
    s = 3
    run = _run_batched(ens, ensemble.batch_states(st0, s), po, pt, pv, s)
    ratios = np.asarray(estats.sim_delivery_ratios(
        run.states.dlv.first_round, run.states.msgs.birth,
        run.states.msgs.topic, run.states.msgs.origin, net.subscribed,
    ))
    for i in range(s):
        want = delivery_stats(
            np.asarray(run.states.dlv.first_round[i]),
            np.asarray(run.states.msgs.birth[i]),
            np.asarray(run.states.msgs.topic[i]),
            np.asarray(run.states.msgs.origin[i]),
            np.asarray(net.subscribed),
        ).ratio
        assert ratios[i] == pytest.approx(want, abs=1e-6)
    # the flap made sims differ — the band is non-degenerate
    band = estats.quantile_band(ratios)
    assert band["n"] == s and band["n_undefined"] == 0
    assert band["min"] <= band["q50"] <= band["max"]
    lo, hi = estats.bootstrap_ci(ratios, n_boot=200)
    assert lo <= np.median(ratios) <= hi


def test_latency_cdf_bands_shapes_and_pooling():
    # hand-built histograms: sim 0 delivers everything at latency 1,
    # sim 1 at latency 3
    counts = np.zeros((2, 5), np.int64)
    counts[0, 1] = 10
    counts[1, 3] = 10
    out = estats.cdf_bands(counts, qs=(0.0, 0.5, 1.0))
    assert out["pooled"].shape == (5,)
    assert out["bands"].shape == (3, 5)
    # pooled CDF: half the mass at latency >= 1, all by 3
    assert out["pooled"][0] == 0.0
    assert out["pooled"][1] == pytest.approx(0.5)
    assert out["pooled"][3] == pytest.approx(1.0)
    # the band at latency 1 spans sim 1's 0.0 to sim 0's 1.0
    assert out["bands"][0, 1] == pytest.approx(0.0)
    assert out["bands"][2, 1] == pytest.approx(1.0)


def test_latency_cdf_counts_device():
    net = _net(seed=23)
    po, pt, pv = _schedule(N, ROUNDS, seed=23)
    st0 = SimState.init(N, M, seed=24, k=net.max_degree)
    ens = ensemble.lift_floodsub(net)
    run = _run_batched(ens, ensemble.batch_states(st0, 2), po, pt, pv, 2)
    hist = np.asarray(estats.latency_cdf_counts(
        run.states.dlv.first_round, run.states.msgs.birth,
        run.states.msgs.topic, run.states.msgs.origin, net.subscribed,
        max_lat=8,
    ))
    assert hist.shape == (2, 9)
    # lossless wire: every expected pair delivers; totals match the
    # device delivery count
    fr = np.asarray(run.states.dlv.first_round)
    for i in range(2):
        exp_pairs = delivery_stats(
            fr[i], np.asarray(run.states.msgs.birth[i]),
            np.asarray(run.states.msgs.topic[i]),
            np.asarray(run.states.msgs.origin[i]),
            np.asarray(net.subscribed),
        )
        assert hist[i].sum() == exp_pairs.delivered


def test_batched_counter_events_drain():
    net = _net(seed=25)
    po, pt, pv = _schedule(N, ROUNDS, seed=25)
    cc = ChaosConfig(loss_rate=0.5)
    st0 = SimState.init(N, M, seed=26, k=net.max_degree)
    ens = ensemble.lift_floodsub(net, chaos=cc)
    run = _run_batched(ens, ensemble.batch_states(st0, 2), po, pt, pv, 2)
    per_sim, totals = batched_counter_events(run.states.events)
    assert len(per_sim) == 2
    # exact per sim: each row equals the unbatched counter_events dict
    ev = np.asarray(run.states.events)
    for i in range(2):
        assert per_sim[i]["LINK_DOWN"] == int(ev[i][13])
        assert per_sim[i]["PUBLISH_MESSAGE"] == int(ev[i][0])
    assert totals["LINK_DOWN"] == sum(d["LINK_DOWN"] for d in per_sim)
    # independent fault streams -> (almost surely) different link tallies
    assert per_sim[0]["LINK_DOWN"] > 0
    with pytest.raises(ValueError, match="batched"):
        batched_counter_events(ev[0])


def test_mesh_reform_latency_semantics():
    # the band-robust partition-repair metric (chaos/metrics.py):
    # trough (<= prune_floor) then re-formation (>= min_edges)
    from go_libp2p_pubsub_tpu.chaos import mesh_reform_latency

    arc = [(10, 30), (12, 8), (14, 1), (18, 2), (22, 9)]
    assert mesh_reform_latency(arc, heal_tick=10) == 12
    # never troughs but stays connected: connectivity never collapsed
    assert mesh_reform_latency(
        [(10, 30), (14, 12), (18, 15)], heal_tick=10) == 0
    # troughs and never re-forms
    assert mesh_reform_latency(
        [(10, 30), (14, 0), (18, 3)], heal_tick=10) is None
    # hovers below min_edges without ever recovering
    assert mesh_reform_latency(
        [(10, 30), (14, 4), (18, 5)], heal_tick=10) is None
    # pre-heal readings are ignored entirely
    assert mesh_reform_latency(
        [(2, 0), (10, 30), (12, 1), (16, 7)], heal_tick=10) == 6


def test_iwant_shares_batched():
    ev = np.zeros((2, 15), np.int64)
    ev[0, 3] = 100  # DELIVER_MESSAGE
    ev[0, 14] = 25  # IWANT_RECOVER
    shares = estats.batched_iwant_shares(ev)
    assert shares[0] == pytest.approx(0.25)
    assert shares[1] == 0.0
