"""Telemetry plane tests (go_libp2p_pubsub_tpu/telemetry/, docs/DESIGN.md
§11).

The load-bearing contracts:

  * **exact reconciliation** — summed per-observation EV deltas of the
    on-device panel equal the end-of-run drained counters BIT-FOR-BIT,
    for every engine (per-round gossipsub incl. churn, phase r∈{1,8} on
    the stacked coalesced wire path, floodsub, randomsub) and per sim in
    a batched S=3 run. A panel that drifts from the counters is lying
    about the run.
  * **elision when off** — ``telemetry=None`` builds add NO state
    leaves and change NOTHING: stripping the ``telem`` leaves from a
    telemetry-on run leaves a tree bit-identical to the telemetry-off
    run (the recorder is purely additive; `make telemetry-smoke`
    additionally pins the chaos-off/telemetry-off compiled kernel
    census against the committed PERF_SMOKE baseline).
  * **registry parity** — every EV member maps to a reference tracer
    event name (pb/trace.proto via trace_pb2) or is listed in the
    documented sim-only set ``trace/drain.py::COUNTER_ONLY_EVENTS``;
    the panel's metric catalog mirrors the enum positionally.
  * **checkpoint carry** — the telemetry panel rides the v6 format
    with NO version bump (v6 is pytree-generic), and template/state
    telemetry settings must match.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, ensemble, graph
from go_libp2p_pubsub_tpu.chaos import ChaosConfig
from go_libp2p_pubsub_tpu.config import GossipSubParams, PeerScoreThresholds
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import make_gossipsub_phase_step
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.pb import trace_pb2
from go_libp2p_pubsub_tpu.state import Net, SimState
from go_libp2p_pubsub_tpu.telemetry import (
    EV_METRICS,
    FLIGHT_METRICS,
    METRICS,
    N_FLIGHT,
    N_METRICS,
    RECONCILED,
    TelemetryConfig,
    TelemetryState,
    metric_index,
    panel_ev_totals,
    reconcile,
    reconcile_batched,
    rows_used,
    timeline_block,
)
from go_libp2p_pubsub_tpu.telemetry.panel import TelemetryConfigError
from go_libp2p_pubsub_tpu.trace import drain
from go_libp2p_pubsub_tpu.trace.events import EV, N_EVENTS

from test_phase import assert_states_equal, score_params

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IID = ChaosConfig(loss_rate=0.35)
N, D, M, P = 32, 6, 64, 3


def _net(n=N, seed=0, n_topics=1):
    topo = graph.random_connect(n, d=D, seed=seed)
    subs = graph.subscribe_all(n, n_topics)
    return Net.build(topo, subs)


def _build_gossip(seed=0, chaos=IID, telemetry=None, n=N, **cfg_kw):
    net = _net(n=n, seed=seed)
    sp = score_params()
    params = dataclasses.replace(GossipSubParams(), flood_publish=True)
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(),
                                score_enabled=True, chaos=chaos, **cfg_kw)
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed,
                             telemetry=telemetry)
    return net, cfg, sp, st


def _schedule(rounds, seed=0, n=N):
    rng = np.random.default_rng(seed)
    po = rng.integers(0, n, size=(rounds, P)).astype(np.int32)
    pt = np.zeros((rounds, P), np.int32)
    pv = np.ones((rounds, P), bool)
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def _strip_telem(tree):
    """Leaf (path, value) pairs excluding the telemetry plane."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(p), v) for p, v in flat
            if "telem" not in jax.tree_util.keystr(p)]


# ---------------------------------------------------------------------------
# EV registry <-> reference tracer parity (the drift audit)


def test_ev_registry_matches_reference_tracer():
    """Every EV member is either a pb/trace.proto TraceEvent.Type name
    (the Go tracer's event registry) or listed in the documented
    sim-only set next to drain.COUNTER_ONLY_EVENTS — and vice versa:
    every reference type has an EV member. Catches silent drift in
    BOTH directions when either registry grows."""
    ref_names = set(trace_pb2.TraceEvent.Type.keys())
    sim_only = set(drain.COUNTER_ONLY_EVENTS)
    for e in EV:
        if e in sim_only:
            # sim-only counters must NOT shadow a reference event name
            assert e.name not in ref_names, (
                f"EV.{e.name} is in COUNTER_ONLY_EVENTS but the "
                "reference tracer HAS that event type — it must be "
                "drained as TraceEvents, not counter-only"
            )
        else:
            assert e.name in ref_names, (
                f"EV.{e.name} maps to no pb/trace.proto TraceEvent.Type "
                "and is not in drain.COUNTER_ONLY_EVENTS — either add "
                "the proto mapping or document it as sim-only"
            )
    for name in ref_names:
        assert name in EV.__members__, (
            f"reference trace event {name} has no EV member — the "
            "device counters cannot count it"
        )
    # the documented sim-only set is exactly the enum tail the proto
    # does not know, and the codes beyond it stay contiguous
    assert sim_only == {e for e in EV if e.name not in ref_names}


def test_metric_catalog_mirrors_ev_enum():
    """The panel writes the EV delta vector by position — the catalog
    must mirror the enum exactly (the telemetry-panel simlint rule
    pins the same contract at lint time)."""
    assert METRICS[0] == "delivery_ratio"
    assert list(EV_METRICS) == [f"ev_{e.name.lower()}" for e in EV]
    assert N_METRICS == 1 + N_EVENTS + 7
    assert RECONCILED == EV_METRICS
    assert metric_index("ev_deliver_message") == 1 + int(EV.DELIVER_MESSAGE)
    assert N_FLIGHT == len(FLIGHT_METRICS)
    with pytest.raises(TelemetryConfigError):
        TelemetryConfig(rows=0).validate()
    with pytest.raises(TelemetryConfigError):
        TelemetryConfig(rows=4, tracked=[0, 1]).validate()  # not hashable


# ---------------------------------------------------------------------------
# drain-vs-timeline reconciliation (the correctness anchor)


def test_reconcile_gossipsub_under_chaos_and_churn():
    rounds = 12
    tcfg = TelemetryConfig(rows=rounds)
    net, cfg, sp, st = _build_gossip(seed=3, telemetry=tcfg)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               dynamic_peers=True, telemetry=tcfg)
    po, pt, pv = _schedule(rounds, seed=3)
    up = np.ones((rounds, N), bool)
    up[4:8, 5] = False   # peer 5 leaves and returns (ADD/REMOVE_PEER)
    up[6:, 11] = False   # peer 11 leaves for good
    for i in range(rounds):
        st = step(st, po[i], pt[i], pv[i], jnp.asarray(up[i]))
    panel = np.asarray(st.core.telem.panel)
    events = np.asarray(st.core.events)
    assert reconcile(panel, events) == []
    # the run actually moved: deliveries, churn and chaos all recorded
    totals = panel_ev_totals(panel)
    assert totals[EV.DELIVER_MESSAGE] > 0
    assert totals[EV.REMOVE_PEER] >= 2 and totals[EV.ADD_PEER] >= 1
    assert totals[EV.LINK_DOWN] > 0
    dr = panel[:, metric_index("delivery_ratio")]
    assert 0.0 <= dr.min() and dr.max() <= 1.0
    deg = panel[:, metric_index("mesh_deg_mean")]
    assert deg[-1] > 0.0  # the mesh formed


@pytest.mark.parametrize("r", [1, pytest.param(8, marks=pytest.mark.slow)])
def test_reconcile_phase_stacked_wire(r):
    """Phase engine on the stacked coalesced wire path: ONE row per
    phase whose deltas cover all r sub-rounds + control head +
    heartbeat, so the panel still telescopes to the drained totals."""
    rounds = 16
    tcfg = TelemetryConfig(rows=rounds // r)
    net, cfg, sp, st = _build_gossip(seed=7, telemetry=tcfg)
    assert cfg.wire_coalesced
    pstep = make_gossipsub_phase_step(cfg, net, r, score_params=sp,
                                      telemetry=tcfg)
    po, pt, pv = _schedule(rounds, seed=7)
    g = rounds // r
    gro = lambda a: a.reshape((g, r) + a.shape[1:])
    po, pt, pv = gro(po), gro(pt), gro(pv)
    for p in range(g):
        st = pstep(st, po[p], pt[p], pv[p], do_heartbeat=True)
    panel = np.asarray(st.core.telem.panel)
    assert reconcile(panel, np.asarray(st.core.events)) == []
    assert panel_ev_totals(panel)[EV.DELIVER_MESSAGE] > 0
    assert rows_used(panel, rounds, rounds_per_row=r) == g


def test_reconcile_floodsub_randomsub_under_chaos():
    net = _net(seed=2)
    rounds = 10
    tcfg = TelemetryConfig(rows=rounds)
    po, pt, pv = _schedule(rounds, seed=2)
    st = SimState.init(N, M, seed=2, k=net.max_degree, telemetry=tcfg)
    for i in range(rounds):
        st = floodsub_step(net, st, po[i], pt[i], pv[i], chaos=IID,
                           telemetry=tcfg)
    panel = np.asarray(st.telem.panel)
    assert reconcile(panel, np.asarray(st.events)) == []
    assert panel_ev_totals(panel)[EV.DELIVER_MESSAGE] > 0
    # mesh-less engine: the mesh/score columns record zeros
    assert not panel[:, metric_index("mesh_deg_mean")].any()
    assert not panel[:, metric_index("score_p50")].any()

    step = make_randomsub_step(net, chaos=IID, telemetry=tcfg)
    st = SimState.init(N, M, seed=3, k=net.max_degree, telemetry=tcfg)
    for i in range(rounds):
        st = step(st, po[i], pt[i], pv[i])
    panel = np.asarray(st.telem.panel)
    assert reconcile(panel, np.asarray(st.events)) == []
    assert panel_ev_totals(panel)[EV.DELIVER_MESSAGE] > 0


@pytest.mark.slow
def test_reconcile_batched_s3_per_sim_exact():
    """S=3 vmapped run: every sim's panel reconciles against ITS OWN
    drained counters, and sim i's panel is bit-identical to the
    single-sim run built with the derived key fold_in(sim_key, i)
    (threefry batches elementwise — the ensemble parity contract)."""
    s, rounds = 3, 10
    tcfg = TelemetryConfig(rows=rounds)
    net, cfg, sp, st0 = _build_gossip(seed=5, telemetry=tcfg)
    step = make_gossipsub_step(cfg, net, score_params=sp, telemetry=tcfg)
    base_key = st0.core.key
    po, pt, pv = _schedule(rounds, seed=5)
    ens = ensemble.lift_step(step)
    states = ensemble.batch_states(st0, s)
    for i in range(rounds):
        states = ens(states, ensemble.tile(po[i], s),
                     ensemble.tile(pt[i], s), ensemble.tile(pv[i], s))
    panels = np.asarray(states.core.telem.panel)
    events = np.asarray(states.core.events)
    assert panels.shape == (s, rounds, N_METRICS)
    assert reconcile_batched(panels, events) == []
    # sims are genuinely different runs (independent fault streams)
    assert not np.array_equal(panels[0], panels[1])
    for i in range(s):
        net_i, cfg_i, sp_i, st_i = _build_gossip(seed=5, telemetry=tcfg)
        st_i = ensemble.with_sim_key(st_i, base_key, i)
        for t in range(rounds):
            st_i = step(st_i, po[t], pt[t], pv[t])
        single = np.asarray(st_i.core.telem.panel)
        # the reconciled columns (delivery ratio + EV deltas — exact
        # integer arithmetic) are BIT-identical per sim; the derived f32
        # state stats (means/quantiles) may differ by float epsilon
        # because vmap changes the XLA reduction order
        np.testing.assert_array_equal(
            panels[i][:, : 1 + N_EVENTS], single[:, : 1 + N_EVENTS],
            err_msg=f"sim {i} batched EV/delivery columns != single-sim",
        )
        np.testing.assert_allclose(
            panels[i], single, rtol=1e-5, atol=1e-6,
            err_msg=f"sim {i} batched panel != its single-sim panel",
        )


def test_rows_past_capacity_drop_without_wrap():
    """Observations beyond the panel capacity DROP (no wraparound — a
    wrapped panel would silently break the reconciliation sums)."""
    net = _net(seed=4)
    tcfg = TelemetryConfig(rows=4)
    po, pt, pv = _schedule(8, seed=4)
    st = SimState.init(N, M, seed=4, k=net.max_degree, telemetry=tcfg)
    for i in range(4):
        st = floodsub_step(net, st, po[i], pt[i], pv[i], telemetry=tcfg)
    first4 = np.asarray(st.telem.panel)
    assert reconcile(first4, np.asarray(st.events)) == []
    for i in range(4, 8):
        st = floodsub_step(net, st, po[i], pt[i], pv[i], telemetry=tcfg)
    np.testing.assert_array_equal(np.asarray(st.telem.panel), first4)
    assert rows_used(st.telem.panel, 8, rounds_per_row=1) == 4


# ---------------------------------------------------------------------------
# elision when off: telemetry must be purely additive


def test_telemetry_off_adds_no_state_leaves():
    net = _net(seed=0)
    off = SimState.init(N, M, seed=0, k=net.max_degree)
    assert off.telem is None
    assert not any("telem" in p for p, _ in
                   jax.tree_util.tree_flatten_with_path(off)[0]
                   for p in [jax.tree_util.keystr(p)])
    _, _, _, goff = _build_gossip(seed=0)
    assert goff.core.telem is None


def test_telemetry_on_is_bitwise_additive():
    """Same seed, telemetry on vs off: stripping the telem leaves from
    the on-run leaves a tree BIT-IDENTICAL to the off-run — recording
    a panel changes nothing else about the simulation."""
    rounds = 8
    po, pt, pv = _schedule(rounds, seed=6)
    finals = []
    for tcfg in (None, TelemetryConfig(rows=rounds, tracked=(0, 3))):
        net, cfg, sp, st = _build_gossip(seed=6, telemetry=tcfg)
        step = make_gossipsub_step(cfg, net, score_params=sp,
                                   telemetry=tcfg)
        for i in range(rounds):
            st = step(st, po[i], pt[i], pv[i])
        finals.append(st)
    off_leaves = _strip_telem(finals[0])
    on_leaves = _strip_telem(finals[1])
    assert [p for p, _ in off_leaves] == [p for p, _ in on_leaves]
    for (path, a), (_, b) in zip(off_leaves, on_leaves):
        if jnp.issubdtype(getattr(a, "dtype", None), jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"telemetry-on run diverged from off at {path}"
        )


# ---------------------------------------------------------------------------
# flight recorder


def test_flight_recorder_tracks_peer_trajectories():
    rounds = 10
    tracked = (0, 9, 17)
    tcfg = TelemetryConfig(rows=rounds, tracked=tracked)
    net, cfg, sp, st = _build_gossip(seed=8, telemetry=tcfg)
    step = make_gossipsub_step(cfg, net, score_params=sp, telemetry=tcfg)
    po, pt, pv = _schedule(rounds, seed=8)
    for i in range(rounds):
        st = step(st, po[i], pt[i], pv[i])
    flight = np.asarray(st.core.telem.flight)
    assert flight.shape == (rounds, len(tracked), N_FLIGHT)
    # the LAST row snapshots the final state's planes exactly
    mesh = np.asarray(st.mesh)
    have = st.core.dlv.have
    fi = {m: i for i, m in enumerate(FLIGHT_METRICS)}
    for k, peer in enumerate(tracked):
        assert flight[-1, k, fi["mesh_degree"]] == mesh[peer].sum()
        held = int(np.asarray(bitset.popcount(have[peer], axis=-1)))
        assert flight[-1, k, fi["msgs_held"]] == held
    # the mesh formed over the run: some tracked peer's degree moved
    assert flight[:, :, fi["mesh_degree"]].max() > 0
    # no flight plane without tracked peers (no extra leaf when unused)
    assert TelemetryState.empty(TelemetryConfig(rows=4)).flight is None


# ---------------------------------------------------------------------------
# checkpoint carry (v6-generic — no format bump)


def test_checkpoint_roundtrip_telemetry_carry(tmp_path):
    assert checkpoint._FORMAT_VERSION == 6, (
        "the telemetry plane must ride the pytree-generic v6 format "
        "WITHOUT a version bump — a bump here breaks every committed "
        "v6 checkpoint for no format reason"
    )
    rounds = 6
    tcfg = TelemetryConfig(rows=rounds, tracked=(2,))
    net, cfg, sp, st = _build_gossip(seed=9, telemetry=tcfg)
    step = make_gossipsub_step(cfg, net, score_params=sp, telemetry=tcfg)
    po, pt, pv = _schedule(rounds, seed=9)
    for i in range(4):
        st = step(st, po[i], pt[i], pv[i])
    path = os.path.join(tmp_path, "telem.ckpt")
    checkpoint.save(path, st)
    template = GossipSubState.init(net, M, cfg, score_params=sp, seed=9,
                                   telemetry=tcfg)
    resumed = checkpoint.restore(path, template)
    assert_states_equal(st, resumed, "telem-ckpt/")
    # resumed run == uninterrupted run, panel included
    st2 = resumed
    for i in range(4, rounds):
        st = step(st, po[i], pt[i], pv[i])
        st2 = step(st2, po[i], pt[i], pv[i])
    assert_states_equal(st, st2, "telem-resume/")
    assert reconcile(np.asarray(st2.core.telem.panel),
                     np.asarray(st2.core.events)) == []
    # a telemetry-off template must refuse the telemetry-on snapshot
    off_template = GossipSubState.init(net, M, cfg, score_params=sp, seed=9)
    with pytest.raises(ValueError, match="telem|leaves|leaf"):
        checkpoint.restore(path, off_template)


# ---------------------------------------------------------------------------
# artifact plumbing: schema-v3 timeline block


def test_timeline_block_and_artifact_roundtrip():
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        TELEMETRY_OFF,
        BenchRecord,
        dump_record,
        record_from_line,
    )
    import json as _json

    rng = np.random.default_rng(0)
    panels = rng.random((3, 6, N_METRICS)).astype(np.float32)
    tl = timeline_block(panels, rounds_per_row=2)
    assert tl["enabled"] and tl["n_sims"] == 3 and tl["rows"] == 6
    assert tl["metrics"] == list(METRICS)
    assert set(tl["series"]) == set(METRICS)
    q = tl["series"]["delivery_ratio"]
    assert set(q) == {"q25", "q50", "q75"} and len(q["q50"]) == 6
    med = np.quantile(panels.astype(np.float64), 0.5, axis=0)
    np.testing.assert_allclose(q["q50"], med[:, 0], atol=1e-5)
    # single-sim panels degenerate to the same shape
    one = timeline_block(panels[0])
    assert one["n_sims"] == 1 and one["series"]["delivery_ratio"]["q25"] \
        == one["series"]["delivery_ratio"]["q75"]

    rec = BenchRecord(metric="m", value=1.0, unit="x", vs_baseline=0.0,
                      schema=3, timeline_raw=tl)
    back = record_from_line(_json.loads(dump_record(rec)))
    assert back.telemetry_on and back.timeline["rows"] == 6
    assert back.timeline["rounds_per_row"] == 2
    # legacy lines read back TELEMETRY_OFF
    legacy = record_from_line({"metric": "m", "value": 1.0, "unit": "x",
                               "vs_baseline": 0.0})
    assert not legacy.telemetry_on
    assert legacy.timeline == TELEMETRY_OFF


def test_panel_bands_matches_host_quantiles():
    from go_libp2p_pubsub_tpu.ensemble import stats as estats

    rng = np.random.default_rng(1)
    panels = rng.random((5, 7, N_METRICS)).astype(np.float32)
    bands = estats.panel_bands(panels, qs=(0.25, 0.5, 0.75))
    assert bands.shape == (3, 7, N_METRICS)
    np.testing.assert_allclose(
        bands[1], np.quantile(panels, 0.5, axis=0), atol=1e-6)


# ---------------------------------------------------------------------------
# the committed chaos band: run_report renders the repair arc


def _committed_records():
    path = os.path.join(REPO_ROOT, "TIMELINE_CHAOS.json")
    if not os.path.exists(path):
        pytest.skip("TIMELINE_CHAOS.json not committed in this checkout")
    from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines

    return load_bench_lines(path)


def test_committed_timeline_band_renders_repair_arc():
    """Acceptance pin: the committed 60%-loss 8-sim chaos band renders
    a dashboard whose partition cell shows the trough→re-form
    mesh-repair arc, with the re-form latency chaos.metrics measured
    (median ~25 ticks, round-10 band)."""
    import sys

    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    import run_report

    records = _committed_records()
    by_metric = {r.metric: r for r in records}
    flap = by_metric["chaos_flap_delivery_ratio_gossipsub"]
    part = by_metric["chaos_partition_delivery_ratio"]
    # the committed cells are the canonical smoke shape
    assert flap.chaos["loss_rate"] == 0.6 and flap.n_sims == 8
    assert flap.telemetry_on and part.telemetry_on
    # flap: v1.1 gossip holds delivery up under 60% loss
    assert flap.value > 0.8
    dr = flap.timeline["series"]["delivery_ratio"]["q50"]
    assert dr[-1] > 0.8 and dr[-1] >= dr[2]
    # partition: the repair arc — pre-partition cross mesh, starvation
    # prune trough, then the re-graft wave after heal
    cm = part.extras["cross_mesh_series"]
    ticks, q50 = cm["ticks"], cm["q50"]
    heal = part.extras["partition_window"][1]
    pre = q50[0]
    trough = min(q50)
    assert trough < 0.25 * pre, (pre, trough)
    post_heal = [v for t, v in zip(ticks, q50) if t > heal]
    assert max(post_heal) > 3 * max(trough, 1.0), "cross mesh never re-formed"
    # the reported latency is the chaos.metrics reading of that series
    lat = part.extras["mesh_reform_latency_median"]
    assert 10 <= lat <= 45, f"mesh re-form median {lat} drifted from ~25"
    # the dashboard renders self-contained, with the arc + CDF sections
    html = run_report.render_html(records, title="t")
    assert "repair arc" in html and "Delivery ratio" in html
    assert "Delivery-latency CDF" in html
    assert "<script src=" not in html  # self-contained: no external assets
    md = run_report.render_markdown(records)
    assert "delivery_ratio" in md and "chaos_partition_delivery_ratio" in md


def test_run_report_renders_legacy_artifact_as_stub():
    import sys

    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    import run_report

    from go_libp2p_pubsub_tpu.perf.artifacts import record_from_line

    legacy = record_from_line({"metric": "m", "value": 1.0, "unit": "x",
                               "vs_baseline": 0.0})
    html = run_report.render_html([legacy])
    assert "TELEMETRY_OFF" in html


def test_reconcile_router_counters_and_off_negative():
    """Round 24: a router run reconciles exactly — the four new EV
    columns (ev_idontwant_sent / ev_dup_suppressed / ev_choke /
    ev_unchoke) telescope to the drained counters like every other
    metric — and the seeded NEGATIVE: a router-off run of the same
    schedule records those columns identically zero (the panel must
    not invent router traffic a v1.1 build never generated)."""
    from go_libp2p_pubsub_tpu.routers import RouterConfig

    rounds = 24
    router_cols = ("ev_idontwant_sent", "ev_dup_suppressed",
                   "ev_choke", "ev_unchoke")
    rc = RouterConfig(idontwant=True, choke=True, choke_ema_alpha=0.5,
                      choke_threshold=0.25, unchoke_threshold=0.05)

    def run(router):
        tcfg = TelemetryConfig(rows=rounds)
        net, cfg, sp, st = _build_gossip(seed=5, telemetry=tcfg,
                                         router=router)
        step = make_gossipsub_step(cfg, net, score_params=sp,
                                   telemetry=tcfg)
        po, pt, pv = _schedule(rounds, seed=5)
        for i in range(rounds):
            st = step(st, po[i], pt[i], pv[i])
        return np.asarray(st.core.telem.panel), np.asarray(st.core.events)

    panel, events = run(rc)
    assert reconcile(panel, events) == []
    totals = panel_ev_totals(panel)
    assert totals[EV.IDONTWANT_SENT] > 0
    assert totals[EV.DUP_SUPPRESSED] > 0
    assert totals[EV.CHOKE] > 0
    # the columns are the counters, positionally (catalog mirrors enum)
    for col in router_cols:
        e = EV[col[3:].upper()]
        assert panel[:, metric_index(col)].sum() == pytest.approx(
            float(events[e]))

    # seeded negative: router=None — same schedule, zero router columns
    panel0, events0 = run(None)
    assert reconcile(panel0, events0) == []
    for col in router_cols:
        assert not panel0[:, metric_index(col)].any()
        assert events0[EV[col[3:].upper()]] == 0
