"""Analysis-plane tests: every simlint rule and every trace guard must
FIRE on a deliberately broken snippet/config (negative), and the repo
itself must pass clean (positive) — so `make analyze` is demonstrably a
live gate, not a rubber stamp. docs/DESIGN.md §9."""

import json
import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.analysis import guards, simlint
from go_libp2p_pubsub_tpu.analysis.guards import (
    EngineHarness,
    GuardViolation,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "go_libp2p_pubsub_tpu")


def lint(src, rel="models/broken.py"):
    return simlint.lint_source(textwrap.dedent(src), rel)


def rules_of(violations):
    return {v.rule for v in violations}


# ---------------------------------------------------------------------------
# simlint rules: each fires on a seeded violation


def test_traced_branch_fires():
    vs = lint("""
        import jax.numpy as jnp
        def step(x):
            if jnp.any(x > 0):
                return x
            return -x
    """)
    assert "traced-branch" in rules_of(vs)


def test_traced_branch_ignores_host_numpy():
    # the calibrated exception: eager numpy branching (detect_banded,
    # chaos/metrics) is host-side and must NOT fire
    vs = lint("""
        import numpy as np
        def detect(nbr, ok):
            if not ok.all():
                return None
            return np.where(ok, nbr, -1)
    """, rel="ops/edges.py")
    assert vs == []


def test_host_sync_item_fires():
    vs = lint("""
        def drain(state):
            return state.events.item()
    """)
    assert "host-sync" in rules_of(vs)


def test_host_sync_nested_fn_fires_once():
    # scoped walking: a violation in a nested def is reported exactly
    # once (in its own scope), not re-reported per enclosing function
    vs = lint("""
        def make_step():
            def step(state):
                return state.events.item()
            return step
    """)
    assert len([v for v in vs if v.rule == "host-sync"]) == 1
    assert vs[0].qual == "make_step.step"


def test_host_sync_conversion_in_traced_step_fires():
    vs = lint("""
        import jax, numpy as np
        @jax.jit
        def step(state, pub):
            cap = int(state.tick)
            return np.asarray(pub)
    """)
    assert sum(v.rule == "host-sync" for v in vs) == 2


def test_traced_branch_through_alias_fires():
    # the round-16 alias-blindness fix (shared resolver: lift.py):
    # a Python if on a NAME assigned from a jnp expression was
    # previously invisible to the rule
    vs = lint("""
        import jax.numpy as jnp
        def step(x):
            w = jnp.any(x > 0)
            if w:
                return x
            return -x
    """)
    assert "traced-branch" in rules_of(vs)


def test_traced_branch_alias_of_alias_fires():
    vs = lint("""
        import jax.numpy as jnp
        def step(x):
            y = jnp.any(x > 0)
            w = y
            if w:
                return x
            return -x
    """)
    assert "traced-branch" in rules_of(vs)


def test_traced_branch_is_none_test_on_alias_ok():
    # identity tests of a traced alias are host-level — the calibrated
    # exception (window_g-style optional-plane plumbing)
    vs = lint("""
        import jax.numpy as jnp
        def step(x, w=None):
            w = jnp.sum(x) if w is None else w
            if w is None:
                return x
            return w
    """)
    assert vs == []


def test_traced_branch_shape_derived_alias_ok():
    # shape reads of a traced array are trace-time Python ints — a
    # branch on them is legal (bitset.pack's pad test), in both the
    # two-statement and the inline single-expression form
    vs = lint("""
        import jax.numpy as jnp
        def step(x):
            y = jnp.asarray(x)
            pad = y.shape[-1] % 32
            if pad:
                return y
            return -y
    """)
    assert vs == []
    vs = lint("""
        import jax.numpy as jnp
        def step(x):
            pad = jnp.asarray(x).shape[-1] % 32
            if pad:
                return x
            return -x
    """)
    assert vs == []


def test_host_sync_through_alias_chain_fires():
    # float() of an alias of a traced local — previously missed
    vs = lint("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def step(state, pub):
            y = jnp.sum(state)
            w = y
            return float(w)
    """)
    assert "host-sync" in rules_of(vs)


def test_config_hash_through_decorator_alias_fires():
    # `from dataclasses import dataclass as dc` previously made the
    # class invisible to the rule (silently skipped)
    vs = lint("""
        from dataclasses import dataclass as dc
        @dc
        class FlapConfig:
            x: int = 1
    """)
    assert "config-hash" in rules_of(vs)


def test_config_hash_struct_alias_still_exempt():
    vs = lint("""
        from flax import struct
        sd = struct.dataclass
        @sd
        class StateConfig:
            x: int = 1
    """)
    assert "config-hash" not in rules_of(vs)


def test_config_hash_struct_import_as_exempt():
    # `from flax import struct as fs` — the dotted tail must survive
    # the alias substitution so the struct exemption still fires
    vs = lint("""
        from flax import struct as fs
        @fs.dataclass
        class StateConfig:
            x: int = 1
    """)
    assert "config-hash" not in rules_of(vs)


def test_config_hash_frozen_through_partial_call_alias():
    # `dc = dataclasses.dataclass(frozen=True)` carries its frozen
    # keyword through the alias — no false 'mutable dataclass'
    vs = lint("""
        import dataclasses
        dc = dataclasses.dataclass(frozen=True)
        @dc
        class FooConfig:
            x: int = 1
    """)
    assert "config-hash" not in rules_of(vs)
    vs = lint("""
        import dataclasses
        dc = dataclasses.dataclass(frozen=False)
        @dc
        class FooConfig:
            x: int = 1
    """)
    assert "config-hash" in rules_of(vs)


def test_host_sync_static_conversion_ok():
    # float()/int() of closure statics inside a traced step are
    # trace-time constants, not per-call syncs
    vs = lint("""
        import jax, numpy as np
        cfg_threshold = 0.5
        sizes = np.cumsum([1, 2, 3])
        def make_step(cfg):
            @jax.jit
            def step(state, pub):
                thr = float(cfg_threshold)
                w = int(sizes[-1])
                return state
            return step
    """)
    assert vs == []


def test_prng_key_underived_fires():
    vs = lint("""
        import jax
        def make_step():
            def step(st, pub):
                return jax.random.uniform(st.key, (4,))
            return step
    """)
    assert "prng-key" in rules_of(vs)


def test_prng_key_reuse_fires():
    vs = lint("""
        import jax
        def pick(key, shape):
            a = jax.random.uniform(key, shape)
            b = jax.random.normal(key, shape)
            return a + b
    """)
    assert any(v.rule == "prng-key" and "second sampler" in v.msg for v in vs)


def test_prng_key_constant_in_step_fires():
    vs = lint("""
        import jax
        @jax.jit
        def step(st):
            k = jax.random.key(0)
            return st
    """)
    assert "prng-key" in rules_of(vs)


def test_prng_key_local_alias_of_state_key_fires():
    # provenance, not naming: 'key = st.key' is still raw-key reuse
    vs = lint("""
        import jax
        def make_step():
            def step(st, pub):
                key = st.key
                return jax.random.uniform(key, (4,))
            return step
    """)
    assert "prng-key" in rules_of(vs)


def test_prng_key_disciplined_ok():
    vs = lint("""
        import jax
        def heartbeat(st, tick):
            key = jax.random.fold_in(st.key, tick)
            k1, k2 = jax.random.split(key)
            noise = jax.random.uniform(k1, (4,))
            more = jax.random.uniform(k2, (4,))
            return noise + more
    """)
    assert vs == []


def test_word_dtype_fires():
    vs = lint("""
        import jax.numpy as jnp
        def bit_probe(words):
            return words & 1
    """, rel="ops/bitset.py")
    assert "word-dtype" in rules_of(vs)


def test_word_dtype_augassign_fires():
    vs = lint("""
        import jax.numpy as jnp
        def bit_probe(words):
            words &= 1
            return words
    """, rel="ops/bitset.py")
    assert "word-dtype" in rules_of(vs)


def test_word_dtype_wrapped_ok():
    vs = lint("""
        import jax.numpy as jnp
        def bit_probe(words):
            return words & jnp.uint32(1)
    """, rel="ops/bitset.py")
    assert vs == []


def test_import_exec_fires():
    vs = lint("""
        import jax.numpy as jnp
        TABLE = jnp.zeros((4,))
    """, rel="score/tables.py")
    assert "import-exec" in rules_of(vs)


def test_import_exec_lambda_factory_ok():
    vs = lint("""
        import jax.numpy as jnp
        from flax import struct
        class Info:
            n: int = struct.field(default_factory=lambda: jnp.int32(0))
    """, rel="models/info.py")
    assert vs == []


def test_config_hash_fires():
    vs = lint("""
        import dataclasses
        @dataclasses.dataclass
        class FlapConfig:
            rates: list = dataclasses.field(default_factory=list)
    """, rel="chaos/flap.py")
    got = [v for v in vs if v.rule == "config-hash"]
    assert len(got) == 2  # not frozen + unhashable field


def test_ev_drain_fires():
    vs = simlint.check_ev_drain(
        ["DELIVER_MESSAGE", "LINK_DOWN", "ORPHANED"],
        {"DELIVER_MESSAGE"},
        drain_src="TraceEvent.DELIVER_MESSAGE ... EV.LINK_DOWN counter-only",
        package_refs={"DELIVER_MESSAGE", "LINK_DOWN"},
    )
    msgs = " | ".join(v.msg for v in vs)
    assert "ORPHANED" in msgs                      # undrained + unreferenced
    assert "DELIVER_MESSAGE" not in msgs           # fully wired
    assert sum("LINK_DOWN" in v.msg for v in vs) == 0  # documented counter


def test_ev_drain_telemetry_column_counts_as_drained():
    """Round 11: a sim-only counter whose ``ev_<name>`` column appears
    in telemetry/panel.py counts as drained — the panel records its
    per-round deltas and the reconciliation gate pins them. Without
    the column (and without drain prose) the rule still fires."""
    args = dict(
        ev_names=["DELIVER_MESSAGE", "IWANT_RECOVER"],
        proto_names={"DELIVER_MESSAGE"},
        drain_src="TraceEvent.DELIVER_MESSAGE",  # no IWANT prose at all
        package_refs={"DELIVER_MESSAGE", "IWANT_RECOVER"},
    )
    vs = simlint.check_ev_drain(
        **args, telemetry_src='EV_METRICS = ("ev_iwant_recover",)')
    assert not any("IWANT_RECOVER" in v.msg for v in vs)
    vs = simlint.check_ev_drain(**args, telemetry_src="")
    assert any("IWANT_RECOVER" in v.msg for v in vs)


def test_ev_drain_adversary_counters_negatives():
    """Round 13: the adversary plane's sim-only counters (ADV_DROP /
    ADV_IHAVE_LIE / ADV_GRAFT_SPAM) must each be accumulated somewhere
    AND named by the drain (COUNTER_ONLY_EVENTS) or recorded as a
    telemetry column — seeded breakage of each half fires the rule."""
    adv = ["ADV_DROP", "ADV_IHAVE_LIE", "ADV_GRAFT_SPAM"]
    clean = simlint.check_ev_drain(
        adv, set(),
        drain_src="EV.ADV_DROP, EV.ADV_IHAVE_LIE, EV.ADV_GRAFT_SPAM "
                  "counter-only",
        package_refs=set(adv),
    )
    assert clean == []
    # never accumulated -> dead counter
    vs = simlint.check_ev_drain(
        adv, set(), drain_src="EV.ADV_DROP EV.ADV_IHAVE_LIE "
        "EV.ADV_GRAFT_SPAM", package_refs={"ADV_DROP"})
    assert any("ADV_IHAVE_LIE" in v.msg for v in vs)
    assert any("ADV_GRAFT_SPAM" in v.msg for v in vs)
    # neither drain-documented nor a telemetry column -> undrained
    vs = simlint.check_ev_drain(
        ["ADV_DROP"], set(), drain_src="", package_refs={"ADV_DROP"},
        telemetry_src="")
    assert any("ADV_DROP" in v.msg for v in vs)
    # the telemetry column alone satisfies the consumer contract
    vs = simlint.check_ev_drain(
        ["ADV_DROP"], set(), drain_src="", package_refs={"ADV_DROP"},
        telemetry_src='EV_METRICS = ("ev_adv_drop",)')
    assert not any("ADV_DROP" in v.msg for v in vs)


def test_telemetry_panel_rule_negatives():
    """The panel catalog must mirror the EV enum positionally, and a
    metric that is RECORDED but never RECONCILED is a violation (a
    timeline column the drain-vs-timeline gate never checks)."""
    ev = ["PUBLISH_MESSAGE", "DELIVER_MESSAGE"]
    ok = ["ev_publish_message", "ev_deliver_message"]
    assert simlint.check_telemetry_panel(ev, ok, ok) == []
    # missing / misordered column relabels everything after it
    vs = simlint.check_telemetry_panel(ev, ok[::-1], ok[::-1])
    assert any("enum order" in v.msg for v in vs)
    vs = simlint.check_telemetry_panel(ev, ok[:1], ok[:1])
    assert any("enum order" in v.msg for v in vs)
    # recorded but never reconciled — the negative test the issue pins
    vs = simlint.check_telemetry_panel(ev, ok, ok[:1])
    assert any("never" in v.msg or "missing from RECONCILED" in v.msg
               for v in vs)
    assert all(v.rule == "telemetry-panel" for v in vs)
    # RECONCILED naming a non-recorded column is equally broken
    vs = simlint.check_telemetry_panel(ev, ok, ok + ["ev_ghost"])
    assert any("ev_ghost" in v.msg for v in vs)


def test_telemetry_panel_rule_on_repo_source():
    """The in-tree catalog satisfies the rule, and the AST extractor
    resolves the RECONCILED = EV_METRICS alias + tuple concatenation."""
    import ast

    panel_p = os.path.join(PKG, "telemetry", "panel.py")
    with open(panel_p) as f:
        tree = ast.parse(f.read())
    ev_metrics = simlint._tuple_literal(tree, "EV_METRICS")
    reconciled = simlint._tuple_literal(tree, "RECONCILED")
    assert ev_metrics and reconciled == ev_metrics
    metrics = simlint._tuple_literal(tree, "METRICS")  # ("x",) + EV + (...)
    assert metrics is not None and metrics[0] == "delivery_ratio"
    assert simlint._rule_telemetry_panel(PKG) == []


def test_invariant_registry_rule_negatives():
    """The invariant-registry rule fires on every broken declaration
    shape: missing/unknown engines, bad kind, missing doc, and a
    property no tests/ file references (the untrippable-property
    failure mode), plus an unparseable (computed) registry."""
    known = ("gossipsub", "phase", "floodsub", "randomsub")
    good = {"name": "mesh-ok", "line": 3, "kind": "safety",
            "engines": ["gossipsub", "phase"], "doc": "mesh ⊆ topology"}
    tests_src = 'CORRUPTIONS = [("mesh-ok", corrupt_mesh)]'
    assert simlint.check_invariant_registry([good], known, tests_src) == []
    # no declared applicability
    vs = simlint.check_invariant_registry(
        [{**good, "engines": None}], known, tests_src)
    assert any("applicability" in v.msg for v in vs)
    vs = simlint.check_invariant_registry(
        [{**good, "engines": []}], known, tests_src)
    assert any("applicability" in v.msg for v in vs)
    # an engine outside the catalog
    vs = simlint.check_invariant_registry(
        [{**good, "engines": ["gossipsub", "bitcoin"]}], known, tests_src)
    assert any("applicability" in v.msg for v in vs)
    # kind must be a literal safety|liveness
    vs = simlint.check_invariant_registry(
        [{**good, "kind": "vibes"}], known, tests_src)
    assert any("safety" in v.msg for v in vs)
    # missing doc citation
    vs = simlint.check_invariant_registry(
        [{**good, "doc": None}], known, tests_src)
    assert any("doc" in v.msg for v in vs)
    # registered but untested — the rule the issue pins
    vs = simlint.check_invariant_registry([good], known, "no mention")
    assert any("seeded-violation" in v.msg for v in vs)
    # computed/empty registry is itself a violation
    vs = simlint.check_invariant_registry([], known, tests_src)
    assert any("catalog" in v.msg for v in vs)
    assert all(v.rule == "invariant-registry" for v in vs)


def test_invariant_registry_rule_on_repo_source():
    """The in-tree catalog satisfies the rule: every @invariant call
    parses to a literal declaration (alias tuples resolved), and every
    name has a seeded-violation reference in tests/."""
    import ast

    inv_p = os.path.join(PKG, "oracle", "invariants.py")
    with open(inv_p) as f:
        tree = ast.parse(f.read())
    entries = simlint.registry_entries(tree)
    assert len(entries) >= 12
    names = [e["name"] for e in entries]
    assert "mesh-degree-bounds" in names and "eventual-delivery" in names
    for e in entries:
        assert e["engines"], e
    assert simlint._rule_invariant_registry(PKG) == []


def test_narrow_dtype_rule_negatives():
    """The narrow-dtype rule (round 23): every sub-i32 ``.astype`` in
    device scope must appear, positionally, in the committed
    RANGE_AUDIT.json manifest — an unlisted narrowing cast is an
    unaudited wrap hazard, a listed-but-vanished one is a stale range
    justification."""
    src = textwrap.dedent("""
        import jax.numpy as jnp
        def pack(x):
            a = x.astype(jnp.int16)
            b = x.astype("uint8")
            return a, b.astype(np.float32)  # widening/float casts pass
    """)
    sites = simlint.narrow_astype_sites(src, "ops/broken.py")
    assert [dt for _ln, dt in sites] == ["int16", "uint8"]

    # unlisted site (seeded negative)
    vs = simlint.check_narrow_dtype({"ops/broken.py": sites}, {})
    assert vs and all(v.rule == "narrow-dtype" for v in vs)
    assert any("do not match the committed RANGE_AUDIT manifest" in v.msg
               for v in vs)
    # exact positional match passes; a reorder or a stale entry fails
    assert simlint.check_narrow_dtype(
        {"ops/broken.py": sites}, {"ops/broken.py": ("int16", "uint8")}) == []
    assert simlint.check_narrow_dtype(
        {"ops/broken.py": sites}, {"ops/broken.py": ("uint8", "int16")})
    assert simlint.check_narrow_dtype(
        {}, {"ops/gone.py": ("int8",)})


def test_narrow_dtype_rule_on_repo_source():
    """The in-tree device scope matches the committed manifest exactly
    (the int8 delivery-plane pack in ops/pallas_delivery.py), and a
    missing artifact is itself a violation, not a silent pass."""
    assert simlint._rule_narrow_dtype(PKG) == []
    vs = simlint._rule_narrow_dtype(os.path.join(PKG, "analysis"))
    assert vs and "RANGE_AUDIT.json is missing" in vs[0].msg


def test_allowlist_filters_by_qual(tmp_path):
    vs = lint("""
        def drain(state):
            return state.events.item()
    """)
    assert vs
    allow = [("host-sync", "models/broken.py", "drain")]
    kept, allowed = simlint.filter_allowed(vs, allow)
    assert kept == [] and len(allowed) == len(vs)
    # a different qualname does not match
    kept2, _ = simlint.filter_allowed(
        vs, [("host-sync", "models/broken.py", "other")])
    assert kept2 == vs


def test_allowlist_parse_rejects_garbage(tmp_path):
    p = tmp_path / "ALLOWLIST"
    p.write_text("host-sync models/x.py::f extra-token\n")
    with pytest.raises(ValueError):
        simlint.load_allowlist(str(p))


def test_repo_lints_clean():
    """The enforced state: zero unallowed violations on the package
    (and, since round 19, the tests/ + scripts/ call-site trees under
    the donated-reuse rule — simlint.run covers both)."""
    kept, _allowed = simlint.run(PKG)
    assert kept == [], "\n".join(v.format() for v in kept)


# ---------------------------------------------------------------------------
# donated-reuse: the call-site rule (round 19) — seeded negatives


def dlint(src, rel="tests/test_broken.py"):
    return simlint.lint_donated_reuse(textwrap.dedent(src), rel)


def test_donated_reuse_fires_on_reuse_after_step():
    vs = dlint("""
        def t(step, fresh):
            st = fresh()
            out = step(st, po)
            return st.events
    """)
    assert rules_of(vs) == {"donated-reuse"}
    assert "DONATED" in vs[0].msg


def test_donated_reuse_fires_on_window_call():
    vs = dlint("""
        def t(window, fresh, xs):
            states = fresh()
            out, ys = window(states, xs)
            return states
    """)
    assert rules_of(vs) == {"donated-reuse"}


def test_donated_reuse_fires_on_module_level_engine_step():
    vs = dlint("""
        def t(net, st):
            out = floodsub_step(net, st, po, pt, pv)
            return st.events
    """)
    assert rules_of(vs) == {"donated-reuse"}


def test_donated_reuse_fires_on_loop_backedge():
    # the canonical loop form of the footgun: donation inside a loop,
    # state never rebound — iteration 2 reads the donated buffers
    vs = dlint("""
        def t(step, fresh):
            st = fresh()
            for i in range(4):
                out = step(st, po)
            return out
    """)
    assert rules_of(vs) == {"donated-reuse"}


def test_donated_reuse_fresh_build_inside_loop_ok():
    vs = dlint("""
        def t(step, fresh):
            for i in range(4):
                st = fresh()
                out = step(st, po)
            return out
    """)
    assert vs == []


def test_donated_reuse_multiline_call_ok():
    # a donating call wrapped across lines must not read its own
    # argument as after-donation reuse
    vs = dlint("""
        def t(step, fresh):
            st = fresh()
            out = step(
                st, po)
            return out
    """)
    assert vs == []


def test_donated_reuse_rebind_idiom_ok():
    vs = dlint("""
        def t(step, fresh):
            st = fresh()
            for i in range(4):
                st = step(st, po)
            return st.events
    """)
    assert vs == []


def test_donated_reuse_fresh_rebind_after_donation_ok():
    vs = dlint("""
        def t(step, fresh):
            st = fresh()
            out = step(st, po)
            st = fresh()
            return st.events
    """)
    assert vs == []


def test_donated_reuse_make_and_observer_calls_exempt():
    # make_* builds a step (never donates); hook.on_step observes the
    # LIVE state (never donates) — both must stay clean
    vs = dlint("""
        def t(cfg, net, fresh, hook):
            st = fresh()
            step = make_gossipsub_step(cfg, net)
            st = step(st, po)
            hook.on_step(0, st)
            return st.events
    """)
    assert vs == []


def test_donated_reuse_callsite_trees_clean():
    """tests/ and scripts/ follow the donation discipline — the rule
    holds repo-wide with the ALLOWLIST still empty."""
    kept = simlint.lint_callsites(ROOT)
    assert kept == [], "\n".join(v.format() for v in kept)


# ---------------------------------------------------------------------------
# trace guards: each fires on a deliberately broken harness


def _harness(fn, state, args_of=None, **jit_kw):
    return EngineHarness(
        name="broken",
        jit_fn=jax.jit(fn, **jit_kw),
        state=state,
        make_args=args_of or (lambda i: (jnp.ones((4,), jnp.int32),)),
        static_kwargs={},
    )


def test_guard_strict_dtype_fires():
    # int32 state mixed with a uint32 operand: standard mode silently
    # promotes, strict mode is the gate
    h = _harness(
        lambda s, a: {"x": s["x"] + a.astype(jnp.uint32)},
        {"x": jnp.zeros((4,), jnp.int32)},
    )
    with pytest.raises(GuardViolation) as ei:
        guards.strict_trace(h)
    assert ei.value.guard == "strict-dtype"


def test_guard_schema_weak_type_fires():
    # a pure python-scalar constant in the carry is a weak-typed leaf:
    # next call re-traces it as an input with a DIFFERENT aval -> the
    # recompile-per-round bug the schema guard exists to catch
    h = _harness(lambda s, a: {"x": s["x"], "t": jnp.asarray(0.0)},
                 {"x": jnp.zeros((4,), jnp.float32)})
    out = jax.eval_shape(lambda s: h.jit_fn(s, jnp.ones((4,), jnp.int32)),
                         h.state)
    assert any(r["weak_type"] for r in guards.schema_of(out))
    with pytest.raises(GuardViolation) as ei:
        guards.check_schema(h, out, None)
    assert ei.value.guard == "schema"


def test_guard_schema_drift_fires():
    h = _harness(lambda s, a: s, {"x": jnp.zeros((4,), jnp.int32)})
    out = jax.eval_shape(lambda s: s, h.state)
    rows = guards.schema_of(out)
    doctored = json.loads(json.dumps(rows))
    doctored[0]["dtype"] = "int64"
    baseline = {"engines": {"broken": {"leaves": doctored}}}
    with pytest.raises(GuardViolation) as ei:
        guards.check_schema(h, out, baseline)
    assert ei.value.guard == "schema"
    assert guards.diff_schema("broken", rows, doctored)


def test_guard_schema_missing_engine_fires():
    h = _harness(lambda s, a: s, {"x": jnp.zeros((4,), jnp.int32)})
    out = jax.eval_shape(lambda s: s, h.state)
    with pytest.raises(GuardViolation):
        guards.check_schema(h, out, {"engines": {}})


def test_guard_donation_fires_and_passes():
    state = {"x": jnp.zeros((8,), jnp.float32)}
    undonated = _harness(lambda s, a: {"x": s["x"] + 1.0}, state)
    with pytest.raises(GuardViolation) as ei:
        guards.check_donation(undonated)
    assert ei.value.guard == "donation"
    donated = _harness(lambda s, a: {"x": s["x"] + 1.0}, state,
                       donate_argnums=0)
    guards.check_donation(donated)


def test_guard_recompile_sentinel_fires():
    # growing arg shapes cache-bust: one compile per round
    h = _harness(
        lambda s, a: s,
        {"x": jnp.zeros((4,), jnp.int32)},
        args_of=lambda i: (jnp.ones((4 + i,), jnp.int32),),
    )
    with pytest.raises(GuardViolation) as ei:
        guards.run_rounds_guarded(h, rounds=3)
    assert ei.value.guard == "recompile"


def test_guard_transfer_fires():
    # a numpy array sneaking into the round loop = an implicit
    # host->device transfer per call; the guard turns it into an error
    h = _harness(
        lambda s, a: {"x": s["x"] + a},
        {"x": jnp.zeros((4,), jnp.int32)},
        args_of=lambda i: (np.ones((4,), np.int32),),
    )
    with pytest.raises(GuardViolation) as ei:
        guards.run_rounds_guarded(h, rounds=2)
    assert ei.value.guard == "transfer"


# ---------------------------------------------------------------------------
# positive: one real engine end-to-end + the committed baseline


def test_floodsub_guards_end_to_end():
    h = guards.build_engine("floodsub")
    out = guards.strict_trace(h)
    rows = guards.check_schema(h, out, None)
    guards.check_donation(h)
    guards.run_rounds_guarded(h)
    # the committed STATE_SCHEMA.json matches what this container traces
    baseline = guards.load_baseline(ROOT)
    assert baseline is not None, "STATE_SCHEMA.json not committed"
    want = baseline["engines"]["floodsub"]["leaves"]
    assert guards.diff_schema("floodsub", rows, want) == []


def test_schema_engines_complete():
    baseline = guards.load_baseline(ROOT)
    assert baseline is not None
    assert set(baseline["engines"]) == set(guards.ENGINES)


# ---------------------------------------------------------------------------
# the declarative row registry (round 16): every derived harness is one
# registry line; the new lifted-score and phase+csr rows are present
# and their builders/runners resolve


def test_guard_registry_rows():
    names = [r.name for r in guards.DERIVED_ROWS]
    assert names == ["ensemble", "telemetry", "csr", "phase_csr", "lifted",
                     "csr_fused", "lifted_fused", "dynamic",
                     "idontwant", "choke"]
    for row in guards.DERIVED_ROWS:
        assert callable(getattr(guards, row.runner)), row.runner
        assert row.base in guards.ENGINES, row
    assert guards.ALL_ROWS == tuple(guards.ENGINES) + tuple(names)


def test_lifted_plane_pair_distinct():
    import numpy as np

    pa, pb = guards.lifted_plane_pair()
    # the A/B sentinel is vacuous unless the two planes differ on every
    # surface the lift exists to sweep
    for leaf in ("w2", "behaviour_penalty_weight", "gossip_threshold",
                 "publish_threshold", "topic_score_cap"):
        assert not np.array_equal(np.asarray(getattr(pa, leaf)),
                                  np.asarray(getattr(pb, leaf))), leaf


def test_lifted_schema_must_equal_base():
    # seeded negative: a state tree that differs from the base rows
    # trips the equal-base schema check with the lifted message
    h = _harness(lambda s, a: {"x": s["x"], "extra": jnp.zeros((2,))},
                 {"x": jnp.zeros((4,), jnp.int32)})
    out = jax.eval_shape(lambda s: h.jit_fn(s, jnp.ones((4,), jnp.int32)),
                         h.state)
    base_rows = [{"path": "['x']", "dtype": "int32", "shape": [4],
                  "weak_type": False}]
    with pytest.raises(GuardViolation) as ei:
        guards.check_schema_equal(h, out, base_rows, "gossipsub",
                                  "the lifted score plane leaked into "
                                  "the state tree")
    assert ei.value.guard == "schema"
    assert "leaked" in str(ei.value)
