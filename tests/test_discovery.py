"""Discovery pipeline tests — mirrors discovery_test.go: an in-memory
rendezvous server with TTL records (mockDiscoveryServer, :27-73), advertise
on join, bootstrap growing a starving topic's connectivity
(TestSimpleDiscovery :126, TestGossipSubDiscoveryAfterBootstrap :221), and
publish-readiness gating (MinTopicSize, discovery.go:76-82)."""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import api, discovery


def test_advertise_on_join():
    server = discovery.MemoryDiscovery()
    net = api.Network(router="floodsub", discovery=server)
    nodes = net.add_nodes(4)
    for nd in nodes[:3]:
        nd.join("foobar")
    ns = discovery.namespace("foobar")
    assert ns == "floodsub:foobar"
    for nd in nodes[:3]:
        assert server.has_peer_record(ns, nd.peer_id)
    assert not server.has_peer_record(ns, nodes[3].peer_id)


def test_leave_stops_advertising():
    server = discovery.MemoryDiscovery()
    net = api.Network(router="floodsub", discovery=server)
    (a,) = net.add_nodes(1)
    a.join("t")
    a.leave("t")
    assert not server.has_peer_record(discovery.namespace("t"), a.peer_id)


def test_ttl_expiry():
    server = discovery.MemoryDiscovery()
    server.advertise("floodsub:x", b"peer-1", ttl=5)
    assert server.find_peers("floodsub:x") == [b"peer-1"]
    server.advance(6)
    assert server.find_peers("floodsub:x") == []


def test_find_peers_limit():
    server = discovery.MemoryDiscovery()
    for i in range(10):
        server.advertise("floodsub:x", b"peer-%d" % i)
    assert len(server.find_peers("floodsub:x", limit=3)) == 3
    assert len(server.find_peers("floodsub:x")) == 10


def test_backoff_connector():
    conn = discovery.BackoffConnector(seed=0)
    assert conn.may_dial(0, 1, tick=0)
    conn.record_dial(0, 1, tick=0)
    # full jitter in [0, 10s) but at least 1 tick
    assert not conn.may_dial(0, 1, tick=0)
    assert conn.may_dial(0, 1, tick=discovery.BACKOFF_MIN_TICKS)
    # growth is capped
    for i in range(10):
        conn.record_dial(0, 1, tick=0)
    assert conn.may_dial(0, 1, tick=discovery.BACKOFF_MAX_TICKS)


def test_bootstrap_connects_starving_topic_floodsub():
    """TestSimpleDiscovery shape: nodes share only a discovery server (no
    pre-wired edges); bootstrap must produce a connected, publishable
    topic."""
    server = discovery.MemoryDiscovery()
    net = api.Network(router="floodsub", discovery=server)
    nodes = net.add_nodes(12)
    subs = [nd.join("foobar").subscribe() for nd in nodes]
    assert len(net._edges) == 0
    ok = net.bootstrap("foobar", min_peers=5)
    assert ok
    assert len(net._edges) > 0
    net.start()
    nodes[0].topics["foobar"].publish(b"hey")
    net.run(6)
    delivered = sum(1 for s in subs if s.next() is not None)
    # floodsub floods the discovered graph; everyone connected transitively
    assert delivered == 12


def test_bootstrap_gossipsub_enough_peers_uses_dlo():
    server = discovery.MemoryDiscovery()
    net = api.Network(router="gossipsub", discovery=server)
    nodes = net.add_nodes(10)
    for nd in nodes:
        nd.join("t")
    assert net.bootstrap("t")  # suggestion 0 -> Dlo (gossipsub.go:572-574)
    sess = net.discovery
    assert any(sess.enough_peers(nd, "t", 0) for nd in nodes)


def test_publish_readiness_gate():
    server = discovery.MemoryDiscovery()
    net = api.Network(router="floodsub", discovery=server)
    a, b = net.add_nodes(2)
    ta = a.join("t")
    b.join("t")
    net.connect(a, b)
    net.start()
    # only 1 topic peer < min 2 -> gated (MinTopicSize semantics)
    with pytest.raises(api.NotReadyError):
        ta.publish(b"x", min_peers=2)
    # suggestion 1 is satisfied
    mid = ta.publish(b"x", min_peers=1)
    assert isinstance(mid, bytes)


def test_enough_peers_floodsub_default_threshold():
    """floodsub.go:52-68: suggestion 0 means FloodSubTopicSearchSize=5."""
    server = discovery.MemoryDiscovery()
    net = api.Network(router="floodsub", discovery=server)
    nodes = net.add_nodes(6)
    for nd in nodes:
        nd.join("t")
    for other in nodes[1:5]:
        net.connect(nodes[0], other)  # 4 topic peers: not enough
    sess = net.discovery
    assert not sess.enough_peers(nodes[0], "t", 0)
    net.connect(nodes[0], nodes[5])  # 5: enough
    assert sess.enough_peers(nodes[0], "t", 0)


def test_poll_respects_backoff_no_duplicate_edges():
    server = discovery.MemoryDiscovery()
    net = api.Network(router="floodsub", discovery=server)
    nodes = net.add_nodes(3)
    for nd in nodes:
        nd.join("t")
    sess = net.discovery
    made_total = 0
    for _ in range(5):
        made_total += sess.poll_once()
    # complete graph on 3 nodes has 3 undirected edges; polling more never
    # duplicates (are_connected check) — K3 still starves vs threshold 5,
    # so the poll keeps running but has nothing left to add
    assert len(net._edges) == 3
    assert made_total == 3


def test_restart_applies_discovered_topology():
    """Edges discovered after start() apply on restart(); protocol state is
    soft-rebuilt (reference semantics: mesh state is reconstructed from the
    network, SURVEY §5)."""
    server = discovery.MemoryDiscovery()
    net = api.Network(router="floodsub", discovery=server)
    nodes = net.add_nodes(6)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.connect(nodes[0], nodes[1])
    net.start()
    # late joiners advertised; poll post-start records intent but cannot
    # rewire the frozen program
    n_edges = len(net._edges)
    net.discovery.poll_once()
    assert len(net._edges) == n_edges  # frozen
    net.restart()  # unfreeze: growth is allowed again
    net.bootstrap("t", min_peers=5)
    net.start()    # refreeze with the discovered edges
    nodes[0].topics["t"].publish(b"after-restart")
    net.run(6)
    assert sum(1 for s in subs if s.next() is not None) == 6
