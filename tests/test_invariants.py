"""Invariant-oracle tests (oracle/invariants.py; docs/DESIGN.md §12).

Two halves, mirroring tests/test_analysis.py's contract for the lint
plane: every registered property must PASS on clean runs of all four
engines (positive — the oracle is not crying wolf), and every property
must be TRIPPED by its own seeded violation — corrupt one leaf, assert
EXACTLY that property fails (negative — the oracle is not a rubber
stamp). The simlint ``invariant-registry`` rule cross-checks that every
registered name appears in this file's literal corruption catalog.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, ensemble, graph
from go_libp2p_pubsub_tpu.config import PeerScoreThresholds
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
    make_gossipsub_phase_step,
)
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.oracle import invariants as inv
from go_libp2p_pubsub_tpu.state import Net, SimState

N = 48
M = 64
ROUNDS = 24
PUB_AT = (2, 5)      # publish rounds [lo, hi)
W = 12               # delivery window for the quiet due clause


def _params():
    from go_libp2p_pubsub_tpu.config import GossipSubParams

    return GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1,
                           history_length=6, history_gossip=4)


def _score_params():
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params

    return bench_score_params("default", 1)[1]


def _schedule(rounds=ROUNDS, seed=0, width=4, pub_at=PUB_AT):
    rng = np.random.default_rng(seed)
    po = np.full((rounds, width), -1, np.int32)
    po[pub_at[0]:pub_at[1]] = rng.integers(0, N, size=(
        pub_at[1] - pub_at[0], width))
    pt = np.zeros((rounds, width), np.int32)
    pv = np.ones((rounds, width), bool)
    return po, pt, pv


def _net(seed=0):
    topo = graph.random_connect(N, d=4, seed=seed)
    subs = graph.subscribe_all(N, 1)
    return Net.build(topo, subs)


def _run_gossip(net, rounds=ROUNDS, seed=0):
    sp = _score_params()
    cfg = GossipSubConfig.build(_params(), PeerScoreThresholds(),
                                score_enabled=True)
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    po, pt, pv = _schedule(rounds, seed)
    for t in range(rounds):
        st = step(st, jnp.asarray(po[t]), jnp.asarray(pt[t]),
                  jnp.asarray(pv[t]))
    return cfg, st


@pytest.fixture(scope="module")
def net():
    return _net()


@pytest.fixture(scope="module")
def lived_in(net):
    """A post-run gossipsub (cfg, state): mesh formed, messages fully
    delivered, mcache populated. The checker never donates, so tests
    may read (and .at[].set-copy) this tree freely."""
    return _run_gossip(net)


def _check(net, state, cfg=None, engine="gossipsub", due=None,
           prev_events=None, window=W):
    icfg = inv.InvariantConfig(delivery_window=window)
    names = inv.invariant_names(engine)
    ok = np.asarray(inv.check_state(engine, net, state, cfg, icfg,
                                    prev_events=prev_events, due=due))
    return dict(zip(names, ok.tolist()))


QUIET = inv.due_vector(quiet=(0, ROUNDS))


# ---------------------------------------------------------------------------
# positive: clean runs of all four engines pass every property


def test_clean_gossipsub_passes_all(net, lived_in):
    cfg, st = lived_in
    res = _check(net, st, cfg, due=QUIET)
    assert all(res.values()), {k: v for k, v in res.items() if not v}
    # the quiet due clause was non-vacuous: validated publishes existed
    # and aged past the window
    births = np.asarray(st.core.msgs.birth)
    assert ((births >= 0) & (births + W <= ROUNDS)).any()


def test_clean_floodsub_passes_all(net):
    st = SimState.init(N, M, seed=0, k=net.max_degree)
    po, pt, pv = _schedule()
    for t in range(ROUNDS):
        st = floodsub_step(net, st, jnp.asarray(po[t]), jnp.asarray(pt[t]),
                           jnp.asarray(pv[t]))
    res = _check(net, st, engine="floodsub", due=QUIET)
    assert all(res.values()), {k: v for k, v in res.items() if not v}


def test_clean_randomsub_passes_all(net):
    st = SimState.init(N, M, seed=0, k=net.max_degree)
    step = make_randomsub_step(net)
    po, pt, pv = _schedule()
    for t in range(ROUNDS):
        st = step(st, jnp.asarray(po[t]), jnp.asarray(pt[t]),
                  jnp.asarray(pv[t]))
    res = _check(net, st, engine="randomsub", due=QUIET)
    assert all(res.values()), {k: v for k, v in res.items() if not v}


@pytest.mark.slow
def test_clean_phase_passes_all(net):
    """Phase engine (stacked coalesced wire): checks at phase
    boundaries; the delivery window scales with the phase-cadence
    control-latency quantum (docs/DESIGN.md §12)."""
    rounds, r = 40, 4
    sp = _score_params()
    cfg = GossipSubConfig.build(_params(), PeerScoreThresholds(),
                                score_enabled=True)
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    step = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
    po, pt, pv = _schedule(rounds, seed=0, pub_at=(8, 11))
    for p in range(rounds // r):
        sl = slice(p * r, (p + 1) * r)
        st = step(st, jnp.asarray(po[sl]), jnp.asarray(pt[sl]),
                  jnp.asarray(pv[sl]), do_heartbeat=True)
    res = _check(net, st, cfg, engine="phase",
                 due=inv.due_vector(quiet=(0, rounds)), window=24)
    assert all(res.values()), {k: v for k, v in res.items() if not v}


# ---------------------------------------------------------------------------
# negative: every property is tripped by its own seeded violation
#
# Each corruption touches one leaf (plus, where the property is about a
# relation, the minimal second input: a doctored net, a due vector, a
# prev snapshot) and declares the EXACT failure set it expects — the
# target property, plus knock-ons only where the corruption necessarily
# violates a second property's statement too.


def _clear_bit_in(words_row, m):
    """Index of a bit < m that is CLEAR in a packed [W] u32 row."""
    bits = np.unpackbits(
        np.asarray(words_row, np.uint32).view(np.uint8), bitorder="little")
    for i in range(m):
        if not bits[i]:
            return i
    raise AssertionError("no clear bit to corrupt with")


def _mesh_edge(st):
    """(i, s, k) of some set mesh bit."""
    idx = np.argwhere(np.asarray(st.mesh))
    assert idx.size, "lived-in state has an empty mesh"
    return tuple(int(v) for v in idx[0])


def _corrupt_msgtable(net, cfg, st):
    msgs = st.core.msgs
    slot = int(np.argwhere(np.asarray(msgs.valid))[0][0])
    msgs = msgs.replace(ignored=msgs.ignored.at[slot].set(True))
    return net, st.replace(core=st.core.replace(msgs=msgs)), {}


def _corrupt_fwd(net, cfg, st):
    dlv = st.core.dlv
    bit = _clear_bit_in(np.asarray(dlv.have)[0], M)
    w, b = bit // 32, np.uint32(1) << np.uint32(bit % 32)
    dlv = dlv.replace(fwd=dlv.fwd.at[0, w].set(dlv.fwd[0, w] | b))
    return net, st.replace(core=st.core.replace(dlv=dlv)), {}


def _corrupt_first_edge(net, cfg, st):
    # two first-arrival edges for one (peer, msg) — and both in have,
    # so only the at-most-one clause trips
    dlv = st.core.dlv
    slot = int(np.argwhere(np.asarray(st.core.msgs.valid))[0][0])
    w, b = slot // 32, np.uint32(1) << np.uint32(slot % 32)
    have = dlv.have.at[0, w].set(dlv.have[0, w] | b)
    fe = dlv.fe_words
    fe = fe.at[0, 0, w].set(fe[0, 0, w] | b)
    fe = fe.at[0, 1, w].set(fe[0, 1, w] | b)
    return net, st.replace(core=st.core.replace(
        dlv=dlv.replace(have=have, fe_words=fe))), {}


def _corrupt_events(net, cfg, st):
    return net, st, {"prev_events": np.asarray(st.core.events) + 1}


def _corrupt_delivery(net, cfg, st):
    # un-deliver one validated, subscribed, non-origin receipt and make
    # the quiet clause due for it
    msgs = st.core.msgs
    slot = int(np.argwhere(np.asarray(msgs.valid))[0][0])
    origin = int(np.asarray(msgs.origin)[slot])
    peer = (origin + 1) % N
    dlv = st.core.dlv
    dlv = dlv.replace(first_round=dlv.first_round.at[peer, slot].set(-1))
    return net, st.replace(core=st.core.replace(dlv=dlv)), {"due": QUIET}


def _corrupt_self_graft(net, cfg, st):
    # a self-loop edge in the doctored topology, GRAFT-targeted
    i, s, k = _mesh_edge(st)
    net2 = net.replace(nbr=net.nbr.at[i, k].set(i))
    st2 = st.replace(graft_out=st.graft_out.at[i, s, k].set(True))
    return net2, st2, {}


def _corrupt_topology(net, cfg, st):
    # a mesh member goes down without the dead-peer cleanup
    i, s, k = _mesh_edge(st)
    j = int(np.asarray(net.nbr)[i, k])
    return net, st.replace(up=st.up.at[j].set(False)), {}


def _corrupt_subscription(net, cfg, st):
    # the far end of a mesh edge degrades to /floodsub/1.0.0 — a
    # floodsub-only peer can never be a mesh member. Its own slots stop
    # being mesh-capable too, so every mesh bit it holds trips the same
    # property (still exactly one property).
    i, s, k = _mesh_edge(st)
    j = int(np.asarray(net.nbr)[i, k])
    net2 = net.replace(protocol=net.protocol.at[j].set(0))
    return net2, st, {}


def _corrupt_degree(net, cfg, st):
    # strip peer 0's mesh below Dlo while eligible candidates remain
    st2 = st.replace(mesh=st.mesh.at[0].set(False))
    return net, st2, {}


def _corrupt_graft_backoff(net, cfg, st):
    i, s, k = _mesh_edge(st)
    tick = int(np.asarray(st.core.tick))
    st2 = st.replace(
        graft_out=st.graft_out.at[i, s, k].set(True),
        backoff_present=st.backoff_present.at[i, s, k].set(True),
        backoff_expire=st.backoff_expire.at[i, s, k].set(tick + 10),
    )
    return net, st2, {}


def _corrupt_graylist(net, cfg, st):
    i, s, k = _mesh_edge(st)
    return net, st.replace(scores=st.scores.at[i, k].set(-5.0)), {}


def _corrupt_mcache(net, cfg, st):
    bit = _clear_bit_in(np.asarray(st.core.dlv.have)[0], M)
    w, b = bit // 32, np.uint32(1) << np.uint32(bit % 32)
    return net, st.replace(
        mcache=st.mcache.at[0, 0, w].set(st.mcache[0, 0, w] | b)), {}


def _corrupt_score_counter(net, cfg, st):
    sc = st.score
    return net, st.replace(score=sc.replace(
        fmd=sc.fmd.at[0, 0, 0].set(-1.0))), {}


def _corrupt_backoff_presence(net, cfg, st):
    # an unexpired backoff whose presence flag is missing
    i, s, k = _mesh_edge(st)
    tick = int(np.asarray(st.core.tick))
    st2 = st.replace(
        backoff_expire=st.backoff_expire.at[i, s, k].set(tick + 50),
        backoff_present=st.backoff_present.at[i, s, k].set(False),
    )
    return net, st2, {}


def _corrupt_backoff_stuck(net, cfg, st):
    # presence surviving far past expiry + slack + a full clear period
    i, s, k = _mesh_edge(st)
    st2 = st.replace(
        backoff_expire=st.backoff_expire.at[i, s, k].set(1),
        backoff_present=st.backoff_present.at[i, s, k].set(True),
    )
    return net, st2, {}


def _corrupt_promise(net, cfg, st):
    return net, st.replace(promise_mid=st.promise_mid.at[0, 0].set(M + 3)), {}


def _corrupt_reform(net, cfg, st):
    # post-heal deadline passed, mesh still empty, candidates available;
    # grace=1 keeps the ordinary degree property suspended so ONLY the
    # heal-liveness clause trips
    tick = int(np.asarray(st.core.tick))
    due = inv.due_vector(recover=(0, 5, tick - 1), grace=True)
    return net, st.replace(mesh=st.mesh.at[0].set(False)), {"due": due}


CORRUPTIONS = [
    ("msgtable-wf", _corrupt_msgtable),
    ("fwd-subset-have", _corrupt_fwd),
    ("first-edge-wf", _corrupt_first_edge),
    ("events-monotone", _corrupt_events),
    ("eventual-delivery", _corrupt_delivery),
    ("no-self-mesh", _corrupt_self_graft),
    ("mesh-in-topology", _corrupt_topology),
    ("mesh-subscribed", _corrupt_subscription),
    ("mesh-degree-bounds", _corrupt_degree),
    ("no-graft-under-backoff", _corrupt_graft_backoff),
    ("graylist-not-in-mesh", _corrupt_graylist),
    ("mcache-subset-seen", _corrupt_mcache),
    ("score-counters-wf", _corrupt_score_counter),
    ("backoff-wf", _corrupt_backoff_presence),
    ("backoff-clears", _corrupt_backoff_stuck),
    ("promise-wf", _corrupt_promise),
    ("mesh-reform-after-heal", _corrupt_reform),
]


@pytest.mark.parametrize("name,corrupt",
                         CORRUPTIONS, ids=[c[0] for c in CORRUPTIONS])
def test_seeded_violation_trips_exact_property(net, lived_in, name, corrupt):
    cfg, st = lived_in
    net2, st2, kw = corrupt(net, cfg, st)
    res = _check(net2, st2, cfg, **kw)
    failed = {k for k, v in res.items() if not v}
    assert failed == {name}, (
        f"corrupting for {name!r} tripped {sorted(failed)}")


def test_word_padding_violation_trips():
    """word-padding-wf needs a capacity that does not fill its words
    (M=48 leaves 16 padding bits); a set padding bit trips exactly it."""
    net = _net()
    st = SimState.init(N, 48, seed=0, k=net.max_degree)
    res = _check(net, st, engine="floodsub")
    assert all(res.values())
    pad_bit = np.uint32(1) << np.uint32(17)   # bit 49 of word 1
    dlv = st.dlv
    st2 = st.replace(dlv=dlv.replace(
        have=dlv.have.at[0, 1].set(dlv.have[0, 1] | pad_bit)))
    res = _check(net, st2, engine="floodsub")
    failed = {k for k, v in res.items() if not v}
    assert failed == {"word-padding-wf"}


def test_grace_suspends_degree_bounds(net, lived_in):
    """The fault-scope contract: the same degree violation that trips
    outside grace is suspended inside it (the clause the papers scope
    out while links are down)."""
    cfg, st = lived_in
    _, st2, _ = _corrupt_degree(net, cfg, st)
    assert not _check(net, st2, cfg)["mesh-degree-bounds"]
    graced = _check(net, st2, cfg, due=inv.due_vector(grace=True))
    assert graced["mesh-degree-bounds"]


# ---------------------------------------------------------------------------
# registry / config surface


def test_registry_declares_engines_and_docs():
    assert len(inv.REGISTRY) >= 12
    for name, prop in inv.REGISTRY.items():
        assert prop.kind in ("safety", "liveness"), name
        assert prop.engines and set(prop.engines) <= set(inv.ENGINES), name
        assert prop.doc and len(prop.doc) > 40, (
            f"{name} doc is not a property statement")
    # the catalog as a whole is anchored in the two verification papers
    docs = " ".join(p.doc for p in inv.REGISTRY.values())
    assert "2311.08859" in docs and "2507.19013" in docs
    core = set(inv.invariant_names("floodsub"))
    assert core == set(inv.invariant_names("randomsub"))
    assert core < set(inv.invariant_names("gossipsub"))
    assert set(inv.invariant_names("gossipsub")) == set(
        inv.invariant_names("phase"))


def test_invariant_config_validation():
    with pytest.raises(inv.InvariantConfigError):
        inv.InvariantConfig(delivery_window=0).validate()
    with pytest.raises(inv.InvariantConfigError):
        inv.InvariantConfig(check_every=0).validate()
    with pytest.raises(inv.InvariantConfigError):
        inv.InvariantConfig(names=("no-such-property",)).validate()
    sub = inv.InvariantConfig(names=("fwd-subset-have",))
    sub.validate()
    assert inv.invariant_names("gossipsub", sub.names) == (
        "fwd-subset-have",)
    # a subset that leaves NO property applicable to the engine fails
    # with the real reason, not a jnp.stack([]) trace error
    net = _net()
    st = SimState.init(N, M, seed=0, k=net.max_degree)
    with pytest.raises(inv.InvariantConfigError, match="empty"):
        inv.check_state("floodsub", net, st,
                        inv=inv.InvariantConfig(names=("no-self-mesh",)))


def test_due_vector_layout():
    d = inv.due_vector()
    assert d.tolist() == [-1, -1, -1, -1, -1, 0, 0]
    d = inv.due_vector(quiet=(3, 9), recover=(5, 7, 40), grace=True)
    assert d.tolist() == [3, 9, 5, 7, 40, 1, 0]
    d = inv.due_vector(mut_grace=True)
    assert d.tolist() == [-1, -1, -1, -1, -1, 0, 1]


def test_check_state_rejects_bare_simstate_for_mesh_engine(net):
    st = SimState.init(N, M, seed=0, k=net.max_degree)
    with pytest.raises(ValueError):
        inv.check_state("gossipsub", net, st)


# ---------------------------------------------------------------------------
# batched checker: vmap parity + the runner hook


def test_batched_checker_matches_per_sim(net):
    """[S, P] rows of the vmapped checker equal per-sim eager checks
    (threefry — the ambient default here — vmaps elementwise; bools
    are exact either way)."""
    sp = _score_params()
    cfg = GossipSubConfig.build(_params(), PeerScoreThresholds(),
                                score_enabled=True)
    st0 = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    base_key = st0.core.key
    step = make_gossipsub_step(cfg, net, score_params=sp)
    ens = ensemble.lift_step(step)
    s = 3
    po, pt, pv = _schedule(rounds=12)
    states = ensemble.batch_states(st0, s)
    for t in range(12):
        states = ens(states, ensemble.tile(po[t], s), ensemble.tile(pt[t], s),
                     ensemble.tile(pv[t], s))
    chk, names = inv.make_checker("gossipsub", net, cfg, batched=True)
    due = jnp.asarray(QUIET)
    prev = states.core.events
    got = np.asarray(chk(states, prev, due))
    assert got.shape == (s, len(names))
    for i in range(s):
        one = ensemble.unbatch(states, i)
        want = np.asarray(inv.check_state(
            "gossipsub", net, one, cfg,
            prev_events=np.asarray(states.core.events)[i], due=QUIET))
        assert (got[i] == want).all(), f"sim {i} diverges"
    assert got.all()


def test_hook_runs_inside_ensemble_runner(net):
    sp = _score_params()
    cfg = GossipSubConfig.build(_params(), PeerScoreThresholds(),
                                score_enabled=True)
    st0 = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    ens = ensemble.lift_step(step)
    s, rounds = 2, 16
    po, pt, pv = _schedule(rounds)
    hook = inv.InvariantHook(
        "gossipsub", net, cfg,
        inv.InvariantConfig(check_every=4),
        due_fn=lambda tick: inv.due_vector(quiet=(0, rounds)))
    run = ensemble.run_rounds(
        ens, ensemble.batch_states(st0, s),
        lambda i: (ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
                   ensemble.tile(pv[i], s)),
        rounds, invariants=hook)
    rep = hook.report()
    assert rep.ticks == (4, 8, 12, 16)
    assert rep.ok.shape == (4, s, len(rep.names))
    assert rep.all_ok and rep.violated == 0
    assert rep.checked == 4 * s * len(rep.names)
    assert rep.last_checked_round == rounds
    assert hook.compiles in (-1, 1)
    assert run.compiles in (-1, 1)
    block = rep.artifact_block()
    assert block["enabled"] and block["violated"] == 0
    assert block["properties"] == list(rep.names)


def test_report_surfaces_violations(net, lived_in):
    """A violating check lands in the report with (round, sim, name)."""
    cfg, st = lived_in
    hook = inv.InvariantHook("gossipsub", net, cfg,
                             inv.InvariantConfig(check_every=1),
                             batched=False)
    hook.precompute(2)
    _, bad, _ = _corrupt_graylist(net, cfg, st)
    hook.on_step(0, st)
    hook.on_step(1, bad)
    rep = hook.report()
    assert not rep.all_ok and rep.violated == 1
    assert rep.violations() == [(2, 0, "graylist-not-in-mesh")]
    per = rep.per_property()
    assert per["graylist-not-in-mesh"] == (2, 1)
    assert per["fwd-subset-have"] == (2, 0)


# ---------------------------------------------------------------------------
# checkpoint round-trip with invariant checking enabled (no version bump)


def test_checkpoint_roundtrip_with_invariants(net, tmp_path):
    """A run with invariant checking enabled checkpoints and resumes
    bit-exactly — the v6 format is pytree-generic, no bump — and the
    resumed run's violation masks equal the uninterrupted run's."""
    assert checkpoint._FORMAT_VERSION == 6

    sp = _score_params()
    cfg = GossipSubConfig.build(_params(), PeerScoreThresholds(),
                                score_enabled=True)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    po, pt, pv = _schedule(rounds=16)

    def drive(st, hook, lo, hi):
        for t in range(lo, hi):
            st = step(st, jnp.asarray(po[t]), jnp.asarray(pt[t]),
                      jnp.asarray(pv[t]))
            hook.on_step(t, st)
        return st

    def fresh_hook():
        h = inv.InvariantHook("gossipsub", net, cfg,
                              inv.InvariantConfig(check_every=4),
                              batched=False)
        h.precompute(16)
        return h

    # uninterrupted reference
    st_a = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    hook_a = fresh_hook()
    st_a = drive(st_a, hook_a, 0, 16)

    # interrupted at round 8: save, restore into a fresh template,
    # resume (the window state the hook carries — the prev-events
    # monotone snapshot — is rebuilt from the restored state itself)
    st_b = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    hook_b = fresh_hook()
    st_b = drive(st_b, hook_b, 0, 8)
    path = os.path.join(tmp_path, "inv_ckpt.npz")
    checkpoint.save(path, st_b)
    template = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    st_c = checkpoint.restore(path, template)
    st_c = drive(st_c, hook_b, 8, 16)

    # resumed final state == uninterrupted, leaf for leaf
    for pa, la, lc in zip(
            [jax.tree_util.keystr(p)
             for p, _ in jax.tree_util.tree_flatten_with_path(st_a)[0]],
            jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda x: jax.random.key_data(x)
                if checkpoint.is_prng_key(x) else x, st_a)),
            jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda x: jax.random.key_data(x)
                if checkpoint.is_prng_key(x) else x, st_c))):
        assert bool(jnp.array_equal(la, lc)), f"leaf {pa} diverged"
    rep_a, rep_b = hook_a.report(), hook_b.report()
    assert rep_a.ticks == rep_b.ticks
    assert (rep_a.ok == rep_b.ok).all()
    assert rep_a.all_ok


# ---------------------------------------------------------------------------
# artifact plumbing (schema-v3 invariants block + tracestat reader)


def test_artifact_invariants_block_roundtrip(net, lived_in):
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        INVARIANTS_OFF,
        BenchRecord,
        dump_record,
        record_from_line,
    )
    import json as _json

    cfg, st = lived_in
    hook = inv.InvariantHook("gossipsub", net, cfg,
                             inv.InvariantConfig(check_every=1),
                             batched=False)
    hook.precompute(1)
    hook.on_step(0, st)
    block = hook.report().artifact_block()
    rec = BenchRecord(metric="m", value=1.0, unit="ratio", vs_baseline=0.0,
                      schema=2, invariants_raw=block)
    line = _json.loads(dump_record(rec))
    assert line["schema"] >= 3          # the block forces v3
    back = record_from_line(line)
    assert back.invariants_on
    assert back.invariants["checked"] == block["checked"]
    # the hook labels rounds by its own dispatch count (1 dispatch here)
    assert back.invariants["last_checked_round"] == 1
    # legacy lines read back the typed OFF default
    legacy = record_from_line({"metric": "m", "value": 1.0})
    assert not legacy.invariants_on
    assert legacy.invariants == INVARIANTS_OFF


def test_partition_cell_refuses_vacuous_invariant_run():
    """A tail shorter than the grace window would leave every
    partition-specific clause unarmed (and degree bounds suspended) for
    the whole post-heal run — the cell must refuse, not rubber-stamp."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import chaos_report

    with pytest.raises(ValueError, match="vacuous"):
        chaos_report.run_partition(
            n=32, seeds=1, tail=chaos_report.PARTITION_GRACE_AFTER_HEAL - 1,
            invariants=True)


def test_tracestat_reads_invariants_block(net, lived_in, tmp_path):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import tracestat

    from go_libp2p_pubsub_tpu.perf.artifacts import BenchRecord, dump_record

    cfg, st = lived_in
    hook = inv.InvariantHook("gossipsub", net, cfg,
                             inv.InvariantConfig(check_every=1),
                             batched=False)
    hook.precompute(1)
    hook.on_step(0, st)
    rec = BenchRecord(metric="m", value=1.0, unit="ratio", vs_baseline=0.0,
                      schema=2, invariants_raw=hook.report().artifact_block())
    p = tmp_path / "run.json"
    p.write_text(dump_record(rec) + "\n")
    got = tracestat.artifact_invariants(str(p))
    assert got["enabled"] and got["violated"] == 0
    # legacy artifact: the typed OFF default, not a KeyError
    p2 = tmp_path / "legacy.json"
    p2.write_text('{"metric": "m", "value": 1.0}\n')
    off = tracestat.artifact_invariants(str(p2))
    assert off["enabled"] is False and off["checked"] == 0


# ---------------------------------------------------------------------------
# router choke properties ("choke-wf", "no-choke-below-dlo"): clean and
# seeded-violation checks need a router-choke build — the v1.1 lived_in
# tree carries choked=None and both properties are vacuously true there


@pytest.fixture(scope="module")
def choke_lived_in(net):
    """A post-run gossipsub (cfg, state) with the lazy-choke router on
    (docs/DESIGN.md §24b): the choke guard has been exercised through
    GRAFT/PRUNE and heartbeat maintenance."""
    from go_libp2p_pubsub_tpu.routers import RouterConfig

    sp = _score_params()
    cfg = GossipSubConfig.build(
        _params(), PeerScoreThresholds(), score_enabled=True,
        router=RouterConfig(choke=True, choke_threshold=0.3,
                            unchoke_threshold=0.1))
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    po, pt, pv = _schedule(ROUNDS, 0)
    for t in range(ROUNDS):
        st = step(st, jnp.asarray(po[t]), jnp.asarray(pt[t]),
                  jnp.asarray(pv[t]))
    return cfg, st


def test_clean_choke_run_passes_all(net, choke_lived_in):
    cfg, st = choke_lived_in
    res = _check(net, st, cfg, due=QUIET)
    bad = [name for name, v in res.items() if not v]
    assert not bad, bad


def test_seeded_choke_outside_mesh_trips_choke_wf(net, choke_lived_in):
    # a choked bit on a non-mesh edge trips exactly "choke-wf"
    cfg, st = choke_lived_in
    mesh = np.asarray(st.mesh)
    i, s, k = map(int, np.argwhere(~mesh)[0])
    st2 = st.replace(choked=st.choked.at[i, s, k].set(True))
    res = _check(net, st2, cfg, due=QUIET)
    failed = {name for name, v in res.items() if not v}
    assert failed == {"choke-wf"}, sorted(failed)


def test_seeded_choke_starvation_trips_dlo_floor(net, choke_lived_in):
    # choke EVERY mesh link of one slot: unchoked degree 0 < Dlo trips
    # exactly "no-choke-below-dlo" (choked stays ⊆ mesh, so choke-wf
    # keeps holding — the two properties separate cleanly)
    cfg, st = choke_lived_in
    deg = np.asarray(st.mesh.sum(axis=-1))
    i, s = map(int, np.argwhere(deg >= cfg.Dlo)[0])
    st2 = st.replace(choked=st.choked.at[i, s].set(st.mesh[i, s]))
    res = _check(net, st2, cfg, due=QUIET)
    failed = {name for name, v in res.items() if not v}
    assert failed == {"no-choke-below-dlo"}, sorted(failed)
