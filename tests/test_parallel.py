"""Device-mesh sharding (parallel/sharding.py): the peer axis sharded over
a 1-D ICI mesh and a 2-D (dcn, ici) multi-host mesh on the virtual
8-device CPU platform, with results identical to the unsharded run."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.parallel import (
    make_mesh,
    make_multihost_mesh,
    peer_spec,
    shard_state,
)
from go_libp2p_pubsub_tpu.state import Net


def _build(n=128, m=32):
    topo = graph.ring_lattice(n, d=4)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    sp = PeerScoreParams(
        topics={0: TopicScoreParams()},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True
    )
    st = GossipSubState.init(net, m, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    return st, step


def _run(st, step, rounds=5):
    for r in range(rounds):
        po = jnp.asarray(np.array([r % 128, -1, -1, -1], np.int32))
        pt = jnp.zeros((4,), jnp.int32)
        pv = jnp.ones((4,), bool)
        st = step(st, po, pt, pv)
    return st


def test_multihost_mesh_shape():
    mesh = make_multihost_mesh(2)
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.devices.shape == (2, len(jax.devices()) // 2)
    assert peer_spec(mesh) == jax.sharding.PartitionSpec(("dcn", "ici"))


@pytest.mark.slow
def test_sharded_step_matches_unsharded():
    st0, step = _build()
    ref = _run(st0, step)

    for mesh in (make_mesh(8), make_multihost_mesh(2)):
        st0, step2 = _build()
        st = shard_state(st0, mesh, 128)
        got = _run(st, step2)
        for la, lb in zip(
            jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(got)
        ):
            if jnp.issubdtype(la.dtype, jax.dtypes.prng_key):
                la, lb = jax.random.key_data(la), jax.random.key_data(lb)
            assert (np.asarray(la) == np.asarray(lb)).all()
