"""Runtime topology growth: post-start connect() via the dormant-edge
pool (VERDICT round-3 item 7; notify.go:19-75 Connected, pubsub.go:614-646
newPeers) — activation on the live device state, no restart/recompile.
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import api


def two_islands(n_each=8, bridges=2):
    """Two internally-dense clusters joined ONLY by dormant bridge pairs."""
    net = api.Network()
    a = net.add_nodes(n_each)
    b = net.add_nodes(n_each)
    for grp in (a, b):
        for i, x in enumerate(grp):
            for y in grp[i + 1 :]:
                net.connect(x, y)
    pairs = [(a[i], b[i]) for i in range(bridges)]
    for x, y in pairs:
        net.connect(x, y, dormant=True)
    return net, a, b, pairs


def drain_all(subs):
    return [sum(1 for _ in s) for s in subs]


def test_post_start_connect_activates_dormant_pair():
    net, a, b, pairs = two_islands()
    subs = [nd.join("t").subscribe() for nd in a + b]
    net.start()
    step_before = net._step

    a[2].topics["t"].publish(b"pre")
    net.run(6)
    got = drain_all(subs)
    assert all(g == 1 for g in got[: len(a)])      # island A delivered
    assert all(g == 0 for g in got[len(a) :])      # island B unreachable

    net.connect(*pairs[0])                          # runtime activation
    net.run(4)                                      # mesh grafts across
    a[3].topics["t"].publish(b"post")
    net.run(6)
    got = drain_all(subs)
    assert all(g == 1 for g in got)                 # everyone got "post"
    assert net._step is step_before                 # no recompile happened


def test_post_start_connect_unprovisioned_raises():
    net, a, b, _ = two_islands(bridges=1)
    for nd in a + b:
        nd.join("t")
    net.start()
    with pytest.raises(api.APIError, match="not provisioned"):
        net.connect(a[5], b[5])


def test_disconnect_edge_returns_to_dormant():
    net, a, b, pairs = two_islands(bridges=1)
    subs = [nd.join("t").subscribe() for nd in a + b]
    net.start()
    net.connect(*pairs[0])
    net.run(4)
    a[0].topics["t"].publish(b"one")
    net.run(6)
    assert all(g == 1 for g in drain_all(subs))

    net.disconnect_edge(*pairs[0])                  # back to dormant
    net.run(2)
    a[0].topics["t"].publish(b"two")
    net.run(8)
    got = drain_all(subs)
    assert all(g == 1 for g in got[: len(a)])
    assert all(g == 0 for g in got[len(a) :])       # bridge is down again

    net.connect(*pairs[0])                          # and up once more
    net.run(4)
    a[1].topics["t"].publish(b"three")
    net.run(6)
    assert all(g == 1 for g in drain_all(subs))


def test_dormant_pool_invisible_before_activation():
    """Dormant edges are not mesh/gossip candidates while inactive."""
    net, a, b, pairs = two_islands()
    for nd in a + b:
        nd.join("t")
    net.start()
    net.run(8)
    mesh = np.asarray(net.state.mesh)  # [N,S,K]
    nbr = np.asarray(net.net.nbr)
    n_each = len(a)
    for p in range(mesh.shape[0]):
        for k in np.flatnonzero(mesh[p].any(axis=0)):
            q = nbr[p, k]
            assert (p < n_each) == (q < n_each), "mesh crossed a dormant bridge"


def test_runtime_ops_guarded_without_liveness_plane():
    """A network compiled WITHOUT the edge-liveness plane must refuse
    runtime edge ops instead of silently writing an unread mask."""
    net = api.Network()
    a, b = net.add_nodes(2)
    for extra in net.add_nodes(6):
        net.connect(a, extra)
        net.connect(b, extra)
    net.connect(a, b)
    for nd in net.nodes:
        nd.join("t")
    net.start()
    with pytest.raises(api.APIError, match="edge-liveness plane"):
        net.disconnect_edge(a, b)


def test_dormant_then_live_prestart_last_wins():
    net = api.Network()
    nodes = net.add_nodes(10)
    net.dense_connect(d=4, seed=2)
    net.connect(nodes[0], nodes[9], dormant=True)
    net.connect(nodes[0], nodes[9])  # explicit live connect overrides
    assert not net._dormant_pairs


def test_dormant_rejected_on_non_gossipsub():
    net = api.Network(router="floodsub")
    a, b = net.add_nodes(2)
    with pytest.raises(api.APIError, match="gossipsub"):
        net.connect(a, b, dormant=True)


def _claim_spare_and_deliver(net, nodes, subs, spare, graft_rounds,
                             deliver_rounds):
    """Shared spare-claim flow: count recompiles around claim + edge
    activation + bidirectional delivery; returns (newcomer, recompiles).
    Used by the per-round and phase-cadence variants below so the claim
    semantics can't drift between them."""
    recompiles = 0
    orig = net._recompile_gossipsub

    def counting():
        nonlocal recompiles
        recompiles += 1
        orig()

    net._recompile_gossipsub = counting

    newcomer = net.add_node()
    assert newcomer is spare
    assert newcomer.up
    sub_new = newcomer.topics["x"].subscribe()
    nbr = np.asarray(net.net.nbr)[newcomer.idx]
    ok = np.asarray(net.net.nbr_ok)[newcomer.idx]
    for nb in [net.nodes[int(j)] for j in nbr[ok]]:
        net.connect(newcomer, nb)

    # a message published INSIDE the claim window (before any heartbeat
    # grafts the row) must still arrive via gossip recovery — the
    # IHAVE/IWANT path serves not-yet-meshed rows
    nodes[0].topics["x"].publish(b"during-claim")
    net.run(graft_rounds)  # heartbeat grafts the claimed row in
    nodes[1].topics["x"].publish(b"to-newcomer")
    net.run(deliver_rounds)
    got_new = [m.data for m in iter(sub_new)]
    assert b"during-claim" in got_new, got_new
    assert b"to-newcomer" in got_new, got_new
    # and the newcomer can publish to the whole network
    newcomer.topics["x"].publish(b"from-newcomer")
    net.run(deliver_rounds)
    for s in subs:
        datas = [m.data for m in iter(s)]
        assert b"from-newcomer" in datas, datas
    return newcomer, recompiles


def test_spare_node_post_start_add_node_zero_recompiles():
    """Dormant PEER rows (round-4 review item 9): provision_spare_nodes
    pre-start, then post-start add_node() claims a row — connect,
    subscribe, and delivery all work with ZERO recompiles (the reference
    admits unknown peers at any moment, pubsub.go:614-646/notify.go:19-75;
    the jit-constant design pre-provisions the capacity)."""
    from go_libp2p_pubsub_tpu import api as api_mod

    net = api_mod.Network(seed=3)
    nodes = net.add_nodes(20)
    net.dense_connect(d=6, seed=3)
    subs = [nd.join("x").subscribe() for nd in nodes]
    spares = net.provision_spare_nodes(2, topics=("x",), degree=4, seed=3)
    net.start()
    net.run(4)  # mesh forms among the 20 live nodes

    # spares are invisible while down: no deliveries to them
    nodes[0].topics["x"].publish(b"before")
    net.run(4)
    assert all(sum(1 for _ in s) >= 1 for s in subs)

    _, recompiles = _claim_spare_and_deliver(
        net, nodes, subs, spares[0], graft_rounds=2, deliver_rounds=4
    )
    assert recompiles == 0, f"claimed spare row triggered {recompiles} recompiles"

    # pool exhaustion is an explicit error pointing at the capacity path
    net.add_node()  # second spare
    with pytest.raises(api_mod.APIError, match="spare-node pool is empty"):
        net.add_node()


def test_spare_node_invisible_while_down():
    """Provisioned-but-unclaimed rows take no part in the protocol: no
    deliveries, no mesh membership, no gossip — the subscription template
    is inert until the row comes up."""
    from go_libp2p_pubsub_tpu import api as api_mod

    net = api_mod.Network(seed=5)
    nodes = net.add_nodes(16)
    net.dense_connect(d=5, seed=5)
    subs = [nd.join("x").subscribe() for nd in nodes]
    spare = net.provision_spare_nodes(1, topics=("x",), degree=3, seed=5)[0]
    spare_sub = spare.topics["x"].subscribe()
    net.start()
    for i in range(3):
        nodes[i].topics["x"].publish(b"m%d" % i)
    net.run(10)
    assert all(sum(1 for _ in s) == 3 for s in subs)
    assert sum(1 for _ in spare_sub) == 0  # down row saw nothing
    mesh = np.asarray(net.state.mesh)
    assert not mesh[spare.idx].any()  # and sits in no mesh
    # nobody meshes TOWARD the down row either
    toward = np.asarray(net.net.nbr) == spare.idx  # [N, K]
    assert not (mesh & toward[:, None, :]).any()


def test_spare_node_claim_under_phase_cadence():
    """Spare-row claiming composes with the phase engine: the same claim
    flow as the per-round variant (shared helper) at rounds_per_phase=4
    — zero recompiles at the flagship cadence; the graft/delivery
    windows widen to whole phases."""
    from go_libp2p_pubsub_tpu import api as api_mod

    net = api_mod.Network(seed=7, rounds_per_phase=4)
    nodes = net.add_nodes(20)
    net.dense_connect(d=6, seed=7)
    subs = [nd.join("x").subscribe() for nd in nodes]
    spare = net.provision_spare_nodes(1, topics=("x",), degree=4, seed=7)[0]
    net.start()
    net.run(4)

    _, recompiles = _claim_spare_and_deliver(
        net, nodes, subs, spare, graft_rounds=8, deliver_rounds=8
    )
    assert recompiles == 0
