"""Static range/overflow audit tests (analysis/ranges.py, `make
range-audit`, docs/DESIGN.md §23).

Three layers:

* interpreter units — the interval domain walked over tiny hand-built
  jaxprs (comparison folding, feasibility-aware select, scan widening,
  the exact pinned-scatter path);
* contract negatives — every hard contract tripped by a DOCTORED
  input and the violation message checked to NAME the exact eqn/leaf
  (the no-silent-pass property is itself under test);
* artifact pins — the committed RANGE_AUDIT.json carries the proofs
  the prose claims (the PR-11 int16 bound, the envelope NEEDS_I64
  refutations, the per-EV horizons), and a doctored copy diverges by
  NAME through costmodel.baseline_divergences.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.analysis import ranges as rg

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _walk(fn, in_ivals, *example_args):
    """Trace fn, walk the jaxpr with the given input intervals."""
    jpr = jax.make_jaxpr(fn)(*example_args)
    rec = rg.Recorder()
    outs = rg.interp_closed(jpr, list(in_ivals), rec)
    return outs, rec


# ---------------------------------------------------------------------------
# interpreter units


def test_interval_arithmetic_affine():
    x = jnp.zeros(4, jnp.int32)
    outs, _ = _walk(lambda x: x * 2 + 1, [rg._full((4,), 0, 10)], x)
    lo, hi = outs[0]
    assert float(lo.min()) == 1 and float(hi.max()) == 21


def test_comparison_folding_feeds_select():
    # the jnp.mod / negative-index fix-up shape: select_n(x < 0, x, x+7)
    # with x proven non-negative must NOT union in the x+7 arm — that
    # false widening is what used to break every gather bound proof
    x = jnp.zeros(4, jnp.int32)
    outs, _ = _walk(lambda x: jnp.where(x < 0, x + 7, x),
                    [rg._full((4,), 0, 6)], x)
    lo, hi = outs[0]
    assert float(lo.min()) == 0 and float(hi.max()) == 6


def test_scan_carry_widens_to_dtype_top():
    # a growing carry cannot keep its seeded bounds across unknown
    # iteration counts — soundness demands dtype-top, not [0, length]
    def f(c):
        out, _ = jax.lax.scan(lambda c, _: (c + 1, c), c,
                              jnp.zeros(4, jnp.int32))
        return out

    outs, _ = _walk(f, [rg._full((), 0, 0)], jnp.int32(0))
    assert float(outs[0][1].max()) == float(np.iinfo(np.int32).max)


def test_gather_in_bounds_proven():
    a = jnp.zeros(8, jnp.int32)
    i = jnp.zeros(3, jnp.int32)
    outs, rec = _walk(lambda a, i: a[i],
                      [rg._full((8,), 0, 5), rg._full((3,), 0, 7)], a, i)
    sites = [s for s in rec.index if s.primitive == "gather"]
    assert sites and all(s.proven for s in sites)
    assert float(outs[0][1].max()) == 5  # operand bounds flow through
    # and the triage accepts it without any catalog entry
    res = rg.check_index_bounds("unit", rec.index, {})
    assert res["proven"] == len(rec.index) and not res["sanctioned"]


def test_gather_oob_promise_is_violation():
    # DOCTORED: index interval [0, 8] into an 8-slot operand under jnp's
    # default PROMISE_IN_BOUNDS — must refuse with the exact eqn path,
    # and a sanctioned-drop catalog entry must NOT rescue a promise mode
    a = jnp.zeros(8, jnp.int32)
    i = jnp.zeros(3, jnp.int32)
    _, rec = _walk(lambda a, i: a[i],
                   [rg._full((8,), 0, 5), rg._full((3,), 0, 8)], a, i)
    bad = [s for s in rec.index if not s.proven]
    assert bad, "the OOB gather site must be recorded as unproven"
    with pytest.raises(rg.RangeContractViolation) as e:
        rg.check_index_bounds("unit", rec.index,
                              {"gather": "not a rescue for promise modes"})
    assert e.value.contract == "index-bounds"
    assert "eqns[" in str(e.value) and "undefined behavior" in str(e.value)


def test_pinned_scatter_add_is_per_slot_exact():
    # the counters.at[EV.X].add(n) shape: only the addressed slot moves
    c = jnp.zeros(18, jnp.int32)
    n = jnp.int32(0)
    outs, rec = _walk(lambda c, n: c.at[3].add(n),
                      [rg._full((18,), 0, 0), rg._full((), 0, 5)], c, n)
    lo, hi = outs[0]
    assert float(hi[3]) == 5
    assert float(np.delete(np.asarray(hi), 3).max()) == 0
    assert all(s.proven for s in rec.index)


def test_narrow_nonwrap_negative_names_eqn():
    # DOCTORED: int16 x + x seeded near the dtype ceiling wraps; the
    # violation must name the eqn and the sub-i32 dtype
    x = jnp.zeros(2, jnp.int16)
    _, rec = _walk(lambda x: x + x, [rg._full((2,), 0, 30000)], x)
    assert rec.narrow and not rec.narrow[-1].fits
    with pytest.raises(rg.RangeContractViolation) as e:
        rg.check_narrow_nonwrap("unit", rec.narrow)
    assert e.value.contract == "narrow-nonwrap"
    assert "eqns[" in str(e.value) and "int16" in str(e.value)

    # in-range bounds prove clean through the same checker
    _, rec2 = _walk(lambda x: x + x, [rg._full((2,), 0, 100)], x)
    assert rec2.narrow and all(s.fits for s in rec2.narrow)
    rg.check_narrow_nonwrap("unit", rec2.narrow)


# ---------------------------------------------------------------------------
# symbolic index-width leg


def test_scale_leg_verdicts_explicit_everywhere():
    leg = rg.scale_leg()
    for geo in leg.values():
        for row in geo["sites"].values():
            for cell in row["by_n"].values():
                assert cell["verdict"] in ("PROVEN_I32", "NEEDS_I64")
    # audit geometry (k=16, m=64) holds i32 through 10M; the flood
    # envelope (k=64, m=1024) is the honest refuter at 10M
    refuted = rg.check_index_width(leg)
    assert refuted and all(r.startswith("envelope.") for r in refuted)
    assert "envelope.flat_ew.10000000" in refuted


def test_index_width_missing_verdict_is_no_silent_pass():
    leg = rg.scale_leg()
    leg["audit"]["sites"]["col"]["by_n"]["100000"]["verdict"] = None
    with pytest.raises(rg.RangeContractViolation) as e:
        rg.check_index_width(leg)
    assert "index_width.audit.sites.col.by_n.100000" in str(e.value)
    assert "no silent pass" in str(e.value)


def test_index_width_audit_refutation_needs_acknowledgment():
    leg = rg.scale_leg()
    leg["audit"]["sites"]["flat_ew"]["by_n"]["10000000"]["verdict"] = \
        "NEEDS_I64"
    with pytest.raises(rg.RangeContractViolation) as e:
        rg.check_index_width(leg)  # I64_ACKNOWLEDGED is empty
    assert "index_width.audit.sites.flat_ew.by_n.10000000" in str(e.value)
    # the same doctored leg passes once the site is acknowledged
    refuted = rg.check_index_width(leg, acknowledged=("flat_ew",))
    assert "audit.flat_ew.10000000" in refuted


def test_index_width_verdict_for_memstat():
    assert rg.index_width_verdict(256) == "PROVEN_I32"
    assert rg.index_width_verdict(10_000_000, "audit") == "PROVEN_I32"
    assert rg.index_width_verdict(10_000_000, "envelope") == "NEEDS_I64"


# ---------------------------------------------------------------------------
# overflow horizons + narrow manifest


def test_horizons_from_deltas():
    h = rg.horizons_from_deltas({"QUIET": 0, "BUSY": 524288})
    assert h["QUIET"]["i32_horizon_rounds"] is None
    assert h["BUSY"]["i32_horizon_rounds"] == (2 ** 31 - 1) // 524288
    assert h["BUSY"]["f32_exact_horizon_rounds"] == 2 ** 24 // 524288


def test_horizon_below_floor_names_counter():
    with pytest.raises(rg.RangeContractViolation) as e:
        rg.horizons_from_deltas({"HOT": 2 ** 31})
    assert e.value.contract == "overflow-horizon"
    assert "horizons.events.HOT" in str(e.value)


def test_narrow_manifest_mismatch_names_file():
    found = dict(rg.NARROW_ASTYPE_MANIFEST)
    rg.check_narrow_manifest(found)  # identity passes
    found["models/doctored.py"] = ("int8",)
    with pytest.raises(rg.RangeContractViolation) as e:
        rg.check_narrow_manifest(found)
    assert "narrow_astype_manifest.models/doctored.py" in str(e.value)

    # and a declared-but-vanished site fails the other direction
    with pytest.raises(rg.RangeContractViolation):
        rg.check_narrow_manifest({}, manifest={"ops/x.py": ("int16",)})


def test_narrow_astype_scan_matches_manifest():
    found = {rel: tuple(dts)
             for rel, dts in rg.narrow_astype_scan().items()}
    assert found == dict(rg.NARROW_ASTYPE_MANIFEST)


# ---------------------------------------------------------------------------
# the committed artifact


def _committed():
    with open(os.path.join(ROOT, rg.AUDIT_NAME)) as f:
        return json.load(f)


def test_committed_artifact_pins_the_proofs():
    audit = _committed()
    assert all(c["pass"] for c in audit["contracts"].values())
    # the PR-11 narrow_counters int16 proof, machine-checked: exactly
    # the peerhave/iasked accumulate sites, with REAL bounds (not top)
    narrow = audit["builds"]["narrow"]["narrow"]
    i16 = [s for s in narrow["sites"] if s["dtype"] == "int16"]
    assert len(i16) == 4 and all(s["fits"] for s in i16)
    assert max(s["hi"] for s in i16) <= 128
    # index triage: every build fully triaged, no unproven-unsanctioned
    for name, b in audit["builds"].items():
        assert b["index"]["proven"] + len(b["index"]["sanctioned"]) \
            == b["index"]["checked"], name
    # envelope-only i64 refutations, audit geometry clean
    assert audit["index_width"]["needs_i64"] == [
        "envelope.dense_nkw.10000000",
        "envelope.first_round_nm.10000000",
        "envelope.flat_ew.10000000",
    ]
    floor = audit["horizons"]["floor_rounds"]
    worst = audit["contracts"]["overflow_horizon"]["min_i32_horizon_rounds"]
    assert worst >= floor
    ev = audit["horizons"]["events"]
    assert ev["DUPLICATE_MESSAGE"]["i32_horizon_rounds"] == worst


def test_doctored_artifact_diverges_by_name():
    # the byte-identity gate's mismatch report: a single doctored leaf
    # is NAMED by its JSON key path (costmodel.baseline_divergences)
    audit = _committed()
    doctored = json.loads(json.dumps(audit))
    doctored["builds"]["narrow"]["narrow"]["sites"][0]["hi"] = 99999
    keys = rg.baseline_divergences(doctored, audit)
    assert any("builds.narrow.narrow.sites" in k for k in keys)
    assert not rg.baseline_divergences(audit, audit)


@pytest.mark.slow
def test_range_audit_script_reproduces_committed():
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items()
           if k != "XLA_FLAGS" and not k.startswith("JAX_")}
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "range_audit.py")],
        capture_output=True, text=True, cwd=ROOT, timeout=570, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.strip().startswith("{")][-1]
    summary = json.loads(line)
    assert summary["range_audit"] == "PASS"
    assert summary["artifact"] == "verified"
    assert summary["min_i32_horizon_rounds"] >= 1000
