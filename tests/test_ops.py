"""Unit tests for the kernel building blocks (packed bitsets, masked
selection) against numpy oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.ops import (
    bit_get,
    bit_set,
    count_true,
    make_mask_below,
    median_masked,
    n_words,
    pack,
    popcount,
    rank_desc,
    select_random_mask,
    select_topk_mask,
    unpack,
)


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    bits = rng.random((5, 70)) < 0.3
    words = pack(jnp.asarray(bits))
    assert words.shape == (5, n_words(70))
    out = np.asarray(unpack(words, 70))
    np.testing.assert_array_equal(out, bits)


def test_popcount():
    rng = np.random.default_rng(1)
    bits = rng.random((4, 100)) < 0.5
    words = pack(jnp.asarray(bits))
    np.testing.assert_array_equal(np.asarray(popcount(words)), bits.sum(axis=1))


def test_bit_get_set():
    bits = np.zeros((3, 64), dtype=bool)
    words = pack(jnp.asarray(bits))
    idx = jnp.asarray([5, 33, 63])
    on = jnp.asarray([True, False, True])
    words2 = bit_set(words, idx, on)
    got = np.asarray(bit_get(words2, idx))
    np.testing.assert_array_equal(got, [True, False, True])
    # untouched bits stay zero
    assert int(popcount(words2).sum()) == 2


def test_make_mask_below():
    m = make_mask_below(jnp.int32(40), 64)
    bits = np.asarray(unpack(m, 64))
    np.testing.assert_array_equal(bits, np.arange(64) < 40)


def test_rank_desc_basic():
    v = jnp.asarray([[3.0, 1.0, 2.0, 9.0]])
    mask = jnp.asarray([[True, True, True, False]])
    r = np.asarray(rank_desc(v, mask))
    # 3.0 is rank 0, 2.0 rank 1, 1.0 rank 2; masked-out 9.0 last
    np.testing.assert_array_equal(r, [[0, 2, 1, 3]])


def test_select_topk_mask_per_row_k():
    v = jnp.asarray([[5.0, 4.0, 3.0, 2.0], [1.0, 2.0, 3.0, 4.0]])
    mask = jnp.ones((2, 4), dtype=bool)
    k = jnp.asarray([1, 2])
    sel = np.asarray(select_topk_mask(v, mask, k))
    np.testing.assert_array_equal(sel, [[True, False, False, False], [False, False, True, True]])


def test_select_topk_respects_mask_and_short_rows():
    v = jnp.asarray([[5.0, 4.0, 3.0, 2.0]])
    mask = jnp.asarray([[False, True, False, True]])
    sel = np.asarray(select_topk_mask(v, mask, 3))
    # only 2 eligible; both selected, none outside mask
    np.testing.assert_array_equal(sel, [[False, True, False, True]])


def test_select_random_mask_uniformity():
    key = jax.random.key(0)
    mask = jnp.ones((2000, 8), dtype=bool)
    sel = np.asarray(select_random_mask(key, mask, 3))
    assert (sel.sum(axis=1) == 3).all()
    freq = sel.mean(axis=0)
    # each slot picked ~3/8 of the time
    assert np.all(np.abs(freq - 3 / 8) < 0.05)


def test_random_tiebreak_varies():
    key = jax.random.key(1)
    v = jnp.zeros((500, 6))
    mask = jnp.ones((500, 6), dtype=bool)
    keys = jax.random.split(key, 500)
    sel = np.asarray(
        jax.vmap(lambda k, vv, mm: select_topk_mask(vv, mm, 2, key=k))(keys, v, mask)
    )
    freq = sel.mean(axis=0)
    assert np.all(np.abs(freq - 2 / 6) < 0.07)


def test_count_true():
    m = jnp.asarray([[True, False, True]])
    assert int(count_true(m)[0]) == 2


def test_median_masked_upper_median():
    # reference uses plst[len/2] after ascending sort (gossipsub.go:1492)
    v = jnp.asarray([[1.0, 2.0, 3.0, 4.0, 0.0]])
    mask = jnp.asarray([[True, True, True, True, False]])
    # n=4 -> index 2 -> value 3.0 (upper median)
    assert float(median_masked(v, mask)[0]) == 3.0
    # empty mask -> +inf
    assert np.isinf(float(median_masked(v, jnp.zeros((1, 5), bool))[0]))


def test_pytest_env_has_8_devices():
    assert len(jax.devices()) == 8


def test_first_edge_of_matches_scan_incl_k128():
    # slot 127 at K=128 must be reported, not confused with the sentinel
    import numpy as np

    from go_libp2p_pubsub_tpu.ops import bitset

    rng = np.random.default_rng(7)
    for n, k, m in [(4, 16, 40), (3, 128, 33)]:
        w = (m + 31) // 32
        trans = rng.integers(0, 2**32, size=(n, k, w), dtype=np.uint64).astype(np.uint32)
        # zero out invalid high bits
        trans = np.asarray(bitset.pack(bitset.unpack(jnp.asarray(trans), m)))
        got = np.asarray(bitset.first_edge_of(jnp.asarray(trans), m))
        bits = np.asarray(bitset.unpack(jnp.asarray(trans), m))  # [n,k,m]
        want = np.full((n, m), -1, np.int8)
        for kk in range(k - 1, -1, -1):
            want = np.where(bits[:, kk, :], kk, want)
        assert (got == want).all()
    # slot-127-only case
    trans = np.zeros((1, 128, 1), np.uint32)
    trans[0, 127, 0] = 0b1000
    got = np.asarray(bitset.first_edge_of(jnp.asarray(trans), 4))
    assert got[0, 3] == 127 and (got[0, :3] == -1).all()


def test_first_set_per_bit_matches_naive():
    from go_libp2p_pubsub_tpu.ops import bitset

    rng = np.random.default_rng(13)
    for n, k, w in [(5, 16, 2), (3, 7, 1), (2, 1, 3)]:
        words = rng.integers(0, 2**32, size=(n, k, w), dtype=np.uint64).astype(
            np.uint32
        )
        got = np.asarray(bitset.first_set_per_bit(jnp.asarray(words), axis=1))
        # naive: for each bit, keep it only on the lowest k that has it
        seen = np.zeros((n, w), np.uint32)
        want = np.zeros_like(words)
        for kk in range(k):
            want[:, kk] = words[:, kk] & ~seen
            seen |= words[:, kk]
        assert (got == want).all(), (n, k, w)
        # exactly one surviving copy of each present bit
        assert (
            np.asarray(bitset.popcount(jnp.asarray(got), axis=None)).sum()
            == np.asarray(
                bitset.popcount(
                    jnp.asarray(seen), axis=None
                )
            ).sum()
        )


def test_lowest_bit_matches_naive():
    from go_libp2p_pubsub_tpu.ops import bitset

    rng = np.random.default_rng(17)
    words = rng.integers(0, 2**32, size=(64, 3), dtype=np.uint64).astype(np.uint32)
    words[5] = 0  # empty row
    words[6, 0] = 0  # first word empty, later set
    idx, has = bitset.lowest_bit(jnp.asarray(words))
    idx, has = np.asarray(idx), np.asarray(has)
    for i in range(64):
        flat = [w * 32 + b for w in range(3) for b in range(32) if (int(words[i, w]) >> b) & 1]
        if flat:
            assert has[i] and idx[i] == min(flat), i
        else:
            assert not has[i] and idx[i] == 0, i


def test_allocate_publishes_scatter_plane_equivalence(monkeypatch):
    """allocate_publishes has two trace-time forms (N-gated: plane
    selects below ~20k peers, column/word scatters above — measured
    crossover on the real chip, see state.py docstring). They must be
    bit-identical; this drives a full gossipsub sim under each via the
    PUBSUB_PUB_SCATTER override and compares every state plane."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.state import Net

    n, m, rounds = 48, 32, 12
    topo = graph.random_connect(n, 6, seed=9)
    subs = graph.subscribe_random(n, n_topics=2, topics_per_peer=2, seed=9)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=False
    )
    rng = np.random.default_rng(9)
    po = rng.integers(-1, n, size=(rounds, 4)).astype(np.int32)
    pt = rng.integers(0, 2, size=(rounds, 4)).astype(np.int32)
    pv = np.ones((rounds, 4), bool)

    def run(form):
        monkeypatch.setenv("PUBSUB_PUB_SCATTER", form)
        st = GossipSubState.init(net, m, cfg, seed=9)
        step = make_gossipsub_step(cfg, net)
        for i in range(rounds):
            st = step(st, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                      jnp.asarray(pv[i]))
        return st

    sa, sb = run("0"), run("1")
    lb, _ = jax.tree_util.tree_flatten(sb)
    paths = jax.tree_util.tree_flatten_with_path(sa)[0]
    for (path, xa), xb in zip(paths, lb):
        if jnp.issubdtype(getattr(xa, "dtype", None), jax.dtypes.prng_key):
            xa, xb = jax.random.key_data(xa), jax.random.key_data(xb)
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), \
            jax.tree_util.keystr(path)


def test_keep_lowest_bits_equals_prefix_cap_bits():
    """The static-cap clear-lowest-bit chain (keep_lowest_bits) must
    match prefix_cap_bits with a full(cap) plane for every cap, shape,
    and bit density — including empty rows, rows with fewer set bits
    than the cap, the >64 fallback, and DIRTY PADDING (m % 32 != 0 with
    the last word's pad bits set: prefix_cap_bits' unpack(m) drops
    pads, so keep_lowest_bits must mask them via its m parameter)."""
    from go_libp2p_pubsub_tpu.ops import bitset

    rng = np.random.default_rng(5)
    for shape, m in (((17,), 64), ((9, 5), 96), ((4, 3), 32), ((7,), 48)):
        for density in (0.0, 0.1, 0.5, 0.95):
            bits = rng.random(shape + (m,)) < density
            words = bitset.pack(jnp.asarray(bits))
            if m % 32 != 0:
                # dirty pads: set bits >= m in the last word
                words = words.at[..., -1].set(
                    words[..., -1] | jnp.uint32(0xFFFF0000)
                )
            for cap in (0, 1, 3, 8, 31, 32, 63, 64, 65, 100, m):
                ref = bitset.prefix_cap_bits(
                    words, jnp.full(shape, cap, jnp.int32), m
                )
                # prefix_cap_bits' output has clean pads by construction;
                # compare on the valid region
                got = bitset.keep_lowest_bits(words, cap, m)
                assert np.array_equal(np.asarray(ref), np.asarray(got)), \
                    (shape, m, density, cap)


def test_masked_keep_matches_per_plane():
    """The round-7 stacked recycled-slot clear == per-plane ANDs, for
    mixed [N,W]/[N,K,W]/[N,V,W] planes, None passthrough, and the
    single-plane fast path."""
    from go_libp2p_pubsub_tpu.ops import bitset

    rng = np.random.default_rng(0)
    n, k, v, w = 5, 3, 2, 4
    keep = jnp.asarray(rng.integers(0, 2**32, size=(w,), dtype=np.uint32))
    a = jnp.asarray(rng.integers(0, 2**32, size=(n, w), dtype=np.uint32))
    b = jnp.asarray(rng.integers(0, 2**32, size=(n, k, w), dtype=np.uint32))
    c = jnp.asarray(rng.integers(0, 2**32, size=(n, v, w), dtype=np.uint32))
    got = bitset.masked_keep([a, None, b, c], keep)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(a & keep[None, :]))
    assert got[1] is None
    np.testing.assert_array_equal(
        np.asarray(got[2]), np.asarray(b & keep[None, None, :]))
    np.testing.assert_array_equal(
        np.asarray(got[3]), np.asarray(c & keep[None, None, :]))
    # single live plane takes the direct path
    (only,) = bitset.masked_keep([b], keep)
    np.testing.assert_array_equal(
        np.asarray(only), np.asarray(b & keep[None, None, :]))
    assert bitset.masked_keep([None, None], keep) == [None, None]
