"""Phase engine vs a heartbeat-cadence ORACLE — the reference's actual
timing shape as the parity anchor (round-4 review item 2).

Until round 4 the phase engine's parity row was engine-vs-engine (r vs
r=1, same seeds), bounding its distance from the PER-ROUND step — which
is itself a deviation from the reference's cadence (control after every
hop). The oracle now speaks the reference's shape directly
(OracleGossipSub with cfg.heartbeat_every = h > 1): delivery and control
PROCESSING stay continuous — the reference handles GRAFT/PRUNE/IHAVE/
IWANT on RPC arrival (gossipsub.go:596-613) — while the heartbeat batch
(score refresh, promise penalties, mesh maintenance, fanout maintenance,
gossip EMISSION, mcache shift) runs every h-th round
(gossipsub.go:1278-1301), at the same executed ticks as the phase
engine's tail heartbeat.

What the measured distance contains: the phase engine additionally
defers control ingest + IWANT service to phase heads (the oracle, like
the reference, does not), so phase(r=h) vs oracle(h) includes the phase
engine's extra control-batching latency — the honest gap vs the
reference's shape, in a way phase-vs-per-round never measured.

Measured (CPU, N=192 d=8, v1.1 scoring, 8 seeds/side, 64 msgs/seed,
leave-one-out jackknife over all 64 drop-one pool pairs — recorded in
PARITY.md):
  h=4:  pooled sup 0.48% (jk mean 0.50% / max 0.96%)  coverage 100%/100%
  h=8:  pooled sup 0.40% (jk mean 0.47% / max 0.91%)  coverage 100%/100%
  h=16: pooled sup 0.13% (jk mean 0.25% / max 0.51%)  coverage 100%/100%
  (5-seed pools measured 1.29%/1.52% at h=4/8 with jk max ~2.35% — the
  distance shrinks with pool size, i.e. it is sampling noise, not
  structure; and it shrinks with h — deeper cadences align the two
  sides' control batching even more closely)
UNDER the 2% north-star envelope at all three cadences including
jackknife max — the flagship mode is reference-anchored, proving the
round-4 "the per-round step is the outlier" claim with a measurement:
against the correctly-shaped target the distance drops from the
engine-vs-engine rows' 3.09%/3.58% (r=4/8) to well under 1% — that old
distance was the PER-ROUND comparison side's over-tight control, as
predicted.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import make_gossipsub_phase_step
from go_libp2p_pubsub_tpu.oracle.gossipsub import OracleGossipSub
from go_libp2p_pubsub_tpu.state import Net, hops

N, D, M = 192, 8, 64
WARMUP, PUB_ROUNDS, DRAIN, PUBS = 24, 16, 16, 4  # 56 rounds, 64 msgs
MAX_H = 16
SEEDS_V = (3, 4, 5, 6, 7, 8, 9, 10)
SEEDS_O = (11, 12, 13, 14, 15, 16, 17, 18)


def _score_params():
    tp = TopicScoreParams(
        mesh_message_deliveries_weight=-0.3,
        mesh_message_deliveries_threshold=3.0,
        mesh_message_deliveries_activation=8.0,
        mesh_message_deliveries_window=2.0,
    )
    return PeerScoreParams(topics={0: tp}, skip_app_specific=True,
                           behaviour_penalty_weight=-1.0,
                           behaviour_penalty_threshold=1.0,
                           behaviour_penalty_decay=0.9)


def _cfg(h):
    return GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
        heartbeat_every=h,
    )


def _schedule(seed, drain):
    """Publish schedule [total, PUBS] shared by both sides of a seed."""
    total = WARMUP + PUB_ROUNDS + drain
    rng = np.random.default_rng(seed * 7 + 1)
    po = np.full((total, PUBS), -1, np.int32)
    po[WARMUP : WARMUP + PUB_ROUNDS] = rng.integers(
        0, N, size=(PUB_ROUNDS, PUBS)
    )
    return po, total


def _run_phase_engine(h, seed, drain):
    """Phase engine at r = h, heartbeat once per phase (tail)."""
    topo = graph.random_connect(N, d=D, seed=seed)
    subs = graph.subscribe_all(N, 1)
    net = Net.build(topo, subs)
    sp = _score_params()
    cfg = _cfg(h)
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed)
    po, total = _schedule(seed, drain)
    pt = np.zeros_like(po)
    pv = np.ones(po.shape, bool)
    pstep = make_gossipsub_phase_step(cfg, net, h, score_params=sp)
    g = total // h
    gro = lambda a: jnp.asarray(a).reshape((g, h) + a.shape[1:])
    xo, xt, xv = gro(po), gro(pt), gro(pv)
    for p in range(g):
        st = pstep(st, xo[p], xt[p], xv[p], do_heartbeat=True)
    hv = np.asarray(hops(st.core.msgs, st.core.dlv))
    return [int(x) for x in hv[hv >= 0]]


def _run_oracle(h, seed, drain):
    """Heartbeat-cadence oracle: continuous control, heartbeat every h."""
    topo = graph.random_connect(N, d=D, seed=seed)
    subs = graph.subscribe_all(N, 1)
    o = OracleGossipSub(topo, subs, _cfg(h), msg_slots=M, seed=seed + 100,
                        score_params=_score_params())
    po, total = _schedule(seed, drain)
    for i in range(total):
        o.step([(int(p), 0, True) for p in po[i] if p >= 0])
    return [int(x) for x in o.hops().values()]


def _sup_with_jackknife(hv_per_seed, ho_per_seed, denom_per_run):
    sv, so = len(hv_per_seed), len(ho_per_seed)

    def pooled(per_seed, skip):
        hist = np.zeros(MAX_H + 1)
        for i, hs in enumerate(per_seed):
            if i == skip:
                continue
            for hh in hs:
                hist[min(int(hh), MAX_H)] += 1
        runs = len(per_seed) - (1 if skip is not None else 0)
        return np.cumsum(hist) / (runs * denom_per_run)

    full = float(np.max(np.abs(pooled(hv_per_seed, None)
                               - pooled(ho_per_seed, None))))
    jk = [
        float(np.max(np.abs(pooled(hv_per_seed, i) - pooled(ho_per_seed, j))))
        for i in range(sv) for j in range(so)
    ]
    return full, float(np.mean(jk)), float(np.max(jk))


def measure(h, seeds_v=SEEDS_V, seeds_o=SEEDS_O, drain=DRAIN):
    """The schedule length (WARMUP + PUB_ROUNDS + drain) must be a
    multiple of h; h=16 passes drain=24 (56 -> 64 rounds)."""
    denom = N * PUB_ROUNDS * PUBS
    hv = [_run_phase_engine(h, s, drain) for s in seeds_v]
    ho = [_run_oracle(h, s, drain) for s in seeds_o]
    cov_v = np.mean([len(x) / denom for x in hv])
    cov_o = np.mean([len(x) / denom for x in ho])
    sup, jk_mean, jk_max = _sup_with_jackknife(hv, ho, denom)
    return sup, jk_mean, jk_max, cov_v, cov_o


# pooled bound = the 2% north-star envelope (measured 0.48/0.40/0.13% at
# h=4/8/16, 8 seeds); jk max enforced under the same envelope (measured
# 0.96/0.91/0.51%) — a margin that only holds for one lucky seed set is
# not parity
POOLED_BOUND = 0.02
JK_MAX_BOUND = 0.02


@pytest.mark.slow
@pytest.mark.parametrize("h,drain", [(4, DRAIN), (8, DRAIN), (16, 24)])
def test_phase_vs_heartbeat_cadence_oracle(h, drain):
    sup, jk_mean, jk_max, cov_v, cov_o = measure(h, drain=drain)
    print(f"phase(r={h}) vs oracle(h={h}): sup={100*sup:.2f}% "
          f"(jk {100*jk_mean:.2f}/{100*jk_max:.2f}%) "
          f"cov {cov_v:.4f}/{cov_o:.4f}")
    assert cov_v > 0.995 and cov_o > 0.995
    assert sup <= POOLED_BOUND, (
        f"h={h}: pooled sup {100*sup:.2f}% above the 2% envelope"
    )
    assert jk_max <= JK_MAX_BOUND, (
        f"h={h}: jackknife max {100*jk_max:.2f}% above bound"
    )


def test_oracle_heartbeat_cadence_mode_basics():
    """Cheap structural checks of the h>1 oracle (quick tier): gossip
    emission only at heartbeat ticks, continuous delivery in between,
    full coverage on a small net."""
    topo = graph.random_connect(48, d=6, seed=2)
    subs = graph.subscribe_all(48, 1)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=False,
        heartbeat_every=4,
    )
    cfg = dataclasses.replace(cfg, fanout_slots=0)
    o = OracleGossipSub(topo, subs, cfg, msg_slots=32, seed=7)
    # heartbeat ticks are ≡ 3 (mod 4): ihave_out is empty right after
    # non-heartbeat rounds (one-shot, cleared after ingest)
    for i in range(12):
        o.step([(0, 0, True)] if i == 6 else [])
        has_ihave = any(o.ihave_out[j] for j in range(48))
        # tick already incremented: ihave_out may be nonzero only right
        # after a heartbeat round (tick % 4 == 0 post-increment)
        if o.tick % 4 != 0:
            assert not has_ihave
    for _ in range(12):
        o.step()
    # the publish reached everyone despite h=4 (mesh formed at tick 3)
    cov = sum(1 for (i, s), r in o.first_round.items() if s == 0)
    assert cov >= 47, cov
