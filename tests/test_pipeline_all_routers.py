"""The sub-router pipeline knobs on floodsub/randomsub.

In the reference both sit BELOW the router: the async validation
pipeline (validation.go:65-83) and the per-peer outbound writer queues
(comm.go:139-170; floodsub's own drop at floodsub.go:91-98) serve every
router. Rounds 1-5 modeled them gossipsub-only at the API layer (the
engine was always router-agnostic — models/common.py); round 6 lifted
the api.Network raises. One behavior test per router per knob.
"""

from __future__ import annotations

import numpy as np
import pytest

pytest.importorskip("cryptography", reason="api layer needs the crypto dep")

from go_libp2p_pubsub_tpu import api  # noqa: E402
from go_libp2p_pubsub_tpu.trace.events import EV


def _mesh(router, n=6, **net_kw):
    net = api.Network(router=router, **net_kw)
    nodes = net.add_nodes(n)
    net.connect_all()
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    return net, nodes, subs


def _drain_counts(subs):
    out = 0
    for s in subs:
        while s.next() is not None:
            out += 1
    return out


# ---------------------------------------------------------------------------
# validation_delay_rounds


@pytest.mark.parametrize("router", ["floodsub", "randomsub"])
def test_validation_delay_defers_delivery(router):
    """With a V-round pipeline, receipts sit between arrival (markSeen)
    and verdict: nothing delivers in the first rounds, everything
    delivers once the pipeline drains — same totals as inline."""
    v = 2
    net, nodes, subs = _mesh(router, validation_delay_rounds=v)
    nodes[0].topics["t"].publish(b"slow")
    remote = subs[1:]  # the origin's own sub delivers locally at publish
    # publish lands in round 0; arrivals happen in round 1; the verdict
    # (and the DeliverMessage timing, incl. the CDF stamp) lands at 1+v
    net.run(2)
    assert _drain_counts(remote) == 0, "delivered before the pipeline drained"
    net.run(2 * (1 + v) + 2)
    assert _drain_counts(remote) == len(nodes) - 1

    # inline twin: same totals, faster
    net2, nodes2, subs2 = _mesh(router)
    nodes2[0].topics["t"].publish(b"fast")
    net2.run(2)
    early = _drain_counts(subs2[1:])
    assert early > 0  # connect_all: one hop reaches everyone inline
    net2.run(2 * (1 + v) + 2)
    assert early + _drain_counts(subs2[1:]) == len(nodes2) - 1


# ---------------------------------------------------------------------------
# queue_cap


@pytest.mark.parametrize("router", ["floodsub", "randomsub"])
def test_queue_cap_loses_traffic(router):
    """A 1-deep outbound budget under a 3-message burst genuinely loses
    traffic (the reference drops the RPC, gossip never retries): fewer
    deliveries than lossless, and the DROP_RPC counter accounts for it."""
    n = 6
    net, nodes, subs = _mesh(router, queue_cap=1, max_publishes_per_round=4)
    for i in range(3):
        nodes[0].topics["t"].publish(b"m%d" % i)
    net.run(10)
    capped = _drain_counts(subs[1:])  # remote deliveries only
    ev = np.asarray(net.state.events)
    assert ev[EV.DROP_RPC] > 0
    # arrival conservation with losses: received = new + duplicates
    assert (ev[EV.DELIVER_MESSAGE] + ev[EV.REJECT_MESSAGE]
            + ev[EV.DUPLICATE_MESSAGE] == ev[EV.RECV_RPC])

    net2, nodes2, subs2 = _mesh(router, max_publishes_per_round=4)
    for i in range(3):
        nodes2[0].topics["t"].publish(b"m%d" % i)
    net2.run(10)
    lossless = _drain_counts(subs2[1:])  # remote deliveries only
    assert lossless == 3 * (n - 1)
    assert capped < lossless
    assert np.asarray(net2.state.events)[EV.DROP_RPC] == 0


@pytest.mark.slow
def test_both_knobs_compose_on_floodsub():
    """Pipeline + backpressure together (the reference composes them the
    same way: the validation queue sits behind the reader, the writer
    queue in front of it)."""
    net, nodes, subs = _mesh("floodsub", validation_delay_rounds=1,
                             queue_cap=1, max_publishes_per_round=4)
    for i in range(2):
        nodes[0].topics["t"].publish(b"x%d" % i)
    net.run(12)
    delivered = _drain_counts(subs[1:])  # remote deliveries only
    ev = np.asarray(net.state.events)
    assert ev[EV.DROP_RPC] > 0
    assert 0 < delivered < 2 * (len(nodes) - 1)
