"""Checkpoint/resume: a restored run must continue exactly like an
uninterrupted one (same PRNG stream, same state trees)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, graph
from go_libp2p_pubsub_tpu.config import GossipSubParams, PeerScoreThresholds
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.state import Net, SimState


def _setup(n=32, seed=0):
    topo = graph.random_connect(n, d=6, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                score_enabled=False)
    st = GossipSubState.init(net, 32, cfg, seed=seed)
    step = make_gossipsub_step(cfg, net)
    return net, st, step


def _drive(step, st, rounds, publish_at=()):
    po = jnp.full((4,), -1, jnp.int32)
    pt = jnp.zeros((4,), jnp.int32)
    pv = jnp.zeros((4,), bool)
    for r in range(rounds):
        if r in publish_at:
            st = step(st, po.at[0].set(r % 8), pt, pv.at[0].set(True))
        else:
            st = step(st, po, pt, pv)
    return st


def _assert_tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_resume_equals_uninterrupted(tmp_path):
    net, st0, step = _setup()
    mid = _drive(step, st0, 5, publish_at=(0, 2))
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, mid)

    # restore into a fresh template and check equality BEFORE the direct
    # drive: the jitted step donates its input buffers, so driving `mid`
    # consumes it
    _, template, _ = _setup()
    resumed_mid = checkpoint.restore(path, template)
    _assert_tree_equal(mid, resumed_mid)

    direct = _drive(step, mid, 5, publish_at=(1,))
    resumed = _drive(step, resumed_mid, 5, publish_at=(1,))
    _assert_tree_equal(direct, resumed)


def test_simstate_roundtrip(tmp_path):
    st = SimState.init(8, 16, seed=7, k=4)
    path = str(tmp_path / "sim.npz")
    checkpoint.save(path, st)
    back = checkpoint.restore(path, SimState.init(8, 16, seed=0, k=4))
    _assert_tree_equal(st, back)


def test_restore_shape_mismatch_rejected(tmp_path):
    st = SimState.init(8, 16, seed=0, k=4)
    path = str(tmp_path / "sim.npz")
    checkpoint.save(path, st)
    with pytest.raises(ValueError):
        checkpoint.restore(path, SimState.init(16, 16, seed=0, k=4))


def test_restore_structure_mismatch_rejected(tmp_path):
    net, st, _ = _setup(n=16)
    path = str(tmp_path / "gs.npz")
    checkpoint.save(path, st)
    with pytest.raises(ValueError):
        checkpoint.restore(path, SimState.init(16, 32, seed=0, k=4))


def test_orbax_roundtrip(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    st = SimState.init(8, 16, seed=3, k=4)
    path = str(tmp_path / "orbax_ckpt")
    checkpoint.save_orbax(path, st)
    back = checkpoint.restore_orbax(path, SimState.init(8, 16, seed=0, k=4))
    _assert_tree_equal(st, back)


def test_restore_rejects_non_checkpoint_npz(tmp_path):
    path = str(tmp_path / "plain.npz")
    np.savez(path, a=np.zeros(3))
    with pytest.raises(ValueError):
        checkpoint.restore(path, SimState.init(4, 16, seed=0, k=4))


def test_orbax_restore_shape_mismatch_rejected(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    st = SimState.init(8, 16, seed=3, k=4)
    path = str(tmp_path / "orbax_bad")
    checkpoint.save_orbax(path, st)
    with pytest.raises(ValueError):
        checkpoint.restore_orbax(path, SimState.init(16, 16, seed=0, k=4))


def test_restore_mismatch_error_names_pytree_paths(tmp_path):
    """Template mismatches must name the offending pytree PATHS (not
    just flat leaf indexes) — shape mismatches list every bad leaf."""
    net, st, _ = _setup(n=16)
    path = str(tmp_path / "gs.npz")
    checkpoint.save(path, st)
    _, template, _ = _setup(n=8)
    with pytest.raises(ValueError) as ei:
        checkpoint.restore(path, template)
    msg = str(ei.value)
    # the delivery plane differs in N: its path must be spelled out
    assert "have" in msg or "mesh" in msg
    assert "leaf path" in msg
    assert ".core." in msg or ".dlv" in msg or "mesh" in msg


def test_restore_old_version_clear_error(tmp_path):
    """A pre-v6 checkpoint must fail with the version-history pointer
    (the chaos-plane format bump)."""
    st = SimState.init(8, 16, seed=0, k=4)
    path = str(tmp_path / "old.npz")
    checkpoint.save(path, st)
    import numpy as _np

    with _np.load(path) as data:
        stale = {k: data[k] for k in data.files}
    stale["__version__"] = _np.int64(5)
    _np.savez_compressed(path, **stale)
    with pytest.raises(ValueError, match="predates.*v6|v5 predates"):
        checkpoint.restore(path, SimState.init(8, 16, seed=0, k=4))


@pytest.mark.parametrize("coalesced", [True, False])
def test_phase_coalesced_roundtrip_resume_r8_mid_run(tmp_path, coalesced):
    """Checkpoint at a phase boundary MID-RUN of an r=8 stacked-path
    build (the round-7 coalesced wire path postdates the original
    checkpoint tests): restore must continue bit-exactly on both the
    coalesced and the legacy path."""
    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        make_gossipsub_phase_step,
    )

    n, r = 32, 8
    topo = graph.random_connect(n, d=6, seed=4)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                score_enabled=False,
                                wire_coalesced=coalesced)
    st0 = GossipSubState.init(net, 64, cfg, seed=4)
    pstep = make_gossipsub_phase_step(cfg, net, r)

    def drive(st, phases, seed_off):
        for p in range(phases):
            po = np.full((r, 4), -1, np.int32)
            po[p % r, 0] = (p + seed_off) % n
            st = pstep(st, jnp.asarray(po),
                       jnp.asarray(np.zeros((r, 4), np.int32)),
                       jnp.asarray(np.ones((r, 4), bool)),
                       do_heartbeat=True)
        return st

    mid = drive(st0, 2, 0)  # tick = 16: an r>1 mid-run phase boundary
    assert int(mid.core.tick) == 2 * r
    path = str(tmp_path / f"phase8_{coalesced}.npz")
    checkpoint.save(path, mid)
    template = GossipSubState.init(net, 64, cfg, seed=4)
    resumed_mid = checkpoint.restore(path, template)
    _assert_tree_equal(mid, resumed_mid)
    direct = drive(mid, 2, 5)
    resumed = drive(resumed_mid, 2, 5)
    _assert_tree_equal(direct, resumed)


def test_phase_engine_roundtrip_resume(tmp_path):
    """Checkpoint/resume at the flagship cadence: a phase-engine run
    restored from a checkpoint continues bit-exactly (the dup_trans /
    fanout / promise planes the phase step carries all survive the npz
    roundtrip)."""
    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        make_gossipsub_phase_step,
    )

    n, r = 32, 4
    topo = graph.random_connect(n, d=6, seed=2)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                score_enabled=False)
    st0 = GossipSubState.init(net, 32, cfg, seed=2)
    pstep = make_gossipsub_phase_step(cfg, net, r)

    def drive(st, phases, seed_off):
        for p in range(phases):
            po = np.full((r, 4), -1, np.int32)
            po[0, 0] = (p + seed_off) % n
            pt = np.zeros((r, 4), np.int32)
            pv = np.zeros((r, 4), bool)
            pv[0, 0] = True
            st = pstep(st, jnp.asarray(po), jnp.asarray(pt),
                       jnp.asarray(pv), do_heartbeat=True)
        return st

    mid = drive(st0, 3, 0)
    path = str(tmp_path / "phase_ckpt.npz")
    checkpoint.save(path, mid)
    template = GossipSubState.init(net, 32, cfg, seed=2)
    resumed_mid = checkpoint.restore(path, template)
    _assert_tree_equal(mid, resumed_mid)

    direct = drive(mid, 3, 7)
    resumed = drive(resumed_mid, 3, 7)
    _assert_tree_equal(direct, resumed)


# ---------------------------------------------------------------------------
# round 17: the integrity layer (CRC32 envelope + CheckpointCorrupt)


def test_envelope_carries_integrity_layer(tmp_path):
    st = SimState.init(8, 16, seed=3, k=4)
    path = str(tmp_path / "crc.npz")
    checkpoint.save(path, st)
    info = checkpoint.verify(path)
    assert info["checksummed"] is True
    assert info["n_leaves"] == len(jax.tree_util.tree_leaves(st))
    with np.load(path) as data:
        assert "__crc32__" in data.files
        assert "__header_len__" in data.files
        assert "__header_crc__" in data.files
        assert int(data["__header_len__"]) == len(data.files)


def test_truncated_checkpoint_raises_typed_error(tmp_path):
    st = SimState.init(8, 16, seed=3, k=4)
    path = str(tmp_path / "trunc.npz")
    checkpoint.save(path, st)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[: len(raw) // 2])
    with pytest.raises(checkpoint.CheckpointCorrupt):
        checkpoint.restore(path, SimState.init(8, 16, seed=0, k=4))
    with pytest.raises(checkpoint.CheckpointCorrupt):
        checkpoint.verify(path)


def test_bitflipped_checkpoint_raises_typed_error(tmp_path):
    st = SimState.init(8, 16, seed=3, k=4)
    path = str(tmp_path / "flip.npz")
    checkpoint.save(path, st)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(raw))
    with pytest.raises(checkpoint.CheckpointCorrupt):
        checkpoint.restore(path, SimState.init(8, 16, seed=0, k=4))


def test_leaf_corruption_named_by_section(tmp_path):
    """A VALID zip whose leaf bytes were rewritten under the committed
    CRC vector: only the round-17 per-leaf CRC can catch it, and the
    error must name the damaged leaf's pytree path."""
    from go_libp2p_pubsub_tpu.serve import corrupt_leaf_member

    st = SimState.init(8, 16, seed=3, k=4)
    path = str(tmp_path / "leaf.npz")
    checkpoint.save(path, st)
    corrupt_leaf_member(path, 2)
    with pytest.raises(checkpoint.CheckpointCorrupt) as ei:
        checkpoint.restore(path, SimState.init(8, 16, seed=0, k=4))
    assert "leaf 2" in str(ei.value)
    assert "CRC32 mismatch" in str(ei.value)


def test_pre_integrity_snapshot_loads_with_note(tmp_path, caplog):
    """Snapshots written before the integrity layer (no __crc32__) load
    backward-compatibly with a logged 'no checksum' note."""
    import logging

    st = SimState.init(8, 16, seed=3, k=4)
    leaves = jax.tree_util.tree_leaves(st)
    legacy = {"__version__": np.int64(6),
              "__n_leaves__": np.int64(len(leaves))}
    for i, leaf in enumerate(leaves):
        if checkpoint.is_prng_key(leaf):
            legacy[f"leaf_{i}"] = np.asarray(jax.random.key_data(leaf))
            legacy[f"leaf_{i}__is_key"] = np.bool_(True)
        else:
            legacy[f"leaf_{i}"] = np.asarray(leaf)
    path = str(tmp_path / "legacy.npz")
    np.savez_compressed(path, **legacy)
    with caplog.at_level(logging.INFO,
                         logger="go_libp2p_pubsub_tpu.checkpoint"):
        back = checkpoint.restore(path, SimState.init(8, 16, seed=0, k=4))
    _assert_tree_equal(st, back)
    assert any("no checksum" in r.message for r in caplog.records)
    assert checkpoint.verify(path)["checksummed"] is False


def test_template_mismatch_stays_plain_valueerror(tmp_path):
    """Corruption is CheckpointCorrupt; a WRONG TEMPLATE must stay the
    plain ValueError contract (the store's fallback must not swallow
    caller bugs)."""
    st = SimState.init(8, 16, seed=3, k=4)
    path = str(tmp_path / "tmpl.npz")
    checkpoint.save(path, st)
    with pytest.raises(ValueError) as ei:
        checkpoint.restore(path, SimState.init(12, 16, seed=0, k=4))
    assert not isinstance(ei.value, checkpoint.CheckpointCorrupt)


def test_uncompressed_save_roundtrips(tmp_path):
    st = SimState.init(8, 16, seed=5, k=4)
    path = str(tmp_path / "raw.npz")
    checkpoint.save(path, st, compress=False)
    assert checkpoint.verify(path)["checksummed"] is True
    back = checkpoint.restore(path, SimState.init(8, 16, seed=0, k=4))
    _assert_tree_equal(st, back)
