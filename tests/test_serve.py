"""Supervised service loop (serve/, docs/DESIGN.md §17): durability,
detection, recovery.

The contract under test: a supervised run is OBSERVATIONAL (bit-exact
vs a bare window) when healthy; a SIGKILL at any point — including
mid-checkpoint-write — resumes bit-exact; every health probe has a
seeded-negative that trips EXACTLY that probe and the rollback replay
localizes the injected dispatch; transient dispatch failures retry and
degrade without dropping rounds."""

import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, ensemble
from go_libp2p_pubsub_tpu.oracle import (
    HealthConfig,
    InvariantConfig,
    ScanInvariants,
    health_check,
    make_health_probe,
)
from go_libp2p_pubsub_tpu.serve import (
    CheckpointStore,
    FaultPlan,
    RetentionPolicy,
    ServiceConfig,
    ServiceHalted,
    Supervisor,
    TransientDispatchError,
    corrupt_leaf_member,
    flip_bit,
    state_digest,
    truncate_file,
)
from go_libp2p_pubsub_tpu.serve._child import build_cell
from go_libp2p_pubsub_tpu.state import SimState

N = 32
ROUNDS = 16
SEG = 4
SEED = 7
LOSS = 0.1

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cell():
    return build_cell(N, ROUNDS, SEED, LOSS)


def _svc(**kw):
    kw.setdefault("n_dispatches", ROUNDS)
    kw.setdefault("segment_len", SEG)
    kw.setdefault("report_name", None)
    kw.setdefault("backoff_base_s", 0.001)
    return ServiceConfig(**kw)


def _spec(cell):
    _step, _margs, _tmpl, net, cfg = cell
    return ScanInvariants(
        "gossipsub", net, cfg,
        InvariantConfig(check_every=SEG, delivery_window=16),
        batched=False)


def _gold_digest(cell):
    step, make_args, template_fn, _net, _cfg = cell
    run = ensemble.WindowRunner(step, ROUNDS).run(template_fn(), make_args)
    return state_digest(run.states)


# ---------------------------------------------------------------------------
# store: retention, manifest, fallback


def _tree(seed=0):
    return SimState.init(8, 16, seed=seed, k=4)


def test_store_retention_keep_last_and_keep_every(tmp_path):
    store = CheckpointStore(str(tmp_path / "s"),
                            RetentionPolicy(keep_last=2, keep_every=3))
    for i in range(7):
        store.save(_tree(i), tick=i * 10)
    ords = [e["ordinal"] for e in store.entries()]
    # last two (5, 6) + every 3rd (0, 3, 6)
    assert ords == [0, 3, 5, 6]
    on_disk = sorted(f for f in os.listdir(store.root)
                     if f.startswith("ckpt_"))
    assert len(on_disk) == 4  # pruned files really deleted
    st, entry = store.restore_latest(_tree())
    assert entry["ordinal"] == 6 and entry["tick"] == 60


def test_store_falls_back_past_damaged_snapshots(tmp_path):
    store = CheckpointStore(str(tmp_path / "s"),
                            RetentionPolicy(keep_last=4))
    for i in range(3):
        store.save(_tree(i), tick=i)
    truncate_file(os.path.join(store.root, store.entries()[-1]["file"]))
    flip_bit(os.path.join(store.root, store.entries()[-2]["file"]))
    st, entry = store.restore_latest(_tree())
    assert entry["ordinal"] == 0
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(st.key)),
        np.asarray(jax.random.key_data(_tree(0).key)))
    # the dropped entries are gone from the rewritten manifest
    store2 = CheckpointStore(store.root)
    assert [e["ordinal"] for e in store2.entries()] == [0]


def test_store_rebuilds_corrupt_manifest_from_files(tmp_path):
    store = CheckpointStore(str(tmp_path / "s"))
    store.save(_tree(1), tick=5)
    store.save(_tree(2), tick=9)
    with open(os.path.join(store.root, "MANIFEST.json"), "w") as f:
        f.write("{not json")
    store2 = CheckpointStore(store.root)
    assert [e["tick"] for e in store2.entries()] == [5, 9]
    st, entry = store2.restore_latest(_tree())
    assert entry["tick"] == 9


def test_store_sweeps_orphan_tmp_files(tmp_path):
    root = str(tmp_path / "s")
    os.makedirs(root)
    orphan = os.path.join(root, "ckpt_000009_t0000000001.npz.tmp.npz")
    with open(orphan, "wb") as f:
        f.write(b"partial write")
    CheckpointStore(root)
    assert not os.path.exists(orphan)


def test_retention_policy_validation():
    with pytest.raises(ValueError):
        RetentionPolicy(keep_last=0)
    with pytest.raises(ValueError):
        RetentionPolicy(keep_every=-1)


# ---------------------------------------------------------------------------
# probes


def test_probe_clean_state_passes(cell):
    _step, _margs, template_fn, _net, _cfg = cell
    st = template_fn()
    probe, names = make_health_probe(HealthConfig())
    ok = np.asarray(probe(st, st.core.events))
    assert names == ("finite-state", "events-monotone", "delivery-floor")
    assert ok.all()


def test_probe_negative_finite_state(cell):
    _step, _margs, template_fn, _net, _cfg = cell
    st = template_fn()
    st = st.replace(scores=st.scores.at[0, 0].set(jnp.nan))
    cfgp = HealthConfig()
    ok = np.asarray(health_check(st, st.core.events, cfgp))
    assert list(ok) == [False, True, True]  # EXACTLY finite-state


def test_probe_negative_events_monotone(cell):
    _step, _margs, template_fn, _net, _cfg = cell
    st = template_fn()
    prev = st.core.events.at[3].set(10)  # counter went backwards
    ok = np.asarray(health_check(st, prev, HealthConfig()))
    assert list(ok) == [True, False, False]  # monotone + floor(delta<0)


def test_probe_negative_delivery_floor(cell):
    _step, _margs, template_fn, _net, _cfg = cell
    st = template_fn()
    ok = np.asarray(health_check(st, st.core.events,
                                 HealthConfig(delivery_floor=10)))
    assert list(ok) == [True, True, False]  # EXACTLY delivery-floor


# ---------------------------------------------------------------------------
# supervisor: clean run, resume, recovery, retry, degradation


def test_supervised_clean_run_bitexact_vs_bare_window(cell, tmp_path):
    step, make_args, template_fn, _net, _cfg = cell
    gold = _gold_digest(cell)
    sup = Supervisor(step, make_args, template_fn, str(tmp_path), _svc(),
                     invariants=_spec(cell))
    rep = sup.run()
    assert state_digest(rep.states) == gold
    assert rep.segments == ROUNDS // SEG
    assert rep.recoveries == 0 and rep.retries == 0
    assert all(v == 1 for v in rep.window_compiles.values())
    assert rep.invariant_checks == ROUNDS // SEG
    hb = json.load(open(rep.heartbeat_path))
    assert hb["status"] == "done" and hb["dispatch"] == ROUNDS
    assert rep.fingerprint()["enabled"] is True


def test_overflow_horizon_startup_note(tmp_path):
    """The serve-side surfacing of the range audit's overflow-horizon
    contract (analysis/ranges.py, docs/DESIGN.md §23): the committed
    RANGE_AUDIT.json horizons become a one-line startup note comparing
    the planned run length against the tightest counter horizon. A
    missing or malformed artifact yields None — never blocks serving."""
    from go_libp2p_pubsub_tpu.serve.supervisor import overflow_horizon_note

    note = overflow_horizon_note(repo_root=_REPO)
    assert note is not None and "int32 event counter" in note
    # the audit's tightest horizon (DUPLICATE_MESSAGE under the flood
    # envelope) appears by name with its round count
    assert "DUPLICATE_MESSAGE" in note

    fits = overflow_horizon_note(total_rounds=1, repo_root=_REPO)
    assert "fits every horizon" in fits
    over = overflow_horizon_note(total_rounds=10**12, repo_root=_REPO)
    assert "EXCEEDS" in over and "counter_events" in over

    # fresh checkout (no artifact) and a corrupt artifact: silent None
    assert overflow_horizon_note(repo_root=str(tmp_path)) is None
    (tmp_path / "RANGE_AUDIT.json").write_text("{not json")
    assert overflow_horizon_note(repo_root=str(tmp_path)) is None


def test_supervised_run_logs_horizon_note(cell, tmp_path, caplog):
    import logging

    step, make_args, template_fn, _net, _cfg = cell
    sup = Supervisor(step, make_args, template_fn, str(tmp_path),
                     _svc(health=None))
    with caplog.at_level(logging.INFO,
                         logger="go_libp2p_pubsub_tpu.serve.supervisor"):
        sup.run()
    assert any("range audit horizons" in r.message for r in caplog.records)


def test_supervised_probes_off_still_bitexact(cell, tmp_path):
    step, make_args, template_fn, _net, _cfg = cell
    sup = Supervisor(step, make_args, template_fn, str(tmp_path),
                     _svc(health=None))
    rep = sup.run()
    assert state_digest(rep.states) == _gold_digest(cell)
    assert rep.probes == ()


def test_supervised_resume_midway_bitexact(cell, tmp_path):
    """Restartable anywhere: a run stopped at the halfway checkpoint and
    re-driven by a FRESH supervisor finishes bit-exact."""
    step, make_args, template_fn, _net, _cfg = cell
    root = str(tmp_path)
    half = Supervisor(step, make_args, template_fn, root,
                      _svc(n_dispatches=ROUNDS // 2))
    half.run()
    full = Supervisor(step, make_args, template_fn, root, _svc())
    rep = full.run()
    assert rep.resumed_from == ROUNDS // 2
    assert state_digest(rep.states) == _gold_digest(cell)


def test_supervised_report_written_incrementally(cell, tmp_path):
    step, make_args, template_fn, _net, _cfg = cell
    sup = Supervisor(step, make_args, template_fn, str(tmp_path),
                     _svc(report_name="service"))
    sup.run()
    rows = [json.loads(x) for x in open(tmp_path / "service.jsonl")]
    assert len(rows) == ROUNDS // SEG
    assert rows[-1]["dispatch"] == ROUNDS
    html = (tmp_path / "service.html").read_text()
    assert "supervised service loop" in html


def test_nan_injection_recovers_and_localizes(cell, tmp_path):
    step, make_args, template_fn, _net, _cfg = cell
    faults = FaultPlan(corrupt_segment=1, corrupt_dispatch=2,
                       corrupt_leaf="scores", corrupt_kind="nan")
    sup = Supervisor(step, make_args, template_fn, str(tmp_path), _svc(),
                     invariants=_spec(cell), faults=faults)
    rep = sup.run()
    assert rep.recoveries == 1
    assert len(rep.bundles) == 1
    b = rep.bundles[0]
    assert b["first_bad_dispatch"] == 1 * SEG + 2
    assert "finite-state" in b["window_probe_failures"]
    assert "finite-state" in b["replay_failures"]
    assert b["nan_census"]  # names the damaged leaf
    assert os.path.exists(os.path.join(b["path"], "bundle.json"))
    # transient corruption: the re-run segment is clean and the final
    # state is the uninterrupted control's
    assert state_digest(rep.states) == _gold_digest(cell)


def test_events_corruption_trips_monotone_probe(cell, tmp_path):
    step, make_args, template_fn, _net, _cfg = cell
    faults = FaultPlan(corrupt_segment=2, corrupt_dispatch=1,
                       corrupt_kind="events")
    sup = Supervisor(step, make_args, template_fn, str(tmp_path), _svc(),
                     faults=faults)
    rep = sup.run()
    assert rep.recoveries == 1
    b = rep.bundles[0]
    assert "events-monotone" in b["window_probe_failures"]
    assert b["first_bad_dispatch"] == 2 * SEG + 1
    assert state_digest(rep.states) == _gold_digest(cell)


def test_persistent_corruption_halts_with_bundle(cell, tmp_path):
    step, make_args, template_fn, _net, _cfg = cell
    faults = FaultPlan(corrupt_segment=1, corrupt_kind="nan",
                       corrupt_leaf="scores",
                       corrupt_max_fires=10 ** 9)
    sup = Supervisor(step, make_args, template_fn, str(tmp_path),
                     _svc(max_recoveries_per_segment=2), faults=faults)
    with pytest.raises(ServiceHalted) as ei:
        sup.run()
    assert ei.value.bundle is not None
    assert "finite-state" in str(ei.value)
    hb = json.load(open(sup.heartbeat_path))
    assert hb["status"] == "halted"


def test_delivery_floor_violation_halts(cell, tmp_path):
    step, make_args, template_fn, _net, _cfg = cell
    sup = Supervisor(
        step, make_args, template_fn, str(tmp_path),
        _svc(health=HealthConfig(delivery_floor=10 ** 9),
             max_recoveries_per_segment=1))
    with pytest.raises(ServiceHalted) as ei:
        sup.run()
    assert "delivery-floor" in str(ei.value)


def test_transient_dispatch_failures_retried(cell, tmp_path):
    step, make_args, template_fn, _net, _cfg = cell
    faults = FaultPlan(fail_dispatches={1: 2})
    sup = Supervisor(step, make_args, template_fn, str(tmp_path), _svc(),
                     faults=faults)
    rep = sup.run()
    assert rep.retries == 2
    assert rep.recoveries == 0
    assert state_digest(rep.states) == _gold_digest(cell)


def test_dispatch_failure_degrades_then_halts(cell, tmp_path):
    step, make_args, template_fn, _net, _cfg = cell
    faults = FaultPlan(fail_dispatches={0: 10 ** 6})
    sup = Supervisor(step, make_args, template_fn, str(tmp_path),
                     _svc(max_retries=1), faults=faults)
    with pytest.raises(ServiceHalted) as ei:
        sup.run()
    assert "degradation ladder is exhausted" in str(ei.value)
    # the ladder was walked: segment halved down to 1 dispatch
    assert [d for d in sup._degradations
            if d.startswith("shrink-segment")] == [
        "shrink-segment:2", "shrink-segment:1"]


def test_degradation_recovers_when_failures_stop(cell, tmp_path):
    """The ladder is for SURVIVING: failures that outlast the retry
    budget but eventually stop leave a degraded-but-complete run with
    every round accounted for."""
    step, make_args, template_fn, _net, _cfg = cell
    faults = FaultPlan(fail_dispatches={0: 3})
    sup = Supervisor(step, make_args, template_fn, str(tmp_path),
                     _svc(max_retries=1), faults=faults)
    rep = sup.run()
    assert rep.degradations == ["shrink-segment:2"]
    assert state_digest(rep.states) == _gold_digest(cell)


def test_config_validation():
    with pytest.raises(ValueError):
        ServiceConfig(n_dispatches=10, segment_len=4)
    with pytest.raises(ValueError):
        ServiceConfig(n_dispatches=8, segment_len=4,
                      checkpoint_every_segments=0)


def test_invariant_cadence_must_divide_segment(cell, tmp_path):
    step, make_args, template_fn, net, cfg = cell
    spec = ScanInvariants("gossipsub", net, cfg,
                          InvariantConfig(check_every=3), batched=False)
    with pytest.raises(ValueError, match="check_every"):
        Supervisor(step, make_args, template_fn, str(tmp_path), _svc(),
                   invariants=spec)


# ---------------------------------------------------------------------------
# the real thing: SIGKILL a child process, resume, compare digests


def _run_child(root, *extra, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SERVE_CHILD_CACHE=os.path.join(_REPO, ".jax_cache"))
    cmd = [sys.executable, "-m", "go_libp2p_pubsub_tpu.serve._child",
           "--root", str(root), "--n", str(N), "--rounds", str(ROUNDS),
           "--segment", str(SEG), "--probes", *extra]
    return subprocess.run(cmd, cwd=_REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


def test_sigkill_mid_checkpoint_write_resumes_bitexact(tmp_path):
    """The dirtiest crash window: SIGKILL while the checkpoint tmp file
    is half-written. The truncated tmp must not poison the store, and
    the resumed run must finish bit-exact vs an uninterrupted control."""
    ctrl = _run_child(tmp_path / "ctrl", "--fresh")
    assert ctrl.returncode == 0, ctrl.stderr[-800:]
    control = json.loads(open(tmp_path / "ctrl" / "FINAL.json").read())

    crashed = _run_child(tmp_path / "kill", "--fresh",
                         "--kill-segment", "1", "--kill-site", "mid-write")
    assert crashed.returncode in (-signal.SIGKILL, 128 + signal.SIGKILL), (
        crashed.returncode, crashed.stderr[-800:])
    resumed = _run_child(tmp_path / "kill")
    assert resumed.returncode == 0, resumed.stderr[-800:]
    final = json.loads(open(tmp_path / "kill" / "FINAL.json").read())
    assert final["resumed_from"] is not None
    assert final["digest"] == control["digest"]


@pytest.mark.slow
def test_sigkill_randomized_sites_resume_bitexact(tmp_path):
    """Seeded random crash points across every kill site: resume is
    bit-exact regardless of where the run died."""
    ctrl = _run_child(tmp_path / "ctrl", "--fresh")
    assert ctrl.returncode == 0, ctrl.stderr[-800:]
    control = json.loads(open(tmp_path / "ctrl" / "FINAL.json").read())
    rng = np.random.default_rng(99)
    for i, site in enumerate(("post-segment", "post-rename")):
        root = tmp_path / f"kill{i}"
        seg = int(rng.integers(0, ROUNDS // SEG))
        crashed = _run_child(root, "--fresh", "--kill-segment", str(seg),
                             "--kill-site", site)
        assert crashed.returncode in (-signal.SIGKILL,
                                      128 + signal.SIGKILL)
        resumed = _run_child(root)
        assert resumed.returncode == 0, resumed.stderr[-800:]
        final = json.loads(open(root / "FINAL.json").read())
        assert final["digest"] == control["digest"], (site, seg)


# ---------------------------------------------------------------------------
# faults: the file-damage helpers really produce typed corruption


def test_corrupt_helpers_raise_typed_errors(tmp_path):
    st = _tree(3)
    p1 = str(tmp_path / "a.npz")
    checkpoint.save(p1, st)
    truncate_file(p1)
    with pytest.raises(checkpoint.CheckpointCorrupt):
        checkpoint.verify(p1)
    p2 = str(tmp_path / "b.npz")
    checkpoint.save(p2, st)
    flip_bit(p2, seed=1)
    with pytest.raises(checkpoint.CheckpointCorrupt):
        checkpoint.verify(p2)
    p3 = str(tmp_path / "c.npz")
    checkpoint.save(p3, st)
    corrupt_leaf_member(p3, 0)
    with pytest.raises(checkpoint.CheckpointCorrupt, match="leaf_0"):
        checkpoint.verify(p3)


def test_fault_plan_validation_and_budget():
    with pytest.raises(ValueError, match="kill_site"):
        FaultPlan(kill_site="nope")
    plan = FaultPlan(fail_dispatches={2: 2})
    with pytest.raises(TransientDispatchError):
        plan.before_dispatch(2)
    with pytest.raises(TransientDispatchError):
        plan.before_dispatch(2)
    plan.before_dispatch(2)  # budget spent: no raise
    plan.before_dispatch(0)  # unscheduled segment: no raise


def test_replay_localizes_under_nonzero_delivery_floor(cell, tmp_path):
    """Review regression: the delivery floor is a PER-SEGMENT quantity —
    the per-dispatch replay must zero it, or it spuriously trips at the
    first replayed dispatch and mislocalizes. A NaN injected mid-segment
    under a satisfiable floor must still be named as finite-state at the
    injected dispatch."""
    step, make_args, template_fn, _net, _cfg = cell
    faults = FaultPlan(corrupt_segment=1, corrupt_dispatch=2,
                       corrupt_leaf="scores", corrupt_kind="nan")
    sup = Supervisor(step, make_args, template_fn, str(tmp_path),
                     _svc(health=HealthConfig(delivery_floor=1)),
                     faults=faults)
    rep = sup.run()
    b = rep.bundles[0]
    assert b["first_bad_dispatch"] == 1 * SEG + 2
    assert "finite-state" in b["replay_failures"]
    assert "delivery-floor" not in b["replay_failures"]
    assert state_digest(rep.states) == _gold_digest(cell)


def test_ladder_exhausted_halt_updates_heartbeat(cell, tmp_path):
    """Review regression: the retry/degradation halt path must leave a
    'halted' heartbeat, not a stale 'running' one."""
    step, make_args, template_fn, _net, _cfg = cell
    faults = FaultPlan(fail_dispatches={0: 10 ** 6})
    sup = Supervisor(step, make_args, template_fn, str(tmp_path),
                     _svc(max_retries=1), faults=faults)
    with pytest.raises(ServiceHalted):
        sup.run()
    assert json.load(open(sup.heartbeat_path))["status"] == "halted"


def test_store_manifest_never_references_deleted_files(tmp_path):
    """Review regression: pruned files are unlinked only AFTER the
    manifest commit — at every point the manifest on disk references
    only files that exist."""
    store = CheckpointStore(str(tmp_path / "s"),
                            RetentionPolicy(keep_last=1))
    seen = []

    def hook(stage, path):
        if stage != "manifest":
            return
        doc = json.load(open(path))
        for e in doc["entries"]:
            seen.append(os.path.exists(
                os.path.join(str(tmp_path / "s"), e["file"])))

    store.write_hook = hook
    for i in range(4):
        store.save(_tree(i), tick=i)
    assert seen and all(seen)


def test_supervised_observations_surfaced(cell, tmp_path):
    """Review regression: observe= results must reach the caller — the
    stacked per-dispatch pytree over every committed dispatch."""
    import jax.numpy as _jnp

    step, make_args, template_fn, _net, _cfg = cell
    sup = Supervisor(step, make_args, template_fn, str(tmp_path), _svc(),
                     observe=lambda st: _jnp.asarray(st.core.tick))
    rep = sup.run()
    ticks = np.asarray(rep.observations)
    assert ticks.shape == (ROUNDS,)
    assert list(ticks) == list(range(1, ROUNDS + 1))
    assert state_digest(rep.states) == _gold_digest(cell)


# ---------------------------------------------------------------------------
# segment-boundary EV drain: unbounded counter horizon


def test_ev_drain_totals_match_bare_run_and_zero_device(cell, tmp_path):
    """drain_event_counters at a SHRUNK horizon: with the drain on, the
    device i32 counters are zeroed at every committed boundary — so the
    worst value any counter ever holds is ONE segment's growth (here a
    4-dispatch segment standing in for the range audit's ~4k-round
    DUPLICATE_MESSAGE horizon) — while the host i64 totals finish equal
    to the counters a bare (undrained) run accumulates on device."""
    step, make_args, template_fn, _net, _cfg = cell
    run = ensemble.WindowRunner(step, ROUNDS).run(template_fn(), make_args)
    bare = np.asarray(run.states.core.events, np.int64)
    sup = Supervisor(step, make_args, template_fn, str(tmp_path),
                     _svc(drain_event_counters=True))
    rep = sup.run()
    assert rep.ev_totals is not None and rep.ev_totals.dtype == np.int64
    np.testing.assert_array_equal(rep.ev_totals, bare)
    # every boundary drained: the final device counters are zero, and a
    # drained run's non-counter state matches the bare run bit-exactly
    assert not np.asarray(rep.states.core.events).any()
    gold = state_digest(_with_events_test(run.states))
    assert state_digest(_with_events_test(rep.states)) == gold


def _with_events_test(st):
    from go_libp2p_pubsub_tpu.serve.supervisor import _with_events

    return _with_events(st, jnp.zeros_like(st.core.events))


def test_ev_drain_totals_survive_resume(cell, tmp_path):
    """The drained totals ride checkpoint meta: a run stopped halfway
    and re-driven by a FRESH supervisor loses no counts."""
    step, make_args, template_fn, _net, _cfg = cell
    run = ensemble.WindowRunner(step, ROUNDS).run(template_fn(), make_args)
    bare = np.asarray(run.states.core.events, np.int64)
    root = str(tmp_path)
    half = Supervisor(step, make_args, template_fn, root,
                      _svc(n_dispatches=ROUNDS // 2,
                           drain_event_counters=True))
    half.run()
    full = Supervisor(step, make_args, template_fn, root,
                      _svc(drain_event_counters=True))
    rep = full.run()
    assert rep.resumed_from == ROUNDS // 2
    np.testing.assert_array_equal(rep.ev_totals, bare)


def test_ev_drain_requires_per_segment_checkpoints():
    with pytest.raises(ValueError, match="drain_event_counters"):
        _svc(drain_event_counters=True, checkpoint_every_segments=2)
