"""Fanout (publish to unjoined topics) + protocol negotiation (floodsub
peers inside gossipsub) — gossipsub.go:981-1002,1517-1554 and
gossipsub_feat.go analogues."""

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net


def pub(o, t, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    pv = np.zeros(p, bool)
    po[0], pt[0], pv[0] = o, t, True
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def run(step, st, k):
    a = no_publish()
    for _ in range(k):
        st = step(st, *a)
    return st


def test_fanout_publish_to_unjoined_topic():
    # peer 0 subscribes only topic 1 but publishes to topic 0: fanout slot
    # is created and subscribers of topic 0 receive the message
    n = 40
    topo = graph.random_connect(n, 8, seed=3)
    mask = np.zeros((n, 2), bool)
    mask[:, 0] = True          # everyone on topic 0 ...
    mask[0, 0] = False         # ... except the publisher
    mask[0, 1] = True
    subs = graph.subscribe_mask(mask, max_slots=2)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build()
    st = GossipSubState.init(net, 32, cfg, seed=0)
    step = make_gossipsub_step(cfg, net)
    st = run(step, st, 10)
    st = step(st, *pub(0, 0))
    # fanout slot exists with ~D peers
    ftop = np.asarray(st.fanout_topic[0])
    assert 0 in ftop.tolist()
    slot = ftop.tolist().index(0)
    assert int(st.fanout_peers[0, slot].sum()) >= 1
    st = run(step, st, 12)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))[:, 0]
    subscribers = mask[:, 0]
    assert have[subscribers].mean() > 0.9, "fanout publish must reach topic"


def test_fanout_expires():
    n = 30
    topo = graph.random_connect(n, 8, seed=5)
    mask = np.zeros((n, 2), bool)
    mask[:, 0] = True
    mask[0, 0] = False
    mask[0, 1] = True
    subs = graph.subscribe_mask(mask, max_slots=2)
    net = Net.build(topo, subs)
    import dataclasses
    from go_libp2p_pubsub_tpu.config import GossipSubParams
    params = dataclasses.replace(GossipSubParams(), fanout_ttl=5.0)
    cfg = GossipSubConfig.build(params)
    st = GossipSubState.init(net, 32, cfg, seed=0)
    step = make_gossipsub_step(cfg, net)
    st = run(step, st, 5)
    st = step(st, *pub(0, 0))
    assert 0 in np.asarray(st.fanout_topic[0]).tolist()
    st = run(step, st, 10)  # > FanoutTTL with no further publishes
    assert 0 not in np.asarray(st.fanout_topic[0]).tolist(), "fanout must expire"


def test_floodsub_peers_interop():
    # a third of the peers only speak /floodsub/1.0.0: they are never
    # grafted into meshes but still receive and propagate everything
    n = 45
    topo = graph.random_connect(n, 10, seed=7)
    subs = graph.subscribe_all(n, 1)
    protocol = np.full((n,), 2, np.int8)
    flood_peers = np.arange(0, n, 3)
    protocol[flood_peers] = 0
    net = Net.build(topo, subs, protocol=protocol)
    cfg = GossipSubConfig.build()
    st = GossipSubState.init(net, 32, cfg, seed=0)
    step = make_gossipsub_step(cfg, net)
    st = run(step, st, 12)
    # no mesh edges toward floodsub peers
    mesh = np.asarray(st.mesh[:, 0, :])
    for j in range(n):
        for k in range(topo.max_degree):
            if topo.nbr_ok[j, k] and protocol[topo.nbr[j, k]] == 0:
                assert not mesh[j, k], "floodsub peers must not be grafted"
    # gossipsub publisher: floodsub peers still receive
    st = step(st, *pub(1, 0))
    st = run(step, st, 10)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))[:, 0]
    assert have.all(), "everyone incl. floodsub peers must receive"
    # floodsub publisher: message still floods the whole network
    st = step(st, *pub(int(flood_peers[0]), 0))
    st = run(step, st, 10)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))[:, 1]
    assert have.all(), "floodsub-originated message must reach everyone"
