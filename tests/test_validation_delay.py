"""Async validation latency (survey §7 hard-part (c)): receipts spend
`validation_delay_rounds` rounds between arrival (markSeen) and their
verdict; forwarding, Deliver/Reject traces, mcache insertion, score
attribution, and the propagation-CDF timestamp all move to the verdict —
the reference's post-validation publishMessage ordering
(validation.go:274-351 -> pubsub.go:1124-1128)."""

import dataclasses

import pytest

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import api, graph
from go_libp2p_pubsub_tpu.config import GossipSubParams
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.trace.events import EV


def build(v, n=24, d=3, msg_slots=16, flood=False, dynamic_peers=False):
    topo = graph.ring_lattice(n, d=d)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    params = dataclasses.replace(GossipSubParams(), flood_publish=flood)
    cfg = GossipSubConfig.build(params, validation_delay_rounds=v)
    st = GossipSubState.init(net, msg_slots, cfg, seed=0)
    step = make_gossipsub_step(cfg, net, dynamic_peers=dynamic_peers)
    return net, cfg, st, step


def pub(o, t=0, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    pv = np.zeros(p, bool)
    po[0], pt[0], pv[0] = o, t, True
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def pub_invalid(o, t=0, p=4):
    po, pt, pv = pub(o, t, p)
    return po, pt, pv.at[0].set(False)


def test_hop_latency_is_one_plus_delay():
    """On a ring with flood-publish off, hop h's first_round must be
    publish_round + h*(1+v): one transmission round plus v validation
    rounds per hop."""
    for v in (0, 2):
        net, cfg, st, step = build(v, n=20, d=1)  # pure ring, degree 2
        # form the mesh first
        for _ in range(5):
            st = step(st, *no_publish())
        t0 = int(st.core.tick)
        st = step(st, *pub(0))
        for _ in range(3 * (1 + v) + 1):
            st = step(st, *no_publish())
        fr = np.asarray(st.core.dlv.first_round)[:, 0]
        # origin stamped at publish
        assert fr[0] == t0
        for h in (1, 2, 3):
            want = t0 + h * (1 + v) + v * 0  # publish interned at end of t0
            # neighbors at distance h (ring, degree 2)
            assert fr[h] == t0 + h * (1 + v), (v, h, fr[:6].tolist())
            assert fr[20 - h] == t0 + h * (1 + v), (v, h)


def test_invalid_messages_rejected_at_verdict_and_not_forwarded():
    v = 2
    net, cfg, st, step = build(v, n=12, d=1)
    for _ in range(5):
        st = step(st, *no_publish())
    st = step(st, *pub_invalid(0))
    # arrival at neighbors after 1 round; verdict v rounds later
    st = step(st, *no_publish())
    ev_before = int(np.asarray(st.core.events)[EV.REJECT_MESSAGE])
    for _ in range(v):
        st = step(st, *no_publish())
    ev_after = int(np.asarray(st.core.events)[EV.REJECT_MESSAGE])
    assert ev_after == ev_before + 2  # the two ring neighbors rejected it
    for _ in range(6):
        st = step(st, *no_publish())
    # never propagated beyond one hop
    have = np.asarray(st.core.dlv.have)[:, 0] & 1
    assert have[0] and have[1] and have[11]
    assert not have[2:11].any()
    # and their seen-cache still dedups re-sends: first_round stays -1
    fr = np.asarray(st.core.dlv.first_round)[:, 0]
    assert (fr[2:11] == -1).all()


def test_delayed_deliveries_catch_up_with_ample_slots():
    """With enough message slots that recycling never clips an in-flight
    message, total deliveries must match the inline-validation run once
    the pipeline drains (the delay shifts timing, not outcomes)."""
    v = 2
    net, cfg0, st0, step0 = build(0, n=24, d=3, msg_slots=64)
    _, cfgd, std, stepd = build(v, n=24, d=3, msg_slots=64)
    for r in range(6):
        st0 = step0(st0, *pub((5 * r) % 24))
        std = stepd(std, *pub((5 * r) % 24))
    # drain: ring diameter ~4 hops, worst hop latency (1+v)
    for _ in range(8 * (1 + v)):
        st0 = step0(st0, *no_publish())
        std = stepd(std, *no_publish())
    ev0 = np.asarray(st0.core.events)
    evd = np.asarray(std.core.events)
    assert evd[EV.DELIVER_MESSAGE] == ev0[EV.DELIVER_MESSAGE]
    # every peer got all 6 messages in both runs
    fr = np.asarray(std.core.dlv.first_round)
    assert (np.sort(np.unique(np.nonzero(fr >= 0)[1])).size) == 6


@pytest.mark.slow
def test_api_network_with_validation_delay():
    net = api.Network(validation_delay_rounds=2)
    nodes = net.add_nodes(14)
    net.dense_connect(d=5, seed=2)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    nodes[0].topics["t"].publish(b"slow")
    net.run(3)  # one hop + partial validation: most should NOT have it yet
    early = sum(1 for s in subs if s.next() is not None)
    net.run(12)
    late = sum(1 for s in subs if s.next() is not None)
    assert early + late == 14
    assert late > 0  # some deliveries arrived only after validation drain


def test_api_accepts_delay_on_all_routers():
    """Round 6 lifted the gossipsub-only restriction: the validation
    pipeline sits below the router in the reference (validation.go:65-83),
    so floodsub/randomsub accept the knob too. Behavior coverage lives in
    tests/test_pipeline_all_routers.py."""
    for router in ("floodsub", "randomsub"):
        net = api.Network(router=router, validation_delay_rounds=1)
        assert net.validation_delay_rounds == 1


def test_p3_mesh_credit_survives_pipeline():
    """meshMessageDeliveries must accrue identically whether validation is
    inline or pipelined (score.go:695-719 credits at DeliverMessage,
    including pendency duplicates via drec.peers)."""
    from go_libp2p_pubsub_tpu.config import (
        PeerScoreParams,
        PeerScoreThresholds,
        TopicScoreParams,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import GossipSubConfig

    def build_scored(v, n=24):
        topo = graph.ring_lattice(n, d=3)
        subs = graph.subscribe_all(n, 1)
        net = Net.build(topo, subs)
        # activation beyond the test horizon: the P3 deficit penalty never
        # fires (a quiet formation phase would otherwise prune the whole
        # mesh), while mmd accrual — what this test measures — is
        # activation-independent
        # near-1 decays so the counters measure total accrual rather than
        # the decay state at the sampling instant (the delayed run drains
        # for 3x as many ticks)
        tp = TopicScoreParams(
            mesh_message_deliveries_weight=-1.0,
            mesh_message_deliveries_threshold=4.0,
            mesh_message_deliveries_activation=120.0,
            mesh_message_deliveries_window=1.0,
            mesh_message_deliveries_decay=0.9999,
            first_message_deliveries_decay=0.9999,
        )
        sp = PeerScoreParams(
            topics={0: tp},
            skip_app_specific=True,
            behaviour_penalty_weight=-1.0,
            behaviour_penalty_threshold=1.0,
            behaviour_penalty_decay=0.9,
        )
        cfg = GossipSubConfig.build(
            GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
            validation_delay_rounds=v,
        )
        st = GossipSubState.init(net, 64, cfg, score_params=sp, seed=0)
        step = make_gossipsub_step(cfg, net, score_params=sp)
        return st, step

    totals = {}
    for v in (0, 2):
        st, step = build_scored(v)
        for _ in range(6):
            st = step(st, *no_publish())  # mesh formation
        for r in range(8):
            st = step(st, *pub((3 * r) % 24))
        for _ in range(10 * (1 + v)):
            st = step(st, *no_publish())
        totals[v] = float(np.asarray(st.score.mmd).sum())
    assert totals[0] > 0
    # pipelined validation must not lose mesh-delivery credit; mesh
    # composition is stochastic per-config, so compare with slack
    assert totals[2] >= 0.7 * totals[0], totals


def test_traced_run_under_delay(tmp_path):
    """The trace drain reconstructs DeliverMessage at the verdict round
    (first_round stamp + first-arrival edge), so a traced run under the
    async pipeline must produce a consistent event stream: one Deliver per
    (peer, msg) pair, senders resolvable, publish count exact."""
    from go_libp2p_pubsub_tpu.pb import trace_pb2
    from go_libp2p_pubsub_tpu.trace import sinks

    path = str(tmp_path / "delay.json")
    net = api.Network(validation_delay_rounds=2,
                      trace_sinks=[sinks.JSONTracer(path)])
    nodes = net.add_nodes(8)
    net.connect_all()
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    nodes[0].topics["t"].publish(b"one")
    net.run(10)
    net.stop()
    evs = list(sinks.read_json_trace(path))
    pubs = [e for e in evs if e.type == trace_pb2.TraceEvent.PUBLISH_MESSAGE]
    dels = [e for e in evs if e.type == trace_pb2.TraceEvent.DELIVER_MESSAGE]
    assert len(pubs) == 1
    # every non-origin subscriber delivers exactly once, after validation
    assert len(dels) == 7
    assert all(sum(1 for _ in s) == 1 for s in subs)


@pytest.mark.slow
def test_churn_clears_pending_pipeline():
    """A peer that dies mid-validation loses its pending receipts with the
    rest of its soft state (handleDeadPeers pubsub.go:648-689): after
    restart it re-receives and re-validates from scratch."""
    v = 3
    net, cfg, st, step = build(v, n=12, d=2, msg_slots=32,
                               dynamic_peers=True)
    up = np.ones(12, bool)

    for _ in range(5):
        st = step(st, *no_publish(), jnp.asarray(up))
    st = step(st, *pub(0), jnp.asarray(up))
    # one hop: direct neighbors (incl. 1 and 11) receive and enter the
    # pipeline
    st = step(st, *no_publish(), jnp.asarray(up))
    pend = np.asarray(st.core.dlv.pending)
    assert pend[1].any() and pend[11].any()
    # peer 1 dies before its verdict completes
    up[1] = False
    st = step(st, *no_publish(), jnp.asarray(up))
    assert not np.asarray(st.core.dlv.pending)[1].any()
    assert not np.asarray(st.core.dlv.have)[1].any()
    # it returns with fresh soft state and re-validates from scratch: its
    # delivery must land a full pipeline (>= 1+v rounds) after the restart
    up[1] = True
    restart_tick = int(st.core.tick)
    for _ in range(3 + v + 2):
        st = step(st, *no_publish(), jnp.asarray(up))
    fr = int(np.asarray(st.core.dlv.first_round)[1, 0])
    assert fr >= restart_tick + 1 + v, (fr, restart_tick)
