"""WithMaxMessageSize (pubsub.go:480-485) + reader frame caps.

Reference semantics being pinned:
  * a published message larger than maxMessageSize delivers locally and
    enters mcache (mcache.Put precedes sendRPC in Publish,
    gossipsub.go:946), so it IS IHAVE-advertised — but every transmit of
    it (mesh push and IWANT responses alike) dies at the wire, the
    fragmentRPC single-message drop (gossipsub.go:1126-1140,
    fragmentRPC :1180-1187);
  * inbound delimited readers are bounded at maxMessageSize
    (comm.go:62,126) so a hostile peer can't demand an unbounded
    allocation with a huge length prefix.
"""

import io

import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import api, graph
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.pb import rpc_pb2
from go_libp2p_pubsub_tpu.state import (
    VERDICT_ACCEPT,
    VERDICT_WIRE_BLOCK,
    Net,
)
from go_libp2p_pubsub_tpu.wire import framing

from test_gossipsub import run


# ---------------------------------------------------------------------------
# API surface


def _net(router="gossipsub", **kw):
    net = api.Network(router=router, max_message_size=256, **kw)
    nodes = net.add_nodes(12)
    net.dense_connect(d=5, seed=2)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    return net, nodes, subs


@pytest.mark.parametrize("router", ["gossipsub", "floodsub"])
def test_oversized_publish_is_local_only(router):
    net, nodes, subs = _net(router)
    nodes[3].topics["t"].publish(b"x" * 1024)   # >> 256B limit
    net.run(8)
    got = [s.next() is not None for s in subs]
    assert got[3], "the origin's own subscription still delivers"
    assert sum(got) == 1, f"oversized message must not propagate: {got}"
    assert net.oversized_publishes == 1

    nodes[3].topics["t"].publish(b"small")      # control: under the limit
    net.run(8)
    got = [s.next() is not None for s in subs]
    assert all(got), "normal messages keep flowing"


def test_no_limit_when_disabled():
    net = api.Network(max_message_size=None)
    nodes = net.add_nodes(8)
    net.dense_connect(d=4, seed=5)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    nodes[0].topics["t"].publish(b"y" * 4096)
    net.run(8)
    assert all(s.next() is not None for s in subs)
    assert net.oversized_publishes == 0


# ---------------------------------------------------------------------------
# engine: mcache/IHAVE presence without deliverability


def test_blocked_message_is_advertised_but_unfetchable():
    """A meshless leech sees the IHAVE for a wire-blocked message and asks
    for it, but the IWANT response dies at the wire — the exact
    advertised-but-undeliverable wrinkle of the reference's size cap."""
    topo = graph.random_connect(30, 6, seed=11)
    subs = graph.subscribe_all(30, 1)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build()
    st = GossipSubState.init(net, 32, cfg, seed=11, wire_block=True)
    step = make_gossipsub_step(cfg, net)

    FAR = 2**30
    leech = 0
    bp = np.zeros(st.backoff_present.shape, bool)
    be = np.zeros(st.backoff_expire.shape, np.int32)
    bp[leech, :, :] = True
    be[leech, :, :] = FAR
    for k in range(topo.max_degree):
        if topo.nbr_ok[leech, k]:
            j, r = topo.nbr[leech, k], topo.rev[leech, k]
            bp[j, :, r] = True
            be[j, :, r] = FAR
    st = st.replace(
        backoff_present=jnp.asarray(bp), backoff_expire=jnp.asarray(be)
    )
    st = run(step, st, 10)
    assert int(st.mesh[leech].sum()) == 0

    # blocked publish: VERDICT_ACCEPT | VERDICT_WIRE_BLOCK
    po = jnp.asarray(np.array([7, -1, -1, -1], np.int32))
    pt = jnp.asarray(np.zeros(4, np.int32))
    pv = jnp.asarray(
        np.array([VERDICT_ACCEPT | VERDICT_WIRE_BLOCK, 0, 0, 0], np.int8)
    )
    st = step(st, po, pt, pv)
    slot = 0  # first allocation of a fresh table
    asked_any = False
    for _ in range(12):
        st = step(st, *no_publish())
        asked = np.asarray(
            bitset.unpack(st.iwant_out, 32)
        )  # [N,K,M] requests I sent
        asked_any = asked_any or bool(asked[leech, :, slot].any())
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))
    assert asked_any, "leech never even asked — IHAVE advertisement missing"
    assert have[:, slot].sum() == 1, "only the origin may hold a blocked msg"

    # control: an unblocked publish through the identical machinery arrives
    st = step(st, po, pt, jnp.asarray(np.array([0, 0, 0, 0], np.int8)))
    st = run(step, st, 12)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))
    assert have[leech, 1], "gossip pull must deliver the unblocked control"


# ---------------------------------------------------------------------------
# wire: bounded readers


def test_reader_frame_cap():
    rpc = rpc_pb2.RPC()
    m = rpc.publish.add()
    m.data = b"z" * 2048
    buf = io.BytesIO()
    framing.write_delimited(buf, rpc)

    buf.seek(0)
    with pytest.raises(framing.FrameTooLargeError):
        framing.read_delimited(buf, rpc_pb2.RPC, max_size=512)
    buf.seek(0)
    assert framing.read_delimited(buf, rpc_pb2.RPC, max_size=1 << 20) == rpc
    buf.seek(0)
    assert framing.read_rpc(buf) == rpc  # default 1 MiB reference cap

    # a hostile length prefix alone (no payload behind it) must be refused
    # before any allocation is attempted
    evil = io.BytesIO(framing.encode_uvarint(1 << 40))
    with pytest.raises(framing.FrameTooLargeError):
        framing.read_rpc(evil)


def test_reader_cap_threads_through_iterator():
    buf = io.BytesIO()
    small, big = rpc_pb2.RPC(), rpc_pb2.RPC()
    small.publish.add().data = b"a"
    big.publish.add().data = b"b" * 4096
    framing.write_delimited(buf, small)
    framing.write_delimited(buf, big)
    buf.seek(0)
    it = framing.read_delimited_messages(buf, rpc_pb2.RPC, max_size=1024)
    assert next(it) == small
    with pytest.raises(framing.FrameTooLargeError):
        next(it)


# ---------------------------------------------------------------------------
# WithMessageAuthor (pubsub.go:372-383)


def test_message_author_trace_ids(tmp_path):
    """Traced messageIDs follow the authored identity (the trace's
    PUBLISH/DELIVER ids must match the wire message's id, trace.go)."""
    from go_libp2p_pubsub_tpu.trace import sinks

    stable = api.Identity.generate(31337)
    path = str(tmp_path / "t.json")
    net = api.Network(trace_sinks=[sinks.JSONTracer(path)])
    nodes = net.add_nodes(6)
    nodes[1].author = stable
    net.dense_connect(d=3, seed=9)
    for nd in nodes:
        nd.join("t")
    net.start()
    mid = nodes[1].topics["t"].publish(b"authored")
    net.run(6)
    net._session.close(None)
    evs = list(sinks.read_json_trace(path))
    pubs = [e for e in evs if e.type == e.PUBLISH_MESSAGE]
    assert len(pubs) == 1
    assert pubs[0].publishMessage.messageID == mid  # DefaultMsgIdFn over from=author
    assert mid.startswith(stable.peer_id)
    # event peerIDs are the nodes' real identities, not synthetic ids
    assert pubs[0].peerID == nodes[1].identity.peer_id


def test_message_author_override():
    stable = api.Identity.generate(4242)
    net = api.Network()
    nodes = net.add_nodes(8)
    # node 0 publishes under a stable logical identity
    nodes[0].author = stable
    net.dense_connect(d=4, seed=7)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    nodes[0].topics["t"].publish(b"authored")
    net.run(8)
    for s in subs:
        msg = s.next()
        assert msg is not None
        assert getattr(msg, "from") == stable.peer_id
        # the signature verifies against the author identity (sign.go:49-107:
        # the key must be extractable from / match the `from` id)
        from go_libp2p_pubsub_tpu.sign import verify_message

        verify_message(msg)
