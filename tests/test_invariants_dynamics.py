"""Seeded-violation negatives for the round-22 dynamic-overlay
invariants (oracle/invariants.py; topo/dynamics.py; docs/DESIGN.md §22).

Same contract as tests/test_invariants.py: a lived-in DYNAMIC state
(the gossipsub step built with ``dynamic_topo=True`` — the mutable
``.core.topo`` plane rides the state tree and the checker rebinds the
net through ``Net.with_overlay``) passes every property clean, and each
overlay property is tripped by its own one-leaf corruption with the
EXACT expected failure set:

  * "edge-involution-wf" — a present slot whose ``edge_perm`` stops
    being partner-consistent (the involution contract every masked
    gather assumes, which mutation batches must preserve), and the
    epoch plane going negative;
  * "mesh-in-topology" (mutation-aware) — a schedule-driven node kill
    landing without the engine's same-round mesh cleanup; the same
    violation is SUSPENDED under ``DUE_MUT_GRACE`` (the re-peering
    transient window ``MutationSchedule.due_fn`` emits around mutation
    ticks) and trips again outside it.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import GossipSubParams, PeerScoreThresholds
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.oracle import invariants as inv
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.topo import dynamics

N = 48
M = 64
ROUNDS = 24
W = 12
PAD_B = 4            # static mutation-batch width of the no-op rows

QUIET = inv.due_vector(quiet=(0, ROUNDS))


def _params():
    return GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1,
                           history_length=6, history_gossip=4)


def _score_params():
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params

    return bench_score_params("default", 1)[1]


def _pad_writes():
    """One all-padding mutation batch: every row's slot is PAD_SLOT, so
    the scatter drops all of them (the mutation-off dispatch shape)."""
    w = np.zeros((PAD_B, 4), np.int32)
    w[:, 0] = dynamics.PAD_SLOT
    return jnp.asarray(w)


@pytest.fixture(scope="module")
def lived_in():
    """(topo, net, cfg, state) after ROUNDS dynamic dispatches (all-pad
    write batches — the overlay plane rides the carry, untouched): mesh
    formed, messages delivered. The checker never donates, so tests may
    read and .at[].set-copy this tree freely."""
    topo = graph.random_connect(N, d=4, seed=0)
    subs = graph.subscribe_all(N, 1)
    net = Net.build(topo, subs, dynamic=True)
    sp = _score_params()
    cfg = GossipSubConfig.build(_params(), PeerScoreThresholds(),
                                score_enabled=True)
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0,
                             dynamic_topo=True)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               dynamic_peers=True, dynamic_topo=True)
    rng = np.random.default_rng(0)
    up = jnp.ones((N,), bool)
    writes = _pad_writes()
    for t in range(ROUNDS):
        po = np.full((4,), -1, np.int32)
        if 2 <= t < 5:
            po[:] = rng.integers(0, N, size=4)
        st = step(st, jnp.asarray(po), jnp.zeros((4,), jnp.int32),
                  jnp.ones((4,), bool), up, writes)
    return topo, net, cfg, st


def _check(net, st, cfg, due=None):
    names = inv.invariant_names("gossipsub")
    ok = np.asarray(inv.check_state(
        "gossipsub", net, st, cfg,
        inv.InvariantConfig(delivery_window=W), due=due))
    return dict(zip(names, ok.tolist()))


def _mesh_edge(st):
    idx = np.argwhere(np.asarray(st.mesh))
    assert idx.size, "lived-in dynamic state has an empty mesh"
    return tuple(int(v) for v in idx[0])


def test_clean_dynamic_passes_all(lived_in):
    """Positive half: the dynamic build's state (overlay plane and all)
    passes every gossipsub property, delivery clause non-vacuous."""
    topo, net, cfg, st = lived_in
    res = _check(net, st, cfg, due=QUIET)
    assert all(res.values()), {k: v for k, v in res.items() if not v}
    births = np.asarray(st.core.msgs.birth)
    assert ((births >= 0) & (births + W <= ROUNDS)).any()


def test_edge_involution_violation_trips(lived_in):
    """A present slot whose edge_perm self-points (instead of aiming at
    its partner slot) trips exactly "edge-involution-wf" through the
    overlay-rebound net."""
    topo, net, cfg, st = lived_in
    tp = st.core.topo
    k_dim = tp.nbr.shape[1]
    i, k = [int(v) for v in np.argwhere(np.asarray(tp.nbr_ok))[0]]
    tp2 = tp.replace(edge_perm=tp.edge_perm.at[i, k].set(i * k_dim + k))
    st2 = st.replace(core=st.core.replace(topo=tp2))
    res = _check(net, st2, cfg)
    failed = {k_ for k_, v in res.items() if not v}
    assert failed == {"edge-involution-wf"}, sorted(failed)


def test_negative_epoch_trips_involution(lived_in):
    """The epoch plane is a monotone mutation counter; a negative entry
    (a torn or miswritten scatter) trips exactly "edge-involution-wf"."""
    topo, net, cfg, st = lived_in
    tp = st.core.topo
    tp2 = tp.replace(epoch=tp.epoch.at[0, 0].set(-1))
    st2 = st.replace(core=st.core.replace(topo=tp2))
    res = _check(net, st2, cfg)
    failed = {k for k, v in res.items() if not v}
    assert failed == {"edge-involution-wf"}, sorted(failed)


def test_mutation_kill_trips_mesh_in_topology(lived_in):
    """Mutation-aware "mesh-in-topology": a schedule kill takes a mesh
    neighbor DOWN without the engine's same-round cleanup — the checker
    trips exactly that property outside the grace window and suspends
    it inside DUE_MUT_GRACE (the re-peering transient the schedule's
    due_fn emits around mutation ticks)."""
    topo, net, cfg, st = lived_in
    i, s, k = _mesh_edge(st)
    j = int(np.asarray(st.core.topo.nbr)[i, k])
    sched = dynamics.MutationSchedule(topo.nbr, topo.nbr_ok, topo.rev,
                                      n_dispatches=1)
    sched.kill(0, j)
    _, up_rows = sched.build()
    st2 = st.replace(up=jnp.asarray(up_rows[0]))
    res = _check(net, st2, cfg)
    failed = {k_ for k_, v in res.items() if not v}
    assert failed == {"mesh-in-topology"}, sorted(failed)
    graced = _check(net, st2, cfg, due=inv.due_vector(mut_grace=True))
    assert graced["mesh-in-topology"]


def test_first_edge_wf_graced_under_mutation(lived_in):
    """The mutation-aware grace also scopes "first-edge-wf": the same
    double-attribution corruption that trips it outside the window
    (tests/test_invariants.py) is suspended inside DUE_MUT_GRACE."""
    topo, net, cfg, st = lived_in
    dlv = st.core.dlv
    slot = int(np.argwhere(np.asarray(st.core.msgs.valid))[0][0])
    w, b = slot // 32, np.uint32(1) << np.uint32(slot % 32)
    have = dlv.have.at[0, w].set(dlv.have[0, w] | b)
    fe = dlv.fe_words
    fe = fe.at[0, 0, w].set(fe[0, 0, w] | b)
    fe = fe.at[0, 1, w].set(fe[0, 1, w] | b)
    st2 = st.replace(core=st.core.replace(
        dlv=dlv.replace(have=have, fe_words=fe)))
    res = _check(net, st2, cfg)
    failed = {k for k, v in res.items() if not v}
    assert failed == {"first-edge-wf"}, sorted(failed)
    graced = _check(net, st2, cfg, due=inv.due_vector(mut_grace=True))
    assert graced["first-edge-wf"]
