"""Static device-cost auditor tests (docs/DESIGN.md §19,
analysis/costmodel.py): the jaxpr interpreter's accounting rules on
tiny known programs, every hard contract TRIPPED by a doctored jaxpr
(negative), the TallyCacheHit footgun fix, the byte-identity gates'
named-divergence satellite, and the roofline term's disarmed-by-default
contract."""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import pytest

from go_libp2p_pubsub_tpu.analysis import costmodel as cm
from go_libp2p_pubsub_tpu.ops import edges
from go_libp2p_pubsub_tpu.perf import artifacts, projection

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# interpreter accounting rules on known programs


def _cost(fn, *args):
    return cm.cost_closed(jax.make_jaxpr(fn)(*args))


def test_dot_general_flops():
    a = jnp.ones((8, 16), jnp.float32)
    b = jnp.ones((16, 4), jnp.float32)
    c = _cost(lambda x: x @ b, a)
    # 2 * out.size * K = 2 * (8*4) * 16
    assert c["flops"] == 2 * 8 * 4 * 16


def test_elementwise_and_reduce_flops():
    x = jnp.ones((32,), jnp.float32)
    c = _cost(lambda v: jnp.sum(v * v), x)
    # one mul (32) + one reduce_sum charging its input (32)
    assert c["flops"] == 64


def test_scan_multiplies_body():
    x = jnp.ones((4,), jnp.float32)

    def f(v):
        def body(carry, _):
            return carry * v, None

        out, _ = jax.lax.scan(body, v, None, length=10)
        return out

    once = _cost(lambda v: v * v, x)["flops"]
    scanned = _cost(f, x)["flops"]
    assert scanned == 10 * once


def test_gather_scatter_bytes():
    x = jnp.arange(64, dtype=jnp.int32)
    idx = jnp.array([3, 5], jnp.int32)
    c = _cost(lambda v: v[idx], x)
    assert c["gather_bytes"] == 2 * 4
    c2 = _cost(lambda v: v.at[idx].add(1), x)
    assert c2["scatter_bytes"] == 2 * 4


def test_rng_bits_counted_and_key_ops_free():
    key = jax.random.key(0)
    c = _cost(lambda k: jax.random.bits(
        jax.random.fold_in(k, 1), (16,), jnp.uint32), key)
    assert c["rng_bits"] == 16 * 32


def test_shape_ops_are_flop_free():
    x = jnp.ones((8, 8), jnp.float32)
    c = _cost(lambda v: jnp.broadcast_to(v.reshape(64)[None], (2, 64)), x)
    assert c["flops"] == 0
    assert c["hbm_bytes"] > 0  # traffic still priced (unfused bound)


def test_cond_charges_max_branch():
    x = jnp.ones((16,), jnp.float32)

    def f(v):
        return jax.lax.cond(v[0] > 0,
                            lambda u: u * u * u,  # 2 muls
                            lambda u: u * 2.0,    # 1 mul
                            v)

    c = _cost(f, x)
    assert c["flops"] >= 32  # the expensive branch (2 * 16)


# ---------------------------------------------------------------------------
# halo accounting + the TallyCacheHit footgun (round-19 satellite)


def _seam_fn(x):
    # one real ops/edges seam: a [N, K] edge involution
    n, k = x.shape
    perm = jnp.arange(n * k, dtype=jnp.int32).reshape(n, k)
    return edges.edge_permute(x, perm)


def test_cost_of_arms_the_byte_tally():
    x = jnp.ones((8, 4), jnp.uint32)
    c = cm.cost_of(lambda v: _seam_fn(v), x)
    assert c["halo_bytes"] == 8 * 4 * 4


def test_tally_step_ok_on_raw_body():
    x = jnp.ones((8, 4), jnp.uint32)
    out = edges.tally_step(_seam_fn, x, count_bytes=True)
    assert sum(b for _, b in out) == 8 * 4 * 4


def test_cost_of_raises_on_empty_halo_tally():
    """cost_of must never record a silent zero halo fit: a cached
    inner jaxpr (or a seam-free program costed with with_halo=True)
    raises the same typed TallyCacheHit the tally_step path uses."""
    inner = jax.jit(_seam_fn)
    x = jnp.ones((8, 4), jnp.uint32)
    inner.lower(x)

    def outer(v):
        return inner(v)

    with pytest.raises(edges.TallyCacheHit):
        cm.cost_of(outer, x)
    # seam-free programs are fine when halo is explicitly not asked for
    c = cm.cost_of(lambda v: v + jnp.uint32(1), x, with_halo=False)
    assert c["halo_bytes"] == 0


def test_tally_cache_hit_raises_typed_error():
    """The CHANGES-r16 footgun as a regression test: a jit hidden
    INSIDE a plain wrapper satisfies eval_shape from its cached jaxpr,
    so the seams never re-run — that must raise TallyCacheHit, never
    return a silent zero."""
    inner = jax.jit(_seam_fn)
    x = jnp.ones((8, 4), jnp.uint32)
    inner.lower(x)  # populate the tracing cache

    def outer(v):  # no __wrapped__ to unwrap through
        return inner(v)

    with pytest.raises(edges.TallyCacheHit):
        edges.tally_step(outer, x, count_bytes=True)
    # the gather tally path raises too
    with pytest.raises(edges.TallyCacheHit):
        edges.tally_step(outer, x)


# ---------------------------------------------------------------------------
# contracts: each tripped by a doctored jaxpr


def test_floodsub_rng_contract_trips_on_doctored_jaxpr():
    key = jax.random.key(3)
    doctored = _cost(lambda k: jax.random.bits(k, (8,), jnp.uint32), key)
    with pytest.raises(cm.CostContractViolation) as e:
        cm.check_floodsub_rng("floodsub", doctored)
    assert e.value.contract == "floodsub-rng"


def test_halo_density_contract_trips_on_doctored_ratio():
    # doctored pair: the "csr" program moves MORE than density*dense
    dense = jnp.ones((16, 4), jnp.uint32)
    csr = jnp.ones((16, 3), jnp.uint32)  # 48 edges of 64 -> ratio 0.75
    cd = cm.cost_of(lambda v: _seam_fn(v), dense)
    cc = cm.cost_of(lambda v: _seam_fn(v), csr)
    with pytest.raises(cm.CostContractViolation) as e:
        cm.check_halo_density(cd["halo_bytes"], cc["halo_bytes"],
                              density=0.5)
    assert e.value.contract == "halo-density"
    # and the exact ratio passes
    assert cm.check_halo_density(
        cd["halo_bytes"], cc["halo_bytes"], density=0.75) == 0.75


def test_halo_measured_contract_trips_on_mismatch():
    x = jnp.ones((8, 4), jnp.uint32)
    model = cm.cost_of(lambda v: _seam_fn(v), x)["halo_bytes"]
    measured = sum(b for _, b in edges.tally_step(
        _seam_fn, x, count_bytes=True))
    cm.check_halo_measured("seam", model, measured)  # agrees
    with pytest.raises(cm.CostContractViolation) as e:
        cm.check_halo_measured("seam", model, measured + 4)
    assert e.value.contract == "halo-measured"


def test_telemetry_flop_ceiling_trips_on_doctored_pair():
    x = jnp.ones((64,), jnp.float32)
    off = _cost(lambda v: v * v, x)["flops"]
    on = _cost(lambda v: jnp.tanh(v * v) * v + v, x)["flops"]
    assert on > off * (1 + cm.TELEMETRY_FLOP_SHARE_CEILING)
    with pytest.raises(cm.CostContractViolation) as e:
        cm.check_telemetry_flops(off, on)
    assert e.value.contract == "telemetry-flops"
    cm.check_telemetry_flops(off, off)  # zero delta passes


def test_oracle_flop_ceiling_trips_on_doctored_pair():
    x = jnp.ones((64,), jnp.float32)
    step = _cost(lambda v: v + 1.0, x)["flops"]
    checker = _cost(lambda v: jnp.sum(v * v) + jnp.sum(v), x)["flops"]
    assert checker > step * cm.ORACLE_FLOP_SHARE_CEILING
    with pytest.raises(cm.CostContractViolation) as e:
        cm.check_oracle_flops(step, checker)
    assert e.value.contract == "oracle-flops"


def test_fused_hbm_contract_trips_on_doctored_ratio():
    # doctored pair: the "fused" build only shaves 10% — over the
    # 0.8 ceiling, so the contract must name the lost traffic cut
    unfused = {"hbm_bytes": {"at_hi": 100.0, "slope": 1.0}}
    bad = {"hbm_bytes": {"at_hi": 90.0, "slope": 0.9}}
    with pytest.raises(cm.CostContractViolation) as e:
        cm.check_fused_hbm("csr", bad, unfused, ceiling=0.8)
    assert e.value.contract == "fused-hbm"
    # a fused build that stopped helping entirely (ratio >= 1) trips
    # even under a permissive ceiling
    flat = {"hbm_bytes": {"at_hi": 100.0, "slope": 1.0}}
    with pytest.raises(cm.CostContractViolation):
        cm.check_fused_hbm("phase_csr", flat, unfused, ceiling=2.0)
    # under the ceiling passes and returns the per-field ratios
    good = {"hbm_bytes": {"at_hi": 70.0, "slope": 0.7}}
    ratios = cm.check_fused_hbm("csr", good, unfused, ceiling=0.8)
    assert ratios["at_hi"] == 0.7 and ratios["slope"] == 0.7


def test_hbm_ceiling_gate_trips_on_doctored_build():
    """The cost-REGRESSION leg: a build whose fresh hbm_bytes/round
    rises past its committed ceiling must trip with the budget named
    — independent of the byte-identity walk."""
    with open(os.path.join(ROOT, cm.AUDIT_NAME)) as f:
        committed = json.load(f)
    ceilings = committed["contracts"]["hbm_ceilings"]["ceilings"]
    assert set(ceilings) == set(cm.AUDIT_BUILDS)
    builds = json.loads(json.dumps(committed["builds"]))
    cm.check_hbm_ceilings(ceilings, builds)  # the committed audit passes
    builds["csr"]["per_round"]["hbm_bytes"]["at_hi"] = (
        ceilings["csr"] * 1.01)
    with pytest.raises(cm.CostContractViolation) as e:
        cm.check_hbm_ceilings(ceilings, builds)
    assert e.value.contract == "hbm-ceiling"
    assert "csr" in str(e.value)
    # a build the committed artifact never priced is skipped, not KeyError'd
    builds["brand_new_engine"] = builds["floodsub"]
    del builds["csr"]
    cm.check_hbm_ceilings(ceilings, builds)


def test_committed_fusion_contract_pins_the_drop():
    """The committed fusion row IS the round-21 acceptance number:
    the fused csr build cuts >= 20% of hbm_bytes/round against the
    same-trace unfused denominator."""
    with open(os.path.join(ROOT, cm.AUDIT_NAME)) as f:
        audit = json.load(f)
    fusion = audit["contracts"]["fusion"]
    assert fusion["csr"]["ratio_at_hi"] <= cm.FUSED_HBM_RATIO_CEILING
    assert fusion["csr"]["ratio_slope"] <= cm.FUSED_HBM_RATIO_CEILING
    assert fusion["phase_csr"]["ratio_at_hi"] < 1.0
    # the unfused rows are the round-20 denominators: they must price
    # STRICTLY MORE traffic than their fused twins
    for name in ("csr", "phase_csr"):
        f_hi = audit["builds"][name]["per_round"]["hbm_bytes"]["at_hi"]
        u_hi = (audit["builds"][f"{name}_unfused"]
                ["per_round"]["hbm_bytes"]["at_hi"])
        assert f_hi < u_hi, name


def test_floodsub_cell_draws_no_randomness():
    """The live contract on the real build (small shape — trace only):
    floodsub prices zero rng bits; randomsub prices some."""
    flood = cm.per_round_cost(cm.build_cell("floodsub", cm.N_LO))
    cm.check_floodsub_rng("floodsub", flood)
    rnd = cm.per_round_cost(cm.build_cell("randomsub", cm.N_LO))
    assert rnd["rng_bits"] > 0
    assert flood["halo_bytes"] > 0


def test_committed_audit_contract_blocks_all_pass():
    """The committed COST_AUDIT.json carries pass=True on every
    contract row (the gate refuses to write otherwise) and prices
    every registry build."""
    with open(os.path.join(ROOT, cm.AUDIT_NAME)) as f:
        audit = json.load(f)
    assert set(audit["builds"]) == set(cm.AUDIT_BUILDS)
    assert audit["contracts"], "no contract rows committed"
    for name, row in audit["contracts"].items():
        assert row["pass"] is True, name
    # the halo-density row commits ratio == density exactly
    hd = audit["contracts"]["halo_density"]
    assert hd["ratio"] == hd["density"]
    # every build prices positive per-round flops and hbm traffic
    for name, b in audit["builds"].items():
        assert b["per_round"]["flops"]["at_hi"] > 0, name
        assert b["per_round"]["hbm_bytes"]["at_hi"] > 0, name
    # floodsub's committed rng row is zero at both fit points
    fs = audit["builds"]["floodsub"]["per_round"]["rng_bits"]
    assert fs["at_lo"] == 0 and fs["at_hi"] == 0


# ---------------------------------------------------------------------------
# byte-identity gates name their divergence (round-19 satellite)


def test_baseline_divergences_names_the_key():
    a = {"x": {"y": [1, 2], "z": 3}, "w": "s"}
    b = {"x": {"y": [1, 5], "z": 3}, "w": "s"}
    d = cm.baseline_divergences(a, b)
    assert d == ["x.y[1]: 2 != 5"]
    assert cm.baseline_divergences(a, a) == []
    d2 = cm.baseline_divergences({"k": 1}, {})
    assert "missing from this run" in d2[0]


def test_doctored_mem_audit_row_fails_naming_key(capsys, monkeypatch,
                                                 tmp_path):
    """A doctored MEM_AUDIT.json row must fail `make mem-audit` with an
    error NAMING the diverging key."""
    memstat = _load_script("memstat")
    with open(os.path.join(ROOT, "MEM_AUDIT.json")) as f:
        doctored = json.load(f)
    doctored["engines"]["gossipsub"]["totals"]["bytes_per_peer"] += 1.0
    p = tmp_path / "MEM_AUDIT.json"
    p.write_text(json.dumps(doctored))
    monkeypatch.setattr(memstat, "AUDIT_PATH", str(p))
    rc = memstat.main()
    out = capsys.readouterr().out
    assert rc == 1
    assert "FAIL" in out
    assert "engines.gossipsub.totals.bytes_per_peer" in out


def test_doctored_lift_audit_verdict_fails_naming_key(capsys, tmp_path):
    """A doctored LIFT_AUDIT.json verdict must fail `make lift-audit`
    with an error NAMING the diverging key."""
    lift_audit = _load_script("lift_audit")
    with open(os.path.join(ROOT, "LIFT_AUDIT.json")) as f:
        committed = json.load(f)
    field = sorted(committed["fields"])[0]
    committed["fields"][field]["verdict"] = "DOCTORED"
    (tmp_path / "LIFT_AUDIT.json").write_text(
        json.dumps(committed, indent=1, sort_keys=True) + "\n")
    rc = lift_audit.main(repo=str(tmp_path))
    err = capsys.readouterr().err
    assert rc == 1
    assert f"fields.{field}.verdict" in err
    assert "DOCTORED" in err


def test_doctored_cost_audit_fails_naming_key():
    """The cost gate's divergence walker over a doctored committed
    audit names the exact fit row that moved."""
    with open(os.path.join(ROOT, cm.AUDIT_NAME)) as f:
        committed = json.load(f)
    doctored = json.loads(json.dumps(committed))
    doctored["builds"]["floodsub"]["per_round"]["flops"]["slope"] += 1.0
    d = cm.baseline_divergences(doctored, committed)
    assert any("builds.floodsub.per_round.flops.slope" in x for x in d)


# ---------------------------------------------------------------------------
# roofline term: disarmed by default, armed via the committed audit


def test_projection_default_summary_has_no_roofline_keys():
    s = projection.project(0.425, 16).summary()
    assert not any("roofline" in k for k in s)
    sp = projection.project_at_scale(100_000, 16).summary()
    assert "roofline" not in sp


def test_roofline_block_from_committed_audit():
    with open(os.path.join(ROOT, cm.AUDIT_NAME)) as f:
        audit = json.load(f)
    blk = projection.roofline_block(audit, 12_500)
    assert blk["build"] == "gossipsub"
    assert blk["roofline_ms_per_round"] > 0
    assert blk["compute_ceiling_rounds_per_sec"] > 0
    # the bandwidth envelope dominates (intensity << 1 flop/byte)
    assert blk["arithmetic_intensity"] < 1.0
    assert blk["roofline_ms_per_round"] == blk["unfused_hbm_ms_per_round"]
    sp = projection.project_at_scale(100_000, 16, cost_audit=audit)
    assert sp.summary()["roofline"]["shard_n"] == 12_500


def test_eval_fit_reads_committed_rows():
    with open(os.path.join(ROOT, cm.AUDIT_NAME)) as f:
        audit = json.load(f)
    rows = audit["builds"]["gossipsub"]["per_round"]
    at_lo = cm.eval_fit(rows, "flops", cm.N_LO)
    assert at_lo == pytest.approx(rows["flops"]["at_lo"])


def test_roofline_ms_per_round_max_of_terms():
    # compute-bound case
    ms = projection.roofline_ms_per_round(
        1e12, 1.0, peak_flops=1e12, hbm_gbps=1000.0)
    assert ms == pytest.approx(1000.0)
    # bandwidth-bound case
    ms = projection.roofline_ms_per_round(
        1.0, 819e9, peak_flops=1e20, hbm_gbps=819.0)
    assert ms == pytest.approx(1000.0)
    with pytest.raises(ValueError):
        projection.roofline_ms_per_round(-1.0, 1.0)


# ---------------------------------------------------------------------------
# the fingerprint["cost"] block (schema v3) + legacy sentinel


def test_cost_fingerprint_roundtrip_and_legacy_sentinel():
    blk = artifacts.cost_fingerprint(
        build="floodsub_csr", flops_per_round=1000.0,
        hbm_bytes_per_round=8000.0, halo_bytes_per_round=512.0,
        rng_bits_per_round=0.0)
    line = {"schema": 3, "metric": "m", "value": 1.0, "unit": "u",
            "vs_baseline": 0.0, "fingerprint": {"cost": blk}}
    rec = artifacts.record_from_line(json.loads(json.dumps(line)))
    assert rec.cost_audited
    assert rec.cost["build"] == "floodsub_csr"
    assert rec.cost["arithmetic_intensity"] == pytest.approx(0.125)
    # round-trips through the line emitter
    rec2 = artifacts.record_from_line(rec.to_line())
    assert rec2.cost == rec.cost
    # legacy: no block -> the explicit COST_UNAUDITED sentinel
    legacy = artifacts.record_from_line(
        {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 0.0})
    assert not legacy.cost_audited
    assert legacy.cost == artifacts.COST_UNAUDITED
    # the committed BENCH_r07 pair predates the block -> sentinel
    variants = artifacts.load_bench_variants(
        os.path.join(ROOT, "BENCH_r07.json"))
    assert not variants["parsed"].cost_audited
    # ...and the round-21 re-cut retires that read for the power-law
    # cell: every BENCH_r08 arm carries a POPULATED cost block
    r08 = artifacts.load_bench_variants(
        os.path.join(ROOT, "BENCH_r08.json"))
    assert set(r08) == {"parsed", "parsed_unfused", "parsed_dense"}
    for key, rec in r08.items():
        assert rec.cost_audited, key
        assert rec.cost["hbm_bytes_per_round"] > 0, key
    assert r08["parsed"].cost["build"] == "floodsub_csr_fused"
    assert r08["parsed_unfused"].cost["build"] == "floodsub_csr"
    # the headline fused arm stays within the known heartbeat-less
    # premium of its unfused twin (scripts/topo_smoke.py docstring)
    ratio = (r08["parsed"].cost["hbm_bytes_per_round"]
             / r08["parsed_unfused"].cost["hbm_bytes_per_round"])
    assert ratio <= 1.10


# ---------------------------------------------------------------------------
# the `make static` umbrella (subprocess — slow tier)


@pytest.mark.slow
def test_analyze_json_umbrella_verdict_block():
    import subprocess
    import sys

    # a CLEAN environment: the conftest's 8-virtual-device XLA_FLAGS
    # would shard the guard builds and trip their transfer guard —
    # `make static` is defined on the plain 1-device CPU config
    env = {k: v for k, v in os.environ.items()
           if k != "XLA_FLAGS" and not k.startswith("JAX_")}
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "analyze.py"),
         "--json"],
        capture_output=True, text=True, cwd=ROOT, timeout=570, env=env)
    line = [ln for ln in proc.stdout.splitlines()
            if ln.strip().startswith("{")][-1]
    block = json.loads(line)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert block["static"] == "PASS"
    assert set(block["passes"]) == {"simlint", "guards", "lift", "hlo",
                                    "cost", "tune", "ranges"}
    for name, p in block["passes"].items():
        assert p["status"] == "PASS", name
        assert "artifacts" in p
    assert block["passes"]["cost"]["artifacts"] == ["COST_AUDIT.json"]
    assert block["passes"]["ranges"]["artifacts"] == ["RANGE_AUDIT.json"]
    assert block["passes"]["ranges"]["summary"]["artifact"] == "verified"
