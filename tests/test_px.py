"""PX (peer exchange): PRUNE carries suggested peers; the pruned peer
activates dormant provisioned edges to them, gated by AcceptPXThreshold
(makePrune gossipsub.go:1814-1850, handlePrune :834-841, pxConnect
:861-941). In the vectorized model a "connect" flips a dormant edge of the
candidate graph live (graph.dormant_edges)."""

import pytest
import dataclasses

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.state import Net


def benign_sp():
    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.01,
        time_in_mesh_quantum=1.0,
        time_in_mesh_cap=10.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.9,
    )
    return PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )


def build_px(n=24, d=8, seed=0, dormant_frac=0.4, accept_px=0.0, score=True):
    topo = graph.random_connect(n, d, seed=seed)
    dormant = graph.dormant_edges(topo, dormant_frac, seed=seed + 1)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    params = dataclasses.replace(GossipSubParams(), do_px=True)
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0,
        publish_threshold=-4.0,
        graylist_threshold=-8.0,
        accept_px_threshold=accept_px,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(params, thr, score_enabled=score)
    sp = benign_sp() if score else None
    st = GossipSubState.init(net, 32, cfg, score_params=sp, seed=seed, dormant=dormant)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    return topo, dormant, net, cfg, st, step


def edge_to(topo, j, target):
    for k in range(topo.max_degree):
        if topo.nbr_ok[j, k] and topo.nbr[j, k] == target:
            return k
    return None


def run(step, st, k):
    a = no_publish()
    for _ in range(k):
        st = step(st, *a)
    return st


def find_px_triple(topo, dormant, mesh, exclude_live_jl=True):
    """(pruner i, pruned j, suggested s): j--i live edge; s in i's mesh;
    j--s edge exists and is dormant."""
    n = topo.n_peers
    for i in range(n):
        for j_k in range(topo.max_degree):
            if not topo.nbr_ok[i, j_k] or dormant[i, j_k]:
                continue
            j = int(topo.nbr[i, j_k])
            for s_k in range(topo.max_degree):
                if not (topo.nbr_ok[i, s_k] and mesh[i, 0, s_k]) or dormant[i, s_k]:
                    continue
                s = int(topo.nbr[i, s_k])
                if s == j:
                    continue
                k_js = edge_to(topo, j, s)
                if k_js is not None and dormant[j, k_js]:
                    return i, j, s, k_js
    return None


def inject_prune_px(st, i, k_ij, px=True):
    p = np.asarray(st.prune_out).copy()
    p[i, 0, k_ij] = True
    ppx = np.asarray(st.prune_px_out).copy()
    ppx[i, 0, k_ij] = px
    return st.replace(prune_out=jnp.asarray(p), prune_px_out=jnp.asarray(ppx))


def test_dormant_edges_carry_nothing():
    topo, dormant, net, cfg, st, step = build_px(seed=1)
    st = run(step, st, 10)
    # no mesh membership ever forms across a dormant edge
    mesh = np.asarray(st.mesh[:, 0, :])
    assert not (mesh & dormant).any()


def test_px_activates_dormant_edge():
    topo, dormant, net, cfg, st, step = build_px(seed=1)
    st = run(step, st, 8)
    trip = find_px_triple(topo, dormant, np.asarray(st.mesh))
    assert trip is not None, "seed should admit a PX triple"
    i, j, s, k_js = trip
    k_ij = edge_to(topo, i, j)

    before = np.asarray(st.edge_live)
    assert not before[j, k_js]
    st = inject_prune_px(st, i, k_ij)
    st = step(st, *no_publish())

    after = np.asarray(st.edge_live)
    assert after[j, k_js], "dormant edge to suggested peer must activate"
    # symmetric on the far side
    k_sj = edge_to(topo, s, j)
    assert after[s, k_sj]
    # and the new edge becomes mesh-eligible: run on, j may graft s
    st = run(step, st, 6)
    assert np.asarray(st.edge_live)[j, k_js]


def test_px_rejected_below_threshold():
    topo, dormant, net, cfg, st, step = build_px(seed=1, accept_px=100.0)
    st = run(step, st, 8)
    trip = find_px_triple(topo, dormant, np.asarray(st.mesh))
    assert trip is not None
    i, j, s, k_js = trip
    k_ij = edge_to(topo, i, j)
    st = inject_prune_px(st, i, k_ij)
    st = step(st, *no_publish())
    # pruner's score cannot clear AcceptPXThreshold=100 -> no activation
    assert not np.asarray(st.edge_live)[j, k_js]


def test_prune_without_px_no_activation():
    topo, dormant, net, cfg, st, step = build_px(seed=1)
    st = run(step, st, 8)
    trip = find_px_triple(topo, dormant, np.asarray(st.mesh))
    assert trip is not None
    i, j, s, k_js = trip
    k_ij = edge_to(topo, i, j)
    st = inject_prune_px(st, i, k_ij, px=False)
    st = step(st, *no_publish())
    assert not np.asarray(st.edge_live)[j, k_js]


def test_heartbeat_oversub_prune_carries_px():
    """Over-subscribed meshes prune with PX attached; score-prunes are
    noPX (gossipsub.go:1365 vs :1446)."""
    # tiny Dhi so over-subscription prunes happen during warmup
    topo = graph.random_connect(24, 10, seed=3)
    dormant = graph.dormant_edges(topo, 0.3, seed=4)
    subs = graph.subscribe_all(24, 1)
    net = Net.build(topo, subs)
    params = dataclasses.replace(GossipSubParams(), do_px=True, D=3, Dlo=2, Dhi=4,
                                 Dscore=2, Dout=1, Dlazy=3)
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-4.0, graylist_threshold=-8.0,
        accept_px_threshold=0.0, opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(params, thr, score_enabled=True)
    st = GossipSubState.init(net, 32, cfg, score_params=benign_sp(), seed=0,
                             dormant=dormant)
    step = make_gossipsub_step(cfg, net, score_params=benign_sp())
    saw_px = False
    for _ in range(12):
        st = step(st, *no_publish())
        if np.asarray(st.prune_px_out).any():
            saw_px = True
    assert saw_px, "over-subscription prunes should carry PX"
    # network stays healthy: all meshes bounded, some dormant edges may
    # have come alive but none beyond the provisioned candidate set
    live = np.asarray(st.edge_live)
    assert not (live & ~np.asarray(net.nbr_ok)).any()


@pytest.mark.slow
def test_direct_connect_reactivates_dormant_direct_edges():
    # directConnect (gossipsub.go:1606-1628): every DirectConnectTicks the
    # router re-dials direct peers; a dormant direct edge comes back live
    n, d = 16, 4
    topo = graph.random_connect(n, d, seed=2)
    dormant = graph.dormant_edges(topo, 0.9, seed=3)
    subs = graph.subscribe_all(n, 1)
    # pick one dormant edge and mark it direct (both directions)
    ij = np.argwhere(dormant & topo.nbr_ok)
    i, k = ij[0]
    j, rk = topo.nbr[i, k], topo.rev[i, k]
    direct = np.zeros_like(topo.nbr_ok)
    direct[i, k] = True
    direct[j, rk] = True
    net = Net.build(topo, subs, direct=direct)
    params = dataclasses.replace(GossipSubParams(), do_px=True,
                                 direct_connect_ticks=5)
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(), score_enabled=True)
    sp = benign_sp()
    st = GossipSubState.init(net, 16, cfg, score_params=sp, seed=0,
                             dormant=dormant)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    assert not bool(st.edge_live[i, k])
    po, pt, pv = no_publish()
    for r in range(4):
        st = step(st, po, pt, pv)
        assert not bool(st.edge_live[i, k]), f"too early at round {r}"
    st = step(st, po, pt, pv)  # tick 4 runs heartbeat at tick%5==0? tick counts from 0
    # by tick 5 the redial must have happened on both directions
    st = step(st, po, pt, pv)
    assert bool(st.edge_live[i, k]) and bool(st.edge_live[j, rk])
