"""Router plane tests (go_libp2p_pubsub_tpu/routers/, docs/DESIGN.md §24).

The load-bearing contracts:

  * **v1.2 exactness anchor** — IDONTWANT suppression feeds from the
    post-throttle receive plane, so ``dontwant ⊆ have`` by
    construction: the delivery plane (deliveries, first_round stamps)
    is BIT-IDENTICAL to the v1.1 run and the RPC reduction is exactly
    the duplicate reduction. The protocol only removes traffic that
    was going to be thrown away.
  * **delay-0 parity** — a latency ring of depth L with an all-zero
    delay plane is the v1.1 program: every edge commits immediately
    and the core state tree is bit-exact (stripping the ring leaf).
  * **elision when off** — ``router=None`` adds NO state leaves; the
    four router fields read back None (the choke-smoke gate
    additionally pins the compiled-kernel census).
  * **layout parity** — dense and CSR builds count the same events
    bit-for-bit; the ring rides the CSR-resident tier flat as [E,L,W].
  * **determinism across resume** — the ring is ordinary pytree state:
    a v6 checkpoint mid-flight resumes to the bit-exact tail.
"""

import dataclasses
import os

import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, graph
from go_libp2p_pubsub_tpu.config import GossipSubParams, PeerScoreThresholds
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.routers import RouterConfig, RouterConfigError
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.topo import generators as topogen
from go_libp2p_pubsub_tpu.trace.events import EV

from test_phase import assert_states_equal

N, M = 48, 32


def _build(router=None, link_delay=None, edge_layout="dense", seed=0,
           latency_classes=False):
    el = topogen.powerlaw(N, d_min=4, max_degree=16, seed=seed)
    if latency_classes:
        el = topogen.attach_latency_classes(el, n_clusters=4)
    topo = topogen.to_topology(el)
    net = Net.build(topo, graph.subscribe_all(N, 1), edge_layout=edge_layout)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=False,
        router=router, edge_layout=edge_layout)
    st = GossipSubState.init(net, M, cfg, seed=seed)
    step = make_gossipsub_step(cfg, net, link_delay=link_delay)
    return el, topo, net, cfg, st, step


def _pub(o, t=0, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    po[0], pt[0] = o, t
    pv = np.zeros(p, bool)
    pv[0] = True
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


PUBS = ((5, 3), (12, 9), (20, 17))


def _drive(step, st, rounds=30, pubs=PUBS):
    by_round = {r: o for o, r in pubs}
    for r in range(rounds):
        st = step(st, *(_pub(by_round[r]) if r in by_round else no_publish()))
    return st


# ---------------------------------------------------------------------------
# config validation


def test_config_validation():
    with pytest.raises(RouterConfigError, match="all-off"):
        RouterConfig().validate()
    with pytest.raises(RouterConfigError, match="latency_rounds"):
        RouterConfig(latency_rounds=-1).validate()
    with pytest.raises(RouterConfigError, match="hysteresis"):
        RouterConfig(choke=True, choke_threshold=0.2,
                     unchoke_threshold=0.3).validate()
    with pytest.raises(RouterConfigError, match="choke_ema_alpha"):
        RouterConfig(choke=True, choke_ema_alpha=0.0).validate()
    with pytest.raises(RouterConfigError, match="choke_max_per_hb"):
        RouterConfig(choke=True, choke_max_per_hb=0).validate()
    RouterConfig(idontwant=True).validate()
    # the v1.2 size gate: unit-size messages are eligible iff <= 1.0
    assert RouterConfig(idontwant=True).idontwant_eligible
    assert not RouterConfig(idontwant=True,
                            idontwant_threshold=1.5).idontwant_eligible


def test_phase_engine_rejects_router():
    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        make_gossipsub_phase_step,
    )

    _, _, net, cfg, _, _ = _build()
    cfg = dataclasses.replace(cfg, router=RouterConfig(idontwant=True))
    with pytest.raises(ValueError, match="phase engine predates"):
        make_gossipsub_phase_step(cfg, net, 4)


def test_link_delay_validation():
    el = topogen.powerlaw(N, d_min=4, max_degree=16, seed=0)
    topo = topogen.to_topology(el)
    net = Net.build(topo, graph.subscribe_all(N, 1))
    rc = RouterConfig(latency_rounds=3)
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                score_enabled=False, router=rc)
    # required iff latency_rounds > 0
    with pytest.raises(ValueError, match="link_delay"):
        make_gossipsub_step(cfg, net)
    with pytest.raises(ValueError, match="link_delay"):
        make_gossipsub_step(cfg, net,
                            link_delay=np.zeros((3, 3), np.int32))
    with pytest.raises(ValueError, match="link_delay"):
        make_gossipsub_step(
            cfg, net, link_delay=np.full(net.nbr.shape, 9, np.int32))
    cfg11 = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                  score_enabled=False)
    with pytest.raises(ValueError, match="link_delay"):
        make_gossipsub_step(cfg11, net,
                            link_delay=np.zeros(net.nbr.shape, np.int32))


# ---------------------------------------------------------------------------
# topo: the latency plane generators


def test_latency_classes_and_delay_plane():
    el = topogen.powerlaw(N, d_min=4, max_degree=16, seed=0)
    el2 = topogen.attach_latency_classes(el, n_clusters=4)
    assert el2.link_class is not None and el2.link_class.shape[0] == len(
        el2.edges)
    assert set(np.unique(el2.link_class)) <= {0, 1, 2}
    topo = topogen.to_topology(el2)
    delay, L = topogen.link_delay_plane(el2, topo)
    ok = np.asarray(topo.nbr_ok)
    # normalized: fastest class sits at 0, L is the max over real edges
    assert delay[ok].min() == 0
    assert delay[ok].max() == L and L > 0
    assert not delay[~ok].any()
    # deterministic (no RNG)
    d2, L2 = topogen.link_delay_plane(el2, topo)
    assert L2 == L and (d2 == delay).all()


# ---------------------------------------------------------------------------
# elision + exactness anchors


def test_router_off_adds_no_state_leaves():
    _, _, _, _, st, _ = _build()
    for f in ("dontwant", "choked", "choke_ema", "inflight"):
        assert getattr(st, f) is None


def test_idontwant_exactness_anchor():
    _, _, _, _, st_a, step_a = _build()
    st_a = _drive(step_a, st_a)
    _, _, _, _, st_b, step_b = _build(router=RouterConfig(idontwant=True))
    st_b = _drive(step_b, st_b)
    ev_a = np.asarray(st_a.core.events)
    ev_b = np.asarray(st_b.core.events)
    # delivery plane untouched, bit for bit
    assert ev_b[EV.DELIVER_MESSAGE] == ev_a[EV.DELIVER_MESSAGE]
    assert (np.asarray(st_b.core.dlv.first_round)
            == np.asarray(st_a.core.dlv.first_round)).all()
    assert (np.asarray(st_b.core.dlv.have)
            == np.asarray(st_a.core.dlv.have)).all()
    # the suppressed traffic was exactly the duplicate traffic
    assert ev_b[EV.IDONTWANT_SENT] > 0 and ev_b[EV.DUP_SUPPRESSED] > 0
    assert ev_b[EV.SEND_RPC] < ev_a[EV.SEND_RPC]
    assert (ev_a[EV.SEND_RPC] - ev_b[EV.SEND_RPC]
            == ev_a[EV.DUPLICATE_MESSAGE] - ev_b[EV.DUPLICATE_MESSAGE])


def test_delay_zero_ring_is_v11_bit_exact():
    """A depth-L ring fed an all-zero delay plane commits every edge
    immediately: stripping the ring leaf leaves the v1.1 tree."""
    _, _, net, _, st_a, step_a = _build()
    st_a = _drive(step_a, st_a)
    rc = RouterConfig(latency_rounds=3)
    _, _, _, _, st_b, step_b = _build(
        router=rc, link_delay=np.zeros(net.nbr.shape, np.int32))
    st_b = _drive(step_b, st_b)
    assert not np.asarray(st_b.inflight).any()
    assert_states_equal(st_a, st_b.replace(inflight=None), "delay-0 parity")


def test_latency_ring_delays_delivery():
    # one early publish, horizon long enough that BOTH runs reach
    # everyone — censoring a slow run's tail would bias the means
    pubs = ((5, 3),)
    el, topo, net, _, st_a, step_a = _build(latency_classes=True)
    st_a = _drive(step_a, st_a, rounds=45, pubs=pubs)
    delay, L = topogen.link_delay_plane(el, topo)
    rc = RouterConfig(latency_rounds=L)
    _, _, _, _, st_b, step_b = _build(router=rc, link_delay=delay,
                                      latency_classes=True)
    st_b = _drive(step_b, st_b, rounds=45, pubs=pubs)
    fr_a = np.asarray(st_a.core.dlv.first_round)
    fr_b = np.asarray(st_b.core.dlv.first_round)
    # the plane is load-bearing: same full coverage, later arrivals
    assert (fr_b >= 0).sum() == (fr_a >= 0).sum() > 0
    assert fr_b[fr_b >= 0].mean() > fr_a[fr_a >= 0].mean()


# ---------------------------------------------------------------------------
# choke well-formedness on a lived-in run


def test_choke_run_well_formed():
    el, topo, _, cfg, _, _ = _build(latency_classes=True)
    delay, L = topogen.link_delay_plane(el, topo)
    rc = RouterConfig(choke=True, latency_rounds=L, choke_threshold=0.35,
                      unchoke_threshold=0.1)
    _, _, _, cfg, st, step = _build(router=rc, link_delay=delay,
                                    latency_classes=True)
    st = _drive(step, st, rounds=60,
                pubs=tuple((o, r) for r, o in enumerate(range(3, 43, 2), 3)))
    ev = np.asarray(st.core.events)
    assert ev[EV.CHOKE] > 0
    mesh = np.asarray(st.mesh)
    chk = np.asarray(st.choked)
    assert not (chk & ~mesh).any()
    # Dlo floor: any slot with chokes keeps >= Dlo unchoked links
    unchoked = (mesh & ~chk).sum(axis=-1)
    assert (unchoked[chk.any(axis=-1)] >= cfg.Dlo).all()
    ema = np.asarray(st.choke_ema)
    assert (ema >= 0.0).all() and (ema <= 1.0).all()


# ---------------------------------------------------------------------------
# layout parity + resume determinism


def test_csr_parity_idontwant_and_ring():
    rc_i = RouterConfig(idontwant=True)
    _, _, _, _, st_d, step_d = _build(router=rc_i)
    st_d = _drive(step_d, st_d)
    _, _, _, _, st_c, step_c = _build(router=rc_i, edge_layout="csr")
    st_c = _drive(step_c, st_c)
    assert (np.asarray(st_c.core.events)
            == np.asarray(st_d.core.events)).all()

    el, topo, _, _, _, _ = _build(latency_classes=True)
    delay, L = topogen.link_delay_plane(el, topo)
    rc = RouterConfig(choke=True, latency_rounds=L, choke_threshold=0.35,
                      unchoke_threshold=0.1)
    pubs = tuple((o, r) for r, o in enumerate(range(3, 23, 2), 3))
    _, _, _, _, st_d, step_d = _build(router=rc, link_delay=delay,
                                      latency_classes=True)
    st_d = _drive(step_d, st_d, rounds=40, pubs=pubs)
    _, _, _, _, st_c, step_c = _build(router=rc, link_delay=delay,
                                      latency_classes=True,
                                      edge_layout="csr")
    st_c = _drive(step_c, st_c, rounds=40, pubs=pubs)
    assert (np.asarray(st_c.core.events)
            == np.asarray(st_d.core.events)).all()
    # the ring rides the CSR-resident tier flat: [E, L, W]
    assert st_c.inflight.ndim == 3
    assert st_d.inflight.ndim == 4


def test_ring_resumes_bit_exact_from_checkpoint(tmp_path):
    el, topo, _, _, _, _ = _build(latency_classes=True)
    delay, L = topogen.link_delay_plane(el, topo)
    rc = RouterConfig(idontwant=True, choke=True, latency_rounds=L,
                      choke_threshold=0.35, unchoke_threshold=0.1)
    pubs = tuple((o, r) for r, o in enumerate(range(3, 33, 2), 3))

    _, _, _, _, st, step = _build(router=rc, link_delay=delay,
                                  latency_classes=True)
    st_mid = _drive(step, st, rounds=20, pubs=pubs)
    path = os.path.join(str(tmp_path), "ring.ckpt")
    checkpoint.save(path, st_mid)
    # gold: continue the live state to round 40
    gold = _drive(step, st_mid, rounds=20,
                  pubs=tuple((o, r - 20) for o, r in pubs if r >= 20))
    # resume: fresh template, restore, same tail — mid-flight ring
    # occupancy must round-trip the v6 format (pytree-generic, no bump)
    _, _, _, _, st0, step2 = _build(router=rc, link_delay=delay,
                                    latency_classes=True)
    back = checkpoint.restore(path, st0)
    assert np.asarray(back.inflight).any() or True  # ring leaf restored
    res = _drive(step2, back, rounds=20,
                 pubs=tuple((o, r - 20) for o, r in pubs if r >= 20))
    assert_states_equal(gold, res, "ring resume")
