"""Host-side pxConnect: PRUNE-with-PX grows the topology to genuinely new
peers, gated by signed peer records (gossipsub.go:861-941, makePrune
:1814-1850; pb/rpc.proto PeerInfo.signedPeerRecord).

Round-1 review items: the engine-level PX plane can only activate
pre-provisioned dormant edges, and PX carried no identity payload, so
record-forgery attacks were inexpressible. These tests drive the new
api-level path: real edge additions via the runtime rebuild (state
carried across an edge-slot remap) and envelope validation that rejects
forged records.
"""

from __future__ import annotations

import pytest

import numpy as np

from go_libp2p_pubsub_tpu import api
from go_libp2p_pubsub_tpu.config import GossipSubParams
from go_libp2p_pubsub_tpu.sign import (
    SignedPeerRecord,
    _record_payload,
    make_peer_record,
    validate_peer_record,
)


def _crowded_net(px_connect=True, **kw):
    """A topology that over-subscribes meshes so heartbeats emit
    PRUNE-with-PX (over-subscription prunes carry PX when do_px is on,
    gossipsub.go:1446)."""
    params = GossipSubParams(do_px=True)
    net = api.Network(params=params, px_connect=px_connect, **kw)
    nodes = net.add_nodes(24)
    net.dense_connect(d=14, seed=3)  # degree >> Dhi=12: prunes guaranteed
    for nd in nodes:
        nd.join("t")
    return net, nodes


def test_record_roundtrip_and_forgery():
    from go_libp2p_pubsub_tpu.sign import Identity

    a, b = api.Identity.generate(1), Identity.generate(2)
    rec = make_peer_record(a, 7)
    assert validate_peer_record(rec, a.peer_id)
    assert not validate_peer_record(rec, b.peer_id)       # wrong subject
    assert not validate_peer_record(None, a.peer_id)      # absent record
    forged = SignedPeerRecord(
        a.peer_id, 9, b.key.sign(_record_payload(a.peer_id, 9))
    )
    assert not validate_peer_record(forged, a.peer_id)    # forged signature


@pytest.mark.slow
def test_px_grows_topology_to_new_peers():
    net, nodes = _crowded_net()
    before = set((min(a, b), max(a, b)) for a, b in net._edges)
    net.start()
    net.run(10)
    after = set((min(a, b), max(a, b)) for a, b in net._edges)
    added = after - before
    assert added, "PRUNE-with-PX never produced a new connection"
    # the new edges exist in the live topology and the mesh keeps working
    nbr = np.asarray(net.net.nbr)
    ok = np.asarray(net.net.nbr_ok)
    for a, b in added:
        row = [int(x) for x in nbr[a][ok[a]]]
        assert b in row
    subs = [nd.topics["t"].subscribe() for nd in nodes]
    nodes[0].topics["t"].publish(b"post-px")
    net.run(6)
    got = sum(1 for s in subs if s.next() is not None)
    assert got == len(nodes)


def test_forged_px_records_rejected():
    net, nodes = _crowded_net()
    attacker = api.Identity.generate(999)
    forged_calls = []

    def forge_everything(pruner_idx, suggested_idx):
        forged_calls.append((pruner_idx, suggested_idx))
        victim_id = net.nodes[suggested_idx].identity.peer_id
        return SignedPeerRecord(
            victim_id, 1, attacker.key.sign(_record_payload(victim_id, 1))
        )

    net._px_record_source = forge_everything
    before = set((min(a, b), max(a, b)) for a, b in net._edges)
    net.start()
    net.run(10)
    after = set((min(a, b), max(a, b)) for a, b in net._edges)
    assert forged_calls, "no PX suggestions were even attempted"
    assert after == before, "forged records must not create connections"


@pytest.mark.slow
def test_state_survives_px_rebuild():
    net, nodes = _crowded_net()
    net.start()
    net.run(4)
    mesh_deg_pre = np.asarray(net.state.mesh).sum()
    tick_pre = int(net.state.core.tick)
    net.run(8)  # rebuilds happen in here when PX fires
    assert int(net.state.core.tick) == tick_pre + 8
    # the mesh neither resets nor explodes across rebuilds
    deg = np.asarray(net.state.mesh).sum(axis=(1, 2))
    assert deg.min() >= 1
    assert np.asarray(net.state.mesh).sum() >= mesh_deg_pre * 0.5
