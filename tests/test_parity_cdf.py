"""Propagation-latency CDF parity: vectorized router vs. scalar oracle.

The north-star parity claim (BASELINE.json) is distributional: RNG
streams can't match between the batched engine and a per-node
implementation (survey §7 hard-part (d)), so we assert that the
propagation-latency CDF of the vectorized GossipSub router stays within
2% (sup-norm) of the scalar oracle's — the same tolerance the north star
specifies against the Go reference, with oracle/gossipsub.py standing in
as the faithful per-node transcription of gossipsub.go.

Both sides run the identical topology, subscriptions, and publish
schedule; only the random choices (mesh selection, gossip targets)
differ. The CDF is over (subscribed peer, message) pairs: fraction first
reached within h rounds of publish.
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import GossipSubParams
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.oracle.gossipsub import OracleGossipSub
from go_libp2p_pubsub_tpu.state import Net, hops
from go_libp2p_pubsub_tpu.trace.events import EV

N = 192
DEG = 8
MSG_SLOTS = 64
WARMUP = 20
PUB_ROUNDS = 18
PUBS_PER_ROUND = 2
DRAIN = 12
MAX_H = 14


def publish_schedule(seed=7):
    rng = np.random.default_rng(seed)
    return rng.integers(0, N, size=(PUB_ROUNDS, PUBS_PER_ROUND)).astype(np.int32)


def cdf_from_hops(hop_counts, n_msgs, n_subscribed):
    """hop_counts: list of hop values (one per first receipt). Returns the
    CDF over all (subscribed peer, msg) pairs at h = 0..MAX_H; pairs never
    reached contribute to the denominator but no step."""
    total = n_msgs * n_subscribed
    hist = np.zeros(MAX_H + 1)
    for h in hop_counts:
        hist[min(h, MAX_H)] += 1
    return np.cumsum(hist) / total


def run_vectorized(topo, subs, params, schedule):
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(params)
    st = GossipSubState.init(net, MSG_SLOTS, cfg, seed=3)
    step = make_gossipsub_step(cfg, net)
    empty = no_publish(PUBS_PER_ROUND)
    for _ in range(WARMUP):
        st = step(st, *empty)
    import jax.numpy as jnp

    pt = jnp.zeros((PUBS_PER_ROUND,), jnp.int32)
    pv = jnp.ones((PUBS_PER_ROUND,), bool)
    for r in range(PUB_ROUNDS):
        st = step(st, jnp.asarray(schedule[r]), pt, pv)
    for _ in range(DRAIN):
        st = step(st, *empty)
    h = np.asarray(hops(st.core.msgs, st.core.dlv))  # [N, M]
    ev = np.asarray(st.core.events)
    return [int(x) for x in h[h >= 0]], ev


def run_oracle(topo, subs, params, schedule):
    cfg = GossipSubConfig.build(params)
    o = OracleGossipSub(topo, subs, cfg, msg_slots=MSG_SLOTS, seed=11)
    for _ in range(WARMUP):
        o.step()
    for r in range(PUB_ROUNDS):
        o.step([(int(p), 0, True) for p in schedule[r]])
    for _ in range(DRAIN):
        o.step()
    return list(o.hops().values()), o.events


@pytest.mark.parametrize("flood_publish", [False, True])
def test_propagation_cdf_within_2pct(flood_publish):
    topo = graph.random_connect(N, d=DEG, seed=5)
    subs = graph.subscribe_all(N, 1)
    params = GossipSubParams(flood_publish=flood_publish)
    schedule = publish_schedule()
    n_msgs = PUB_ROUNDS * PUBS_PER_ROUND

    hv, ev_v = run_vectorized(topo, subs, params, schedule)
    ho, ev_o = run_oracle(topo, subs, params, schedule)

    cv = cdf_from_hops(hv, n_msgs, N)
    co = cdf_from_hops(ho, n_msgs, N)

    sup = float(np.max(np.abs(cv - co)))
    assert sup <= 0.02, f"CDF sup-distance {sup:.4f} > 2%\nvec={cv}\noracle={co}"

    # full coverage on an honest connected network, both sides
    assert cv[-1] >= 0.999 and co[-1] >= 0.999

    # mean propagation latency within 2% relative
    mv, mo = np.mean(hv), np.mean(ho)
    assert abs(mv - mo) / mo <= 0.02, f"mean hops {mv:.3f} vs {mo:.3f}"


def test_event_accounting_tracks_oracle():
    """Aggregate trace counters (deliver / duplicate / RPC volume) are
    RNG-dependent but must land in the same regime: within 10%."""
    topo = graph.random_connect(N, d=DEG, seed=5)
    subs = graph.subscribe_all(N, 1)
    params = GossipSubParams()
    schedule = publish_schedule()

    _, ev_v = run_vectorized(topo, subs, params, schedule)
    _, ev_o = run_oracle(topo, subs, params, schedule)

    for e in (EV.DELIVER_MESSAGE, EV.DUPLICATE_MESSAGE, EV.SEND_RPC):
        v, o = float(ev_v[e]), float(ev_o[e])
        assert o > 0
        assert abs(v - o) / o <= 0.10, f"event {e}: vec {v} oracle {o}"
    assert int(ev_v[EV.PUBLISH_MESSAGE]) == int(ev_o[EV.PUBLISH_MESSAGE])


def test_randomsub_propagation_cdf_within_2pct():
    """RandomSub (sqrt-fanout) CDF parity against its scalar oracle —
    distributional, like gossipsub (fresh random draws every round on
    both sides)."""
    from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
    from go_libp2p_pubsub_tpu.oracle.randomsub import OracleRandomSub
    from go_libp2p_pubsub_tpu.state import SimState

    import jax.numpy as jnp

    topo = graph.random_connect(N, d=DEG, seed=5)
    subs = graph.subscribe_all(N, 1)
    schedule = publish_schedule()
    n_msgs = PUB_ROUNDS * PUBS_PER_ROUND

    net = Net.build(topo, subs)
    st = SimState.init(N, MSG_SLOTS, seed=3, k=net.max_degree)
    step = make_randomsub_step(net)
    pt = jnp.zeros((PUBS_PER_ROUND,), jnp.int32)
    pv = jnp.ones((PUBS_PER_ROUND,), bool)
    for r in range(PUB_ROUNDS):
        st = step(st, jnp.asarray(schedule[r]), pt, pv)
    for _ in range(DRAIN):
        st = step(st, *no_publish(PUBS_PER_ROUND))
    h = np.asarray(hops(st.msgs, st.dlv))
    hv = [int(x) for x in h[h >= 0]]

    o = OracleRandomSub(topo, subs, msg_slots=MSG_SLOTS, seed=11)
    for r in range(PUB_ROUNDS):
        o.step([(int(p), 0, True) for p in schedule[r]])
    for _ in range(DRAIN):
        o.step()
    ho = list(o.hops().values())

    cv = cdf_from_hops(hv, n_msgs, N)
    co = cdf_from_hops(ho, n_msgs, N)
    sup = float(np.max(np.abs(cv - co)))
    assert sup <= 0.02, f"CDF sup-distance {sup:.4f} > 2%\nvec={cv}\noracle={co}"
    assert cv[-1] >= 0.999 and co[-1] >= 0.999
