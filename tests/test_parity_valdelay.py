"""Mixed per-topic validation latency: parity vs the scalar oracle.

The reference's validation pipeline completes verdicts at variable times
(NumCPU async workers + per-topic throttles, validation.go:123-135,
391-438), so messages of different topics forward out of arrival order —
the ordering hazard survey §7(c) flags. `validation_delay_topic` models
it as a static per-topic delay-in-rounds; this file pins

  * the deterministic interleaving law on a pure ring (no delivery
    randomness): a topic with delay d propagates one hop per 1+d rounds,
    so a fast topic published later overtakes a slow one, and
  * distributional CDF parity (<= 2% sup, per topic and pooled) against
    the oracle's pending-verdict model on a random topology.
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import GossipSubParams
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.oracle.gossipsub import OracleGossipSub
from go_libp2p_pubsub_tpu.state import Net, hops
from go_libp2p_pubsub_tpu.trace.events import EV

DELAYS = (1, 3, 2)  # per-topic verdict latency in rounds
N = 128
DEG = 8
MSG_SLOTS = 64
WARMUP = 20
PUB_ROUNDS = 15
DRAIN = 30
MAX_H = 14


def test_mixed_latency_hop_law_and_overtaking():
    """Pure ring, flood-free: topic t's hop-h first_round (the verdict
    instant) is publish + h*(1+delay[t]); a delay-1 topic published two
    rounds after a delay-3 topic still reaches hop 3 first."""
    n = 24
    topo = graph.ring_lattice(n, d=1)
    subs = graph.subscribe_all(n, 2)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(
        GossipSubParams(), validation_delay_topic=(3, 1)
    )
    st = GossipSubState.init(net, 16, cfg, seed=0)
    step = make_gossipsub_step(cfg, net)
    for _ in range(5):
        st = step(st, *no_publish())
    t0 = int(st.core.tick)

    def pub(o, t):
        po = jnp.asarray(np.array([o, -1, -1, -1], np.int32))
        pt = jnp.asarray(np.array([t, 0, 0, 0], np.int32))
        pv = jnp.asarray(np.array([True, False, False, False]))
        return po, pt, pv

    st = step(st, *pub(0, 0))      # slow topic (delay 3) at t0
    st = step(st, *no_publish())
    st = step(st, *pub(0, 1))      # fast topic (delay 1) at t0+2
    for _ in range(40):
        st = step(st, *no_publish())

    fr = np.asarray(st.core.dlv.first_round)
    ms = np.asarray(st.core.msgs.topic)
    slow = int(np.flatnonzero(ms == 0)[0])
    fast = int(np.flatnonzero(ms == 1)[0])
    # hop h = ring distance; verdict at publish + h*(1+d)
    for h in (1, 2, 3):
        assert fr[h, slow] == t0 + h * 4, (h, fr[h, slow], t0)
        assert fr[h, fast] == (t0 + 2) + h * 2, (h, fr[h, fast], t0)
    # overtaking: at hop 3 the late fast message validated first
    assert fr[3, fast] < fr[3, slow]


def _schedule(seed=9):
    rng = np.random.default_rng(seed)
    po = rng.integers(0, N, size=(PUB_ROUNDS, 2)).astype(np.int32)
    # balanced topics: equal message counts per delay class
    pt = (np.arange(PUB_ROUNDS * 2) % len(DELAYS)).reshape(PUB_ROUNDS, 2).astype(np.int32)
    return po, pt


def _cdf(hop_list, total):
    hist = np.zeros(MAX_H + 1)
    for h in hop_list:
        hist[min(h, MAX_H)] += 1
    return np.cumsum(hist) / total


def test_mixed_latency_cdf_parity_vs_oracle():
    topo = graph.random_connect(N, d=DEG, seed=5)
    subs = graph.subscribe_all(N, len(DELAYS))
    params = GossipSubParams()
    po_s, pt_s = _schedule()

    # engine
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(params, validation_delay_topic=DELAYS)
    st = GossipSubState.init(net, MSG_SLOTS, cfg, seed=3)
    step = make_gossipsub_step(cfg, net)
    empty = no_publish(2)
    for _ in range(WARMUP):
        st = step(st, *empty)
    pv = jnp.ones((2,), bool)
    for r in range(PUB_ROUNDS):
        st = step(st, jnp.asarray(po_s[r]), jnp.asarray(pt_s[r]), pv)
    for _ in range(DRAIN):
        st = step(st, *empty)
    h_eng = np.asarray(hops(st.core.msgs, st.core.dlv))  # [N, M]
    topic_eng = np.asarray(st.core.msgs.topic)
    ev_v = np.asarray(st.core.events)

    # oracle
    o = OracleGossipSub(topo, subs, cfg, msg_slots=MSG_SLOTS, seed=11)
    for _ in range(WARMUP):
        o.step()
    for r in range(PUB_ROUNDS):
        o.step([(int(po_s[r][j]), int(pt_s[r][j]), True) for j in range(2)])
    for _ in range(DRAIN):
        o.step()

    n_msgs = PUB_ROUNDS * 2
    # pooled + per-topic CDFs
    hv_all, ho_all = [], []
    for t in range(len(DELAYS)):
        hv = [
            int(h_eng[i, m]) for i in range(N)
            for m in np.flatnonzero(topic_eng == t)
            if h_eng[i, m] >= 0
        ]
        ho = [
            hop for (i, slot), hop in o.hops().items()
            if o.msgs[slot].topic == t
        ]
        hv_all += hv
        ho_all += ho
        nt = int(np.sum(pt_s == t))
        if nt == 0:
            continue
        # per-topic: ~10 messages/topic puts the RNG-noise floor of the
        # sup-distance near 1/nt-scale steps (measured 3.4% with matching
        # means); bound the sup at 5% and the mean tightly instead — the
        # 2% north-star tolerance applies to the pooled CDF below
        sup = float(np.max(np.abs(_cdf(hv, nt * N) - _cdf(ho, nt * N))))
        assert sup <= 0.05, f"topic {t} (delay {DELAYS[t]}): sup {sup:.4f}"
        mv, mo = np.mean(hv), np.mean(ho)
        assert abs(mv - mo) / mo <= 0.025, (
            f"topic {t} mean hops {mv:.3f} vs {mo:.3f}"
        )
    sup = float(np.max(np.abs(_cdf(hv_all, n_msgs * N) - _cdf(ho_all, n_msgs * N))))
    assert sup <= 0.02, f"pooled sup {sup:.4f}"

    # full coverage and aggregate accounting in the same regime
    assert _cdf(hv_all, n_msgs * N)[-1] >= 0.999
    assert _cdf(ho_all, n_msgs * N)[-1] >= 0.999
    for e in (EV.DELIVER_MESSAGE, EV.DUPLICATE_MESSAGE, EV.SEND_RPC):
        v, ov = float(ev_v[e]), float(o.events[e])
        assert ov > 0
        assert abs(v - ov) / ov <= 0.10, f"event {e}: vec {v} oracle {ov}"


def test_uniform_topic_delays_equal_scalar_delay():
    """validation_delay_topic=(v,v,..) is bit-identical to
    validation_delay_rounds=v (the uniform pipeline is the special case)."""
    import jax

    topo = graph.random_connect(32, d=6, seed=2)
    subs = graph.subscribe_all(32, 2)
    net = Net.build(topo, subs)
    params = GossipSubParams()
    cfg_u = GossipSubConfig.build(params, validation_delay_rounds=2)
    cfg_t = GossipSubConfig.build(params, validation_delay_topic=(2, 2))
    assert cfg_t.validation_delay_rounds == 2
    sa = GossipSubState.init(net, 16, cfg_u, seed=4)
    sb = GossipSubState.init(net, 16, cfg_t, seed=4)
    step_a = make_gossipsub_step(cfg_u, net)
    step_b = make_gossipsub_step(cfg_t, net)
    rng = np.random.default_rng(0)
    for r in range(10):
        po = jnp.asarray(rng.integers(0, 32, size=2).astype(np.int32))
        pt = jnp.asarray(rng.integers(0, 2, size=2).astype(np.int32))
        pv = jnp.ones((2,), bool)
        sa = step_a(sa, po, pt, pv)
        sb = step_b(sb, po, pt, pv)
    for a, b in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            continue
        assert (np.asarray(a) == np.asarray(b)).all()
