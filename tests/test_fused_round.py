"""Fused Pallas data plane (ops/fused_round.py) vs the XLA path.

Runs full multi-round simulations through make_gossipsub_step twice — once
with PUBSUB_FUSED=1 (interpret mode on CPU) and once with the XLA path —
and asserts the complete state trees stay bit-identical. Both paths consume
the same PRNG streams (selection/gater randomness lives outside the
kernel), so exact equality is the contract, not a tolerance.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerGaterParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import fused_round as fr
from go_libp2p_pubsub_tpu.state import Net


# the fused Pallas kernels are opt-in (PUBSUB_FUSED=1) and off in
# production; their 13 ~20s parity suites run in the nightly tier
pytestmark = pytest.mark.slow

def _build(n=96, d=4, n_topics=1, msg_slots=32, score=True, flood_publish=False,
           gater=False, adversary=None, protocol=None, validation_capacity=0,
           fanout=False, do_px=False, count_events=True):
    topo = graph.ring_lattice(n, d=d)
    if n_topics == 1:
        subs = graph.subscribe_all(n, 1)
    else:
        subs = graph.subscribe_random(n, n_topics=n_topics, topics_per_peer=2,
                                      seed=3)
    net = Net.build(topo, subs, protocol=protocol)
    assert net.band_off is not None, "test topology must be banded"
    params = dataclasses.replace(
        GossipSubParams(), flood_publish=flood_publish, do_px=do_px
    )
    tp = TopicScoreParams(
        mesh_message_deliveries_weight=-0.2,
        mesh_message_deliveries_threshold=2.0,
        mesh_message_deliveries_activation=4.0,
        mesh_message_deliveries_window=2.0,
    )
    sp = PeerScoreParams(
        topics={t: tp for t in range(n_topics)},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    gp = PeerGaterParams() if gater else None
    cfg = GossipSubConfig.build(
        params, PeerScoreThresholds(), score_enabled=score, gater_params=gp,
        validation_capacity=validation_capacity,
    )
    if not fanout:
        cfg = dataclasses.replace(cfg, fanout_slots=0)
    cfg = dataclasses.replace(cfg, count_events=count_events)
    st = GossipSubState.init(net, msg_slots, cfg,
                             score_params=sp if score else None, seed=0)
    return net, cfg, sp, gp, st, adversary


def _run_both(n_rounds, invalid_every=0, **kw):
    net, cfg, sp, gp, st0, adversary = _build(**kw)
    n = net.n_peers
    rng = np.random.default_rng(0)
    po = rng.integers(0, n, size=(n_rounds, 4)).astype(np.int32)
    pt = rng.integers(0, net.n_topics, size=(n_rounds, 4)).astype(np.int32)
    pv = np.ones((n_rounds, 4), bool)
    if invalid_every:
        pv[::invalid_every, 0] = False

    results = []
    for fused in ("0", "1"):
        os.environ["PUBSUB_FUSED"] = fused
        try:
            step = make_gossipsub_step(
                cfg, net, score_params=sp if cfg.score_enabled else None,
                gater_params=gp, adversary_no_forward=adversary,
            )
            st = jax.tree.map(jnp.copy, st0)
            for r in range(n_rounds):
                st = step(st, jnp.asarray(po[r]), jnp.asarray(pt[r]),
                          jnp.asarray(pv[r]))
            results.append(jax.device_get(st))
        finally:
            del os.environ["PUBSUB_FUSED"]
    ref, fus = results
    _assert_trees_equal(ref, fus)
    return ref


def _assert_trees_equal(ref, fus):
    paths_r = jax.tree_util.tree_flatten_with_path(ref)[0]
    flat_f = jax.tree.leaves(fus)
    for (path, a), b in zip(paths_r, flat_f):
        if jnp.issubdtype(jnp.asarray(a).dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"mismatch in {jax.tree_util.keystr(path)}"
        )


def test_supported_detects_banded():
    topo = graph.ring_lattice(64, d=4)
    net = Net.build(topo, graph.subscribe_all(64, 1))
    assert fr.fused_supported(net.n_peers, net.band_off, net.max_degree)
    assert fr.pick_block(64, net.band_off) == 64


def test_parity_v11_scoring():
    st = _run_both(24, score=True)
    # sanity: traffic actually flowed
    assert int(np.asarray(st.core.events).sum()) > 0


def test_parity_v10_no_score():
    _run_both(20, score=False)


def test_parity_invalid_messages():
    _run_both(20, score=True, invalid_every=3)


def test_parity_flood_publish():
    _run_both(16, score=True, flood_publish=True)


def test_parity_multi_topic_fanout():
    _run_both(20, n_topics=8, fanout=True, msg_slots=32)


def test_parity_gater_and_throttle():
    _run_both(16, gater=True, validation_capacity=2)


def test_parity_adversary():
    rng = np.random.default_rng(1)
    adv = rng.random(96) < 0.25
    _run_both(20, adversary=adv)


def test_parity_floodsub_interop():
    proto = np.full(96, 2, np.int8)
    proto[::7] = 0  # floodsub-only peers
    _run_both(20, protocol=proto)


def test_parity_no_events():
    _run_both(12, count_events=False)


def test_parity_do_px_dormant_edges():
    # PX wire segment + edge_live-masked live set through the kernel
    n = 96
    topo = graph.ring_lattice(n, d=4)
    dormant = graph.dormant_edges(topo, 0.3, seed=5)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    params = dataclasses.replace(GossipSubParams(), do_px=True)
    tp = TopicScoreParams()
    sp = PeerScoreParams(topics={0: tp}, skip_app_specific=True,
                         behaviour_penalty_weight=-1.0,
                         behaviour_penalty_threshold=1.0,
                         behaviour_penalty_decay=0.9)
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(),
                                score_enabled=True)
    cfg = dataclasses.replace(cfg, fanout_slots=0)
    st0 = GossipSubState.init(net, 32, cfg, score_params=sp, seed=0,
                              dormant=dormant)
    rng = np.random.default_rng(2)
    po = rng.integers(0, n, size=(20, 4)).astype(np.int32)
    results = []
    for fused in ("0", "1"):
        os.environ["PUBSUB_FUSED"] = fused
        try:
            step = make_gossipsub_step(cfg, net, score_params=sp)
            st = jax.tree.map(jnp.copy, st0)
            for r in range(20):
                st = step(st, jnp.asarray(po[r]),
                          jnp.asarray(np.zeros(4, np.int32)),
                          jnp.asarray(np.ones(4, bool)))
            results.append(jax.device_get(st))
        finally:
            del os.environ["PUBSUB_FUSED"]
    _assert_trees_equal(results[0], results[1])


def test_parity_dynamic_peers_churn():
    net, cfg, sp, gp, st0, _ = _build()
    n = net.n_peers
    rng = np.random.default_rng(4)
    po = rng.integers(0, n, size=(20, 4)).astype(np.int32)
    up = np.ones((20, n), bool)
    up[8:14, ::9] = False  # a churn window taking ~11% of peers down
    results = []
    for fused in ("0", "1"):
        os.environ["PUBSUB_FUSED"] = fused
        try:
            step = make_gossipsub_step(cfg, net, score_params=sp,
                                       dynamic_peers=True)
            st = jax.tree.map(jnp.copy, st0)
            for r in range(20):
                st = step(st, jnp.asarray(po[r]),
                          jnp.asarray(np.zeros(4, np.int32)),
                          jnp.asarray(np.ones(4, bool)),
                          jnp.asarray(up[r]))
            results.append(jax.device_get(st))
        finally:
            del os.environ["PUBSUB_FUSED"]
    _assert_trees_equal(results[0], results[1])


def test_parity_heartbeat_every_3():
    net, cfg, sp, gp, st0, _ = _build()
    cfg = dataclasses.replace(cfg, heartbeat_every=3)
    n = net.n_peers
    po, pt, pv = no_publish()
    results = []
    for fused in ("0", "1"):
        os.environ["PUBSUB_FUSED"] = fused
        try:
            step = make_gossipsub_step(cfg, net, score_params=sp)
            st = jax.tree.map(jnp.copy, st0)
            po2 = jnp.asarray(np.array([1, -1, -1, -1], np.int32))
            pt2 = jnp.asarray(np.zeros(4, np.int32))
            pv2 = jnp.asarray(np.ones(4, bool))
            for r in range(9):
                st = step(st, po2, pt2, pv2)
            results.append(jax.device_get(st))
        finally:
            del os.environ["PUBSUB_FUSED"]
    _assert_trees_equal(results[0], results[1])
