"""Sensitivity of two documented engine approximations, measured at
adversarial rates (round-1 review item):

1. IWANT-promise granularity: the engine keeps ONE promise slot per edge
   (promise_mid/expire), the reference one promise per IWANT *batch* with
   several outstanding per peer (gossip_tracer.go:48-75). Under an
   advertise-but-never-serve attacker the per-edge model can only break
   ~1 promise per followup window; the per-batch model breaks up to one
   per round. These tests measure both machines' P7 response and assert
   the behavioural outcome — attacker edges driven below the gossip
   threshold and cut off from IWANT traffic — is reached by both.

2. IHAVE ask truncation: when the MaxIHaveLength budget binds, the
   engine keeps the lowest message slots, the reference shuffles then
   truncates (gossipsub.go:655-667). With the budget forced to bind hard
   the propagation CDFs of the two policies must stay within the 2%
   parity envelope.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.oracle.gossipsub import OracleGossipSub
from go_libp2p_pubsub_tpu.state import Net, hops

N = 96
DEG = 6


def _score_params():
    return PeerScoreParams(
        topics={0: TopicScoreParams(
            mesh_message_deliveries_weight=0.0,
            mesh_failure_penalty_weight=0.0,
        )},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
    )


def _build(adversary, thresholds=None, d=DEG, small_mesh=False):
    topo = graph.random_connect(N, d=d, seed=3)
    subs = graph.subscribe_all(N, 1)
    net = Net.build(topo, subs)
    sp = _score_params()
    thr = thresholds or PeerScoreThresholds(
        gossip_threshold=-2.0, publish_threshold=-5.0,
        graylist_threshold=-10.0,
    )
    cfg = GossipSubConfig.build(GossipSubParams(), thr, score_enabled=True)
    cfg = dataclasses.replace(cfg, fanout_slots=0)
    if small_mesh:
        # meshes well below the connection degree, so non-mesh edges exist
        # for gossip and mesh capture by attackers is possible
        cfg = dataclasses.replace(cfg, D=2, Dlo=1, Dhi=3, Dscore=1, Dout=1,
                                  Dlazy=4, gossip_factor=0.5)
    st = GossipSubState.init(net, 64, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               adversary_no_forward=adversary)
    return topo, subs, net, cfg, sp, st, step


def test_promise_granularity_p7_both_machines_cut_attackers():
    """Advertise-but-never-serve attackers: both promise models must
    accumulate P7 on attacker edges; the magnitudes may differ (the
    documented granularity gap) but the protective outcome must not.

    Promises only break when the message never arrives some other way
    within the followup window, so the scenario strands honest peers
    behind a majority of attackers on a sparse graph — gossip to an
    attacker is then a dead end and the promise expires."""
    rng = np.random.default_rng(0)
    adversary = rng.random(N) < 0.6
    topo, subs, net, cfg, sp, st, step = _build(adversary, d=3,
                                                small_mesh=True)

    # steady publish load so gossip (IHAVE from attackers too — they
    # receive and advertise, but never serve IWANT) keeps flowing
    sched = np.flatnonzero(~adversary)[
        rng.integers(0, (~adversary).sum(), size=(40, 2))
    ].astype(np.int32)
    pt = jnp.zeros((2,), jnp.int32)
    pv = jnp.ones((2,), bool)
    for _ in range(10):
        st = step(st, *no_publish(2))
    for r in range(40):
        st = step(st, jnp.asarray(sched[r]), pt, pv)

    bp = np.asarray(st.score.bp)
    nbr = np.asarray(net.nbr)
    ok = np.asarray(net.nbr_ok)
    adv_e = adversary[np.clip(nbr, 0, None)] & ok
    engine_bp_adv = bp[adv_e].mean()
    engine_bp_hon = bp[~adv_e & ok].mean()

    o = OracleGossipSub(
        topo, subs, cfg, msg_slots=64, seed=7, score_params=sp,
        adversary=set(np.flatnonzero(adversary).tolist()),
    )
    for _ in range(10):
        o.step()
    for r in range(40):
        o.step([(int(p), 0, True) for p in sched[r]])
    o_adv, o_hon = [], []
    for i in range(N):
        for k, s, r in o._edges(i):
            (o_adv if s in o.adversary else o_hon).append(
                o.oscore[i].bp.get(k, 0.0)
            )
    oracle_bp_adv, oracle_bp_hon = np.mean(o_adv), np.mean(o_hon)

    # P7 pressure lands on attacker edges in both machines; honest edges
    # stay (essentially) clean
    assert engine_bp_adv > 0.1, f"engine P7 never fired: {engine_bp_adv}"
    assert oracle_bp_adv > 0.1, f"oracle P7 never fired: {oracle_bp_adv}"
    assert engine_bp_hon < 0.05 and oracle_bp_hon < 0.05

    # the documented granularity gap: per-batch (oracle) accrues at most a
    # small multiple of per-edge (engine) at these rates — record it
    ratio = oracle_bp_adv / engine_bp_adv
    print(f"P7 granularity ratio (per-batch / per-edge): {ratio:.2f} "
          f"(engine {engine_bp_adv:.3f}, oracle {oracle_bp_adv:.3f})")
    assert 0.2 < ratio < 5.0


def test_ihave_truncation_policy_cdf_within_2pct():
    """Lowest-slot (engine) vs shuffled (oracle) IHAVE truncation with the
    MaxIHaveLength budget forced to bind: propagation CDFs stay within
    the parity envelope, so the approximation is distributionally
    insensitive even at the cap."""
    topo = graph.random_connect(N, d=4, seed=5)  # sparse: gossip matters
    subs = graph.subscribe_all(N, 1)
    net = Net.build(topo, subs)
    params = GossipSubParams()
    cfg = GossipSubConfig.build(params)
    # budget binds hard: at most 4 asks per heartbeat per edge while the
    # window advertises up to 64 slots
    cfg = dataclasses.replace(cfg, fanout_slots=0, max_ihave_length=4,
                              Dlazy=8, gossip_factor=0.5)
    st = GossipSubState.init(net, 64, cfg, seed=0)
    step = make_gossipsub_step(cfg, net)

    rng = np.random.default_rng(1)
    sched = rng.integers(0, N, size=(16, 2)).astype(np.int32)
    pt = jnp.zeros((2,), jnp.int32)
    pv = jnp.ones((2,), bool)
    for _ in range(16):
        st = step(st, *no_publish(2))
    for r in range(16):
        st = step(st, jnp.asarray(sched[r]), pt, pv)
    for _ in range(14):
        st = step(st, *no_publish(2))
    h = np.asarray(hops(st.core.msgs, st.core.dlv))
    hv = [int(x) for x in h[h >= 0]]

    o = OracleGossipSub(topo, subs, cfg, msg_slots=64, seed=7)
    for _ in range(16):
        o.step()
    for r in range(16):
        o.step([(int(p), 0, True) for p in sched[r]])
    for _ in range(14):
        o.step()
    ho = list(o.hops().values())

    MAX_H = 20
    total = 16 * 2 * N

    def cdf(hs):
        hist = np.zeros(MAX_H + 1)
        for x in hs:
            hist[min(x, MAX_H)] += 1
        return np.cumsum(hist) / total

    cv, co = cdf(hv), cdf(ho)
    sup = float(np.max(np.abs(cv - co)))
    print(f"IHAVE truncation CDF sup-distance at binding cap: {sup:.4f}")
    assert sup <= 0.02, f"truncation policy diverges: {sup:.4f}\n{cv}\n{co}"
