"""Score-lift parity + recompile-free sentinels (round 16, docs/
DESIGN.md §16): the lifted engines must reproduce the static builds
BIT-EXACTLY at matched values on all four engines (phase at r in
{1, 8}), one compiled program must serve >= 2 distinct weight sets,
the stacked-plane ensemble sweep must equal its per-plane runs, and
the params fingerprint block must round-trip."""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.checkpoint import is_prng_key
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreThresholds,
)
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
    make_gossipsub_phase_step,
)
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params
from go_libp2p_pubsub_tpu.score.params import ScoreParams
from go_libp2p_pubsub_tpu.state import Net, SimState

N, M, K_D = 96, 64, 8


def build_net():
    return Net.build(graph.ring_lattice(N, d=K_D),
                     graph.subscribe_all(N, 1))


def build_cfg(heartbeat_every=1):
    # the sybil parameterization: every score plane live (P3 deficit,
    # P4, P7), so the phase engine's static elision keeps all
    # attribution planes on BOTH sides of the parity compare
    _tp, sp = bench_score_params("sybil", 1)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
        heartbeat_every=heartbeat_every,
    )
    return cfg, sp


def assert_trees_equal(a, b, context=""):
    la = jax.tree_util.tree_flatten_with_path(a)[0]
    lb = {jax.tree_util.keystr(p): leaf
          for p, leaf in jax.tree_util.tree_flatten_with_path(b)[0]}
    assert len(la) == len(lb), f"{context}: leaf count differs"
    for p, x in la:
        k = jax.tree_util.keystr(p)
        y = lb[k]
        if is_prng_key(x):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"{context}: leaf {k}")


def pub(i, r=None, width=4):
    po = np.full((width,), -1, np.int32)
    po[0] = i % N
    args = [po, np.zeros((width,), np.int32), np.ones((width,), bool)]
    if r:
        args = [np.broadcast_to(a, (r,) + a.shape).copy() for a in args]
    return tuple(jnp.asarray(a) for a in args)


def second_plane():
    """A plane moving EVERY lifted surface away from the bench values."""
    tp_a, sp_a = bench_score_params("sybil", 1)
    tp_b = dc.replace(
        tp_a, first_message_deliveries_weight=2.0,
        mesh_message_deliveries_weight=-0.25, time_in_mesh_weight=0.5,
        invalid_message_deliveries_weight=-0.5,
    )
    sp_b = dc.replace(sp_a, topics={0: tp_b}, behaviour_penalty_weight=-2.0,
                      topic_score_cap=50.0)
    thr_b = PeerScoreThresholds(
        gossip_threshold=-4.0, publish_threshold=-20.0,
        graylist_threshold=-40.0, accept_px_threshold=5.0,
        opportunistic_graft_threshold=10.0,
    )
    return ScoreParams.build(sp_b, thr_b, 1)


# ---------------------------------------------------------------------------
# bit-exact parity at matched values, all four engines


def _gossipsub_parity(rounds=12):
    net = build_net()
    cfg, sp = build_cfg()
    plane = ScoreParams.from_config(cfg, sp, 1)
    st_s = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    st_l = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    step_s = make_gossipsub_step(cfg, net, score_params=sp)
    step_l = make_gossipsub_step(cfg, net, score_params=sp,
                                 lift_scores=True)
    for i in range(rounds):
        st_s = step_s(st_s, *pub(i))
        st_l = step_l(st_l, *pub(i), plane)
    return st_s, st_l, step_l, plane


def test_gossipsub_lifted_parity():
    st_s, st_l, _, _ = _gossipsub_parity()
    assert_trees_equal(st_s, st_l, "gossipsub per-round lifted-vs-static")


@pytest.mark.parametrize(
    "r", [1, pytest.param(8, marks=pytest.mark.slow)])
def test_phase_lifted_parity(r):
    net = build_net()
    cfg, sp = build_cfg(heartbeat_every=max(r, 1))
    plane = ScoreParams.from_config(cfg, sp, 1)
    st_s = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    st_l = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    ph_s = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
    ph_l = make_gossipsub_phase_step(cfg, net, r, score_params=sp,
                                     lift_scores=True)
    for i in range(3):
        st_s = ph_s(st_s, *pub(i, r), do_heartbeat=True)
        st_l = ph_l(st_l, *pub(i, r), plane, do_heartbeat=True)
    assert_trees_equal(st_s, st_l, f"phase r={r} lifted-vs-static")


def test_floodsub_plane_seam_parity():
    net = build_net()
    st_a = SimState.init(N, M, k=net.max_degree)
    st_b = SimState.init(N, M, k=net.max_degree)
    plane = second_plane()
    for i in range(6):
        st_a = floodsub_step(net, st_a, *pub(i))
        st_b = floodsub_step(net, st_b, *pub(i), score_plane=plane)
    assert_trees_equal(st_a, st_b, "floodsub plane seam")


def test_randomsub_plane_seam_parity():
    net = build_net()
    st_a = SimState.init(N, M, k=net.max_degree)
    st_b = SimState.init(N, M, k=net.max_degree)
    plane = second_plane()
    step = make_randomsub_step(net)
    step_l = make_randomsub_step(net, lift_scores=True)
    for i in range(6):
        st_a = step(st_a, *pub(i))
        st_b = step_l(st_b, *pub(i), plane)
    assert_trees_equal(st_a, st_b, "randomsub plane seam")


# ---------------------------------------------------------------------------
# the recompile-free sentinel: one compile across >= 2 weight sets


def test_one_compile_across_weight_sets():
    _, st_l, step_l, plane = _gossipsub_parity(rounds=2)
    plane_b = second_plane()
    before = step_l._cache_size()
    st = st_l
    for i in range(4):
        st = step_l(st, *pub(i), plane if i % 2 == 0 else plane_b)
    assert step_l._cache_size() == before, (
        "a weight-set change recompiled the lifted step"
    )
    assert step_l._cache_size() == 1


def test_lifted_values_actually_differ():
    # the A/B sentinel must not pass because the plane is ignored:
    # different thresholds/weights must CHANGE the trajectory
    net = build_net()
    cfg, sp = build_cfg()
    plane_a = ScoreParams.from_config(cfg, sp, 1)
    plane_b = second_plane()
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               lift_scores=True)
    st_a = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    st_b = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    for i in range(10):
        st_a = step(st_a, *pub(i), plane_a)
        st_b = step(st_b, *pub(i), plane_b)
    # P1 weight differs (1.0 vs 0.5): held scores must diverge
    assert not np.array_equal(np.asarray(st_a.scores),
                              np.asarray(st_b.scores))


# ---------------------------------------------------------------------------
# configs×sims: a stacked plane axis sweeps weight sets in ONE program


def test_stacked_plane_ensemble_sweep():
    from go_libp2p_pubsub_tpu.ensemble import batch as ebatch

    net = build_net()
    cfg, sp = build_cfg()
    plane_a = ScoreParams.from_config(cfg, sp, 1)
    plane_b = second_plane()
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               lift_scores=True)
    base = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    base_key = base.core.key
    states = ebatch.batch_states(base, 2)
    planes = ebatch.stack_planes([plane_a, plane_b])
    ens = ebatch.lift_step(step)
    for i in range(6):
        args = tuple(ebatch.tile(a, 2) for a in pub(i))
        states = ens(states, *args, planes)
    assert ens._cache_size() == 1
    # row i == the single-sim run with plane i (threefry vmaps
    # bit-exactly — the ensemble plane's standing parity contract)
    for idx, plane in ((0, plane_a), (1, plane_b)):
        st = ebatch.with_sim_key(
            GossipSubState.init(net, M, cfg, score_params=sp, seed=0),
            base_key, idx)
        for i in range(6):
            st = step(st, *pub(i), plane)
        assert_trees_equal(ebatch.unbatch(states, idx), st,
                           f"sweep row {idx}")


def test_lift_floodsub_plane_slot():
    # the uniform trailing-plane slot for configs×sims sweeps: the
    # lift_floodsub adapter routes the last positional to floodsub's
    # keyword-only score_plane seam (inert — parity vs the plain lift)
    from go_libp2p_pubsub_tpu.ensemble import batch as ebatch

    net = build_net()
    base = SimState.init(N, M, k=net.max_degree)
    states_a = ebatch.batch_states(base, 2)
    states_b = ebatch.batch_states(base, 2)
    planes = ebatch.stack_planes([second_plane(), second_plane()])
    ens_plain = ebatch.lift_floodsub(net)
    ens_lift = ebatch.lift_floodsub(net, lift_scores=True)
    for i in range(4):
        args = tuple(ebatch.tile(a, 2) for a in pub(i))
        states_a = ens_plain(states_a, *args)
        states_b = ens_lift(states_b, *args, planes)
    assert ens_lift._cache_size() == 1
    assert_trees_equal(states_a, states_b, "lift_floodsub plane slot")


def test_stack_planes_rejects_static_field_mismatch():
    from go_libp2p_pubsub_tpu.ensemble import batch as ebatch

    _tp, sp = bench_score_params("sybil", 1)
    pa = ScoreParams.build(sp, PeerScoreThresholds(), 1)
    sp_b = dc.replace(sp, app_specific_weight=1.0, skip_app_specific=True)
    pb = ScoreParams.build(sp_b, PeerScoreThresholds(), 1)
    with pytest.raises(ValueError, match="app_specific_weight"):
        ebatch.stack_planes([pa, pb])


# ---------------------------------------------------------------------------
# scanned windows: the plane rides make_window/make_scan `consts`


def test_scanned_window_lifted_parity():
    from go_libp2p_pubsub_tpu.driver import make_scan

    net = build_net()
    cfg, sp = build_cfg()
    plane = ScoreParams.from_config(cfg, sp, 1)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               lift_scores=True)
    rounds = 8
    po = np.full((rounds, 4), -1, np.int32)
    po[:, 0] = np.arange(rounds) % N
    pt = np.zeros((rounds, 4), np.int32)
    pv = np.ones((rounds, 4), bool)
    po_j, pt_j, pv_j = jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)

    st_loop = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    for i in range(rounds):
        st_loop = step(st_loop, jnp.asarray(po[i]), jnp.asarray(pt[i]),
                       jnp.asarray(pv[i]), plane)

    scan = make_scan(step, heartbeat_every=1, rounds_per_phase=1,
                     static_heartbeat=False)
    st_scan = scan(GossipSubState.init(net, M, cfg, score_params=sp, seed=0),
                   po_j, pt_j, pv_j, None, (plane,))
    assert_trees_equal(st_loop, st_scan, "scanned lifted window")

    # the SAME compiled window serves a different weight set
    before = scan._cache_size()
    scan(GossipSubState.init(net, M, cfg, score_params=sp, seed=0),
         po_j, pt_j, pv_j, None, (second_plane(),))
    assert scan._cache_size() == before == 1


# ---------------------------------------------------------------------------
# artifact self-description


def test_params_fingerprint_round_trip():
    from go_libp2p_pubsub_tpu.perf import artifacts, sweep
    from go_libp2p_pubsub_tpu.score.params import LIFTED_FIELD_NAMES

    fp = sweep.workload_fingerprint("default", 1000, 64, 1, 1,
                                    lift_scores=True)
    assert fp["params"]["lifted"] is True
    assert fp["params"]["traced"] == sorted(LIFTED_FIELD_NAMES)
    rec = artifacts.record_from_line({
        "metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 0.0,
        "schema": 3, "fingerprint": fp,
    })
    assert rec.params_lifted
    assert rec.params["recorded"] is True
    # static builds record the split explicitly
    fp_s = sweep.workload_fingerprint("default", 1000, 64, 1, 1)
    assert fp_s["params"] == {"recorded": True, "lifted": False,
                              "traced": []}
    # legacy lines read back the PARAMS_STATIC sentinel
    legacy = artifacts.record_from_line({
        "metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 0.0,
    })
    assert legacy.params == artifacts.PARAMS_STATIC
    assert not legacy.params_lifted
