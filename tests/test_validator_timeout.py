"""Validator timeout (WithValidatorTimeout, validation.go:522-529).

An async validator whose verdict cannot land within the timeout has its
context expire: the message resolves to IGNORE — dropped without the P4
sender penalty, exactly like an explicit ValidationIgnore. The knob
composes with per-topic validation delays: a topic whose effective
pipeline delay exceeds the timeout never produces an Accept; faster
topics are untouched.
"""

from __future__ import annotations

import pytest

from go_libp2p_pubsub_tpu.models.gossipsub import GossipSubConfig

try:  # the API layer needs the crypto dep; config-layer tests don't
    from go_libp2p_pubsub_tpu import api
except ModuleNotFoundError:  # pragma: no cover — crippled sandbox images
    api = None

needs_api = pytest.mark.skipif(api is None, reason="api needs cryptography")


# ---------------------------------------------------------------------------
# config layer: per-topic composition


def test_config_composes_with_per_topic_delays():
    cfg = GossipSubConfig.build(
        validation_delay_topic=(1, 3), validator_timeout_rounds=2)
    assert not cfg.validation_timed_out(0)  # delay 1 <= timeout 2
    assert cfg.validation_timed_out(1)      # delay 3 > timeout 2


def test_config_uniform_delay_and_disabled():
    cfg = GossipSubConfig.build(
        validation_delay_rounds=3, validator_timeout_rounds=2)
    assert cfg.validation_timed_out(0)
    # timeout 0 = disabled, whatever the delay
    cfg = GossipSubConfig.build(
        validation_delay_rounds=9, validator_timeout_rounds=0)
    assert not cfg.validation_timed_out(0)
    with pytest.raises(ValueError):
        GossipSubConfig.build(validator_timeout_rounds=-1)


# ---------------------------------------------------------------------------
# API layer: end-to-end ignore semantics


def _net(**kw):
    net = api.Network(**kw)
    nodes = net.add_nodes(6)
    net.connect_all()
    subs = [nd.join("t").subscribe() for nd in nodes]
    return net, nodes, subs


@needs_api
def test_timed_out_async_validator_ignores():
    """delay 3 > timeout 2: the async verdict expires. Local publishes
    surface the ignore as ValidationError (the reference returns the
    validation error to Publish); the validator itself still ran."""
    net, nodes, subs = _net(validation_delay_rounds=3,
                            validator_timeout_rounds=2)
    calls = []
    nodes[0].register_topic_validator(
        "t", lambda pid, msg: calls.append(pid) or True)
    net.start()
    with pytest.raises(api.ValidationError, match="timed out"):
        nodes[1].topics["t"].publish(b"never lands")
    assert calls, "the validator goroutine still runs; only its verdict expires"
    net.run(12)
    assert all(s.next() is None for s in subs)


@pytest.mark.slow
@needs_api
def test_fast_pipeline_unaffected_by_timeout():
    """delay 2 <= timeout 2: verdicts land in time; deliveries complete
    (late, per the pipeline) exactly as without the knob."""
    net, nodes, subs = _net(validation_delay_rounds=2,
                            validator_timeout_rounds=2)
    nodes[0].register_topic_validator("t", lambda pid, msg: True)
    net.start()
    nodes[1].topics["t"].publish(b"lands")
    net.run(12)
    # every node delivers: 5 remote + the publisher's local copy
    got = sum(1 for s in subs if s.next() is not None)
    assert got == len(nodes)


@needs_api
def test_inline_validators_never_time_out():
    """WithValidatorTimeout bounds ASYNC validators only — inline ones
    run synchronously on the caller (validation.go:305-316)."""
    net, nodes, subs = _net(validation_delay_rounds=3,
                            validator_timeout_rounds=1)
    nodes[0].register_topic_validator("t", lambda pid, msg: True, inline=True)
    net.start()
    nodes[1].topics["t"].publish(b"inline ok")
    net.run(14)
    # every node delivers (incl. the publisher's local copy)
    assert sum(1 for s in subs if s.next() is not None) == len(nodes)


@needs_api
def test_timeout_applies_below_router_floodsub():
    """The validation pipeline sits below the router; the timeout knob
    rides with it on floodsub too (uniform delay at the API layer)."""
    net = api.Network(router="floodsub", validation_delay_rounds=2,
                      validator_timeout_rounds=1)
    nodes = net.add_nodes(4)
    net.connect_all()
    subs = [nd.join("t").subscribe() for nd in nodes]
    nodes[0].register_topic_validator("t", lambda pid, msg: True)
    net.start()
    with pytest.raises(api.ValidationError, match="timed out"):
        nodes[1].topics["t"].publish(b"x")
    net.run(8)
    assert all(s.next() is None for s in subs)
