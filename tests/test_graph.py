"""Topology builder invariants: symmetry, reverse-edge index, outbound
direction, subscription slot compression."""

import numpy as np

from go_libp2p_pubsub_tpu import graph


def _check_topology(topo: graph.Topology):
    n, K = topo.nbr.shape
    for i in range(n):
        for k in range(K):
            j = topo.nbr[i, k]
            if j < 0:
                assert not topo.nbr_ok[i, k]
                continue
            assert topo.nbr_ok[i, k]
            # reverse edge points back
            r = topo.rev[i, k]
            assert topo.nbr[j, r] == i
            # exactly one side is outbound (the dialer)
            assert topo.outbound[i, k] != topo.outbound[j, r]


def test_connect_all():
    topo = graph.connect_all(8)
    _check_topology(topo)
    assert (topo.degree == 7).all()


def test_random_connect():
    topo = graph.random_connect(50, d=3, seed=7)
    _check_topology(topo)
    assert (topo.degree >= 3).all()  # everyone dialed 3


def test_ring_lattice():
    topo = graph.ring_lattice(10, d=2)
    _check_topology(topo)
    assert (topo.degree == 4).all()


def test_subscribe_all():
    subs = graph.subscribe_all(5, 3)
    assert subs.subscribed.all()
    assert (subs.my_topics == np.arange(3)[None, :]).all()
    assert (subs.slot_of == np.arange(3)[None, :]).all()


def test_subscribe_random_slots_consistent():
    subs = graph.subscribe_random(40, n_topics=16, topics_per_peer=3, seed=1)
    assert (subs.subscribed.sum(axis=1) == 3).all()
    for i in range(40):
        for s in range(subs.max_slots):
            t = subs.my_topics[i, s]
            if t >= 0:
                assert subs.subscribed[i, t]
                assert subs.slot_of[i, t] == s
        for t in range(16):
            if subs.subscribed[i, t]:
                assert subs.my_topics[i, subs.slot_of[i, t]] == t
            else:
                assert subs.slot_of[i, t] == -1


def test_ip_groups_with_sybils():
    g = graph.ip_groups_with_sybils(100, n_sybil_groups=2, sybil_frac=0.2, seed=0)
    honest = g[:80]
    sybil = g[80:]
    assert len(np.unique(honest)) == 80
    assert len(np.unique(sybil)) <= 2
