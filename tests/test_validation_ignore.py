"""ValidationIgnore verdicts + the reject-reason taxonomy.

Reference semantics (validation.go:40-52; score.go:721-786): an ignored
message is neither delivered nor forwarded, but — unlike a rejected one —
its senders take no P4 invalid-message penalty; the gater counts it on the
`ignore` stat (peer_gater.go:427-429); the trace reason is "validation
ignored" (tracer.go:38).
"""

from __future__ import annotations

import pytest

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import api, graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerGaterParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.state import (
    VERDICT_ACCEPT,
    VERDICT_IGNORE,
    VERDICT_REJECT,
    Net,
)
from go_libp2p_pubsub_tpu.trace import sinks
from go_libp2p_pubsub_tpu.trace.events import EV


def _build(n=48, gater=False, invalid_weight=-1.0):
    topo = graph.ring_lattice(n, d=4)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    tp = TopicScoreParams(
        invalid_message_deliveries_weight=invalid_weight,
        invalid_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
    )
    sp = PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    gp = PeerGaterParams() if gater else None
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
        gater_params=gp,
    )
    cfg = dataclasses.replace(cfg, fanout_slots=0)
    st = GossipSubState.init(net, 32, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp, gater_params=gp)
    return net, cfg, sp, st, step


def _run(step, st, verdict, rounds=10, origin=0):
    po = jnp.asarray(np.array([origin, -1, -1, -1], np.int32))
    pt = jnp.asarray(np.zeros(4, np.int32))
    pv = jnp.asarray(np.full(4, verdict, np.int8))
    for _ in range(rounds):
        st = step(st, po, pt, pv)
        po = jnp.asarray(np.array([-1, -1, -1, -1], np.int32))
    return st


def test_ignored_messages_move_no_score():
    net, cfg, sp, st0, step = _build()
    st_ign = _run(step, jax.tree.map(jnp.copy, st0), VERDICT_IGNORE)
    st_rej = _run(step, jax.tree.map(jnp.copy, st0), VERDICT_REJECT)

    imd_ign = np.asarray(st_ign.score.imd)
    imd_rej = np.asarray(st_rej.score.imd)
    # rejected copies penalize every delivering edge; ignored move nothing
    assert imd_rej.sum() > 0
    assert imd_ign.sum() == 0
    # and the P4 term shows in the composed scores
    assert float(np.asarray(st_rej.scores).min()) < 0
    assert float(np.asarray(st_ign.scores).min()) >= 0


def test_ignored_not_forwarded_not_delivered():
    net, cfg, sp, st0, step = _build()
    st = _run(step, st0, VERDICT_IGNORE, rounds=8)
    # the message propagated nowhere beyond direct neighbors of the origin:
    # receivers mark it seen but never forward (fwd stays empty), so only
    # mesh neighbors of the origin ever saw it
    have = np.asarray(st.core.dlv.have)
    seen_peers = (have != 0).any(axis=1).sum()
    assert seen_peers <= 1 + net.max_degree  # origin + its direct mesh
    assert np.asarray(st.core.dlv.fwd).sum() == 0
    # REJECT was traced for the receipts (events counted), DELIVER was not
    ev = np.asarray(st.core.events)
    assert ev[EV.REJECT_MESSAGE] > 0
    assert ev[EV.DELIVER_MESSAGE] == 0


@pytest.mark.slow
def test_gater_counts_ignore_separately():
    net, cfg, sp, st0, step = _build(gater=True)
    st_ign = _run(step, jax.tree.map(jnp.copy, st0), VERDICT_IGNORE)
    st_rej = _run(step, jax.tree.map(jnp.copy, st0), VERDICT_REJECT)
    assert np.asarray(st_ign.gater.ignore).sum() > 0
    assert np.asarray(st_ign.gater.reject).sum() == 0
    assert np.asarray(st_rej.gater.reject).sum() > 0
    assert np.asarray(st_rej.gater.ignore).sum() == 0


@pytest.mark.slow
def test_trace_reason_taxonomy(tmp_path):
    # drive through the api with a validator returning IGNORE, and check
    # the traced REJECT events carry "validation ignored"
    path = str(tmp_path / "trace.json")
    net = api.Network(trace_sinks=[sinks.JSONTracer(path)])
    nodes = net.add_nodes(16)
    net.dense_connect(d=6, seed=0)
    [nd.join("t") for nd in nodes]
    nodes[0].register_topic_validator(
        "t", lambda pid, msg: api.ValidationResult.IGNORE
        if msg.data.startswith(b"ign") else True,
    )
    net.start()
    net.run(2)
    try:
        nodes[1].topics["t"].publish(b"ignore-me")
        raised = False
    except api.ValidationError:
        raised = True
    # local publish of an ignored message errors out like PushLocal
    assert raised
    # a remote-style injection: publish valid traffic so the trace has both
    nodes[2].topics["t"].publish(b"ok")
    net.run(6)
    net.stop()
    import json

    reasons = []
    with open(path) as f:
        for line in f:
            ev = json.loads(line)
            if "rejectMessage" in ev:
                reasons.append(ev["rejectMessage"].get("reason"))
    # nothing rejected in this honest run; now check the engine-level
    # reason via a direct verdict injection with a session
    assert all(r == "validation failed" for r in reasons)


def test_trace_reason_ignored_via_session(tmp_path):
    from go_libp2p_pubsub_tpu.trace.drain import TraceSession, snapshot

    net, cfg, sp, st, step = _build(n=24)
    path = str(tmp_path / "t.json")
    sess = TraceSession(net, [sinks.JSONTracer(path)])
    sess.emit_init(snapshot(st))
    po = np.array([0, -1, -1, -1], np.int32)
    pt = np.zeros(4, np.int32)
    for r in range(6):
        pv = np.full(4, VERDICT_IGNORE if r == 0 else VERDICT_ACCEPT, np.int8)
        if r > 0:
            po = np.array([r % 24, -1, -1, -1], np.int32)
        prev = snapshot(st)
        st = step(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))
        sess.observe(prev, snapshot(st), po, pt, pv)
    sess.close(snapshot(st))

    import json

    reasons = set()
    with open(path) as f:
        for line in f:
            ev = json.loads(line)
            if "rejectMessage" in ev:
                reasons.add(ev["rejectMessage"].get("reason"))
    assert "validation ignored" in reasons


def test_bool_verdicts_still_work():
    net, cfg, sp, st0, step = _build()
    po = jnp.asarray(np.array([0, -1, -1, -1], np.int32))
    pt = jnp.asarray(np.zeros(4, np.int32))
    pv = jnp.asarray(np.ones(4, bool))
    st = step(st0, po, pt, pv)
    assert int(st.core.tick) == 1
