"""perf/ subsystem: schema-v2 artifacts, the projection engine, the
profiler's parsing layers, and the workload fingerprint.

The projection test is the load-bearing one (ISSUE round 6): the v5e-8
feasibility number that BASELINE.md rounds 3-5 computed by hand must
reproduce from code + committed artifacts, so future rounds change it by
changing inputs, not prose.
"""

from __future__ import annotations

import glob
import json
import os

import pytest

from go_libp2p_pubsub_tpu.perf import artifacts, profile, projection, sweep

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# artifacts: schema v2 + legacy readers


def test_v2_readers_parse_all_committed_bench_artifacts():
    """Every in-tree BENCH_r0*.json (v1 driver wrappers rounds 1-5,
    schema-v3 scanned-window lines from round 15 on) must normalize
    through the reader — the artifact trajectory is the regression
    gate's ground truth."""
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    assert len(paths) >= 5, paths
    recs = [artifacts.load_bench_artifact(p) for p in paths]
    for rec in recs:
        assert rec.value > 0
        # rounds 1-6 are the gossipsub headline; round 7 (round-18
        # topo-smoke) is the power-law floodsub A/B cell
        assert rec.metric.startswith(("gossipsub_v1.1_", "floodsub_"))
        assert rec.schema in (1, 2, 3)
        assert rec.config in ("default", "topo_powerlaw")
    # rounds 1-5: the 100k headline; round 6+ record their own N in the
    # fingerprint (r06 is the CPU-container scanned-window artifact)
    assert all(r.n_peers == 100_000 for r in recs[:5])
    r06_paths = [p for p, r in zip(paths, recs) if r.round_index == 6]
    if r06_paths:
        variants = artifacts.load_bench_variants(r06_paths[0])
        assert variants["parsed"].scanned is True
        assert variants["parsed"].edge_layout == "dense"  # the headline
        # the dense-vs-csr tradeoff is a committed, READABLE pair: the
        # csr cell must parse with a live value at the same shape
        csr = variants["parsed_csr"]
        assert csr.edge_layout == "csr" and csr.value > 0
        assert csr.n_peers == variants["parsed"].n_peers
        assert csr.rounds_per_phase == variants["parsed"].rounds_per_phase
    r07_paths = [p for p, r in zip(paths, recs) if r.round_index == 7]
    if r07_paths:
        variants = artifacts.load_bench_variants(r07_paths[0])
        # round 18: the headline IS the csr cell (it wins here), the
        # dense sibling stays reader-visible at the same shape
        assert variants["parsed"].edge_layout == "csr"
        assert variants["parsed"].topology_recorded
        dense = variants["parsed_dense"]
        assert dense.edge_layout == "dense" and dense.value > 0
        assert variants["parsed"].value > dense.value
    # the metric-name fallbacks recover cadence for v1 lines
    assert [r.rounds_per_phase for r in recs[:5]] == [1, 1, 1, 8, 8]
    # trajectory ordering by driver round
    assert [r.round_index for r in recs[:5]] == [1, 2, 3, 4, 5]


def test_v2_round_trip_is_lossless():
    fp = sweep.workload_fingerprint("default", 100_000, 64, 8, 8,
                                    seg_rounds=1600, unroll=16)
    rec = artifacts.BenchRecord(
        metric="gossipsub_v1.1_delivery_rounds_per_sec_n100000_phase8",
        value=1835.84, unit="delivery-rounds/s", vs_baseline=0.1836,
        schema=2, fingerprint=fp,
        extras={"heartbeats_per_sec": 229.48, "continuity_r1_ticks_per_sec": 403.89},
    )
    back = artifacts.record_from_line(json.loads(artifacts.dump_record(rec)))
    assert back == rec


def test_wrapper_with_unparsed_tail_recovers_line(tmp_path):
    """Driver wrappers whose parse failed driver-side still carry the
    line in `tail`; the reader recovers it."""
    line = {"schema": 2, "metric": "gossipsub_v1.1_heartbeat_ticks_per_sec_n100000",
            "value": 400.0, "unit": "ticks/s", "vs_baseline": 0.04}
    p = tmp_path / "BENCH_rXX.json"
    p.write_text(json.dumps({
        "n": 9, "cmd": "python bench.py", "rc": 0,
        "tail": "WARNING: something\n" + json.dumps(line) + "\n",
    }))
    rec = artifacts.load_bench_artifact(str(p))
    assert rec.value == 400.0 and rec.round_index == 9 and rec.schema == 2


def test_multichip_reader_and_bad_artifact(tmp_path):
    m = artifacts.load_multichip_artifact(os.path.join(ROOT, "MULTICHIP_r05.json"))
    assert m["ok"] is True and m["rc"] == 0
    bad = tmp_path / "x.json"
    bad.write_text(json.dumps({"foo": 1}))
    with pytest.raises(ValueError):
        artifacts.load_multichip_artifact(str(bad))


# ---------------------------------------------------------------------------
# fingerprint: the self-description must match the workload decisions


def test_fingerprint_records_elision_flags():
    # honest-net phase configs elide BOTH attribution planes
    fp = sweep.workload_fingerprint("default", 100_000, 64, 8, 8)
    assert fp["elides_invalid_message_deliveries"] is True
    assert fp["elides_mesh_message_deliveries"] is True
    assert fp["score_weights"]["invalid_message_deliveries_weight"] == 0.0
    # sybil keeps full weights — its adversary vector is what P4 catches
    fp = sweep.workload_fingerprint("sybil", 50_000, 64, 16, 16)
    assert fp["elides_invalid_message_deliveries"] is False
    assert fp["engine"]["gater"] is True
    assert fp["adversary_fraction"] == 0.2
    # elision is phase-engine-only: the r=1 continuity metric never elides
    fp = sweep.workload_fingerprint("default", 100_000, 64, 1, 1)
    assert fp["elides_invalid_message_deliveries"] is False
    assert fp["engine"]["mode"] == "per_round"


def test_fingerprint_records_engine_gating():
    # the scatter publish-allocation gate (state.py: phase + N >= 20k)
    assert sweep.workload_fingerprint("default", 100_000, 64, 8, 8)[
        "engine"]["scatter_publish_alloc"] is True
    assert sweep.workload_fingerprint("default", 12_500, 64, 16, 16)[
        "engine"]["scatter_publish_alloc"] is False
    # incremental membership planes: narrow universes, phase engine only
    assert sweep.workload_fingerprint("default", 100_000, 64, 8, 8)[
        "engine"]["incr_members"] is True
    assert sweep.workload_fingerprint("eth2", 100_000, 64, 8, 8)[
        "engine"]["incr_members"] is False
    assert sweep.workload_fingerprint("default", 100_000, 64, 1, 1)[
        "engine"]["incr_members"] is False


# ---------------------------------------------------------------------------
# projection engine


def test_projection_reproduces_round5_number():
    """The committed round-5 projection — "~3,700-5,200 rounds/s,
    central ~4,500 ≈ 45% of the 10k north star" (BASELINE.md round-5
    addendum) — must come out of the code given the round-5 artifacts:
    BENCH_r05 (the session the 12.5k r=16 shard rate of 5,823 was
    measured in) and MULTICHIP_r05 (the collective audit whose permute
    counts the ICI term is built from)."""
    proj = projection.project_from_artifacts(
        os.path.join(ROOT, "BENCH_r05.json"),
        os.path.join(ROOT, "MULTICHIP_r05.json"),
    )
    lo, central, hi = proj.rounds_per_sec
    assert 0.44 <= central / 10_000.0 <= 0.455, proj.summary()
    assert 3_600 <= lo <= 3_800, proj.summary()
    assert 5_100 <= hi <= 5_300, proj.summary()
    # the ICI band is the 0.02-0.10 ms/round the BASELINE projections used
    assert proj.ici_ms[0] == pytest.approx(0.02)
    assert proj.ici_ms[2] == pytest.approx(0.10)


def test_projection_refuses_failed_multichip(tmp_path):
    """A projection built on a failed collective audit would be fiction;
    the round-1 MULTICHIP artifact (libtpu mismatch, ok=false) must be
    rejected."""
    with pytest.raises(ValueError, match="not ok"):
        projection.project_from_artifacts(
            os.path.join(ROOT, "BENCH_r01.json"),
            os.path.join(ROOT, "MULTICHIP_r01.json"),
            shard_rate=5_823.0,
        )


def test_permute_model_matches_collective_audit():
    """The ICI term's LEGACY fallback stays the 16·(r+4) formula the
    committed rounds-3..6 artifacts were projected with."""
    assert projection.permutes_per_round(8) == pytest.approx(16 * 12 / 8)  # 24
    assert projection.permutes_per_round(16) == pytest.approx(20.0)
    # at r=16 the launch-latency band gives the canonical 0.02-0.10 ms
    assert projection.ici_serialized_ms(16, 1.0) == pytest.approx(0.02)
    assert projection.ici_serialized_ms(16, 5.0) == pytest.approx(0.10)


def test_permute_model_measured_sets():
    """Round 7: a MEASURED gather-set count parameterizes the ICI term —
    the coalesced engine's r+1 sets replace the hard-coded r+4."""
    assert projection.permutes_per_round(16, 17) == pytest.approx(17.0)
    assert projection.permutes_per_round(8, 9) == pytest.approx(18.0)
    # fewer sets -> strictly cheaper ICI -> strictly higher rate
    legacy = projection.project(0.172, 16)
    coalesced = projection.project(0.172, 16, permute_sets_per_phase=17)
    assert coalesced.central > legacy.central
    assert coalesced.permute_sets_per_phase == 17
    with pytest.raises(ValueError, match="permute_sets_per_phase"):
        projection.permutes_per_round(16, 8)  # fewer sets than sub-rounds


def test_projection_uses_fingerprint_permute_sets(tmp_path):
    """A v2 artifact carrying the measured count must project strictly
    higher than the same artifact without it (legacy fallback), with the
    dryrun gate behavior intact — and the control-set count translates
    across cadences (artifact r=8, projection r=16)."""
    import json as _json

    with open(os.path.join(ROOT, "BENCH_r05.json")) as f:
        wrapper = _json.load(f)
    multi = os.path.join(ROOT, "MULTICHIP_r05.json")
    legacy = projection.project_from_artifacts(
        os.path.join(ROOT, "BENCH_r05.json"), multi)

    wrapper["parsed"]["schema"] = 2
    wrapper["parsed"]["fingerprint"] = {
        "rounds_per_phase": 8,
        "n_peers": 100_000,
        "engine": {"wire_coalesced": True},
        "permute_sets_per_phase": 9,  # the coalesced r+1 at r=8
    }
    p = tmp_path / "BENCH_r07.json"
    p.write_text(_json.dumps(wrapper))
    coalesced = projection.project_from_artifacts(str(p), multi)
    # r=8 artifact -> 1 control set -> 17 sets at the r=16 projection
    assert coalesced.permute_sets_per_phase == 17
    assert coalesced.central > legacy.central
    assert legacy.permute_sets_per_phase is None

    # reader properties
    rec = artifacts.load_bench_artifact(str(p))
    assert rec.wire_coalesced is True
    assert rec.permute_sets_per_phase == 9
    legacy_rec = artifacts.load_bench_artifact(
        os.path.join(ROOT, "BENCH_r05.json"))
    assert legacy_rec.wire_coalesced is None
    assert legacy_rec.permute_sets_per_phase is None

    # the dryrun gate still guards the measured-input path
    with pytest.raises(ValueError, match="not ok"):
        projection.project_from_artifacts(
            str(p), os.path.join(ROOT, "MULTICHIP_r01.json"))


def test_measured_gather_sets_coalesced_vs_legacy():
    """The fingerprint's trace-time measurement: the coalesced engine
    traces exactly r+1 halo gather sets, the legacy A/B path r+3 (wire,
    score, window; the P5 app set is weight-elided on the bench)."""
    assert sweep.measure_phase_gather_sets(
        "default", 8, wire_coalesced=True) == 9
    assert sweep.measure_phase_gather_sets(
        "default", 8, wire_coalesced=False) == 11


def test_fingerprint_records_wire_coalesced_and_permute_sets():
    fp = sweep.workload_fingerprint("default", 12_500, 64, 16, 16)
    assert fp["engine"]["wire_coalesced"] is True
    assert fp["permute_sets_per_phase"] == 17
    fp = sweep.workload_fingerprint("default", 12_500, 64, 16, 16,
                                    wire_coalesced=False)
    assert fp["engine"]["wire_coalesced"] is False
    assert fp["permute_sets_per_phase"] == 19
    # per-round cells record the engine switch but no phase permute count
    fp = sweep.workload_fingerprint("default", 100_000, 64, 1, 1)
    assert "permute_sets_per_phase" not in fp


def test_hlo_kernel_census():
    """The perf-smoke kernel gate's parser: fusion bodies and reduction
    regions don't count; bookkeeping ops don't count."""
    hlo = """\
HloModule m

%fused_computation.1 (p: u32[8]) -> u32[8] {
  %p = u32[8]{0} parameter(0)
  %a = u32[8]{0} and(u32[8]{0} %p, u32[8]{0} %p)
  ROOT %b = u32[8]{0} or(u32[8]{0} %a, u32[8]{0} %a)
}

%region_0.2 (x: u32[], y: u32[]) -> u32[] {
  %x = u32[] parameter(0)
  %y = u32[] parameter(1)
  ROOT %o = u32[] or(u32[] %x, u32[] %y)
}

ENTRY %main (i: u32[8]) -> u32[8] {
  %i = u32[8]{0} parameter(0)
  %c = u32[] constant(0)
  %f = u32[8]{0} fusion(u32[8]{0} %i), kind=kLoop, calls=%fused_computation.1
  %w = (s32[], u32[8]{0}) while((s32[], u32[8]{0}) %t), condition=%cond, body=%body
  %r = u32[] reduce(u32[8]{0} %f, u32[] %c), dimensions={0}, to_apply=%region_0.2
  %bc = u32[8]{0} bitcast(u32[8]{0} %f)
  ROOT %cp = u32[8]{0} copy(u32[8]{0} %bc)
}
"""
    census = profile.hlo_kernel_census(hlo)
    # tuple-result kernels (while, multi-output fusions) count too
    assert census["by_op"] == {"fusion": 1, "while": 1, "reduce": 1, "copy": 1}
    assert census["total"] == 4


def test_projection_input_validation():
    with pytest.raises(ValueError):
        projection.project(0.0, 16)
    with pytest.raises(ValueError):
        projection.permutes_per_round(0)
    # the committed shard table is r=16: a conflicting explicit cadence
    # without its own shard rate must refuse, not silently project r=16
    with pytest.raises(ValueError, match="rounds_per_phase=16"):
        projection.project_from_artifacts(
            os.path.join(ROOT, "BENCH_r05.json"),
            os.path.join(ROOT, "MULTICHIP_r05.json"),
            rounds_per_phase=8,
        )


# ---------------------------------------------------------------------------
# profiler parsing layers (pure — no trace capture)


def test_self_times_nesting():
    # a[0,100) contains b[10,30) (contains d[12,17)) and c[40,50)
    got = dict(profile._self_times(
        [(0, 100, "a"), (10, 20, "b"), (40, 10, "c"), (12, 5, "d")]))
    assert got == {"a": 70, "b": 15, "c": 10, "d": 5}


def test_parse_hlo_stats_obj():
    """The converter-backend normalizer must aggregate the hlo_stats
    column layout scripts/profile_trace.py consumed (cat=2, name=3,
    text=4, self-us=9, src=25)."""
    def row(cat, name, text, selft, src):
        r = [None] * 26
        r[2], r[3], r[4], r[9], r[25] = cat, name, text, selft, src
        return {"c": r}

    obj = {"rows": [
        row("fusion", "fusion.1", "f32[8] fusion(...)", 100.0, "a.py:1"),
        row("fusion", "fusion.1", "f32[8] fusion(...)", 50.0, "a.py:1"),
        row("copy", "copy.2", "copy(...)", 30.0, "<a href='x'>b.py:2</a>"),
    ]}
    table = profile.parse_hlo_stats_obj(obj, rounds=10)
    assert table.rows[0].name == "fusion.1"
    assert table.rows[0].self_us_per_round == pytest.approx(15.0)
    assert table.rows[0].occurrences == 2
    assert table.rows[1].source == "b.py:2"  # html stripped
    assert table.total_us_per_round == pytest.approx(18.0)
    assert table.by_category == {"fusion": 15.0, "copy": 3.0}


def test_parse_xspace_bytes_synthetic():
    """The direct-proto backend must attribute self times from a
    synthetic XSpace shaped like an XLA:CPU executor trace."""
    xplane_pb2 = profile._import_xplane_pb2()
    if xplane_pb2 is None:
        pytest.skip("no xplane proto module available")
    xs = xplane_pb2.XSpace()
    plane = xs.planes.add(name="/host:CPU")
    em_call = plane.event_metadata[1]
    em_call.id, em_call.name = 1, "call"
    em_op = plane.event_metadata[2]
    em_op.id, em_op.name = 2, "fusion.7"
    sm = plane.stat_metadata[1]
    sm.id, sm.name = 1, "hlo_op"
    line = plane.lines.add(name="tf_XLATfrtCpuClient/1")
    ev = line.events.add(metadata_id=1, offset_ps=0, duration_ps=1_000_000)
    ev.stats.add(metadata_id=1, str_value="call")
    ev2 = line.events.add(metadata_id=2, offset_ps=100, duration_ps=600_000)
    ev2.stats.add(metadata_id=1, str_value="fusion.7")
    # a python-bookkeeping line with no hlo stats must be ignored
    pl = plane.lines.add(name="python")
    pl.events.add(metadata_id=1, offset_ps=0, duration_ps=5_000_000)

    table = profile.parse_xspace_bytes([xs.SerializeToString()], rounds=2)
    got = {r.name: r for r in table.rows}
    assert set(got) == {"call", "fusion.7"}
    assert got["fusion.7"].self_us_per_round == pytest.approx(0.3)
    assert got["call"].self_us_per_round == pytest.approx(0.2)
    assert got["fusion.7"].category == "fusion"
    # the round-7 launch-count summary: 2 executed kernels over 2 rounds
    assert table.n_kernels_per_round == pytest.approx(1.0)
    assert table.kernels_by_category == {"fusion": 0.5, "call": 0.5}
    txt = profile.format_table(table)
    assert "fusion.7" in txt
    assert "kernels/round" in txt


@pytest.mark.slow
def test_profile_workload_end_to_end(tmp_path):
    """Capture + summarize a real (tiny) phase-engine segment on CPU:
    the 12.5k-shard table in docs/PERF.md is produced by this exact
    path at (12500, r=16)."""
    table = profile.profile_workload(
        256, rounds=8, config="default", rounds_per_phase=2,
        logdir=str(tmp_path / "prof"))
    assert table.rows, "no ops attributed"
    assert table.total_us_per_round > 0
    assert table.fingerprint["n_peers"] == 256
    assert table.fingerprint["rounds_per_phase"] == 2
    txt = profile.format_table(table, top=5)
    assert "by category" in txt


# ---------------------------------------------------------------------------
# sweep + regress plumbing (cheap paths only; the mini-bench itself is
# exercised by `make perf-smoke`)


def test_sweep_spec_cells():
    spec = sweep.SweepSpec(configs=("default", "eth2"), ns=(12_500, 25_000),
                           rs=(16,))
    cells = list(spec.cells())
    assert len(cells) == 4
    assert cells[0] == ("default", 12_500, 16, 16)  # he defaults to r


def test_metric_name_convention():
    assert sweep.metric_name("default", 100_000, 8) == \
        "gossipsub_v1.1_delivery_rounds_per_sec_n100000_phase8"
    assert sweep.metric_name("eth2", 12_500, 16) == \
        "gossipsub_v1.1_delivery_rounds_per_sec_n12500_eth2_phase16"
    assert sweep.metric_name("default", 100_000, 1) == \
        "gossipsub_v1.1_heartbeat_ticks_per_sec_n100000"


def test_regress_trajectory_and_projection_checks():
    from go_libp2p_pubsub_tpu.perf import regress

    assert regress.check_trajectory(ROOT) == []
    assert regress.check_projection(ROOT) == []


def test_regress_catches_corrupt_artifact(tmp_path):
    from go_libp2p_pubsub_tpu.perf import regress

    (tmp_path / "BENCH_r01.json").write_text("{not json")
    errs = regress.check_trajectory(str(tmp_path))
    assert any("BENCH_r01" in e for e in errs)


def test_adversary_and_score_weight_blocks_round_trip():
    """Round 13: the `adversary` and `score_weights` fingerprint blocks
    (ADVICE r5 item 1 for the weights) round-trip through the line
    format, and LEGACY lines read back the typed sentinels —
    ADVERSARY_OFF / SCORE_WEIGHTS_UNKNOWN, never a KeyError or a
    silently-assumed zero."""

    class _FakeAdv:
        enabled = True

        @staticmethod
        def fingerprint():
            return {"enabled": True, "n_sybils": 7,
                    "behaviors": ["drop_forward"], "onset": 3,
                    "stop": None, "promo_score": 1.0,
                    "population": "abc123"}

    fp = {
        "adversary": artifacts.adversary_fingerprint(_FakeAdv()),
        "score_weights": artifacts.score_weights_fingerprint(
            invalid_message_deliveries_weight=-1.0,
            behaviour_penalty_weight=-10.0,
        ),
    }
    rec = artifacts.BenchRecord(
        metric="attack_sybil_honest_delivery", value=1.0, unit="ratio",
        vs_baseline=0.0, schema=3, fingerprint=fp,
    )
    back = artifacts.record_from_line(json.loads(artifacts.dump_record(rec)))
    assert back.adversary_on
    assert back.adversary["n_sybils"] == 7
    assert back.adversary["behaviors"] == ["drop_forward"]
    assert back.score_weights["recorded"] is True
    assert back.score_weights["behaviour_penalty_weight"] == -10.0

    # legacy / honest lines: typed sentinels
    legacy = artifacts.record_from_line(
        {"metric": "m", "value": 1.0, "unit": "x", "vs_baseline": 0.0})
    assert legacy.adversary == artifacts.ADVERSARY_OFF
    assert not legacy.adversary_on
    assert legacy.score_weights == artifacts.SCORE_WEIGHTS_UNKNOWN
    assert legacy.score_weights["recorded"] is False
    # the off block is explicit on new honest artifacts
    off = artifacts.adversary_fingerprint()
    assert off["enabled"] is False and off["scenario"] is None

    # every committed BENCH_r* line reads the sentinels without error
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    for p in paths:
        r = artifacts.load_bench_artifact(p)
        assert not r.adversary_on
        assert r.adversary["n_sybils"] == 0


def test_service_block_round_trips_and_legacy_sentinel():
    """Round 17: the `service` fingerprint block (the supervised
    service loop's self-description) round-trips through the line
    format, and LEGACY lines read back the typed SERVICE_OFF sentinel
    — never a KeyError or a silently-assumed bare run."""
    fp = {
        "service": artifacts.service_fingerprint(
            segment_rounds=8, keep_last=3, keep_every=4,
            probes=("finite-state", "events-monotone"),
            recoveries=2, segments=40, resumes=1),
    }
    rec = artifacts.BenchRecord(
        metric="service_loop_rounds_per_sec", value=32.0, unit="rounds/s",
        vs_baseline=0.0, schema=3, fingerprint=fp,
    )
    back = artifacts.record_from_line(json.loads(artifacts.dump_record(rec)))
    assert back.service_on
    assert back.service["segment_rounds"] == 8
    assert back.service["retention"] == {"keep_last": 3, "keep_every": 4}
    assert back.service["probes"] == ["finite-state", "events-monotone"]
    assert back.service["recoveries"] == 2 and back.service["resumes"] == 1

    legacy = artifacts.record_from_line(
        {"metric": "m", "value": 1.0, "unit": "x", "vs_baseline": 0.0})
    assert legacy.service == artifacts.SERVICE_OFF
    assert not legacy.service_on

    # every committed BENCH_r* line reads the sentinel without error
    for p in sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json"))):
        r = artifacts.load_bench_artifact(p)
        assert r.service["enabled"] is False


def test_topology_block_round_trips_and_legacy_sentinel():
    """Round 18: the `topology` fingerprint block (which generated
    graph a cell ran on) round-trips through the line format; LEGACY
    lines read back the typed TOPOLOGY_BANDED sentinel (the banded
    bench ring, recorded: false) — never a KeyError."""
    fp = {
        "topology": artifacts.topology_fingerprint(
            generator="powerlaw", family="power-law",
            params={"exponent": 2.2, "d_min": 2, "max_degree": 64},
            n_edges=10186, mean_degree=4.97, max_degree=61,
            density=0.078, seed=0,
            link_classes={"local": 100, "regional": 40, "global": 10},
            workload_pattern="attestation_storm"),
    }
    rec = artifacts.BenchRecord(
        metric="powerlaw_rounds_per_sec", value=117.0,
        unit="delivery-rounds/s", vs_baseline=0.0117, schema=3,
        fingerprint=fp,
    )
    back = artifacts.record_from_line(json.loads(artifacts.dump_record(rec)))
    assert back.topology_recorded
    assert back.topology["generator"] == "powerlaw"
    assert back.topology["n_edges"] == 10186
    assert back.topology["density"] == pytest.approx(0.078)
    assert back.topology["workload_pattern"] == "attestation_storm"
    assert back.topology["link_classes"]["regional"] == 40

    legacy = artifacts.record_from_line(
        {"metric": "m", "value": 1.0, "unit": "x", "vs_baseline": 0.0})
    assert legacy.topology == artifacts.TOPOLOGY_BANDED
    assert not legacy.topology_recorded
    assert legacy.topology["generator"] == "ring_lattice"

    # the committed BENCH_r07 pair carries the block; every earlier
    # committed line reads the sentinel without error
    variants = artifacts.load_bench_variants(
        os.path.join(ROOT, "BENCH_r07.json"))
    assert variants["parsed"].topology_recorded
    assert variants["parsed"].edge_layout == "csr"
    assert variants["parsed_dense"].topology == variants["parsed"].topology
    for p in sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json"))):
        r = artifacts.load_bench_artifact(p)
        assert isinstance(r.topology["generator"], str)


def test_service_report_fingerprint_matches_block(tmp_path):
    """ServiceReport.fingerprint() emits exactly the artifacts block
    shape (the execution/params-block pattern), and tracestat's
    artifact reader surfaces it."""
    import sys

    from go_libp2p_pubsub_tpu.oracle import probes as _probes
    from go_libp2p_pubsub_tpu.serve import RetentionPolicy
    from go_libp2p_pubsub_tpu.serve.supervisor import ServiceReport

    rep = ServiceReport(
        states=None, n_dispatches=16, rounds=16, segments=4,
        segment_rounds=4, seconds=1.0, recoveries=1, retries=2,
        degradations=[], resumed_from=8, window_compiles={"L4": 1},
        checkpoints=[], heartbeat_path="", invariant_checks=4,
        probes=_probes.HealthConfig().names,
        retention=RetentionPolicy(keep_last=2, keep_every=3), bundles=[])
    block = rep.fingerprint()
    assert block["enabled"] and block["segment_rounds"] == 4
    assert block["retention"] == {"keep_last": 2, "keep_every": 3}
    assert block["resumes"] == 1

    rec = artifacts.BenchRecord(
        metric="m", value=1.0, unit="x", vs_baseline=0.0, schema=3,
        fingerprint={"service": block})
    art = tmp_path / "svc.json"
    art.write_text(artifacts.dump_record(rec) + "\n")
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    try:
        from tracestat import artifact_service

        got = artifact_service(str(art))
    finally:
        sys.path.pop(0)
    assert got == block


def test_service_off_sentinel_is_mutation_safe():
    """Review regression: SERVICE_OFF is the only sentinel with nested
    containers — a caller mutating a legacy record's service block must
    not corrupt the module default for later reads."""
    legacy = artifacts.record_from_line(
        {"metric": "m", "value": 1.0, "unit": "x", "vs_baseline": 0.0})
    sv = legacy.service
    sv["retention"]["keep_last"] = 99
    sv["probes"].append("bogus")
    fresh = artifacts.record_from_line(
        {"metric": "m2", "value": 1.0, "unit": "x",
         "vs_baseline": 0.0}).service
    assert fresh["retention"] == {"keep_last": 0, "keep_every": 0}
    assert fresh["probes"] == []


def test_router_block_round_trips_and_legacy_sentinel(tmp_path):
    """Round 24: the `router` fingerprint block (which protocol
    generation cut the number — v1.1 | v1.2-IDONTWANT — plus the choke
    decision rule and latency ring depth) round-trips through the line
    format; LEGACY lines read back the typed ROUTER_V11 sentinel (plain
    v1.1 semantics — literally what every pre-round-24 build ran), and
    tracestat's artifact reader surfaces the block."""
    import sys

    from go_libp2p_pubsub_tpu.routers import RouterConfig

    rc = RouterConfig(idontwant=True, choke=True, latency_rounds=7,
                      choke_threshold=0.35, unchoke_threshold=0.1)
    block = artifacts.router_fingerprint(rc)
    assert block["enabled"] and block["protocol"] == "v1.2"
    assert block["idontwant"] and block["choke"]
    assert block["latency_rounds"] == 7
    assert block["choke_threshold"] == pytest.approx(0.35)
    assert block["choke_max_per_hb"] == 1

    rec = artifacts.BenchRecord(
        metric="choke_dup_ratio", value=0.2, unit="dup/delivery",
        vs_baseline=0.0, schema=3, fingerprint={"router": block})
    back = artifacts.record_from_line(json.loads(artifacts.dump_record(rec)))
    assert back.router_on
    assert back.router == block

    # router=None IS the explicit v1.1 block (what the sweep emits for
    # every bench cell), and a latency-only build stays protocol v1.1
    # with its choke knobs typed-None, not garbage defaults
    assert artifacts.router_fingerprint(None) == artifacts.ROUTER_V11
    lat = artifacts.router_fingerprint(RouterConfig(latency_rounds=3))
    assert lat["enabled"] and lat["protocol"] == "v1.1"
    assert lat["latency_rounds"] == 3 and lat["choke_ema_alpha"] is None
    fp = sweep.workload_fingerprint("default", 100_000, 64, 8, 8)
    assert fp["router"] == artifacts.ROUTER_V11

    legacy = artifacts.record_from_line(
        {"metric": "m", "value": 1.0, "unit": "x", "vs_baseline": 0.0})
    assert legacy.router == artifacts.ROUTER_V11
    assert not legacy.router_on
    assert legacy.router["protocol"] == "v1.1"

    # tracestat surfaces the block; every committed BENCH_r* line reads
    # the sentinel without error
    art = tmp_path / "router.json"
    art.write_text(artifacts.dump_record(rec) + "\n")
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    try:
        from tracestat import artifact_router

        got = artifact_router(str(art))
    finally:
        sys.path.pop(0)
    assert got == block
    for p in sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json"))):
        r = artifacts.load_bench_artifact(p)
        assert not r.router_on and r.router["protocol"] == "v1.1"
