"""Fused CSR plane (round 21, docs/DESIGN.md §21): the Pallas kernels
of ops/pallas_csr.py and the restructured XLA composite behind
``cfg.fused``.

Pins the §21 contracts:

  * ``csr_delivery`` (three pallas_calls: edge phase / row phase / edge
    commit) is BIT-EXACT vs the XLA composite chain
    (peer/edge/owner gathers + ops/csr.segment_or_scan + the
    finish_delivery_flat commit algebra) in interpret mode — on ragged,
    banded and power-law topologies, chaos link-deny masks on and off;
  * ``select_topk_pallas`` equals the rank_desc pairwise form
    (including the traced masked-width k) bit for bit;
  * the fused composite pieces are exact recompositions: the
    capacity-bounded segmented scan equals the log2(E)
    associative_scan form on random ragged segments, and the
    sort-composite rank equals the pairwise count — ties, signed
    zeros, masks, keyed and unkeyed;
  * fused-vs-unfused FULL STATE TREES are bit-exact for all four
    engines (gossipsub, gossipsub_phase r∈{1,8}, floodsub, randomsub);
  * the PUBSUB_PALLAS_CSR hook in models/common.delivery_round returns
    the same (Delivery, RoundInfo) as the composite path.

The Pallas kernels run in interpret mode only (the Mosaic caveat —
see the module docstring of ops/pallas_csr.py); the composite is the
shipping TPU form and the one `make cost-audit`'s fusion contract
prices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph, topo
from go_libp2p_pubsub_tpu.models import common
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.ops import csr as csrops
from go_libp2p_pubsub_tpu.ops import pallas_csr as pcsr
from go_libp2p_pubsub_tpu.ops import select
from go_libp2p_pubsub_tpu.state import Net, SimState

M = 32
W = bitset.n_words(M)


# ---------------------------------------------------------------------------
# topologies: ragged (uneven real degrees), banded, power-law


def _net(kind: str) -> Net:
    if kind == "ragged":
        t = graph.random_connect(96, d=4, seed=2)
        subs = graph.subscribe_all(96, 1)
        return Net.build(t, subs, edge_layout="csr", fused=True)
    if kind == "banded":
        t = graph.ring_lattice(64, d=8)
        subs = graph.subscribe_all(64, 1)
        return Net.build(t, subs, edge_layout="csr", fused=True)
    if kind == "powerlaw":
        el = topo.powerlaw(128, exponent=2.2, d_min=2, max_degree=16,
                           seed=0)
        subs = graph.subscribe_all(128, 1)
        _t, _net_d, net_c = topo.build_nets(el, subs, max_degree=16)
        return Net.build(_t, subs, edge_layout="csr", fused=True)
    raise ValueError(kind)


def _rand_planes(net: Net, rng):
    """Arbitrary word planes — the kernels are pure bit algebra, so
    parity must hold for ANY inputs, not just reachable states."""
    n, k = net.nbr.shape
    e = net.n_edges
    u32 = lambda shape: jnp.asarray(
        rng.integers(0, 1 << 32, size=shape, dtype=np.uint32))
    return {
        "fwd": u32((n, W)),
        "fe_e": u32((e, W)),
        "edge_mask": u32((n, k, W)),
        "not_mine": u32((n, W)),
        "have": u32((n, W)),
        "first_round": jnp.asarray(
            rng.integers(-1, 50, size=(n, M)), jnp.int32),
        "valid": jnp.asarray(rng.random(M) < 0.8),
    }


def _composite_reference(net: Net, p: dict, tick, link_ok_e=None):
    """The exact XLA chain the kernels replace, piecewise (the same ops
    models/common.delivery_round + finish_delivery_flat compose)."""
    fwd_e = net.peer_gather_flat(p["fwd"])
    echo_e = net.edge_gather_flat(p["fe_e"])
    mask_e = net.pack_edges(p["edge_mask"])
    nm_e = net.owner_gather(p["not_mine"])
    trans_e = fwd_e & ~echo_e & mask_e & nm_e
    if link_ok_e is not None:
        trans_e = trans_e & jnp.where(
            link_ok_e[:, None], jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    inc, exc = csrops.segment_or_scan(trans_e, net.csr_seg_start,
                                      cap=net.max_degree)
    recv = jnp.where(net.csr_row_nonempty[:, None],
                     inc[jnp.clip(net.csr_row_last, 0)], jnp.uint32(0))
    new = recv & ~p["have"]
    new_e = net.owner_gather(new)
    fa_e = trans_e & ~exc & new_e
    valid_words = bitset.pack(p["valid"])
    first_round = jnp.where(bitset.unpack(new, M), tick,
                            p["first_round"])
    return {
        "trans_e": trans_e,
        "recv": recv,
        "new": new,
        "have": p["have"] | new,
        "fwd": new & valid_words[None, :],
        "first_round": first_round,
        "fe": (p["fe_e"] & ~new_e) | fa_e,
        "fa_e": fa_e,
    }


def _blocks(net: Net):
    e, cap = net.n_edges, net.max_degree
    block = common._pick_div(e, cap, 256)
    block_rows = common._pick_div(net.n_peers, 1, 256)
    assert block is not None and block_rows is not None
    assert pcsr.pallas_csr_supported(e, block, cap), (e, block, cap)
    return block, block_rows


@pytest.mark.parametrize("kind", ["ragged", "banded", "powerlaw"])
@pytest.mark.parametrize("chaos", [False, True])
def test_csr_delivery_kernel_bit_exact(kind, chaos):
    net = _net(kind)
    rng = np.random.default_rng(
        {"ragged": 1, "banded": 2, "powerlaw": 3}[kind] * 2 + int(chaos))
    block, block_rows = _blocks(net)
    for trial in range(2):
        p = _rand_planes(net, rng)
        link_ok = (jnp.asarray(rng.random(net.n_edges) < 0.7)
                   if chaos else None)
        tick = jnp.int32(7 + trial)
        want = _composite_reference(net, p, tick, link_ok)
        got = pcsr.csr_delivery(
            p["fwd"], p["fe_e"], net.pack_edges(p["edge_mask"]),
            p["not_mine"], p["have"], p["first_round"],
            bitset.pack(p["valid"])[None, :], tick,
            net.csr_col, net.csr_row, net.csr_eperm, net.csr_seg_start,
            net.csr_row_last, net.csr_row_nonempty,
            cap=net.max_degree, block=block, block_rows=block_rows,
            interpret=True, link_ok_e=link_ok,
        )
        for key in want:
            np.testing.assert_array_equal(
                np.asarray(got[key]), np.asarray(want[key]),
                err_msg=f"{kind} chaos={chaos} trial={trial} {key}")


def test_select_topk_pallas_bit_exact():
    rng = np.random.default_rng(5)
    r, k = 64, 16
    for trial in range(3):
        # quantized values force ties; random mask; per-row traced k
        values = jnp.asarray(
            rng.integers(0, 4, size=(r, k)).astype(np.float32))
        mask = jnp.asarray(rng.random((r, k)) < 0.7)
        noise = jnp.asarray(
            rng.integers(0, 3, size=(r, k)).astype(np.float32) / 2.0)
        k_arr = jnp.asarray(rng.integers(0, k + 1, size=(r,)), jnp.int32)
        primary = jnp.where(mask, values, jnp.float32(-jnp.inf))
        rank = select._rank_desc_pairwise(primary, noise)
        want = (rank < k_arr[:, None]) & mask
        got = pcsr.select_topk_pallas(values, mask, k_arr, noise,
                                      block=16, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=f"trial={trial}")


# ---------------------------------------------------------------------------
# the fused composite pieces are exact recompositions


def test_bounded_scan_equals_associative_scan():
    rng = np.random.default_rng(11)
    for trial in range(10):
        e = int(rng.integers(8, 200))
        cap = int(rng.integers(1, 20))
        # random ragged segments, each no longer than cap
        flags = np.zeros(e, bool)
        i = 0
        while i < e:
            flags[i] = True
            i += int(rng.integers(1, cap + 1))
        x = jnp.asarray(rng.integers(0, 1 << 32, size=(e, 2),
                                     dtype=np.uint32))
        f = jnp.asarray(flags)
        inc_a, exc_a = csrops.segment_or_scan(x, f, cap=None)
        inc_b, exc_b = csrops.segment_or_scan(x, f, cap=cap)
        np.testing.assert_array_equal(np.asarray(inc_a),
                                      np.asarray(inc_b))
        np.testing.assert_array_equal(np.asarray(exc_a),
                                      np.asarray(exc_b))


def test_sorted_rank_equals_pairwise():
    rng = np.random.default_rng(13)
    for trial in range(10):
        r, k = int(rng.integers(1, 20)), int(rng.integers(1, 24))
        # quantized + signed zeros: the tie/total-order hazards
        values = rng.integers(-2, 3, size=(r, k)).astype(np.float32)
        values[rng.random((r, k)) < 0.2] = -0.0
        mask = rng.random((r, k)) < 0.6
        key = (jax.random.key(trial) if trial % 2 == 0 else None)
        a = select.rank_desc(jnp.asarray(values), jnp.asarray(mask),
                             key, fused=False)
        b = select.rank_desc(jnp.asarray(values), jnp.asarray(mask),
                             key, fused=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_selection_kernels_fused_parity():
    rng = np.random.default_rng(17)
    values = jnp.asarray(rng.integers(0, 5, size=(32, 16))
                         .astype(np.float32))
    mask = jnp.asarray(rng.random((32, 16)) < 0.7)
    width = jnp.asarray(rng.integers(0, 20, size=(32,)), jnp.int32)
    key = jax.random.key(3)
    for a, b in [
        (select.select_topk_mask(values, mask, 6, key),
         select.select_topk_mask(values, mask, 6, key, fused=True)),
        (select.select_random_mask(key, mask, 4),
         select.select_random_mask(key, mask, 4, fused=True)),
        (select.masked_width_topk(values, mask, width, 16, key),
         select.masked_width_topk(values, mask, width, 16, key,
                                  fused=True)),
        (select.masked_width_random(key, mask, width, 16),
         select.masked_width_random(key, mask, width, 16, fused=True)),
    ]:
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# the delivery_round hook (PUBSUB_PALLAS_CSR=1 on a fused Net)


def test_delivery_round_pallas_csr_hook(monkeypatch):
    net = _net("banded")
    st = SimState.init(net.n_peers, M, seed=0, k=net.max_degree,
                       n_edges=net.n_edges)
    rng = np.random.default_rng(23)

    def run(use_pallas):
        monkeypatch.setattr(common, "USE_PALLAS_CSR", use_pallas)
        s = st
        out = []
        for t in range(3):
            po = jnp.asarray(rng.integers(0, net.n_peers, size=(2,)),
                             jnp.int32)
            # fresh rng per path would desync draws — reseed instead
            raw = floodsub_step.__wrapped__
            s2 = raw(net, s, po, jnp.zeros((2,), jnp.int32),
                     jnp.ones((2,), bool))
            out.append(s2)
            s = s2
        return out

    rng = np.random.default_rng(23)
    a = run(False)
    rng = np.random.default_rng(23)
    b = run(True)
    for sa, sb in zip(a, b):
        la, lb = jtu.tree_leaves(sa), jtu.tree_leaves(sb)
        assert len(la) == len(lb)
        for x, y in zip(la, lb):
            if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
                x, y = jax.random.key_data(x), jax.random.key_data(y)
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# engine-level fused-vs-unfused parity: full state trees, four engines


def _tree_equal(a, b):
    la, lb = jtu.tree_leaves(a), jtu.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _run_bench(fused, rounds_per_phase=1, steps=4, n=96):
    from go_libp2p_pubsub_tpu.perf.sweep import build_bench

    st, step, _, _ = build_bench(n, M, rounds_per_phase=rounds_per_phase,
                                 heartbeat_every=max(rounds_per_phase, 1),
                                 edge_layout="csr", fused=fused)
    rng = np.random.default_rng(0)
    for t in range(steps):
        if rounds_per_phase > 1:
            r = rounds_per_phase
            po = jnp.asarray(rng.integers(0, n, size=(r, 2)), jnp.int32)
            st = step(st, po, jnp.zeros((r, 2), jnp.int32),
                      jnp.ones((r, 2), bool), do_heartbeat=True)
        else:
            po = jnp.asarray(rng.integers(0, n, size=(2,)), jnp.int32)
            st = step(st, po, jnp.zeros((2,), jnp.int32),
                      jnp.ones((2,), bool))
    return st


def test_gossipsub_fused_parity():
    _tree_equal(_run_bench(False), _run_bench(True))


def test_phase_fused_parity_r1():
    # r=1 phase engine: the degenerate single-sub-round phase dispatch
    _tree_equal(_run_bench(False, rounds_per_phase=1),
                _run_bench(True, rounds_per_phase=1))


@pytest.mark.slow
def test_phase_fused_parity_r8():
    _tree_equal(_run_bench(False, rounds_per_phase=8, steps=3),
                _run_bench(True, rounds_per_phase=8, steps=3))


@pytest.mark.parametrize("engine", ["floodsub", "randomsub"])
def test_factoryless_engines_fused_parity(engine):
    t = graph.ring_lattice(96, d=8)
    subs = graph.subscribe_all(96, 1)

    def run(fused):
        net = Net.build(t, subs, edge_layout="csr", fused=fused)
        st = SimState.init(96, M, seed=0, k=net.max_degree,
                           n_edges=net.n_edges)
        if engine == "floodsub":
            step = lambda s, *a: floodsub_step.__wrapped__(net, s, *a)
        else:
            step = make_randomsub_step(net)
        rng = np.random.default_rng(1)
        for t_ in range(4):
            po = jnp.asarray(rng.integers(0, 96, size=(2,)), jnp.int32)
            st = step(st, po, jnp.zeros((2,), jnp.int32),
                      jnp.ones((2,), bool))
        return st

    _tree_equal(run(False), run(True))


def test_cfg_net_fused_mismatch_raises():
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        prepare_step_consts,
    )

    t = graph.ring_lattice(64, d=8)
    subs = graph.subscribe_all(64, 1)
    net = Net.build(t, subs, edge_layout="csr", fused=True)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), edge_layout="csr",
        fused=False,
    )
    with pytest.raises(ValueError, match="fused"):
        prepare_step_consts(cfg, net, None, 1.0, None, None, None)
