"""Outbound-queue backpressure: overflow genuinely loses traffic.

The reference's per-peer writer queue is 32 deep; a full queue drops the
whole RPC (doDropRPC gossipsub.go:1153-1160, comm.go:139-170) and gossip
is never retried (gossipsub.go:1757-1764). With GossipSubConfig.queue_cap
the engine enforces the same failure mode: delivery ratio degrades under
overload, P3 mesh-delivery deficits appear, and the DROP_RPC counter
accounts for the lost transmissions.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.trace.events import EV


def _build(queue_cap: int, n=64, msg_slots=96):
    topo = graph.ring_lattice(n, d=4)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    tp = TopicScoreParams(
        mesh_message_deliveries_weight=-0.5,
        mesh_message_deliveries_threshold=4.0,
        mesh_message_deliveries_activation=4.0,
        mesh_message_deliveries_window=2.0,
    )
    sp = PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
        queue_cap=queue_cap,
    )
    cfg = dataclasses.replace(cfg, fanout_slots=0)
    st = GossipSubState.init(net, msg_slots, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    return net, st, step


def _overload(st, step, rounds=20, pubs=4, n=64, seed=0, quiet=8):
    """Publish burst then quiet drain rounds so propagation completes
    before measuring (msg_slots must exceed rounds*pubs — no recycling)."""
    rng = np.random.default_rng(seed)
    for r in range(rounds):
        po = jnp.asarray(rng.integers(0, n, size=pubs).astype(np.int32))
        pt = jnp.asarray(np.zeros(pubs, np.int32))
        pv = jnp.asarray(np.ones(pubs, bool))
        st = step(st, po, pt, pv)
    po = jnp.asarray(np.full(pubs, -1, np.int32))
    for _ in range(quiet):
        st = step(st, po, pt, pv)
    return st


def _delivery_ratio(st):
    have = np.ascontiguousarray(np.asarray(st.core.dlv.have))
    live = np.asarray(st.core.msgs.birth) >= 0
    if not live.any():
        return 0.0
    bits = np.unpackbits(
        have.view(np.uint8), axis=1, bitorder="little"
    )[:, : len(live)]
    return bits[:, live].mean()


def test_congestion_loses_traffic_and_p3_deficits():
    net, st0, step0 = _build(queue_cap=0)
    netc, stc, stepc = _build(queue_cap=1)

    st_free = _overload(jax.tree.map(jnp.copy, st0), step0)
    st_cap = _overload(stc, stepc)

    ev_free = np.asarray(st_free.core.events)
    ev_cap = np.asarray(st_cap.core.events)

    # drops occurred, and only in the capped run
    assert ev_free[EV.DROP_RPC] == 0
    assert ev_cap[EV.DROP_RPC] > 0

    # the capped network delivers measurably less of the traffic
    r_free = _delivery_ratio(st_free)
    r_cap = _delivery_ratio(st_cap)
    assert r_free > 0.9
    assert r_cap < r_free - 0.05

    # arrival conservation holds with losses: every received transmission
    # is a first receipt or a duplicate (drops are not received at all)
    assert (
        ev_cap[EV.DELIVER_MESSAGE] + ev_cap[EV.REJECT_MESSAGE]
        + ev_cap[EV.DUPLICATE_MESSAGE]
        == ev_cap[EV.RECV_RPC]
    )
    # and the capped run genuinely transmitted less
    assert ev_cap[EV.SEND_RPC] < ev_free[EV.SEND_RPC]

    # P3 mesh-delivery deficits appear under congestion: starved mesh
    # edges accumulate deficit and drag scores negative
    assert float(np.asarray(st_cap.scores).min()) < float(
        np.asarray(st_free.scores).min()
    ) or (np.asarray(st_cap.score.mmd).sum() < np.asarray(st_free.score.mmd).sum())


def test_queue_cap_off_is_lossless_identity():
    # queue_cap=0 must be bit-identical to the pre-backpressure engine:
    # compare against a queue_cap large enough to never bind
    net, st_a, step_a = _build(queue_cap=0)
    _, st_b, step_b = _build(queue_cap=10**6)
    st_a = _overload(st_a, step_a, rounds=8)
    st_b = _overload(st_b, step_b, rounds=8)
    for (pa, a), b in zip(
        jax.tree_util.tree_flatten_with_path(st_a)[0], jax.tree.leaves(st_b)
    ):
        if jnp.issubdtype(jnp.asarray(a).dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"mismatch at {jax.tree_util.keystr(pa)}",
        )
