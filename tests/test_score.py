"""Score engine golden tests — the score_test.go scenario matrix (P1..P7,
caps, decay, activation, sticky failure) against the scalar oracle."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import PeerScoreParams, TopicScoreParams
from go_libp2p_pubsub_tpu.oracle.score import OracleScore
from go_libp2p_pubsub_tpu.score import (
    ScoreState,
    TopicParamsArrays,
    compute_scores,
    ip_colocation_surplus_sq,
    on_deliveries,
    on_graft,
    on_prune,
    refresh_scores,
)
from go_libp2p_pubsub_tpu.ops.bitset import edge_eq_words, pack
from go_libp2p_pubsub_tpu.score.engine import add_penalties
from go_libp2p_pubsub_tpu.state import Net


def star_net(n_leaves=6, n_topics=1, ip_group=None):
    """Node 0 connected to 1..n_leaves (observer pattern)."""
    dialed = [set(range(1, n_leaves + 1))] + [set() for _ in range(n_leaves)]
    topo = graph._from_edge_lists(n_leaves + 1, dialed, None)
    subs = graph.subscribe_all(n_leaves + 1, n_topics)
    return topo, Net.build(topo, subs, ip_group)


def mk_params(n_topics=1, **topic_kw):
    base = dict(
        topic_weight=1.0,
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=0.0,
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
        invalid_message_deliveries_weight=0.0,
    )
    base.update(topic_kw)
    tp = TopicScoreParams(**base)
    return PeerScoreParams(
        topics={t: tp for t in range(n_topics)},
        skip_app_specific=True,
    )


class Harness:
    """Drives the vectorized engine and the scalar oracle in lockstep for
    observer node 0 of a star topology."""

    def __init__(self, params, n_leaves=6, n_topics=1, m=16, ip_group=None):
        self.params = params
        self.topo, self.net = star_net(n_leaves, n_topics, ip_group)
        n = n_leaves + 1
        s = self.net.n_slots
        k = self.net.max_degree
        self.n, self.s, self.k, self.m = n, s, k, m
        self.tpa = TopicParamsArrays.build(params, n_topics)
        self.tp = self.tpa.gather(self.net.my_topics)
        self.st = ScoreState.empty(n, s, k)
        self.in_mesh = jnp.zeros((n, s, k), bool)
        self.oracle = OracleScore(params)
        self.p6 = ip_colocation_surplus_sq(self.net, params.ip_colocation_factor_threshold)
        self.msg_topic = np.full(m, -1, np.int32)
        self.msg_valid = np.zeros(m, bool)
        self.first_round = np.full((n, m), -1, np.int32)
        self.first_edge = np.full((n, m), -1, np.int8)
        self.next_slot = 0

    def leaf_edge(self, leaf):
        # observer 0's edge slot for leaf peer id
        return int(np.nonzero(self.topo.nbr[0] == leaf)[0][0])

    def graft(self, leaf, topic, tick):
        k = self.leaf_edge(leaf)
        mask = np.zeros((self.n, self.s, self.k), bool)
        mask[0, topic, k] = True
        self.in_mesh = self.in_mesh | jnp.asarray(mask)
        self.st = on_graft(self.st, jnp.asarray(mask), tick)
        self.oracle.graft(leaf, topic, tick)

    def prune(self, leaf, topic):
        k = self.leaf_edge(leaf)
        mask = np.zeros((self.n, self.s, self.k), bool)
        mask[0, topic, k] = True
        self.st = on_prune(self.st, jnp.asarray(mask), self.tp)
        self.in_mesh = self.in_mesh & ~jnp.asarray(mask)
        self.oracle.prune(leaf, topic)

    def deliver_round(self, tick, deliveries):
        """deliveries: list of (leaf, topic, valid, is_new).
        All listed arrivals happen this round at node 0."""
        arrivals = np.zeros((self.n, self.k, self.m), bool)
        new_bits = np.zeros((self.n, self.m), bool)
        for leaf, topic, valid, is_new in deliveries:
            slot = self.next_slot
            self.next_slot = (self.next_slot + 1) % self.m
            self.msg_topic[slot] = topic
            self.msg_valid[slot] = valid
            ke = self.leaf_edge(leaf)
            arrivals[0, ke, slot] = True
            if is_new:
                new_bits[0, slot] = True
                self.first_round[0, slot] = tick
                self.first_edge[0, slot] = ke
                if valid:
                    self.oracle.first_delivery(leaf, topic)
                else:
                    self.oracle.invalid_delivery(leaf, topic)
            else:
                if valid:
                    self.oracle.duplicate_delivery(leaf, topic, in_window=True)
                else:
                    self.oracle.invalid_delivery(leaf, topic)
        self.st = on_deliveries(
            self.st,
            self.net,
            self.in_mesh,
            self.tp,
            pack(jnp.asarray(arrivals)),
            pack(jnp.asarray(new_bits)),
            edge_eq_words(jnp.asarray(self.first_edge), self.k),
            jnp.asarray(self.first_round),
            jnp.asarray(self.msg_topic),
            jnp.asarray(self.msg_valid),
            tick,
            jnp.asarray(self.tpa.window_rounds),
        )

    def refresh(self, tick):
        self.st = refresh_scores(self.st, self.in_mesh, tick, self.tp, self.params)
        self.oracle.refresh(tick)

    def penalty(self, leaf, count):
        inc = np.zeros((self.n, self.k), np.float32)
        inc[0, self.leaf_edge(leaf)] = count
        self.st = add_penalties(self.st, jnp.asarray(inc))
        self.oracle.add_penalty(leaf, count)

    def scores(self):
        app = jnp.zeros((self.n,), jnp.float32)
        return np.asarray(
            compute_scores(self.st, self.in_mesh, self.tp, self.params, self.p6, app, self.net)
        )

    def check(self, leaf, ip_count=1, app=0.0, tol=1e-5):
        got = self.scores()[0, self.leaf_edge(leaf)]
        want = self.oracle.score(leaf, ip_count=ip_count, app_score=app)
        assert abs(got - want) < tol, f"leaf {leaf}: engine {got} oracle {want}"
        return got


def test_p1_time_in_mesh():
    # TestScoreTimeInMesh: score grows with mesh time up to the cap
    params = mk_params(time_in_mesh_weight=1.0, time_in_mesh_quantum=1.0, time_in_mesh_cap=5.0)
    h = Harness(params)
    h.graft(1, 0, tick=0)
    for tick in range(1, 10):
        h.refresh(tick)
        got = h.check(1)
    assert got == pytest.approx(5.0)  # capped


def test_p2_first_message_deliveries_cap_and_decay():
    params = mk_params(
        first_message_deliveries_weight=2.0,
        first_message_deliveries_cap=10.0,
        first_message_deliveries_decay=0.5,
    )
    h = Harness(params)
    for i in range(15):
        h.deliver_round(0, [(1, 0, True, True)])
    got = h.check(1)
    assert got == pytest.approx(20.0)  # capped at 10 * weight 2
    h.refresh(1)
    assert h.check(1) == pytest.approx(10.0)
    for _ in range(20):
        h.refresh(2)
    assert h.check(1) == 0.0  # decayed to zero


def test_p3_mesh_message_deliveries_deficit():
    # TestScoreMeshMessageDeliveries: inactive until activation ticks; then
    # deficit^2 penalty for under-delivering mesh peers
    params = mk_params(
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_threshold=5.0,
        mesh_message_deliveries_cap=10.0,
        mesh_message_deliveries_decay=1.0 - 1e-9,  # ~no decay
        mesh_message_deliveries_activation=2.0,
    )
    h = Harness(params)
    h.graft(1, 0, tick=0)  # peer 1 delivers nothing
    h.graft(2, 0, tick=0)  # peer 2 delivers well
    assert h.check(1) == 0.0  # not active yet
    for tick in range(1, 6):
        h.deliver_round(tick, [(2, 0, True, True)])
        h.refresh(tick)
    # peer 1: active, 0 deliveries -> -(5^2); peer 2: 5 deliveries -> 0
    assert h.check(1) == pytest.approx(-25.0, rel=1e-4)
    assert h.check(2) == pytest.approx(0.0, abs=1e-4)


def test_p3_near_first_duplicates_count():
    params = mk_params(
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_threshold=4.0,
        mesh_message_deliveries_cap=10.0,
        mesh_message_deliveries_decay=1.0 - 1e-9,
        mesh_message_deliveries_activation=1.0,
    )
    h = Harness(params)
    h.graft(1, 0, tick=0)
    h.graft(2, 0, tick=0)
    # same-round arrival: peer1 first, peer2 duplicate -> both mesh credit
    for tick in range(0, 4):
        slot_pairs = [(1, 0, True, True), (2, 0, True, False)]
        # mark peer2's duplicate arrival of the same message
        arrivals = np.zeros((h.n, h.k, h.m), bool)
        new_bits = np.zeros((h.n, h.m), bool)
        slot = h.next_slot
        h.next_slot += 1
        h.msg_topic[slot] = 0
        h.msg_valid[slot] = True
        arrivals[0, h.leaf_edge(1), slot] = True
        arrivals[0, h.leaf_edge(2), slot] = True
        new_bits[0, slot] = True
        h.first_round[0, slot] = tick
        h.first_edge[0, slot] = h.leaf_edge(1)
        h.oracle.first_delivery(1, 0)
        h.oracle.duplicate_delivery(2, 0, in_window=True)
        h.st = on_deliveries(
            h.st, h.net, h.in_mesh, h.tp,
            pack(jnp.asarray(arrivals)), pack(jnp.asarray(new_bits)),
            edge_eq_words(jnp.asarray(h.first_edge), h.k), jnp.asarray(h.first_round),
            jnp.asarray(h.msg_topic), jnp.asarray(h.msg_valid),
            tick, jnp.asarray(h.tpa.window_rounds),
        )
    h.refresh(4)
    # both peers hit the threshold -> no deficit for either
    assert h.check(1) == pytest.approx(0.0, abs=1e-4)
    assert h.check(2) == pytest.approx(0.0, abs=1e-4)


def test_p3b_sticky_failure_on_prune():
    params = mk_params(
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_threshold=3.0,
        mesh_message_deliveries_cap=10.0,
        mesh_message_deliveries_decay=1.0 - 1e-9,
        mesh_message_deliveries_activation=1.0,
        mesh_failure_penalty_weight=-2.0,
        mesh_failure_penalty_decay=0.5,
    )
    h = Harness(params)
    h.graft(1, 0, tick=0)
    h.refresh(1)
    h.refresh(2)  # mesh_time=2 > activation 1 -> active
    h.prune(1, 0)
    # deficit 3 -> mfp=9 -> P3b = -18; the P3 activation latch is NOT
    # cleared by prune (score.go:662-684), so P3 = -9 still applies
    assert h.check(1) == pytest.approx(-27.0, rel=1e-4)
    h.refresh(3)
    # mfp decayed 0.5 -> P3b=-9; mmd ~undecayed -> P3=-9
    assert h.check(1) == pytest.approx(-18.0, rel=1e-4)


def test_p4_invalid_squared():
    params = mk_params(
        invalid_message_deliveries_weight=-1.0, invalid_message_deliveries_decay=0.9
    )
    h = Harness(params)
    for _ in range(3):
        h.deliver_round(0, [(1, 0, False, True)])
    assert h.check(1) == pytest.approx(-9.0)  # 3^2 * -1


def test_p5_app_specific():
    params = dataclasses.replace(mk_params(), app_specific_weight=0.5)
    h = Harness(params)
    h.oracle.params = params
    h.params = params
    app = jnp.zeros((h.n,), jnp.float32).at[1].set(-10.0)
    got = np.asarray(
        compute_scores(h.st, h.in_mesh, h.tp, params, h.p6, app, h.net)
    )[0, h.leaf_edge(1)]
    want = h.oracle.score(1, app_score=-10.0)
    assert got == pytest.approx(want) == -5.0


def test_p6_ip_colocation():
    # leaves 1,2,3 share an ip group; threshold 1 -> surplus 2 -> -4 each
    ip = np.arange(7, dtype=np.int32)
    ip[[1, 2, 3]] = 100
    params = dataclasses.replace(
        mk_params(),
        ip_colocation_factor_weight=-1.0,
        ip_colocation_factor_threshold=1,
    )
    h = Harness(params, ip_group=ip)
    assert h.check(1, ip_count=3) == pytest.approx(-4.0)
    assert h.check(4, ip_count=1) == pytest.approx(0.0)


def test_p7_behaviour_penalty():
    params = dataclasses.replace(
        mk_params(),
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=2.0,
        behaviour_penalty_decay=0.5,
    )
    h = Harness(params)
    h.penalty(1, 2)
    assert h.check(1) == pytest.approx(0.0)  # at threshold, no excess
    h.penalty(1, 4)
    assert h.check(1) == pytest.approx(-16.0)  # (6-2)^2
    h.refresh(1)
    assert h.check(1) == pytest.approx(-1.0)  # bp 3 -> excess 1


def test_topic_score_cap():
    params = mk_params(first_message_deliveries_weight=1.0,
                       first_message_deliveries_cap=100.0,
                       first_message_deliveries_decay=0.9)
    params = dataclasses.replace(params, topic_score_cap=5.0)
    h = Harness(params)
    for _ in range(20):
        h.deliver_round(0, [(1, 0, True, True)])
    assert h.check(1) == pytest.approx(5.0)


def test_unscored_topic_ignored():
    # deliveries on a topic with no params contribute nothing
    params = mk_params(first_message_deliveries_weight=1.0,
                       first_message_deliveries_cap=100.0,
                       first_message_deliveries_decay=0.9)
    h = Harness(params, n_topics=2)
    # params only cover topic 0..0? mk_params(n_topics=1) -> topic 0 scored
    h.deliver_round(0, [(1, 1, True, True)])
    assert h.check(1) == pytest.approx(0.0)


def test_random_scenario_equivalence():
    # randomized multi-peer multi-topic scenario, engine == oracle
    rng = np.random.default_rng(3)
    params = mk_params(
        n_topics=3,
        time_in_mesh_weight=0.1,
        time_in_mesh_quantum=1.0,
        time_in_mesh_cap=100.0,
        first_message_deliveries_weight=1.5,
        first_message_deliveries_cap=30.0,
        first_message_deliveries_decay=0.7,
        mesh_message_deliveries_weight=-0.5,
        mesh_message_deliveries_threshold=4.0,
        mesh_message_deliveries_cap=20.0,
        mesh_message_deliveries_decay=0.8,
        mesh_message_deliveries_activation=2.0,
        mesh_failure_penalty_weight=-1.0,
        mesh_failure_penalty_decay=0.6,
        invalid_message_deliveries_weight=-2.0,
        invalid_message_deliveries_decay=0.5,
    )
    params = dataclasses.replace(
        params, behaviour_penalty_weight=-0.3, behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.5,
    )
    h = Harness(params, n_leaves=5, n_topics=3, m=64)
    for tick in range(12):
        for leaf in range(1, 6):
            if rng.random() < 0.3:
                t = int(rng.integers(3))
                if rng.random() < 0.5:
                    h.graft(leaf, t, tick)
                else:
                    h.prune(leaf, t)
        dels = []
        for leaf in range(1, 6):
            if rng.random() < 0.6:
                dels.append((leaf, int(rng.integers(3)), bool(rng.random() < 0.8), True))
        h.deliver_round(tick, dels)
        if rng.random() < 0.4:
            h.penalty(int(rng.integers(1, 6)), int(rng.integers(1, 3)))
        h.refresh(tick)
        for leaf in range(1, 6):
            h.check(leaf, tol=1e-3)
