"""Long-horizon soak: 300 rounds of the full v1.1 machine under sustained
publishing, random churn, and a silent-adversary cohort — asserting the
standing invariants the short tests can't see drift in (the reference's
closest analogues are the long multi-hop/churn integration tests,
gossipsub_test.go:853-1121, and the 50-host opportunistic-grafting run)."""

import pytest
import dataclasses

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.trace.events import EV


@pytest.mark.slow
def test_soak_300_rounds_churn_and_adversary():
    n, m, rounds = 60, 32, 300
    rng = np.random.default_rng(42)
    topo = graph.random_connect(n, d=6, seed=1)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)

    adversary = np.zeros(n, bool)
    adversary[rng.choice(n, size=6, replace=False)] = True

    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.01,
        time_in_mesh_quantum=1.0,
        time_in_mesh_cap=10.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_decay=0.9,
        mesh_message_deliveries_threshold=2.0,
        mesh_message_deliveries_cap=10.0,
        mesh_message_deliveries_activation=10,
        mesh_failure_penalty_weight=-1.0,
        mesh_failure_penalty_decay=0.9,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.9,
    )
    sp = PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )
    thr = PeerScoreThresholds(
        gossip_threshold=-10.0,
        publish_threshold=-20.0,
        graylist_threshold=-40.0,
        accept_px_threshold=5.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(
        dataclasses.replace(GossipSubParams(), flood_publish=False),
        thr,
        score_enabled=True,
    )
    st = GossipSubState.init(net, m, cfg, score_params=sp, seed=7)
    step = make_gossipsub_step(
        cfg, net, score_params=sp, dynamic_peers=True,
        adversary_no_forward=adversary,
    )

    up = np.ones(n, bool)
    honest = ~adversary
    deliver_mid = None
    for r in range(rounds):
        # churn: ~2% of honest peers flip state each round, never below 80% up
        flips = rng.random(n) < 0.02
        cand = up.copy()
        cand[flips & honest] = ~up[flips & honest]
        if cand.sum() >= int(0.8 * n):
            up = cand
        # publish from random honest up peers
        k = rng.integers(1, 3)
        pubs = rng.choice(np.flatnonzero(up & honest), size=k, replace=False)
        po = np.full(4, -1, np.int32)
        po[:k] = pubs
        pt = np.where(po >= 0, 0, -1).astype(np.int32)
        pv = po >= 0
        st = step(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv),
                  jnp.asarray(up))
        if r == rounds // 2:
            deliver_mid = int(np.asarray(st.core.events)[EV.DELIVER_MESSAGE])

    # --- standing invariants after 300 rounds -------------------------
    scores = np.asarray(st.scores)
    assert np.isfinite(scores).all(), "scores must stay finite"
    mesh = np.asarray(st.mesh)
    deg = mesh.sum(axis=(1, 2))
    nbr_ok = np.asarray(net.nbr_ok)
    # mesh members only on existing edges
    assert not (mesh & ~nbr_ok[:, None, :]).any()
    # degree bounded by Dhi everywhere (heartbeat prunes oversubscription)
    assert (deg <= cfg.Dhi).all(), deg.max()
    # up honest peers keep receiving: deliveries strictly grew
    ev = np.asarray(st.core.events)
    # sustained delivery: the counter kept growing through the second half
    assert deliver_mid and ev[EV.DELIVER_MESSAGE] > deliver_mid
    assert ev[EV.GRAFT] > 0 and ev[EV.PRUNE] > 0
    assert ev[EV.REMOVE_PEER] > 0 and ev[EV.ADD_PEER] > 0
    # silent adversaries starve their mesh: their observed score at honest
    # neighbors must have gone negative somewhere (P3/P7 catching them)
    adv_cols = np.asarray(net.nbr)  # [N,K] neighbor ids
    adv_edge = adversary[np.clip(adv_cols, 0, None)] & nbr_ok
    adv_scores = scores[adv_edge]
    assert (adv_scores < 0).any(), "adversaries should be penalized"
    # counters the decay loops manage must not blow up
    sc = st.score
    for f in ("fmd", "mmd", "mfp", "imd"):
        arr = np.asarray(getattr(sc, f))
        assert np.isfinite(arr).all() and (arr >= 0).all(), f


@pytest.mark.slow
def test_soak_phase_engine_300_rounds():
    """The phase engine under the same sustained load: 300 rounds as
    ~38 phases of r=8 with churn + silent adversaries. Same standing
    invariants — finite scores, healthy mesh, adversary deficit, live
    delivery — plus continuity across hundreds of phase boundaries."""
    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        make_gossipsub_phase_step,
    )

    n, m, r_phase, phases = 60, 64, 8, 38
    rng = np.random.default_rng(42)
    topo = graph.random_connect(n, d=6, seed=1)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    adversary = np.zeros(n, bool)
    adversary[rng.choice(n, size=6, replace=False)] = True

    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.01,
        time_in_mesh_quantum=1.0,
        time_in_mesh_cap=10.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_decay=0.9,
        mesh_message_deliveries_threshold=2.0,
        mesh_message_deliveries_cap=10.0,
        mesh_message_deliveries_activation=10,
        mesh_failure_penalty_weight=-1.0,
        mesh_failure_penalty_decay=0.9,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.9,
    )
    sp = PeerScoreParams(
        topics={0: tp}, skip_app_specific=True,
        behaviour_penalty_weight=-10.0, behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9, ip_colocation_factor_weight=0.0,
    )
    thr = PeerScoreThresholds(
        gossip_threshold=-10.0, publish_threshold=-20.0,
        graylist_threshold=-40.0,
    )
    cfg = GossipSubConfig.build(
        dataclasses.replace(GossipSubParams(), flood_publish=False), thr,
        score_enabled=True,
    )
    st = GossipSubState.init(net, m, cfg, score_params=sp, seed=7)
    pstep = make_gossipsub_phase_step(
        cfg, net, r_phase, score_params=sp, dynamic_peers=True,
        adversary_no_forward=adversary,
    )

    up = np.ones(n, bool)
    honest = ~adversary
    for p in range(phases):
        flips = rng.random(n) < 0.05
        cand = up.copy()
        cand[flips & honest] = ~up[flips & honest]
        if cand.sum() >= int(0.8 * n):
            up = cand
        po = np.full((r_phase, 4), -1, np.int32)
        for i in range(r_phase):
            k = rng.integers(1, 3)
            po[i, :k] = rng.choice(np.flatnonzero(up & honest), size=k,
                                   replace=False)
        pt = np.where(po >= 0, 0, -1).astype(np.int32)
        pv = po >= 0
        st = pstep(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv),
                   jnp.asarray(up), do_heartbeat=True)

    scores = np.asarray(st.scores)
    assert np.isfinite(scores).all(), "scores must stay finite"
    mesh = np.asarray(st.mesh)
    deg = mesh.sum(axis=(1, 2))
    up_now = np.asarray(st.up)
    # peers that flipped up in the last phase or two are still regrafting
    # (grafts cross one phase after the heartbeat that issues them) — the
    # overwhelming majority of up honest peers must be meshed
    live_deg = deg[up_now & honest]
    assert (live_deg >= 1).mean() > 0.85, (live_deg >= 1).mean()
    assert live_deg.mean() >= cfg.Dlo / 2
    assert (deg <= cfg.Dhi).all()
    # adversary edges sit below honest edges on score (deficit + P7 bite)
    nbr = np.asarray(net.nbr)
    ok = np.asarray(net.nbr_ok)
    adv_edge = adversary[np.clip(nbr, 0, None)] & ok
    hon_edge = ~adversary[np.clip(nbr, 0, None)] & ok
    assert scores[adv_edge].mean() < scores[hon_edge].mean() - 1.0
    ev = np.asarray(st.core.events)
    assert int(ev[EV.DELIVER_MESSAGE]) > 0
