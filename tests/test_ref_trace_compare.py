"""Go-reference trace comparator round-trip (scripts/compare_ref_trace.py):
a synthetic trace in the reference PBTracer format (varint-delimited
TraceEvent protos, tracer.go:131-181) parses and compares against a real
simulator-produced PB trace. No Go toolchain exists in this image (see
README), so the reference side is synthesized in the exact wire format a
Go run would produce — the comparator is format-complete the moment a
real file exists.
"""

import sys

import numpy as np
import pytest

sys.path.insert(0, "scripts")

from compare_ref_trace import cdf_of, latency_samples, load_events, main


def synth_ref_trace(path, hop_ms=50.0, n_msgs=24, n_peers=40, seed=0):
    """Reference-format file: publish + per-peer deliveries whose latency
    is hops x hop_ms with jitter — what a real libp2p run's trace looks
    like after identity details are stripped to the CDF-relevant fields."""
    from go_libp2p_pubsub_tpu.pb import trace_pb2
    from go_libp2p_pubsub_tpu.wire import framing

    rng = np.random.default_rng(seed)
    hop_ns = hop_ms * 1e6
    hops_drawn = []
    with open(path, "wb") as f:
        for m in range(n_msgs):
            mid = b"ref-msg-%04d" % m
            t0 = int(1e9 * m)
            ev = trace_pb2.TraceEvent(
                type=trace_pb2.TraceEvent.PUBLISH_MESSAGE,
                peerID=b"origin", timestamp=t0,
            )
            ev.publishMessage.messageID = mid
            framing.write_delimited(f, ev)
            for p in range(n_peers - 1):
                hops = int(rng.choice([1, 2, 2, 3, 3, 3, 4, 5]))
                hops_drawn.append(hops)
                jitter = rng.uniform(-0.2, 0.2) * hop_ns
                ev = trace_pb2.TraceEvent(
                    type=trace_pb2.TraceEvent.DELIVER_MESSAGE,
                    peerID=b"peer-%d" % p,
                    timestamp=t0 + int(hops * hop_ns + jitter),
                )
                ev.deliverMessage.messageID = mid
                framing.write_delimited(f, ev)
    return hops_drawn


def sim_trace(path, seed=3):
    import jax

    from go_libp2p_pubsub_tpu import api
    from go_libp2p_pubsub_tpu.trace import sinks

    net = api.Network(trace_sinks=[sinks.PBTracer(str(path))], seed=seed)
    nodes = net.add_nodes(40)
    net.dense_connect(d=8, seed=seed)
    [nd.join("t") for nd in nodes]
    net.start()
    net.run(8)  # warm mesh
    for i in range(12):
        nodes[i % 40].topics["t"].publish(b"m%d" % i)
        net.run(1)
    net.run(10)
    net.stop()


def test_ref_format_roundtrip(tmp_path):
    """The synthetic reference file parses (format check) and its CDF is
    recovered exactly (auto hop-time estimation lands on hop_ms)."""
    ref = tmp_path / "ref_trace.pb"
    hops = synth_ref_trace(str(ref))
    events = load_events(str(ref))
    assert len(events) == 24 * 40  # 1 publish + 39 deliveries per msg
    rounds, n_pub, n_dlv, auto = latency_samples(events, None)
    assert n_pub == 24 and n_dlv == 24 * 39
    assert abs(auto - 50e6) / 50e6 < 0.25  # refined hop-time ~50ms
    want = cdf_of(np.asarray(hops, float), 16)
    # with the KNOWN hop time the CDF is recovered exactly (jitter is
    # < half a hop); the auto estimate is asserted close above
    rounds_exact, _, _, _ = latency_samples(events, 50e6)
    got = cdf_of(rounds_exact, 16)
    assert float(np.max(np.abs(want - got))) < 1e-9


def test_compare_ref_vs_sim(tmp_path, capsys):
    """End-to-end: synthetic reference trace vs a real simulator PB trace
    through the CLI entry point; the tool runs, reports a sup-distance,
    and distinguishes matched from mismatched distributions."""
    ref = tmp_path / "ref.pb"
    sim = tmp_path / "sim.pb"
    synth_ref_trace(str(ref))
    sim_trace(str(sim))
    rc = main([str(ref), str(sim), "--envelope", "1.0"])
    out = capsys.readouterr().out
    assert rc == 0 and '"verdict": "PASS"' in out

    # a deliberately slow reference (3x hop time read as 1x) must FAIL a
    # tight envelope — the tool detects distribution mismatch
    slow = tmp_path / "slow.pb"
    synth_ref_trace(str(slow), hop_ms=150.0, seed=1)
    rc = main([str(slow), str(sim), "--ref-round-ns", str(50e6),
               "--envelope", "0.02"])
    out = capsys.readouterr().out
    assert rc == 1 and '"verdict": "FAIL"' in out
