"""Structural-topology tests — tier-2 analogues of the reference's
chain/tree/star suites (gossipsub_test.go:853-1024).

The line and tree graphs have degree < Dlo, so the heartbeat grafts every
edge and the mesh IS the graph: propagation becomes deterministic and the
hop law (first_round - birth == BFS distance) is assertable exactly —
something the reference can only approximate with sleeps. The star test is
the composed PX-bootstrapping scenario: a hub that over-subscribes prunes
with PX, and the leaves must build a working overlay out of those PX
suggestions (host-side pxConnect, the round-2 signed-record path).
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import api, graph, state
from go_libp2p_pubsub_tpu.config import GossipSubParams
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net

from test_gossipsub import pub, run


def _build(topo, n_topics=1, msg_slots=32, seed=0):
    subs = graph.subscribe_all(topo.n_peers, n_topics)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build()
    st = GossipSubState.init(net, msg_slots, cfg, seed=seed)
    step = make_gossipsub_step(cfg, net)
    return net, cfg, st, step


def _bfs_dist(topo, src):
    n = topo.n_peers
    dist = np.full(n, -1, np.int64)
    dist[src] = 0
    frontier = [src]
    while frontier:
        nxt = []
        for i in frontier:
            for k in range(topo.max_degree):
                if topo.nbr_ok[i, k]:
                    j = int(topo.nbr[i, k])
                    if dist[j] < 0:
                        dist[j] = dist[i] + 1
                        nxt.append(j)
        frontier = nxt
    return dist


def test_multihop_line_hop_law():
    # 6-host chain (gossipsub_test.go:853-894): the far end receives, and
    # each node's arrival round is exactly its distance from the origin
    topo = graph.line(6)
    net, cfg, st, step = _build(topo)
    st = run(step, st, 8)  # mesh warmup (grafts all edges: degree <= 2)
    mesh = np.asarray(st.mesh[:, 0, :])
    assert (mesh.sum(axis=1) == topo.degree).all(), "line mesh must be the line"
    st = step(st, *pub([0], [0]))
    st = run(step, st, 8)
    h = np.asarray(state.hops(st.core.msgs, st.core.dlv))[:, 0]
    assert (h == _bfs_dist(topo, 0)).all()


def test_tree_topology_hop_law():
    # the reference's hand-built 10-node tree (gossipsub_test.go:903-921)
    edges = [(0, 1), (1, 2), (1, 4), (2, 3), (0, 5), (5, 6), (5, 8),
             (6, 7), (8, 9)]
    topo = graph.from_edges(10, edges)
    net, cfg, st, step = _build(topo)
    st = run(step, st, 8)
    mesh = np.asarray(st.mesh[:, 0, :])
    assert mesh.sum() == 2 * len(edges), "tree mesh must be the whole tree"
    # checkMessageRouting publishes from 9 and 3 (gossipsub_test.go:940)
    for origin, slot in ((9, 0), (3, 1)):
        st = step(st, *pub([origin], [0]))
        st = run(step, st, 8)
        h = np.asarray(state.hops(st.core.msgs, st.core.dlv))[:, slot]
        assert (h == _bfs_dist(topo, origin)).all()


def test_tree_generator_shape():
    topo = graph.tree(13, branching=3)
    deg = topo.degree
    assert deg[0] == 3            # root: 3 children
    assert deg.max() == 4         # internal: parent + 3 children
    assert (deg >= 1).all()
    d = _bfs_dist(topo, 0)
    assert d.max() == 2 and (d >= 0).all()


@pytest.mark.slow
def test_star_px_bootstrap():
    """gossipsub_test.go:945-1024: start as a star; PRUNE-with-PX must grow
    the overlay until leaves connect to each other, and publishes from
    every corner still reach everyone."""
    params = GossipSubParams(do_px=True, flood_publish=True)
    net = api.Network(params=params, px_connect=True)
    nodes = net.add_nodes(20)
    for leaf in nodes[1:]:
        net.connect(nodes[0], leaf)  # hub-and-spoke
    for nd in nodes:
        nd.join("test")
    net.start()
    net.run(16)

    # every peer ends up with more than its single hub link
    # (gossipsub_test.go:1009-1013)
    deg = np.zeros(len(nodes), np.int64)
    for a, b in net._edges:
        deg[a] += 1
        deg[b] += 1
    assert (deg[1:] > 1).all(), f"leaves still hub-only: {deg.tolist()}"

    # propagation from three corners of the overlay reaches all peers
    subs = [nd.topics["test"].subscribe() for nd in nodes]
    for origin in (0, 7, 19):
        nodes[origin].topics["test"].publish(b"star-%d" % origin)
        net.run(8)
        got = sum(1 for s in subs if any(True for _ in s))
        assert got == len(nodes), f"origin {origin}: {got}/{len(nodes)}"
