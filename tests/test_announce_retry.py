"""Announce-retry with jitter under backpressure (pubsub.go:842-901).

A runtime Join must announce its subscription (SubOpts) to every peer;
with `queue_cap` an announcement riding a saturated link is dropped and
retried with jitter. Until it lands, that neighbor cannot see the
subscription — no grafts, no gossip, no fanout selection toward the
joiner (the stale-subscription window the reference exhibits under
churn + congestion)."""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import api


def _mesh_degree(net, idx):
    return int(np.asarray(net.state.mesh)[idx].sum())


def test_announce_holes_delay_mesh_formation_then_converge():
    """With queue_cap congestion, a late joiner's mesh forms only as the
    announce retries land; without congestion it forms immediately. Both
    converge."""
    net = api.Network(queue_cap=2, seed=5)
    nodes = net.add_nodes(16)
    net.dense_connect(d=6, seed=2)
    subs = [nd.join("t").subscribe() for nd in nodes[:-1]]  # node 15 waits
    net.start()
    net.run(6)

    # saturate the network so announce drops are likely: heavy publishing
    for r in range(3):
        for nd in nodes[:6]:
            nd.topics["t"].publish(b"x%d" % r + bytes([nd.idx]))
    late = nodes[-1].join("t").subscribe()
    # the announce is pending toward every neighbor of node 15
    assert net._pending_announce, "join under queue_cap must queue announces"
    assert net._sub_holes is not None and net._sub_holes[:, :, 0].any()

    net.run(20)
    # all announces eventually land (retries with jitter, then delivery)
    assert not net._pending_announce
    assert net._sub_holes is None
    # and the late joiner is meshed + receiving
    net.run(5)
    assert _mesh_degree(net, 15) >= 1
    nodes[0].topics["t"].publish(b"final")
    net.run(6)
    got = [m for m in iter(late.next, None)]
    assert any(m.data == b"final" for m in got)


def test_announce_retries_counted_under_sustained_congestion():
    """Sustained saturation of the JOINER's own outbound links produces
    measured retries (the announce shares the joiner's per-peer writer
    queues with its forwarding traffic — announce/DropRPC/retry path).
    The joiner is a busy forwarder on a background topic, so its queues
    are full when the new topic's SubOpts goes out."""
    net = api.Network(queue_cap=1, seed=7)
    nodes = net.add_nodes(12)
    net.dense_connect(d=5, seed=3)
    for nd in nodes:
        nd.join("bg").subscribe()      # everyone forwards bg traffic
    for nd in nodes[:-1]:
        nd.join("t").subscribe()
    net.start()
    net.run(8)
    # saturate the queue_cap=1 links BEFORE the join so the announce's
    # first attempt already rides full queues, and keep them saturated
    for r in range(3):
        for nd in nodes[:6]:
            nd.topics["bg"].publish(bytes([r, nd.idx]))
        net.run(1)
    retries_seen = False
    nodes[-1].join("t")
    assert net._pending_announce
    for r in range(10):
        for nd in nodes[:6]:
            nd.topics["bg"].publish(bytes([64 + r, nd.idx]))
        net.run(1)
        retries_seen = retries_seen or net.announce_retries > 0
    assert retries_seen, "saturated joiner links must drop + retry announces"
    net.run(25)
    assert not net._pending_announce  # converges once congestion clears


def test_no_queue_cap_announce_is_instantaneous():
    """Without backpressure the announce model is inert: visibility next
    round, no pending state (the documented lossless-wire behavior)."""
    net = api.Network(seed=3)
    nodes = net.add_nodes(10)
    net.dense_connect(d=4, seed=1)
    for nd in nodes[:-1]:
        nd.join("t").subscribe()
    net.start()
    net.run(4)
    nodes[-1].join("t").subscribe()
    assert not net._pending_announce
    assert net._sub_holes is None
    net.run(10)
    assert _mesh_degree(net, 9) >= 1
