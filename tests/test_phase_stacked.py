"""Stacked/coalesced vs legacy parity suite (round-7 tentpole).

The round-7 data-plane restructuring — the coalesced wire exchange, the
leading-axis-stacked attribution accumulators (_AccStack), the
phase-head publish plan (state.PhasePubPlan), and the stacked
recycled-slot clears in allocate_publishes — claims BIT-IDENTICAL
semantics to the legacy per-plane path. This suite is that claim's
oracle: every router (gossipsub phase engine, floodsub, randomsub, the
per-round gossipsub step) is run on both paths over the same schedule
and the FULL state trees compared, at r ∈ {1, 8, 16} for the phase
engine and across the feature matrix (gater + validation throttle +
queue_cap + adversary, async validation + per-topic delays + exact
trace, wide topic universes (non-incremental membership planes),
dynamic peers).

The phase engine's legacy path additionally stays pinned to the
per-round step through the existing r=1 suite (tests/test_phase.py runs
the DEFAULT — coalesced — path against the per-round oracle), so the
chain per-round == phase(r=1, coalesced) == phase(r=1, legacy) closes.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import PeerGaterParams
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.gossipsub import make_gossipsub_step
from go_libp2p_pubsub_tpu.models.gossipsub_phase import make_gossipsub_phase_step
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.state import Net, PhasePubPlan, SimState, allocate_publishes

from test_phase import N, P, assert_states_equal, build, run_phase, schedule

M = 64


def _ab_phase(r, rounds=16, seed=3, codes=True, n=N, sched_seed=None,
              dynamic=False, **cfg_kw):
    """Run the phase engine stacked (wire_coalesced=True, the default)
    and legacy over one schedule; return both final states."""
    outs = []
    po, pt, pv = schedule(rounds, seed=sched_seed or seed, n=n, codes=codes)
    ups = None
    if dynamic:
        rng = np.random.default_rng(seed)
        ups = rng.random((rounds // r, n)) > 0.05
    for coalesced in (True, False):
        net, cfg, sp, st = build(seed=seed, n=n, **cfg_kw)
        cfg = dataclasses.replace(cfg, wire_coalesced=coalesced)
        pstep = make_gossipsub_phase_step(
            cfg, net, r, score_params=sp,
            gater_params=cfg_kw.get("gater_params"),
            dynamic_peers=dynamic,
        )
        if dynamic:
            g = po.shape[0] // r
            for p in range(g):
                st = pstep(st, po[p * r:(p + 1) * r], pt[p * r:(p + 1) * r],
                           pv[p * r:(p + 1) * r], jnp.asarray(ups[p]),
                           do_heartbeat=True)
        else:
            st = run_phase(pstep, st, po, pt, pv, r)
        outs.append(st)
    return outs


@pytest.mark.parametrize("r", [1, 8])
def test_phase_stacked_vs_legacy_bitexact(r):
    """Rich v1.1 config (score + flood_publish + PX + fanout + mixed
    verdicts): full state trees bit-identical across the A/B paths."""
    sa, sb = _ab_phase(r)
    assert_states_equal(sa, sb, f"stacked-r{r}/")


@pytest.mark.slow
def test_phase_stacked_vs_legacy_bitexact_r16():
    sa, sb = _ab_phase(16, rounds=32)
    assert_states_equal(sa, sb, "stacked-r16/")


@pytest.mark.slow
def test_phase_stacked_vs_legacy_gater_throttle_queuecap():
    """The gater accumulator lanes + validation throttle + lossy queue:
    the stacked [N,K,W] dup/rejw/ignw lanes and the throttle's accepted
    lane must fold identically."""
    sa, sb = _ab_phase(
        4, rounds=12, seed=7,
        gater_params=PeerGaterParams(), validation_capacity=3, queue_cap=3,
    )
    assert_states_equal(sa, sb, "stacked-gater/")


@pytest.mark.slow
def test_phase_stacked_vs_legacy_validation_delay_trace_exact():
    """Async validation pipeline (per-topic delays) + the exact-trace dup
    lane — the one NON-keep-masked lane of the stack."""
    sa, sb = _ab_phase(
        4, rounds=12, seed=11,
        validation_delay_rounds=2, validation_delay_topic=(1, 2, 1),
        trace_exact=True,
    )
    assert_states_equal(sa, sb, "stacked-valdelay/")


@pytest.mark.slow
def test_phase_stacked_vs_legacy_dynamic_peers():
    sa, sb = _ab_phase(4, rounds=12, seed=13, codes=False, dynamic=True)
    assert_states_equal(sa, sb, "stacked-dyn/")


def test_phase_stacked_vs_legacy_wide_topics():
    """T > 8 disables the incremental membership planes: the coalesced
    path's per-sub-round recompute must read the plan's table snapshots
    bit-identically (the non-incr branch of the loop)."""
    n, t = 48, 12
    outs = []
    rng = np.random.default_rng(5)
    po = jnp.asarray(rng.integers(0, n, size=(8, P)).astype(np.int32))
    pt = jnp.asarray(rng.integers(0, t, size=(8, P)).astype(np.int32))
    pv = jnp.asarray(np.ones((8, P), bool))
    for coalesced in (True, False):
        topo = graph.random_connect(n, 8, seed=5)
        subs = graph.subscribe_random(n, n_topics=t, topics_per_peer=3, seed=5)
        net = Net.build(topo, subs)
        from test_phase import score_params
        sp = score_params(n_topics=t)
        from go_libp2p_pubsub_tpu.config import (
            GossipSubParams,
            PeerScoreThresholds,
        )
        from go_libp2p_pubsub_tpu.models.gossipsub import GossipSubConfig

        cfg = GossipSubConfig.build(
            GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
            wire_coalesced=coalesced,
        )
        from go_libp2p_pubsub_tpu.models.gossipsub import GossipSubState

        st = GossipSubState.init(net, M, cfg, score_params=sp, seed=5)
        pstep = make_gossipsub_phase_step(cfg, net, 4, score_params=sp)
        st = run_phase(pstep, st, po, pt, pv, 4)
        outs.append(st)
    assert_states_equal(outs[0], outs[1], "stacked-wide/")


def test_phase_pub_plan_matches_sequential_allocate():
    """PhasePubPlan's last-write-wins snapshots == r sequential
    allocate_publishes calls, bit for bit — including HEAVY slot
    recycling (r·P >> M) and REJECT/IGNORE verdict codes."""
    n, m, r, p = 16, 8, 6, 4  # 24 publishes into 8 slots: 3x recycled
    rng = np.random.default_rng(0)
    po = rng.integers(0, n, size=(r, p)).astype(np.int32)
    po[rng.random((r, p)) < 0.3] = -1  # pads
    pt = rng.integers(0, 3, size=(r, p)).astype(np.int32)
    pv = rng.choice([0, 0, 0, 1, 2], size=(r, p)).astype(np.int32)
    st = SimState.init(n, m, seed=0, k=4)
    msgs, dlv = st.msgs, st.dlv
    # non-trivial initial table so untouched slots must survive
    msgs = msgs.replace(
        topic=jnp.arange(m, dtype=jnp.int32) % 3,
        origin=jnp.arange(m, dtype=jnp.int32) % n,
        valid=jnp.asarray(np.arange(m) % 2 == 0),
    )
    plan = PhasePubPlan(msgs, n, st.tick, jnp.asarray(po), jnp.asarray(pt),
                        jnp.asarray(pv))
    for i in range(r):
        snap = plan.msgs_at(i)
        for f in ("topic", "origin", "birth", "valid", "ignored", "cursor"):
            np.testing.assert_array_equal(
                np.asarray(getattr(snap, f)), np.asarray(getattr(msgs, f)),
                err_msg=f"snapshot[{i}].{f}",
            )
        msgs, dlv, slots, is_pub, keep_w, pub_words = allocate_publishes(
            msgs, dlv, st.tick + i, jnp.asarray(po[i]), jnp.asarray(pt[i]),
            jnp.asarray(pv[i]),
        )
        np.testing.assert_array_equal(
            np.asarray(plan.keep_w[i]), np.asarray(keep_w), err_msg=f"keep[{i}]")
        np.testing.assert_array_equal(
            np.asarray(plan.pub_words[i]), np.asarray(pub_words),
            err_msg=f"pub_words[{i}]")
        got = np.asarray(plan.sidx[i])[np.asarray(is_pub)]
        np.testing.assert_array_equal(
            got, np.asarray(slots)[np.asarray(is_pub)], err_msg=f"slots[{i}]")
    final = plan.msgs_at(r)
    for f in ("topic", "origin", "birth", "valid", "ignored", "cursor"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final, f)), np.asarray(getattr(msgs, f)),
            err_msg=f"final.{f}",
        )


def _sim_net(seed=1, n=32):
    topo = graph.random_connect(n, 6, seed=seed)
    subs = graph.subscribe_random(n, n_topics=2, topics_per_peer=1, seed=seed)
    return Net.build(topo, subs)


@pytest.mark.parametrize("queue_cap,val_delay", [(0, 0), (2, 2)])
def test_floodsub_stacked_vs_legacy(queue_cap, val_delay):
    """Floodsub shares allocate_publishes' stacked clears: state trees
    bit-identical with them on vs off (incl. pipeline + lossy queue)."""
    n = 32
    net = _sim_net()
    rng = np.random.default_rng(2)
    po_all = rng.integers(0, n, size=(10, 2)).astype(np.int32)
    po_all[6:] = -1  # drain tail
    outs = []
    for stacked in (True, False):
        st = SimState.init(n, 16, seed=2, k=net.max_degree,
                           val_delay=val_delay)
        for i in range(10):
            st = floodsub_step(
                net, st, jnp.asarray(po_all[i]),
                jnp.asarray(np.full((2,), i % 2, np.int32)),
                jnp.asarray(np.ones((2,), bool)),
                queue_cap=queue_cap, stacked=stacked,
            )
        outs.append(st)
    assert_states_equal(outs[0], outs[1], "flood-stacked/")


def test_randomsub_stacked_vs_legacy():
    n = 32
    net = _sim_net(seed=3)
    rng = np.random.default_rng(4)
    po_all = rng.integers(0, n, size=(10, 2)).astype(np.int32)
    outs = []
    for stacked in (True, False):
        step = make_randomsub_step(net, stacked=stacked)
        st = SimState.init(n, 16, seed=4, k=net.max_degree)
        for i in range(10):
            st = step(st, jnp.asarray(po_all[i]),
                      jnp.asarray(np.full((2,), i % 2, np.int32)),
                      jnp.asarray(np.ones((2,), bool)))
        outs.append(st)
    assert_states_equal(outs[0], outs[1], "randomsub-stacked/")


def test_per_round_gossipsub_stacked_vs_legacy():
    """The per-round step's stacked clears (allocate_publishes + the
    iwant/served tail fold) A/B via cfg.wire_coalesced."""
    outs = []
    po, pt, pv = schedule(10, seed=9, codes=True)
    for coalesced in (True, False):
        net, cfg, sp, st = build(seed=9)
        cfg = dataclasses.replace(cfg, wire_coalesced=coalesced)
        step = make_gossipsub_step(cfg, net, score_params=sp)
        for i in range(10):
            st = step(st, po[i], pt[i], pv[i])
        outs.append(st)
    assert_states_equal(outs[0], outs[1], "per-round-stacked/")
