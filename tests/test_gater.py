"""Peer gater + validation-throttle tests (peer_gater_test.go /
TestValidateOverload analogues)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerGaterParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.score.gater import GaterState, gater_accept, gater_on_round
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.trace.events import EV


def test_gater_accept_calm_conditions():
    n, k = 4, 3
    topo = graph.connect_all(n)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    params = PeerGaterParams()
    gs = GaterState.empty(n, net.max_degree)
    key = jax.random.key(0)
    # no throttle history -> accept everything
    acc = gater_accept(gs, net, params, 60, jnp.int32(100), key)
    assert bool(np.asarray(acc).all())
    # throttle pressure but quiet period elapsed -> accept
    gs2 = gs.replace(throttle=jnp.full((n,), 10.0), validate=jnp.full((n,), 10.0),
                     last_throttle=jnp.zeros((n,), jnp.int32))
    acc = gater_accept(gs2, net, params, 60, jnp.int32(1000), key)
    assert bool(np.asarray(acc).all())
    # fresh throttling + bad ratio + bad stats -> drops appear
    gs3 = gs2.replace(
        last_throttle=jnp.full((n,), 999, jnp.int32),
        reject=jnp.full((n, net.max_degree), 50.0),
    )
    accs = []
    for i in range(50):
        accs.append(np.asarray(gater_accept(gs3, net, params, 60, jnp.int32(1000),
                                            jax.random.fold_in(key, i))))
    frac = np.mean(accs)
    # acceptance prob = (1+0)/(1+16*50*shared...) ~ tiny
    assert frac < 0.2


def test_gater_good_peer_mostly_accepted():
    n = 4
    topo = graph.connect_all(n)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    params = PeerGaterParams()
    gs = GaterState.empty(n, net.max_degree)
    gs = gs.replace(
        throttle=jnp.full((n,), 10.0),
        validate=jnp.full((n,), 10.0),
        last_throttle=jnp.full((n,), 999, jnp.int32),
        deliver=jnp.full((n, net.max_degree), 100.0),
        duplicate=jnp.full((n, net.max_degree), 1.0),
    )
    key = jax.random.key(1)
    accs = [
        np.asarray(gater_accept(gs, net, params, 60, jnp.int32(1000), jax.random.fold_in(key, i)))
        for i in range(50)
    ]
    # (1+deliver)/(1+deliver+0.125*dup) ~ high acceptance
    assert np.mean(accs) > 0.9


def test_validation_throttle_limits_intake():
    # capacity 1/round: a burst of publishes from many origins overflows
    # receivers' validation queues -> throttled receipts traced as Reject
    n = 30
    topo = graph.connect_all(n)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    cfg = GossipSubConfig.build(
        gater_params=PeerGaterParams(), validation_capacity=1
    )
    st = GossipSubState.init(net, 64, cfg, seed=0)
    step = make_gossipsub_step(cfg, net, gater_params=PeerGaterParams())
    # warm the mesh
    for _ in range(6):
        st = step(st, *no_publish())
    # burst: 4 distinct publishes in one round
    po = jnp.asarray(np.array([0, 1, 2, 3], np.int32))
    pt = jnp.zeros((4,), jnp.int32)
    pv = jnp.ones((4,), bool)
    st = step(st, po, pt, pv)
    for _ in range(4):
        st = step(st, *no_publish())
    ev = np.asarray(st.core.events)
    assert ev[EV.REJECT_MESSAGE] > 0, "overflow receipts must be throttled"
    g = st.gater
    assert float(jnp.sum(g.throttle)) > 0
    # throttled peers eventually still converge via re-receipt (the message
    # isn't marked seen); most peers should have most messages
    have = np.asarray(bitset.unpack(st.core.dlv.have, 64))
    assert have[:, :4].mean() > 0.6


@pytest.mark.slow
def test_gater_protects_under_overload():
    # sustained invalid flood from one peer + tight validation capacity:
    # gater kicks in and the spammer's edges see drops while the honest
    # publisher keeps delivering
    n = 24
    topo = graph.connect_all(n)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    gp = PeerGaterParams()
    tp = TopicScoreParams(mesh_message_deliveries_weight=0.0, mesh_failure_penalty_weight=0.0)
    sp = PeerScoreParams(topics={0: tp}, skip_app_specific=True,
                         behaviour_penalty_weight=-1.0, behaviour_penalty_threshold=1.0,
                         behaviour_penalty_decay=0.9)
    import dataclasses
    params = dataclasses.replace(GossipSubParams(), flood_publish=True)
    cfg = GossipSubConfig.build(
        params, PeerScoreThresholds(), score_enabled=True,
        gater_params=gp, validation_capacity=2,
    )
    st = GossipSubState.init(net, 64, cfg, score_params=sp, seed=1)
    step = make_gossipsub_step(cfg, net, score_params=sp, gater_params=gp)
    for _ in range(6):
        st = step(st, *no_publish())
    spammer = 5
    for i in range(25):
        po = jnp.asarray(np.array([spammer, spammer, spammer, -1], np.int32))
        pt = jnp.zeros((4,), jnp.int32)
        pv = jnp.zeros((4,), bool)  # invalid spam flood
        st = step(st, po, pt, pv)
    g = st.gater
    assert float(jnp.sum(g.throttle)) > 0, "validation overload must register"
    # spammer edges accumulated reject stats at its neighbors
    rej = np.asarray(g.reject)
    spam_rej = []
    for j in range(n):
        for k in range(topo.max_degree):
            if topo.nbr_ok[j, k] and topo.nbr[j, k] == spammer:
                spam_rej.append(rej[j, k])
    assert max(spam_rej) > 0
