"""PJRT C-API bridge (native/pjrt_bridge.cc): load a real PJRT plugin,
compile StableHLO exported from jax, and execute against host buffers —
zero Python in the device loop. Survey §2 BUILD-NEW ("cgo→PJRT bridge");
the C ABI is Go-consumable, these tests drive it through ctypes.

The execute tests run on whatever plugin is discoverable (the axon TPU
plugin on this image); they skip — not fail — when no plugin or no
device session is available, since that's an environment property.
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.native import pjrt

pytestmark = pytest.mark.skipif(
    not pjrt.available() and not pjrt.build(),
    reason="pjrt bridge library not buildable",
)


def test_load_bad_path_errors():
    with pytest.raises(pjrt.PjrtError):
        pjrt.PjrtPlugin.load("/nonexistent/plugin.so")


@pytest.fixture(scope="module")
def client():
    path = pjrt.default_plugin_path()
    if path is None:
        pytest.skip("no PJRT plugin on this machine")
    plugin = pjrt.PjrtPlugin.load(path)
    opts = pjrt.axon_create_options() if "axon" in path else {}
    try:
        c = plugin.create_client(opts)
    except pjrt.PjrtError as e:
        pytest.skip(f"PJRT client unavailable: {e}")
    yield c
    c.close()


def test_plugin_api_version():
    path = pjrt.default_plugin_path()
    if path is None:
        pytest.skip("no PJRT plugin on this machine")
    plugin = pjrt.PjrtPlugin.load(path)
    major, minor = plugin.api_version
    assert major == 0 and minor > 0


def test_client_platform_and_devices(client):
    assert client.platform_name != ""
    assert client.device_count() >= 1


def test_buffer_host_roundtrip(client):
    for arr in (
        np.arange(24, dtype=np.float32).reshape(4, 6),
        np.array([1, -2, 3, -4], dtype=np.int32),
        np.arange(30, dtype=np.float32).reshape(2, 3, 5),
    ):
        buf = client.buffer_from_numpy(arr)
        out = buf.to_numpy()
        assert out.dtype == arr.dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)


def test_compile_and_execute(client):
    import jax
    import jax.numpy as jnp

    def f(x, y):
        return x @ y, jnp.sum(x) + 1.0

    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    y = np.full((4, 2), 2.0, np.float32)
    exported = jax.export.export(jax.jit(f))(
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        jax.ShapeDtypeStruct(y.shape, y.dtype),
    )
    exe = client.compile(exported.mlir_module_serialized)
    assert exe.num_outputs == 2
    outs = exe.run([x, y])
    np.testing.assert_allclose(outs[0], x @ y)
    np.testing.assert_allclose(outs[1], x.sum() + 1.0)


def test_execute_router_selection_kernel(client):
    """Execute a real framework kernel through the bridge: the random-k
    peer selection primitive the heartbeat is built on (ops/select.py)."""
    import jax

    from go_libp2p_pubsub_tpu.ops.select import select_random_mask

    def kern(key, elig):
        return select_random_mask(key, elig, 3)

    key = np.zeros(2, dtype=np.uint32)
    elig = np.ones((8, 16), bool)
    exported = jax.export.export(jax.jit(kern))(
        jax.ShapeDtypeStruct((2,), np.uint32),
        jax.ShapeDtypeStruct(elig.shape, bool),
    )
    exe = client.compile(exported.mlir_module_serialized)
    (sel,) = exe.run([key, elig])
    assert sel.shape == elig.shape
    assert (sel.sum(axis=1) == 3).all()


def test_compile_garbage_errors(client):
    with pytest.raises(pjrt.PjrtError):
        client.compile(b"not an mlir module")


@pytest.mark.parametrize("scored", [False, True])
@pytest.mark.slow
def test_execute_full_gossipsub_step(client, scored):
    """The flagship program end-to-end through the native bridge: export
    the full jitted GossipSub round step (state pytree flattened to
    buffers, PRNG key passed as raw key-data) and run one round with zero
    Python in the loop — the embedding a Go host would use. The scored
    variant is the production v1.1 machine (live score plane +
    thresholds), pinning the ABI the Go embedder depends on."""
    import jax
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreParams,
        PeerScoreThresholds,
        TopicScoreParams,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.state import Net

    n, m = 64, 32
    topo = graph.ring_lattice(n, d=3)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    if scored:
        sp = PeerScoreParams(
            topics={0: TopicScoreParams(
                mesh_message_deliveries_weight=-0.5,
                mesh_message_deliveries_threshold=2.0,
                mesh_message_deliveries_activation=4.0,
                mesh_message_deliveries_window=2.0,
            )},
            skip_app_specific=True,
            behaviour_penalty_weight=-1.0,
            behaviour_penalty_threshold=1.0,
            behaviour_penalty_decay=0.9,
        )
        cfg = GossipSubConfig.build(
            GossipSubParams(), PeerScoreThresholds(), score_enabled=True
        )
        st = GossipSubState.init(net, m, cfg, score_params=sp, seed=0)
        step = make_gossipsub_step(cfg, net, score_params=sp)
    else:
        cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds())
        st = GossipSubState.init(net, m, cfg, seed=0)
        step = make_gossipsub_step(cfg, net)

    leaves, treedef = jax.tree_util.tree_flatten(st)
    key_idx = [
        i for i, l in enumerate(leaves)
        if jnp.issubdtype(l.dtype, jax.dtypes.prng_key)
    ]
    assert len(key_idx) == 1
    ki = key_idx[0]

    def step_raw(*flat):
        flat = list(flat)
        flat[ki] = jax.random.wrap_key_data(flat[ki])
        po, pt, pv = flat[-3:]
        s = jax.tree_util.tree_unflatten(treedef, flat[:-3])
        out = step(s, po, pt, pv)
        out_leaves = jax.tree_util.tree_flatten(out)[0]
        out_leaves[ki] = jax.random.key_data(out_leaves[ki])
        return tuple(out_leaves)

    np_in = []
    for i, l in enumerate(leaves):
        if i == ki:
            l = jax.random.key_data(l)
        np_in.append(np.asarray(l))
    po = np.array([5, -1, -1, -1], np.int32)
    pt = np.array([0, -1, -1, -1], np.int32)
    pv = np.array([True, False, False, False])
    np_in += [po, pt, pv]

    shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in np_in]
    exported = jax.export.export(jax.jit(step_raw))(*shapes)
    # compile_exported records module_kept_var_idx: XLA prunes unused
    # parameters (e.g. state fields this config never reads), and passing
    # the full list would mismatch the executable's arity
    exe = client.compile_exported(exported)
    outs = exe.run(np_in)
    assert len(outs) == len(leaves)

    # the same step in-process must agree exactly
    ref = step(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))
    ref_leaves = jax.tree_util.tree_flatten(ref)[0]
    ref_leaves[ki] = jax.random.key_data(ref_leaves[ki])
    for a, b in zip(outs, ref_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pure_c_host_executes_module(tmp_path):
    """The Go-embedding proof, minus Go (not in this image): a pure-C
    program (native/example_host.c) linked against the bridge library
    compiles and executes an exported StableHLO module with no Python in
    the process at all."""
    import pathlib
    import subprocess

    import jax

    from go_libp2p_pubsub_tpu.native.pjrt import (
        axon_create_options,
        default_compile_options,
        default_plugin_path,
    )

    repo = pathlib.Path(__file__).resolve().parent.parent
    host = repo / "native" / "example_host"
    if not host.exists():
        rc = subprocess.run(["make", "-C", str(repo / "native"), "example_host"],
                            capture_output=True, text=True)
        if rc.returncode != 0:
            pytest.skip(f"example_host not buildable: {rc.stderr[-200:]}")
    plugin = default_plugin_path()
    if plugin is None:
        pytest.skip("no PJRT plugin on this machine")

    def f(x):
        return x * 2.0 + 1.0

    exported = jax.export.export(jax.jit(f))(
        jax.ShapeDtypeStruct((8,), np.float32)
    )
    mod = tmp_path / "m.mlirpb"
    mod.write_bytes(exported.mlir_module_serialized)
    opts = tmp_path / "opts.pb"
    opts.write_bytes(default_compile_options())

    args = [str(host), plugin, str(mod), str(opts)]
    if "axon" in plugin:
        for name, val in axon_create_options().items():
            t = "s" if isinstance(val, str) else "i"
            args.append(f"{name}:{t}:{val}")
    rc = subprocess.run(args, capture_output=True, text=True, timeout=240)
    if rc.returncode != 0 and "client:" in rc.stderr:
        pytest.skip(f"PJRT client unavailable to C host: {rc.stderr[-150:]}")
    assert rc.returncode == 0, rc.stderr[-400:]
    # f([1..8]) = [3 5 7 9 11 13 15 17]
    assert rc.stdout.strip().startswith("out0: 3 5 7 9 11 13 15 17"), rc.stdout
