"""PJRT C-API bridge (native/pjrt_bridge.cc): load a real PJRT plugin,
compile StableHLO exported from jax, and execute against host buffers —
zero Python in the device loop. Survey §2 BUILD-NEW ("cgo→PJRT bridge");
the C ABI is Go-consumable, these tests drive it through ctypes.

The execute tests run on whatever plugin is discoverable (the axon TPU
plugin on this image); they skip — not fail — when no plugin or no
device session is available, since that's an environment property.
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.native import pjrt

pytestmark = pytest.mark.skipif(
    not pjrt.available() and not pjrt.build(),
    reason="pjrt bridge library not buildable",
)


def test_load_bad_path_errors():
    with pytest.raises(pjrt.PjrtError):
        pjrt.PjrtPlugin.load("/nonexistent/plugin.so")


@pytest.fixture(scope="module")
def client():
    path = pjrt.default_plugin_path()
    if path is None:
        pytest.skip("no PJRT plugin on this machine")
    plugin = pjrt.PjrtPlugin.load(path)
    opts = pjrt.axon_create_options() if "axon" in path else {}
    try:
        c = plugin.create_client(opts)
    except pjrt.PjrtError as e:
        pytest.skip(f"PJRT client unavailable: {e}")
    yield c
    c.close()


def test_plugin_api_version():
    path = pjrt.default_plugin_path()
    if path is None:
        pytest.skip("no PJRT plugin on this machine")
    plugin = pjrt.PjrtPlugin.load(path)
    major, minor = plugin.api_version
    assert major == 0 and minor > 0


def test_client_platform_and_devices(client):
    assert client.platform_name != ""
    assert client.device_count() >= 1


def test_buffer_host_roundtrip(client):
    for arr in (
        np.arange(24, dtype=np.float32).reshape(4, 6),
        np.array([1, -2, 3, -4], dtype=np.int32),
        np.arange(30, dtype=np.float32).reshape(2, 3, 5),
    ):
        buf = client.buffer_from_numpy(arr)
        out = buf.to_numpy()
        assert out.dtype == arr.dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)


def test_compile_and_execute(client):
    import jax
    import jax.numpy as jnp

    def f(x, y):
        return x @ y, jnp.sum(x) + 1.0

    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    y = np.full((4, 2), 2.0, np.float32)
    exported = jax.export.export(jax.jit(f))(
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        jax.ShapeDtypeStruct(y.shape, y.dtype),
    )
    exe = client.compile(exported.mlir_module_serialized)
    assert exe.num_outputs == 2
    outs = exe.run([x, y])
    np.testing.assert_allclose(outs[0], x @ y)
    np.testing.assert_allclose(outs[1], x.sum() + 1.0)


def test_execute_router_selection_kernel(client):
    """Execute a real framework kernel through the bridge: the random-k
    peer selection primitive the heartbeat is built on (ops/select.py)."""
    import jax

    from go_libp2p_pubsub_tpu.ops.select import select_random_mask

    def kern(key, elig):
        return select_random_mask(key, elig, 3)

    key = np.zeros(2, dtype=np.uint32)
    elig = np.ones((8, 16), bool)
    exported = jax.export.export(jax.jit(kern))(
        jax.ShapeDtypeStruct((2,), np.uint32),
        jax.ShapeDtypeStruct(elig.shape, bool),
    )
    exe = client.compile(exported.mlir_module_serialized)
    (sel,) = exe.run([key, elig])
    assert sel.shape == elig.shape
    assert (sel.sum(axis=1) == 3).all()


def test_compile_garbage_errors(client):
    with pytest.raises(pjrt.PjrtError):
        client.compile(b"not an mlir module")
