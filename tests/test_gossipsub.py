"""GossipSub protocol tests — tier-2 analogues of gossipsub_test.go
(mesh formation, propagation, gossip retrieval, backoff) on the vectorized
router."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import GossipSubParams
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.trace.events import EV


def build(n=50, d=8, n_topics=1, msg_slots=32, seed=0, cfg=None, subs=None, **net_kw):
    topo = graph.random_connect(n, d, seed=seed)
    subs = subs or graph.subscribe_all(n, n_topics)
    net = Net.build(topo, subs, **net_kw)
    cfg = cfg or GossipSubConfig.build()
    st = GossipSubState.init(net, msg_slots, cfg, seed=seed)
    step = make_gossipsub_step(cfg, net)
    return topo, net, cfg, st, step


def pub(origins, topics, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    pv = np.zeros(p, bool)
    for i, (o, t) in enumerate(zip(origins, topics)):
        po[i], pt[i], pv[i] = o, t, True
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def run(step, st, n, args=None):
    a = args or no_publish()
    for _ in range(n):
        st = step(st, *a)
    return st


def test_mesh_forms_and_stays_bounded():
    topo, net, cfg, st, step = build(n=60, d=10, seed=3)
    st = run(step, st, 30)
    deg = np.asarray(st.mesh.sum(axis=(1, 2)))
    assert (deg >= 1).all()
    assert (deg <= cfg.Dhi).all()
    # most peers should sit in the healthy band
    assert deg.mean() >= cfg.Dlo


def test_mesh_links_become_mutual():
    topo, net, cfg, st, step = build(n=40, d=8, seed=5)
    st = run(step, st, 20)
    mesh = np.asarray(st.mesh[:, 0, :])
    total = mutual = 0
    for j in range(40):
        for k in range(topo.max_degree):
            if topo.nbr_ok[j, k] and mesh[j, k]:
                total += 1
                mutual += bool(mesh[topo.nbr[j, k], topo.rev[j, k]])
    assert total > 0
    assert mutual / total > 0.95


def test_propagation_all_peers():
    # multihop propagation through the mesh (gossipsub_test.go dense harness)
    topo, net, cfg, st, step = build(n=100, d=10, seed=7)
    st = run(step, st, 10)  # mesh warmup
    st = step(st, *pub([3], [0]))
    st = run(step, st, 10)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))[:, 0]
    assert have.all()
    ev = np.asarray(st.core.events)
    assert ev[EV.DELIVER_MESSAGE] == 99


def test_multi_topic_slot_compression():
    # peers subscribe 2 of 8 topics; messages stay within their topic's
    # subscriber set and reach all of it
    n = 120
    topo = graph.random_connect(n, 12, seed=9)
    subs = graph.subscribe_random(n, n_topics=8, topics_per_peer=2, seed=9)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build()
    st = GossipSubState.init(net, 32, cfg, seed=0)
    step = make_gossipsub_step(cfg, net)
    st = run(step, st, 15)
    origin = int(np.nonzero(subs.subscribed[:, 3])[0][0])
    st = step(st, *pub([origin], [3]))
    st = run(step, st, 15)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))[:, 0]
    subscribers = subs.subscribed[:, 3]
    # no leakage outside the topic
    assert not have[~subscribers].any()
    # gossipsub may need the subnet to be connected *within* subscribers via
    # the union graph; require strong majority coverage
    assert have[subscribers].mean() > 0.9


def test_gossip_ihave_iwant_path():
    # a peer that cannot mesh (permanent backoff both ways) still receives
    # messages via IHAVE -> IWANT -> retransmission (the lazy gossip pull,
    # gossipsub.go:615-716)
    topo, net, cfg, st, step = build(n=30, d=6, seed=11)
    FAR = 2**30
    leech = 0
    # backoff presence blocks heartbeat grafting in both directions
    bp = np.zeros(st.backoff_present.shape, bool)
    be = np.zeros(st.backoff_expire.shape, np.int32)
    bp[leech, :, :] = True
    be[leech, :, :] = FAR
    for k in range(topo.max_degree):
        if topo.nbr_ok[leech, k]:
            j, r = topo.nbr[leech, k], topo.rev[leech, k]
            bp[j, :, r] = True
            be[j, :, r] = FAR
    st = st.replace(
        backoff_present=jnp.asarray(bp), backoff_expire=jnp.asarray(be)
    )
    st = run(step, st, 10)
    assert int(st.mesh[leech].sum()) == 0, "leech must stay out of the mesh"

    st = step(st, *pub([7], [0]))
    st = run(step, st, 12)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))
    assert have[leech, 0], "gossip pull must deliver to the meshless peer"


def test_backoff_blocks_regraft():
    topo, net, cfg, st, step = build(n=20, d=6, seed=13)
    st = run(step, st, 10)
    # force-prune everything from peer 0's view with a long backoff
    bp = np.array(st.backoff_present)
    be = np.array(st.backoff_expire)
    bp[0, :, :] = True
    be[0, :, :] = int(st.core.tick) + 50
    mesh = np.array(st.mesh)
    mesh[0, :, :] = False
    st = st.replace(
        backoff_present=jnp.asarray(bp),
        backoff_expire=jnp.asarray(be),
        mesh=jnp.asarray(mesh),
    )
    st2 = run(step, st, 5)
    # peer 0 must not graft anyone while backoff presence holds
    assert int(st2.mesh[0].sum()) == 0


def test_backoff_expiry_allows_regraft():
    topo, net, cfg, st, step = build(n=20, d=6, seed=13)
    st = run(step, st, 10)
    bp = np.array(st.backoff_present)
    be = np.array(st.backoff_expire)
    bp[0, :, :] = True
    be[0, :, :] = int(st.core.tick) + 3
    mesh = np.array(st.mesh)
    mesh[0, :, :] = False
    st = st.replace(
        backoff_present=jnp.asarray(bp),
        backoff_expire=jnp.asarray(be),
        mesh=jnp.asarray(mesh),
    )
    # run past expiry + clear cadence (15) + slack
    st2 = run(step, st, 25)
    assert int(st2.mesh[0].sum()) >= cfg.Dlo


def test_mcache_window_shift():
    topo, net, cfg, st, step = build(n=20, d=6, seed=15)
    st = run(step, st, 5)
    st = step(st, *pub([1], [0]))
    st = run(step, st, 2)
    # the message sits in some window of its receivers
    mc = np.asarray(st.mcache)
    assert (mc != 0).any()
    # after > history_length heartbeats with no traffic, windows drain
    st = run(step, st, cfg.history_length + 1)
    mc = np.asarray(st.mcache)
    assert (mc == 0).all()


def test_ihave_respects_joined_topics():
    # messages of topics a peer didn't join are never requested
    n = 40
    topo = graph.random_connect(n, 8, seed=17)
    subs = graph.subscribe_random(n, n_topics=2, topics_per_peer=1, seed=17)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build()
    st = GossipSubState.init(net, 32, cfg, seed=0)
    step = make_gossipsub_step(cfg, net)
    st = run(step, st, 10)
    origin = int(np.nonzero(subs.subscribed[:, 0])[0][0])
    st = step(st, *pub([origin], [0]))
    st = run(step, st, 15)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))[:, 0]
    assert not have[~subs.subscribed[:, 0]].any()


def test_graft_prune_events_traced():
    topo, net, cfg, st, step = build(n=30, d=8, seed=19)
    st = run(step, st, 10)
    ev = np.asarray(st.core.events)
    assert ev[EV.GRAFT] > 0
    # over-subscription pruning should have fired somewhere
    deg = np.asarray(st.mesh.sum(axis=(1, 2)))
    assert (deg <= cfg.Dhi).all()


@pytest.mark.slow
def test_count_events_off_identical_protocol_state():
    """Tracer-detached mode (count_events=False) must change nothing but
    the aggregate counters — every protocol-visible array stays identical
    (tracing is opt-in in the reference: WithEventTracer, pubsub.go)."""
    import jax

    cfg_on = GossipSubConfig.build()
    cfg_off = dataclasses.replace(cfg_on, count_events=False)
    topo = graph.random_connect(40, 8, seed=9)
    subs = graph.subscribe_all(40, 1)
    net = Net.build(topo, subs)
    states = {}
    for name, cfg in [("on", cfg_on), ("off", cfg_off)]:
        st = GossipSubState.init(net, 32, cfg, seed=1)
        step = make_gossipsub_step(cfg, net)
        for r in range(12):
            st = step(st, *pub([r % 40], [0]))
        states[name] = st
    a, b = states["on"], states["off"]
    la_all = dict(
        (jax.tree_util.keystr(p), l) for p, l in jax.tree_util.tree_leaves_with_path(a)
    )
    lb_all = dict(
        (jax.tree_util.keystr(p), l) for p, l in jax.tree_util.tree_leaves_with_path(b)
    )
    assert la_all.keys() == lb_all.keys()
    for name in la_all:
        if "events" in name or "key" in name:
            continue
        assert (np.asarray(la_all[name]) == np.asarray(lb_all[name])).all(), name
    # counters-off leaves the event array untouched
    assert (np.asarray(b.core.events) == 0).all()
    assert int(np.asarray(a.core.events)[EV.DELIVER_MESSAGE]) > 0


def test_static_heartbeat_matches_cond():
    """make_gossipsub_step(static_heartbeat=True) is bit-identical to the
    lax.cond cadence when driven with do_heartbeat == (tick % he == 0).
    (The static form exists because the cond's branch-materialization
    copies measured 407 -> 113 ticks/s on the bench — BASELINE.md r3.)"""
    import jax

    he = 3
    cfg = dataclasses.replace(GossipSubConfig.build(), heartbeat_every=he)
    topo = graph.random_connect(48, 8, seed=5)
    net = Net.build(topo, graph.subscribe_all(48, 1))
    st0 = GossipSubState.init(net, 32, cfg, seed=1)
    step_c = make_gossipsub_step(cfg, net)
    step_s = make_gossipsub_step(cfg, net, static_heartbeat=True)

    sa = jax.tree.map(jnp.copy, st0)
    sb = st0
    rng = np.random.default_rng(7)
    for t in range(2 * he + 1):
        args = pub([int(rng.integers(0, 48))], [0])
        sa = step_c(sa, *args)
        sb = step_s(sb, *args, do_heartbeat=(t % he == 0))
    la = jax.tree.leaves(sa)
    lb = jax.tree.leaves(sb)
    for a, b in zip(la, lb):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        assert (np.asarray(a) == np.asarray(b)).all()
