"""Dynamic overlay plane tests (topo/dynamics.py; round 22,
docs/DESIGN.md §22): the host-compiled mutation schedule, the
device-side write-batch kernel, and the contracts the dynamic build
makes with the rest of the repo —

  * schedule compilation is deterministic (same seed, same program,
    same ``schedule_hash``) and involution-correct batch by batch;
  * ``apply_mutation`` tracks the host mirror bit for bit and bumps
    epoch exactly once per real write row;
  * mutation-off is FREE: a ``dynamic_topo=True`` run fed all-padding
    batches matches the plain ``dynamic_peers`` build bit-exactly on
    every non-overlay leaf, and the overlay planes never move;
  * the same storm through the dense [N, K] and flat-[E] CSR faces is
    bit-identical, scanned or loop-stepped;
  * chaos fault streams re-key per (slot-pair × epoch): symmetric over
    the involution, deterministic, and local — bumping one edge's epoch
    redraws exactly that link's stream (chaos/faults.py);
  * the mutated topology rides checkpoint v6 with no version bump;
  * the schema-v3 ``dynamics`` fingerprint block round-trips, with the
    ``DYNAMICS_OFF`` sentinel on legacy lines;
  * ``make_gossipsub_step`` rejects the build combinations that would
    bake neighbor identity into the program.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, graph
from go_libp2p_pubsub_tpu import topo as topolib
from go_libp2p_pubsub_tpu.chaos import faults as chaos_faults
from go_libp2p_pubsub_tpu.config import GossipSubParams, PeerScoreThresholds
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.ops.edges import involution_wf
from go_libp2p_pubsub_tpu.state import Net, TopoState
from go_libp2p_pubsub_tpu.topo import dynamics

N = 32
M = 64
D = 8          # storm dispatches
DEGREE = 10    # capacity cap K (slack above the power-law tail)


def _topology(seed=0):
    el = topolib.powerlaw(N, max_degree=DEGREE - 4, seed=seed)
    return topolib.to_topology(el, max_degree=DEGREE)


def _storm(tp, seed=0, d=D):
    return topolib.churn_storm(tp, n_dispatches=d, kill_frac=0.2,
                               rewires=4, joins=1, join_links=2, seed=seed)


def _cell(seed=0, edge_layout="dense", dynamic_topo=True):
    tp = _topology(seed)
    subs = graph.subscribe_all(N, 1)
    net = Net.build(tp, subs, edge_layout=edge_layout, dynamic=True)
    params = dataclasses.replace(GossipSubParams(), flood_publish=False)
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(),
                                score_enabled=False,
                                edge_layout=edge_layout)
    st = GossipSubState.init(net, M, cfg, seed=seed,
                             dynamic_topo=dynamic_topo)
    step = make_gossipsub_step(cfg, net, dynamic_peers=True,
                               dynamic_topo=dynamic_topo)
    return tp, net, cfg, st, step


def _publishes(d=D, seed=0):
    rng = np.random.default_rng(seed)
    po = np.full((d, 4), -1, np.int32)
    po[:, 0] = rng.integers(0, N, size=d)
    pt = np.zeros((d, 4), np.int32)
    pv = np.zeros((d, 4), bool)
    pv[:, 0] = True
    return po, pt, pv


def _pad_writes(d=D, b=4):
    w = np.zeros((d, b, 4), np.int32)
    w[:, :, 0] = dynamics.PAD_SLOT
    return w


def _leaves(tree, skip_topo=False):
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        if skip_topo and ".topo." in key:
            continue
        if jnp.issubdtype(getattr(leaf, "dtype", None), jax.dtypes.prng_key):
            leaf = jax.random.key_data(leaf)
        out[key] = np.asarray(leaf)
    return out


# ---------------------------------------------------------------------------
# schedule compilation


def test_schedule_deterministic_and_hashed():
    tp = _topology()
    a, b = _storm(tp), _storm(tp)
    wa, ua = a.build()
    wb, ub = b.build()
    assert np.array_equal(wa, wb) and np.array_equal(ua, ub)
    assert a.schedule_hash() == b.schedule_hash()
    assert a.schedule_hash() != _storm(tp, seed=1).schedule_hash()
    assert a.mutation_dispatches
    assert a.n_kills > 0 and a.n_joins > 0 and a.n_rewires > 0


def test_schedule_rejects_malformed_programs():
    tp = _topology()
    s = dynamics.MutationSchedule(tp.nbr, tp.nbr_ok, tp.rev, 4)
    with pytest.raises(dynamics.ScheduleError):
        s.add_edge(0, 3, 3)                  # self-edge
    u = int(np.argwhere(np.asarray(tp.nbr_ok))[0][0])
    v = int(np.asarray(tp.nbr)[u][np.asarray(tp.nbr_ok)[u]][0])
    with pytest.raises(dynamics.ScheduleError):
        s.add_edge(0, u, v)                  # duplicate edge
    s.remove_edge(2, u, v)
    with pytest.raises(dynamics.ScheduleError):
        s.add_edge(1, u, v)                  # out-of-order dispatch
    with pytest.raises(dynamics.ScheduleError):
        s.build(batch=1)                     # batch < widest dispatch


def test_storm_generator_never_compiles_scatter_races():
    """A rewire frees a slot in the mirror mid-batch; a join later in
    the SAME dispatch must not re-target it (two rows on one slot is
    the race ``_write`` rejects). churn_storm routes around touched
    slots — fuzz it over seeds and verify every program applies clean
    and mirror-exact. (Regression: N=64/D=32/seed=3 raised
    ScheduleError before the dispatch-aware ``_free_slot``.)"""
    el = topolib.powerlaw(64, max_degree=8, seed=7)
    tp = topolib.to_topology(el, max_degree=12)
    topolib.churn_storm(tp, n_dispatches=32, kill_frac=0.2, rewires=8,
                        joins=2, join_links=2, seed=3).build()
    for seed in range(8):
        tp2 = _topology(seed)
        s2 = topolib.churn_storm(tp2, n_dispatches=16, kill_frac=0.3,
                                 rewires=12, joins=4, join_links=3,
                                 seed=seed)
        w2, _ = s2.build()
        t2 = TopoState.from_net(
            Net.build(tp2, graph.subscribe_all(N, 1), dynamic=True))
        for dw in w2:
            t2 = dynamics.apply_mutation(t2, jnp.asarray(dw))
        assert bool(involution_wf(t2.nbr, t2.rev, t2.nbr_ok,
                                  t2.edge_perm)), seed
        assert np.array_equal(np.asarray(t2.nbr), s2.nbr), seed


def test_apply_mutation_tracks_mirror_and_preserves_involution():
    """Every dispatch batch applied on device keeps the involution
    closed, and the final device planes equal the schedule's host
    mirror bit for bit; epoch counts exactly the real write rows."""
    tp = _topology()
    subs = graph.subscribe_all(N, 1)
    net = Net.build(tp, subs, dynamic=True)
    sched = _storm(tp)
    writes, _ = sched.build()
    topo_st = TopoState.from_net(net)
    assert bool(involution_wf(topo_st.nbr, topo_st.rev, topo_st.nbr_ok,
                              topo_st.edge_perm))
    for dw in writes:
        topo_st = dynamics.apply_mutation(topo_st, jnp.asarray(dw))
        assert bool(involution_wf(topo_st.nbr, topo_st.rev,
                                  topo_st.nbr_ok, topo_st.edge_perm))
    assert np.array_equal(np.asarray(topo_st.nbr), sched.nbr)
    assert np.array_equal(np.asarray(topo_st.nbr_ok), sched.nbr_ok)
    assert np.array_equal(np.asarray(topo_st.rev), sched.rev)
    real_rows = int((writes[:, :, 0] != dynamics.PAD_SLOT).sum())
    assert int(np.asarray(topo_st.epoch).sum()) == real_rows


def test_written_edge_mask_matches_batch():
    tp = _topology()
    sched = _storm(tp)
    writes, _ = sched.build()
    d = sched.mutation_dispatches[0]
    m = np.asarray(dynamics.written_edge_mask(
        jnp.asarray(writes[d]), sched.n, sched.k))
    rows = writes[d][writes[d][:, 0] != dynamics.PAD_SLOT]
    want = np.zeros((sched.n * sched.k,), bool)
    want[rows[:, 0]] = True
    assert np.array_equal(m.reshape(-1), want)


# ---------------------------------------------------------------------------
# engine contracts


def test_mutation_off_bit_exact():
    """The mutation-off contract (satellite a): a dynamic_topo build
    fed all-padding batches matches the plain dynamic_peers build
    bit-exactly on every non-overlay leaf, and the overlay planes
    never move (epoch stays zero)."""
    _, net, cfg, st_dyn, step_dyn = _cell(dynamic_topo=True)
    *_, st_ref, step_ref = _cell(dynamic_topo=False)
    po, pt, pv = _publishes()
    writes = _pad_writes()
    up = jnp.ones((N,), bool)
    init_topo = _leaves(st_dyn.core.topo)
    for t in range(D):
        args = (jnp.asarray(po[t]), jnp.asarray(pt[t]), jnp.asarray(pv[t]))
        st_dyn = step_dyn(st_dyn, *args, up, jnp.asarray(writes[t]))
        st_ref = step_ref(st_ref, *args, up)
    got = _leaves(st_dyn, skip_topo=True)
    want = _leaves(st_ref)
    assert set(got) == set(want)
    diff = [k for k in want if not np.array_equal(got[k], want[k])]
    assert not diff, f"mutation-off diverged on {diff}"
    final_topo = _leaves(st_dyn.core.topo)
    assert all(np.array_equal(final_topo[k], init_topo[k])
               for k in init_topo)
    assert int(final_topo[".epoch"].sum()) == 0


def test_dense_csr_parity_under_mutation():
    """The same storm through the dense and full-capacity CSR faces
    finishes with bit-identical counters, delivery and topology."""
    finals = {}
    for layout in ("dense", "csr"):
        tp, net, cfg, st, step = _cell(edge_layout=layout)
        sched = _storm(tp)
        writes, up = sched.build()
        po, pt, pv = _publishes()
        for t in range(D):
            st = step(st, jnp.asarray(po[t]), jnp.asarray(pt[t]),
                      jnp.asarray(pv[t]), jnp.asarray(up[t]),
                      jnp.asarray(writes[t]))
        finals[layout] = st
    a, b = finals["dense"], finals["csr"]
    assert np.array_equal(np.asarray(a.core.events),
                          np.asarray(b.core.events))
    assert np.array_equal(np.asarray(a.core.dlv.have),
                          np.asarray(b.core.dlv.have))
    ta, tb = _leaves(a.core.topo), _leaves(b.core.topo)
    assert all(np.array_equal(ta[k], tb[k]) for k in ta)


def test_scan_vs_loop_parity():
    """The storm scanned (mutation batches riding the xs) equals the
    python-loop dispatch sequence bit-exactly on every leaf."""
    tp, net, cfg, st0, step = _cell()
    sched = _storm(tp)
    writes, up = sched.build()
    po, pt, pv = _publishes()

    st_loop = st0
    for t in range(D):
        st_loop = step(st_loop, jnp.asarray(po[t]), jnp.asarray(pt[t]),
                       jnp.asarray(pv[t]), jnp.asarray(up[t]),
                       jnp.asarray(writes[t]))

    *_, st1, _ = _cell()   # fresh state: the loop donated st0's buffers

    def body(st, xs):
        return step(st, *xs), None

    xs = tuple(jnp.asarray(x) for x in (po, pt, pv, up, writes))
    st_scan = jax.jit(lambda s, x: jax.lax.scan(body, s, x)[0])(st1, xs)
    got, want = _leaves(st_scan), _leaves(st_loop)
    diff = [k for k in want if not np.array_equal(got[k], want[k])]
    assert not diff, f"scan vs loop diverged on {diff}"


# ---------------------------------------------------------------------------
# chaos re-keying


def test_chaos_rekey_symmetric_deterministic_and_local():
    tp = _topology()
    subs = graph.subscribe_all(N, 1)
    net = Net.build(tp, subs, dynamic=True)
    topo_st = TopoState.from_net(net)
    seed = jnp.uint32(0xABCD1234)

    u1 = np.asarray(chaos_faults.link_uniform(seed, net.nbr, 5, 0x11D,
                                              topo=topo_st))
    u2 = np.asarray(chaos_faults.link_uniform(seed, net.nbr, 5, 0x11D,
                                              topo=topo_st))
    assert np.array_equal(u1, u2)

    # symmetric over the involution: both directions of a present edge
    # draw the same stream
    nbr = np.asarray(net.nbr)
    rev = np.asarray(net.rev)
    ok = np.asarray(net.nbr_ok)
    for i, k in np.argwhere(ok)[:16]:
        j, kr = nbr[i, k], rev[i, k]
        assert u1[i, k] == u1[j, kr]

    # local: bumping ONE edge's endpoint epochs redraws exactly that
    # link's stream (both directions), nothing else
    i, k = [int(v) for v in np.argwhere(ok)[0]]
    j, kr = int(nbr[i, k]), int(rev[i, k])
    ep = topo_st.epoch.at[i, k].add(1)
    ep = ep.at[j, kr].add(1)
    u3 = np.asarray(chaos_faults.link_uniform(
        seed, net.nbr, 5, 0x11D, topo=topo_st.replace(epoch=ep)))
    assert u3[i, k] != u1[i, k]
    assert u3[i, k] == u3[j, kr]
    changed = u3 != u1
    changed[i, k] = changed[j, kr] = False
    assert not changed.any()

    # the static path (topo=None) ignores the overlay entirely
    s1 = np.asarray(chaos_faults.link_uniform(seed, net.nbr, 5, 0x11D))
    s2 = np.asarray(chaos_faults.link_uniform(seed, net.nbr, 5, 0x11D))
    assert np.array_equal(s1, s2)


# ---------------------------------------------------------------------------
# checkpoint + artifact surfaces


def test_checkpoint_v6_roundtrip_mid_storm(tmp_path):
    """The mutated overlay rides checkpoint v6 pytree-generically — no
    format bump — and restores bit-exact mid-storm."""
    assert checkpoint._FORMAT_VERSION == 6
    tp, net, cfg, st, step = _cell()
    sched = _storm(tp)
    writes, up = sched.build()
    po, pt, pv = _publishes()
    mid = D // 2
    for t in range(mid):
        st = step(st, jnp.asarray(po[t]), jnp.asarray(pt[t]),
                  jnp.asarray(pv[t]), jnp.asarray(up[t]),
                  jnp.asarray(writes[t]))
    assert int(np.asarray(st.core.topo.epoch).sum()) > 0  # storm is live
    path = str(tmp_path / "mid.ckpt")
    checkpoint.save(path, st)
    template = _cell()[3]
    back = checkpoint.restore(path, template)
    got, want = _leaves(back), _leaves(st)
    assert all(np.array_equal(got[k], want[k]) for k in want)


def test_dynamics_fingerprint_roundtrip(tmp_path):
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        DYNAMICS_OFF,
        BenchRecord,
        dump_record,
        dynamics_fingerprint,
        load_bench_lines,
    )

    fp = dynamics_fingerprint(mutation_dispatches=3, writes_per_dispatch=8,
                              kills=2, joins=1, rewires=4,
                              schedule_hash="ab" * 32)
    rec = BenchRecord(metric="m", value=1.0, unit="r/s", vs_baseline=0.0,
                      schema=3, fingerprint={"dynamics": fp})
    path = str(tmp_path / "bench.json")
    with open(path, "w") as f:
        f.write(dump_record(rec) + "\n")
    back = load_bench_lines(path)[0]
    assert back.dynamics == fp
    assert back.dynamics_on

    legacy = BenchRecord(metric="m", value=1.0, unit="r/s",
                         vs_baseline=0.0)
    assert legacy.dynamics == DYNAMICS_OFF
    assert not legacy.dynamics_on


# ---------------------------------------------------------------------------
# build validation


def test_make_step_validation_raises():
    tp = _topology()
    subs = graph.subscribe_all(N, 1)
    net = Net.build(tp, subs, dynamic=True)
    params = dataclasses.replace(GossipSubParams(), flood_publish=False)
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(),
                                score_enabled=False)
    with pytest.raises(ValueError, match="dynamic_peers"):
        make_gossipsub_step(cfg, net, dynamic_topo=True)

    # a banded (non-dynamic) net bakes edge geometry at trace time
    ring = graph.ring_lattice(N, d=4)
    net_banded = Net.build(ring, subs)
    if net_banded.band_off is not None:
        with pytest.raises(ValueError, match="unbanded"):
            make_gossipsub_step(cfg, net_banded, dynamic_peers=True,
                                dynamic_topo=True)

    # do_px binds connection state to static slot identity
    px_params = dataclasses.replace(params, do_px=True)
    px_cfg = GossipSubConfig.build(px_params, PeerScoreThresholds(),
                                   score_enabled=False)
    with pytest.raises(ValueError, match="do_px"):
        make_gossipsub_step(px_cfg, net, dynamic_peers=True,
                            dynamic_topo=True)
