"""Phase-engine parity row: the measured CDF impact of r-round control
latency (VERDICT round-3 item 1's bound).

The phase engine changes ONE thing vs the per-round step: control
(grafts, gossip, IWANT service, score refresh, gater draws) acts every r
rounds instead of every round — the reference's own timing shape, where
control runs at 1 Hz against ~ms delivery hops (gossipsub.go:1278-1301).
Delivery hops keep 1-round resolution, so the propagation-latency CDF
difference vs r=1 *is* the control-latency effect, measured here over
pooled seeds with both engines fed identical workloads and RNG streams
(same seeds both sides — no formation-lottery noise in the comparison,
unlike the engine-vs-oracle rows).

Measured (CPU, N=192 d=8 v1.1 scoring, 5-seed pools, 64 msgs/seed —
recorded in PARITY.md):
  r=2 vs r=1: sup 2.60%    r=4: 3.09%    r=8: 3.58%   (coverage 100% all)
and with an 80-round warmup the series extends to r=16: 2.75%,
r=32: 3.09% — the sup PLATEAUS at ~3-4% rather than growing with r
(delivery hops are unchanged; only gossip recovery and mesh repair lag).
The cold-start constraint the long-r runs surfaced (publishing before
the first tail heartbeat found no mesh; r=32 with a 24-round warmup
delivered 56%) is closed by the driver-owned formation prelude
(driver.form_mesh; Network.start() applies it automatically) —
test_phase_cold_start_formation_prelude below pins the fix. The bounds
asserted below are the measured values + margin; they document the
designed deviation rather than an error — "at the reference's own
cadence ratio the per-round step is the outlier, not the phase engine",
a claim now PROVEN by the oracle-anchored rows in
tests/test_parity_phase_oracle.py (phase-vs-oracle(h) sup 1.29/1.52% at
h=4/8, under the 2% envelope the engine-vs-engine rows here exceed).
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import make_gossipsub_phase_step
from go_libp2p_pubsub_tpu.driver import heartbeat_schedule

N, D, M = 192, 8, 64
WARMUP, PUB_ROUNDS, DRAIN, PUBS = 24, 16, 16, 4  # 56 rounds, 64 msgs
MAX_H = 16


def _score_params():
    tp = TopicScoreParams(
        mesh_message_deliveries_weight=-0.3,
        mesh_message_deliveries_threshold=3.0,
        mesh_message_deliveries_activation=8.0,
        mesh_message_deliveries_window=2.0,
    )
    return PeerScoreParams(topics={0: tp}, skip_app_specific=True,
                           behaviour_penalty_weight=-1.0,
                           behaviour_penalty_threshold=1.0,
                           behaviour_penalty_decay=0.9)


def _run(r: int, seed: int):
    """One run at rounds_per_phase=r; returns (latency list, coverage)."""
    topo = graph.random_connect(N, d=D, seed=seed)
    subs = graph.subscribe_all(N, 1)
    net = __import__("go_libp2p_pubsub_tpu.state", fromlist=["Net"]).Net.build(
        topo, subs
    )
    sp = _score_params()
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True
    )
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed)

    total = WARMUP + PUB_ROUNDS + DRAIN
    rng = np.random.default_rng(seed * 7 + 1)
    po = np.full((total, PUBS), -1, np.int32)
    pt = np.zeros((total, PUBS), np.int32)
    pv = np.ones((total, PUBS), bool)
    po[WARMUP : WARMUP + PUB_ROUNDS] = rng.integers(
        0, N, size=(PUB_ROUNDS, PUBS)
    )
    po_j, pt_j, pv_j = jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)

    if r == 1:
        step = make_gossipsub_step(cfg, net, score_params=sp)
        for i in range(total):
            st = step(st, po_j[i], pt_j[i], pv_j[i])
    else:
        pstep = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
        sched = heartbeat_schedule(1, r)
        g = total // r
        gro = lambda a: a.reshape((g, r) + a.shape[1:])
        xo, xt, xv = gro(po_j), gro(pt_j), gro(pv_j)
        for p in range(g):
            st = pstep(st, xo[p], xt[p], xv[p],
                       do_heartbeat=sched[p % len(sched)])

    origin = np.asarray(st.core.msgs.origin)
    birth = np.asarray(st.core.msgs.birth)
    fr = np.asarray(st.core.dlv.first_round)
    lats, delivered, expected = [], 0, 0
    for s in np.nonzero(origin >= 0)[0]:
        got = fr[:, s] >= 0
        delivered += int(got.sum())
        expected += N
        lats.extend((fr[got, s] - birth[s]).tolist())
    return lats, delivered / expected


def _pooled_cdf(per_seed_lats, denom):
    hist = np.zeros(MAX_H + 1)
    for lats in per_seed_lats:
        for h in lats:
            hist[min(int(h), MAX_H)] += 1
    return np.cumsum(hist) / (len(per_seed_lats) * denom)


SEEDS = (3, 4, 5, 6, 7)
# measured sup + margin (see module docstring); these are the documented
# control-latency deviations, not error bounds
BOUNDS = {2: 0.035, 4: 0.04, 8: 0.045}


@pytest.mark.slow
@pytest.mark.parametrize("r", [2, 4, 8])
def test_phase_control_latency_cdf_impact(r):
    denom = N * PUB_ROUNDS * PUBS
    base, cov_base = [], []
    phase, cov_phase = [], []
    for seed in SEEDS:
        l1, c1 = _run(1, seed)
        lr, cr = _run(r, seed)
        base.append(l1)
        phase.append(lr)
        cov_base.append(c1)
        cov_phase.append(cr)
    sup = float(np.max(np.abs(_pooled_cdf(base, denom)
                              - _pooled_cdf(phase, denom))))
    print(f"phase r={r}: CDF sup vs per-round = {100*sup:.2f}%  "
          f"coverage {np.mean(cov_base):.4f} vs {np.mean(cov_phase):.4f}")
    assert np.mean(cov_phase) > 0.995  # delivery still completes
    assert sup < BOUNDS[r], f"r={r}: sup {100*sup:.2f}% above documented bound"


def _run_prelude(r: int, seed: int, warmup: int, pub_rounds: int,
                 drain: int, prelude: bool):
    """Like _run but with a configurable (short) schedule and an optional
    driver.form_mesh formation prelude before round 0."""
    from go_libp2p_pubsub_tpu.driver import form_mesh

    topo = graph.random_connect(N, d=D, seed=seed)
    subs = graph.subscribe_all(N, 1)
    net = __import__("go_libp2p_pubsub_tpu.state", fromlist=["Net"]).Net.build(
        topo, subs
    )
    sp = _score_params()
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True
    )
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed)

    total = warmup + pub_rounds + drain
    assert total % r == 0
    rng = np.random.default_rng(seed * 7 + 1)
    po = np.full((total, PUBS), -1, np.int32)
    pt = np.zeros((total, PUBS), np.int32)
    pv = np.ones((total, PUBS), bool)
    po[warmup : warmup + pub_rounds] = rng.integers(
        0, N, size=(pub_rounds, PUBS)
    )
    po_j, pt_j, pv_j = jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)

    pstep = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
    if prelude:
        st = form_mesh(pstep, st, rounds_per_phase=r)
    g = total // r
    gro = lambda a: a.reshape((g, r) + a.shape[1:])
    xo, xt, xv = gro(po_j), gro(pt_j), gro(pv_j)
    for p in range(g):
        st = pstep(st, xo[p], xt[p], xv[p], do_heartbeat=True)

    origin = np.asarray(st.core.msgs.origin)
    fr = np.asarray(st.core.dlv.first_round)
    delivered = expected = 0
    for s in np.nonzero(origin >= 0)[0]:
        delivered += int((fr[:, s] >= 0).sum())
        expected += N
    return delivered / expected


@pytest.mark.slow
def test_phase_cold_start_formation_prelude():
    """The round-4 caveat case — deep phases with warmup shorter than one
    phase (publishes land BEFORE the first tail heartbeat): without the
    prelude coverage collapses; with driver.form_mesh it is ~complete.
    This is the driver-owned cold-start contract: callers never have to
    size warmup against rounds_per_phase."""
    # r=32, 16-round warmup: every publish round is inside phase 0
    cov_without = _run_prelude(32, seed=3, warmup=16, pub_rounds=16,
                               drain=32, prelude=False)
    cov_with = _run_prelude(32, seed=3, warmup=16, pub_rounds=16,
                            drain=32, prelude=True)
    print(f"r=32 cold start: coverage without prelude {cov_without:.3f}, "
          f"with prelude {cov_with:.3f}")
    assert cov_without < 0.90  # the documented failure mode is real
    assert cov_with > 0.995    # prelude restores reference behavior
