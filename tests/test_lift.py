"""Liftability-pass tests (analysis/lift.py, docs/DESIGN.md §16):
every classification rule must FIRE on a seeded snippet (negative),
the alias/interprocedural resolution must see through the patterns it
claims to, and the committed LIFT_AUDIT.json must reproduce
byte-identically with the shipped plane proven liftable (positive)."""

import os
import textwrap

from go_libp2p_pubsub_tpu.analysis import lift

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "go_libp2p_pubsub_tpu")


def sites_of(src, rel="models/broken.py"):
    return lift.analyze_source(textwrap.dedent(src), rel)


def kinds(sites, field):
    return sorted(s.kind for s in sites if s.field == field)


# ---------------------------------------------------------------------------
# classification rules — one seeded snippet per rule


def test_branch_site_classifies_shape():
    sites = sites_of("""
        def step(cfg, st):
            if cfg.flood_publish:
                return st
            return -st
    """)
    assert kinds(sites, "GossipSubConfig.flood_publish") == ["branch"]


def test_while_and_assert_tests_classify_branch():
    sites = sites_of("""
        def step(cfg, st):
            assert cfg.queue_cap >= 0
            while cfg.heartbeat_every:
                st = st + 1
            return st
    """)
    assert kinds(sites, "GossipSubConfig.queue_cap") == ["branch"]
    assert kinds(sites, "GossipSubConfig.heartbeat_every") == ["branch"]


def test_conditional_expression_test_classifies_branch():
    sites = sites_of("""
        def step(cfg, st):
            dt = jnp.int16 if cfg.narrow_counters else jnp.int32
            return st.astype(dt)
    """)
    assert kinds(sites, "GossipSubConfig.narrow_counters") == ["branch"]


def test_shape_arg_classifies_shape():
    sites = sites_of("""
        import jax.numpy as jnp
        def step(cfg, st):
            return jnp.zeros((cfg.fanout_slots, 4))
    """)
    assert kinds(sites, "GossipSubConfig.fanout_slots") == ["shape"]


def test_host_conversion_classifies_shape():
    sites = sites_of("""
        def step(cfg, st):
            return st * float(cfg.gossip_threshold)
    """)
    assert kinds(sites, "GossipSubConfig.gossip_threshold") == ["shape"]


def test_slice_bound_classifies_shape():
    sites = sites_of("""
        def step(cfg, st):
            return st[:, : cfg.history_gossip, :]
    """)
    assert kinds(sites, "GossipSubConfig.history_gossip") == ["shape"]


def test_traced_compare_classifies_value():
    sites = sites_of("""
        def step(cfg, st):
            return st.scores >= cfg.gossip_threshold
    """)
    assert kinds(sites, "GossipSubConfig.gossip_threshold") == ["value"]


def test_fused_gate_classifies_gated():
    sites = sites_of("""
        def step(cfg, st, use_fused):
            if use_fused:
                return st * float(cfg.gossip_threshold)
            return st
    """)
    assert kinds(sites, "GossipSubConfig.gossip_threshold") == ["gated"]


def test_tp_subscript_maps_to_topic_field():
    sites = sites_of("""
        def refresh(st, tp):
            return st.fmd * tp["decay2"]
    """)
    assert kinds(
        sites, "TopicScoreParams.first_message_deliveries_decay"
    ) == ["value"]


def test_static_argnames_kw_classifies_shape():
    sites = sites_of("""
        import jax
        def make_jitted(cfg, fn):
            return jax.jit(fn, static_argnames=cfg.edge_layout)
    """)
    assert kinds(sites, "GossipSubConfig.edge_layout") == ["shape"]


# ---------------------------------------------------------------------------
# alias + interprocedural resolution


def test_single_assign_alias_resolves():
    # w = cfg.score_weights-style single-assignment alias: the use of
    # the NAME classifies at the aliased field (the defining read is a
    # second evidence site — both value-kind here)
    sites = sites_of("""
        def step(cfg, st):
            w = cfg.graylist_threshold
            if w:
                return st
            return st.scores >= w
    """)
    got = kinds(sites, "GossipSubConfig.graylist_threshold")
    assert "branch" in got and "value" in got


def test_reassigned_alias_not_trusted():
    # a name assigned twice is no longer a sound alias — dropped
    sites = sites_of("""
        def step(cfg, st):
            thr = cfg.graylist_threshold
            thr = 0.0
            if thr:
                return st
            return -st
    """)
    assert kinds(sites, "GossipSubConfig.graylist_threshold") == ["value"]


def test_alias_of_whole_config_resolves():
    sites = sites_of("""
        def step(cfg, st):
            c = cfg
            if c.do_px:
                return st
            return -st
    """)
    assert kinds(sites, "GossipSubConfig.do_px") == ["branch"]


def test_closure_capture_resolves():
    # nested defs see the builder's cfg through lexical scoping —
    # including defs nested under an `if` (heartbeat's _oppo_grafts)
    sites = sites_of("""
        def make_step(cfg, net):
            flag = True
            if flag:
                def inner(st):
                    return st >= cfg.opportunistic_graft_threshold
            def step(st):
                return inner(st)
            return step
    """)
    assert kinds(
        sites, "GossipSubConfig.opportunistic_graft_threshold"
    ) == ["value"]


def test_consts_attribute_chain_resolves():
    sites = sites_of("""
        import numpy as np
        def make_step(cfg, net, score_params):
            consts = prepare_step_consts(cfg, net, score_params)
            w3 = np.asarray(consts.tpa.w3)
            return w3
    """)
    assert kinds(
        sites, "TopicScoreParams.mesh_message_deliveries_weight"
    ) == ["shape"]


def test_interprocedural_field_propagation():
    # a field passed positionally roots the callee's parameter: its
    # uses classify as reads of that field even though the callee knows
    # nothing of configs
    sites = sites_of("""
        def helper(wnd, msgs):
            return wnd[msgs]

        def step(cfg, st, consts):
            return helper(consts.window_rounds_t, st.topic)
    """)
    got = kinds(sites,
                "TopicScoreParams.mesh_message_deliveries_window")
    assert "value" in got


def test_method_invocation_is_not_a_read():
    sites = sites_of("""
        def build(cfg, gater_params):
            gater_params.validate()
            return cfg
    """)
    assert not any(s.field == "PeerGaterParams.validate" for s in sites)


def test_build_scope_excluded():
    sites = sites_of("""
        class FooConfig:
            def validate(self, params):
                if params.decay_to_zero <= 0:
                    raise ValueError()
    """)
    assert sites == []


# ---------------------------------------------------------------------------
# verdict aggregation


def test_verdict_shape_wins_over_value():
    sites = sites_of("""
        import jax.numpy as jnp
        def step(cfg, st):
            x = st * cfg.max_ihave_length
            return jnp.zeros((cfg.max_ihave_length,)) + x
    """)
    v = lift.field_verdicts(sites)["GossipSubConfig.max_ihave_length"]
    assert v["verdict"] == "SHAPE"


def test_verdict_gated_does_not_block():
    sites = sites_of("""
        def step(cfg, st, use_fused):
            if use_fused:
                return st * float(cfg.gossip_threshold)
            return st.scores >= cfg.gossip_threshold
    """)
    v = lift.field_verdicts(sites)["GossipSubConfig.gossip_threshold"]
    assert v["verdict"] == "VALUE"


def test_declared_shape_forced():
    sites = sites_of("""
        def score(params, st):
            return st * params.app_specific_weight
    """)
    v = lift.field_verdicts(sites)["PeerScoreParams.app_specific_weight"]
    assert v["verdict"] == "SHAPE"
    assert "declared_shape" in v


def test_elision_table_guards_verdict():
    # the compute_scores topic-score-cap branch is a declared
    # value-neutral elision: the branch site exists but the verdict is
    # VALUE_GUARDED, not SHAPE
    sites = sites_of("""
        import jax.numpy as jnp
        def compute_scores(st, tp, params):
            score = st * tp["topic_weight"]
            if params.topic_score_cap > 0:
                score = jnp.minimum(score, params.topic_score_cap)
            return score
    """, rel="score/engine.py")
    v = lift.field_verdicts(sites)["PeerScoreParams.topic_score_cap"]
    assert v["verdict"] == "VALUE_GUARDED"
    assert any("elision_ok" in r for r in v["sites"])


def test_check_plane_flags_unsound_lift(monkeypatch):
    sites = sites_of("""
        import jax.numpy as jnp
        def step(cfg, st):
            return jnp.zeros((int(cfg.gossip_threshold),)) + st
    """)
    verdicts = lift.field_verdicts(sites)
    fails = lift.check_plane(verdicts)
    assert any("GossipSubConfig.gossip_threshold" in f
               and "UNSOUND" in f for f in fails)


# ---------------------------------------------------------------------------
# the repo audit: the shipped lift is proven, the artifact reproduces


def test_repo_audit_proves_the_plane():
    payload = lift.audit(PKG)
    assert lift.check_plane(payload["fields"]) == []
    # the honest headline facts: thresholds VALUE, the P5 weight SHAPE,
    # the phase elision weights guarded
    f = payload["fields"]
    assert f["GossipSubConfig.gossip_threshold"]["verdict"] == "VALUE"
    assert f["PeerScoreParams.app_specific_weight"]["verdict"] == "SHAPE"
    assert f["TopicScoreParams.mesh_message_deliveries_weight"][
        "verdict"] == "VALUE_GUARDED"


def test_plane_manifest_matches_score_params():
    from go_libp2p_pubsub_tpu.score.params import LIFTED_FIELD_NAMES

    assert set(lift.SCORE_PLANE_FIELDS) == set(LIFTED_FIELD_NAMES)


def test_committed_audit_reproduces_byte_identical():
    path = lift.audit_path(ROOT)
    assert os.path.exists(path), "LIFT_AUDIT.json not committed"
    with open(path) as f:
        committed = f.read()
    assert committed == lift.dump_audit(lift.audit(PKG)), (
        "LIFT_AUDIT.json is stale — LIFT_UPDATE=1 scripts/lift_audit.py"
    )
