"""Trace subsystem tests — the tier-4 strategy of the reference
(trace_test.go:26-195): run a network under tracers, replay the written
files, and check event accounting; plus framing/sink unit tests."""

import dataclasses
import gzip
import io

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.pb import trace_pb2
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.trace import drain, sinks
from go_libp2p_pubsub_tpu.wire import framing


# ---------------------------------------------------------------------------
# framing


def test_uvarint_roundtrip():
    for n in [0, 1, 127, 128, 300, 2**21 - 1, 2**35, 2**63 - 1]:
        buf = framing.encode_uvarint(n)
        v, pos = framing.decode_uvarint(buf)
        assert v == n and pos == len(buf)


def test_delimited_stream_roundtrip():
    buf = io.BytesIO()
    evs = []
    for i in range(10):
        ev = trace_pb2.TraceEvent(type=trace_pb2.TraceEvent.JOIN, timestamp=i)
        ev.join.topic = f"t{i}"
        evs.append(ev)
        framing.write_delimited(buf, ev)
    buf.seek(0)
    out = list(framing.read_delimited_messages(buf, trace_pb2.TraceEvent))
    assert out == evs


def test_delimited_truncation_raises():
    buf = io.BytesIO()
    ev = trace_pb2.TraceEvent(timestamp=5)
    framing.write_delimited(buf, ev)
    data = buf.getvalue()[:-1]
    with pytest.raises(EOFError):
        list(framing.read_delimited_messages(io.BytesIO(data), trace_pb2.TraceEvent))


# ---------------------------------------------------------------------------
# sinks


def _mk_event(i):
    ev = trace_pb2.TraceEvent(
        type=trace_pb2.TraceEvent.DELIVER_MESSAGE, peerID=b"p%d" % i, timestamp=i
    )
    ev.deliverMessage.messageID = b"m%d" % i
    return ev


def test_json_tracer_roundtrip(tmp_path):
    path = str(tmp_path / "trace.json")
    t = sinks.JSONTracer(path)
    evs = [_mk_event(i) for i in range(5)]
    t.trace_many(evs)
    t.close()
    assert list(sinks.read_json_trace(path)) == evs


def test_pb_tracer_roundtrip(tmp_path):
    path = str(tmp_path / "trace.pb")
    t = sinks.PBTracer(path)
    evs = [_mk_event(i) for i in range(5)]
    t.trace_many(evs)
    t.close()
    assert list(sinks.read_pb_trace(path)) == evs


def test_remote_tracer_batching():
    frames: list[bytes] = []
    t = sinks.RemoteTracer(frames.append, min_batch=4)
    evs = [_mk_event(i) for i in range(10)]
    t.trace_many(evs)  # two full batches sent eagerly
    assert len(frames) == 2
    t.close()          # remainder flushed + gzip stream finished
    assert len(frames) == 4
    got = sinks.decode_remote_stream(b"".join(frames))
    assert got == evs
    # the connection's stream is one real gzip member (header magic), and
    # close() finished it so a plain one-shot gunzip also works
    assert frames[0][:2] == b"\x1f\x8b"
    assert gzip.decompress(b"".join(frames))


def test_remote_tracer_reconnect_semantics():
    """tracer.go:201-301: failed batch lost, redial, fresh gzip stream."""
    col = sinks.MemoryCollector()
    t = sinks.RemoteTracer(connect=col.connect, min_batch=4, redial_backoff=2)
    evs = [_mk_event(i) for i in range(24)]

    t.trace_many(evs[:4])           # batch 0 lands on connection 1
    assert col.connections == 1 and t.dials == 1

    col.fail_writes = 1             # collector resets the stream mid-write
    t.trace_many(evs[4:8])          # batch 1 is LOST; immediate redial wins
    assert t.write_failures == 1 and t.lost_events == 4
    assert col.connections == 2     # fresh connection, fresh gzip stream

    t.trace_many(evs[8:12])         # batch 2 lands on connection 2
    got = col.events()
    assert got == evs[:4] + evs[8:12]   # the failed batch is really gone

    # collector goes down entirely: write fails AND redial fails
    col.go_down()
    t.trace_many(evs[12:16])        # batch 3 lost on write; dial fails
    assert t.lost_events == 8 and t.dial_failures == 1

    # while down, events are retained (lossy at cap), flushes back off
    t.trace_many(evs[16:20])        # flush -> backoff tick, retained
    assert len(t._pending) == 4 and col.connections == 2

    col.go_up()
    t.trace_many(evs[20:24])        # flush: backoff expires -> redial -> send
    t.close()
    assert col.connections == 3
    # retained events arrive after downtime, in order, on the new stream
    assert col.events() == evs[:4] + evs[8:12] + evs[16:24]


def test_decode_spliced_abandoned_member():
    """A write-failed connection abandons its gzip member mid-stream; the
    redial's fresh member is concatenated right after it (a plain `send`
    byte sink has no per-connection segmentation). The decoder must
    salvage the abandoned member's sync-flushed batches AND decode the
    fresh member fully."""
    chunks: list[bytes] = []
    t = sinks.RemoteTracer(chunks.append, min_batch=4)
    evs = [_mk_event(i) for i in range(12)]
    t.trace_many(evs[:8])        # two sync-flushed batches on member 1
    t._stream = None             # stream reset: member 1 never Z_FINISHed
    t.trace_many(evs[8:12])      # redial -> fresh member, same byte sink
    t.close()
    got = sinks.decode_remote_stream(b"".join(chunks))
    # everything was written at sync-flush boundaries, so nothing is lost
    assert got == evs


def test_remote_tracer_closed_is_inert():
    col = sinks.MemoryCollector()
    t = sinks.RemoteTracer(connect=col.connect, min_batch=2)
    t.trace_many([_mk_event(0), _mk_event(1)])
    t.close()
    dials = t.dials
    t.trace_many([_mk_event(2), _mk_event(3)])  # post-close: no dial, no send
    assert t.dials == dials and len(col.events()) == 2


def test_remote_tracer_close_while_down_counts_losses():
    col = sinks.MemoryCollector()
    col.go_down()
    t = sinks.RemoteTracer(connect=col.connect, min_batch=64, redial_backoff=0)
    t.trace_many([_mk_event(i) for i in range(5)])
    t.close()
    # stranded events are accounted, not silently forgotten
    assert t.lost_events == 5 and not t._pending


def test_remote_tracer_buffer_cap_while_down():
    col = sinks.MemoryCollector()
    col.go_down()
    t = sinks.RemoteTracer(connect=col.connect, min_batch=4,
                           redial_backoff=0, buffer_cap=6)
    for i in range(20):
        t.trace(_mk_event(i))
    # buffer holds at most cap events; the rest were dropped lossily
    assert len(t._pending) <= 6 and t.dropped >= 14
    col.go_up()
    t.flush()
    t.close()
    assert len(col.events()) >= 6  # survivors land after the collector returns


def test_tracer_lossy_buffer():
    t = sinks.Tracer(buffer_cap=3)
    t._write = lambda evs: None
    for i in range(10):
        t.trace(_mk_event(i))
    assert t.dropped == 7


# ---------------------------------------------------------------------------
# integration: 24-peer gossipsub run under all three tracers


def _build(n=24, m=32, seed=0):
    topo = graph.random_connect(n, d=4, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    params = dataclasses.replace(GossipSubParams(), flood_publish=True)
    sp = PeerScoreParams(
        topics={0: TopicScoreParams(mesh_message_deliveries_weight=0.0,
                                    mesh_failure_penalty_weight=0.0)},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(), score_enabled=True)
    st = GossipSubState.init(net, m, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp, dynamic_peers=True)
    return net, st, step


def test_traced_run_accounting(tmp_path):
    import jax.numpy as jnp

    net, st, step = _build()
    n = net.n_peers
    jpath = str(tmp_path / "t.json")
    ppath = str(tmp_path / "t.pb")
    frames: list[bytes] = []
    all_sinks = [
        sinks.JSONTracer(jpath),
        sinks.PBTracer(ppath),
        sinks.RemoteTracer(frames.append),
    ]
    # queue_cap=1 guarantees DROP_RPC events in flood rounds
    sess = drain.TraceSession(net, all_sinks, queue_cap=1)
    sess.emit_init(drain.snapshot(st))

    rng = np.random.default_rng(0)
    up = np.ones(n, bool)
    n_pub = 0
    for r in range(12):
        po, pt, pv = no_publish(4)
        if r < 6:  # publish two msgs per round from random peers
            o = rng.integers(0, n, 2)
            po = jnp.asarray(np.array([o[0], o[1], -1, -1], np.int32))
            pt = jnp.asarray(np.zeros(4, np.int32))
            pv = jnp.asarray(np.array([True, True, False, False]))
            n_pub += 2
        if r == 7:
            up[3] = False  # kill a peer -> REMOVE_PEER
        if r == 9:
            up[3] = True   # revive -> ADD_PEER
        prev = drain.snapshot(st)
        st = step(st, po, pt, pv, jnp.asarray(up))
        sess.observe(prev, drain.snapshot(st), po, pt, pv)
    final = drain.snapshot(st)
    sess.close(final)

    evs = list(sinks.read_pb_trace(ppath))
    # replay matches across sinks
    assert list(sinks.read_json_trace(jpath)) == evs
    remote = sinks.decode_remote_stream(b"".join(frames))
    assert remote == evs

    types = {e.type for e in evs}
    # all 13 event types observed (trace_test.go's completeness check)
    for name in ("PUBLISH_MESSAGE", "DELIVER_MESSAGE", "REJECT_MESSAGE",
                 "DUPLICATE_MESSAGE", "ADD_PEER", "REMOVE_PEER", "RECV_RPC",
                 "SEND_RPC", "DROP_RPC", "JOIN", "LEAVE", "GRAFT", "PRUNE"):
        code = trace_pb2.TraceEvent.Type.Value(name)
        if name == "DUPLICATE_MESSAGE":
            # aggregate-only: exact in device counters
            assert sess.counter_events(final)["DUPLICATE_MESSAGE"] > 0
        elif name == "REJECT_MESSAGE":
            # this run publishes only valid messages; rejects counted at 0
            assert sess.counter_events(final)["REJECT_MESSAGE"] == 0
        else:
            assert code in types, f"missing {name}"

    # publish accounting: one PUBLISH event per scheduled publish
    pubs = [e for e in evs if e.type == trace_pb2.TraceEvent.PUBLISH_MESSAGE]
    assert len(pubs) == n_pub
    # every delivery references a published message id; full flood coverage
    # means most messages reach ~all peers
    pub_ids = {e.publishMessage.messageID for e in pubs}
    delivers = [e for e in evs if e.type == trace_pb2.TraceEvent.DELIVER_MESSAGE]
    assert delivers and all(e.deliverMessage.messageID in pub_ids for e in delivers)
    # per-event deliver stream matches the device counter exactly
    assert len(delivers) == sess.counter_events(final)["DELIVER_MESSAGE"]
    # every deliver names a real neighbor edge
    ids = {pid: i for i, pid in enumerate(sess.peer_ids)}
    nbr_sets = [set(net.nbr[i][np.asarray(net.nbr_ok)[i]].tolist()) for i in range(n)]
    for e in delivers:
        p = ids[e.peerID]
        frm = ids[e.deliverMessage.receivedFrom]
        assert frm in nbr_sets[p]

    # SEND/RECV pairing: one of each per deliver/reject
    sends = [e for e in evs if e.type == trace_pb2.TraceEvent.SEND_RPC]
    recvs = [e for e in evs if e.type == trace_pb2.TraceEvent.RECV_RPC]
    assert len(sends) == len(recvs) == len(delivers)

    # lifecycle: exactly one REMOVE and one extra ADD for peer 3
    rem = [e for e in evs if e.type == trace_pb2.TraceEvent.REMOVE_PEER]
    assert len(rem) == 1 and rem[0].removePeer.peerID == drain.peer_id(3)
    adds = [e for e in evs if e.type == trace_pb2.TraceEvent.ADD_PEER]
    assert len(adds) == n + 1


def test_session_accounting_caveats_by_stride():
    """The live-session form of the phase-cadence caveat (ADVICE round 5):
    ``accounting_caveats()`` is empty while every observed stride is 1
    and returns the shared ``PHASE_CADENCE_NOTE`` once any observe()
    spans more than one round — same flag->prose shape as tracestat
    --json's ``caveat_notes``."""
    net, st, _ = _build(n=8)
    sess = drain.TraceSession(net, [])
    snap = drain.snapshot(st)

    # per-round cadence: no caveat
    sess.observe(snap, dataclasses.replace(snap, tick=snap.tick + 1),
                 *no_publish(4))
    assert sess.max_tick_stride == 1
    assert sess.accounting_caveats() == {}

    # one phase-cadence step flips the caveat on, permanently
    sess.observe(snap, dataclasses.replace(snap, tick=snap.tick + 4),
                 *no_publish(4))
    assert sess.max_tick_stride == 4
    caveats = sess.accounting_caveats()
    assert caveats == {"phase_cadence": drain.PHASE_CADENCE_NOTE}
    assert "undercount" in caveats["phase_cadence"]

    # later per-round steps don't clear it (the stream already coarsened)
    sess.observe(snap, dataclasses.replace(snap, tick=snap.tick + 1),
                 *no_publish(4))
    assert "phase_cadence" in sess.accounting_caveats()


def test_tracestat_cli(tmp_path):
    # run a traced network, then the tracestat summarizer over both sink
    # formats — the analysis workflow the reference points its users at
    import json as jsonlib
    import pathlib
    import subprocess
    import sys

    from go_libp2p_pubsub_tpu import api
    from go_libp2p_pubsub_tpu.trace import sinks

    jpath = tmp_path / "t.ndjson"
    ppath = tmp_path / "t.pb"
    net = api.Network(
        trace_sinks=[sinks.JSONTracer(str(jpath)), sinks.PBTracer(str(ppath))]
    )
    nodes = net.add_nodes(12)
    for nd in nodes:
        nd.join("x").subscribe()
    net.dense_connect(d=4, seed=0)
    net.start()
    for i in range(3):
        nodes[i].topics["x"].publish(b"m%d" % i)
    net.run(6)
    net.stop()

    repo = pathlib.Path(__file__).resolve().parent.parent
    results = {}
    for path in (jpath, ppath):
        out = subprocess.run(
            [sys.executable, "scripts/tracestat.py", str(path), "--json"],
            capture_output=True, text=True, check=True, cwd=str(repo),
        )
        results[path] = jsonlib.loads(out.stdout)
    for stats in results.values():
        assert stats["published"] == 3
        assert stats["delivered"] >= 3 * 11  # every other node got each one
        assert stats["delay_ns"]["p50"] is not None
        assert stats["counts"]["GRAFT"] > 0
        # per-round cadence: control and data share the tick stride, so
        # no phase-cadence caveat is emitted
        assert "cadence" not in stats
        # round 11: machine-readable caveat FLAGS (gates/run_report
        # branch on these, never on report prose)
        assert "phase_cadence" not in stats["caveats"]
        assert "counter_only_events" in stats["caveats"]
        assert "counter_only_events" in stats["caveat_notes"]
        assert "no_publishes" not in stats["caveats"]
    # both formats describe the same run
    assert results[jpath] == results[ppath]


def test_tracestat_cli_phase_cadence(tmp_path):
    """The north star's "tracestat analysis is unchanged" at the FLAGSHIP
    cadence: a rounds_per_phase > 1 network writes the same trace schema
    and the summarizer's propagation analysis works unmodified (delays
    carry per-sub-round resolution from the device's first_round
    stamps)."""
    import json as jsonlib
    import pathlib
    import subprocess
    import sys

    from go_libp2p_pubsub_tpu import api
    from go_libp2p_pubsub_tpu.trace import sinks

    ppath = tmp_path / "phase.pb"
    net = api.Network(rounds_per_phase=4, trace_exact=True,
                      trace_sinks=[sinks.PBTracer(str(ppath))], seed=3)
    nodes = net.add_nodes(20)
    net.dense_connect(d=6, seed=3)
    for nd in nodes:
        nd.join("x").subscribe()
    net.start()
    for i in range(4):
        nodes[i].topics["x"].publish(b"m%d" % i)
    net.run(12)
    net.stop()

    repo = pathlib.Path(__file__).resolve().parent.parent
    out = subprocess.run(
        [sys.executable, "scripts/tracestat.py", str(ppath), "--json"],
        capture_output=True, text=True, check=True, cwd=str(repo),
    )
    stats = jsonlib.loads(out.stdout)
    assert stats["published"] == 4
    assert stats["delivered"] == 4 * 19  # full coverage, every non-origin
    assert stats["deliveries_per_publish"] == 19.0
    assert stats["counts"]["DUPLICATE_MESSAGE"] > 0  # exact mode expanded
    # per-sub-round timestamp resolution survives the pipeline: if a
    # regression quantized DELIVER timestamps to phase boundaries, every
    # delay would be a multiple of the 4-round phase duration
    phase_ns = 4 * 10**9  # rounds_per_phase * tick_ns
    assert any(
        stats["delay_ns"][q] % phase_ns != 0
        for q in ("p50", "p90", "p99", "max")
    ), stats["delay_ns"]
    # the r>1 accounting caveats surface in the output itself (ADVICE
    # round 5 item 3), detected from the control-timestamp stride
    assert "cadence" in stats, stats.keys()
    assert stats["cadence"]["rounds_per_phase_estimate"] % 4 == 0
    assert "undercount" in stats["cadence"]["note"]
    # the flag form of the same caveat (round 11): stable strings for
    # gates + run_report, prose mirrored in caveat_notes
    assert "phase_cadence" in stats["caveats"]
    assert stats["caveat_notes"]["phase_cadence"] == stats["cadence"]["note"]


# ---------------------------------------------------------------------------
# round 24: router counters are drain-counter-only (seeded negative)


def test_router_counters_are_drain_counter_only():
    """The four router counters (IDONTWANT_SENT / DUP_SUPPRESSED /
    CHOKE / UNCHOKE) are sim-only: the reference's v1.1 trace schema
    predates the v1.2/episub extensions, so the drain must surface them
    EXCLUSIVELY through counter_events() — a v1.2 suppression run emits
    a per-event stream bit-identical to the v1.1 run's (the delivery
    plane is unchanged; only duplicate traffic disappears), and the
    seeded negative pins every router counter at zero on the v1.1 run."""
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.routers import RouterConfig
    from go_libp2p_pubsub_tpu.trace.events import EV

    def run(router):
        topo = graph.random_connect(24, d=4, seed=0)
        net = Net.build(topo, graph.subscribe_all(24, 1))
        params = dataclasses.replace(GossipSubParams(), flood_publish=True)
        cfg = GossipSubConfig.build(params, PeerScoreThresholds(),
                                    score_enabled=False, router=router)
        st = GossipSubState.init(net, 32, cfg, seed=0)
        step = make_gossipsub_step(cfg, net)
        frames: list[bytes] = []
        sess = drain.TraceSession(net, [sinks.RemoteTracer(frames.append)])
        sess.emit_init(drain.snapshot(st))
        rng = np.random.default_rng(0)
        for r in range(12):
            po, pt, pv = no_publish(4)
            if r < 6:
                o = rng.integers(0, 24, 2)
                po = jnp.asarray(np.array([o[0], o[1], -1, -1], np.int32))
                pt = jnp.asarray(np.zeros(4, np.int32))
                pv = jnp.asarray(np.array([True, True, False, False]))
            prev = drain.snapshot(st)
            st = step(st, po, pt, pv)
            sess.observe(prev, drain.snapshot(st), po, pt, pv)
        final = drain.snapshot(st)
        sess.close(final)
        return sinks.decode_remote_stream(b"".join(frames)), \
            sess.counter_events(final)

    evs_a, cnt_a = run(None)
    evs_b, cnt_b = run(RouterConfig(idontwant=True))

    # parity audit stays green: no proto record type exists for any of
    # the four, and all four are documented sim-only
    for name in ("IDONTWANT_SENT", "DUP_SUPPRESSED", "CHOKE", "UNCHOKE"):
        assert name not in trace_pb2.TraceEvent.Type.keys()
        assert EV[name] in drain.COUNTER_ONLY_EVENTS

    # suppression changed NO per-event record — the stream is the v1.1
    # stream, bit for bit (delivery plane unchanged by the exactness
    # anchor: dontwant ⊆ have)
    assert evs_b == evs_a

    # counters tell the suppression story exactly: the RPC drop IS the
    # duplicate drop, and the lazy-choke counters never move without a
    # choke-armed router
    assert cnt_b["IDONTWANT_SENT"] > 0 and cnt_b["DUP_SUPPRESSED"] > 0
    assert cnt_b["DELIVER_MESSAGE"] == cnt_a["DELIVER_MESSAGE"]
    assert cnt_b["SEND_RPC"] < cnt_a["SEND_RPC"]
    assert (cnt_a["SEND_RPC"] - cnt_b["SEND_RPC"]
            == cnt_a["DUPLICATE_MESSAGE"] - cnt_b["DUPLICATE_MESSAGE"])
    # seeded negative: the v1.1 run pins all four at zero
    for name in ("IDONTWANT_SENT", "DUP_SUPPRESSED", "CHOKE", "UNCHOKE"):
        assert cnt_a[name] == 0
    assert cnt_b["CHOKE"] == 0 and cnt_b["UNCHOKE"] == 0
