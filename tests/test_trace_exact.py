"""Exact per-event trace mode (cfg.trace_exact + TraceSession(exact=True)):
full event accounting in the style of the reference's traceStats.check
(trace_test.go:26-195) — every DuplicateMessage and every control-only RPC
as an individual event, totals reconciled against the device counters.
"""

import dataclasses

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import GossipSubParams, PeerScoreParams, \
    PeerScoreThresholds, TopicScoreParams
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.pb import trace_pb2
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.trace import drain
from go_libp2p_pubsub_tpu.trace.events import EV

T = trace_pb2.TraceEvent


class MemSink:
    def __init__(self):
        self.events = []

    def trace(self, ev):
        self.events.append(type(ev).FromString(ev.SerializeToString()))

    def close(self):
        pass


def run_traced(n=32, d=6, n_topics=2, m=32, rounds=14, seed=3, exact=True):
    topo = graph.random_connect(n, d, seed=seed)
    subs = graph.subscribe_random(n, n_topics=n_topics, topics_per_peer=2,
                                  seed=seed)
    net = Net.build(topo, subs)
    cfg = dataclasses.replace(GossipSubConfig.build(), trace_exact=exact)
    st = GossipSubState.init(net, m, cfg, seed=seed)
    step = make_gossipsub_step(cfg, net)
    sink = MemSink()
    sess = drain.TraceSession(net, [sink], queue_cap=0, exact=exact)
    sess.emit_init(drain.snapshot(st))
    rng = np.random.default_rng(seed)
    import jax.numpy as jnp

    n_pub = 0
    for i in range(rounds):
        p = 3
        po = rng.integers(0, n, size=p).astype(np.int32)
        pt = rng.integers(0, n_topics, size=p).astype(np.int32)
        pv = np.ones(p, bool)
        if i >= rounds - 4:
            po[:] = -1  # drain tail
        else:
            n_pub += p
        prev = drain.snapshot(st)
        st = step(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))
        sess.observe(prev, drain.snapshot(st), po, pt, pv)
    final = drain.snapshot(st)
    sess.close(final)
    return sink.events, final, n_pub


def by_type(events):
    out = {}
    for ev in events:
        out.setdefault(ev.type, []).append(ev)
    return out


def test_exact_accounting_vs_device_counters():
    """The reference's traceStats.check: per-type event totals reconcile —
    here against the exact device counters, which the per-event stream
    must now match rather than summarize."""
    events, final, n_pub = run_traced()
    ev = by_type(events)
    counters = drain.TraceSession.counter_events(final)

    assert len(ev.get(T.PUBLISH_MESSAGE, [])) == n_pub
    assert len(ev.get(T.PUBLISH_MESSAGE, [])) == counters["PUBLISH_MESSAGE"]
    assert len(ev.get(T.DELIVER_MESSAGE, [])) == counters["DELIVER_MESSAGE"]
    assert len(ev.get(T.REJECT_MESSAGE, [])) == counters["REJECT_MESSAGE"]
    # the new guarantee: duplicates are individual events, total exact
    assert len(ev.get(T.DUPLICATE_MESSAGE, [])) == counters["DUPLICATE_MESSAGE"]
    assert counters["DUPLICATE_MESSAGE"] > 0  # workload actually has dups

    # RPC records are per-(sender,receiver,round); the device counters are
    # per-(edge,message): the message-entry sum must equal the counter
    sent_msgs = sum(len(e.sendRPC.meta.messages)
                    for e in ev.get(T.SEND_RPC, []))
    recv_msgs = sum(len(e.recvRPC.meta.messages)
                    for e in ev.get(T.RECV_RPC, []))
    assert sent_msgs == counters["SEND_RPC"]
    assert recv_msgs == counters["RECV_RPC"]
    assert len(ev.get(T.SEND_RPC, [])) == len(ev.get(T.RECV_RPC, []))

    # mesh-diff GRAFT/PRUNE events match the device's ingest+heartbeat
    # accounting (both count every mesh-set mutation)
    assert len(ev.get(T.GRAFT, [])) == counters["GRAFT"]
    assert len(ev.get(T.PRUNE, [])) == counters["PRUNE"]


def test_every_arrival_is_deliver_dup_or_reject():
    """Conservation per message id: each transmitted instance lands as
    exactly one of DELIVER / DUPLICATE / REJECT at its receiver (arrival
    accounting over RecvRPC metas)."""
    events, final, _ = run_traced()
    ev = by_type(events)
    arrivals = {}
    for e in ev.get(T.RECV_RPC, []):
        for mm in e.recvRPC.meta.messages:
            arrivals[mm.messageID] = arrivals.get(mm.messageID, 0) + 1
    outcomes = {}
    for e in ev.get(T.DELIVER_MESSAGE, []):
        mid = e.deliverMessage.messageID
        outcomes[mid] = outcomes.get(mid, 0) + 1
    for e in ev.get(T.DUPLICATE_MESSAGE, []):
        mid = e.duplicateMessage.messageID
        outcomes[mid] = outcomes.get(mid, 0) + 1
    for e in ev.get(T.REJECT_MESSAGE, []):
        mid = e.rejectMessage.messageID
        outcomes[mid] = outcomes.get(mid, 0) + 1
    assert arrivals == outcomes

    # and every delivered/duplicated id was actually published
    published = {e.publishMessage.messageID
                 for e in ev.get(T.PUBLISH_MESSAGE, [])}
    assert set(arrivals) <= published


def test_control_rpcs_expand():
    """Heartbeat gossip + mesh control cross as RPC records with full
    RPCMeta: IHAVE advertisements name real published ids, IWANT asks are
    a subset of what was advertised on that edge, GRAFT events have a
    matching control entry crossing the following round."""
    events, final, _ = run_traced()
    ev = by_type(events)
    published = {e.publishMessage.messageID
                 for e in ev.get(T.PUBLISH_MESSAGE, [])}

    ihave_edges = {}  # (sender, receiver) -> advertised mids
    n_ihave = n_iwant = n_graft_meta = 0
    for e in ev.get(T.SEND_RPC, []):
        key = (e.peerID, e.sendRPC.sendTo)
        for ih in e.sendRPC.meta.control.ihave:
            n_ihave += 1
            assert set(ih.messageIDs) <= published
            ihave_edges.setdefault(key, set()).update(ih.messageIDs)
        for iw in e.sendRPC.meta.control.iwant:
            n_iwant += 1
            # asks ride the reverse edge: I ask the peer who advertised
            adv = ihave_edges.get((e.sendRPC.sendTo, e.peerID), set())
            assert set(iw.messageIDs) <= adv
        n_graft_meta += len(e.sendRPC.meta.control.graft)
    assert n_ihave > 0 and n_iwant > 0 and n_graft_meta > 0

    # initiator-side GRAFT events are followed by a graft control entry
    # from that peer (the outbox crosses one round later)
    graft_events = {(e.peerID, e.graft.peerID, e.graft.topic)
                    for e in ev.get(T.GRAFT, [])}
    graft_meta = set()
    for e in ev.get(T.SEND_RPC, []):
        for g in e.sendRPC.meta.control.graft:
            graft_meta.add((e.peerID, e.sendRPC.sendTo, g.topic))
    # every control graft corresponds to a mesh addition at the sender
    assert graft_meta <= graft_events


def test_exact_off_is_free():
    """trace_exact=False keeps the state plane absent (zero hot-path cost)
    and the session in aggregate mode."""
    events, final, _ = run_traced(exact=False, rounds=8)
    assert final.dup_trans is None
    ev = by_type(events)
    assert T.DUPLICATE_MESSAGE not in ev
    counters = drain.TraceSession.counter_events(final)
    assert counters["DUPLICATE_MESSAGE"] > 0  # still counted exactly


def test_api_network_exact_trace():
    """Exact mode through the L6 API: real ed25519 peer ids and real
    message ids on duplicate + control events."""
    import jax

    from go_libp2p_pubsub_tpu import api

    net = api.Network(trace_exact=True, trace_sinks=[MemSink()])
    sink = net.trace_sinks[0]
    nodes = net.add_nodes(16)
    net.dense_connect(d=5, seed=1)
    subs = [nd.join("x").subscribe() for nd in nodes]
    net.start()
    for i in range(3):
        nodes[i].topics["x"].publish(b"m%d" % i)
    net.run(8)
    ev = by_type(sink.events)
    assert all(sum(1 for _ in s) == 3 for s in subs)
    counters = drain.TraceSession.counter_events(
        drain.snapshot(net.state)
    )
    assert len(ev.get(T.DUPLICATE_MESSAGE, [])) == counters["DUPLICATE_MESSAGE"]
    assert counters["DUPLICATE_MESSAGE"] > 0
    pids = {nd.identity.peer_id for nd in nodes}
    for e in ev[T.DUPLICATE_MESSAGE]:
        assert e.peerID in pids
        assert e.duplicateMessage.receivedFrom in pids
    # control-only RPCs exist (heartbeat gossip/graft crossings)
    assert any(
        len(e.sendRPC.meta.messages) == 0 for e in ev.get(T.SEND_RPC, [])
    )


def test_exact_control_rpcs_respect_churn():
    """A peer downed at round t gets NO control-only RPC events at round
    t: the engine applies peer down-transitions — clearing down edges'
    outboxes and masking the gather — BEFORE the same round's control
    exchange (apply_peer_transitions precedes control_exchange), so the
    drain must gate the prev-outbox expansion with POST-transition
    liveness. The round-4 advisor repro: with prev.up gating, a downed
    peer still showed SEND_RPC/RECV_RPC (IHAVE) events the device never
    transmitted."""
    import jax.numpy as jnp

    n, d, n_topics, m, seed = 32, 6, 2, 32, 3
    topo = graph.random_connect(n, d, seed=seed)
    subs = graph.subscribe_random(n, n_topics=n_topics, topics_per_peer=2,
                                  seed=seed)
    net = Net.build(topo, subs)
    cfg = dataclasses.replace(GossipSubConfig.build(), trace_exact=True)
    st = GossipSubState.init(net, m, cfg, seed=seed)
    step = make_gossipsub_step(cfg, net, dynamic_peers=True)
    sink = MemSink()
    sess = drain.TraceSession(net, [sink], queue_cap=0, exact=True)
    sess.emit_init(drain.snapshot(st))
    rng = np.random.default_rng(seed)
    up = np.ones(n, bool)
    down_peer, down_round = 0, 8
    for i in range(14):
        p = 3
        po = rng.integers(0, n, size=p).astype(np.int32)
        pt = rng.integers(0, n_topics, size=p).astype(np.int32)
        pv = np.ones(p, bool)
        if i >= 10:
            po[:] = -1
        if i == down_round:
            # the interesting case: the downed peer must have control
            # pending in its outboxes (heartbeat_every=1 repopulates
            # IHAVE each round) — otherwise there is no control to
            # phantom-emit and the test is vacuous
            prev_snap = drain.snapshot(st)
            assert (prev_snap.ihave_out[down_peer].any()
                    or prev_snap.graft_out[down_peer].any()), \
                "precondition: downed peer needs pending control"
            up[down_peer] = False
        prev = drain.snapshot(st)
        st = step(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv),
                  jnp.asarray(up))
        sess.observe(prev, drain.snapshot(st), po, pt, pv)
    final = drain.snapshot(st)
    sess.close(final)
    ev = by_type(sink.events)

    down_pid = drain.peer_id(down_peer)
    down_ts = down_round * sess.tick_ns
    # no RPC traffic involving the downed peer from its down round on
    # (it stays down; its edges died with it)
    for e in ev.get(T.SEND_RPC, []):
        if e.timestamp >= down_ts:
            assert e.peerID != down_pid, \
                "downed peer emitted a phantom SEND_RPC"
            assert e.sendRPC.sendTo != down_pid, \
                "downed peer received a phantom RPC (send side)"
    for e in ev.get(T.RECV_RPC, []):
        if e.timestamp >= down_ts:
            assert e.peerID != down_pid
            assert e.recvRPC.receivedFrom != down_pid
    # and the downed peer delivers/duplicates nothing after going down
    for typ, field in ((T.DELIVER_MESSAGE, "deliverMessage"),
                       (T.DUPLICATE_MESSAGE, "duplicateMessage")):
        for e in ev.get(typ, []):
            if e.timestamp >= down_ts:
                assert e.peerID != down_pid
    # accounting still reconciles under churn (message-grained)
    counters = drain.TraceSession.counter_events(final)
    sent_msgs = sum(len(e.sendRPC.meta.messages)
                    for e in ev.get(T.SEND_RPC, []))
    assert sent_msgs == counters["SEND_RPC"]
    assert len(ev.get(T.DUPLICATE_MESSAGE, [])) == counters["DUPLICATE_MESSAGE"]


def run_traced_phase(r=4, n=32, d=6, n_topics=2, m=32, phases=4, seed=3,
                     exact=True):
    """Raw-engine phase run under a TraceSession: one observe() per
    phase, publishes landing per sub-round."""
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.driver import form_mesh
    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        make_gossipsub_phase_step,
    )

    topo = graph.random_connect(n, d, seed=seed)
    subs = graph.subscribe_random(n, n_topics=n_topics, topics_per_peer=2,
                                  seed=seed)
    net = Net.build(topo, subs)
    cfg = dataclasses.replace(GossipSubConfig.build(), trace_exact=exact)
    st = GossipSubState.init(net, m, cfg, seed=seed)
    step = make_gossipsub_phase_step(cfg, net, r)
    sink = MemSink()
    sess = drain.TraceSession(net, [sink], queue_cap=0, exact=exact)
    sess.emit_init(drain.snapshot(st))
    st = form_mesh(step, st, rounds_per_phase=r, pub_width=3,
                   pv_dtype=bool)
    rng = np.random.default_rng(seed)
    n_pub = 0
    for ph in range(phases):
        po = rng.integers(0, n, size=(r, 3)).astype(np.int32)
        pt = rng.integers(0, n_topics, size=(r, 3)).astype(np.int32)
        pv = np.ones((r, 3), bool)
        if ph >= phases - 2:
            po[:] = -1  # drain tail
        else:
            n_pub += r * 3
        prev = drain.snapshot(st)
        st = step(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv),
                  do_heartbeat=True)
        sess.observe(prev, drain.snapshot(st), po, pt, pv)
    final = drain.snapshot(st)
    sess.close(final)
    return sink.events, final, n_pub, r


def test_phase_exact_accounting_vs_device_counters():
    """The traceStats.check reconciliation at the FLAGSHIP cadence
    (rounds_per_phase > 1): every event type the phase drain emits
    reconciles against the device counters — the round-4 review's top
    item (api.py previously hard-rejected observers at r > 1)."""
    events, final, n_pub, r = run_traced_phase()
    ev = by_type(events)
    counters = drain.TraceSession.counter_events(final)

    assert len(ev.get(T.PUBLISH_MESSAGE, [])) == n_pub == \
        counters["PUBLISH_MESSAGE"]
    assert len(ev.get(T.DELIVER_MESSAGE, [])) == counters["DELIVER_MESSAGE"]
    assert len(ev.get(T.REJECT_MESSAGE, [])) == counters["REJECT_MESSAGE"]
    assert len(ev.get(T.DUPLICATE_MESSAGE, [])) == \
        counters["DUPLICATE_MESSAGE"]
    assert counters["DUPLICATE_MESSAGE"] > 0
    # same-phase attribution: a message published at sub-round i
    # duplicates from sub-round i+2 of the SAME phase — those dup bits
    # must resolve to the real published mid, not the phase-start
    # occupant / "?unknown" (published slots use the end-of-phase map)
    published = {e.publishMessage.messageID
                 for e in ev.get(T.PUBLISH_MESSAGE, [])}
    for e in ev.get(T.DUPLICATE_MESSAGE, []):
        assert e.duplicateMessage.messageID in published, \
            e.duplicateMessage.messageID
    sent_msgs = sum(len(e.sendRPC.meta.messages)
                    for e in ev.get(T.SEND_RPC, []))
    recv_msgs = sum(len(e.recvRPC.meta.messages)
                    for e in ev.get(T.RECV_RPC, []))
    assert sent_msgs == counters["SEND_RPC"]
    assert recv_msgs == counters["RECV_RPC"]
    # GRAFT/PRUNE are boundary diffs at r > 1: a head-graft undone by the
    # same phase's tail heartbeat cancels in the diff, so the event
    # stream can undercount the device's mutation counters (documented
    # in observe()); it can never overcount
    assert len(ev.get(T.GRAFT, [])) <= counters["GRAFT"]
    assert len(ev.get(T.PRUNE, [])) <= counters["PRUNE"]
    assert len(ev.get(T.GRAFT, [])) > 0


def test_phase_deliver_timestamps_are_per_subround():
    """DELIVER events under the phase drain carry their own sub-round
    timestamps (the device's first_round stamps), NOT phase-boundary
    quantized ones — the propagation CDF keeps 1-round resolution at the
    flagship cadence."""
    events, final, _, r = run_traced_phase()
    ev = by_type(events)
    ticks = {e.timestamp // 10**9 for e in ev.get(T.DELIVER_MESSAGE, [])}
    # r ticks per phase: if deliveries quantized to boundaries, every
    # timestamp would be ≡ 0 (mod r) + prelude offset; sub-round stamps
    # hit non-boundary ticks too
    assert any(t % r != 0 for t in ticks), sorted(ticks)
    # and every deliver names a mid published at an EARLIER-or-equal tick
    pub_tick = {}
    for e in ev.get(T.PUBLISH_MESSAGE, []):
        pub_tick[e.publishMessage.messageID] = e.timestamp
    for e in ev.get(T.DELIVER_MESSAGE, []):
        assert e.timestamp >= pub_tick[e.deliverMessage.messageID]


def test_phase_conservation_per_message():
    """Arrival conservation (DELIVER/DUPLICATE/REJECT partition RecvRPC
    message entries) holds at the phase cadence."""
    events, final, _, _ = run_traced_phase()
    ev = by_type(events)
    arrivals = {}
    for e in ev.get(T.RECV_RPC, []):
        for mm in e.recvRPC.meta.messages:
            arrivals[mm.messageID] = arrivals.get(mm.messageID, 0) + 1
    outcomes = {}
    for typ, f in ((T.DELIVER_MESSAGE, "deliverMessage"),
                   (T.DUPLICATE_MESSAGE, "duplicateMessage"),
                   (T.REJECT_MESSAGE, "rejectMessage")):
        for e in ev.get(typ, []):
            mid = getattr(e, f).messageID
            outcomes[mid] = outcomes.get(mid, 0) + 1
    assert arrivals == outcomes


def test_api_network_phase_trace_and_tags():
    """The full observer stack through the L6 API at the flagship
    cadence: Network(rounds_per_phase=4, trace_sinks=[...],
    trace_exact=True, track_tags=True) — previously hard-rejected
    (round-4 review item 1). Deliveries complete, exact accounting
    reconciles, tag tracer bumps."""
    from go_libp2p_pubsub_tpu import api

    net = api.Network(rounds_per_phase=4, trace_exact=True,
                      trace_sinks=[MemSink()], track_tags=True)
    sink = net.trace_sinks[0]
    nodes = net.add_nodes(16)
    net.dense_connect(d=5, seed=1)
    subs = [nd.join("x").subscribe() for nd in nodes]
    net.start()
    for i in range(3):
        nodes[i].topics["x"].publish(b"m%d" % i)
    net.run(8)
    ev = by_type(sink.events)
    assert all(sum(1 for _ in s) == 3 for s in subs)
    counters = drain.TraceSession.counter_events(drain.snapshot(net.state))
    assert len(ev.get(T.DUPLICATE_MESSAGE, [])) == \
        counters["DUPLICATE_MESSAGE"]
    assert counters["DUPLICATE_MESSAGE"] > 0
    pids = {nd.identity.peer_id for nd in nodes}
    for e in ev.get(T.DELIVER_MESSAGE, []):
        assert e.peerID in pids
    # control-only RPCs exist at boundary resolution
    assert any(len(e.sendRPC.meta.messages) == 0
               for e in ev.get(T.SEND_RPC, []))
    # connmgr tags bumped by phase-boundary first deliveries
    assert net.tag_tracer.cm.tags.sum() > 0


def test_ambiguous_recycled_slot_dup_carries_flag():
    """Round-7 (ADVICE round-5 item 4): at phase cadence, a duplicate on
    a slot recycled WITHIN the observed phase resolves to the
    end-of-phase mid as before, but the event now says so —
    ``duplicateMessage.ambiguousMid`` is set exactly when the slot's
    previous occupant was a different message (a freshly-used slot stays
    unflagged)."""
    n, d, m = 8, 2, 64
    topo = graph.random_connect(n, d, seed=1)
    subs = graph.subscribe_random(n, n_topics=1, topics_per_peer=1, seed=1)
    net = Net.build(topo, subs)
    sink = MemSink()
    sess = drain.TraceSession(net, [sink], exact=True)

    w = (m + 31) // 32
    dup = np.zeros((n, net.max_degree, w), np.uint32)
    dup[0, 0, 0] = (1 << 2) | (1 << 3)  # dup arrivals on slots 2 and 3
    mk = lambda tick: drain.Snapshot(
        tick=tick, cursor=0,
        msg_topic=np.zeros(m, np.int32), msg_origin=np.zeros(m, np.int32),
        msg_valid=np.ones(m, bool), msg_ignored=np.zeros(m, bool),
        first_round=np.full((n, m), -1, np.int32),
        first_edge=np.full((n, m), -1, np.int8),
        events=np.zeros(32, np.int64),
        dup_trans=None,
    )
    prev, new = mk(0), mk(4)  # phase cadence: r = 4
    new.dup_trans = dup
    # slot 2: recycled this phase over an OLD occupant -> ambiguous;
    # slot 3: first-ever use this phase -> not ambiguous
    sess.slot_mid = {2: b"new-mid", 3: b"fresh-mid"}
    prev_slot_mid = {2: b"old-mid"}
    sess._observe_exact(prev, new, 0, {}, {}, prev_slot_mid,
                        published_slots={2, 3})
    dups = {ev.duplicateMessage.messageID: ev.duplicateMessage
            for ev in sink.events if ev.type == T.DUPLICATE_MESSAGE}
    assert set(dups) == {b"new-mid", b"fresh-mid"}
    assert dups[b"new-mid"].ambiguousMid is True
    assert dups[b"fresh-mid"].ambiguousMid is False
