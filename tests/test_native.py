"""Native runtime layer: C++ codec/writer/interner round-tripped against
the pure-Python wire implementation (wire/framing.py)."""

import gzip
import io
import os

import pytest

from go_libp2p_pubsub_tpu import native
from go_libp2p_pubsub_tpu.pb import trace_pb2
from go_libp2p_pubsub_tpu.wire import framing

pytestmark = pytest.mark.skipif(
    not native.available() and not native.build(),
    reason="native library not buildable",
)


@pytest.mark.parametrize("n", [0, 1, 127, 128, 300, 2**21, 2**32, 2**63 - 1])
def test_uvarint_matches_python(n):
    assert native.encode_uvarint(n) == framing.encode_uvarint(n)
    v, consumed = native.decode_uvarint(framing.encode_uvarint(n) + b"tail")
    assert v == n
    assert consumed == len(framing.encode_uvarint(n))


def test_uvarint_truncated_and_overlong():
    with pytest.raises(EOFError):
        native.decode_uvarint(b"\x80")
    with pytest.raises(ValueError):
        native.decode_uvarint(b"\xff" * 10 + b"\x01")


def test_frame_join_split_roundtrip():
    payloads = [b"", b"a", b"hello world", os.urandom(5000)]
    stream = b"".join(native.frame_join(p) for p in payloads)
    out, consumed = native.frame_split(stream)
    assert out == payloads
    assert consumed == len(stream)


def test_frame_split_partial_tail():
    full = native.frame_join(b"complete")
    partial = native.frame_join(b"never-finished")[:-3]
    out, consumed = native.frame_split(full + partial)
    assert out == [b"complete"]
    assert consumed == len(full)  # partial tail left for the next read


def test_frame_split_interop_with_python_writer():
    buf = io.BytesIO()
    evs = []
    for i in range(10):
        ev = trace_pb2.TraceEvent(type=trace_pb2.TraceEvent.PUBLISH_MESSAGE,
                                  peerID=b"peer-%d" % i, timestamp=i)
        evs.append(ev)
        framing.write_delimited(buf, ev)
    payloads, consumed = native.frame_split(buf.getvalue())
    assert consumed == len(buf.getvalue())
    got = [trace_pb2.TraceEvent.FromString(p) for p in payloads]
    assert got == evs


def test_native_writer_read_back_with_python_reader(tmp_path):
    path = str(tmp_path / "trace.pb")
    evs = [trace_pb2.TraceEvent(type=trace_pb2.TraceEvent.GRAFT,
                                peerID=b"p%d" % i, timestamp=i)
           for i in range(50)]
    with native.NativeTraceWriter(path) as w:
        for ev in evs:
            assert w.write_message(ev)
        assert w.frames == 50
        w.flush()
    with open(path, "rb") as f:
        got = list(framing.read_delimited_messages(f, trace_pb2.TraceEvent))
    assert got == evs


def test_native_writer_gzip(tmp_path):
    path = str(tmp_path / "trace.pb.gz")
    evs = [trace_pb2.TraceEvent(type=trace_pb2.TraceEvent.PRUNE,
                                peerID=b"z", timestamp=i) for i in range(20)]
    with native.NativeTraceWriter(path, gzip_level=6) as w:
        for ev in evs:
            w.write_message(ev)
    with gzip.open(path, "rb") as f:
        got = list(framing.read_delimited_messages(f, trace_pb2.TraceEvent))
    assert got == evs


def test_native_writer_drops_oversize(tmp_path):
    path = str(tmp_path / "t.pb")
    with native.NativeTraceWriter(path, max_frame=16) as w:
        assert w.write(b"x" * 8)
        assert not w.write(b"x" * 64)  # dropped, lossy contract
        assert w.frames == 1 and w.dropped == 1


def test_interner_basic():
    t = native.Interner(4)
    assert t.get(b"missing") is None
    t.put(b"msg-1", 7)
    t.put(b"msg-2", 9)
    assert t.get(b"msg-1") == 7
    assert b"msg-2" in t and len(t) == 2
    t.put(b"msg-1", 42)  # update, not duplicate
    assert t.get(b"msg-1") == 42 and len(t) == 2


def test_interner_growth_many_keys():
    t = native.Interner(4)
    for i in range(5000):
        t.put(b"key-%d" % i, i)
    assert len(t) == 5000
    for i in range(0, 5000, 37):
        assert t.get(b"key-%d" % i) == i


def test_interner_matches_dict_random():
    import random

    rng = random.Random(0)
    t = native.Interner()
    ref = {}
    for _ in range(2000):
        k = bytes(rng.randbytes(rng.randint(0, 40)))
        v = rng.randint(-2**62, 2**62)
        t.put(k, v)
        ref[k] = v
    assert len(t) == len(ref)
    for k, v in ref.items():
        assert t.get(k) == v


def test_pbtracer_native_path_matches_python(tmp_path):
    """PBTracer with use_native=True/False writes byte-identical files."""
    from go_libp2p_pubsub_tpu.trace import sinks

    evs = [trace_pb2.TraceEvent(type=trace_pb2.TraceEvent.DELIVER_MESSAGE,
                                peerID=b"p%d" % i, timestamp=i)
           for i in range(40)]
    p_native = str(tmp_path / "n.pb")
    p_python = str(tmp_path / "p.pb")
    for path, use in ((p_native, True), (p_python, False)):
        t = sinks.PBTracer(path, use_native=use)
        t.trace_many(evs)
        t.close()
    with open(p_native, "rb") as a, open(p_python, "rb") as b:
        assert a.read() == b.read()


def test_native_writer_append_mode(tmp_path):
    path = str(tmp_path / "a.pb")
    with native.NativeTraceWriter(path) as w:
        w.write(b"one")
    with native.NativeTraceWriter(path, append=True) as w:
        w.write(b"two")
    with open(path, "rb") as f:
        data = f.read()
    payloads, _ = native.frame_split(data)
    assert payloads == [b"one", b"two"]


def test_uvarint_64bit_overflow_rejected():
    # 2^64 (10th byte = 0x02): Python's arbitrary-precision decoder returns
    # 2^64 but uint64 wraps — the native decoder must reject, not wrap
    with pytest.raises(ValueError):
        native.decode_uvarint(b"\x80" * 9 + b"\x02")
    # bit 63 alone is the largest legal 10-byte varint
    v, c = native.decode_uvarint(b"\x80" * 9 + b"\x01")
    assert v == 2**63 and c == 10


def test_frame_split_rejects_overflowing_length():
    # frame length 2^64-1 must read as a partial tail (or error), never as
    # an accepted frame via size_t wraparound
    evil = b"\xff" * 9 + b"\x01" + b"payload"
    out, consumed = native.frame_split(evil)
    assert out == [] and consumed == 0


def test_frame_split_many_tiny_frames():
    # more frames than one C call's offset-array capacity (len//2+1 when
    # every frame is a bare empty-payload header byte)
    stream = native.frame_join(b"") * 300
    out, consumed = native.frame_split(stream)
    assert out == [b""] * 300 and consumed == len(stream)


def test_writer_use_after_close_raises(tmp_path):
    w = native.NativeTraceWriter(str(tmp_path / "c.pb"))
    w.write(b"x")
    w.close()
    for op in (lambda: w.write(b"y"), lambda: w.flush(),
               lambda: w.frames, lambda: w.dropped):
        with pytest.raises(ValueError):
            op()
    w.close()  # idempotent
