"""GossipSub v1.1 integration: scoring live in the router loop —
honest-network health, invalid-message spammer punishment (P4 -> prune ->
graylist), flood-publish. Tier-2/3 analogues of gossipsub_spam_test.go."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net


def benign_score_params(n_topics=1):
    """Score params that don't penalize honest small-network behavior:
    P3/P3b off (tiny meshes can't hit delivery thresholds), P4 on."""
    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.01,
        time_in_mesh_quantum=1.0,
        time_in_mesh_cap=10.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.9,
    )
    return PeerScoreParams(
        topics={t: tp for t in range(n_topics)},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )


def build_v11(n=40, d=8, seed=0, flood_publish=False, score_params=None):
    topo = graph.random_connect(n, d, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    params = dataclasses.replace(GossipSubParams(), flood_publish=flood_publish)
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0,
        publish_threshold=-4.0,
        graylist_threshold=-8.0,
        accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(params, thr, score_enabled=True)
    sp = score_params or benign_score_params()
    st = GossipSubState.init(net, 32, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    return topo, net, cfg, st, step


def pub(o, t, valid=True, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    pv = np.zeros(p, bool)
    po[0], pt[0], pv[0] = o, t, valid
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def run(step, st, k):
    a = no_publish()
    for _ in range(k):
        st = step(st, *a)
    return st


def test_honest_network_scores_nonnegative():
    topo, net, cfg, st, step = build_v11(seed=3)
    st = run(step, st, 10)
    st = step(st, *pub(2, 0))
    st = run(step, st, 15)
    scores = np.asarray(st.scores)
    ok = np.asarray(net.nbr_ok)
    assert (scores[ok] >= 0).all()
    deg = np.asarray(st.mesh.sum(axis=(1, 2)))
    assert (deg >= 1).all() and (deg <= cfg.Dhi).all()
    # delivery happened
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))[:, 0]
    assert have.all()


def test_invalid_spammer_scored_negative_and_pruned():
    topo, net, cfg, st, step = build_v11(seed=5)
    spammer = 4
    st = run(step, st, 8)  # mesh warmup
    for i in range(12):
        st = step(st, *pub(spammer, 0, valid=False))
    # neighbors of the spammer hold strongly negative scores of it
    scores = np.asarray(st.scores)
    neg = []
    for j in range(net.n_peers):
        for k in range(topo.max_degree):
            if topo.nbr_ok[j, k] and topo.nbr[j, k] == spammer:
                neg.append(scores[j, k])
    assert len(neg) > 0
    # every neighbor that saw the spam (spammer's mesh members) is negative;
    # a neighbor outside the spammer's mesh never received it and stays at 0
    # (scores reflect observed behavior only)
    assert min(neg) < -0.5
    assert np.mean(np.asarray(neg) < 0) >= 0.7, neg
    # peers with negative scores pruned the spammer (heartbeat drops
    # score<0, gossipsub.go:1361-1368) and its own mesh empties via PRUNEs
    mesh = np.asarray(st.mesh[:, 0, :])
    for j in range(net.n_peers):
        for k in range(topo.max_degree):
            if topo.nbr_ok[j, k] and topo.nbr[j, k] == spammer and scores[j, k] < 0:
                assert not mesh[j, k]
    # The spammer's own mesh may retain entries in exactly two legitimate
    # states (both reference behavior): a neighbor whose P4 decayed back
    # above zero re-admitting it (score.go:497-558), or a neighbor that
    # GRAYLISTED it — AcceptFrom drops the whole RPC silently
    # (gossipsub.go:583-594), so the spammer's GRAFT gets no PRUNE
    # response and its stale mesh entry lingers while the far end ignores
    # everything it sends. A neighbor between those bands actively prunes
    # (score<0 heartbeat drop). Settle two rounds so in-flight PRUNEs
    # land, then check every remaining edge is in one of the two bands.
    st = run(step, st, 2)
    scores2 = np.asarray(st.scores)
    mesh2 = np.asarray(st.mesh[:, 0, :])
    rev = np.asarray(topo.rev)
    nbrm = np.asarray(topo.nbr)
    for k in range(topo.max_degree):
        if mesh2[spammer, k] and topo.nbr_ok[spammer, k]:
            j, r = int(nbrm[spammer, k]), int(rev[spammer, k])
            s = scores2[j, r]
            assert s >= 0 or s < cfg.graylist_threshold, (k, j, s)
    assert int(st.mesh[spammer].sum()) <= cfg.Dlo


def test_graylisted_peer_messages_ignored():
    # flood-publish keeps the spam flowing even after mesh ejection, so the
    # score keeps sinking past the graylist threshold
    topo, net, cfg, st, step = build_v11(seed=7, flood_publish=True)
    spammer = 1
    st = run(step, st, 8)
    for i in range(20):
        st = step(st, *pub(spammer, 0, valid=False))
    # drive the score below the graylist threshold
    scores = np.asarray(st.scores)
    sn = [
        scores[j, k]
        for j in range(net.n_peers)
        for k in range(topo.max_degree)
        if topo.nbr_ok[j, k] and topo.nbr[j, k] == spammer
    ]
    assert max(sn) < cfg.graylist_threshold
    # now even VALID messages from the spammer are dropped at ingress
    # (AcceptFrom -> AcceptNone, gossipsub.go:583-594)
    before = np.asarray(bitset.unpack(st.core.dlv.have, 32)).sum()
    st = step(st, *pub(spammer, 0, valid=True))
    st = run(step, st, 6)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))
    # the message lives only at the spammer itself
    spread = have.sum() - before
    assert spread <= 1, f"graylisted publish must not spread, spread={spread}"


def test_flood_publish_reaches_direct_neighbors_first():
    topo, net, cfg, st, step = build_v11(seed=9, flood_publish=True)
    st = run(step, st, 8)
    origin = 3
    st = step(st, *pub(origin, 0))
    st = step(st, *no_publish())
    # after one transmit round, ALL topic neighbors of origin have it
    # (flood-publish sends beyond the mesh, gossipsub.go:957-963)
    have = np.asarray(bitset.unpack(st.core.dlv.have, 32))[:, 0]
    for k in range(topo.max_degree):
        if topo.nbr_ok[origin, k]:
            assert have[topo.nbr[origin, k]]


def test_first_deliverers_gain_score():
    topo, net, cfg, st, step = build_v11(seed=11)
    st = run(step, st, 8)
    st = step(st, *pub(6, 0))
    st = run(step, st, 10)
    # peers that relayed first deliveries earn positive P2 — someone's
    # score of some neighbor must exceed the pure time-in-mesh baseline
    scores = np.asarray(st.scores)
    ok = np.asarray(net.nbr_ok)
    # (one delivery, P2 decayed ~0.9^10 plus P1 time-in-mesh)
    assert scores[ok].max() > 0.3


def test_eth2_subnet_shape_isolation_and_delivery():
    """BASELINE.json config-5 geometry at reduced N: 64 attestation-subnet
    topics, 3 subscribed per validator (topic-slot compression keeps state
    at [N,3,K], not [N,64,K]). Publishes must reach only subscribers, and
    every subnet with enough members must deliver."""
    n, n_topics, tpp = 256, 64, 3
    topo = graph.random_connect(n, d=8, seed=4)
    subs = graph.subscribe_random(n, n_topics=n_topics, topics_per_peer=tpp, seed=4)
    net = Net.build(topo, subs)
    assert net.n_slots == tpp  # compression, not dense topics
    params = dataclasses.replace(GossipSubParams(), flood_publish=True)
    # P3 deficit penalties off: attestation subnets here are quiet, and a
    # live mesh-delivery threshold would (correctly) collapse every mesh
    # as delivery-deficient — the reference's guidance is to disable the
    # deficit terms on low-traffic topics
    sp = PeerScoreParams(
        topics={t: TopicScoreParams(mesh_message_deliveries_weight=0.0,
                                    mesh_failure_penalty_weight=0.0)
                for t in range(n_topics)},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(), score_enabled=True)
    st = GossipSubState.init(net, 64, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp)

    sub_table = np.asarray(net.subscribed)
    rng = np.random.default_rng(0)
    topics = rng.choice(n_topics, size=6, replace=False)
    origins, pts = [], []
    for t in topics:
        members = np.flatnonzero(sub_table[:, t])
        assert len(members) > 1, f"subnet {t} too small for the test"
        origins.append(int(members[0]))
        pts.append(int(t))
    # publish two per round
    for i in range(0, 6, 2):
        po = np.array(origins[i : i + 2] + [-1, -1], np.int32)
        pt = np.array(pts[i : i + 2] + [-1, -1], np.int32)
        pv = po >= 0
        st = step(st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))
    # drain long enough for the farthest component paths (mesh grafting
    # takes a heartbeat or two before push paths exist)
    for _ in range(25):
        st = step(st, *no_publish())

    have = np.asarray(bitset.unpack(st.core.dlv.have, 64))
    mtopic = np.asarray(st.core.msgs.topic)
    nbr, ok = np.asarray(net.nbr), np.asarray(net.nbr_ok)

    def reachable(origin, members):
        # BFS over contact-graph edges between co-subscribed peers — the
        # only paths a static topology offers (the reference grows more
        # via discovery, which this test deliberately leaves out)
        seen, frontier = {origin}, [origin]
        while frontier:
            u = frontier.pop()
            for k in np.flatnonzero(ok[u]):
                v = int(nbr[u, k])
                if v in members and v not in seen:
                    seen.add(v)
                    frontier.append(v)
        return seen

    for t, o in zip(pts, origins):
        slots = np.flatnonzero((mtopic == t) & (np.asarray(st.core.msgs.origin) >= 0))
        assert len(slots) == 1, (t, slots)  # the publish must be resident
        members = set(np.flatnonzero(sub_table[:, t]))
        comp = reachable(o, members)
        for s in slots:
            holders = set(np.flatnonzero(have[:, s]))
            # no leakage outside the subnet
            assert holders <= members, (t, s)
            # complete delivery within the origin's connected component
            assert holders == comp, (t, sorted(comp - holders))
