"""Compiled-program contract tests (analysis/hloaudit.py, docs/
DESIGN.md §16): every contract must FIRE on doctored HLO text
(negative — the PR-4/PR-7 pattern), the attributor must name exactly
the changed static, and one real build must pass end-to-end."""

import dataclasses as dc

import pytest

from go_libp2p_pubsub_tpu.analysis import hloaudit as ha
from go_libp2p_pubsub_tpu.analysis.hloaudit import HloContractViolation

CLEAN = """
module @jit_step {
  func.func public @main(%arg0: tensor<4xi32> {tf.aliasing_output = 0 : i32},
                         %arg1: tensor<4xi32> {tf.aliasing_output = 1 : i32}) -> tensor<4xi32> {
    %0 = stablehlo.gather %arg0 : tensor<4xi32>
    %1 = stablehlo.reduce %0 : tensor<4xi32>
    %2 = stablehlo.rng_bit_generator %1 : tensor<4xi32>
    return %2 : tensor<4xi32>
  }
}
"""


# ---------------------------------------------------------------------------
# negatives: corrupt one thing, assert the exact contract trips


def test_host_transfer_infeed_fires():
    doctored = CLEAN.replace("stablehlo.reduce", "stablehlo.infeed")
    with pytest.raises(HloContractViolation) as ei:
        ha.check_no_host_transfer("broken", doctored)
    assert ei.value.contract == "host-transfer"


def test_host_transfer_callback_fires():
    doctored = CLEAN + (
        '\n%9 = stablehlo.custom_call @x(%arg0) '
        '{call_target_name = "xla_python_cpu_callback"}\n'
    )
    with pytest.raises(HloContractViolation) as ei:
        ha.check_no_host_transfer("broken", doctored)
    assert ei.value.contract == "host-transfer"


def test_donation_coverage_fires_on_stripped_markers():
    doctored = CLEAN.replace(" {tf.aliasing_output = 0 : i32}", "").replace(
        " {tf.aliasing_output = 1 : i32}", "")
    with pytest.raises(HloContractViolation) as ei:
        ha.check_donation_coverage("broken", doctored, 0.5)
    assert ei.value.contract == "donation"
    # the clean text passes the same floor
    assert ha.check_donation_coverage("ok", CLEAN, 0.5) == 1.0


def test_rng_contract_fires_both_directions():
    with pytest.raises(HloContractViolation) as ei:
        ha.check_rng("floodsub-like", CLEAN, expect_rng=False)
    assert ei.value.contract == "rng"
    no_rng = CLEAN.replace("stablehlo.rng_bit_generator", "stablehlo.abs")
    with pytest.raises(HloContractViolation) as ei:
        ha.check_rng("gossipsub-like", no_rng, expect_rng=True)
    assert ei.value.contract == "rng"
    ha.check_rng("ok", CLEAN, expect_rng=True)


def test_gather_bound_fires():
    with pytest.raises(HloContractViolation) as ei:
        ha.check_gather_bound("broken", CLEAN, n_tally=5)
    assert ei.value.contract == "census"
    ha.check_gather_bound("ok", CLEAN, n_tally=1)


def test_while_contract_fires():
    with pytest.raises(HloContractViolation) as ei:
        ha.check_while_count("window", CLEAN, expect_min=1)
    assert ei.value.contract == "scan"
    scanned = CLEAN + "\n%8 = stablehlo.while %arg0\n"
    assert ha.check_while_count("window", scanned, expect_min=1) == 1
    with pytest.raises(HloContractViolation):
        ha.check_while_count("step", scanned, expect_min=0, expect_max=0)


def test_census_categories():
    c = ha.hlo_census(CLEAN)
    assert c["cat:gather_family"] == 1
    assert c["cat:reduction"] == 1
    assert c["cat:rng"] == 1


# ---------------------------------------------------------------------------
# recompile-cause attribution


def test_attributor_names_the_changed_static():
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import GossipSubConfig

    cfg_a = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                  score_enabled=True)
    cfg_b = dc.replace(cfg_a, gossip_threshold=-5.0, Dlazy=8)
    named = ha.attribute_recompile(ha.static_fingerprint(cfg_a),
                                   ha.static_fingerprint(cfg_b))
    keys = [n.split(":")[0] for n in named]
    assert keys == ["Dlazy", "gossip_threshold"]
    # under the lifted surface the threshold is a traced input — only
    # the mesh knob remains a recompile cause
    named_l = ha.attribute_recompile(
        ha.static_fingerprint(cfg_a, lifted=True),
        ha.static_fingerprint(cfg_b, lifted=True))
    assert [n.split(":")[0] for n in named_l] == ["Dlazy"]
    # identical builds: empty diff
    assert ha.attribute_recompile(ha.static_fingerprint(cfg_a),
                                  ha.static_fingerprint(cfg_a)) == []


def test_attributor_sees_baked_score_params():
    # the engines close over score_params as trace constants — a
    # weight-only change IS a recompile cause on the static path, and
    # must vanish under the lifted surface
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import GossipSubConfig
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params

    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                score_enabled=True)
    _tp, sp_a = bench_score_params("default", 1)
    sp_b = dc.replace(sp_a, topic_score_cap=50.0)
    named = ha.attribute_recompile(
        ha.static_fingerprint(cfg, score_params=sp_a),
        ha.static_fingerprint(cfg, score_params=sp_b))
    assert [n.split(":")[0] for n in named] == [
        "score_params.topic_score_cap"]
    # a per-topic weight change too
    tp_b = dc.replace(_tp, first_message_deliveries_weight=2.0)
    sp_c = dc.replace(sp_a, topics={0: tp_b})
    named = ha.attribute_recompile(
        ha.static_fingerprint(cfg, score_params=sp_a),
        ha.static_fingerprint(cfg, score_params=sp_c))
    assert named and all(n.startswith("score_params.topics.0.")
                         for n in named)
    # both vanish under the lifted surface
    assert ha.attribute_recompile(
        ha.static_fingerprint(cfg, score_params=sp_a, lifted=True),
        ha.static_fingerprint(cfg, score_params=sp_c, lifted=True)) == []


def test_attributor_sees_net_meta():
    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.config import GossipSubParams
    from go_libp2p_pubsub_tpu.models.gossipsub import GossipSubConfig
    from go_libp2p_pubsub_tpu.state import Net

    cfg = GossipSubConfig.build(GossipSubParams())
    net_a = Net.build(graph.ring_lattice(64, d=4),
                      graph.subscribe_all(64, 1))
    net_b = Net.build(graph.ring_lattice(64, d=4),
                      graph.subscribe_all(64, 1), edge_layout="csr")
    named = ha.attribute_recompile(ha.static_fingerprint(cfg, net_a),
                                   ha.static_fingerprint(cfg, net_b))
    assert any(n.startswith("net.edge_layout") for n in named)


# ---------------------------------------------------------------------------
# one real build end-to-end (small — shares the guards shapes)


def test_floodsub_hlo_contracts_end_to_end():
    from go_libp2p_pubsub_tpu.analysis import guards

    h = guards.build_engine("floodsub")
    tally = ha.tally_gathers(h)  # cache-immune: traces the raw body
    text = ha.lowered_text(h)
    assert tally["total"] >= 1
    ha.check_no_host_transfer("floodsub", text)
    ratio = ha.check_donation_coverage("floodsub", text, 0.5)
    assert 0.5 <= ratio <= 1.0
    # floodsub draws no randomness — the reference defines it without
    ha.check_rng("floodsub", text, expect_rng=False)
    ha.check_while_count("floodsub", text, expect_min=0, expect_max=0)
