"""Peer lifecycle: churn (dead/returning peers) + blacklist.

Reference behavior being modeled: notify.go:19-75 (connection events),
handleDeadPeers pubsub.go:648-689 (writer death => remove peer + router
RemovePeer), gossipsub.go:545-562 (RemovePeer drops mesh/fanout/gossip
state), score.go:604-637 (score retention across disconnect: negative
scores survive, non-negative stats are deleted), blacklist.go:12-64 +
pubsub.go:1048-1060,636-639 (blacklisted peers disconnected and ignored).
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish as nopub,
    set_blacklist,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net
from go_libp2p_pubsub_tpu.trace.events import EV


def benign_score_params(n_topics=1):
    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.95,
    )
    return PeerScoreParams(
        topics={t: tp for t in range(n_topics)},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )


def build(n=30, d=6, seed=0, score=False, msg_slots=32):
    topo = graph.random_connect(n, d, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    params = dataclasses.replace(GossipSubParams(), flood_publish=False)
    sp = benign_score_params() if score else None
    thr = PeerScoreThresholds(
        gossip_threshold=-2.0,
        publish_threshold=-4.0,
        graylist_threshold=-8.0,
        accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )
    cfg = GossipSubConfig.build(params, thr, score_enabled=score)
    st = GossipSubState.init(net, msg_slots, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp, dynamic_peers=True)
    return topo, net, cfg, st, step


def pub(o, t=0, valid=True, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    pv = np.zeros(p, bool)
    po[0], pt[0], pv[0] = o, t, valid
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def run(step, st, up, k, publishes=()):
    pubs = dict(publishes)
    for i in range(k):
        po, pt, pv = pubs.get(i, nopub())
        st = step(st, po, pt, pv, up)
    return st


def received(st, peer):
    """Set of message slots `peer` has seen."""
    have = np.asarray(bitset.unpack(st.core.dlv.have, st.core.msgs.capacity))
    return set(np.nonzero(have[peer])[0])


def test_down_peer_stops_receiving_and_events_counted():
    topo, net, cfg, st, step = build()
    n = net.n_peers
    up = jnp.ones((n,), bool)

    # warm up the mesh, then take peer 0 down
    st = run(step, st, up, 5)
    down = up.at[0].set(False)
    ev_before = np.asarray(st.core.events)
    st = step(st, *nopub(), down)
    ev_after = np.asarray(st.core.events)
    assert ev_after[EV.REMOVE_PEER] - ev_before[EV.REMOVE_PEER] == 1

    # a message published elsewhere while 0 is down must not reach 0
    st = run(step, st, down, 8, publishes={0: pub(n - 1)})
    assert received(st, 0) == set()
    # but reaches everyone else
    for p in range(1, n):
        assert received(st, p) >= {0} or p == n - 1  # origin counts too

    # no live mesh edges point at peer 0
    mesh = np.asarray(st.mesh)
    nbr = np.asarray(net.nbr)
    for j in range(1, n):
        for k in range(net.max_degree):
            if nbr[j, k] == 0:
                assert not mesh[j, :, k].any()


def test_mesh_heals_after_peer_death():
    topo, net, cfg, st, step = build(n=40, d=8)
    n = net.n_peers
    up = jnp.ones((n,), bool)
    st = run(step, st, up, 5)
    down = np.ones(n, bool)
    down[:4] = False  # kill 4 peers at once
    down = jnp.asarray(down)
    st = run(step, st, down, 20)
    mesh = np.asarray(st.mesh)
    deg = mesh.sum(axis=(1, 2))
    # survivors regraft back into a healthy mesh
    alive_deg = deg[4:]
    assert (alive_deg >= cfg.Dlo).mean() > 0.9
    # the dead peers' own mesh state was cleared
    assert deg[:4].sum() == 0


def test_returning_peer_rejoins_and_receives():
    topo, net, cfg, st, step = build()
    n = net.n_peers
    up = jnp.ones((n,), bool)
    st = run(step, st, up, 5)
    down = up.at[0].set(False)
    st = run(step, st, down, 5)
    ev_before = np.asarray(st.core.events)
    st = step(st, *nopub(), up)  # peer 0 returns
    assert np.asarray(st.core.events)[EV.ADD_PEER] - ev_before[EV.ADD_PEER] == 1
    st = run(step, st, up, 10, publishes={2: pub(n - 1)})
    assert len(received(st, 0)) > 0
    # and it regrafted into someone's mesh
    mesh = np.asarray(st.mesh)
    deg0 = mesh[0].sum()
    assert deg0 > 0


def test_blacklisted_peer_fully_isolated():
    topo, net, cfg, st, step = build()
    n = net.n_peers
    up = jnp.ones((n,), bool)
    st = run(step, st, up, 5)
    bl = np.zeros(n, bool)
    bl[3] = True
    st = set_blacklist(st, bl)
    st = run(step, st, up, 10, publishes={1: pub(0), 3: pub(3)})
    # messages published by the blacklisted peer reach nobody
    got3 = [p for p in range(n) if p != 3 and 1 in received(st, p)]
    # slot 1 = second publish (peer 3's); slot 0 = peer 0's publish
    assert got3 == []
    # the network still works without it
    reached = sum(1 for p in range(n) if p != 3 and 0 in received(st, p))
    assert reached > n - 5
    # the blacklisted peer sees only its own local publish, nothing from
    # the network
    assert received(st, 3) <= {1}


def test_score_retention_negative_survives_reconnect():
    topo, net, cfg, st, step = build(score=True)
    n = net.n_peers
    up = jnp.ones((n,), bool)
    st = run(step, st, up, 5)

    # peer 7 spams invalid messages -> its neighbors score it negative (P4)
    for i in range(6):
        st = step(st, *pub(7, valid=False), up)
    nbr = np.asarray(net.nbr)
    scores = np.asarray(st.scores)
    viewers = [(j, k) for j in range(n) for k in range(net.max_degree) if nbr[j, k] == 7]
    neg_before = [scores[j, k] for j, k in viewers if scores[j, k] < 0]
    assert len(neg_before) > 0

    # bounce peer 7: negative opinions survive (retention)
    down = up.at[7].set(False)
    st = step(st, *nopub(), down)
    st = step(st, *nopub(), up)
    st = run(step, st, up, 2)
    scores_after = np.asarray(st.scores)
    still_neg = [scores_after[j, k] for j, k in viewers if scores_after[j, k] < 0]
    assert len(still_neg) >= len(neg_before) * 0.8  # decay may clear a few


def test_positive_stats_cleared_on_disconnect():
    topo, net, cfg, st, step = build(score=True)
    n = net.n_peers
    up = jnp.ones((n,), bool)
    st = run(step, st, up, 3)
    # peer 5 earns positive score via first deliveries
    for i in range(5):
        st = step(st, *pub(5, valid=True), up)
    st = run(step, st, up, 3)
    nbr = np.asarray(net.nbr)
    scores = np.asarray(st.scores)
    viewers = [(j, k) for j in range(n) for k in range(net.max_degree) if nbr[j, k] == 5]
    assert max(scores[j, k] for j, k in viewers) > 0

    down = up.at[5].set(False)
    st = step(st, *nopub(), down)
    # positive stats deleted immediately: fmd for those edges is zero
    fmd = np.asarray(st.score.fmd)
    for j, k in viewers:
        assert fmd[j, :, k].sum() == 0


def test_retained_deficit_converts_to_decaying_penalty():
    """removePeer (score.go:604-637): when a mesh peer with a negative
    (retained) score disconnects, its standing P3 deficit must convert to
    the decaying P3b penalty once and the activation latch must drop —
    not stay latched as a permanent deficit. With heartbeat_every=1 the
    heartbeat prunes negative-score mesh edges with the same memoized
    score snapshot the disconnect sees, so the window only opens in
    multi-round-heartbeat configs; this exercises the engine path the
    model's down-transition composes (on_prune + clear_mesh_status +
    clear_edges with a retention mask)."""
    from go_libp2p_pubsub_tpu.score.engine import (
        ScoreState,
        TopicParamsArrays,
        clear_edges,
        clear_mesh_status,
        compute_scores,
        on_prune,
        refresh_scores,
    )

    tp_params = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=0.0,
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_decay=0.9,
        mesh_message_deliveries_cap=100.0,
        mesh_message_deliveries_threshold=10.0,
        mesh_message_deliveries_activation=1.0,
        mesh_failure_penalty_weight=-1.0,
        mesh_failure_penalty_decay=0.5,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.95,
    )
    sp = PeerScoreParams(
        topics={0: tp_params},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )
    topo = graph.ring_lattice(6, d=2)
    net = Net.build(topo, graph.subscribe_all(6, 1))
    n, k, s = net.n_peers, net.max_degree, net.n_slots
    tpa = TopicParamsArrays.build(sp, 1, 1.0)
    tp = tpa.gather(net.my_topics)

    st = ScoreState.empty(n, s, k)
    # viewer 0 has neighbor slot 0 in mesh, activation latched, zero mmd
    # counter -> full deficit
    in_mesh = jnp.zeros((n, s, k), bool).at[0, 0, 0].set(True)
    st = st.replace(mmd_active=jnp.zeros((n, s, k), bool).at[0, 0, 0].set(True))

    # down-transition composition from make_gossipsub_step for a dead
    # neighbor with a retained (negative) score
    down_nbr = jnp.zeros((n, k), bool).at[0, 0].set(True)
    retained = jnp.zeros((n, k), bool)  # negative score -> NOT cleared
    st2 = on_prune(st, in_mesh & down_nbr[:, None, :], tp)
    st2 = clear_mesh_status(st2, down_nbr)
    st2 = clear_edges(st2, retained)

    thr = float(np.asarray(tp["thr3"])[0, 0])
    assert not bool(np.asarray(st2.mmd_active)[0, 0, 0])
    assert np.asarray(st2.mfp)[0, 0, 0] == pytest.approx(thr * thr)

    # scores after: P3 no longer applies (latch cleared), P3b does, and
    # decays away over refreshes
    no_mesh = jnp.zeros((n, s, k), bool)
    sc = np.asarray(compute_scores(st2, no_mesh, tp, sp, jnp.zeros((n, k)),
                                   jnp.zeros((n,)), net))
    assert sc[0, 0] == pytest.approx(-thr * thr)
    for t in range(20):
        st2 = refresh_scores(st2, no_mesh, t, tp, sp)
    sc_late = np.asarray(compute_scores(st2, no_mesh, tp, sp,
                                        jnp.zeros((n, k)), jnp.zeros((n,)), net))
    assert abs(sc_late[0, 0]) < 1e-3

    # contrast: without the status clear the deficit would be permanent
    st_bug = on_prune(st, in_mesh & down_nbr[:, None, :], tp)
    st_bug = clear_edges(st_bug, retained)
    for t in range(20):
        st_bug = refresh_scores(st_bug, no_mesh, t, tp, sp)
    sc_bug = np.asarray(compute_scores(st_bug, no_mesh, tp, sp,
                                       jnp.zeros((n, k)), jnp.zeros((n,)), net))
    assert sc_bug[0, 0] < -thr * thr / 2  # latched deficit never heals


def test_restarting_peer_loses_soft_state():
    """A crashing node restarts with an empty seen-cache/mcache (soft state
    is rebuilt from the network — survey §5 failure detection; the engine's
    down transition models the process dying)."""
    topo, net, cfg, st, step = build()
    n = net.n_peers
    up = jnp.ones((n,), bool)
    st = run(step, st, up, 5, publishes={0: pub(n - 1)})
    assert len(received(st, 0)) > 0

    down = up.at[0].set(False)
    st = step(st, *nopub(), down)
    # seen-cache wiped at the crash
    assert received(st, 0) == set()
    assert np.asarray(st.mcache)[0].sum() == 0

    # back up: re-receives traffic from scratch
    st = step(st, *nopub(), up)
    st = run(step, st, up, 10, publishes={2: pub(n - 1)})
    assert len(received(st, 0)) > 0
