"""tune/ subsystem tests (round 20, docs/DESIGN.md §20).

Four claims pinned here, mirroring the tune-smoke gates at unit scale:

  * masked-width selection is BIT-EXACT with the static kernels at
    matched widths, from the ops level up through the gossipsub and
    phase engines fed a matched-values CandidateParams plane;
  * one compiled program serves a heterogeneous 16-candidate
    CandidateParams plane stack, and every stacked row equals its
    single-sim run (the configs×sims pairing contract);
  * the ES checkpoint resumes BIT-IDENTICALLY (and refuses a changed
    space), with no simulator in the loop;
  * the space's legality-by-construction claim is falsifiable: a
    doctored box fails check_space, and --cost-weight measurably
    reorders the ranking.
"""

import dataclasses as dc
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.checkpoint import is_prng_key
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
    make_gossipsub_phase_step,
)
from go_libp2p_pubsub_tpu.ops import select
from go_libp2p_pubsub_tpu.tune import (
    ESConfig,
    default_space,
    es_ask,
    es_init,
    es_tell,
    load_es_state,
    rank_scores,
    save_es_state,
    sybil_profile,
)
from go_libp2p_pubsub_tpu.tune.space import Knob, SearchSpace, check_space

N, M, K_D = 48, 32, 8


def build_net():
    return graph.ring_lattice(N, d=K_D)


def build_cell_statics(heartbeat_every=1):
    """(net, cfg, sp, space, profile): the tune profile's static half,
    on a small lattice — parity runs at the SAME values the search's
    candidate 0 decodes to."""
    from go_libp2p_pubsub_tpu.state import Net

    profile = sybil_profile()
    space = default_space()
    net = Net.build(build_net(), graph.subscribe_all(N, 1))
    cfg = GossipSubConfig.build(
        profile.params, profile.thresholds, score_enabled=True,
        heartbeat_every=heartbeat_every)
    return net, cfg, profile.sp, space, profile


def assert_trees_equal(a, b, context=""):
    la = jax.tree_util.tree_flatten_with_path(a)[0]
    lb = {jax.tree_util.keystr(p): leaf
          for p, leaf in jax.tree_util.tree_flatten_with_path(b)[0]}
    assert len(la) == len(lb), f"{context}: leaf count differs"
    for p, x in la:
        k = jax.tree_util.keystr(p)
        y = lb[k]
        if is_prng_key(x):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"{context}: leaf {k}")


def trees_differ(a, b) -> bool:
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        if is_prng_key(x):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            return True
    return False


def pub(i, r=None, width=4):
    po = np.full((width,), -1, np.int32)
    po[0] = i % N
    args = [po, np.zeros((width,), np.int32), np.ones((width,), bool)]
    if r:
        args = [np.broadcast_to(a, (r,) + a.shape).copy() for a in args]
    return tuple(jnp.asarray(a) for a in args)


# ---------------------------------------------------------------------------
# masked-width kernels: bit-exact vs the static selection at matched k


@pytest.mark.parametrize("k", [0, 1, 3, 8])
def test_masked_width_topk_matches_static(k):
    rng = np.random.default_rng(7)
    values = jnp.asarray(rng.normal(size=(12, K_D)), jnp.float32)
    mask = jnp.asarray(rng.random((12, K_D)) < 0.7)
    key = jax.random.PRNGKey(k)
    static = select.select_topk_mask(values, mask, k, key)
    traced = select.masked_width_topk(
        values, mask, jnp.int32(k), K_D, key)
    np.testing.assert_array_equal(np.asarray(static), np.asarray(traced))


@pytest.mark.parametrize("k", [0, 2, 8])
def test_masked_width_random_matches_static(k):
    rng = np.random.default_rng(11)
    mask = jnp.asarray(rng.random((12, K_D)) < 0.7)
    key = jax.random.PRNGKey(k + 100)
    static = select.select_random_mask(key, mask, k)
    traced = select.masked_width_random(key, mask, jnp.int32(k), K_D)
    np.testing.assert_array_equal(np.asarray(static), np.asarray(traced))


def test_masked_width_clips_to_ceiling():
    # a width past the static ceiling behaves as width_max, never as a
    # shape change
    rng = np.random.default_rng(3)
    values = jnp.asarray(rng.normal(size=(6, K_D)), jnp.float32)
    mask = jnp.ones((6, K_D), bool)
    at_max = select.masked_width_topk(values, mask, jnp.int32(K_D), K_D)
    over = select.masked_width_topk(
        values, mask, jnp.int32(K_D + 40), K_D)
    np.testing.assert_array_equal(np.asarray(at_max), np.asarray(over))


# ---------------------------------------------------------------------------
# engine parity: a matched-values CandidateParams plane reproduces the
# static build bit for bit (candidate 0's pairing claim)


def test_gossipsub_candidate_plane_parity():
    net, cfg, sp, space, profile = build_cell_statics()
    plane = space.to_plane(space.base_values(profile), profile, cfg)
    st_s = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    st_l = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    step_s = make_gossipsub_step(cfg, net, score_params=sp)
    step_l = make_gossipsub_step(cfg, net, score_params=sp,
                                 lift_scores=True)
    for i in range(12):
        st_s = step_s(st_s, *pub(i))
        st_l = step_l(st_l, *pub(i), plane)
    assert_trees_equal(st_s, st_l, "gossipsub candidate-plane parity")


@pytest.mark.parametrize(
    "r", [1, pytest.param(8, marks=pytest.mark.slow)])
def test_phase_candidate_plane_parity(r):
    net, cfg, sp, space, profile = build_cell_statics(
        heartbeat_every=max(r, 1))
    plane = space.to_plane(space.base_values(profile), profile, cfg)
    st_s = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    st_l = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    ph_s = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
    ph_l = make_gossipsub_phase_step(cfg, net, r, score_params=sp,
                                     lift_scores=True)
    for i in range(3):
        st_s = ph_s(st_s, *pub(i, r), do_heartbeat=True)
        st_l = ph_l(st_l, *pub(i, r), plane, do_heartbeat=True)
    assert_trees_equal(st_s, st_l, f"phase r={r} candidate-plane parity")


def test_mesh_plane_values_actually_steer():
    # the parity above must not pass because the mesh half is ignored:
    # a wide-mesh candidate on the SAME compiled program must change
    # the trajectory, without recompiling
    net, cfg, sp, space, profile = build_cell_statics()
    base = space.base_values(profile)
    wide = dict(base)
    wide.update(D=10, Dlo=6, Dhi=16, Dscore=5, Dout=5, Dlazy=12)
    plane_a = space.to_plane(base, profile, cfg)
    plane_b = space.to_plane(wide, profile, cfg)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               lift_scores=True)
    st_a = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    st_b = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    for i in range(10):
        st_a = step(st_a, *pub(i), plane_a)
        st_b = step(st_b, *pub(i), plane_b)
    assert step._cache_size() == 1, (
        "a mesh-degree change recompiled the lifted step")
    assert trees_differ(st_a, st_b), (
        "wide-mesh candidate left the trajectory unchanged — the mesh "
        "plane is being ignored")


# ---------------------------------------------------------------------------
# configs×sims: 16 heterogeneous candidates, one program, row parity


def test_sixteen_candidate_stack_one_compile():
    from go_libp2p_pubsub_tpu.ensemble import batch as ebatch

    net, cfg, sp, space, profile = build_cell_statics()
    c = 16
    genomes = space.sample(np.random.default_rng(0), c - 1)
    values = [space.base_values(profile)] + [
        space.decode(g) for g in genomes]
    plane_list = [space.to_plane(v, profile, cfg) for v in values]
    planes = ebatch.stack_planes(plane_list)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               lift_scores=True)
    base = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    base_key = base.core.key
    states = ebatch.batch_states(base, c)
    ens = ebatch.lift_step(step)
    rounds = 4
    for i in range(rounds):
        args = tuple(ebatch.tile(a, c) for a in pub(i))
        states = ens(states, *args, planes)
    assert ens._cache_size() == 1, (
        "16 heterogeneous mesh+score candidates did not share one "
        "compiled program")
    # stacked row idx == the single-sim run with plane idx (threefry
    # vmaps bit-exactly — the paired-fitness contract)
    for idx in (0, 9):
        st = ebatch.with_sim_key(
            GossipSubState.init(net, M, cfg, score_params=sp, seed=0),
            base_key, idx)
        for i in range(rounds):
            st = step(st, *pub(i), plane_list[idx])
        assert_trees_equal(ebatch.unbatch(states, idx), st,
                           f"candidate-stack row {idx}")


# ---------------------------------------------------------------------------
# ES driver: bit-identical checkpoint/resume, no simulator needed


def _fake_scores(genomes: np.ndarray) -> np.ndarray:
    # deterministic, genome-only fitness: a bowl with its optimum off
    # the defaults so the mean actually moves
    return -np.sum((genomes - 0.3) ** 2, axis=1)


def _drive(es, space, escfg, base, gens):
    for _ in range(gens):
        x = es_ask(es, space, escfg, base)
        vals = [space.decode(g) for g in x]
        es_tell(es, escfg, x, _fake_scores(x), vals)


def test_es_checkpoint_resume_bit_identical(tmp_path):
    space = default_space()
    profile = sybil_profile()
    base = space.encode(space.base_values(profile))
    escfg = ESConfig(n_candidates=6, mu=2, seed=3)
    path = str(tmp_path / "es.json")

    es_a = es_init(space, escfg, base)
    _drive(es_a, space, escfg, base, 4)

    es_b = es_init(space, escfg, base)
    _drive(es_b, space, escfg, base, 2)
    save_es_state(path, es_b, space, escfg)
    es_c, escfg_c = load_es_state(path, space)
    assert escfg_c == escfg
    _drive(es_c, space, escfg, base, 2)

    np.testing.assert_array_equal(es_a.mean, es_c.mean)
    assert es_a.sigma == es_c.sigma
    assert es_a.generation == es_c.generation == 4
    assert es_a.best_score == es_c.best_score
    assert es_a.best_generation == es_c.best_generation
    assert (es_a.rng.bit_generator.state
            == es_c.rng.bit_generator.state), (
        "resumed PRNG stream diverged from the straight-through run")
    # and the NEXT generation's population is identical too
    np.testing.assert_array_equal(
        es_ask(es_a, space, escfg, base),
        es_ask(es_c, space, escfg, base))


def test_es_checkpoint_refuses_changed_space(tmp_path):
    space = default_space()
    profile = sybil_profile()
    base = space.encode(space.base_values(profile))
    escfg = ESConfig(n_candidates=4, mu=1, seed=0)
    path = str(tmp_path / "es.json")
    save_es_state(path, es_init(space, escfg, base), space, escfg)
    doctored = SearchSpace(
        tuple(space.knobs[:-1])
        + (Knob("opportunistic_graft_threshold", 0.0, 9.0),))
    with pytest.raises(ValueError, match="different search space"):
        load_es_state(path, doctored)


def test_es_defaults_always_candidate_zero():
    space = default_space()
    profile = sybil_profile()
    base = space.encode(space.base_values(profile))
    escfg = ESConfig(n_candidates=5, mu=2, seed=1)
    es = es_init(space, escfg, base)
    for _ in range(3):
        x = es_ask(es, space, escfg, base)
        assert x.shape == (5, space.dim)
        np.testing.assert_array_equal(x[0], base)
        es_tell(es, escfg, x, _fake_scores(x),
                [space.decode(g) for g in x])


# ---------------------------------------------------------------------------
# cost pricing: --cost-weight measurably reorders the ranking (pinned)


def test_cost_weight_reorders_ranking():
    # candidate 0: better lift, 2x the relative wire bytes;
    # candidate 1: smaller lift at baseline cost
    fitness = np.array([0.10, 0.08])
    cost_rel = np.array([2.0, 1.0])
    free = rank_scores(fitness, cost_rel, 0.0)
    assert np.argmax(free) == 0
    np.testing.assert_allclose(free, fitness)
    priced = rank_scores(fitness, cost_rel, 0.05)
    assert np.argmax(priced) == 1, (
        "cost_weight=0.05 must flip the ranking: 0.10 - 0.05*(2-1) "
        "< 0.08")
    np.testing.assert_allclose(priced, [0.05, 0.08])


def test_cost_weight_keeps_disqualified_at_neg_inf():
    scores = rank_scores(np.array([-np.inf, 0.1]),
                         np.array([0.5, 1.0]), 0.2)
    assert scores[0] == -np.inf
    assert np.isfinite(scores[1])


# ---------------------------------------------------------------------------
# space legality: the claim holds for the default space, and a
# doctored box is caught (the falsifiability half)


def test_default_space_proves_legal():
    assert check_space(default_space(), sybil_profile(),
                       n_random=8, seed=0) == []


def _doctored(name, lo, hi):
    space = default_space()
    knobs = tuple(
        Knob(name, lo, hi, integer=k.integer) if k.name == name else k
        for k in space.knobs)
    return SearchSpace(knobs)


@pytest.mark.parametrize(
    "name,lo,hi",
    [
        ("gossip_factor", 0.0, 1.5),                  # > 1 rejected
        ("mesh_message_deliveries_weight", -4.0, 0.5),  # must be <= 0
        ("first_message_deliveries_decay", 0.5, 1.2),   # decay < 1
    ],
)
def test_doctored_space_fails_check(name, lo, hi):
    failures = check_space(_doctored(name, lo, hi), sybil_profile(),
                           n_random=0, seed=0)
    assert failures, (
        f"a {name} box of [{lo}, {hi}] reaches outside config.py's "
        "accepted region but check_space did not flag it")
    assert any("ILLEGAL" in f for f in failures)


def test_defaults_round_trip_exact():
    space = default_space()
    profile = sybil_profile()
    base = space.base_values(profile)
    rt = space.decode(space.encode(base))
    assert set(rt) == set(base)
    for name, want in base.items():
        got = rt[name]
        if isinstance(want, int):
            assert got == want, f"{name}: {want} -> {got}"
        else:
            assert math.isclose(float(got), float(want),
                                rel_tol=1e-9, abs_tol=1e-9), (
                f"{name}: {want} -> {got}")


def test_degree_envelope_covers_space():
    space = default_space()
    env = space.degree_envelope()
    assert env == {"Dlo": 2, "Dhi": 16, "Dout": 5}
    _net, cfg, _sp, _space, _profile = build_cell_statics()
    widened = space.envelope_config(cfg)
    assert widened.Dlo == min(cfg.Dlo, env["Dlo"])
    assert widened.Dhi == max(cfg.Dhi, env["Dhi"])
    assert widened.Dout == max(cfg.Dout, env["Dout"])
    # every in-space candidate's mesh fits inside the envelope bounds
    for g in space.sample(np.random.default_rng(5), 32):
        v = space.decode(g)
        assert env["Dlo"] <= v["Dlo"]
        assert v["Dhi"] <= env["Dhi"]
        assert v["Dout"] <= env["Dout"]


def test_fingerprint_tracks_knob_edits():
    space = default_space()
    assert space.fingerprint() == default_space().fingerprint()
    assert (space.fingerprint()
            != _doctored("gossip_factor", 0.0, 1.5).fingerprint())


def test_space_rejects_duplicate_knobs():
    with pytest.raises(ValueError, match="duplicate"):
        SearchSpace((Knob("Dlazy", 0, 12, integer=True),) * 2)
