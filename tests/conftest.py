"""Test harness config: force an 8-device virtual CPU platform so the
multi-chip sharding path is exercised without TPU hardware (survey §7
stage 7; the driver's dryrun uses the same mechanism).

The image's sitecustomize registers the axon TPU plugin and imports jax at
interpreter startup, so JAX_PLATFORMS env tweaks are too late — we must go
through jax.config before any backend is initialized.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compilation cache: the suite's dominant cost is repeated
# jit compiles of near-identical step functions across test files; cached
# executables cut a warm full-tier run roughly in half. Keyed by HLO +
# platform + flags, so correctness is jax's problem, not ours. Repo-local
# and gitignored. The version gate + JAX_NO_TEST_CACHE opt-out live in
# go_libp2p_pubsub_tpu/compile_cache.py (jax 0.4.x segfaults LOADING
# cached executables; perf/regress.py applies the same policy).

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _root)

from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache(os.path.join(_root, ".jax_cache"))
