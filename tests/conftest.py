"""Test harness config: force an 8-device virtual CPU platform so the
multi-chip sharding path is exercised without TPU hardware (survey §7
stage 7; the driver's dryrun uses the same mechanism).

The image's sitecustomize registers the axon TPU plugin and imports jax at
interpreter startup, so JAX_PLATFORMS env tweaks are too late — we must go
through jax.config before any backend is initialized.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
