"""Test harness config: force an 8-device virtual CPU platform so the
multi-chip sharding path is exercised without TPU hardware (survey §7
stage 7; the driver's dryrun uses the same mechanism).

The image's sitecustomize registers the axon TPU plugin and imports jax at
interpreter startup, so JAX_PLATFORMS env tweaks are too late — we must go
through jax.config before any backend is initialized.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compilation cache: the suite's dominant cost is repeated
# jit compiles of near-identical step functions across test files; cached
# executables cut a warm full-tier run roughly in half. Keyed by HLO +
# platform + flags, so correctness is jax's problem, not ours. Repo-local
# and gitignored; JAX_NO_TEST_CACHE=1 opts out (e.g. when bisecting a
# suspected stale-cache issue).
if os.environ.get("JAX_NO_TEST_CACHE", "") != "1":
    _cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    )
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
