"""Banded-regular topology fast path (ops/edges.detect_banded): rolls must
be bit-identical to the generic edge-permutation gathers — the bench's
ring-lattice runs take only this path, so parity here is what makes its
numbers trustworthy."""

import pytest
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.ops import edges
from go_libp2p_pubsub_tpu.state import Net


def test_ring_lattice_detects_banded():
    topo = graph.ring_lattice(64, d=3)
    band = edges.detect_banded(topo.nbr, topo.rev, topo.nbr_ok)
    assert band is not None
    off, rev = band
    assert sorted(off) == sorted((o % 64) for o in [1, 2, 3, -1, -2, -3])
    # rev is an involution on slots: rev[rev[k]] == k
    assert all(rev[rev[k]] == k for k in range(6))


def test_random_connect_not_banded():
    topo = graph.random_connect(64, d=3, seed=0)
    assert edges.detect_banded(topo.nbr, topo.rev, topo.nbr_ok) is None


def test_banded_kernels_match_gather():
    rng = np.random.default_rng(3)
    topo = graph.ring_lattice(50, d=4)
    band = edges.detect_banded(topo.nbr, topo.rev, topo.nbr_ok)
    assert band is not None
    off, rev = band
    perm = jnp.asarray(edges.build_edge_perm(topo.nbr, topo.rev, topo.nbr_ok))

    x = jnp.asarray(rng.integers(0, 2**31, size=(50, 8, 3), dtype=np.int64).astype(np.uint32))
    a = np.asarray(edges.edge_permute(x, perm))
    b = np.asarray(edges.edge_permute_banded(x, off, rev))
    assert (a == b).all()

    v = jnp.asarray(rng.normal(size=(50,)).astype(np.float32))
    pa = np.asarray(v[jnp.asarray(topo.nbr)])
    pb = np.asarray(edges.peer_gather_banded(v, off))
    assert (pa == pb).all()


@pytest.mark.slow
def test_gossipsub_step_banded_equals_gather():
    # the full v1.1 step (publishes, heartbeats, scoring, fanout) must be
    # bit-identical between the roll path and the generic gather path
    n, m = 96, 32
    topo = graph.ring_lattice(n, d=3)
    subs = graph.subscribe_all(n, 1)
    net_banded = Net.build(topo, subs)
    assert net_banded.band_off is not None
    net_gather = net_banded.replace(band_off=None, band_rev=None)

    params = dataclasses.replace(GossipSubParams(), flood_publish=True)
    sp = PeerScoreParams(
        topics={0: TopicScoreParams()},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(), score_enabled=True)

    finals = []
    for net in (net_banded, net_gather):
        st = GossipSubState.init(net, m, cfg, score_params=sp, seed=0)
        step = make_gossipsub_step(cfg, net, score_params=sp)
        for r in range(10):
            po = jnp.asarray(
                np.random.default_rng(r).integers(0, n, size=(4,)).astype(np.int32)
            )
            pt = jnp.zeros((4,), jnp.int32)
            pv = jnp.ones((4,), bool)
            st = step(st, po, pt, pv)
        finals.append(st)

    a_leaves = jax.tree_util.tree_leaves(finals[0])
    b_leaves = jax.tree_util.tree_leaves(finals[1])
    for la, lb in zip(a_leaves, b_leaves):
        if jnp.issubdtype(la.dtype, jax.dtypes.prng_key):
            la, lb = jax.random.key_data(la), jax.random.key_data(lb)
        assert (np.asarray(la) == np.asarray(lb)).all()
