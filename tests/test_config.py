"""Config validation tests — mirrors the reference's rejection matrices
(score_params_test.go, and the parameter constraints at gossipsub.go:84-90,
mcache.go:23-28, peer_gater.go:57-88)."""

import dataclasses

import pytest

from go_libp2p_pubsub_tpu.config import (
    ConfigError,
    GossipSubParams,
    PeerGaterParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
    score_parameter_decay,
    ticks_for,
)


def test_gossipsub_defaults_valid():
    GossipSubParams().validate()


@pytest.mark.parametrize(
    "kw",
    [
        {"Dout": 5},          # Dout >= Dlo (gossipsub.go:89)
        {"Dout": 4},          # Dout > D/2
        {"history_gossip": 6},  # gossip > history (mcache.go:23-28)
        {"D": 20},            # D > Dhi
        {"gossip_factor": 1.5},
        {"heartbeat_interval": 0.0},
    ],
)
def test_gossipsub_invalid(kw):
    with pytest.raises(ConfigError):
        dataclasses.replace(GossipSubParams(), **kw).validate()


@pytest.mark.parametrize(
    "kw,fragment",
    [
        # the degree rejections must carry the ACTUAL values — tune/
        # candidates that trip a validator surface a debuggable message
        ({"D": 20}, "Dlo=5 D=20 Dhi=12"),
        ({"Dlo": 7}, "Dlo=7 D=6 Dhi=12"),
        ({"Dscore": 9}, "Dscore=9 D=6"),
        ({"Dout": 5}, "Dout=5 Dlo=5 D=6"),
    ],
)
def test_degree_errors_carry_values(kw, fragment):
    with pytest.raises(ConfigError) as e:
        dataclasses.replace(GossipSubParams(), **kw).validate()
    assert fragment in str(e.value)


def test_topic_score_defaults_valid():
    TopicScoreParams().validate()


@pytest.mark.parametrize(
    "kw",
    [
        {"topic_weight": -1.0},
        {"time_in_mesh_quantum": 0.0},
        {"time_in_mesh_weight": -1.0},
        {"time_in_mesh_cap": 0.0},
        {"first_message_deliveries_weight": -1.0},
        {"first_message_deliveries_decay": 2.0},
        {"first_message_deliveries_cap": 0.0},
        {"mesh_message_deliveries_weight": 1.0},      # must be negative
        {"mesh_message_deliveries_decay": 0.0},
        {"mesh_message_deliveries_cap": -1.0},
        {"mesh_message_deliveries_threshold": 0.0},
        {"mesh_message_deliveries_window": -1.0},
        {"mesh_message_deliveries_activation": 0.5},  # must be >= 1s
        {"mesh_failure_penalty_weight": 1.0},
        {"mesh_failure_penalty_decay": 1.0},
        {"invalid_message_deliveries_weight": 1.0},
        {"invalid_message_deliveries_decay": 1.0},
    ],
)
def test_topic_score_invalid(kw):
    with pytest.raises(ConfigError):
        dataclasses.replace(TopicScoreParams(), **kw).validate()


def test_peer_score_params():
    p = PeerScoreParams(topics={0: TopicScoreParams()}, skip_app_specific=True)
    p.validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(p, topic_score_cap=-1.0).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(p, ip_colocation_factor_weight=1.0).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(
            p, ip_colocation_factor_weight=-1.0, ip_colocation_factor_threshold=0
        ).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(p, behaviour_penalty_weight=1.0).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(
            p, behaviour_penalty_weight=-1.0, behaviour_penalty_decay=0.0
        ).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(p, decay_interval=0.5).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(p, decay_to_zero=1.5).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(p, skip_app_specific=False).validate()
    # bad nested topic params surface with topic id
    bad = dataclasses.replace(p, topics={3: dataclasses.replace(TopicScoreParams(), topic_weight=-1)})
    with pytest.raises(ConfigError, match="topic 3"):
        bad.validate()


def test_thresholds():
    PeerScoreThresholds().validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(PeerScoreThresholds(), gossip_threshold=1.0).validate()
    with pytest.raises(ConfigError):
        # publish > gossip (score_params.go:38-40)
        dataclasses.replace(
            PeerScoreThresholds(), gossip_threshold=-10.0, publish_threshold=-5.0
        ).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(
            PeerScoreThresholds(), publish_threshold=-50.0, graylist_threshold=-20.0
        ).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(PeerScoreThresholds(), accept_px_threshold=-1.0).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(PeerScoreThresholds(), opportunistic_graft_threshold=-1.0).validate()


def test_gater_params():
    PeerGaterParams().validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(PeerGaterParams(), threshold=0.0).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(PeerGaterParams(), global_decay=1.0).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(PeerGaterParams(), duplicate_weight=0.0).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(PeerGaterParams(), ignore_weight=0.5).validate()
    with pytest.raises(ConfigError):
        dataclasses.replace(PeerGaterParams(), reject_weight=0.5).validate()


def test_score_parameter_decay():
    # after `decay_seconds` of 1s intervals, counter reaches decay_to_zero
    # (score_params.go:277-287)
    f = score_parameter_decay(10.0)
    assert abs(f**10 - 0.01) < 1e-9
    # decay shorter than the base interval: Go's integer division gives
    # ticks=0 -> pow(dtz, +Inf) = 0.0, which validators then reject
    assert score_parameter_decay(0.5) == 0.0


def test_ticks_for():
    assert ticks_for(0.0, 1.0) == 0
    assert ticks_for(0.5, 1.0) == 1   # round up
    assert ticks_for(60.0, 1.0) == 60
    assert ticks_for(60.0, 0.5) == 120
