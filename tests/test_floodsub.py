"""FloodSub end-to-end slice tests.

Tier-2 analogue of TestBasicFloodsub (floodsub_test.go:129-169): N hosts,
publish, assert everyone subscribed receives. Tier-1 analogue: exact
golden equivalence of the vectorized engine against the scalar oracle on
random graphs (floodsub is deterministic, so bit-for-bit)."""

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step, run_rounds
from go_libp2p_pubsub_tpu.oracle.floodsub import OracleFloodSub
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net, SimState, hops
from go_libp2p_pubsub_tpu.trace.events import EV, N_EVENTS


def _mk(n, d=None, n_topics=1, msg_slots=32, seed=0, all_topics=True):
    topo = graph.connect_all(n) if d is None else graph.random_connect(n, d, seed=seed)
    subs = (
        graph.subscribe_all(n, n_topics)
        if all_topics
        else graph.subscribe_random(n, n_topics, 1, seed=seed)
    )
    net = Net.build(topo, subs)
    state = SimState.init(n, msg_slots, seed=seed, k=net.max_degree)
    return topo, subs, net, state


def _pub(origins, topics, valids, p=4):
    po = np.full(p, -1, np.int32)
    pt = np.full(p, -1, np.int32)
    pv = np.zeros(p, bool)
    for i, (o, t, v) in enumerate(zip(origins, topics, valids)):
        po[i], pt[i], pv[i] = o, t, v
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def _no_pub(p=4):
    return _pub([], [], [], p)


def test_basic_floodsub_all_receive():
    # 20 hosts, complete graph, one topic: publish from host 0 -> everyone
    # has it after one transmit round (assertReceive, floodsub_test.go:117)
    _, _, net, state = _mk(20)
    state = floodsub_step(net, state, *_pub([0], [0], [True]))
    state = floodsub_step(net, state, *_no_pub())
    have = np.asarray(bitset.unpack(state.dlv.have, 32))
    assert have[:, 0].all()
    ev = np.asarray(state.events)
    assert ev[EV.PUBLISH_MESSAGE] == 1
    assert ev[EV.DELIVER_MESSAGE] == 19  # everyone but origin
    assert ev[EV.REJECT_MESSAGE] == 0


def test_sparse_propagation_multihop():
    # sparse graph: message floods over multiple hops to every subscriber
    topo, _, net, state = _mk(50, d=3, seed=2)
    state = floodsub_step(net, state, *_pub([7], [0], [True]))
    state = run_rounds(net, state, 12)
    have = np.asarray(bitset.unpack(state.dlv.have, 32))
    assert have[:, 0].all(), "flood must reach all peers on a connected graph"
    h = np.asarray(hops(state.msgs, state.dlv))[:, 0]
    assert h[7] == 0
    assert (h[np.arange(50) != 7] >= 1).all()
    # some peer needs >1 hop on a sparse graph
    assert h.max() > 1


def test_invalid_message_not_forwarded():
    # invalid message: direct neighbors of origin see+reject it; it never
    # propagates further (Reject stops the pipeline, validation.go:309-351)
    topo, _, net, state = _mk(30, d=3, seed=4)
    state = floodsub_step(net, state, *_pub([0], [0], [False]))
    state = run_rounds(net, state, 8)
    have = np.asarray(bitset.unpack(state.dlv.have, 32))[:, 0]
    nbrs = set(topo.nbr[0][topo.nbr_ok[0]].tolist())
    got = set(np.nonzero(have)[0].tolist()) - {0}
    assert got == nbrs, "invalid msg must stop at first hop"
    ev = np.asarray(state.events)
    assert ev[EV.REJECT_MESSAGE] == len(nbrs)
    assert ev[EV.DELIVER_MESSAGE] == 0


def test_topic_isolation():
    # peers not subscribed to the topic never receive it
    n = 24
    topo = graph.random_connect(n, 4, seed=5)
    subs = graph.subscribe_random(n, n_topics=2, topics_per_peer=1, seed=5)
    net = Net.build(topo, subs)
    state = SimState.init(n, 32, seed=0, k=net.max_degree)
    origin = int(np.nonzero(subs.subscribed[:, 0])[0][0])
    state = floodsub_step(net, state, *_pub([origin], [0], [True]))
    state = run_rounds(net, state, 10)
    have = np.asarray(bitset.unpack(state.dlv.have, 32))[:, 0]
    non_subs = ~subs.subscribed[:, 0]
    assert not have[non_subs].any()


def _run_oracle_equivalence(n, d, n_topics, msg_slots, schedule, seed):
    topo = graph.random_connect(n, d, seed=seed)
    subs = graph.subscribe_random(n, n_topics, max(1, n_topics // 2), seed=seed)
    net = Net.build(topo, subs)
    state = SimState.init(n, msg_slots, seed=seed, k=net.max_degree)
    oracle = OracleFloodSub(topo, subs, msg_slots=msg_slots)

    for pubs in schedule:
        state = floodsub_step(net, state, *_pub(*zip(*pubs) if pubs else ([], [], [])))
        oracle.step(pubs)

    m = msg_slots
    have = np.asarray(bitset.unpack(state.dlv.have, m))
    fr = np.asarray(state.dlv.first_round)
    fe = np.asarray(state.dlv.first_edge)
    for i in range(n):
        assert set(np.nonzero(have[i])[0].tolist()) == oracle.seen[i], f"seen mismatch peer {i}"
        for slot in oracle.seen[i]:
            assert fr[i, slot] == oracle.first_round[(i, slot)], (i, slot)
            assert fe[i, slot] == oracle.first_edge[(i, slot)], (i, slot)
    ev = np.asarray(state.events)
    for e in range(N_EVENTS):
        assert ev[e] == oracle.events[e], f"event {EV(e).name}: {ev[e]} vs {oracle.events[e]}"


def test_oracle_equivalence_single_topic():
    rng = np.random.default_rng(0)
    n = 40
    schedule = []
    for r in range(15):
        pubs = []
        if r % 3 == 0:
            pubs.append((int(rng.integers(n)), 0, True))
        if r % 5 == 0:
            pubs.append((int(rng.integers(n)), 0, bool(rng.random() < 0.5)))
        schedule.append(pubs)
    _run_oracle_equivalence(n, d=3, n_topics=1, msg_slots=64, schedule=schedule, seed=1)


def test_oracle_equivalence_multi_topic_with_recycling():
    # msg_slots=8 forces slot recycling mid-run; oracle and engine must
    # stay bit-identical through recycles
    rng = np.random.default_rng(7)
    n = 25
    schedule = []
    for r in range(20):
        pubs = [(int(rng.integers(n)), int(rng.integers(4)), bool(rng.random() < 0.8))]
        schedule.append(pubs)
    _run_oracle_equivalence(n, d=4, n_topics=4, msg_slots=8, schedule=schedule, seed=3)


def test_hops_cdf_vs_oracle():
    # propagation-latency (hops) distribution matches the oracle exactly
    n = 60
    topo = graph.random_connect(n, 3, seed=9)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    state = SimState.init(n, 32, seed=0, k=net.max_degree)
    oracle = OracleFloodSub(topo, subs, msg_slots=32)
    pubs0 = [(5, 0, True)]
    state = floodsub_step(net, state, *_pub(*zip(*pubs0)))
    oracle.step(pubs0)
    for _ in range(15):
        state = floodsub_step(net, state, *_no_pub())
        oracle.step([])
    h = np.asarray(hops(state.msgs, state.dlv))[:, 0]
    oh = np.array([oracle.first_round.get((i, 0), -1) for i in range(n)])
    oh = np.where(oh >= 0, oh - 0, -1)  # birth = 0
    np.testing.assert_array_equal(h, oh)
