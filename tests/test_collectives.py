"""GSPMD collective profile of the sharded step (round-1 review item:
"prove the banded relabeling makes neighbor gathers halo exchanges").

Compiles the full v1.1 step sharded over the 8-virtual-device CPU mesh
and pins the collective profile of the partitioned HLO:

  * ZERO all-gathers — no peer-sized tensor is ever replicated; every
    cross-peer neighbor gather lowers to collective-permute of the band
    halo (the ring offsets are +-8, so each shard exchanges only its
    edge rows with its two neighbor shards);
  * a bounded, device-count-independent number of collective-permutes
    (one per rolled gather, not per device pair);
  * a handful of scalar all-reduces (event counters / popcount sums).

GSPMD partitioning decisions are platform-independent, so this CPU-mesh
check pins what XLA will emit on real ICI. scripts/scaling_cpu_mesh.py
produces the full 1/2/4/8-device table recorded in BASELINE.md.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.parallel import (
    collective_profile,
    make_mesh,
    shard_state,
)
from go_libp2p_pubsub_tpu.state import Net


def _bench_prng():
    """Pin the audits to the bench's PRNG (bench.py BENCH_PRNG default):
    threefry's sharded lowering emits 24 extra rng collective-permutes
    inside the heartbeat's selection passes on this image's XLA — launch
    traffic the measured configuration never pays. Returns a restore fn."""
    old = str(jax.config.jax_default_prng_impl)
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")
    return lambda: jax.config.update("jax_default_prng_impl", old)


def test_sharded_step_collective_profile():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU harness")
    restore = _bench_prng()
    try:
        _run_sharded_step_collective_profile()
    finally:
        restore()


def _run_sharded_step_collective_profile():
    n = 4096
    topo = graph.ring_lattice(n, d=8)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    sp = PeerScoreParams(
        topics={0: TopicScoreParams(
            mesh_message_deliveries_weight=0.0,
            mesh_failure_penalty_weight=0.0,
        )},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True
    )
    import dataclasses

    cfg = dataclasses.replace(cfg, fanout_slots=0, count_events=False)
    st = GossipSubState.init(net, 64, cfg, score_params=sp, seed=0)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    st = shard_state(st, make_mesh(8), n)

    import jax.numpy as jnp

    po = jnp.asarray(np.array([0, -1, -1, -1], np.int32))
    pt = jnp.asarray(np.zeros(4, np.int32))
    pv = jnp.asarray(np.ones(4, bool))
    compiled = step.lower(st, po, pt, pv).compile()
    prof = collective_profile(compiled.as_text())

    # the claim: neighbor gathers are halo exchanges, never replication
    assert prof["all-gather"] == 0, prof
    assert prof["all-to-all"] == 0, prof
    # one permute per rolled gather — bounded and independent of device
    # count (regression guard: a layout/sharding change that makes GSPMD
    # replicate or per-pair-permute would blow past this).
    # Pinned at 112 (round 3): 16 ring offsets x 7 gathers (merged
    # control wire, score plane, fwd, fe, window, + heartbeat's
    # direct/suppress gathers); 96 since round 7 (the weight-elided P5
    # app gather no longer lowers on zero-weight configs like this one).
    # Round-2 history: 96 with the score column folded into the wire
    # gather (cost 1.2 ms/round single-chip), 144 with fully per-part
    # gathers (the bf9cbc9 regression). The merge policy in
    # models/gossipsub.py trades one extra halo exchange (+16 permutes,
    # ~K*W halo rows each) for the measured single-chip win; BASELINE.md
    # "round 3" records the deliberate tradeoff.
    assert 0 < prof["collective-permute"] <= 116, prof
    assert prof["all-reduce"] <= 10, prof

    # and the sharded step actually runs
    out = compiled(st, po, pt, pv)
    jax.block_until_ready(out)
    assert int(out.core.tick) == 1


def test_phase_step_collective_profile():
    """The phase engine's ICI profile at the BENCH configuration (incl.
    its unsafe_rbg PRNG — threefry's sharded lowering adds 24 rng
    permutes the bench never pays): ONE halo-exchange set per sub-round
    (the sender-side fused data gather) + ONE coalesced control set
    (round-7 stacked wire exchange) = exactly 16·(r+1) permutes/phase,
    the projection engine's new measured input; the legacy A/B path
    (cfg.wire_coalesced=False) compiles to its 16·(r+3) (wire + score +
    window sets; the P5 app gather is weight-elided since round 7 —
    the committed rounds-3..6 artifacts' 16·(r+4) stays as the
    projection's legacy-artifact fallback only). Still zero all-gathers.

    This is also the pytest mirror of the multichip-dryrun audit
    (__graft_entry__.dryrun_multichip asserts the same equalities): the
    trace-time gather tally — what perf.sweep.measure_phase_gather_sets
    records into the bench fingerprint — must equal what GSPMD emits."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU harness")
    import os
    import sys

    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build_bench

    from go_libp2p_pubsub_tpu.ops import edges
    from go_libp2p_pubsub_tpu.perf import projection

    r = 8
    n = 4096
    po = jnp.asarray(np.full((r, 4), -1, np.int32)).at[0, 0].set(3)
    pt = jnp.asarray(np.zeros((r, 4), np.int32))
    pv = jnp.asarray(np.ones((r, 4), bool))
    restore = _bench_prng()
    try:
        st, step, _, _ = build_bench(n, 64, config="default", rounds_per_phase=r)
        st = shard_state(st, make_mesh(8), n)
        tally = []
        with edges.tally_halo_gathers(tally):
            lowered = step.lower(st, po, pt, pv, do_heartbeat=True)
        compiled = lowered.compile()
        prof = collective_profile(compiled.as_text())
        assert prof["all-gather"] == 0, prof
        assert prof["all-to-all"] == 0, prof
        # 16 ring offsets x (r data gathers + 1 coalesced control set)
        assert prof["collective-permute"] == 16 * (r + 1), prof
        # the fingerprint's measurement mechanism equals the GSPMD truth
        assert len(tally) == r + 1, tally
        assert projection.permutes_per_round(r, len(tally)) * r == \
            prof["collective-permute"]
        out = compiled(st, po, pt, pv)
        jax.block_until_ready(out)
        assert int(out.core.tick) == r

        # legacy A/B path: wire + score + window control sets
        st_l, step_l, _, _ = build_bench(
            n, 64, config="default", rounds_per_phase=r, wire_coalesced=False
        )
        st_l = shard_state(st_l, make_mesh(8), n)
        prof_l = collective_profile(
            step_l.lower(st_l, po, pt, pv, do_heartbeat=True)
            .compile().as_text()
        )
        assert prof_l["all-gather"] == 0, prof_l
        assert prof_l["collective-permute"] == 16 * (r + 3), prof_l
    finally:
        restore()


@pytest.mark.slow
def test_bench_shape_sharded_step():
    """GSPMD partitioning at the REAL bench shape (N=100k, the round-3
    review's 'extrapolated from 4,096' gap): the 8-device profile is
    identical to the 4,096-peer pin (112 permutes, 0 all-gathers) and the
    sharded step executes."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU harness")
    import os
    import sys

    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build_bench

    n = 100_000
    restore = _bench_prng()
    try:
        st, step, _, _ = build_bench(n, 64, config="default")
        st = shard_state(st, make_mesh(8), n)
        po = jnp.asarray(np.array([3, -1, -1, -1], np.int32))
        pt = jnp.asarray(np.zeros(4, np.int32))
        pv = jnp.asarray(np.ones(4, bool))
        compiled = step.lower(st, po, pt, pv).compile()
        prof = collective_profile(compiled.as_text())
        assert prof["all-gather"] == 0, prof
        assert prof["all-to-all"] == 0, prof
        assert 0 < prof["collective-permute"] <= 116, prof
        out = compiled(st, po, pt, pv)
        jax.block_until_ready(out)
        assert int(out.core.tick) == 1
    finally:
        restore()
