"""Composed integration: this round's features running together — a
flaky remote trace collector (downtime + reconnect mid-run), oversized
publishes under max_message_size, and authored messages — on a live
gossipsub network. The reference never exercises these in combination;
the point here is that the compositions hold: delivery stays correct,
the salvaged collector stream is a clean subset of the lossless JSON
trace, and the wire-block plane doesn't disturb normal traffic."""

import pytest

from go_libp2p_pubsub_tpu import api
from go_libp2p_pubsub_tpu.trace import sinks


@pytest.mark.slow
def test_flaky_collector_oversized_and_authors(tmp_path):
    col = sinks.MemoryCollector()
    jpath = str(tmp_path / "truth.json")
    json_sink = sinks.JSONTracer(jpath)
    remote = sinks.RemoteTracer(connect=col.connect, min_batch=8,
                                redial_backoff=1)
    net = api.Network(max_message_size=300,
                      trace_sinks=[json_sink, remote])
    nodes = net.add_nodes(16)
    stable = api.Identity.generate(99)
    nodes[4].author = stable       # one node publishes as a stable author
    net.dense_connect(d=5, seed=8)
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()

    small_published = 0
    for r in range(30):
        if r == 8:
            col.go_down()          # collector outage mid-run
        if r == 18:
            col.go_up()
        if r % 3 == 0:
            origin = nodes[(r // 3) % 16]
            origin.topics["t"].publish(b"m%02d" % r)
            small_published += 1
        if r in (6, 21):           # oversized: local-only, one in outage
            nodes[0].topics["t"].publish(b"X" * 1024)
        net.run(1)
    net.run(6)                     # drain
    net._session.close(None)

    # 1. delivery correctness: every small message reaches every node;
    #    the two oversized ones only reached node 0's own subscription
    counts = [sum(1 for _ in s) for s in subs]
    assert counts[0] == small_published + 2
    assert all(c == small_published for i, c in enumerate(counts) if i != 0)
    assert net.oversized_publishes == 2

    # 2. the collector really went down and came back
    assert remote.dial_failures > 0, "outage never hit the tracer"
    assert col.connections >= 2, "no reconnect happened"
    assert remote.dropped == 0     # buffer never overflowed at this scale

    # 3. the salvaged collector stream is a clean subset of the lossless
    #    JSON truth: every decoded remote event exists in the JSON trace
    truth = [e.SerializeToString() for e in sinks.read_json_trace(jpath)]
    got = [e.SerializeToString() for e in col.events()]
    assert got, "collector decoded nothing"
    from collections import Counter

    missing = Counter(got) - Counter(truth)
    assert not missing, f"{sum(missing.values())} corrupted/foreign events"
    # and it isn't trivially empty: at least the pre-outage and
    # post-recovery spans must be present (more than half of all events)
    assert len(got) > len(truth) / 2

    # 4. authored messages carry the stable identity end to end
    authored = [e for e in sinks.read_json_trace(jpath)
                if e.type == e.PUBLISH_MESSAGE
                and e.publishMessage.messageID.startswith(stable.peer_id)]
    assert len(authored) >= 1
