"""Sparse data plane (round 15): capacity-bounded CSR edge exchange.

Pins the docs/DESIGN.md §15 contracts:

  * the CSR kernels (ops/csr.py) are exact: the flat involution is an
    involution, pack/unpack round-trips, and both segment-reduction
    forms (segmented scan, segment_sum) equal their dense word-algebra
    counterparts;
  * dense-vs-CSR engine parity is BIT-EXACT for all four engines —
    full state trees, ragged AND banded topologies, chaos masks on,
    ensemble S>1, scanned windows — because the layout only changes
    HOW the exchange is computed, never what. Since round 18 the csr
    build carries the CSR-RESIDENT state tier (fe_words/served_* as
    [E, W], peerhave/iasked as [E] — docs/DESIGN.md §18), so parity
    compares under state.densify_edge_planes (exact: dense per-edge
    planes are zero on absent slots by construction);
  * the layout touches the state tree ONLY through that sanctioned
    tier: checkpoint v6 round-trips a CSR-run tree with no version
    bump, and the guards' csr row matches the committed gossipsub
    schema under the derived csr_variant_rows transformation;
  * the narrowing contract: ``narrow_counters`` stores the IHAVE
    flood-protection counters as int16 with bit-identical VALUES
    (exact by range analysis), and build() refuses configs whose caps
    don't fit;
  * the N-scaling projection (perf.projection.project_at_scale)
    reproduces the committed shard table at its anchor points and
    prices the memory term.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, driver, graph
from go_libp2p_pubsub_tpu.chaos.faults import ChaosConfig
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreThresholds,
    default_peer_score_params,
)
from go_libp2p_pubsub_tpu.models import floodsub
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import make_gossipsub_phase_step
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.ops import csr as csrops
from go_libp2p_pubsub_tpu.state import (
    Net,
    SimState,
    densify_edge_planes,
)

N = 96
M = 32
PUBW = 3

CHAOS = ChaosConfig(generator="iid", loss_rate=0.3)


def ragged_topo(n=N, d=4, seed=2):
    """random_connect pads uneven degrees — real absent slots."""
    return graph.random_connect(n, d=d, seed=seed)


def canon(net, st, batched=False):
    """Canonicalize a state for dense-vs-csr comparison: densify the
    CSR-resident planes (a no-op on dense builds)."""
    if net.edge_layout != "csr":
        return st
    if batched:
        return jax.vmap(lambda s: densify_edge_planes(net, s))(st)
    return densify_edge_planes(net, st)


def assert_trees_equal(a, b, tag=""):
    la = jtu.tree_flatten_with_path(a)[0]
    lb = jtu.tree_flatten_with_path(b)[0]
    assert len(la) == len(lb), f"{tag}: leaf count differs"
    for (p, x), (_, y) in zip(la, lb):
        if hasattr(x, "dtype") and "key" in str(x.dtype):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        assert np.asarray(x).dtype == np.asarray(y).dtype, (
            f"{tag}: dtype differs at {jtu.keystr(p)}")
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"{tag}: mismatch at {jtu.keystr(p)}")


def publish_schedule(rounds, n=N, seed=0):
    rng = np.random.default_rng(seed)
    po = rng.integers(-1, n, size=(rounds, PUBW)).astype(np.int32)
    pt = np.zeros((rounds, PUBW), np.int32)
    pv = np.ones((rounds, PUBW), bool)
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


# ---------------------------------------------------------------------------
# kernel exactness


def test_build_csr_structure():
    topo = ragged_topo()
    ct = csrops.build_csr(topo.nbr, topo.rev, topo.nbr_ok)
    e = ct.n_edges
    assert e == int(topo.nbr_ok.sum())
    assert 0 < ct.density <= 1.0
    # flat involution is an involution with no fixed points (no self
    # edges) and maps each edge to its reverse endpoint pair
    assert (ct.eperm[ct.eperm] == np.arange(e)).all()
    assert (ct.eperm != np.arange(e)).all()
    assert (ct.row[ct.eperm] == ct.col).all()
    assert (ct.col[ct.eperm] == ct.row).all()
    # row spans cover the edges in sorted owner order
    assert (np.diff(ct.row) >= 0).all()
    assert ct.row_ptr[-1] == e
    counts = np.bincount(ct.row, minlength=ct.n_peers)
    assert (np.diff(ct.row_ptr) == counts).all()


def test_build_csr_rejects_asymmetry():
    topo = ragged_topo()
    nbr_ok = topo.nbr_ok.copy()
    i, k = np.argwhere(nbr_ok)[0]
    nbr_ok[i, k] = False  # drop one direction only
    j, rk = topo.nbr[i, k], topo.rev[i, k]
    assert nbr_ok[j, rk]
    with pytest.raises(ValueError, match="not symmetric"):
        csrops.build_csr(topo.nbr, topo.rev, nbr_ok)


def test_pack_unpack_roundtrip_and_gather_parity():
    topo = ragged_topo()
    subs = graph.subscribe_all(N, 1)
    net_d = Net.build(topo, subs)
    net_c = Net.build(topo, subs, edge_layout="csr")
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2 ** 32, size=(N, topo.max_degree, 2),
                                 dtype=np.uint32))
    v = jnp.asarray(rng.integers(0, 2 ** 32, size=(N,), dtype=np.uint32))
    # pack -> unpack restores present slots, zeros absent ones
    back = net_c.unpack_edges(net_c.pack_edges(x))
    ok3 = jnp.asarray(topo.nbr_ok)[:, :, None]
    np.testing.assert_array_equal(
        np.asarray(back), np.asarray(jnp.where(ok3, x, jnp.uint32(0))))
    # the two layouts' gathers are bit-identical INCLUDING the junk
    # convention on absent slots (self-pointing / v[0])
    np.testing.assert_array_equal(
        np.asarray(net_d.edge_gather(x)), np.asarray(net_c.edge_gather(x)))
    np.testing.assert_array_equal(
        np.asarray(net_d.peer_gather(v)), np.asarray(net_c.peer_gather(v)))


def test_segment_reductions_match_dense():
    topo = ragged_topo()
    ct = csrops.build_csr(topo.nbr, topo.rev, topo.nbr_ok)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, 2 ** 32, size=(N, topo.max_degree, 2),
                                 dtype=np.uint32))
    ok3 = jnp.asarray(topo.nbr_ok)[:, :, None]
    x_masked = jnp.where(ok3, x, jnp.uint32(0))
    xe = csrops.pack_edges(x, jnp.asarray(ct.e2nk), topo.max_degree)

    # segmented-scan OR == dense word_or_reduce
    got = csrops.segment_or_words(
        xe, jnp.asarray(ct.seg_start), jnp.asarray(ct.row_last),
        jnp.asarray(ct.row_nonempty))
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(bitset.word_or_reduce(x_masked, axis=1)))

    # exclusive scan isolates first-per-bit == bitset.first_set_per_bit
    _inc, exc = csrops.segment_or_scan(xe, jnp.asarray(ct.seg_start))
    fa_flat = csrops.unpack_edges(xe & ~exc, jnp.asarray(ct.e_of_nk))
    fa_dense = jnp.where(
        ok3, bitset.first_set_per_bit(x_masked, axis=1), jnp.uint32(0))
    np.testing.assert_array_equal(np.asarray(fa_flat), np.asarray(fa_dense))

    # segment_sum == masked dense sum; popcount likewise
    vals = jnp.asarray(rng.normal(size=ct.n_edges).astype(np.float32))
    dense_sum = np.zeros(N, np.float32)
    np.add.at(dense_sum, ct.row, np.asarray(vals))
    np.testing.assert_allclose(
        np.asarray(csrops.segment_sum_edges(vals, jnp.asarray(ct.row), N)),
        dense_sum, rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(csrops.segment_popcount(xe, jnp.asarray(ct.row), N)),
        np.asarray(bitset.popcount(x_masked, axis=None).sum(axis=-1)))


# ---------------------------------------------------------------------------
# engine parity, dense vs csr (bit-exact, chaos on)


def _run_floodsub(net, rounds=6):
    po, pt, pv = publish_schedule(rounds)
    # n_edges=net.n_edges allocates the CSR-RESIDENT flat fe plane on a
    # csr net (None on dense — the same call covers both layouts)
    st = SimState.init(N, M, k=net.max_degree, n_edges=net.n_edges)
    for i in range(rounds):
        st = floodsub.floodsub_step(net, st, po[i], pt[i], pv[i],
                                    chaos=CHAOS)
    return canon(net, st)


@pytest.mark.parametrize("topo_kind", ["ragged", "banded"])
def test_floodsub_parity(topo_kind):
    topo = ragged_topo() if topo_kind == "ragged" else graph.ring_lattice(N, d=4)
    subs = graph.subscribe_all(N, 1)
    net_d = Net.build(topo, subs)
    net_c = Net.build(topo, subs, edge_layout="csr")
    if topo_kind == "banded":
        assert net_d.band_off is not None and net_c.band_off is None
    assert_trees_equal(_run_floodsub(net_d), _run_floodsub(net_c),
                       f"floodsub/{topo_kind}")


def test_randomsub_parity():
    topo = ragged_topo()
    subs = graph.subscribe_all(N, 1)
    po, pt, pv = publish_schedule(6)

    def run(layout):
        net = Net.build(topo, subs, edge_layout=layout)
        step = make_randomsub_step(net, chaos=CHAOS)
        st = SimState.init(N, M, k=net.max_degree, n_edges=net.n_edges)
        for i in range(6):
            st = step(st, po[i], pt[i], pv[i])
        return canon(net, st)

    assert_trees_equal(run("dense"), run("csr"), "randomsub")


def _gossip_cfg(layout, **kw):
    return GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
        chaos=CHAOS, edge_layout=layout, **kw)


def test_gossipsub_parity():
    topo = ragged_topo()
    subs = graph.subscribe_all(N, 1)
    sp = default_peer_score_params(1)
    po, pt, pv = publish_schedule(8)

    def run(layout):
        net = Net.build(topo, subs, edge_layout=layout)
        cfg = _gossip_cfg(layout)
        st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
        step = make_gossipsub_step(cfg, net, score_params=sp)
        for i in range(8):
            st = step(st, po[i], pt[i], pv[i])
        return canon(net, st)

    assert_trees_equal(run("dense"), run("csr"), "gossipsub")


@pytest.mark.parametrize("r", [4, pytest.param(8, marks=pytest.mark.slow)])
def test_gossipsub_phase_parity(r):
    topo = ragged_topo()
    subs = graph.subscribe_all(N, 1)
    sp = default_peer_score_params(1)
    po, pt, pv = publish_schedule(2 * r)

    def run(layout):
        net = Net.build(topo, subs, edge_layout=layout)
        cfg = _gossip_cfg(layout, heartbeat_every=r)
        st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
        step = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
        for p in range(2):
            st = step(st, po[p * r:(p + 1) * r], pt[:r], pv[:r],
                      do_heartbeat=True)
        return canon(net, st)

    assert_trees_equal(run("dense"), run("csr"), f"phase r={r}")


def test_scanned_window_parity():
    """driver.make_scan over a CSR step == the dense python loop — the
    scanned window carries the sparse exchange inside one program."""
    topo = ragged_topo()
    subs = graph.subscribe_all(N, 1)
    sp = default_peer_score_params(1)
    rounds = 8
    po, pt, pv = publish_schedule(rounds)

    net_d = Net.build(topo, subs)
    cfg_d = _gossip_cfg("dense")
    st = GossipSubState.init(net_d, M, cfg_d, score_params=sp, seed=0)
    step_d = make_gossipsub_step(cfg_d, net_d, score_params=sp)
    for i in range(rounds):
        st = step_d(st, po[i], pt[i], pv[i])

    net_c = Net.build(topo, subs, edge_layout="csr")
    cfg_c = _gossip_cfg("csr")
    stc = GossipSubState.init(net_c, M, cfg_c, score_params=sp, seed=0)
    scan = driver.make_scan(
        make_gossipsub_step(cfg_c, net_c, score_params=sp),
        heartbeat_every=1, rounds_per_phase=1, static_heartbeat=False)
    stc = scan(stc, po, pt, pv)
    assert_trees_equal(st, canon(net_c, stc),
                       "scanned csr window vs dense loop")


def test_ensemble_parity_s3():
    """S=3 vmapped CSR ensemble == vmapped dense ensemble, bit-exact
    (threefry — the parity-gate PRNG — vmaps elementwise)."""
    from go_libp2p_pubsub_tpu.ensemble import batch as ebatch

    topo = ragged_topo()
    subs = graph.subscribe_all(N, 1)
    sp = default_peer_score_params(1)
    s_dim = 3
    rounds = 6
    po, pt, pv = publish_schedule(rounds)

    def run(layout):
        net = Net.build(topo, subs, edge_layout=layout)
        cfg = _gossip_cfg(layout)
        st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
        states = ebatch.batch_states(st, s_dim)
        ens = ebatch.lift_step(make_gossipsub_step(cfg, net, score_params=sp))
        for i in range(rounds):
            states = ens(states, ebatch.tile(po[i], s_dim),
                         ebatch.tile(pt[i], s_dim), ebatch.tile(pv[i], s_dim))
        return canon(net, states, batched=True)

    assert_trees_equal(run("dense"), run("csr"), "ensemble S=3")


def test_checkpoint_v6_roundtrip_csr(tmp_path):
    """A CSR-run state tree checkpoints and restores with NO version
    bump (the layout lives in the Net, never the state), and the
    resumed run continues bit-identical to the uninterrupted one."""
    topo = ragged_topo()
    subs = graph.subscribe_all(N, 1)
    sp = default_peer_score_params(1)
    po, pt, pv = publish_schedule(8)
    net = Net.build(topo, subs, edge_layout="csr")
    cfg = _gossip_cfg("csr")
    step = make_gossipsub_step(cfg, net, score_params=sp)

    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    for i in range(4):
        st = step(st, po[i], pt[i], pv[i])
    path = str(tmp_path / "csr_mid.ckpt")
    checkpoint.save(path, st)
    template = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    restored = checkpoint.restore(path, template)
    assert_trees_equal(st, restored, "checkpoint restore")

    resumed = restored
    for i in range(4, 8):
        resumed = step(resumed, po[i], pt[i], pv[i])
    uninterrupted = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
    for i in range(8):
        uninterrupted = step(uninterrupted, po[i], pt[i], pv[i])
    assert_trees_equal(uninterrupted, resumed, "resume == uninterrupted")


# ---------------------------------------------------------------------------
# narrowing contract


def test_narrow_counters_value_exact():
    topo = ragged_topo()
    subs = graph.subscribe_all(N, 1)
    sp = default_peer_score_params(1)
    po, pt, pv = publish_schedule(8)

    def run(narrow):
        net = Net.build(topo, subs)
        cfg = GossipSubConfig.build(
            GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
            narrow_counters=narrow)
        st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
        step = make_gossipsub_step(cfg, net, score_params=sp)
        for i in range(8):
            st = step(st, po[i], pt[i], pv[i])
        return st

    wide, narrow = run(False), run(True)
    assert narrow.peerhave.dtype == jnp.int16
    assert narrow.iasked.dtype == jnp.int16
    np.testing.assert_array_equal(
        np.asarray(wide.peerhave),
        np.asarray(narrow.peerhave).astype(np.int32))
    np.testing.assert_array_equal(
        np.asarray(wide.iasked), np.asarray(narrow.iasked).astype(np.int32))
    # every OTHER leaf bit-identical — the narrowing never leaks
    np.testing.assert_array_equal(np.asarray(wide.scores),
                                  np.asarray(narrow.scores))
    np.testing.assert_array_equal(np.asarray(wide.core.dlv.have),
                                  np.asarray(narrow.core.dlv.have))


def test_narrow_counters_rejects_oversized_cap():
    with pytest.raises(ValueError, match="max_ihave_length"):
        GossipSubConfig.build(
            dataclasses.replace(GossipSubParams(), max_ihave_length=2 ** 15),
            narrow_counters=True)
    # peerhave's bound is the heartbeat clear cadence, not the IHAVE
    # message cap — a cadence outside int16 must be refused too
    with pytest.raises(ValueError, match="heartbeat_every"):
        GossipSubConfig.build(
            GossipSubParams(), narrow_counters=True,
            heartbeat_every=2 ** 15)


# ---------------------------------------------------------------------------
# static selection + guards + artifacts


def test_layout_mismatch_rejected():
    topo = ragged_topo()
    subs = graph.subscribe_all(N, 1)
    net = Net.build(topo, subs, edge_layout="csr")
    cfg = GossipSubConfig.build(GossipSubParams(), edge_layout="dense")
    with pytest.raises(ValueError, match="edge_layout"):
        make_gossipsub_step(cfg, net)
    with pytest.raises(ValueError, match="edge_layout"):
        Net.build(topo, subs, edge_layout="coo")
    with pytest.raises(ValueError, match="edge_layout"):
        GossipSubConfig.build(GossipSubParams(), edge_layout="coo")


def test_dense_build_has_no_csr_leaves():
    """The dense path's Net tree is unchanged — the elision-when-off
    face of the layout (the HLO census gates pin the program side)."""
    topo = graph.ring_lattice(N, d=4)
    subs = graph.subscribe_all(N, 1)
    net = Net.build(topo, subs)
    assert net.edge_layout == "dense"
    assert net.csr_col is None and net.csr_eperm is None
    assert net.csr_e2nk is None and net.csr_e_of_nk is None
    assert net.csr_row is None
    assert net.n_edges is None


def test_guards_csr_negative():
    """Seeded negative: the csr guard row must FAIL loudly when the
    committed base rows disagree (schema drift = layout leaked into
    the state tree)."""
    from go_libp2p_pubsub_tpu.analysis import guards

    base = guards.load_baseline()
    assert base is not None, "STATE_SCHEMA.json missing"
    rows = [dict(r) for r in base["engines"]["gossipsub"]["leaves"]]
    h = guards.build_csr_harness()
    out_tree = guards.strict_trace(h)
    # positive: match against the committed rows (check_schema_csr
    # applies the round-18 csr_variant_rows transformation itself)
    guards.check_schema_csr(h, out_tree, rows)
    # negative: corrupt one committed dtype
    rows[0] = {**rows[0], "dtype": "int64"}
    with pytest.raises(guards.GuardViolation,
                       match="leaked beyond the resident tier"):
        guards.check_schema_csr(h, out_tree, rows)


def test_simlint_covers_csr_kernels():
    """Seeded negatives: the word-dtype / traced-branch rules police
    ops/csr.py like every other ops module (the repo's own csr.py must
    lint clean — the make-analyze positive covers that)."""
    import textwrap

    from go_libp2p_pubsub_tpu.analysis import simlint

    def lint(src):
        return {v.rule
                for v in simlint.lint_source(textwrap.dedent(src),
                                             "ops/csr.py")}

    assert "word-dtype" in lint("""
        import jax.numpy as jnp
        def segment_or_bad(words_e):
            return words_e & 1
    """)
    assert "traced-branch" in lint("""
        import jax.numpy as jnp
        def unpack_bad(x_e, e_of_nk):
            if jnp.any(e_of_nk < 0):
                return x_e
            return x_e + jnp.uint32(1)
    """)
    assert lint("""
        import jax.numpy as jnp
        def segment_or_ok(words_e):
            return words_e & jnp.uint32(1)
    """) == set()


def test_fingerprint_and_artifact_edge_layout():
    from go_libp2p_pubsub_tpu.perf.artifacts import BenchRecord
    from go_libp2p_pubsub_tpu.perf.sweep import workload_fingerprint

    fp = workload_fingerprint("default", 1000, 64, 1, 1)
    assert fp["engine"]["edge_layout"] == "dense"
    fp_csr = workload_fingerprint("default", 1000, 64, 1, 1,
                                  edge_layout="csr")
    assert fp_csr["engine"]["edge_layout"] == "csr"
    rec = BenchRecord(metric="m", value=1.0, unit="u", vs_baseline=0.1,
                      fingerprint=fp_csr)
    assert rec.edge_layout == "csr"
    legacy = BenchRecord(metric="m", value=1.0, unit="u", vs_baseline=0.1)
    assert legacy.edge_layout == "dense"


# ---------------------------------------------------------------------------
# N-scaling projection


def test_project_at_scale():
    from go_libp2p_pubsub_tpu.perf.projection import (
        ROUND5_SHARD_RATES_R16,
        project,
        project_at_scale,
        shard_ms_at,
    )

    # anchor points reproduce the committed table exactly
    for n, rate in ROUND5_SHARD_RATES_R16.items():
        assert shard_ms_at(n) == pytest.approx(1000.0 / rate)
    # monotone between/beyond anchors
    assert shard_ms_at(125_000) > shard_ms_at(100_000)
    assert shard_ms_at(400_000) > shard_ms_at(200_000)
    # the 100k projection through the scale API == the round-5 path
    base = project(1000.0 / ROUND5_SHARD_RATES_R16[12_500], 16)
    scaled = project_at_scale(100_000)
    assert scaled.shard_n == 12_500
    assert scaled.projection.rounds_per_sec == base.rounds_per_sec
    # memory term: a plainly-too-big bytes/peer fails the HBM gate
    tight = project_at_scale(1_000_000, bytes_per_peer=1e6)
    assert tight.fits_hbm is False
    roomy = project_at_scale(1_000_000, bytes_per_peer=2300.0)
    assert roomy.fits_hbm is True and roomy.hbm_headroom > 1.0


def test_mem_audit_reproduces():
    """The committed MEM_AUDIT.json is pure shape arithmetic and must
    reproduce byte-identical with defaults (the make mem-audit gate)."""
    import json
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(root, "scripts"))
    import memstat

    with open(memstat.AUDIT_PATH) as f:
        committed = json.load(f)
    assert memstat.build_audit() == committed
