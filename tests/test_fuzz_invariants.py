"""Randomized configuration fuzz: the protocol invariants that must hold
for EVERY valid configuration, checked across randomly drawn topologies,
parameter sets, and feature combinations.

The parity suites pin exact behavior on fixed configs; this sweep guards
the configuration space between them — the analogue of the reference's
breadth of hand-written per-feature integration tests, compressed into
properties (mesh containment/degree bounds, topic isolation, causal hop
timing, backoff exclusion) that hold regardless of the drawn config.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net

M = 32
N_CONFIGS = 6


def _draw_config(rng):
    """One random valid configuration (params validated by construction)."""
    n = int(rng.integers(24, 72))
    d = int(rng.integers(3, 9))
    n_topics = int(rng.choice([1, 2, 4]))
    tpp = 1 if n_topics == 1 else int(rng.integers(1, n_topics))
    dlo = int(rng.integers(2, 5))
    dd = dlo + int(rng.integers(1, 3))
    dhi = dd + int(rng.integers(1, 5))
    params = dataclasses.replace(
        GossipSubParams(),
        D=dd, Dlo=dlo, Dhi=dhi,
        Dscore=int(rng.integers(0, dlo + 1)),
        Dout=int(rng.integers(0, min(dlo - 1, dd // 2) + 1)),
        Dlazy=int(rng.integers(2, 8)),
        flood_publish=bool(rng.random() < 0.5),
        gossip_factor=float(rng.uniform(0.1, 0.4)),
        history_length=int(rng.integers(3, 6)),
        history_gossip=3,
    )
    params = dataclasses.replace(
        params, history_gossip=min(3, params.history_length)
    )
    score_on = bool(rng.random() < 0.5)
    val_delay = int(rng.choice([0, 0, 1, 2]))
    queue_cap = int(rng.choice([0, 0, 0, 8]))
    return n, d, n_topics, tpp, params, score_on, val_delay, queue_cap


def _build(seed):
    rng = np.random.default_rng(seed)
    n, d, n_topics, tpp, params, score_on, val_delay, queue_cap = _draw_config(rng)
    topo = graph.random_connect(n, d, seed=seed)
    if n_topics == 1:
        subs = graph.subscribe_all(n, 1)
    else:
        subs = graph.subscribe_random(n, n_topics=n_topics,
                                      topics_per_peer=tpp, seed=seed)
    net = Net.build(topo, subs)
    sp = None
    if score_on:
        sp = PeerScoreParams(
            topics={t: TopicScoreParams(mesh_message_deliveries_weight=0.0,
                                        mesh_failure_penalty_weight=0.0)
                    for t in range(n_topics)},
            skip_app_specific=True,
            behaviour_penalty_weight=-1.0,
            behaviour_penalty_threshold=1.0,
            behaviour_penalty_decay=0.9,
        )
    cfg = GossipSubConfig.build(
        params, PeerScoreThresholds(), score_enabled=score_on,
        validation_delay_rounds=val_delay, queue_cap=queue_cap,
    )
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    return topo, subs, net, cfg, st, step, rng


def _check_invariants(topo, subs, cfg, st, tick_desc):
    mesh = np.asarray(st.mesh)              # [N, S, K]
    n, s_slots, k_dim = mesh.shape

    # 1. mesh edges only on live topology edges
    ok = np.asarray(topo.nbr_ok)
    assert not mesh[~np.broadcast_to(ok[:, None, :], mesh.shape)].any(), (
        f"{tick_desc}: mesh bit on a nonexistent edge"
    )

    # 2. mesh degree bounded by Dhi after a heartbeat settles
    deg = mesh.sum(axis=2)
    assert (deg <= cfg.Dhi).all(), (
        f"{tick_desc}: mesh degree {deg.max()} exceeds Dhi={cfg.Dhi}"
    )

    # 3. mesh edges only toward peers subscribed to that topic slot
    sub = subs.subscribed                   # [N, T]
    mt = subs.my_topics                     # [N, S]
    nbr = np.asarray(topo.nbr)
    for s in range(s_slots):
        t_of = mt[:, s]                     # my slot-s topic, -1 pad
        for k in range(k_dim):
            rows = mesh[:, s, k]
            if not rows.any():
                continue
            js = np.nonzero(rows)[0]
            ts = t_of[js]
            assert (ts >= 0).all(), f"{tick_desc}: mesh on an empty topic slot"
            assert sub[nbr[js, k], ts].all(), (
                f"{tick_desc}: mesh edge toward a non-subscriber"
            )

    # 4. scores finite
    if cfg.score_enabled:
        sc = np.asarray(st.scores)
        assert np.isfinite(sc).all(), f"{tick_desc}: non-finite score"

    # 5. backoff excludes mesh (a pruned/backing-off edge must not be in
    #    the mesh once the heartbeat has run)
    bp = np.asarray(st.backoff_present)
    be = np.asarray(st.backoff_expire)
    live_backoff = bp & (be > int(st.core.tick))
    assert not (mesh & live_backoff).any(), (
        f"{tick_desc}: mesh edge under live backoff"
    )


def _check_delivery(topo, subs, st, slot, topic, origin, pub_tick, tick_desc):
    have = np.asarray(bitset.unpack(st.core.dlv.have, M))[:, slot]
    fr = np.asarray(st.core.dlv.first_round)[:, slot]
    sub = subs.subscribed[:, topic]

    # topic isolation: non-subscribers never hold the message
    leaked = have & ~sub
    leaked[origin] = False
    assert not leaked.any(), f"{tick_desc}: delivery outside the topic"

    # causality: receivers see it strictly after publish; origin exactly at
    got = have.copy()
    got[origin] = False
    assert (fr[got] > pub_tick).all(), f"{tick_desc}: receipt before publish"
    assert fr[origin] == pub_tick, f"{tick_desc}: origin first_round wrong"


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(N_CONFIGS))
def test_random_config_invariants(seed):
    topo, subs, net, cfg, st, step, rng = _build(seed + 1000)
    n = topo.n_peers

    # warmup: mesh formation
    for _ in range(12):
        st = step(st, *no_publish())
    _check_invariants(topo, subs, cfg, st, f"seed {seed} post-warmup")

    # publish from three random subscribed origins on random topics
    published = []
    for _ in range(3):
        t = int(rng.integers(0, subs.n_topics))
        cands = np.nonzero(subs.subscribed[:, t])[0]
        o = int(cands[rng.integers(0, len(cands))])
        po = jnp.asarray(np.array([o, -1, -1, -1], np.int32))
        pt = jnp.asarray(np.array([t, 0, 0, 0], np.int32))
        pv = jnp.asarray(np.array([True, False, False, False]))
        pub_tick = int(st.core.tick)
        slot = int(st.core.msgs.cursor) % M
        st = step(st, po, pt, pv)
        published.append((slot, t, o, pub_tick))
        for _ in range(4):
            st = step(st, *no_publish())

    # settle, then re-check everything
    for _ in range(8):
        st = step(st, *no_publish())
    _check_invariants(topo, subs, cfg, st, f"seed {seed} post-publish")
    for slot, t, o, pub_tick in published:
        _check_delivery(topo, subs, st, slot, t, o, pub_tick,
                        f"seed {seed} slot {slot}")

    # lossless configs must reach every subscriber in the union-connected
    # component; lossy (queue_cap) configs may genuinely drop
    if cfg.queue_cap == 0:
        for slot, t, o, pub_tick in published:
            have = np.asarray(bitset.unpack(st.core.dlv.have, M))[:, slot]
            sub = subs.subscribed[:, t]
            cover = have[sub].mean() if sub.any() else 1.0
            assert cover > 0.85, (
                f"seed {seed}: coverage {cover:.0%} on topic {t} "
                f"(subscribers {int(sub.sum())})"
            )
