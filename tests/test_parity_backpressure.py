"""Backpressure (queue_cap) parity vs the scalar oracle.

The engine's outbound-queue model (per-link per-round budget, lowest
slots kept, overflow lost, saturated links suppressing the next IHAVE —
models the reference's 32-deep per-peer writer queue with doDropRPC,
gossipsub.go:1153-1160, comm.go:139-170) gets its distributional parity
row here: under a publish load heavy enough that links genuinely
saturate, the engine's and oracle's propagation CDFs, coverage ratios,
and drop accounting must agree. RNG streams differ, so the comparison is
distributional like every gossipsub parity row (survey §7 hard-part d).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import GossipSubParams
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.oracle.gossipsub import OracleGossipSub
from go_libp2p_pubsub_tpu.state import Net, hops
from go_libp2p_pubsub_tpu.trace.events import EV

N = 128
DEG = 8
MSG_SLOTS = 128    # > total messages: no slot recycling, full hop record
QUEUE_CAP = 2      # tight: 4 publishes/round through D~6 meshes saturates
WARMUP = 20
PUB_ROUNDS = 24
PUBS_PER_ROUND = 4
DRAIN = 25
MAX_H = 14


def _schedule(seed=17):
    rng = np.random.default_rng(seed)
    return rng.integers(0, N, size=(PUB_ROUNDS, PUBS_PER_ROUND)).astype(np.int32)


def _cdf(hop_list, total):
    hist = np.zeros(MAX_H + 1)
    for h in hop_list:
        hist[min(h, MAX_H)] += 1
    return np.cumsum(hist) / total


ENGINE_SEEDS = (3, 4, 5, 6, 7)
ORACLE_SEEDS = (21, 22, 23, 24, 25)


def _run_engine(topo, subs, cfg, seed):
    net = Net.build(topo, subs)
    st = GossipSubState.init(net, MSG_SLOTS, cfg, seed=seed)
    step = make_gossipsub_step(cfg, net)
    empty = no_publish(PUBS_PER_ROUND)
    po_s = _schedule()
    for _ in range(WARMUP):
        st = step(st, *empty)
    pt = jnp.zeros((PUBS_PER_ROUND,), jnp.int32)
    pv = jnp.ones((PUBS_PER_ROUND,), bool)
    for r in range(PUB_ROUNDS):
        st = step(st, jnp.asarray(po_s[r]), pt, pv)
    for _ in range(DRAIN):
        st = step(st, *empty)
    h = np.asarray(hops(st.core.msgs, st.core.dlv))
    return [int(x) for x in h.ravel() if x >= 0], np.asarray(st.core.events)


def _run_oracle(topo, subs, cfg, seed):
    o = OracleGossipSub(topo, subs, cfg, msg_slots=MSG_SLOTS, seed=seed)
    po_s = _schedule()
    for _ in range(WARMUP):
        o.step()
    for r in range(PUB_ROUNDS):
        o.step([(int(po_s[r][j]), 0, True) for j in range(PUBS_PER_ROUND)])
    for _ in range(DRAIN):
        o.step()
    return [hop for _, hop in o.hops().items()], o.events


# Measured margins for this row (this config, 5-seed pools, 96 msgs/seed):
# engine self-sup 1.48%, oracle self-sup 1.27%, cross-sups 2.4-3.1%. The
# residual above self-noise is attributed (by ablation, see the session
# notes in PARITY.md) to the mesh-formation lottery's tail: with identical
# incoming-graft marginals (~6.1/node), mutuality (0.36-0.43), and sent
# counts, the engine forms fewer >Dhi rows at the formation heartbeat
# (9.4 vs 13.0 of 128), so fewer rows get cut to D and its converged mesh
# is denser (8.77 vs 8.44) — fewer gossip targets, slower loss recovery.
# The cap/recovery mechanics themselves are exactly equal: a deterministic
# 3-peer differential (blocked-mesh leech, cap=1) matches bit-for-bit,
# including the unrecoverable-drop case. Hence the bound: above the
# measured cross-sup, far below anything a mechanics bug would produce.
SUP_BOUND = 0.035


@pytest.mark.slow
def test_backpressure_cdf_parity_vs_oracle():
    """Pooled multi-seed comparison: a single seed can legitimately lose a
    whole message to the cap (an origin whose neighborhood is almost fully
    meshed pushes once into saturated links; the lone gossip target is
    congested; the window closes — the reference behaves identically when
    its writer queues eat an origin's only send), which moves coverage by
    1/n_msgs at a stroke. Pooling seeds on both sides absorbs that tail,
    the same methodology as every gossipsub parity row (PARITY.md)."""
    topo = graph.random_connect(N, d=DEG, seed=6)
    subs = graph.subscribe_all(N, 1)
    cfg = GossipSubConfig.build(GossipSubParams(), queue_cap=QUEUE_CAP)

    hv_all, ho_all = [], []
    drops_v = drops_o = 0.0
    ev_sum = np.zeros(3)
    ov_sum = np.zeros(3)
    keys = (EV.DELIVER_MESSAGE, EV.DUPLICATE_MESSAGE, EV.SEND_RPC)
    for s in ENGINE_SEEDS:
        hv, ev = _run_engine(topo, subs, cfg, s)
        hv_all += hv
        drops_v += float(ev[EV.DROP_RPC])
        ev_sum += [float(ev[e]) for e in keys]
    for s in ORACLE_SEEDS:
        ho, oev = _run_oracle(topo, subs, cfg, s)
        ho_all += ho
        drops_o += float(oev[EV.DROP_RPC])
        ov_sum += [float(oev[e]) for e in keys]

    n_msgs = PUB_ROUNDS * PUBS_PER_ROUND
    total = n_msgs * N * len(ENGINE_SEEDS)

    # the cap must actually bite, on both sides, at comparable volume
    assert drops_v > 0 and drops_o > 0
    assert abs(drops_v - drops_o) / drops_o <= 0.25, (drops_v, drops_o)

    # pooled coverage: the sustained 24-round storm at cap=2 genuinely
    # loses a few percent on both sides — parity is that they lose the
    # SAME few percent
    cov_v, cov_o = len(hv_all) / total, len(ho_all) / total
    assert cov_v > 0.9 and cov_o > 0.9, (cov_v, cov_o)
    assert abs(cov_v - cov_o) <= 0.02, f"coverage: {cov_v:.4f} vs {cov_o:.4f}"

    # pooled propagation CDF within the measured-noise-derived bound (see
    # SUP_BOUND above; the 2% north-star tolerance applies to lossless
    # rows — the lossy regime's seed noise is structurally larger)
    sup = float(np.max(np.abs(_cdf(hv_all, total) - _cdf(ho_all, total))))
    assert sup <= SUP_BOUND, f"pooled sup {sup:.4f}"

    # mean propagation latency must agree tightly even where the CDF's
    # step noise is larger
    mv, mo = np.mean(hv_all), np.mean(ho_all)
    assert abs(mv - mo) / mo <= 0.03, f"mean hops {mv:.3f} vs {mo:.3f}"

    # aggregate accounting in the lossy regime
    for j, e in enumerate(keys):
        assert ov_sum[j] > 0
        assert abs(ev_sum[j] - ov_sum[j]) / ov_sum[j] <= 0.10, (
            f"event {e}: vec {ev_sum[j]} oracle {ov_sum[j]}"
        )


def test_deterministic_cap_recovery_bit_exact():
    """3-peer line, 0-1 mesh-blocked, cap=1, two same-round publishes at
    node 2: the whole cap + recovery timeline is deterministic (no
    selection randomness: gossip candidates never exceed targets), so
    engine and oracle must agree BIT-FOR-BIT — slot 0 crosses the 2->1
    mesh link (cap keeps the lowest slot), slot 1 is dropped and dies
    (node 2 has no non-mesh neighbor to gossip to; the reference's full
    writer queue kills it identically), node 0 recovers slot 0 via
    IHAVE -> IWANT -> response exactly two rounds after node 1 holds it."""
    from go_libp2p_pubsub_tpu.ops import bitset

    M = 16
    topo = graph.line(3)
    subs = graph.subscribe_all(3, 1)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(GossipSubParams(), queue_cap=1)
    FAR = 2 ** 30
    nbr, ok, rev = np.asarray(topo.nbr), np.asarray(topo.nbr_ok), np.asarray(topo.rev)

    st = GossipSubState.init(net, M, cfg, seed=0)
    step = make_gossipsub_step(cfg, net)
    bp = np.zeros(st.backoff_present.shape, bool)
    be = np.zeros(st.backoff_expire.shape, np.int32)
    o = OracleGossipSub(topo, subs, cfg, msg_slots=M, seed=1)
    for k in range(topo.max_degree):
        if ok[0, k] and nbr[0, k] == 1:
            bp[0, :, k] = True
            be[0, :, k] = FAR
            bp[1, :, rev[0, k]] = True
            be[1, :, rev[0, k]] = FAR
            o.backoff_present[0].add((0, int(k)))
            o.backoff_expire[0][(0, int(k))] = FAR
            rk = int(rev[0, k])
            o.backoff_present[1].add((0, rk))
            o.backoff_expire[1][(0, rk)] = FAR
    st = st.replace(backoff_present=jnp.asarray(bp), backoff_expire=jnp.asarray(be))

    for _ in range(5):
        st = step(st, *no_publish())
        o.step()
    po = jnp.asarray(np.array([2, 2, -1, -1], np.int32))
    pt = jnp.asarray(np.zeros(4, np.int32))
    pv = jnp.asarray(np.array([True, True, False, False]))
    st = step(st, po, pt, pv)
    o.step([(2, 0, True), (2, 0, True)])

    for r in range(8):
        st = step(st, *no_publish())
        o.step()
        seen_eng = [
            set(np.flatnonzero(row).tolist())
            for row in np.asarray(bitset.unpack(st.core.dlv.have, M))
        ]
        seen_orc = [set(o.seen[i]) for i in range(3)]
        assert seen_eng == seen_orc, (r, seen_eng, seen_orc)
    # the timeline's endpoints: slot 0 everywhere, slot 1 only at its origin
    assert seen_eng[0] == {0} and seen_eng[1] == {0} and seen_eng[2] == {0, 1}
    # first-receipt rounds agree exactly (the CDF source, not just the sets)
    fr_eng = np.asarray(st.core.dlv.first_round)
    for i in range(3):
        for slot in (0, 1):
            assert fr_eng[i, slot] == o.first_round.get((i, slot), -1), (
                i, slot, fr_eng[i, slot], o.first_round.get((i, slot)))


@pytest.mark.slow
def test_backpressure_shared_mesh_no_gossip_bitexact():
    """Round-4 attribution closer: with the SAME converged mesh injected
    into both sides and the gossip plane off (Dlazy=0, gossip_factor=0),
    the capped mesh-push pipeline is fully deterministic — and the engine
    and oracle agree BIT-EXACTLY at pool scale (hop multisets and
    coverage identical; measured sup 0.00%, cov 0.6286 both). This
    upgrades round 3's 3-peer differential to the full 128-peer storm:
    the lossy-regime mechanics (per-link budgets, lowest-slot drops,
    echo exclusion, recovery windows) carry NO residual at all. The
    row's remaining cross-sup is the gossip-selection lottery (shared-
    mesh cross-sup 1.1-2.2% vs oracle self-noise 1.5%) stacked on the
    mesh-formation lottery (PARITY.md backpressure section)."""
    import dataclasses

    topo = graph.random_connect(N, d=DEG, seed=6)
    subs = graph.subscribe_all(N, 1)
    cfg = GossipSubConfig.build(
        dataclasses.replace(GossipSubParams(), Dlazy=0), queue_cap=QUEUE_CAP
    )
    cfg = dataclasses.replace(cfg, gossip_factor=0.0)
    net = Net.build(topo, subs)
    step = make_gossipsub_step(cfg, net)
    empty = no_publish(PUBS_PER_ROUND)
    po_s = _schedule()
    pt = jnp.zeros((PUBS_PER_ROUND,), jnp.int32)
    pv = jnp.ones((PUBS_PER_ROUND,), bool)

    for w in (3, 5):
        st = GossipSubState.init(net, MSG_SLOTS, cfg, seed=w)
        for _ in range(WARMUP):
            st = step(st, *empty)
        mesh_np = np.asarray(st.mesh)
        for r in range(PUB_ROUNDS):
            st = step(st, jnp.asarray(po_s[r]), pt, pv)
        for _ in range(DRAIN):
            st = step(st, *empty)
        h = np.asarray(hops(st.core.msgs, st.core.dlv))
        hv = sorted(int(x) for x in h.ravel() if x >= 0)

        o = OracleGossipSub(topo, subs, cfg, msg_slots=MSG_SLOTS, seed=900 + w)
        for i in range(N):
            for t in list(o.mesh[i].keys()):
                o.mesh[i][t] = set(
                    int(k) for k in np.flatnonzero(mesh_np[i, 0])
                )
        for r in range(PUB_ROUNDS):
            o.step([(int(po_s[r][j]), 0, True)
                    for j in range(PUBS_PER_ROUND)])
        for _ in range(DRAIN):
            o.step()
        ho = sorted(hop for _, hop in o.hops().items())
        assert hv == ho, (
            f"w={w}: shared-mesh no-gossip run diverged "
            f"({len(hv)} vs {len(ho)} deliveries)"
        )
