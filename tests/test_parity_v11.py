"""Composed v1.1 parity: engine vs per-node oracle WITH the score plane
live in the loop.

The north star's CDF claim is for GossipSub v1.1 (BASELINE.json); round 1
only proved v1.0 parity (oracle excluded scoring). These harnesses run
the composed machine — scoring + thresholds + promise penalties (+ sybil
adversary / multi-topic fanout) — on both sides and assert the
propagation-latency CDF stays within the 2% sup-norm budget.

Scaled-down instances of the BASELINE.json configs:
  * sybil (#4): 20% control-plane-only attackers, deficit scoring active,
    graylist threshold live (gater + validation throttle excluded: both
    add RNG-heavy admission noise orthogonal to the score-plane claim)
  * eth2 (#5): multi-topic attestation-subnet geometry with publishes to
    unjoined topics (fanout) and scoring on every subnet
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
    no_publish,
)
from go_libp2p_pubsub_tpu.oracle.gossipsub import OracleGossipSub
from go_libp2p_pubsub_tpu.state import Net, hops

N = 192
DEG = 8
MSG_SLOTS = 64
WARMUP = 24
PUB_ROUNDS = 18
PUBS_PER_ROUND = 2
DRAIN = 12
MAX_H = 14


def _sybil_setup():
    topo = graph.random_connect(N, d=DEG, seed=5)
    subs = graph.subscribe_all(N, 1)
    rng = np.random.default_rng(2)
    adversary = rng.random(N) < 0.2
    tp = TopicScoreParams(
        mesh_message_deliveries_weight=-0.5,
        mesh_message_deliveries_threshold=4.0,
        mesh_message_deliveries_activation=10.0,
        mesh_message_deliveries_window=2.0,
    )
    sp = PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    thr = PeerScoreThresholds(
        gossip_threshold=-10.0, publish_threshold=-20.0,
        graylist_threshold=-40.0,
    )
    params = GossipSubParams()
    cfg = GossipSubConfig.build(params, thr, score_enabled=True)
    cfg = dataclasses.replace(cfg, fanout_slots=0)
    # honest origins only (a sybil origin transmits nothing)
    honest = np.flatnonzero(~adversary)
    sched = honest[
        rng.integers(0, len(honest), size=(PUB_ROUNDS, PUBS_PER_ROUND))
    ].astype(np.int32)
    topics = np.zeros((PUB_ROUNDS, PUBS_PER_ROUND), np.int32)
    return topo, subs, cfg, sp, adversary, sched, topics, 1


def _eth2_setup():
    n_topics = 8
    topo = graph.random_connect(N, d=DEG, seed=9)
    subs = graph.subscribe_random(N, n_topics=n_topics, topics_per_peer=2,
                                  seed=3)
    rng = np.random.default_rng(4)
    tp = TopicScoreParams(
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
    )
    sp = PeerScoreParams(
        topics={t: tp for t in range(n_topics)},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True
    )
    sched = rng.integers(0, N, size=(PUB_ROUNDS, PUBS_PER_ROUND)).astype(np.int32)
    topics = rng.integers(0, n_topics, size=(PUB_ROUNDS, PUBS_PER_ROUND)).astype(np.int32)
    return topo, subs, cfg, sp, None, sched, topics, n_topics


def _run_engine(topo, subs, cfg, sp, adversary, sched, topics):
    import jax.numpy as jnp

    net = Net.build(topo, subs)
    st = GossipSubState.init(net, MSG_SLOTS, cfg, score_params=sp, seed=3)
    step = make_gossipsub_step(
        cfg, net, score_params=sp, adversary_no_forward=adversary,
    )
    empty = no_publish(PUBS_PER_ROUND)
    for _ in range(WARMUP):
        st = step(st, *empty)
    pv = jnp.ones((PUBS_PER_ROUND,), bool)
    for r in range(sched.shape[0]):
        st = step(st, jnp.asarray(sched[r]), jnp.asarray(topics[r]), pv)
    for _ in range(DRAIN):
        st = step(st, *empty)
    h = np.asarray(hops(st.core.msgs, st.core.dlv))  # [N, M]
    sub = np.asarray(net.subscribed)                  # [N, T]
    mt = np.asarray(st.core.msgs.topic)
    # count only receipts at subscribed peers (the CDF denominator)
    mask = (h >= 0) & sub[:, np.clip(mt, 0, None)]
    return [int(x) for x in h[mask]], subs


def _run_oracle(topo, subs, cfg, sp, adversary, sched, topics):
    adv = set(np.flatnonzero(adversary).tolist()) if adversary is not None else None
    o = OracleGossipSub(
        topo, subs, cfg, msg_slots=MSG_SLOTS, seed=11,
        score_params=sp, adversary=adv,
    )
    for _ in range(WARMUP):
        o.step()
    for r in range(sched.shape[0]):
        o.step([(int(p), int(t), True)
                for p, t in zip(sched[r], topics[r])])
    for _ in range(DRAIN):
        o.step()
    sub = np.asarray(subs.subscribed)
    # subscribed receivers only — an unsubscribed fanout origin's own
    # hop-0 receipt is outside the CDF population (same filter as the
    # engine side)
    return [
        h for (i, slot), h in o.hops().items()
        if sub[i, o.msgs[slot].topic]
    ]


def _denominator(subs, topics, n_msgs_per_topic):
    """Total (subscribed peer, message) pairs over the schedule."""
    sub = np.asarray(subs.subscribed)
    total = 0
    for t, cnt in n_msgs_per_topic.items():
        total += cnt * int(sub[:, t].sum())
    return total


def _cdf(hop_counts, total):
    hist = np.zeros(MAX_H + 1)
    for h in hop_counts:
        hist[min(h, MAX_H)] += 1
    return np.cumsum(hist) / total


@pytest.mark.parametrize("setup,name", [
    (_sybil_setup, "sybil"),
    (_eth2_setup, "eth2"),
])
def test_v11_composed_cdf_within_2pct(setup, name):
    topo, subs, cfg, sp, adversary, sched, topics, n_topics = setup()

    hv, _ = _run_engine(topo, subs, cfg, sp, adversary, sched, topics)
    ho = _run_oracle(topo, subs, cfg, sp, adversary, sched, topics)

    per_topic = {}
    for t in topics.ravel():
        per_topic[int(t)] = per_topic.get(int(t), 0) + 1
    total = _denominator(subs, topics, per_topic)

    cv = _cdf(hv, total)
    co = _cdf(ho, total)
    sup = float(np.max(np.abs(cv - co)))
    assert sup <= 0.02, (
        f"[{name}] composed v1.1 CDF sup-distance {sup:.4f} > 2%\n"
        f"vec={np.round(cv, 4)}\noracle={np.round(co, 4)}"
    )
    # both sides reach (nearly) every subscribed honest pair
    assert cv[-1] > 0.9 and co[-1] > 0.9
    # and the distance is recorded for PARITY.md
    print(f"PARITY[{name}]: sup={sup:.4f} cov_v={cv[-1]:.4f} cov_o={co[-1]:.4f}")


def test_v11_scoring_catches_sybils_both_sides():
    """The composed machines agree qualitatively: sybil neighbors end with
    lower mean score than honest ones on both implementations. P1
    (time-in-mesh) is zeroed so the delivery-driven terms (P2 credit, P3
    deficit) provide the separation — the signal this config exists to
    test."""
    topo, subs, cfg, sp, adversary, sched, topics, _ = _sybil_setup()
    tp0 = dataclasses.replace(
        sp.topics[0],
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=1.0,
    )
    sp = dataclasses.replace(sp, topics={0: tp0})
    import jax.numpy as jnp

    net = Net.build(topo, subs)
    st = GossipSubState.init(net, MSG_SLOTS, cfg, score_params=sp, seed=3)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               adversary_no_forward=adversary)
    pv = jnp.ones((PUBS_PER_ROUND,), bool)
    for _ in range(WARMUP):
        st = step(st, *no_publish(PUBS_PER_ROUND))
    for r in range(sched.shape[0]):
        st = step(st, jnp.asarray(sched[r]), jnp.asarray(topics[r]), pv)
    for _ in range(16):
        st = step(st, *no_publish(PUBS_PER_ROUND))

    scores = np.asarray(st.scores)          # [N,K]
    nbr = np.asarray(net.nbr)
    ok = np.asarray(net.nbr_ok)
    mesh = np.asarray(st.mesh)[:, 0, :].astype(bool)
    hon_rows = ~adversary
    adv_nbr = adversary[np.clip(nbr, 0, None)] & ok
    # the deficit machinery largely expels sybils from honest meshes
    # (they started at ~20% of edges)
    syb_frac_v = (mesh & adv_nbr)[hon_rows].sum() / max(mesh[hon_rows].sum(), 1)
    assert syb_frac_v < 0.10
    # and across all edges, sybil neighbors score below honest ones
    assert scores[adv_nbr].mean() < scores[~adv_nbr & ok].mean()

    o = OracleGossipSub(
        topo, subs, cfg, msg_slots=MSG_SLOTS, seed=11, score_params=sp,
        adversary=set(np.flatnonzero(adversary).tolist()),
    )
    for _ in range(WARMUP):
        o.step()
    for r in range(sched.shape[0]):
        o.step([(int(p), int(t), True) for p, t in zip(sched[r], topics[r])])
    for _ in range(16):
        o.step()
    adv_s, hon_s = [], []
    syb_mesh = tot_mesh = 0
    for i in range(N):
        if adversary[i]:
            continue
        m = o.mesh[i].get(0, set())
        for k, s, r in o._edges(i):
            if k in m:
                tot_mesh += 1
                syb_mesh += s in o.adversary
            (adv_s if s in o.adversary else hon_s).append(o._score(i, k))
    assert syb_mesh / max(tot_mesh, 1) < 0.10
    assert np.mean(adv_s) < np.mean(hon_s)
