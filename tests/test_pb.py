"""Wire-schema tests: RPC round-trips and old/new compat (the reference's
compat_test.go:10-83 scenarios on our generated bindings)."""

from go_libp2p_pubsub_tpu.pb import compat_pb2, rpc_pb2, trace_pb2


def test_rpc_roundtrip_full():
    rpc = rpc_pb2.RPC()
    rpc.subscriptions.add(subscribe=True, topicid="news")
    rpc.subscriptions.add(subscribe=False, topicid="olds")
    m = rpc.publish.add()
    setattr(m, "from", b"\x01peerA")  # `from` is a Python keyword
    m.data = b"payload"
    m.seqno = (7).to_bytes(8, "big")
    m.topic = "news"
    m.signature = b"sig"
    m.key = b"key"
    rpc.control.ihave.add(topicID="news", messageIDs=["m1", "m2"])
    rpc.control.iwant.add(messageIDs=["m1"])
    rpc.control.graft.add(topicID="news")
    pr = rpc.control.prune.add(topicID="news", backoff=60)
    pr.peers.add(peerID=b"\x01peerB", signedPeerRecord=b"rec")

    out = rpc_pb2.RPC()
    out.ParseFromString(rpc.SerializeToString())
    assert out == rpc
    assert out.publish[0].topic == "news"
    assert out.control.prune[0].backoff == 60


def test_compat_new_to_old():
    # a single-topic new-form message parses as old-form with one topicID
    m = rpc_pb2.Message(data=b"d", seqno=b"\0" * 8, topic="t")
    setattr(m, "from", b"p")
    old = compat_pb2.Message()
    old.ParseFromString(m.SerializeToString())
    assert list(old.topicIDs) == ["t"]
    assert old.data == b"d"


def test_compat_old_to_new():
    # old-form single topic parses as the new single `topic` field;
    # multi-topic old messages surface as the *last* topic (proto2
    # last-wins for repeated->optional), which is the documented reference
    # behavior for deprecated multi-topic messages
    old = compat_pb2.Message(data=b"d", topicIDs=["a"])
    m = rpc_pb2.Message()
    m.ParseFromString(old.SerializeToString())
    assert m.topic == "a"

    old2 = compat_pb2.Message(data=b"d", topicIDs=["a", "b"])
    m2 = rpc_pb2.Message()
    m2.ParseFromString(old2.SerializeToString())
    assert m2.topic == "b"


def test_trace_event_schema():
    ev = trace_pb2.TraceEvent(
        type=trace_pb2.TraceEvent.GRAFT,
        peerID=b"p0",
        timestamp=123,
    )
    ev.graft.peerID = b"p1"
    ev.graft.topic = "t"
    out = trace_pb2.TraceEvent()
    out.ParseFromString(ev.SerializeToString())
    assert out.type == trace_pb2.TraceEvent.GRAFT
    assert out.graft.topic == "t"
    # all 13 event types exist with the reference's numbering
    assert trace_pb2.TraceEvent.PUBLISH_MESSAGE == 0
    assert trace_pb2.TraceEvent.PRUNE == 12


def test_trace_batch():
    b = trace_pb2.TraceEventBatch()
    for i in range(3):
        b.batch.add(timestamp=i)
    out = trace_pb2.TraceEventBatch()
    out.ParseFromString(b.SerializeToString())
    assert len(out.batch) == 3
