"""Wire-schema tests: RPC round-trips and old/new compat (the reference's
compat_test.go:10-83 scenarios on our generated bindings)."""

from go_libp2p_pubsub_tpu.pb import compat_pb2, rpc_pb2, trace_pb2


def test_rpc_roundtrip_full():
    rpc = rpc_pb2.RPC()
    rpc.subscriptions.add(subscribe=True, topicid="news")
    rpc.subscriptions.add(subscribe=False, topicid="olds")
    m = rpc.publish.add()
    setattr(m, "from", b"\x01peerA")  # `from` is a Python keyword
    m.data = b"payload"
    m.seqno = (7).to_bytes(8, "big")
    m.topic = "news"
    m.signature = b"sig"
    m.key = b"key"
    rpc.control.ihave.add(topicID="news", messageIDs=["m1", "m2"])
    rpc.control.iwant.add(messageIDs=["m1"])
    rpc.control.graft.add(topicID="news")
    pr = rpc.control.prune.add(topicID="news", backoff=60)
    pr.peers.add(peerID=b"\x01peerB", signedPeerRecord=b"rec")

    out = rpc_pb2.RPC()
    out.ParseFromString(rpc.SerializeToString())
    assert out == rpc
    assert out.publish[0].topic == "news"
    assert out.control.prune[0].backoff == 60


def test_compat_new_to_old():
    # a single-topic new-form message parses as old-form with one topicID
    m = rpc_pb2.Message(data=b"d", seqno=b"\0" * 8, topic="t")
    setattr(m, "from", b"p")
    old = compat_pb2.Message()
    old.ParseFromString(m.SerializeToString())
    assert list(old.topicIDs) == ["t"]
    assert old.data == b"d"


def test_compat_old_to_new():
    # old-form single topic parses as the new single `topic` field;
    # multi-topic old messages surface as the *last* topic (proto2
    # last-wins for repeated->optional), which is the documented reference
    # behavior for deprecated multi-topic messages
    old = compat_pb2.Message(data=b"d", topicIDs=["a"])
    m = rpc_pb2.Message()
    m.ParseFromString(old.SerializeToString())
    assert m.topic == "a"

    old2 = compat_pb2.Message(data=b"d", topicIDs=["a", "b"])
    m2 = rpc_pb2.Message()
    m2.ParseFromString(old2.SerializeToString())
    assert m2.topic == "b"


def test_trace_event_schema():
    ev = trace_pb2.TraceEvent(
        type=trace_pb2.TraceEvent.GRAFT,
        peerID=b"p0",
        timestamp=123,
    )
    ev.graft.peerID = b"p1"
    ev.graft.topic = "t"
    out = trace_pb2.TraceEvent()
    out.ParseFromString(ev.SerializeToString())
    assert out.type == trace_pb2.TraceEvent.GRAFT
    assert out.graft.topic == "t"
    # all 13 event types exist with the reference's numbering
    assert trace_pb2.TraceEvent.PUBLISH_MESSAGE == 0
    assert trace_pb2.TraceEvent.PRUNE == 12


def test_trace_batch():
    b = trace_pb2.TraceEventBatch()
    for i in range(3):
        b.batch.add(timestamp=i)
    out = trace_pb2.TraceEventBatch()
    out.ParseFromString(b.SerializeToString())
    assert len(out.batch) == 3


# ---------------------------------------------------------------------------
# RPC fragmentation
# (fragmentRPC, gossipsub.go:1162-1251)


def _mk_rpc(n_msgs=0, msg_size=0, n_ids=0, subs=("a",), grafts=(), id_size=20):
    rpc = rpc_pb2.RPC()
    for t in subs:
        rpc.subscriptions.add(subscribe=True, topicid=t)
    for i in range(n_msgs):
        m = rpc.publish.add()
        m.data = bytes(msg_size)
        m.seqno = i.to_bytes(8, "big")
        m.topic = "a"
    for t in grafts:
        rpc.control.graft.add(topicID=t)
    if n_ids:
        ih = rpc.control.ihave.add()
        ih.topicID = "a"
        ih.messageIDs.extend("m%0*d" % (id_size - 1, i) for i in range(n_ids))
    return rpc


def test_fragment_noop_under_limit():
    from go_libp2p_pubsub_tpu.wire.fragment import fragment_rpc

    rpc = _mk_rpc(n_msgs=3, msg_size=100)
    frags, dropped = fragment_rpc(rpc, limit=1 << 20)
    assert frags == [rpc] and dropped == []


def test_fragment_splits_messages_and_preserves_content():
    from go_libp2p_pubsub_tpu.wire.fragment import fragment_rpc

    rpc = _mk_rpc(n_msgs=40, msg_size=4000)
    limit = 20_000
    frags, dropped = fragment_rpc(rpc, limit=limit)
    assert not dropped and len(frags) > 1
    assert all(f.ByteSize() <= limit for f in frags)
    got = [m.seqno for f in frags for m in f.publish]
    assert got == [m.seqno for m in rpc.publish]
    # subscriptions only in the first fragment
    assert len(frags[0].subscriptions) == 1
    assert all(not f.subscriptions for f in frags[1:])


def test_fragment_drops_single_oversize_message():
    from go_libp2p_pubsub_tpu.wire.fragment import fragment_rpc

    rpc = _mk_rpc(n_msgs=2, msg_size=50_000)
    frags, dropped = fragment_rpc(rpc, limit=10_000)
    assert len(dropped) == 2
    assert all(f.ByteSize() <= 10_000 for f in frags)


def test_fragment_splits_ihave_id_lists():
    from go_libp2p_pubsub_tpu.wire.fragment import fragment_rpc

    rpc = _mk_rpc(n_ids=5000, grafts=("a", "b"))
    limit = 30_000
    frags, dropped = fragment_rpc(rpc, limit=limit)
    assert not dropped and len(frags) > 1
    assert all(f.ByteSize() <= limit for f in frags)
    ids = [m for f in frags for ih in f.control.ihave for m in ih.messageIDs]
    assert ids == list(rpc.control.ihave[0].messageIDs)
    assert all(ih.topicID == "a" for f in frags for ih in f.control.ihave)
    n_grafts = sum(len(f.control.graft) for f in frags)
    assert n_grafts == 2


def test_fragment_mixed_publish_then_control_respects_limit():
    # regression: first id of a control entry appended without a room check
    from go_libp2p_pubsub_tpu.wire.fragment import fragment_rpc

    rpc = _mk_rpc(n_msgs=7, msg_size=1400)  # lands near the limit boundary
    iw = rpc.control.iwant.add()
    iw.messageIDs.extend(["x" * 500, "y" * 500])
    limit = 10_000
    frags, dropped = fragment_rpc(rpc, limit=limit)
    assert not dropped
    assert all(f.ByteSize() <= limit for f in frags), [f.ByteSize() for f in frags]
    ids = [m for f in frags for w in f.control.iwant for m in w.messageIDs]
    assert ids == list(iw.messageIDs)


def test_write_rpc_fragments_on_stream():
    import io

    from go_libp2p_pubsub_tpu.pb import rpc_pb2
    from go_libp2p_pubsub_tpu.wire import framing

    rpc = _mk_rpc(n_ids=3000)
    buf = io.BytesIO()
    n, dropped = framing.write_rpc(buf, rpc, limit=20_000)
    assert not dropped and n == len(buf.getvalue())
    buf.seek(0)
    got = list(framing.read_delimited_messages(buf, rpc_pb2.RPC))
    assert len(got) > 1
    ids = [m for f in got for ih in f.control.ihave for m in ih.messageIDs]
    assert ids == list(rpc.control.ihave[0].messageIDs)
