"""Run-window compiler tests (driver.make_window, docs/DESIGN.md §14).

The round-14 bit-exactness gates: a whole run window compiled as ONE
scan program must reproduce the per-dispatch Python loop EXACTLY —

  * **scanned vs loop parity** on full state trees for all four
    engines (per-round gossipsub under chaos, phase r ∈ {1, 8} on the
    stacked coalesced wire, floodsub, randomsub), telemetry panels
    included bit-for-bit;
  * **identical invariant verdicts** — the folded checker
    (oracle.ScanInvariants inside the scan body) produces the same
    violation masks and tick labels as the per-dispatch InvariantHook,
    on clean runs AND on a seeded violation;
  * **make_scan adapter parity** — the rounds-4..13 driver API, now a
    thin wrapper over the window body, still matches the hand loop for
    plain, static-heartbeat and phase cadences;
  * **segment/checkpoint semantics** — a window split into checkpoint
    segments, saved and restored mid-run, finishes bit-identical to
    the uninterrupted single-dispatch window;
  * **2-D (sims × peers) sharding** — an S=8 ensemble window placed on
    a make_mesh_2d mesh is bit-exact vs unplaced (the 8-virtual-device
    conftest harness);
  * **execution fingerprint + projection dispatch term** — the
    schema-v3 ``execution`` block round-trips (legacy lines read back
    SCAN_OFF) and projection's ``dispatch_overhead_ms`` term defaults
    to zero (the committed round-5 projection reproduces unchanged).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, ensemble, graph
from go_libp2p_pubsub_tpu.chaos import (
    ChaosConfig,
    halves,
    make_cross_mesh_observer,
    two_group_partition,
)
from go_libp2p_pubsub_tpu.chaos import metrics as cmetrics
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.driver import make_scan, make_window, min_cycle
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
    make_gossipsub_phase_step,
)
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.oracle import invariants as oinv
from go_libp2p_pubsub_tpu.state import Net, SimState

N = 48
M = 64
ROUNDS = 8


def _keyless(tree):
    def unkey(x):
        if checkpoint.is_prng_key(x):
            return jax.random.key_data(x)
        return x

    return jax.tree_util.tree_map(unkey, tree)


def assert_trees_bitexact(got, want, context=""):
    flat_g, _ = jax.tree_util.tree_flatten_with_path(_keyless(got))
    flat_w, _ = jax.tree_util.tree_flatten_with_path(_keyless(want))
    assert len(flat_g) == len(flat_w)
    for (path, a), (_, b) in zip(flat_g, flat_w):
        assert a.dtype == b.dtype and a.shape == b.shape, (
            f"{context}{jax.tree_util.keystr(path)}: aval mismatch"
        )
        assert bool(jnp.array_equal(a, b)), (
            f"{context}{jax.tree_util.keystr(path)}: values differ"
        )


def _net(n=N, seed=0):
    topo = graph.random_connect(n, d=4, seed=seed)
    return Net.build(topo, graph.subscribe_all(n, 1))


def _schedule(n, rounds, seed=0, width=4):
    rng = np.random.default_rng(seed)
    po = rng.integers(0, n, size=(rounds, width)).astype(np.int32)
    po[rounds // 2:] = -1
    pt = np.zeros((rounds, width), np.int32)
    pv = np.ones((rounds, width), bool)
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def _score_params():
    return PeerScoreParams(topics={0: TopicScoreParams()},
                           skip_app_specific=True)


def _gossip_cfg(chaos=None, heartbeat_every=1):
    return GossipSubConfig.build(
        GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1),
        PeerScoreThresholds(), score_enabled=True, chaos=chaos,
        heartbeat_every=heartbeat_every,
    )


# ---------------------------------------------------------------------------
# scanned-window vs Python-loop parity, all four engines


def test_window_vs_loop_parity_floodsub():
    net = _net()
    po, pt, pv = _schedule(N, ROUNDS)
    cc = ChaosConfig(loss_rate=0.3)

    def init():
        return SimState.init(N, M, seed=2, k=net.max_degree)

    ref = init()
    for i in range(ROUNDS):
        ref = floodsub_step(net, ref, po[i], pt[i], pv[i], chaos=cc)

    def step(s, a, b, c):
        return floodsub_step(net, s, a, b, c, chaos=cc)

    win = make_window(step)
    got, ys = win(init(), (po, pt, pv))
    assert ys == {}
    assert_trees_bitexact(got, ref, "floodsub window ")


def test_window_vs_loop_parity_randomsub():
    net = _net(seed=3)
    po, pt, pv = _schedule(N, ROUNDS, seed=3)
    step = make_randomsub_step(net)

    def init():
        return SimState.init(N, M, seed=4, k=net.max_degree)

    ref = init()
    for i in range(ROUNDS):
        ref = step(ref, po[i], pt[i], pv[i])
    got, _ = make_window(step)(init(), (po, pt, pv))
    assert_trees_bitexact(got, ref, "randomsub window ")


def test_window_vs_loop_parity_gossipsub_chaos():
    net = _net(seed=5)
    po, pt, pv = _schedule(N, ROUNDS, seed=5)
    sp = _score_params()
    cfg = _gossip_cfg(chaos=ChaosConfig(generator="ge", ge_p_down=0.2,
                                        ge_p_up=0.4))

    def init():
        return GossipSubState.init(net, M, cfg, score_params=sp, seed=6)

    step = make_gossipsub_step(cfg, net, score_params=sp)
    ref = init()
    for i in range(ROUNDS):
        ref = step(ref, po[i], pt[i], pv[i])
    got, _ = make_window(step)(init(), (po, pt, pv))
    assert_trees_bitexact(got, ref, "gossipsub window ")


@pytest.mark.parametrize(
    "r", [1, pytest.param(8, marks=pytest.mark.slow)])
def test_window_vs_loop_parity_phase(r):
    net = _net(seed=7)
    n_phases = 2
    po, pt, pv = _schedule(N, n_phases * r, seed=7)
    po3, pt3, pv3 = (a.reshape(n_phases, r, -1) for a in (po, pt, pv))
    sp = _score_params()
    cfg = _gossip_cfg(heartbeat_every=max(r, 1))
    assert cfg.wire_coalesced

    def init():
        return GossipSubState.init(net, M, cfg, score_params=sp, seed=8)

    step = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
    ref = init()
    for p in range(n_phases):
        ref = step(ref, po3[p], pt3[p], pv3[p], do_heartbeat=True)
    got, _ = make_window(step, heartbeat=[True])(init(), (po3, pt3, pv3))
    assert_trees_bitexact(got, ref, f"phase r={r} window ")


def test_make_scan_adapter_parity_static_heartbeat():
    # the rounds-4..13 make_scan API — now window-backed — must still
    # match a hand loop at every cadence; the static-heartbeat per-round
    # build is the one measure_rate drives for BENCH continuity runs
    net = _net(seed=9)
    he, rounds = 2, ROUNDS
    po, pt, pv = _schedule(N, rounds, seed=9)
    sp = _score_params()
    cfg = _gossip_cfg(heartbeat_every=he)

    def init():
        return GossipSubState.init(net, M, cfg, score_params=sp, seed=10)

    step = make_gossipsub_step(cfg, net, score_params=sp,
                               static_heartbeat=True)
    ref = init()
    for i in range(rounds):
        ref = step(ref, po[i], pt[i], pv[i], do_heartbeat=(i % he == 0))
    scan = make_scan(step, heartbeat_every=he, static_heartbeat=True)
    got = scan(init(), po, pt, pv)
    assert_trees_bitexact(got, ref, "make_scan static-heartbeat ")


def test_min_cycle():
    assert min_cycle([True, False, True, False]) == [True, False]
    assert min_cycle([True]) == [True]
    assert min_cycle([True, True, False]) == [True, True, False]


# ---------------------------------------------------------------------------
# folded invariants: identical verdicts vs the per-dispatch hook


def _flap_cell(seed=11, s=2, rounds=ROUNDS):
    net = _net(seed=seed)
    po, pt, pv = _schedule(N, rounds, seed=seed)
    sp = _score_params()
    cfg = _gossip_cfg(chaos=ChaosConfig(loss_rate=0.4))
    st0 = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed + 1)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    ens = ensemble.lift_step(step)

    def margs(i):
        return (ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
                ensemble.tile(pv[i], s))

    return net, cfg, st0, ens, margs


def test_window_invariant_masks_match_hook():
    s, rounds = 2, ROUNDS
    net, cfg, st0, ens, margs = _flap_cell(s=s, rounds=rounds)
    icfg = oinv.InvariantConfig(check_every=4)

    hook = oinv.InvariantHook("gossipsub", net, cfg, icfg)
    loop = ensemble.run_rounds(ens, ensemble.batch_states(st0, s), margs,
                               rounds, invariants=hook)
    rep_loop = hook.report()

    spec = oinv.ScanInvariants("gossipsub", net, cfg, icfg)
    win = ensemble.run_window(ens, ensemble.batch_states(st0, s), margs,
                              rounds, invariants=spec)
    rep_win = win.invariant_report

    assert rep_win.names == rep_loop.names
    assert rep_win.ticks == rep_loop.ticks
    assert np.array_equal(rep_win.ok, rep_loop.ok)
    assert win.dispatches == 1 and win.compiles == 1
    assert_trees_bitexact(win.states, loop.states, "checked window ")


def test_window_invariant_seeded_violation_matches_hook():
    # corrupt one leaf (a first-receipt stamp on a DEAD message slot —
    # the msgtable-wf property's "stamped ⇒ live" negative shape; the
    # stamp plane is only ever written on first receipt and only
    # cleared on recycle of that slot, which never happens here, so
    # the violation persists across checks) identically for both
    # paths: the folded checker must trip the SAME property at the
    # SAME checks as the hook
    s, rounds = 2, ROUNDS
    net, cfg, st0, ens, margs = _flap_cell(seed=13, s=s, rounds=rounds)

    def corrupt(states):
        dlv = states.core.dlv
        fr = dlv.first_round.at[:, 0, -1].set(0)  # slot M-1: never born
        return states.replace(
            core=states.core.replace(dlv=dlv.replace(first_round=fr)))

    icfg = oinv.InvariantConfig(check_every=4)
    hook = oinv.InvariantHook("gossipsub", net, cfg, icfg)
    ensemble.run_rounds(ens, corrupt(ensemble.batch_states(st0, s)),
                        margs, rounds, invariants=hook)
    rep_loop = hook.report()

    spec = oinv.ScanInvariants("gossipsub", net, cfg, icfg)
    win = ensemble.run_window(ens, corrupt(ensemble.batch_states(st0, s)),
                              margs, rounds, invariants=spec)
    rep_win = win.invariant_report

    assert not rep_loop.all_ok  # the seed actually tripped something
    assert np.array_equal(rep_win.ok, rep_loop.ok)
    assert rep_win.violations() == rep_loop.violations()


# ---------------------------------------------------------------------------
# telemetry rides the carry: panels bit-exact through a window


def test_window_telemetry_panel_bitexact():
    from go_libp2p_pubsub_tpu.telemetry import TelemetryConfig, reconcile

    net = _net(seed=15)
    rounds = ROUNDS
    po, pt, pv = _schedule(N, rounds, seed=15)
    sp = _score_params()
    tcfg = TelemetryConfig(rows=rounds)
    cfg = GossipSubConfig.build(
        GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1),
        PeerScoreThresholds(), score_enabled=True,
    )
    import dataclasses as dc

    cfg = dc.replace(cfg, count_events=True)

    def init():
        return GossipSubState.init(net, M, cfg, score_params=sp, seed=16,
                                   telemetry=tcfg)

    step = make_gossipsub_step(cfg, net, score_params=sp, telemetry=tcfg)
    ref = init()
    for i in range(rounds):
        ref = step(ref, po[i], pt[i], pv[i])
    got, _ = make_window(step)(init(), (po, pt, pv))
    panel = np.asarray(got.core.telem.panel)
    assert np.array_equal(panel, np.asarray(ref.core.telem.panel))
    assert reconcile(panel, np.asarray(got.core.events)) == []
    assert_trees_bitexact(got, ref, "telemetry window ")


# ---------------------------------------------------------------------------
# scheduled deny masks + churn-style extra xs through the window


def test_window_scheduled_deny_xs_parity():
    net = _net(seed=17)
    rounds = ROUNDS
    po, pt, pv = _schedule(N, rounds, seed=17)
    sp = _score_params()
    cfg = _gossip_cfg(chaos=ChaosConfig(scheduled=True))
    scenario = two_group_partition(N, start=2, rounds=4)
    nbr = np.asarray(net.nbr)
    denies = jnp.asarray(np.stack([
        d if (d := scenario.link_deny_at(t, nbr)) is not None
        else np.zeros(nbr.shape, bool)
        for t in range(rounds)]))

    def init():
        return GossipSubState.init(net, M, cfg, score_params=sp, seed=18)

    step = make_gossipsub_step(cfg, net, score_params=sp)
    ref = init()
    for i in range(rounds):
        ref = step(ref, po[i], pt[i], pv[i], denies[i])
    got, _ = make_window(step)(init(), (po, pt, pv, denies))
    assert_trees_bitexact(got, ref, "scheduled-deny window ")


def test_window_observe_matches_host_series():
    net = _net(seed=19)
    rounds = ROUNDS
    po, pt, pv = _schedule(N, rounds, seed=19)
    sp = _score_params()
    cfg = _gossip_cfg()
    groups = halves(N)

    def init():
        return GossipSubState.init(net, M, cfg, score_params=sp, seed=20)

    step = make_gossipsub_step(cfg, net, score_params=sp)
    ref, host_series = init(), []
    for i in range(rounds):
        ref = step(ref, po[i], pt[i], pv[i])
        host_series.append(cmetrics.cross_group_mesh_count(
            np.asarray(ref.mesh), np.asarray(net.nbr),
            np.asarray(net.nbr_ok), groups))
    obs = make_cross_mesh_observer(net.nbr, net.nbr_ok, groups)
    got, ys = make_window(step, observe=obs)(init(), (po, pt, pv))
    assert [int(x) for x in np.asarray(ys["obs"])] == host_series
    assert_trees_bitexact(got, ref, "observed window ")


# ---------------------------------------------------------------------------
# segments = checkpoint quantum: mid-window resume == uninterrupted


def test_window_checkpoint_segment_resume(tmp_path):
    s, rounds, seg = 2, ROUNDS, ROUNDS // 2
    net, cfg, st0, ens, margs = _flap_cell(seed=21, s=s, rounds=rounds)

    gold = ensemble.run_window(ens, ensemble.batch_states(st0, s), margs,
                               rounds)
    assert gold.dispatches == 1

    # segmented: checkpoint at the segment boundary, then RESUME FROM
    # DISK into a fresh runner — must finish bit-identical
    path = str(tmp_path / "mid.npz")
    runner = ensemble.WindowRunner(ens, rounds, segment_len=seg)
    runner.run(ensemble.batch_states(st0, s), margs,
               on_segment=lambda g, states: checkpoint.save(path, states))
    restored = checkpoint.restore(path, ensemble.batch_states(st0, s))
    resumed = ensemble.WindowRunner(ens, seg).run(
        restored, lambda i: margs(i + seg))
    assert_trees_bitexact(resumed.states, gold.states, "resumed window ")


# ---------------------------------------------------------------------------
# 2-D (sims × peers) mesh: bit-exact vs unplaced, S=8 window


@pytest.mark.parametrize("axis", ["sims+peers"])
def test_window_2d_mesh_parity(axis):
    from go_libp2p_pubsub_tpu.parallel import make_mesh_2d

    if jax.device_count() < 8:
        pytest.skip("needs the 8-virtual-device harness")
    s = 8
    net = _net(seed=23)
    po, pt, pv = _schedule(N, ROUNDS, seed=23)
    ens = ensemble.lift_floodsub(net)

    def batched():
        return ensemble.batch_states(
            SimState.init(N, M, seed=24, k=net.max_degree), s)

    def margs(i):
        return (ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
                ensemble.tile(pv[i], s))

    gold = ensemble.run_window(ens, batched(), margs, ROUNDS)
    mesh = make_mesh_2d(2, 4)
    placed = ensemble.shard_ensemble_state(batched(), mesh, N, axis=axis)
    run = ensemble.run_window(ens, placed, margs, ROUNDS)
    assert run.dispatches == 1
    assert_trees_bitexact(run.states, gold.states, "2-D placed window ")


def test_mesh_2d_shape_validation():
    from go_libp2p_pubsub_tpu.parallel import make_mesh_2d

    if jax.device_count() < 8:
        pytest.skip("needs the 8-virtual-device harness")
    mesh = make_mesh_2d(2)
    assert mesh.axis_names == ("sims", "peers")
    assert mesh.devices.shape == (2, 4)
    with pytest.raises(ValueError):
        make_mesh_2d(3)  # 3 does not divide 8


# ---------------------------------------------------------------------------
# execution fingerprint + the projection dispatch term


def test_execution_fingerprint_roundtrip():
    import json

    from go_libp2p_pubsub_tpu.perf.artifacts import (
        SCAN_OFF,
        BenchRecord,
        dump_record,
        execution_fingerprint,
        record_from_line,
    )

    rec = BenchRecord(
        metric="x", value=100.0, unit="ticks/s", vs_baseline=0.01,
        schema=3,
        fingerprint={"execution": execution_fingerprint(
            scan=True, segment_rounds=1600, dispatches_per_window=1,
            rounds_per_dispatch=1600, mesh_shape={"sims": 2, "peers": 4},
            unroll=16, check_every=8)},
    )
    back = record_from_line(json.loads(dump_record(rec)))
    assert back.scanned is True
    assert back.execution["mesh_shape"] == {"sims": 2, "peers": 4}
    assert back.dispatches_per_round == 1 / 1600
    # legacy lines: the explicit SCAN_OFF sentinel, never a KeyError
    legacy = record_from_line({"metric": "y", "value": 1.0})
    assert legacy.execution == SCAN_OFF
    assert legacy.scanned is None
    assert legacy.dispatches_per_round is None


def test_projection_dispatch_term():
    from go_libp2p_pubsub_tpu.perf.projection import project

    base = project(0.4247, 16)
    # default: the term is off — pre-round-14 projections unchanged
    assert base.dispatch_ms_per_round == 0.0
    armed_scan = project(0.4247, 16, dispatch_overhead_ms=1.0,
                         dispatches_per_round=1 / 1600)
    armed_loop = project(0.4247, 16, dispatch_overhead_ms=1.0,
                         dispatches_per_round=1 / 16)
    # per-dispatch execution pays 100x the scanned dispatch cost
    assert armed_loop.dispatch_ms_per_round == pytest.approx(
        100 * armed_scan.dispatch_ms_per_round)
    assert armed_loop.central < armed_scan.central <= base.central
    with pytest.raises(ValueError):
        project(0.4, 16, dispatch_overhead_ms=-1.0)


def test_projection_round5_reproduces_with_dispatch_term_off():
    import os

    from go_libp2p_pubsub_tpu.perf.artifacts import _repo_root
    from go_libp2p_pubsub_tpu.perf.projection import project_from_artifacts

    root = _repo_root()
    bench = os.path.join(root, "BENCH_r05.json")
    multi = os.path.join(root, "MULTICHIP_r05.json")
    if not (os.path.exists(bench) and os.path.exists(multi)):
        pytest.skip("committed round-5 artifacts not present")
    proj = project_from_artifacts(bench, multi)
    assert 0.44 <= proj.central / 10_000.0 <= 0.455
    assert proj.dispatch_ms_per_round == 0.0


# ---------------------------------------------------------------------------
# window validation errors


def test_window_rejects_misaligned_lengths():
    net = _net(seed=25)
    po, pt, pv = _schedule(N, 6, seed=25)
    step = make_randomsub_step(net)
    win = make_window(step, check=lambda s, p, d: jnp.zeros((1,), bool),
                      check_every=4)
    with pytest.raises(ValueError, match="not a multiple"):
        win(SimState.init(N, M, seed=26, k=net.max_degree),
            (po, pt, pv), jnp.zeros((1, 6), jnp.int32))


def test_window_runner_rejects_misaligned_segments():
    net, cfg, st0, ens, margs = _flap_cell(seed=27)
    with pytest.raises(ValueError, match="segment_len"):
        ensemble.WindowRunner(ens, ROUNDS, segment_len=3)
    spec = oinv.ScanInvariants("gossipsub", net, cfg,
                               oinv.InvariantConfig(check_every=3))
    with pytest.raises(ValueError, match="check_every"):
        ensemble.WindowRunner(ens, ROUNDS, invariants=spec,
                              segment_len=4)
