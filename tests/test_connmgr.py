"""Connection-manager / tag-tracer tests (tag_tracer.go semantics:
protection of direct+mesh peers, decaying delivery tags cap 15 / decay
1 per 10 min, trim keeps protected and high-value connections)."""

import numpy as np

from go_libp2p_pubsub_tpu import connmgr, graph
from go_libp2p_pubsub_tpu.state import Net, SimState
from go_libp2p_pubsub_tpu.trace.drain import snapshot


def _net(n=8, d=4, direct_edges=None):
    topo = graph.random_connect(n, d=d, seed=3)
    subs = graph.subscribe_all(n, 1)
    direct = None
    if direct_edges:
        direct = np.zeros(topo.nbr.shape, bool)
        nbr = topo.nbr
        for a, b in direct_edges:
            for k in range(nbr.shape[1]):
                if nbr[a, k] == b and topo.nbr_ok[a, k]:
                    direct[a, k] = True
    return Net.build(topo, subs, direct=direct)


def test_direct_peers_protected():
    net = _net(direct_edges=[(0, int(np.asarray(graph.random_connect(8, 4, seed=3).nbr)[0, 0]))])
    cm = connmgr.ConnManager(net.n_peers, net.n_slots, net.max_degree)
    prot = cm.protected(net, mesh=None)
    assert prot[0].any()
    assert not prot[1:].any()


def test_mesh_peers_protected_and_unprotected_on_prune():
    net = _net()
    cm = connmgr.ConnManager(net.n_peers, net.n_slots, net.max_degree)
    mesh = np.zeros((net.n_peers, net.n_slots, net.max_degree), bool)
    mesh[2, 0, 1] = True  # grafted
    assert cm.protected(net, mesh)[2, 1]
    mesh[2, 0, 1] = False  # pruned
    assert not cm.protected(net, mesh)[2, 1]


def test_delivery_tag_bump_cap_and_decay():
    cm = connmgr.ConnManager(4, 1, 4)
    for _ in range(20):
        cm.bump(0, 0, 2)
    assert cm.tags[0, 0, 2] == connmgr.TAG_CAP  # BumpSumBounded cap 15
    # decay 1 per 600 ticks (10 min at 1s heartbeats)
    cm.maybe_decay(connmgr.TAG_DECAY_INTERVAL_TICKS)
    assert cm.tags[0, 0, 2] == connmgr.TAG_CAP - 1
    cm.maybe_decay(connmgr.TAG_DECAY_INTERVAL_TICKS * 16)
    assert cm.tags[0, 0, 2] == 0  # floors at 0


def test_edge_value_and_trim():
    net = _net(n=8, d=6)
    n, k = net.n_peers, net.max_degree
    cm = connmgr.ConnManager(n, net.n_slots, k)
    nbr_ok = np.asarray(net.nbr_ok)
    live = np.nonzero(nbr_ok[0])[0]
    assert live.size >= 4
    mesh = np.zeros((n, net.n_slots, k), bool)
    mesh[0, 0, live[0]] = True              # mesh peer: protected
    cm.tags[0, 0, live[1]] = 9              # valuable
    cm.tags[0, 0, live[2]] = 1              # cheap
    keep = cm.trim(net, mesh, max_conns=2)
    assert keep[0, live[0]]                 # protected survives
    assert keep[0, live[1]]                 # highest tag fills the budget
    assert not keep[0, live[2]]
    # value ordering: mesh adds 20, direct would add 1000
    val = cm.edge_value(net, mesh)
    assert val[0, live[0]] == connmgr.MESH_PEER_TAG_VALUE
    assert val[0, live[1]] == 9


def test_tag_tracer_bumps_first_delivery_edge():
    """Integration: flood a message through a small floodsub net; every
    first receipt must bump exactly the arrival edge's tag for the topic."""
    from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step

    net = _net(n=10, d=4)
    st = SimState.init(net.n_peers, 32, seed=0, k=net.max_degree)
    tracer = connmgr.TagTracer(net)

    po = np.full(4, -1, np.int32); po[0] = 0
    pt = np.zeros(4, np.int32)
    pv = np.zeros(4, bool); pv[0] = True
    import jax.numpy as jnp
    for r in range(5):
        prev = snapshot(st)
        st = floodsub_step(net, st, jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv))
        tracer.observe(prev, snapshot(st))
        po[:] = -1; pv[:] = False  # publish only in round 0

    fr = np.asarray(st.dlv.first_round)[:, 0]
    fe = np.asarray(st.dlv.first_edge)[:, 0]
    receivers = np.nonzero(fe >= 0)[0]
    assert receivers.size >= 5  # flood reached most of the graph
    for p in receivers:
        assert tracer.cm.tags[p, 0, fe[p]] == connmgr.TAG_BUMP
        # and nothing else bumped for that peer
        assert tracer.cm.tags[p].sum() == connmgr.TAG_BUMP
    # origin never gets a delivery bump (local publish, first_edge=-1)
    assert tracer.cm.tags[0].sum() == 0


def test_network_track_tags_end_to_end():
    """API-level: track_tags=True wires the tracer into run()."""
    from go_libp2p_pubsub_tpu import api

    net = api.Network(router="floodsub", track_tags=True)
    nodes = net.add_nodes(6)
    net.connect_all()
    subs = [nd.join("t").subscribe() for nd in nodes]
    net.start()
    nodes[0].topics["t"].publish(b"tagged")
    net.run(4)
    assert sum(1 for s in subs if s.next() is not None) == 6
    # someone's arrival edge got a bump
    assert net.tag_tracer.cm.tags.sum() >= 5
