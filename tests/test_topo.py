"""Topology + workload plane (round 18, docs/DESIGN.md §18).

Pins the generator contracts:

  * determinism — same seed ⇒ byte-identical canonical edge list, and
    the dense/CSR emissions are built from ONE Topology (identical
    adjacency bytes);
  * capacity bounds — the degree cap holds at EVERY node for every
    generator;
  * geo link classes are sum-preserving (each edge in exactly one
    class) and their per-slot planes cover exactly the present slots;
  * dense-vs-CSR engine parity stays BIT-EXACT on a generated
    power-law graph for all four engines (the ragged long-tail regime
    the sparse plane wins on — r=8 phase slow-marked);
  * workload schedules are deterministic scan xs with the documented
    burst shapes;
  * the row-owner-aligned block padding (ops/csr.pad_csr_blocks) keeps
    the flat involution + engine parity intact (the edge-sharding
    layout, MULTICHIP_r07);
  * the round-18 audit/projection seams: the CSR-resident tier rows in
    MEM_AUDIT.json and the density-priced memory term.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph, topo
from go_libp2p_pubsub_tpu.chaos.faults import ChaosConfig
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerScoreThresholds,
    default_peer_score_params,
)
from go_libp2p_pubsub_tpu.models import floodsub
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
    make_gossipsub_phase_step,
)
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.ops import csr as csrops
from go_libp2p_pubsub_tpu.state import (
    Net,
    SimState,
    densify_edge_planes,
)
from go_libp2p_pubsub_tpu.topo.generators import link_class_planes

N = 128
M = 32
PUBW = 3
CAP = 16

CHAOS = ChaosConfig(generator="iid", loss_rate=0.25)

GENERATORS = [
    ("powerlaw", lambda seed: topo.powerlaw(
        N, exponent=2.2, d_min=2, max_degree=CAP, seed=seed)),
    ("small_world", lambda seed: topo.small_world(
        N, d=4, beta=0.2, seed=seed, max_degree=CAP)),
    ("geo", lambda seed: topo.geo_clusters(
        N, n_clusters=4, d_local=4, d_regional=1, d_global=1, seed=seed)),
]


def _powerlaw_nets(seed=0):
    el = topo.powerlaw(N, exponent=2.2, d_min=2, max_degree=CAP, seed=seed)
    subs = graph.subscribe_all(N, 1)
    return topo.build_nets(el, subs, max_degree=CAP)


def canon(net, st):
    return (densify_edge_planes(net, st)
            if net.edge_layout == "csr" else st)


def assert_trees_equal(a, b, tag=""):
    la = jtu.tree_flatten_with_path(a)[0]
    lb = jtu.tree_flatten_with_path(b)[0]
    assert len(la) == len(lb), f"{tag}: leaf count differs"
    for (p, x), (_, y) in zip(la, lb):
        if hasattr(x, "dtype") and "key" in str(x.dtype):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"{tag}: mismatch at {jtu.keystr(p)}")


def publish_schedule(rounds, seed=0):
    rng = np.random.default_rng(seed)
    po = rng.integers(-1, N, size=(rounds, PUBW)).astype(np.int32)
    pt = np.zeros((rounds, PUBW), np.int32)
    pv = np.ones((rounds, PUBW), bool)
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


# ---------------------------------------------------------------------------
# generator determinism + capacity bounds


@pytest.mark.parametrize("name,gen", GENERATORS, ids=[g[0] for g in GENERATORS])
def test_generator_deterministic_and_capped(name, gen):
    a, b = gen(7), gen(7)
    # same seed ⇒ byte-identical canonical edge list
    assert a.canonical_bytes() == b.canonical_bytes()
    # a different seed moves it (the rng is actually consulted)
    assert a.canonical_bytes() != gen(8).canonical_bytes()
    # degree cap at EVERY node; no self/duplicate edges (canonical form
    # is a sorted set of a<b pairs by construction — verify anyway)
    deg = a.degree
    cap = CAP if name != "geo" else a.max_degree
    assert deg.max() <= cap
    assert (a.edges[:, 0] < a.edges[:, 1]).all()
    assert len({tuple(e) for e in a.edges}) == a.n_undirected
    # the graph is usable: nobody isolated, edges exist
    assert a.n_undirected > 0
    assert deg.min() >= 1


def test_one_edge_list_two_emissions_identical_graph():
    """The A/B construction invariant: both layouts are built from ONE
    Topology whose adjacency is a deterministic function of the
    canonical edge list."""
    el = topo.powerlaw(N, exponent=2.2, d_min=2, max_degree=CAP, seed=3)
    t1, net_d, net_c = topo.build_nets(el, graph.subscribe_all(N, 1),
                                       max_degree=CAP)
    t2 = topo.to_topology(el, max_degree=CAP)
    assert t1.nbr.tobytes() == t2.nbr.tobytes()
    assert t1.rev.tobytes() == t2.rev.tobytes()
    # the two Nets see the same adjacency
    np.testing.assert_array_equal(np.asarray(net_d.nbr),
                                  np.asarray(net_c.nbr))
    assert net_d.edge_layout == "dense" and net_c.edge_layout == "csr"
    assert int(net_c.n_edges) == int(t1.nbr_ok.sum())
    # E is the undirected count doubled (symmetric directed edges)
    assert int(net_c.n_edges) == 2 * el.n_undirected


def test_powerlaw_is_the_sparse_regime():
    """mean degree ≪ K: the density the topo-smoke win lives on."""
    el = topo.powerlaw(2048, exponent=2.2, d_min=2, max_degree=64, seed=0)
    assert el.max_degree <= 64
    assert el.mean_degree < 64 * 0.25  # long tail, not a regular graph
    # a zipf-ish tail: some node is far above the mean
    assert el.degree.max() >= 4 * el.mean_degree


# ---------------------------------------------------------------------------
# geo link classes


def test_geo_link_classes_sum_preserving():
    el = topo.geo_clusters(N, n_clusters=4, d_local=4, d_regional=2,
                           d_global=1, seed=5)
    assert el.link_class is not None
    counts = np.bincount(el.link_class, minlength=3)
    # every edge in EXACTLY one class
    assert counts.sum() == el.n_undirected
    assert (el.link_class >= 0).all() and (el.link_class <= 2).all()
    # all three classes occur at this shape
    assert (counts > 0).all()

    t = topo.to_topology(el)
    cls, lat = link_class_planes(el, t)
    # class plane covers exactly the present slots
    assert ((cls >= 0) == t.nbr_ok).all()
    # symmetric over the involution (an undirected edge has one class)
    j, k = np.nonzero(t.nbr_ok)
    assert (cls[j, k] == cls[t.nbr[j, k], t.rev[j, k]]).all()
    # latency plane maps classes through class_latency, 0 on absent
    for c, rounds in enumerate(el.class_latency):
        assert (lat[cls == c] == rounds).all()
    assert (lat[~t.nbr_ok] == 0).all()
    # directed class counts are the undirected ones doubled
    dir_counts = np.bincount(cls[cls >= 0], minlength=3)
    np.testing.assert_array_equal(dir_counts, counts * 2)


# ---------------------------------------------------------------------------
# workload plane


def test_publish_bursts_patterns_and_determinism():
    for pat in topo.workloads.PATTERNS:
        a = topo.publish_bursts(pat, 32, 8, N, seed=3)
        b = topo.publish_bursts(pat, 32, 8, N, seed=3)
        for x, y in zip(a, b):
            assert x.tobytes() == y.tobytes()
        po, pt, pv = a
        assert po.shape == (32, 8) and pv.all()
        assert ((po >= -1) & (po < N)).all()

    po, _, _ = topo.publish_bursts("attestation_storm", 32, 8, N,
                                   seed=1, period=8, burst_len=2,
                                   base_rate=1)
    width = (po >= 0).sum(axis=1)
    assert (width[(np.arange(32) % 8) < 2] == 8).all()
    assert (width[(np.arange(32) % 8) >= 2] == 1).all()

    po, pt, _ = topo.publish_bursts("flash_crowd", 30, 6, N, seed=1,
                                    onset=10, duration=5, base_rate=2)
    width = (po >= 0).sum(axis=1)
    assert (width[10:15] == 6).all()
    # the crowd lands on the hot topic
    assert (pt[10:15][po[10:15] >= 0] == 0).all()
    assert (width[:10] == 2).all() and (width[15:] == 2).all()

    with pytest.raises(ValueError, match="unknown pattern"):
        topo.publish_bursts("nope", 8, 4, N)


# ---------------------------------------------------------------------------
# dense-vs-CSR parity on the generated power-law graph (all 4 engines)


def test_floodsub_powerlaw_parity():
    _t, net_d, net_c = _powerlaw_nets()
    po, pt, pv = publish_schedule(6)

    def run(net):
        st = SimState.init(N, M, k=net.max_degree, n_edges=net.n_edges)
        for i in range(6):
            st = floodsub.floodsub_step(net, st, po[i], pt[i], pv[i],
                                        chaos=CHAOS)
        return canon(net, st)

    assert_trees_equal(run(net_d), run(net_c), "floodsub/powerlaw")


def test_randomsub_powerlaw_parity():
    _t, net_d, net_c = _powerlaw_nets()
    po, pt, pv = publish_schedule(6)

    def run(net):
        step = make_randomsub_step(net, chaos=CHAOS)
        st = SimState.init(N, M, k=net.max_degree, n_edges=net.n_edges)
        for i in range(6):
            st = step(st, po[i], pt[i], pv[i])
        return canon(net, st)

    assert_trees_equal(run(net_d), run(net_c), "randomsub/powerlaw")


def _gossip_cfg(layout, **kw):
    return GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True,
        chaos=CHAOS, edge_layout=layout, **kw)


def test_gossipsub_powerlaw_parity():
    _t, net_d, net_c = _powerlaw_nets()
    sp = default_peer_score_params(1)
    po, pt, pv = publish_schedule(8)

    def run(net):
        cfg = _gossip_cfg(net.edge_layout)
        st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
        step = make_gossipsub_step(cfg, net, score_params=sp)
        for i in range(8):
            st = step(st, po[i], pt[i], pv[i])
        return canon(net, st)

    assert_trees_equal(run(net_d), run(net_c), "gossipsub/powerlaw")


@pytest.mark.parametrize("r", [4, pytest.param(8, marks=pytest.mark.slow)])
def test_gossipsub_phase_powerlaw_parity(r):
    _t, net_d, net_c = _powerlaw_nets()
    sp = default_peer_score_params(1)
    po, pt, pv = publish_schedule(2 * r)

    def run(net):
        cfg = _gossip_cfg(net.edge_layout, heartbeat_every=r)
        st = GossipSubState.init(net, M, cfg, score_params=sp, seed=0)
        step = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
        for p in range(2):
            st = step(st, po[p * r:(p + 1) * r], pt[:r], pv[:r],
                      do_heartbeat=True)
        return canon(net, st)

    assert_trees_equal(run(net_d), run(net_c), f"phase/powerlaw r={r}")


# ---------------------------------------------------------------------------
# edge-space sharding layout (row-owner-aligned block padding)


def test_block_boundaries_row_aligned():
    el = topo.powerlaw(N, exponent=2.2, d_min=2, max_degree=CAP, seed=1)
    t = topo.to_topology(el, max_degree=CAP)
    ct = csrops.build_csr(t.nbr, t.rev, t.nbr_ok)
    for n_blocks in (2, 4, 8):
        bounds = csrops.block_boundaries(ct.row_ptr, n_blocks)
        assert bounds[0] == 0 and bounds[-1] == ct.n_edges
        assert (np.diff(bounds) >= 0).all()
        # every boundary is a row boundary: whole rows per block
        assert np.isin(bounds, ct.row_ptr).all()


def test_pad_csr_blocks_structure_and_parity():
    el = topo.powerlaw(N, exponent=2.2, d_min=2, max_degree=CAP, seed=1)
    subs = graph.subscribe_all(N, 1)
    _t, net_d, net_p = topo.build_nets(el, subs, max_degree=CAP,
                                       edge_shards=4)
    assert net_p.csr_e_valid is not None
    assert net_p.n_edges % 4 == 0
    ev = np.asarray(net_p.csr_e_valid)
    # padding never owned by e_of_nk; real edges all mapped
    eon = np.asarray(net_p.csr_e_of_nk)
    mapped = eon[eon >= 0]
    assert ev[mapped].all()
    assert mapped.shape[0] == int(ev.sum())
    # flat involution survives the padding
    eperm = np.asarray(net_p.csr_eperm)
    assert (eperm[eperm] == np.arange(net_p.n_edges)).all()
    # row ids stay sorted (segment reductions rely on it)
    assert (np.diff(np.asarray(net_p.csr_row)) >= 0).all()

    # engine parity: padded csr == dense, and padding stays zero
    po, pt, pv = publish_schedule(6)

    def run(net):
        st = SimState.init(N, M, k=net.max_degree, n_edges=net.n_edges)
        for i in range(6):
            st = floodsub.floodsub_step(net, st, po[i], pt[i], pv[i],
                                        chaos=CHAOS)
        return st

    a, b = run(net_d), run(net_p)
    assert_trees_equal(a, canon(net_p, b), "padded-csr floodsub")
    assert (np.asarray(b.dlv.fe_words)[~ev] == 0).all()


def test_edge_sharding_specs():
    """state_shardings recognizes [E]-leading leaves (single-device
    spec check — the placed-window contract lives in mesh2d_dryrun /
    MULTICHIP_r07.json)."""
    from go_libp2p_pubsub_tpu.parallel import make_mesh, state_shardings

    _t, _net_d, net_c = _powerlaw_nets()
    st = SimState.init(N, M, k=net_c.max_degree, n_edges=net_c.n_edges)
    mesh = make_mesh(1)
    sh = state_shardings(st, mesh, N, n_edges=int(net_c.n_edges))
    flat = jtu.tree_flatten_with_path(sh)[0]
    specs = {jtu.keystr(p): s.spec for p, s in flat}
    fe_key = next(k for k in specs if "fe_words" in k)
    have_key = next(k for k in specs if k.endswith("have") or "have" in k)
    assert specs[fe_key] == specs[have_key]
    # replicated leaves stay replicated
    ev_key = next(k for k in specs if "events" in k)
    assert len(specs[ev_key]) == 0


# ---------------------------------------------------------------------------
# round-18 audit + projection seams


def test_mem_audit_csr_tier():
    import json
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(root, "scripts"))
    import memstat

    with open(memstat.AUDIT_PATH) as f:
        audit = json.load(f)
    tier = audit["csr_tier"]["engines"]["gossipsub_csr"]
    # the named planes ride the tier
    leaves = tier["edge_resident_leaves"]
    for sf in (".fe_words", ".served_lo", ".served_hi", ".peerhave",
               ".iasked"):
        assert any(p.endswith(sf) for p in leaves), sf
    # density prices the tier: full density saves nothing, sparse saves
    assert tier["saved_bytes_per_peer_by_density"]["1.0"] == 0.0
    assert tier["saved_bytes_per_peer_by_density"]["0.25"] > 0
    # the helper agrees with the block
    assert memstat.bytes_per_peer_for(
        audit, "gossipsub", "csr", 1.0) == pytest.approx(
            audit["engines"]["gossipsub"]["totals"]["bytes_per_peer"])
    assert memstat.bytes_per_peer_for(
        audit, "gossipsub", "csr", 0.25) < memstat.bytes_per_peer_for(
            audit, "gossipsub", "dense")


def test_project_at_scale_csr_tier():
    import json
    import os

    from go_libp2p_pubsub_tpu.perf.projection import (
        audit_bytes_per_peer,
        project_at_scale,
    )

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "MEM_AUDIT.json")) as f:
        audit = json.load(f)
    dense = project_at_scale(1_000_000, audit=audit)
    sparse = project_at_scale(1_000_000, audit=audit, edge_layout="csr",
                              density=0.25)
    # bytes/peer DROPS at the audit's density on the csr tier
    assert sparse.bytes_per_peer < dense.bytes_per_peer
    assert sparse.hbm_headroom > dense.hbm_headroom
    # full density: the tier saves nothing — identical memory term
    even = project_at_scale(1_000_000, audit=audit, edge_layout="csr",
                            density=1.0)
    assert even.bytes_per_peer == pytest.approx(dense.bytes_per_peer)
    # the helper is the audit's own arithmetic
    assert audit_bytes_per_peer(audit, edge_layout="csr", density=0.25) \
        == pytest.approx(sparse.bytes_per_peer)
