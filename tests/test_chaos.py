"""Chaos plane tests (chaos/faults.py, chaos/scenario.py,
chaos/metrics.py + the engine threading).

The two load-bearing contracts:

  * **elision when off** — a build with ``cfg.chaos=None`` and a build
    with a disabled ``ChaosConfig`` produce BIT-IDENTICAL state trees
    on every router and both phase paths (the chaos plane must cost
    literally nothing when off; `make chaos-smoke` additionally pins
    the compiled HLO kernel census against the committed PERF_SMOKE
    baseline);
  * **reproducible faults** — masks are symmetric per-link functions
    of (sim key, tick), so the same seed + the same Scenario replays
    the identical fault sequence, a checkpoint resumed mid-scenario
    continues it exactly, and the per-round engine and the r=1 phase
    engine flap the same links.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import checkpoint, graph
from go_libp2p_pubsub_tpu.chaos import (
    ChaosConfig,
    ChaosConfigError,
    delivery_stats,
    halves,
    iwant_recovery_share,
    two_group_partition,
)
from go_libp2p_pubsub_tpu.chaos import faults
from go_libp2p_pubsub_tpu.config import GossipSubParams, PeerScoreThresholds
from go_libp2p_pubsub_tpu.models.floodsub import floodsub_step
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import make_gossipsub_phase_step
from go_libp2p_pubsub_tpu.models.randomsub import make_randomsub_step
from go_libp2p_pubsub_tpu.state import Net, SimState
from go_libp2p_pubsub_tpu.trace.events import EV

from test_phase import assert_states_equal, build, run_phase, schedule

IID = ChaosConfig(loss_rate=0.35)
GE = ChaosConfig(generator="ge", ge_p_down=0.15, ge_p_up=0.4)
OFF_CONFIGS = (None, ChaosConfig(), ChaosConfig(generator="ge"))


def _net(n=32, d=6, seed=0, n_topics=1):
    topo = graph.random_connect(n, d=d, seed=seed)
    subs = graph.subscribe_all(n, n_topics)
    return Net.build(topo, subs)


# ---------------------------------------------------------------------------
# config + generators


def test_chaos_config_validation():
    with pytest.raises(ChaosConfigError):
        ChaosConfig(loss_rate=1.5).validate()
    with pytest.raises(ChaosConfigError):
        ChaosConfig(generator="nope").validate()
    with pytest.raises(ChaosConfigError):
        ChaosConfig(generator="ge", ge_p_down=0.2, ge_p_up=0.0).validate()
    assert not ChaosConfig().enabled
    assert not ChaosConfig(generator="ge").enabled  # ge_p_down == 0
    assert ChaosConfig(scheduled=True).enabled
    assert IID.enabled and not IID.needs_state
    assert GE.enabled and GE.needs_state
    # an invalid config that is ENABLED must be rejected at build time
    with pytest.raises(ChaosConfigError):
        GossipSubConfig.build(
            GossipSubParams(), PeerScoreThresholds(),
            chaos=ChaosConfig(loss_rate=2.0),
        )
    # resolve() validates BEFORE the elision decision: a typo'd
    # generator must raise, not silently run a lossless experiment
    with pytest.raises(ChaosConfigError):
        faults.resolve(ChaosConfig(generator="gilbert", loss_rate=0.3))
    assert faults.resolve(ChaosConfig()) is None
    assert faults.resolve(None) is None


def _mask_at(net, seed_key, tick, p=0.3):
    seed = faults.chaos_seed(seed_key)
    return np.asarray(faults.iid_link_down(seed, net.nbr, tick, p))


def test_iid_masks_symmetric_deterministic_and_rated():
    net = _net(n=64, d=6)
    key = jax.random.key(7)
    nbr = np.asarray(net.nbr)
    rev = np.asarray(net.rev)
    ok = np.asarray(net.nbr_ok)
    downs = []
    for tick in range(40):
        m = _mask_at(net, key, tick, p=0.3)
        # symmetry over the edge involution: m[j,k] == m[nbr[j,k], rev[j,k]]
        jj, kk = np.nonzero(ok)
        assert np.array_equal(m[jj, kk], m[nbr[jj, kk], rev[jj, kk]])
        downs.append(m[ok].mean())
        # deterministic: same (key, tick) -> same mask
        np.testing.assert_array_equal(m, _mask_at(net, key, tick, p=0.3))
    rate = float(np.mean(downs))
    assert 0.25 < rate < 0.35, rate  # ~p with hash-quality tolerance
    # a different sim key gives a different stream
    assert not np.array_equal(_mask_at(net, key, 3),
                              _mask_at(net, jax.random.key(8), 3))


def test_ge_chain_symmetric_and_bursty():
    net = _net(n=64, d=6)
    seed = faults.chaos_seed(jax.random.key(3))
    nbr = np.asarray(net.nbr)
    rev = np.asarray(net.rev)
    ok = np.asarray(net.nbr_ok)
    bad = jnp.zeros(nbr.shape, bool)
    seq = []
    for tick in range(60):
        bad = faults.ge_advance(seed, net.nbr, tick, bad,
                                p_down=0.1, p_up=0.3)
        b = np.asarray(bad)
        jj, kk = np.nonzero(ok)
        assert np.array_equal(b[jj, kk], b[nbr[jj, kk], rev[jj, kk]])
        seq.append(b)
    seq = np.stack(seq)  # [T, N, K]
    frac = seq[:, ok].mean()
    # stationary bad fraction ~ p_down / (p_down + p_up) = 0.25
    assert 0.15 < frac < 0.35, frac
    # burstiness: P(bad_t | bad_{t-1}) = 1 - p_up = 0.7 >> marginal
    prev, cur = seq[:-1][:, ok], seq[1:][:, ok]
    stay = cur[prev].mean()
    assert stay > 0.55, stay


# ---------------------------------------------------------------------------
# elision when off: bit-exact state trees on every router


def test_chaos_off_bitexact_per_round_gossipsub():
    po, pt, pv = schedule(8, seed=5, codes=True)
    outs = []
    for chaos in OFF_CONFIGS:
        net, cfg, sp, st = build(seed=5, chaos=chaos)
        step = make_gossipsub_step(cfg, net, score_params=sp)
        for i in range(8):
            st = step(st, po[i], pt[i], pv[i])
        outs.append(st)
    assert_states_equal(outs[0], outs[1], "off-per-round/")
    assert_states_equal(outs[0], outs[2], "off-per-round-ge0/")


@pytest.mark.slow
@pytest.mark.parametrize("r", [1, 8])
def test_chaos_off_bitexact_phase(r):
    rounds = 16
    po, pt, pv = schedule(rounds, seed=6, codes=True)
    outs = []
    for chaos in (None, ChaosConfig()):
        net, cfg, sp, st = build(seed=6, chaos=chaos)
        pstep = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
        st = run_phase(pstep, st, po, pt, pv, r)
        outs.append(st)
    assert_states_equal(outs[0], outs[1], f"off-phase-r{r}/")


@pytest.mark.slow
def test_chaos_off_bitexact_phase_r16():
    po, pt, pv = schedule(32, seed=6, codes=True)
    outs = []
    for chaos in (None, ChaosConfig()):
        net, cfg, sp, st = build(seed=6, chaos=chaos)
        pstep = make_gossipsub_phase_step(cfg, net, 16, score_params=sp)
        st = run_phase(pstep, st, po, pt, pv, 16)
        outs.append(st)
    assert_states_equal(outs[0], outs[1], "off-phase-r16/")


def test_chaos_off_bitexact_floodsub_randomsub():
    net = _net(seed=2)
    po = jnp.asarray(np.array([1, -1, -1, -1], np.int32))
    pt = jnp.zeros((4,), jnp.int32)
    pv = jnp.ones((4,), bool)
    outs = []
    for chaos in (None, ChaosConfig()):
        st = SimState.init(32, 32, seed=2, k=net.max_degree)
        for i in range(6):
            st = floodsub_step(net, st, po if i == 0 else jnp.full((4,), -1, jnp.int32),
                               pt, pv, chaos=chaos)
        outs.append(st)
    assert_states_equal(outs[0], outs[1], "off-flood/")
    outs = []
    for chaos in (None, ChaosConfig()):
        step = make_randomsub_step(net, chaos=chaos)
        st = SimState.init(32, 32, seed=3, k=net.max_degree)
        for i in range(6):
            st = step(st, po if i == 0 else jnp.full((4,), -1, jnp.int32),
                      pt, pv)
        outs.append(st)
    assert_states_equal(outs[0], outs[1], "off-randomsub/")


# ---------------------------------------------------------------------------
# chaos ON: engine agreement + A/B parity


@pytest.mark.slow
@pytest.mark.parametrize("chaos", [IID, GE], ids=["iid", "ge"])
def test_phase_r1_equals_per_round_under_chaos(chaos):
    """The r=1 identity extends to the chaos plane: the phase engine's
    head-masked control + per-sub-round data masks reduce to exactly
    the per-round step's masking (same links flap, same losses)."""
    po, pt, pv = schedule(8, seed=9, codes=True)
    net, cfg, sp, st1 = build(seed=9, chaos=chaos)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    for i in range(8):
        st1 = step(st1, po[i], pt[i], pv[i])
    net, cfg, sp, st2 = build(seed=9, chaos=chaos)
    pstep = make_gossipsub_phase_step(cfg, net, 1, score_params=sp)
    st2 = run_phase(pstep, st2, po, pt, pv, 1)
    assert_states_equal(st1, st2, "chaos-r1/")


@pytest.mark.slow
@pytest.mark.parametrize("chaos", [IID, GE], ids=["iid", "ge"])
def test_phase_stacked_vs_legacy_under_chaos(chaos):
    """The coalesced stacked wire path and the legacy per-plane path
    must flap identically (the chaos mask is one AND on the stacked
    gather vs per-plane ANDs — bit-identical by algebra)."""
    r, rounds = 4, 16
    po, pt, pv = schedule(rounds, seed=11, codes=True)
    outs = []
    for coalesced in (True, False):
        net, cfg, sp, st = build(seed=11, chaos=chaos)
        cfg = dataclasses.replace(cfg, wire_coalesced=coalesced)
        pstep = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
        st = run_phase(pstep, st, po, pt, pv, r)
        outs.append(st)
    assert_states_equal(outs[0], outs[1], "chaos-AB/")


def test_flap_counters_and_recovery():
    """Under i.i.d. loss the LINK_DOWN counter counts undirected flapped
    link-rounds, IWANT_RECOVER attributes lazy-gossip recoveries, and
    the delivery plane still converges (the machinery under test)."""
    n = 48
    net = _net(n=n, d=4, seed=4)
    params = GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1)
    cfg = GossipSubConfig.build(params, PeerScoreThresholds(),
                                chaos=ChaosConfig(loss_rate=0.4))
    st = GossipSubState.init(net, 64, cfg, seed=4)
    step = make_gossipsub_step(cfg, net)
    rng = np.random.default_rng(4)
    for i in range(40):
        po = np.full((4,), -1, np.int32)
        if i < 2:
            po[:] = rng.integers(0, n, size=4)
        st = step(st, jnp.asarray(po), jnp.asarray(np.zeros(4, np.int32)),
                  jnp.asarray(np.ones(4, bool)))
    ev = np.asarray(st.core.events)
    assert ev[EV.LINK_DOWN] > 0
    assert ev[EV.IWANT_RECOVER] > 0
    assert 0.0 < iwant_recovery_share(ev) <= 1.0
    stats = delivery_stats(
        np.asarray(st.core.dlv.first_round), np.asarray(st.core.msgs.birth),
        np.asarray(st.core.msgs.topic), np.asarray(st.core.msgs.origin),
        np.asarray(net.subscribed),
    )
    assert stats.ratio > 0.9, stats


def test_scheduled_partition_blocks_and_heals():
    """A 2-group partition carries nothing across the cut while active;
    after heal, partition-era messages cross (IWANT recovery from
    mcache) — the engine-level version of the chaos-smoke assertion."""
    n, r = 32, 4
    net = _net(n=n, d=6, seed=1)
    groups = np.asarray(halves(n))
    sc = two_group_partition(n, start=0, rounds=8)
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds(),
                                chaos=ChaosConfig(scheduled=True))
    st = GossipSubState.init(net, 64, cfg, seed=1)
    pstep = make_gossipsub_phase_step(cfg, net, r)
    deny = jnp.asarray(sc.link_deny_at(0, np.asarray(net.nbr)))
    zeros = jnp.zeros((n, net.max_degree), bool)
    po0 = jnp.full((r, 4), -1, jnp.int32).at[1, 0].set(2)  # group-0 origin
    pt = jnp.zeros((r, 4), jnp.int32)
    pv = jnp.ones((r, 4), bool)
    none = jnp.full((r, 4), -1, jnp.int32)
    st = pstep(st, po0, pt, pv, deny, do_heartbeat=True)
    st = pstep(st, none, pt, pv, deny, do_heartbeat=True)
    fr = np.asarray(st.core.dlv.first_round)
    slot = 0  # first publish lands on slot 0 (fresh table)
    assert (fr[groups == 1, slot] < 0).all(), "partition leaked"
    for _ in range(8):
        st = pstep(st, none, pt, pv, zeros, do_heartbeat=True)
    fr = np.asarray(st.core.dlv.first_round)
    assert (fr[groups == 1, slot] >= 0).all(), "no recovery after heal"


def test_scenario_compilation_and_hash():
    n = 16
    sc = two_group_partition(n, start=5, rounds=10)
    sc.validate()
    net = _net(n=n, d=3, seed=0)
    nbr = np.asarray(net.nbr)
    assert sc.link_deny_at(4, nbr) is None
    deny = sc.link_deny_at(5, nbr)
    g = np.asarray(halves(n))
    jj, kk = np.nonzero(np.asarray(net.nbr_ok))
    np.testing.assert_array_equal(
        deny[jj, kk], g[jj] != g[nbr[jj, kk]]
    )
    assert sc.link_deny_at(15, nbr) is None  # healed
    assert sc.scenario_hash() == two_group_partition(
        n, start=5, rounds=10).scenario_hash()
    assert sc.scenario_hash() != two_group_partition(
        n, start=5, rounds=11).scenario_hash()
    ev = sc.events()
    assert [e[1] for e in ev] == ["PartitionStart", "PartitionHeal"]
    # crash storms compose through the churn plane's up vector
    from go_libp2p_pubsub_tpu.chaos import CrashStorm, Scenario

    s2 = Scenario(n_peers=n, crashes=(CrashStorm(start=2, rounds=3,
                                                 peers=(1, 4)),))
    s2.validate()
    assert s2.up_at(1)[1]       # up before the window
    assert not s2.up_at(2)[1]   # crashed inside it
    assert s2.up_at(5)[1]       # restarted after
    assert s2.dynamic and not s2.scheduled


# ---------------------------------------------------------------------------
# checkpoint/resume mid-scenario


def _chaos_build(n=32, seed=3):
    net = _net(n=n, d=6, seed=seed)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(),
        chaos=ChaosConfig(generator="ge", ge_p_down=0.2, ge_p_up=0.4,
                          scheduled=True),
    )
    st = GossipSubState.init(net, 64, cfg, seed=seed)
    step = make_gossipsub_step(cfg, net)
    return net, cfg, st, step


def test_checkpoint_mid_scenario_resumes_exact_fault_stream(tmp_path):
    """A checkpoint taken mid-scenario restores and continues to a state
    (and therefore trace) identical to the uninterrupted run — the GE
    chain state rides the pytree and the i.i.d./schedule masks are
    functions of the checkpointed (key, tick)."""
    n = 32
    net, cfg, st, step = _chaos_build(n=n)
    sc = two_group_partition(n, start=4, rounds=12)
    nbr = np.asarray(net.nbr)
    zeros = np.zeros(nbr.shape, bool)

    def drive(st, t0, t1):
        rng = np.random.default_rng(100)  # schedule indexed by tick
        for t in range(t1):
            po = np.full((4,), -1, np.int32)
            po[0] = rng.integers(0, n)
            if t < t0:
                continue  # burn the rng to keep the schedule tick-indexed
            deny = sc.link_deny_at(t, nbr)
            st = step(st, jnp.asarray(po),
                      jnp.asarray(np.zeros(4, np.int32)),
                      jnp.asarray(np.ones(4, bool)),
                      jnp.asarray(zeros if deny is None else deny))
        return st

    mid = drive(st, 0, 8)  # checkpoint INSIDE the partition window
    path = str(tmp_path / "chaos_ckpt.npz")
    checkpoint.save(path, mid)
    _, _, template, _ = _chaos_build(n=n)
    resumed_mid = checkpoint.restore(path, template)
    assert_states_equal(mid, resumed_mid, "ckpt-mid/")

    direct = drive(mid, 8, 20)
    resumed = drive(resumed_mid, 8, 20)
    assert_states_equal(direct, resumed, "ckpt-resume/")


def test_same_seed_same_scenario_identical_trace(tmp_path):
    """Determinism: the same seed + the same Scenario produce the exact
    same serialized trace twice (TraceSession over a chaos run)."""
    from go_libp2p_pubsub_tpu.trace.drain import TraceSession, snapshot
    from go_libp2p_pubsub_tpu.trace.sinks import Tracer

    class ListSink(Tracer):
        def __init__(self):
            super().__init__()
            self.events = []

        def _write(self, evs):
            self.events.extend(e.SerializeToString() for e in evs)

    def run_once():
        n = 24
        net = _net(n=n, d=4, seed=6)
        cfg = GossipSubConfig.build(
            GossipSubParams(), PeerScoreThresholds(),
            chaos=ChaosConfig(loss_rate=0.3, scheduled=True),
        )
        st = GossipSubState.init(net, 64, cfg, seed=6)
        step = make_gossipsub_step(cfg, net)
        sc = two_group_partition(n, start=3, rounds=5)
        nbr = np.asarray(net.nbr)
        zeros = np.zeros(nbr.shape, bool)
        sink = ListSink()
        sess = TraceSession(net, [sink])
        sess.emit_init(snapshot(st))
        for t in range(12):
            po = np.full((4,), -1, np.int32)
            if t < 2:
                po[0] = t
            deny = sc.link_deny_at(t, nbr)
            prev = snapshot(st)
            st = step(st, jnp.asarray(po),
                      jnp.asarray(np.zeros(4, np.int32)),
                      jnp.asarray(np.ones(4, bool)),
                      jnp.asarray(zeros if deny is None else deny))
            sess.observe(prev, snapshot(st), po, np.zeros(4, np.int32),
                         np.ones(4, bool))
        sess.close()
        return sink.events, np.asarray(st.core.events)

    ev_a, cnt_a = run_once()
    ev_b, cnt_b = run_once()
    assert ev_a == ev_b
    np.testing.assert_array_equal(cnt_a, cnt_b)
    assert cnt_a[EV.LINK_DOWN] > 0


# ---------------------------------------------------------------------------
# artifacts: chaos fingerprint + legacy off-defaults


def test_artifact_chaos_fingerprint_roundtrip_and_legacy_defaults():
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        BenchRecord,
        chaos_fingerprint,
        record_from_line,
    )

    sc = two_group_partition(16, start=1, rounds=2)
    fp = chaos_fingerprint(IID, sc)
    assert fp["generator"] == "iid" and fp["loss_rate"] == 0.35
    assert fp["scenario"] == sc.scenario_hash()
    rec = BenchRecord(metric="m", value=1.0, unit="ratio", vs_baseline=0.0,
                      schema=2, fingerprint={"chaos": fp})
    line = rec.to_line()
    back = record_from_line(line)
    assert back.chaos == {**fp}
    assert not back.chaos_off
    # legacy v1/v2 lines (no chaos block) read back as chaos off
    legacy = record_from_line({"metric": "m", "value": 2.0, "unit": "x",
                               "vs_baseline": 0.1})
    assert legacy.chaos["generator"] == "off"
    assert legacy.chaos["scenario"] is None
    assert legacy.chaos_off
    # the sweep fingerprint now carries the explicit off block
    from go_libp2p_pubsub_tpu.perf.sweep import workload_fingerprint

    wf = workload_fingerprint("default", 64, 64, 1, 1)
    assert wf["chaos"]["generator"] == "off"
