"""Multi-round phase engine tests (models/gossipsub_phase.py).

The load-bearing guarantee: a phase step with rounds_per_phase=1 is the
per-round step — bit-exact across every state plane, for every feature
combination. That pins the phase engine's sender-side transmit
composition and accumulated attribution to the per-round semantics the
oracle-parity suite already validates, so r>1 runs differ only by the
designed r-round control latency.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu import graph
from go_libp2p_pubsub_tpu.config import (
    GossipSubParams,
    PeerGaterParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
)
from go_libp2p_pubsub_tpu.driver import heartbeat_schedule, make_scan
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSubConfig,
    GossipSubState,
    make_gossipsub_step,
)
from go_libp2p_pubsub_tpu.models.gossipsub_phase import make_gossipsub_phase_step
from go_libp2p_pubsub_tpu.ops import bitset
from go_libp2p_pubsub_tpu.state import Net

N, D, T, M, P = 48, 8, 3, 64, 4


def score_params(n_topics=T):
    tp = TopicScoreParams(
        mesh_message_deliveries_weight=-0.3,
        mesh_message_deliveries_threshold=3.0,
        mesh_message_deliveries_activation=6.0,
        mesh_message_deliveries_window=2.0,
    )
    return PeerScoreParams(
        topics={t: tp for t in range(n_topics)},
        skip_app_specific=True,
        behaviour_penalty_weight=-1.0,
        behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )


def build(seed=0, he=1, n=N, **cfg_kw):
    topo = graph.random_connect(n, D, seed=seed)
    subs = graph.subscribe_random(n, n_topics=T, topics_per_peer=2, seed=seed)
    net = Net.build(topo, subs)
    sp = score_params()
    params = dataclasses.replace(
        GossipSubParams(), flood_publish=True, do_px=True
    )
    cfg = GossipSubConfig.build(
        params, PeerScoreThresholds(), score_enabled=True,
        heartbeat_every=he, **cfg_kw,
    )
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=seed)
    return net, cfg, sp, st


def schedule(rounds, seed=0, n=N, codes=False):
    """[R,P] publish schedule; with codes=True a few REJECT/IGNORE verdicts."""
    rng = np.random.default_rng(seed)
    po = rng.integers(0, n, size=(rounds, P)).astype(np.int32)
    pt = rng.integers(0, T, size=(rounds, P)).astype(np.int32)
    if codes:
        pv = rng.choice([0, 0, 0, 0, 0, 1, 2], size=(rounds, P)).astype(np.int32)
    else:
        pv = np.ones((rounds, P), bool)
    return jnp.asarray(po), jnp.asarray(pt), jnp.asarray(pv)


def assert_states_equal(a, b, what=""):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, _ = jax.tree_util.tree_flatten(b)
    paths = jax.tree_util.tree_flatten_with_path(a)[0]
    for (path, xa), xb in zip(paths, lb):
        if jnp.issubdtype(getattr(xa, "dtype", None), jax.dtypes.prng_key):
            xa, xb = jax.random.key_data(xa), jax.random.key_data(xb)
        xa, xb = np.asarray(xa), np.asarray(xb)
        name = jax.tree_util.keystr(path)
        if np.issubdtype(xa.dtype, np.floating):
            np.testing.assert_allclose(
                xa, xb, rtol=1e-5, atol=1e-6,
                err_msg=f"{what}{name} differs",
            )
        else:
            assert np.array_equal(xa, xb), f"{what}{name} differs"


def run_per_round(step, st, po, pt, pv, he=1):
    sched = heartbeat_schedule(he, 1)
    for i in range(po.shape[0]):
        if he == 1:
            st = step(st, po[i], pt[i], pv[i])
        else:
            st = step(st, po[i], pt[i], pv[i],
                      do_heartbeat=sched[i % len(sched)])
    return st


def run_phase(pstep, st, po, pt, pv, r, he=1):
    sched = heartbeat_schedule(he, r)
    g = po.shape[0] // r
    gro = lambda a: a[: g * r].reshape((g, r) + a.shape[1:])
    po, pt, pv = gro(po), gro(pt), gro(pv)
    for p in range(g):
        st = pstep(st, po[p], pt[p], pv[p], do_heartbeat=sched[p % len(sched)])
    return st


# ---------------------------------------------------------------------------
# r=1 bit-exactness: the phase engine IS the per-round step


@pytest.mark.parametrize("score_counts", [False, True])
def test_phase_r1_bitexact_rich_v11(score_counts):
    """score + flood_publish + PX + fanout + mixed verdicts, he=1.
    16 rounds x 4 pubs < 64 slots => no recycling => every plane equal
    including score counters — on BOTH score-attribution paths (plane
    default and opt-in counts)."""
    net, cfg, sp, st = build(seed=3)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    pstep = make_gossipsub_phase_step(cfg, net, 1, score_params=sp,
                                      score_counts=score_counts)
    po, pt, pv = schedule(16, seed=3, codes=True)
    sa = run_per_round(step, st, po, pt, pv)
    net, cfg, sp, st2 = build(seed=3)
    sb = run_phase(pstep, st2, po, pt, pv, 1)
    assert_states_equal(sa, sb, "r1/")


@pytest.mark.slow
def test_phase_r1_bitexact_static_heartbeat_he2():
    net, cfg, sp, st = build(seed=5, he=2)
    step = make_gossipsub_step(cfg, net, score_params=sp, static_heartbeat=True)
    pstep = make_gossipsub_phase_step(cfg, net, 1, score_params=sp)
    po, pt, pv = schedule(16, seed=5)
    sa = run_per_round(step, st, po, pt, pv, he=2)
    net, cfg, sp, st2 = build(seed=5, he=2)
    sb = run_phase(pstep, st2, po, pt, pv, 1, he=2)
    assert_states_equal(sa, sb, "r1-he2/")


@pytest.mark.slow
def test_phase_r1_bitexact_gater_throttle_queuecap_adversary():
    gp = PeerGaterParams()
    rng = np.random.default_rng(7)
    adv = rng.random(N) < 0.2
    net, cfg, sp, st = build(
        seed=7, gater_params=gp, validation_capacity=3, queue_cap=3,
    )
    step = make_gossipsub_step(cfg, net, score_params=sp, gater_params=gp,
                               adversary_no_forward=adv)
    pstep = make_gossipsub_phase_step(cfg, net, 1, score_params=sp,
                                      gater_params=gp,
                                      adversary_no_forward=adv)
    po, pt, pv = schedule(14, seed=7, codes=True)
    sa = run_per_round(step, st, po, pt, pv)
    net, cfg, sp, st2 = build(seed=7, gater_params=gp, validation_capacity=3,
                              queue_cap=3)
    sb = run_phase(pstep, st2, po, pt, pv, 1)
    assert_states_equal(sa, sb, "r1-gater/")


def test_phase_r1_bitexact_validation_delay():
    net, cfg, sp, st = build(
        seed=11, validation_delay_rounds=2,
        validation_delay_topic=(1, 2, 1),
    )
    step = make_gossipsub_step(cfg, net, score_params=sp)
    pstep = make_gossipsub_phase_step(cfg, net, 1, score_params=sp)
    po, pt, pv = schedule(14, seed=11, codes=True)
    sa = run_per_round(step, st, po, pt, pv)
    net, cfg, sp, st2 = build(seed=11, validation_delay_rounds=2,
                              validation_delay_topic=(1, 2, 1))
    sb = run_phase(pstep, st2, po, pt, pv, 1)
    assert_states_equal(sa, sb, "r1-valdelay/")


def test_phase_r1_bitexact_dynamic_peers():
    net, cfg, sp, st = build(seed=13)
    step = make_gossipsub_step(cfg, net, score_params=sp, dynamic_peers=True)
    pstep = make_gossipsub_phase_step(cfg, net, 1, score_params=sp,
                                      dynamic_peers=True)
    po, pt, pv = schedule(12, seed=13)
    rng = np.random.default_rng(13)
    ups = rng.random((12, N)) > 0.05  # ~5% churn per round
    sa = st
    for i in range(12):
        sa = step(sa, po[i], pt[i], pv[i], jnp.asarray(ups[i]))
    net, cfg, sp, sb = build(seed=13)
    for i in range(12):
        sb = pstep(sb, po[i : i + 1], pt[i : i + 1], pv[i : i + 1],
                   jnp.asarray(ups[i]), do_heartbeat=True)
    assert_states_equal(sa, sb, "r1-dyn/")


# ---------------------------------------------------------------------------
# r > 1: delivery still completes; control latency is the only difference


@pytest.mark.parametrize("r", [2, 4])
def test_phase_delivers_everywhere(r):
    net, cfg, sp, st = build(seed=17)
    pstep = make_gossipsub_phase_step(cfg, net, r, score_params=sp)
    rounds = 24
    po, pt, pv = schedule(rounds, seed=17)
    # stop publishing after round 8 so the tail drains
    po = po.at[8:].set(-1)
    st = run_phase(pstep, st, po, pt, pv, r)
    subs = np.asarray(net.subscribed)          # [N,T]
    topic = np.asarray(st.core.msgs.topic)     # [M]
    origin = np.asarray(st.core.msgs.origin)
    have = np.asarray(bitset.unpack(st.core.dlv.have, M))  # [N,M]
    fr_ = np.asarray(st.core.dlv.first_round)
    for s in range(M):
        if origin[s] < 0:
            continue
        subscribers = np.flatnonzero(subs[:, topic[s]])
        cov = have[subscribers, s].mean() if len(subscribers) else 1.0
        assert cov > 0.9, f"slot {s}: coverage {cov}"
    # first_round stamps keep 1-round resolution: arrivals exist at
    # non-phase-boundary ticks
    arr = fr_[(fr_ >= 0) & (np.asarray(st.core.msgs.origin)[None, :] >= 0)]
    assert (arr % r != 0).any()


def test_phase_mesh_maintains():
    net, cfg, sp, st = build(seed=19)
    pstep = make_gossipsub_phase_step(cfg, net, 4, score_params=sp)
    po, pt, pv = schedule(32, seed=19)
    st = run_phase(pstep, st, po, pt, pv, 4)
    deg = np.asarray(st.mesh.sum(axis=2))          # [N,S]
    slot_live = np.asarray(net.my_topics) >= 0
    assert (deg[slot_live] >= 1).all()
    assert (deg[slot_live] <= cfg.Dhi).all()


def test_phase_recycling_invariants():
    """Slot recycling inside a phase: accumulators must drop recycled
    columns (no cross-message attribution) and the engine must stay
    consistent. 10 phases x 8 rounds x 4 pubs >> 64 slots."""
    net, cfg, sp, st = build(seed=23)
    pstep = make_gossipsub_phase_step(cfg, net, 8, score_params=sp)
    po, pt, pv = schedule(80, seed=23)
    st = run_phase(pstep, st, po, pt, pv, 8)
    fr_ = np.asarray(st.core.dlv.first_round)
    birth = np.asarray(st.core.msgs.birth)
    have = np.asarray(bitset.unpack(st.core.dlv.have, M))
    # no receipt can predate its message's birth (stale-bit leak check)
    ok = (fr_ < 0) | (fr_ >= birth[None, :]) | ~have
    assert ok.all()
    # scores stay finite
    assert np.isfinite(np.asarray(st.scores)).all()


# ---------------------------------------------------------------------------
# driver schedule + scan


def test_heartbeat_schedule():
    assert heartbeat_schedule(1, 1) == [True]
    assert heartbeat_schedule(4, 1) == [True, False, False, False]
    assert heartbeat_schedule(4, 2) == [True, False]
    assert heartbeat_schedule(2, 4) == [True]
    assert heartbeat_schedule(3, 2) == [True, True, False]


def test_make_scan_matches_manual_phase():
    net, cfg, sp, st = build(seed=29, he=2)
    pstep = make_gossipsub_phase_step(cfg, net, 2, score_params=sp)
    po, pt, pv = schedule(16, seed=29)
    run = make_scan(pstep, heartbeat_every=2, rounds_per_phase=2, donate=False)
    sa = run(st, po, pt, pv)
    net, cfg, sp, st2 = build(seed=29, he=2)
    sb = run_phase(pstep, st2, po, pt, pv, 2, he=2)
    assert_states_equal(sa, sb, "scan/")


def test_make_scan_per_round_static():
    net, cfg, sp, st = build(seed=31, he=2)
    step = make_gossipsub_step(cfg, net, score_params=sp, static_heartbeat=True)
    po, pt, pv = schedule(12, seed=31)
    run = make_scan(step, heartbeat_every=2, rounds_per_phase=1,
                    static_heartbeat=True, donate=False)
    sa = run(st, po, pt, pv)
    net, cfg, sp, st2 = build(seed=31, he=2)
    sb = run_per_round(step, st2, po, pt, pv, he=2)
    assert_states_equal(sa, sb, "scan-r1/")
    with pytest.raises(ValueError):
        make_scan(step, heartbeat_every=2, rounds_per_phase=1)


def test_phase_trace_exact_dup_plane_reconciles():
    """cfg.trace_exact under the phase engine: the phase-end duplicate
    plane's popcount equals the device duplicate-counter delta — including
    with the validation throttle binding (throttled receipts are fresh
    Rejects, never duplicates)."""
    from go_libp2p_pubsub_tpu.ops import bitset as bs
    from go_libp2p_pubsub_tpu.trace.events import EV

    net, cfg, sp, st = build(seed=37, validation_capacity=3)
    cfg = dataclasses.replace(cfg, trace_exact=True, count_events=True)
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=37)
    pstep = make_gossipsub_phase_step(cfg, net, 4, score_params=sp)
    po, pt, pv = schedule(16, seed=37)
    sched = heartbeat_schedule(1, 4)
    g = po.shape[0] // 4
    gro = lambda a: a.reshape((g, 4) + a.shape[1:])
    po, pt, pv = gro(po), gro(pt), gro(pv)
    prev_dup = 0
    for p in range(g):
        st = pstep(st, po[p], pt[p], pv[p], do_heartbeat=sched[p % len(sched)])
        dup_now = int(st.core.events[EV.DUPLICATE_MESSAGE])
        plane = int(np.asarray(bs.popcount(st.dup_trans, axis=None)).sum())
        assert plane == dup_now - prev_dup, (p, plane, dup_now - prev_dup)
        prev_dup = dup_now
    assert prev_dup > 0


@pytest.mark.slow
def test_phase_count_vs_plane_score_paths_equal_no_recycle():
    """r=4, no slot recycling: the count-fold and plane score paths are
    bit-equal (integer popcounts are exact in f32; OR preserves the
    transmission multiset)."""
    net, cfg, sp, st = build(seed=41)
    pa = make_gossipsub_phase_step(cfg, net, 4, score_params=sp,
                                   score_counts=False)
    pb = make_gossipsub_phase_step(cfg, net, 4, score_params=sp,
                                   score_counts=True)
    po, pt, pv = schedule(16, seed=41)
    sa = run_phase(pa, st, po, pt, pv, 4)
    net, cfg, sp, st2 = build(seed=41)
    sb = run_phase(pb, st2, po, pt, pv, 4)
    assert_states_equal(sa, sb, "count-vs-plane/")


@pytest.mark.slow
def test_phase_count_path_retains_recycled_credit():
    """Under within-phase recycling the count path retains the score
    credit the plane path sheds (its stated reason to exist): total P2
    first-delivery credit count >= plane, strictly greater when recycling
    actually bites; delivery planes stay identical (attribution never
    affects propagation)."""
    net, cfg, sp, st = build(seed=43)
    pa = make_gossipsub_phase_step(cfg, net, 8, score_params=sp,
                                   score_counts=False)
    pb = make_gossipsub_phase_step(cfg, net, 8, score_params=sp,
                                   score_counts=True)
    po, pt, pv = schedule(80, seed=43)  # 320 pubs >> 64 slots: recycling
    sa = run_phase(pa, st, po, pt, pv, 8)
    net, cfg, sp, st2 = build(seed=43)
    sb = run_phase(pb, st2, po, pt, pv, 8)
    assert np.array_equal(np.asarray(sa.core.dlv.have),
                          np.asarray(sb.core.dlv.have))
    assert np.array_equal(np.asarray(sa.core.dlv.first_round),
                          np.asarray(sb.core.dlv.first_round))
    fa = float(np.asarray(sa.score.fmd).sum())
    fb = float(np.asarray(sb.score.fmd).sum())
    assert fb >= fa
    assert fb > fa, "expected recycling to bite in this workload"


def test_phase_static_weight_elision_scores_exact():
    """With mesh_message_deliveries_weight=0 everywhere (the honest-net
    bench shape) the phase engine skips the in-window mesh-credit plane:
    every state plane except the untracked mmd counter stays bit-exact vs
    the per-round step at r=1, and the SCORES are identical (the elided
    term multiplies by zero)."""
    tp0 = TopicScoreParams(
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
    )
    sp = PeerScoreParams(
        topics={t: tp0 for t in range(T)}, skip_app_specific=True,
        behaviour_penalty_weight=-1.0, behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    topo = graph.random_connect(N, D, seed=47)
    subs = graph.subscribe_random(N, n_topics=T, topics_per_peer=2, seed=47)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True
    )
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=47)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    pstep = make_gossipsub_phase_step(cfg, net, 1, score_params=sp)
    po, pt, pv = schedule(14, seed=47, codes=True)
    sa = run_per_round(step, st, po, pt, pv)
    sb = run_phase(pstep,
                   GossipSubState.init(net, M, cfg, score_params=sp, seed=47),
                   po, pt, pv, 1)
    # scores identical; everything except the untracked mmd counter exact
    np.testing.assert_allclose(np.asarray(sa.scores), np.asarray(sb.scores),
                               rtol=1e-6)
    assert np.array_equal(np.asarray(sa.core.dlv.have),
                          np.asarray(sb.core.dlv.have))
    assert np.array_equal(np.asarray(sa.core.dlv.first_round),
                          np.asarray(sb.core.dlv.first_round))
    assert np.array_equal(np.asarray(sa.score.imd), np.asarray(sb.score.imd))
    assert np.array_equal(np.asarray(sa.score.fmd), np.asarray(sb.score.fmd))
    # the elided in-window plane leaves mmd tracking first-arrival credit
    # only (on_deliveries adds it regardless); near-first credit is the
    # untracked part — the counter undercounts, the score is untouched
    ma, mb = np.asarray(sa.score.mmd), np.asarray(sb.score.mmd)
    assert (mb <= ma + 1e-6).all()
    assert mb.sum() < ma.sum()


@pytest.mark.slow
def test_phase_no_elision_when_p3b_live():
    """w3=0 but the sticky mesh-failure penalty live (default w3b=-1,
    thr3>0): mmd feeds on_prune's deficit, so the mesh-credit plane must
    NOT be elided — full bit-exactness vs per-round, mmd included (the
    round-4 review's failure scenario)."""
    tp0 = TopicScoreParams(
        mesh_message_deliveries_weight=0.0,
        # mesh_failure_penalty_weight keeps its default (-1): P3b live
        mesh_message_deliveries_threshold=4.0,
        mesh_message_deliveries_activation=6.0,
    )
    sp = PeerScoreParams(
        topics={t: tp0 for t in range(T)}, skip_app_specific=True,
        behaviour_penalty_weight=-1.0, behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    topo = graph.random_connect(N, D, seed=53)
    subs = graph.subscribe_random(N, n_topics=T, topics_per_peer=2, seed=53)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True
    )
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=53)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    pstep = make_gossipsub_phase_step(cfg, net, 1, score_params=sp)
    po, pt, pv = schedule(14, seed=53)
    sa = run_per_round(step, st, po, pt, pv)
    sb = run_phase(pstep,
                   GossipSubState.init(net, M, cfg, score_params=sp, seed=53),
                   po, pt, pv, 1)
    assert_states_equal(sa, sb, "p3b-live/")
    assert float(np.asarray(sb.score.mmd).sum()) > 0.0  # plane tracked


def test_phase_exact_counters_disables_elision():
    """exact_counters=True (the api.Network build flag): even with every
    elidable weight zeroed, ALL counters stay bit-exact vs the per-round
    step — the reference's always-exact inspect surface
    (score.go:120-177). This is the introspection-safety contract:
    peer_score_snapshots consumers never see elided counters."""
    tp0 = TopicScoreParams(
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
    )
    sp = PeerScoreParams(
        topics={t: tp0 for t in range(T)}, skip_app_specific=True,
        behaviour_penalty_weight=-1.0, behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )
    topo = graph.random_connect(N, D, seed=47)
    subs = graph.subscribe_random(N, n_topics=T, topics_per_peer=2, seed=47)
    net = Net.build(topo, subs)
    cfg = GossipSubConfig.build(
        GossipSubParams(), PeerScoreThresholds(), score_enabled=True
    )
    st = GossipSubState.init(net, M, cfg, score_params=sp, seed=47)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    pstep = make_gossipsub_phase_step(cfg, net, 1, score_params=sp,
                                      exact_counters=True)
    po, pt, pv = schedule(14, seed=47, codes=True)
    sa = run_per_round(step, st, po, pt, pv)
    sb = run_phase(pstep,
                   GossipSubState.init(net, M, cfg, score_params=sp, seed=47),
                   po, pt, pv, 1)
    # full bit-exactness INCLUDING the counters elision would corrupt
    assert_states_equal(sa, sb, "exact-counters/")
    # and the elidable planes actually accrued (the test would be vacuous
    # on a workload where no near-first/invalid deliveries happen)
    assert float(np.asarray(sb.score.mmd).sum()) > 0.0
    assert float(np.asarray(sb.score.imd).sum()) > 0.0


def test_phase_api_network_snapshots_exact_counters():
    """api.Network(rounds_per_phase=r) builds with exact_counters: the
    peer_score_snapshots surface shows reference-faithful counters even
    on an all-weights-zero (maximally elidable) config."""
    from go_libp2p_pubsub_tpu.api import Network

    tp0 = TopicScoreParams(
        mesh_message_deliveries_weight=0.0,
        mesh_failure_penalty_weight=0.0,
    )
    sp = PeerScoreParams(
        topics={0: tp0}, skip_app_specific=True,
        behaviour_penalty_weight=-1.0, behaviour_penalty_threshold=1.0,
        behaviour_penalty_decay=0.9,
    )

    def build_net(r):
        netw = Network(score_params=sp, seed=11, rounds_per_phase=r,
                       msg_slots=M)
        nodes = netw.add_nodes(16)
        netw.sparse_connect(d=4, seed=11)
        subs = [n.join("t").subscribe() for n in nodes]
        netw.start()
        return netw, nodes

    na, nodes_a = build_net(1)
    nb, nodes_b = build_net(4)
    for _ in range(3):
        nodes_a[0].topics["t"].publish(b"x")
        nodes_b[0].topics["t"].publish(b"x")
        na.run(4)
        nb.run(4)
    for i in range(16):
        snap_a = nodes_a[i].peer_score_snapshots()
        snap_b = nodes_b[i].peer_score_snapshots()
        assert snap_a.keys() == snap_b.keys()
        for pid, ss_a in snap_a.items():
            ss_b = snap_b[pid]
            for t_name, ts_a in ss_a.topics.items():
                ts_b = ss_b.topics[t_name]
                # the phase build must not elide: fmd/mmd/imd all tracked
                # (values can differ by the designed r-round control
                # latency, but an elided counter would be identically 0
                # network-wide while the r=1 run accrues)
                assert ts_b.mesh_message_deliveries >= 0.0
    # elision would zero mmd network-wide; exact_counters keeps it live.
    # compare network totals within the control-latency tolerance
    mmd_a = float(np.asarray(na.state.score.mmd).sum())
    mmd_b = float(np.asarray(nb.state.score.mmd).sum())
    if mmd_a > 0:
        assert mmd_b > 0, "phase build elided the mmd plane"


def test_admission_invariant_enforced_direct_drivers():
    """The phase engine's publish-capacity invariant (ADVICE round 5,
    item 2), now ENFORCED at the engine layer in two tiers:

    * ``r * pub_width > msg_slots`` — a slot can be re-allocated WITHIN
      one phase, which the deferred recycled-slot clears assume never
      happens: hard PhaseAdmissionError at trace time;
    * ``msg_slots // 2 < r * pub_width <= msg_slots`` — in-flight
      receipts of recycled slots can be wiped before the boundary
      drain observes them: warning.

    API builds (which enforce the flat admission cap on ACTUAL
    publishes) suppress both via admission_capped=True."""
    import warnings

    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        PhaseAdmissionError,
    )

    n = 16
    topo = graph.random_connect(n, 4, seed=3)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    cfg = GossipSubConfig.build(GossipSubParams(), PeerScoreThresholds())
    r = 4
    po = jnp.full((r, P), -1, jnp.int32)
    pt = jnp.zeros((r, P), jnp.int32)
    pv = jnp.zeros((r, P), bool)

    # M=8 < r*P=16: within-phase re-allocation possible — hard error
    st = GossipSubState.init(net, 8, cfg, seed=3)
    pstep = make_gossipsub_phase_step(cfg, net, r)
    with pytest.raises(PhaseAdmissionError, match="re-allocated WITHIN"):
        pstep(st, po, pt, pv, do_heartbeat=True)

    # M=24: cap 12 < 16 <= 24 — the warning band
    stw = GossipSubState.init(net, 24, cfg, seed=3)
    pwarn = make_gossipsub_phase_step(cfg, net, r)
    with pytest.warns(UserWarning, match="phase publish capacity"):
        pwarn(stw, po, pt, pv, do_heartbeat=True)

    # the API-certified build stays silent on the raising shape
    st2 = GossipSubState.init(net, 8, cfg, seed=3)
    pcapped = make_gossipsub_phase_step(cfg, net, r, admission_capped=True)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        pcapped(st2, po, pt, pv, do_heartbeat=True)

    # within-capacity shapes never warn
    st3 = GossipSubState.init(net, 64, cfg, seed=3)  # cap 32 >= 16
    pok = make_gossipsub_phase_step(cfg, net, r)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        pok(st3, po, pt, pv, do_heartbeat=True)
