"""Signing, subscription-filter, and blacklist tests (reference
sign_test.go, subscription_filter_test.go, blacklist.go semantics)."""

import pytest

from go_libp2p_pubsub_tpu import blacklist as bl
from go_libp2p_pubsub_tpu import subscription_filter as sf
from go_libp2p_pubsub_tpu.pb import rpc_pb2
from go_libp2p_pubsub_tpu.sign import (
    Identity,
    SignError,
    SignPolicy,
    check_signing_policy,
    pubkey_from_peer_id,
    sign_message,
    verify_message,
)


def _msg(ident, data=b"hello", topic="t", seqno=1):
    m = rpc_pb2.Message(data=data, topic=topic, seqno=seqno.to_bytes(8, "big"))
    setattr(m, "from", ident.peer_id)
    return m


def test_sign_verify_roundtrip():
    ident = Identity.generate(1)
    m = _msg(ident)
    sign_message(m, ident)
    verify_message(m)  # no raise


def test_identity_deterministic_and_key_embedded():
    a, b = Identity.generate(7), Identity.generate(7)
    assert a.peer_id == b.peer_id
    assert pubkey_from_peer_id(a.peer_id) is not None
    assert a.peer_id != Identity.generate(8).peer_id


def test_tampered_data_fails():
    ident = Identity.generate(2)
    m = _msg(ident)
    sign_message(m, ident)
    m.data = b"tampered"
    with pytest.raises(SignError):
        verify_message(m)


def test_wrong_from_fails():
    ident, other = Identity.generate(3), Identity.generate(4)
    m = _msg(ident)
    sign_message(m, ident)
    setattr(m, "from", other.peer_id)  # impersonation
    with pytest.raises(SignError):
        verify_message(m)


def test_sign_requires_matching_identity():
    ident, other = Identity.generate(5), Identity.generate(6)
    m = _msg(ident)
    with pytest.raises(SignError):
        sign_message(m, other)


def test_policy_strict_sign():
    ident = Identity.generate(9)
    m = _msg(ident)
    with pytest.raises(SignError):
        check_signing_policy(SignPolicy.STRICT_SIGN, m)  # unsigned
    sign_message(m, ident)
    check_signing_policy(SignPolicy.STRICT_SIGN, m)


def test_policy_strict_no_sign():
    ident = Identity.generate(10)
    m = _msg(ident)
    sign_message(m, ident)
    with pytest.raises(SignError):
        check_signing_policy(SignPolicy.STRICT_NO_SIGN, m)
    anon = rpc_pb2.Message(data=b"x", topic="t")
    check_signing_policy(SignPolicy.STRICT_NO_SIGN, anon)


def test_policy_lax():
    ident = Identity.generate(11)
    anon = rpc_pb2.Message(data=b"x", topic="t")
    check_signing_policy(SignPolicy.LAX_SIGN, anon)     # absent sig ok
    m = _msg(ident)
    sign_message(m, ident)
    check_signing_policy(SignPolicy.LAX_SIGN, m)        # present verifies
    m.data = b"bad"
    with pytest.raises(SignError):
        check_signing_policy(SignPolicy.LAX_SIGN, m)


# -- subscription filters ---------------------------------------------------


def test_allowlist_filter():
    f = sf.AllowlistSubscriptionFilter(["a", "b"])
    assert f.can_subscribe("a") and not f.can_subscribe("c")
    out = f.filter_incoming_subscriptions(
        b"p", [(True, "a"), (True, "c"), (True, "a"), (False, "b")]
    )
    assert out == [(True, "a"), (False, "b")]


def test_regex_filter():
    f = sf.RegexSubscriptionFilter(r"^news/")
    assert f.can_subscribe("news/world")
    assert not f.can_subscribe("sports")


def test_limit_filter():
    f = sf.LimitSubscriptionFilter(sf.AllowlistSubscriptionFilter(["a"]), limit=2)
    assert f.filter_incoming_subscriptions(b"p", [(True, "a")]) == [(True, "a")]
    with pytest.raises(sf.TooManySubscriptions):
        f.filter_incoming_subscriptions(
            b"p", [(True, "a"), (True, "b"), (True, "c")]
        )


# -- blacklists -------------------------------------------------------------


def test_map_blacklist():
    b = bl.MapBlacklist()
    assert not b.contains(b"p")
    b.add(b"p")
    assert b.contains(b"p")
    b.remove(b"p")
    assert not b.contains(b"p")


def test_timecached_blacklist_expiry():
    t = [0.0]
    b = bl.TimeCachedBlacklist(ttl=10.0, now=lambda: t[0])
    b.add(b"p")
    assert b.contains(b"p")
    t[0] = 9.9
    assert b.contains(b"p")
    t[0] = 10.1
    assert not b.contains(b"p")


def test_blacklist_mask():
    b = bl.MapBlacklist()
    b.add(b"p1")
    mask = bl.blacklist_mask(b, [b"p0", b"p1", b"p2"])
    assert mask.tolist() == [False, True, False]
