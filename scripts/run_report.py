#!/usr/bin/env python
"""run_report — render a timeline dashboard from any schema-v3 artifact.

The telemetry plane (docs/DESIGN.md §11) gives every run a per-round
``[T, n_metrics]`` panel; chaos_report/ensemble_report ``--timeline``
embed its median/IQR bands as the artifact's ``timeline`` block. This
script turns any such artifact into a SELF-CONTAINED dashboard — no
external assets, one HTML file (or ``--md`` markdown) — with:

  * per-round band plots (median line + IQR wash) for delivery ratio,
    mesh degree, score quantiles, recovery events and link-down
    occupancy;
  * the delivery-latency CDF envelope when the artifact carries one
    (``extras["latency_cdf"]``);
  * the partition→heal mesh-repair arc (``extras["cross_mesh_series"]``
    — the same series chaos.metrics.mesh_reform_latency is computed
    from, so the plot and the reported latency can never disagree);
  * a stat-tile row of the artifact's headline numbers, and a table
    view per chart (values are never tooltip-gated).

Legacy (pre-v3) artifacts read back TELEMETRY_OFF and render a stub
section saying so. ``--tracestat FILE`` additionally embeds a
``tracestat --json`` summary (counters + caveat flags) as a section.

Usage:
  python scripts/run_report.py ARTIFACT.json [--out report.html] [--md]
                               [--tracestat ts.json]
"""

from __future__ import annotations

import argparse
import html as _html
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines  # noqa: E402

# ---------------------------------------------------------------------------
# chart chrome — the dataviz reference palette (first three categorical
# slots; documented all-pairs-safe in both modes), surfaces and ink as CSS
# custom properties so light/dark swap in one place

_CSS = """
.viz-root { color-scheme: light;
  --surface-1:#fcfcfb; --page:#f9f9f7;
  --ink-1:#0b0b0b; --ink-2:#52514e; --ink-3:#898781;
  --grid:#e1e0d9; --axis:#c3c2b7; --border:rgba(11,11,11,0.10);
  --series-1:#2a78d6; --series-2:#eb6834; --series-3:#1baf7a;
  font-family: system-ui, -apple-system, "Segoe UI", sans-serif;
  background: var(--page); color: var(--ink-1); margin:0; padding:24px; }
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .viz-root { color-scheme: dark;
    --surface-1:#1a1a19; --page:#0d0d0d;
    --ink-1:#ffffff; --ink-2:#c3c2b7; --ink-3:#898781;
    --grid:#2c2c2a; --axis:#383835; --border:rgba(255,255,255,0.10);
    --series-1:#3987e5; --series-2:#d95926; --series-3:#199e70; } }
.viz-root h1 { font-size:20px; font-weight:600; margin:0 0 4px; }
.viz-root h2 { font-size:15px; font-weight:600; margin:28px 0 10px; }
.viz-root .sub { color:var(--ink-2); font-size:12.5px; margin:0 0 18px; }
.viz-root .tiles { display:flex; flex-wrap:wrap; gap:12px; margin:14px 0 6px; }
.viz-root .tile { background:var(--surface-1); border:1px solid var(--border);
  border-radius:8px; padding:10px 14px; min-width:128px; }
.viz-root .tile .lab { color:var(--ink-2); font-size:11.5px; }
.viz-root .tile .val { font-size:24px; font-weight:600; margin-top:2px; }
.viz-root .tile .d { font-size:11.5px; color:var(--ink-3); margin-top:2px; }
.viz-root .grid2 { display:flex; flex-wrap:wrap; gap:16px; }
.viz-root .card { background:var(--surface-1); border:1px solid var(--border);
  border-radius:8px; padding:12px 14px 8px; position:relative; }
.viz-root .card h3 { font-size:13px; font-weight:600; margin:0 0 2px; }
.viz-root .card .note { color:var(--ink-3); font-size:11px; margin:0 0 6px; }
.viz-root .legend { display:flex; gap:14px; font-size:11.5px;
  color:var(--ink-2); margin:2px 0 4px; }
.viz-root .legend .key { display:inline-block; width:14px; height:0;
  border-top:2.5px solid; vertical-align:middle; margin-right:5px;
  border-radius:2px; }
.viz-root svg text { font-family:inherit; font-size:10.5px;
  fill:var(--ink-3); font-variant-numeric: tabular-nums; }
.viz-root svg .dl { fill:var(--ink-2); font-size:11px; }
.viz-root details { margin:4px 0 8px; }
.viz-root summary { color:var(--ink-2); font-size:11.5px; cursor:pointer; }
.viz-root table { border-collapse:collapse; font-size:11px; margin-top:6px; }
.viz-root td, .viz-root th { border:1px solid var(--grid); padding:2px 7px;
  text-align:right; font-variant-numeric: tabular-nums; }
.viz-root th { color:var(--ink-2); font-weight:600; }
.viz-root .tip { position:fixed; pointer-events:none; display:none;
  background:var(--surface-1); border:1px solid var(--border);
  border-radius:6px; padding:6px 9px; font-size:11.5px; z-index:9;
  box-shadow:0 2px 8px rgba(0,0,0,0.12); }
.viz-root .tip .v { font-weight:600; color:var(--ink-1); }
.viz-root .tip .k { display:inline-block; width:11px; height:0;
  border-top:2.5px solid; vertical-align:middle; margin-right:5px; }
.viz-root pre { background:var(--surface-1); border:1px solid var(--border);
  border-radius:8px; padding:10px 12px; font-size:11.5px; overflow-x:auto; }
"""

# one shared hover layer: crosshair snapped to the nearest x, one tooltip
# listing every series at that x (names inserted via textContent)
_JS = """
(function(){
  var tip = document.createElement('div'); tip.className='tip';
  document.body.appendChild(tip);
  document.querySelectorAll('.viz-chart').forEach(function(card){
    var data = JSON.parse(card.querySelector('script[type="application/json"]').textContent);
    var svg = card.querySelector('svg'); if (!svg) return;
    var hair = svg.querySelector('.hair');
    svg.addEventListener('pointerleave', function(){
      tip.style.display='none'; if (hair) hair.setAttribute('opacity','0');
    });
    svg.addEventListener('pointermove', function(ev){
      var r = svg.getBoundingClientRect();
      var fx = (ev.clientX - r.left) * (data.w / r.width);
      var best = 0, bd = 1e18;
      data.px.forEach(function(p, i){
        var d = Math.abs(p - fx); if (d < bd) { bd = d; best = i; } });
      if (hair) { hair.setAttribute('x1', data.px[best]);
        hair.setAttribute('x2', data.px[best]);
        hair.setAttribute('opacity','1'); }
      while (tip.firstChild) tip.removeChild(tip.firstChild);
      var head = document.createElement('div');
      head.style.color = 'var(--ink-3)';
      head.textContent = data.xlabel + ' ' + data.x[best];
      tip.appendChild(head);
      data.series.forEach(function(s){
        var row = document.createElement('div');
        var k = document.createElement('span'); k.className = 'k';
        k.style.borderTopColor = s.color;
        var v = document.createElement('span'); v.className = 'v';
        var val = s.values[best];
        v.textContent = (val === null || val === undefined)
          ? '—' : (Math.round(val * 10000) / 10000);
        var n = document.createElement('span');
        n.textContent = ' ' + s.name; n.style.color = 'var(--ink-2)';
        row.appendChild(k); row.appendChild(v); row.appendChild(n);
        tip.appendChild(row);
      });
      tip.style.display = 'block';
      var tx = ev.clientX + 14, ty = ev.clientY + 12;
      tip.style.left = Math.min(tx, window.innerWidth - 170) + 'px';
      tip.style.top = ty + 'px';
    });
  });
})();
"""

W, H = 520, 200
ML, MR, MT, MB = 46, 10, 8, 22


def _ticks(lo: float, hi: float, n: int = 4) -> list:
    if not math.isfinite(lo) or not math.isfinite(hi) or hi <= lo:
        return [lo]
    raw = (hi - lo) / n
    mag = 10 ** math.floor(math.log10(raw))
    step = min((m for m in (1, 2, 2.5, 5, 10)
                if m * mag >= raw), default=10) * mag
    t0 = math.ceil(lo / step) * step
    out = []
    t = t0
    while t <= hi + 1e-12:
        out.append(round(t, 10))
        t += step
    return out or [lo]


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e6:
            return str(int(v))
        return f"{v:.4g}"
    return str(v)


def svg_chart(title: str, x: list, series: list, xlabel: str = "round",
              note: str = "", y0: float | None = None,
              y1: float | None = None, spans: list | None = None,
              vlines: list | None = None) -> str:
    """One band/line chart card. ``series`` rows are dicts:
    name, values, color (css var), optional band=(lo, hi) and
    muted=True (context series — hairline gray, no legend emphasis)."""
    vals = [v for s in series for v in (s["values"] or []) if v is not None]
    for s in series:
        for b in s.get("band") or ():
            vals += [v for v in b if v is not None]
    lo = min(vals) if vals else 0.0
    hi = max(vals) if vals else 1.0
    if y0 is not None:
        lo = min(lo, y0)
    if y1 is not None:
        hi = max(hi, y1)
    if hi <= lo:
        hi = lo + 1.0
    pad = (hi - lo) * 0.06
    lo2, hi2 = (lo - pad if y0 is None else max(lo - pad, y0)), hi + pad
    pw, ph = W - ML - MR, H - MT - MB
    n = max(len(x), 2)
    px = [ML + pw * i / (n - 1) for i in range(len(x))]

    def sy(v):
        return MT + ph * (1 - (v - lo2) / (hi2 - lo2))

    g = []
    # partition-window wash + heal line annotations (neutral, behind data)
    for sp in spans or ():
        xa = ML + pw * (x.index(sp[0]) / (n - 1)) if sp[0] in x else None
        xb = ML + pw * (x.index(sp[1]) / (n - 1)) if sp[1] in x else None
        if xa is None or xb is None:
            # clamp to the x range positionally
            xa = ML + pw * max(0.0, min(1.0, (sp[0] - x[0]) / max(x[-1] - x[0], 1)))
            xb = ML + pw * max(0.0, min(1.0, (sp[1] - x[0]) / max(x[-1] - x[0], 1)))
        g.append(f'<rect x="{xa:.1f}" y="{MT}" width="{max(xb - xa, 1):.1f}" '
                 f'height="{ph}" fill="var(--grid)" opacity="0.45"/>')
        if len(sp) > 2:
            g.append(f'<text x="{(xa + xb) / 2:.1f}" y="{MT + 11}" '
                     f'text-anchor="middle">{_html.escape(str(sp[2]))}</text>')
    for vl in vlines or ():
        xv = ML + pw * max(0.0, min(1.0, (vl[0] - x[0]) / max(x[-1] - x[0], 1)))
        g.append(f'<line x1="{xv:.1f}" x2="{xv:.1f}" y1="{MT}" y2="{MT + ph}" '
                 f'stroke="var(--axis)" stroke-width="1"/>')
        if len(vl) > 1:
            g.append(f'<text x="{xv + 4:.1f}" y="{MT + 11}">'
                     f'{_html.escape(str(vl[1]))}</text>')
    for t in _ticks(lo2, hi2):
        yy = sy(t)
        g.append(f'<line x1="{ML}" x2="{W - MR}" y1="{yy:.1f}" y2="{yy:.1f}" '
                 f'stroke="var(--grid)" stroke-width="1"/>')
        g.append(f'<text x="{ML - 6}" y="{yy + 3.5:.1f}" text-anchor="end">'
                 f'{_fmt(float(t))}</text>')
    g.append(f'<line x1="{ML}" x2="{W - MR}" y1="{MT + ph}" y2="{MT + ph}" '
             f'stroke="var(--axis)" stroke-width="1"/>')
    for i in range(0, len(x), max(1, (len(x) + 5) // 6)):
        g.append(f'<text x="{px[i]:.1f}" y="{H - 7}" text-anchor="middle">'
                 f'{x[i]}</text>')
    # bands first (washes under every line)
    for s in series:
        b = s.get("band")
        if b:
            up = " ".join(f"{px[i]:.1f},{sy(v):.1f}" for i, v in enumerate(b[1]))
            dn = " ".join(f"{px[i]:.1f},{sy(v):.1f}"
                          for i, v in reversed(list(enumerate(b[0]))))
            g.append(f'<polygon points="{up} {dn}" fill="{s["color"]}" '
                     f'opacity="0.10"/>')
    for s in series:
        pts = " ".join(f"{px[i]:.1f},{sy(v):.1f}"
                       for i, v in enumerate(s["values"]) if v is not None)
        width = 1 if s.get("muted") else 2
        color = "var(--axis)" if s.get("muted") else s["color"]
        g.append(f'<polyline points="{pts}" fill="none" stroke="{color}" '
                 f'stroke-width="{width}" stroke-linejoin="round" '
                 f'stroke-linecap="round"/>')
    # end marker + direct label on the first (emphasized) series only
    main = series[0]
    if main["values"]:
        ex, ey = px[len(main["values"]) - 1], sy(main["values"][-1])
        g.append(f'<circle cx="{ex:.1f}" cy="{ey:.1f}" r="4" '
                 f'fill="{main["color"]}" stroke="var(--surface-1)" '
                 f'stroke-width="2"/>')
        g.append(f'<text x="{min(ex, W - MR - 2):.1f}" y="{ey - 7:.1f}" '
                 f'text-anchor="end" class="dl">'
                 f'{_fmt(main["values"][-1])}</text>')
    g.append(f'<line class="hair" x1="0" x2="0" y1="{MT}" y2="{MT + ph}" '
             f'stroke="var(--axis)" stroke-width="1" opacity="0"/>')

    data = {"w": W, "x": x, "px": [round(p, 1) for p in px],
            "xlabel": xlabel,
            "series": [{"name": s["name"], "values": s["values"],
                        "color": ("var(--axis)" if s.get("muted")
                                  else s["color"])} for s in series]}
    legend = ""
    if len(series) > 1:
        legend = '<div class="legend">' + "".join(
            f'<span><span class="key" style="border-top-color:'
            f'{"var(--axis)" if s.get("muted") else s["color"]}"></span>'
            f'{_html.escape(s["name"])}</span>' for s in series) + "</div>"
    # table view: the values are never tooltip-gated
    head = "<tr><th>" + _html.escape(xlabel) + "</th>" + "".join(
        f"<th>{_html.escape(s['name'])}</th>" for s in series) + "</tr>"
    stride = max(1, len(x) // 24)
    rows = "".join(
        "<tr><td>" + str(x[i]) + "</td>" + "".join(
            f"<td>{_fmt(s['values'][i] if i < len(s['values']) else None)}</td>"
            for s in series) + "</tr>"
        for i in range(0, len(x), stride))
    payload = json.dumps(data).replace("<", "\\u003c")
    return (
        f'<div class="card viz-chart"><h3>{_html.escape(title)}</h3>'
        + (f'<p class="note">{_html.escape(note)}</p>' if note else "")
        + legend
        + f'<svg viewBox="0 0 {W} {H}" width="{W}" height="{H}" '
          f'role="img" aria-label="{_html.escape(title)}">{"".join(g)}</svg>'
        + f'<details><summary>Table view</summary><table>{head}{rows}'
          f'</table></details>'
        + f'<script type="application/json">{payload}</script></div>'
    )


# ---------------------------------------------------------------------------
# artifact -> chart specs


def _tile(label: str, value, detail: str = "") -> str:
    return (f'<div class="tile"><div class="lab">{_html.escape(label)}</div>'
            f'<div class="val">{_fmt(value)}</div>'
            + (f'<div class="d">{_html.escape(detail)}</div>' if detail else "")
            + "</div>")


def record_sections(rec) -> str:
    tl = rec.timeline
    ex = rec.extras or {}
    chaos = rec.chaos
    sub = (f'{rec.unit} · {rec.n_sims} sims · chaos generator '
           f'{chaos["generator"]} loss {chaos["loss_rate"]}'
           + (" · scheduled scenario" if chaos.get("scheduled") else ""))
    out = [f"<h2>{_html.escape(rec.metric)}</h2>",
           f'<p class="sub">{_html.escape(sub)}</p>']
    tiles = [_tile(rec.metric.rsplit("_", 1)[-1] + " (median)", rec.value,
                   f"IQR {ex.get('iqr')}" if ex.get("iqr") else "")]
    if "iwant_recovery_share_median" in ex:
        tiles.append(_tile("IWANT recovery share",
                           ex["iwant_recovery_share_median"],
                           f"IQR {ex.get('iwant_recovery_share_iqr')}"))
    if "mesh_reform_latency_median" in ex:
        tiles.append(_tile("mesh re-form latency",
                           ex["mesh_reform_latency_median"],
                           f"ticks after heal · IQR "
                           f"{ex.get('mesh_reform_latency_iqr')}"))
    if "time_to_recover_median" in ex:
        tiles.append(_tile("time to recover", ex["time_to_recover_median"],
                           f"ticks · IQR {ex.get('time_to_recover_iqr')}"))
    tiles.append(_tile("sims", tl["n_sims"] or rec.n_sims,
                       f"{tl['rows']} obs × {tl['rounds_per_row']} round(s)"
                       if tl["enabled"] else "no timeline recorded"))
    out.append('<div class="tiles">' + "".join(tiles) + "</div>")

    charts = []
    spans, vlines = [], []
    if "partition_window" in ex:
        a, b = ex["partition_window"][:2]
        spans = [(a, b, "partition")]
        vlines = [(b, "heal")]
    if tl["enabled"]:
        s = tl["series"]
        rpr = tl["rounds_per_row"]
        x = [i * rpr for i in range(tl["rows"])]

        def band(name):
            return (s[name]["q25"], s[name]["q75"])

        charts.append(svg_chart(
            "Delivery ratio", x,
            [{"name": "median", "values": s["delivery_ratio"]["q50"],
              "color": "var(--series-1)", "band": band("delivery_ratio")}],
            note="cumulative delivered/expected · IQR wash over sims",
            y0=0.0, y1=1.0, spans=spans, vlines=vlines))
        charts.append(svg_chart(
            "Mesh degree", x,
            [{"name": "mean (median)", "values": s["mesh_deg_mean"]["q50"],
              "color": "var(--series-1)", "band": band("mesh_deg_mean")},
             {"name": "min", "values": s["mesh_deg_min"]["q50"],
              "color": "var(--series-1)", "muted": True},
             {"name": "max", "values": s["mesh_deg_max"]["q50"],
              "color": "var(--series-1)", "muted": True}],
            note="per-(peer, topic) mesh degree across the network",
            y0=0.0, spans=spans, vlines=vlines))
        charts.append(svg_chart(
            "Peer score quantiles", x,
            [{"name": "p50", "values": s["score_p50"]["q50"],
              "color": "var(--series-1)", "band": band("score_p50")},
             {"name": "p5", "values": s["score_p5"]["q50"],
              "color": "var(--series-1)", "muted": True},
             {"name": "p95", "values": s["score_p95"]["q50"],
              "color": "var(--series-1)", "muted": True}],
            note="across peers: each peer's mean held neighbor score",
            spans=spans, vlines=vlines))
        charts.append(svg_chart(
            "Deliveries & recovery per observation", x,
            [{"name": "deliveries", "values": s["ev_deliver_message"]["q50"],
              "color": "var(--series-1)",
              "band": band("ev_deliver_message")},
             {"name": "duplicates", "values": s["ev_duplicate_message"]["q50"],
              "color": "var(--series-2)"},
             {"name": "IWANT recoveries",
              "values": s["ev_iwant_recover"]["q50"],
              "color": "var(--series-3)"}],
            note="EV-counter deltas per observation (reconciled against "
                 "the drained totals)", y0=0.0, spans=spans, vlines=vlines))
        if any(v > 0 for v in s["links_down_frac"]["q75"]):
            charts.append(svg_chart(
                "Link-down occupancy", x,
                [{"name": "median", "values": s["links_down_frac"]["q50"],
                  "color": "var(--series-2)",
                  "band": band("links_down_frac")}],
                note="fraction of live undirected links down per round",
                y0=0.0, y1=1.0, spans=spans, vlines=vlines))
    if "cross_mesh_series" in ex:
        cm = ex["cross_mesh_series"]
        charts.append(svg_chart(
            "Cross-group mesh edges — the repair arc", cm["ticks"],
            [{"name": "median", "values": cm["q50"],
              "color": "var(--series-1)", "band": (cm["q25"], cm["q75"])}],
            xlabel="tick",
            note="directed mesh edges crossing the partition: starve → "
                 "prune trough → backoff-clear re-graft wave "
                 "(chaos.metrics.mesh_reform_latency reads this series)",
            y0=0.0, spans=spans, vlines=vlines))
    if "latency_cdf" in ex:
        cdf = ex["latency_cdf"]
        charts.append(svg_chart(
            "Delivery-latency CDF", cdf["lat"],
            [{"name": "pooled", "values": cdf["pooled"],
              "color": "var(--series-1)",
              "band": (cdf.get("q10", cdf["pooled"]),
                       cdf.get("q90", cdf["pooled"]))}],
            xlabel="rounds after publish",
            note="pooled over sims · band = per-sim CDF 10/90 percentiles",
            y0=0.0, y1=1.0))
    if not charts:
        out.append('<p class="sub">This artifact predates the telemetry '
                   'plane (TELEMETRY_OFF) — no per-round series to plot; '
                   're-run the producing report with --timeline.</p>')
    out.append('<div class="grid2">' + "".join(charts) + "</div>")
    return "".join(out)


def render_html(records, title: str = "pubsub run report",
                tracestat: dict | None = None) -> str:
    body = [f"<h1>{_html.escape(title)}</h1>",
            '<p class="sub">telemetry-plane timeline dashboard '
            "(go_libp2p_pubsub_tpu, docs/DESIGN.md §11) · bands are "
            "median/IQR across sims</p>"]
    for rec in records:
        body.append(record_sections(rec))
    if tracestat is not None:
        body.append("<h2>trace summary (tracestat)</h2>")
        counts = tracestat.get("counts", {})
        rows = "".join(f"<tr><th>{_html.escape(k)}</th><td>{v}</td></tr>"
                       for k, v in counts.items())
        body.append(f'<div class="card"><table>{rows}</table>')
        caveats = tracestat.get("caveats", [])
        if caveats:
            body.append('<p class="note">caveats: '
                        + _html.escape(", ".join(caveats)) + "</p>")
        body.append("</div>")
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{_html.escape(title)}</title>"
            f"<style>{_CSS}</style></head>"
            f"<body class='viz-root'>{''.join(body)}"
            f"<script>{_JS}</script></body></html>")


def write_report(prefix: str, records) -> tuple:
    """Write ``records`` as ``<prefix>.json`` (one dump_record line
    each) and render the parsed-back lines as the self-contained
    ``<prefix>.html`` dashboard. The shared tail of every ``--timeline``
    mode (chaos_report, ensemble_report) — round-tripping through
    load_bench_lines so the HTML shows exactly what the artifact
    carries. Returns ``(json_path, html_path)``."""
    from go_libp2p_pubsub_tpu.perf.artifacts import dump_record

    json_path = prefix + ".json"
    with open(json_path, "w") as f:
        for rec in records:
            f.write(dump_record(rec) + "\n")
    html_path = prefix + ".html"
    with open(html_path, "w") as f:
        f.write(render_html(load_bench_lines(json_path),
                            title=os.path.basename(json_path)))
    return json_path, html_path


def render_markdown(records) -> str:
    out = ["# pubsub run report", ""]
    for rec in records:
        tl = rec.timeline
        ex = rec.extras or {}
        out += [f"## {rec.metric}", "",
                f"- value (median over {rec.n_sims} sims): **{rec.value}**"
                f" {rec.unit}"]
        for k in ("iqr", "iwant_recovery_share_median",
                  "mesh_reform_latency_median", "time_to_recover_median"):
            if k in ex:
                out.append(f"- {k}: {ex[k]}")
        if tl["enabled"]:
            s = tl["series"]
            x = [i * tl["rounds_per_row"] for i in range(tl["rows"])]
            cols = ["delivery_ratio", "mesh_deg_mean", "score_p50",
                    "ev_deliver_message", "ev_duplicate_message",
                    "ev_iwant_recover", "links_down_frac"]
            out += ["", "| round | " + " | ".join(cols) + " |",
                    "|" + "---|" * (len(cols) + 1)]
            stride = max(1, len(x) // 16)
            for i in range(0, len(x), stride):
                out.append("| " + str(x[i]) + " | " + " | ".join(
                    _fmt(s[c]["q50"][i]) for c in cols) + " |")
        else:
            out.append("- no timeline block (TELEMETRY_OFF artifact)")
        out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("artifact", help="schema-v3 artifact (JSON lines)")
    ap.add_argument("--out", help="output path (default: artifact + "
                                  ".html/.md)")
    ap.add_argument("--md", action="store_true",
                    help="emit markdown instead of HTML")
    ap.add_argument("--tracestat",
                    help="tracestat --json output to embed as a section")
    args = ap.parse_args(argv)
    records = load_bench_lines(args.artifact)
    ts = None
    if args.tracestat:
        with open(args.tracestat) as f:
            ts = json.load(f)
    if args.md:
        text = render_markdown(records)
        suffix = ".md"
    else:
        text = render_html(
            records, title=os.path.basename(args.artifact), tracestat=ts)
        suffix = ".html"
    out = args.out or (os.path.splitext(args.artifact)[0] + suffix)
    with open(out, "w") as f:
        f.write(text)
    print(f"wrote {out} ({len(records)} record(s), "
          f"{sum(1 for r in records if r.telemetry_on)} with timelines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
