"""Ensemble-plane gate + Monte Carlo throughput report
(``make ensemble-smoke``; docs/DESIGN.md §10).

Runs the chaos smoke's flap scenario (scripts/chaos_report.py shape:
N=128, 60% i.i.d. link loss, 80 rounds, gossipsub v1.1 with live
scoring) as an S=8 ensemble — ONE vmapped XLA program — and asserts
the ensemble plane's whole contract:

  1. **one compile** — the lifted step's compile-cache grows by
     exactly 1 across the full S×80-round run (cache-size sentinel;
     the one-program promise `jax.vmap` exists to make).
  2. **per-sim bit-exactness** — EVERY sim's final state tree equals
     the corresponding single-sim run built with the derived key
     ``fold_in(sim_key, sim_idx)``, leaf for leaf, bit for bit. The
     gate pins the THREEFRY PRNG: its counter-mode draws batch
     elementwise, so vmap(step) == step per sim exactly. (unsafe_rbg
     keeps sims independent but its RngBitGenerator batching is not
     elementwise — documented in ensemble/batch.py; the chaos fault
     hashes are impl-independent.)
  3. **artifact integrity** — the emitted schema-v2 line carries the
     ``fingerprint["ensemble"]`` block (S, sim-key derivation,
     aggregation mode) and round-trips through perf.artifacts.
  4. **aggregate-throughput floor** — batched sim-rounds/s must stay
     above ENSEMBLE_SMOKE_TOL × the committed ENSEMBLE_SMOKE.json
     baseline (ENSEMBLE_SMOKE_UPDATE=1 rewrites it). The sequential
     rate (the same S sims run one-by-one through the single-sim jit)
     is measured alongside — it is the docs/PERF.md comparison row,
     and the batched/sequential ratio is reported in the artifact.

CPU-only by contract, like perf-smoke/chaos-smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))  # repo root
if _here not in sys.path:  # scripts/ — chaos_report owns the smoke shape
    sys.path.insert(1, _here)

import numpy as np  # noqa: E402

from chaos_report import FLAP_LOSS, FLAP_ROUNDS, SMOKE_N  # noqa: E402

ENSEMBLE_SMOKE_S = 8
BASELINE_NAME = "ENSEMBLE_SMOKE.json"
#: aggregate-throughput floor: fraction of the committed baseline the
#: fresh batched rate must reach (machines vary; deliberately loose,
#: like perf-smoke's DEFAULT_TOL)
DEFAULT_TOL = 0.4


def _keyless_leaves(tree):
    """Flat leaf list with PRNG keys unwrapped to their raw data (so
    bit-comparison covers the key plane too)."""
    import jax

    from go_libp2p_pubsub_tpu.checkpoint import is_prng_key

    def unkey(x):
        if is_prng_key(x):
            return jax.random.key_data(x)
        return x

    return jax.tree_util.tree_leaves(jax.tree_util.tree_map(unkey, tree))


def _leaf_paths(tree):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(p) for p, _ in flat]


def build_flap_cell(n: int, loss: float, seed: int):
    """The smoke flap cell: (initial gossipsub state, jitted step,
    schedule arrays) — the same overlay/score/chaos configuration
    chaos_report.run_flap measures, built once and shared by the
    batched and sequential runs."""
    from chaos_report import _flap_params, _publish_schedule, _score_params

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.chaos import ChaosConfig
    from go_libp2p_pubsub_tpu.config import PeerScoreThresholds
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.state import Net

    topo = graph.random_connect(n, d=4, seed=seed)
    subs = graph.subscribe_all(n, 1)
    net = Net.build(topo, subs)
    cc = ChaosConfig(loss_rate=loss)
    rng = np.random.default_rng(seed)
    po, pt, pv = _publish_schedule(rng, n, FLAP_ROUNDS, pub_rounds=3)
    sp = _score_params()
    cfg = GossipSubConfig.build(_flap_params(), PeerScoreThresholds(),
                                score_enabled=True, chaos=cc)
    st0 = GossipSubState.init(net, 64, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp)
    return st0, step, net, (po, pt, pv)


def run_gate(s: int, n: int, loss: float, seed: int) -> dict:
    """The full gate; returns the result dict (failures list inside)."""
    import jax
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu import ensemble
    from go_libp2p_pubsub_tpu.ensemble import stats as estats

    failures: list[str] = []
    st0, step, net, (po, pt, pv) = build_flap_cell(n, loss, seed)
    base_key = st0.core.key
    rounds = po.shape[0]
    ens = ensemble.lift_step(step)

    def margs(i):
        return (ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
                ensemble.tile(pv[i], s))

    # --- batched: compile + warm run (the one-compile sentinel) -------
    # round 14: the whole batched cell is ONE scan-window program
    # (ensemble.WindowRunner) — S sims x all rounds in a single
    # dispatch; the runner is reused so the warm re-run pins
    # zero-recompile on the same jit
    runner = ensemble.WindowRunner(ens, rounds)
    run = runner.run(ensemble.batch_states(st0, s), margs)
    if run.compiles not in (-1, 1):  # -1 = sentinel API unavailable
        failures.append(
            f"one-compile: the scan window compiled {run.compiles} times "
            f"across the S={s} x {rounds}-round run (expected exactly 1)"
        )
    if run.dispatches != 1:
        failures.append(
            f"one-dispatch: the batched cell executed as {run.dispatches} "
            "dispatches (expected ONE whole-run window)"
        )
    # timed warm segment (fresh batched states; the first run paid the
    # compile, this one is the throughput number)
    timed = runner.run(ensemble.batch_states(st0, s), margs)
    if timed.compiles not in (-1, 0):
        failures.append(
            f"one-compile: warm re-run recompiled ({timed.compiles} "
            "fresh compiles) — shape/weak-type wobble in the window"
        )
    aggregate = timed.aggregate_rounds_per_sec

    # --- sequential baseline + per-sim bit-exactness ------------------
    # apples-to-apples with the batched number: the S initial states
    # are built OUTSIDE the timer (the batched run's batch_states is
    # untimed too) and the single-sim jit is warmed first, so the
    # window times execution only — not XLA compile or host topology
    # rebuilds. Fresh donatable buffers come from copying st0's leaves
    # (the jitted step donates its state, so each run needs its own) —
    # key leaves pass through untouched because with_sim_key replaces
    # them anyway.
    def fresh_state(sim_key):
        from go_libp2p_pubsub_tpu.checkpoint import is_prng_key

        st = jax.tree_util.tree_map(
            lambda x: x if is_prng_key(x) else jnp.copy(x), st0)
        return ensemble.with_sim_key(st, base_key, sim_key)

    inits = [fresh_state(i) for i in range(s)]
    jax.block_until_ready(
        step(fresh_state(0), jnp.asarray(po[0]), jnp.asarray(pt[0]),
             jnp.asarray(pv[0])))
    finals = []
    t0 = time.perf_counter()
    for st_i in inits:
        for t in range(rounds):
            st_i = step(st_i, jnp.asarray(po[t]), jnp.asarray(pt[t]),
                        jnp.asarray(pv[t]))
        jax.block_until_ready(st_i)
        finals.append(st_i)
    seq_dt = time.perf_counter() - t0
    sequential = s * rounds / seq_dt if seq_dt > 0 else float("inf")

    paths = _leaf_paths(finals[0])
    for i, ref in enumerate(finals):
        got = ensemble.unbatch(timed.states, i)
        for path, a, b in zip(paths, _keyless_leaves(got),
                              _keyless_leaves(ref)):
            if not bool(jnp.array_equal(a, b)):
                failures.append(
                    f"parity: sim {i} diverges from its single-sim run "
                    f"at state leaf {path} (first of possibly many)"
                )
                break

    ratios = np.asarray(estats.sim_delivery_ratios(
        timed.states.core.dlv.first_round, timed.states.core.msgs.birth,
        timed.states.core.msgs.topic, timed.states.core.msgs.origin,
        net.subscribed,
    ))
    return {
        "failures": failures,
        "aggregate": aggregate,
        "sequential": sequential,
        "speedup": aggregate / sequential if sequential else float("inf"),
        "ratios": ratios,
        "n_sims": s,
        "rounds": rounds,
        "n_peers": n,
        "loss": loss,
        "compiles": run.compiles,
        "dispatches": run.dispatches,
    }


def emit_artifact(res: dict, loss: float) -> dict:
    """Emit + round-trip-check the schema-v2 ensemble artifact line."""
    from go_libp2p_pubsub_tpu.ensemble import stats as estats
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        SIM_KEY_DERIVATION,
        BenchRecord,
        chaos_fingerprint,
        dump_record,
        ensemble_fingerprint,
        execution_fingerprint,
        record_from_line,
    )

    band = estats.quantile_band(res["ratios"])
    rec = BenchRecord(
        metric="ensemble_flap_aggregate_sim_rounds_per_sec",
        value=round(res["aggregate"], 2),
        unit="sim-rounds/s",
        vs_baseline=0.0,
        schema=2,
        fingerprint={
            "chaos": chaos_fingerprint(_chaos_cfg(loss)),
            "ensemble": ensemble_fingerprint(res["n_sims"]),
            "execution": execution_fingerprint(
                scan=True, segment_rounds=res["rounds"],
                dispatches_per_window=res["dispatches"],
                rounds_per_dispatch=res["rounds"]),
        },
        extras={
            "sequential_sim_rounds_per_sec": round(res["sequential"], 2),
            "batched_over_sequential": round(res["speedup"], 3),
            "rounds": res["rounds"],
            "delivery_ratio_median": round(band["q50"], 4),
            "delivery_ratio_iqr": [round(band["q25"], 4),
                                   round(band["q75"], 4)],
        },
    )
    line = dump_record(rec)
    print(line, flush=True)
    back = record_from_line(json.loads(line))
    errors = []
    if back.n_sims != res["n_sims"]:
        errors.append(
            f"artifact: ensemble block lost n_sims on round-trip "
            f"({back.n_sims} != {res['n_sims']})"
        )
    if back.ensemble.get("sim_key") != SIM_KEY_DERIVATION:
        errors.append("artifact: sim-key derivation missing from the "
                      "ensemble block")
    return {"record": rec, "errors": errors}


def _chaos_cfg(loss: float):
    from go_libp2p_pubsub_tpu.chaos import ChaosConfig

    return ChaosConfig(loss_rate=loss)


def check_floor(root: str, res: dict) -> list[str]:
    """Aggregate-throughput floor vs the committed baseline."""
    path = os.path.join(root, BASELINE_NAME)
    if not os.path.exists(path) or os.environ.get("ENSEMBLE_SMOKE_UPDATE"):
        return []
    with open(path) as f:
        base = json.load(f)
    tol = float(os.environ.get("ENSEMBLE_SMOKE_TOL", DEFAULT_TOL))
    errors = []
    # the committed floor is shape-specific: a --sims/--n/--loss/--rounds
    # variant run must not be judged against (or silently weaken) the
    # default shape's number
    for dim in ("n_sims", "n_peers", "rounds", "loss"):
        if res[dim] != type(res[dim])(base.get(dim, res[dim])):
            return []
    committed = base.get("aggregate_sim_rounds_per_sec")
    if committed and res["aggregate"] < tol * committed:
        errors.append(
            f"aggregate throughput regressed: {res['aggregate']:.1f} < "
            f"{tol:.2f} x committed {committed:.1f} sim-rounds/s "
            f"({BASELINE_NAME}; ENSEMBLE_SMOKE_TOL overrides, "
            "ENSEMBLE_SMOKE_UPDATE=1 rewrites)"
        )
    return errors


def write_baseline(root: str, res: dict) -> str:
    path = os.path.join(root, BASELINE_NAME)
    payload = {
        "schema": 1,
        "aggregate_sim_rounds_per_sec": round(res["aggregate"], 2),
        "sequential_sim_rounds_per_sec": round(res["sequential"], 2),
        "batched_over_sequential": round(res["speedup"], 3),
        "n_sims": res["n_sims"],
        "rounds": res["rounds"],
        "n_peers": res["n_peers"],
        "loss": res["loss"],
        "note": (
            "ensemble-smoke aggregate-throughput baseline "
            "(scripts/ensemble_report.py); ENSEMBLE_SMOKE_UPDATE=1 "
            "rewrites"
        ),
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return path


def run_timeline(prefix: str, s: int, n: int, loss: float, seed: int) -> tuple:
    """The ``--timeline`` mode: the S-sim flap cell TELEMETRY-ON (one
    vmapped program, one panel row per round per sim), panels reconciled
    against the drained counters per sim, reduced to schema-v3 timeline
    bands, written as ``<prefix>.json`` and rendered as the
    self-contained ``<prefix>.html`` dashboard (scripts/run_report.py)."""
    from chaos_report import run_flap

    import run_report as run_report_mod

    from go_libp2p_pubsub_tpu.ensemble import stats as estats
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        BenchRecord,
        chaos_fingerprint,
        ensemble_fingerprint,
    )
    from go_libp2p_pubsub_tpu.telemetry import timeline_block

    flap = run_flap(n=n, loss=loss, seed=seed, seeds=s, full=False,
                    telemetry=True)
    band = estats.quantile_band(np.asarray(flap["gossipsub_ratios"]))
    rec = BenchRecord(
        metric="ensemble_flap_delivery_ratio",
        value=round(float(band["q50"]), 4),
        unit="ratio",
        vs_baseline=0.0,
        schema=3,
        fingerprint={"chaos": chaos_fingerprint(flap["chaos"]),
                     "ensemble": ensemble_fingerprint(flap["seeds"])},
        extras={
            "n_peers": flap["n"], "rounds": flap["rounds"],
            "iqr": [round(float(band["q25"]), 4),
                    round(float(band["q75"]), 4)],
            "latency_cdf": flap["latency_cdf"],
        },
        timeline_raw=timeline_block(flap["panels"]),
    )
    return run_report_mod.write_report(prefix, [rec])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="exit non-zero on any gate failure")
    ap.add_argument("--timeline", metavar="PREFIX",
                    help="run the S-sim flap cell telemetry-on and write "
                         "the PREFIX.json timeline artifact + the "
                         "PREFIX.html dashboard (scripts/run_report.py), "
                         "then exit")
    ap.add_argument("--sims", type=int,
                    default=int(os.environ.get("ENSEMBLE_SMOKE_S",
                                               ENSEMBLE_SMOKE_S)))
    ap.add_argument("--n", type=int, default=SMOKE_N)
    ap.add_argument("--loss", type=float, default=FLAP_LOSS)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.sims < 1:
        ap.error("--sims must be >= 1")

    # CPU-only by contract; THREEFRY pinned (see the module docstring:
    # the per-sim bit-parity assertion is only meaningful under an
    # elementwise-batching PRNG). The persistent compile cache policy
    # matches the other gates.
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "threefry2x32")
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    enable_persistent_cache(os.path.join(repo_root(), ".jax_cache"))

    if args.timeline:
        json_path, html_path = run_timeline(
            args.timeline, args.sims, args.n, args.loss, args.seed,
        )
        print(json.dumps({"timeline_artifact": json_path,
                          "report": html_path}))
        return 0

    res = run_gate(args.sims, args.n, args.loss, args.seed)
    failures = list(res["failures"])
    art = emit_artifact(res, args.loss)
    failures += art["errors"]
    root = repo_root()
    if os.environ.get("ENSEMBLE_SMOKE_UPDATE"):
        print("wrote", write_baseline(root, res))
    failures += check_floor(root, res)

    if args.smoke and failures:
        for f in failures:
            print(f"ensemble-smoke FAIL: {f}", file=sys.stderr)
        print(json.dumps({"ensemble_smoke": "FAIL",
                          "errors": len(failures)}))
        return 1
    print(json.dumps({"ensemble_smoke": "PASS" if not failures else "REPORT",
                      "warnings": failures}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
