"""``make analyze`` — the analysis-plane gate (docs/DESIGN.md §9).

Two halves, either of which failing exits non-zero:

  1. **simlint** (analysis/simlint.py): AST lint over the whole package
     with the repo-specific rule set; intentional exceptions live in
     the committed ``analysis/ALLOWLIST``.
  2. **trace guards** (analysis/guards.py): re-trace + run all four
     engines — plus the S=2 ENSEMBLE lift of the gossipsub step (the
     batched path, round 10) — under strict dtype promotion,
     jax_enable_checks and the transfer guard; assert one compile per
     engine, buffer donation, and the committed ``STATE_SCHEMA.json``
     state-leaf baseline (``ANALYZE_UPDATE=1`` rewrites it — the
     PERF_SMOKE pattern). The ensemble engine's leaves validate by
     STRIPPING the leading S axis against the gossipsub rows, so the
     baseline is never duplicated.

CPU-only by contract, like perf-smoke/chaos-smoke: it must mean the
same thing on any dev box or CI runner. Emits one JSON summary line;
human-readable findings go to stderr.

Flags: ``--lint-only`` / ``--guards-only``.
"""

from __future__ import annotations

import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    lint_only = "--lint-only" in argv
    guards_only = "--guards-only" in argv

    failures: list[str] = []
    summary: dict = {}

    if not guards_only:
        from go_libp2p_pubsub_tpu.analysis import simlint

        violations, allowed = simlint.run()
        for v in violations:
            failures.append(v.format())
        summary["lint"] = {
            "violations": len(violations), "allowed": len(allowed),
        }

    if not lint_only:
        import jax

        # CPU + the bench PRNG + the shared persistent compile cache —
        # identical policy to perf/regress.py, so the guard shapes
        # compile once per container, not once per run
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_default_prng_impl", "unsafe_rbg")
        from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache

        enable_persistent_cache(os.path.join(_ROOT, ".jax_cache"))

        from go_libp2p_pubsub_tpu.analysis import guards

        guard_failures = guards.run()
        failures.extend(guard_failures)
        summary["guards"] = {
            "engines": list(guards.ALL_ROWS),
            "failures": len(guard_failures),
            "updated": bool(os.environ.get("ANALYZE_UPDATE")),
        }

    if failures:
        for f in failures:
            print(f"analyze FAIL: {f}", file=sys.stderr)
        print(json.dumps({"analyze": "FAIL", **summary}))
        return 1
    print(json.dumps({"analyze": "PASS", **summary}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
