"""``make analyze`` — the analysis-plane gate (docs/DESIGN.md §9).

Two halves, either of which failing exits non-zero:

  1. **simlint** (analysis/simlint.py): AST lint over the whole package
     with the repo-specific rule set; intentional exceptions live in
     the committed ``analysis/ALLOWLIST``.
  2. **trace guards** (analysis/guards.py): re-trace + run all four
     engines — plus the S=2 ENSEMBLE lift of the gossipsub step (the
     batched path, round 10) — under strict dtype promotion,
     jax_enable_checks and the transfer guard; assert one compile per
     engine, buffer donation, and the committed ``STATE_SCHEMA.json``
     state-leaf baseline (``ANALYZE_UPDATE=1`` rewrites it — the
     PERF_SMOKE pattern). The ensemble engine's leaves validate by
     STRIPPING the leading S axis against the gossipsub rows, so the
     baseline is never duplicated.

CPU-only by contract, like perf-smoke/chaos-smoke: it must mean the
same thing on any dev box or CI runner. Emits one JSON summary line;
human-readable findings go to stderr.

Flags: ``--lint-only`` / ``--guards-only`` / ``--json``.

``--json`` (round 19, ``make static``) runs the WHOLE static suite —
simlint, guards, lift-audit, hlo-audit, cost-audit, range-audit — and
emits ONE machine-readable verdict block: per-pass pass/fail plus the
committed artifact path(s) each pass gates on, with a single exit code
over all six. The audit passes run as subprocesses (each pins its own
platform/PRNG policy); their one-line JSON summaries are embedded.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

#: the subprocess passes of the --json umbrella: (name, script,
#: committed artifacts the pass gates on)
_SUBPROCESS_PASSES = (
    ("lift", "lift_audit.py", ("LIFT_AUDIT.json",)),
    ("hlo", "hlo_audit.py", ()),
    ("cost", "cost_audit.py", ("COST_AUDIT.json",)),
    ("tune", "tune_check.py", ()),
    ("ranges", "range_audit.py", ("RANGE_AUDIT.json",)),
)


def _last_json_line(text: str) -> dict | None:
    out = None
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                out = json.loads(line)
            except json.JSONDecodeError:
                continue
    return out


def _run_pass(script: str) -> tuple[int, dict | None]:
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts", script)],
        capture_output=True, text=True, cwd=_ROOT)
    if proc.stderr:
        sys.stderr.write(proc.stderr)
    return proc.returncode, _last_json_line(proc.stdout)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    lint_only = "--lint-only" in argv
    guards_only = "--guards-only" in argv
    as_json = "--json" in argv
    if as_json and (lint_only or guards_only):
        # a skipped half must never read as PASS in the umbrella
        # verdict (the scale-smoke SKIPPED-marker lesson, PR 14)
        print("analyze: --json runs the WHOLE static suite; it cannot "
              "be combined with --lint-only/--guards-only",
              file=sys.stderr)
        return 2

    failures: list[str] = []
    summary: dict = {}

    if not guards_only:
        from go_libp2p_pubsub_tpu.analysis import simlint

        violations, allowed = simlint.run()
        for v in violations:
            failures.append(v.format())
        summary["lint"] = {
            "violations": len(violations), "allowed": len(allowed),
        }

    if not lint_only:
        import jax

        # CPU + the bench PRNG + the shared persistent compile cache —
        # identical policy to perf/regress.py, so the guard shapes
        # compile once per container, not once per run
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_default_prng_impl", "unsafe_rbg")
        from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache

        enable_persistent_cache(os.path.join(_ROOT, ".jax_cache"))

        from go_libp2p_pubsub_tpu.analysis import guards

        guard_failures = guards.run()
        failures.extend(guard_failures)
        summary["guards"] = {
            "engines": list(guards.ALL_ROWS),
            "failures": len(guard_failures),
            "updated": bool(os.environ.get("ANALYZE_UPDATE")),
        }

    if as_json:
        for f in failures:
            print(f"analyze FAIL: {f}", file=sys.stderr)
        # the `make static` umbrella verdict: the two in-process halves
        # plus every audit pass, one block, one exit code
        # the two in-process halves classify by their own counters
        passes = {
            "simlint": {
                "status": ("FAIL" if summary.get("lint", {}).get(
                    "violations") else "PASS"),
                "artifacts": ["go_libp2p_pubsub_tpu/analysis/ALLOWLIST"],
                "summary": summary.get("lint", {}),
            },
            "guards": {
                "status": ("FAIL" if summary.get("guards", {}).get(
                    "failures") else "PASS"),
                "artifacts": ["STATE_SCHEMA.json"],
                "summary": summary.get("guards", {}),
            },
        }
        for name, script, artifacts in _SUBPROCESS_PASSES:
            rc, sub_summary = _run_pass(script)
            passes[name] = {
                "status": "PASS" if rc == 0 else "FAIL",
                "artifacts": list(artifacts),
                "summary": sub_summary or {},
            }
        ok = all(p["status"] == "PASS" for p in passes.values())
        print(json.dumps({"static": "PASS" if ok else "FAIL",
                          "passes": passes}))
        return 0 if ok else 1

    if failures:
        for f in failures:
            print(f"analyze FAIL: {f}", file=sys.stderr)
        print(json.dumps({"analyze": "FAIL", **summary}))
        return 1
    print(json.dumps({"analyze": "PASS", **summary}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
