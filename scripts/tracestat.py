#!/usr/bin/env python
"""tracestat — summarize a pubsub trace file (the analysis the reference's
README points at for its `trace.pb` streams; north star: "tracestat
analysis is unchanged").

Reads JSON-lines (JSONTracer) or varint-delimited protobuf (PBTracer)
TraceEvent files and reports:
  * per-type event counts;
  * publish/deliver/duplicate/reject totals and the delivery ratio;
  * propagation delay percentiles (DELIVER_MESSAGE timestamps relative to
    the message's PUBLISH_MESSAGE, by message id), in the trace's time
    base (nanoseconds; the drain writes tick * tick_ns).

With ``--json`` the summary is machine-readable: the per-type counts
plus a ``caveats`` list of stable flag strings (``phase_cadence``,
``counter_only_events``, ``no_publishes``) with their prose in
``caveat_notes`` — so gates and scripts/run_report.py consume the
accounting caveats structurally instead of re-parsing report text.

``--artifact PATH`` additionally reads the run's schema-v3 bench
artifact and reports its ``invariants`` block (the invariant oracle
plane's checked/violated counts and last-checked round,
docs/DESIGN.md §12) alongside the trace accounting; legacy artifacts
— every line that predates the oracle plane — read back
``INVARIANTS_OFF`` (enabled=false), never a KeyError.

Usage: python scripts/tracestat.py TRACEFILE [--json] [--artifact RUN.json]
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter, defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from go_libp2p_pubsub_tpu.pb import trace_pb2
from go_libp2p_pubsub_tpu.trace import sinks


def read_events(path: str, fmt: str = "auto"):
    """Yield TraceEvent via the package's tested readers. `fmt` is "json",
    "pb", or "auto" — auto tries JSON first and falls back to delimited
    protobuf (first-byte sniffing alone is unsound: a PB record of length
    123 starts with the same 0x7b byte as '{')."""
    if fmt == "json":
        yield from sinks.read_json_trace(path)
        return
    if fmt == "pb":
        yield from sinks.read_pb_trace(path)
        return
    try:
        events = list(sinks.read_json_trace(path))
    except Exception:
        events = None
    if events is None:
        events = list(sinks.read_pb_trace(path))
    yield from events


def percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _gcd_of_gaps(sorted_ts: list[int]) -> int:
    import math

    g = 0
    for a, b in zip(sorted_ts, sorted_ts[1:]):
        g = math.gcd(g, b - a)
    return g


def _cadence_note(data_ts: set, control_ts: set) -> dict | None:
    """Detect a phase-cadence trace (rounds_per_phase > 1) from timestamp
    granularity: data events (PUBLISH/DELIVER) carry per-sub-round
    resolution while control events (GRAFT/PRUNE) are emitted at phase
    boundaries — which sit at tick multiples of the phase length — so
    the gcd of the ABSOLUTE control timestamps is a multiple of the data
    tick. Absolute alignment (not gap stride) is what makes the
    heuristic robust to sparse control activity: an r=1 trace whose only
    two GRAFT batches land 4 ticks apart at ticks 5 and 9 has gcd 1 tick
    (no false positive), while real phase traces graft at boundary ticks
    {0, r, 2r, ...} however few of them fire. When detected, surface the
    r>1 accounting caveats that otherwise live only in trace/drain.py
    docstrings (ADVICE round 5)."""
    import math

    base = _gcd_of_gaps(sorted(data_ts))
    ctrl = 0
    for t in control_ts:
        ctrl = math.gcd(ctrl, t)
    if len(control_ts) < 2 or not base or not ctrl or ctrl <= base or ctrl % base:
        return None
    from go_libp2p_pubsub_tpu.trace.drain import PHASE_CADENCE_NOTE

    return {
        "tick_ns": base,
        "control_stride_ns": ctrl,
        "rounds_per_phase_estimate": ctrl // base,
        # single source of truth: the drain session surfaces the same
        # text live via TraceSession.accounting_caveats()
        "note": PHASE_CADENCE_NOTE,
    }


def summarize(events) -> dict:
    counts = Counter()
    publish_ts: dict[bytes, int] = {}
    delays: list[int] = []
    peers = set()
    data_ts: set[int] = set()
    control_ts: set[int] = set()

    for ev in events:
        tname = trace_pb2.TraceEvent.Type.Name(ev.type)
        counts[tname] += 1
        peers.add(bytes(ev.peerID))
        if ev.type == trace_pb2.TraceEvent.PUBLISH_MESSAGE:
            publish_ts[bytes(ev.publishMessage.messageID)] = ev.timestamp
            data_ts.add(ev.timestamp)
        elif ev.type == trace_pb2.TraceEvent.DELIVER_MESSAGE:
            t0 = publish_ts.get(bytes(ev.deliverMessage.messageID))
            if t0 is not None:
                delays.append(ev.timestamp - t0)
            data_ts.add(ev.timestamp)
        elif ev.type in (trace_pb2.TraceEvent.GRAFT, trace_pb2.TraceEvent.PRUNE):
            control_ts.add(ev.timestamp)

    delays.sort()
    pub = counts.get("PUBLISH_MESSAGE", 0)
    dlv = counts.get("DELIVER_MESSAGE", 0)
    cadence = _cadence_note(data_ts, control_ts)
    # stable machine-readable caveat FLAGS (the prose lives in
    # caveat_notes): gates and run_report branch on the flag strings,
    # never on report text
    caveats = []
    notes = {}
    if cadence:
        caveats.append("phase_cadence")
        notes["phase_cadence"] = cadence["note"]
    # the per-event stream never carries the sim-only chaos counters
    # (trace/drain.py COUNTER_ONLY_EVENTS) — flag it so a gate reading
    # this file knows LINK_DOWN/IWANT_RECOVER totals live in the
    # drained counters (counter_events()), not here
    caveats.append("counter_only_events")
    notes["counter_only_events"] = (
        "LINK_DOWN/IWANT_RECOVER have no TraceEvent record type; their "
        "exact totals come from the device counters "
        "(trace.drain.counter_events), not this stream."
    )
    if not pub:
        caveats.append("no_publishes")
        notes["no_publishes"] = (
            "no PUBLISH_MESSAGE events: delivery ratio and delay "
            "percentiles are undefined for this trace."
        )
    return {
        **({"cadence": cadence} if cadence else {}),
        "caveats": caveats,
        "caveat_notes": notes,
        "events": sum(counts.values()),
        "peers": len(peers),
        "counts": dict(sorted(counts.items())),
        "published": pub,
        "delivered": dlv,
        "duplicates": counts.get("DUPLICATE_MESSAGE", 0),
        "rejected": counts.get("REJECT_MESSAGE", 0),
        "deliveries_per_publish": round(dlv / pub, 3) if pub else None,
        "delay_ns": {
            "p50": percentile(delays, 0.50),
            "p90": percentile(delays, 0.90),
            "p99": percentile(delays, 0.99),
            "max": delays[-1] if delays else None,
            "samples": len(delays),
        },
    }


def artifact_invariants(path: str) -> dict:
    """The ``invariants`` block of a bench artifact's last metric line
    (perf.artifacts readers; INVARIANTS_OFF for legacy lines)."""
    from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines

    recs = load_bench_lines(path)
    # a multi-line artifact may mix checked and unchecked cells — the
    # block of the last line that carries one wins, else the typed OFF
    for rec in reversed(recs):
        if rec.invariants_on:
            return rec.invariants
    return recs[-1].invariants


def artifact_adversary(path: str) -> dict:
    """The ``adversary`` fingerprint block of a bench artifact's last
    metric line (perf.artifacts readers; ADVERSARY_OFF for legacy
    lines and honest-population runs)."""
    from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines

    recs = load_bench_lines(path)
    for rec in reversed(recs):
        if rec.adversary_on:
            return rec.adversary
    return recs[-1].adversary


def artifact_execution(path: str) -> dict:
    """The ``execution`` fingerprint block (round 14: scan on/off,
    segment length, dispatches per window, mesh shape) of a bench
    artifact's last metric line; legacy lines read back
    perf.artifacts.SCAN_OFF (scan: null = unrecorded)."""
    from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines

    recs = load_bench_lines(path)
    for rec in reversed(recs):
        if rec.scanned is not None:
            return rec.execution
    return recs[-1].execution


def artifact_params(path: str) -> dict:
    """The ``params`` fingerprint block (round 16: the traced-vs-static
    config split — which knobs rode the compiled program as the lifted
    ScoreParams plane) of a bench artifact's last metric line; legacy
    lines read back perf.artifacts.PARAMS_STATIC (recorded: false)."""
    from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines

    recs = load_bench_lines(path)
    for rec in reversed(recs):
        if rec.params.get("recorded"):
            return rec.params
    return recs[-1].params


def artifact_service(path: str) -> dict:
    """The ``service`` fingerprint block (round 17: was the run driven
    by the supervised service loop — checkpoint quantum, retention,
    armed probes, recoveries performed) of a bench artifact's last
    metric line; legacy lines read back perf.artifacts.SERVICE_OFF."""
    from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines

    recs = load_bench_lines(path)
    for rec in reversed(recs):
        if rec.service_on:
            return rec.service
    return recs[-1].service


def artifact_dynamics(path: str) -> dict:
    """The ``dynamics`` fingerprint block (round 22: did the overlay
    mutate under the measurement — mutation dispatches, write-row
    budget, kills/joins/rewires, schedule hash) of a bench artifact's
    last metric line; legacy lines read back perf.artifacts.
    DYNAMICS_OFF (frozen overlay)."""
    from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines

    recs = load_bench_lines(path)
    for rec in reversed(recs):
        if rec.dynamics_on:
            return rec.dynamics
    return recs[-1].dynamics


def artifact_topology(path: str) -> dict:
    """The ``topology`` fingerprint block (round 18: which generated
    graph the cell ran on — generator/params, E, degree stats, geo link
    classes, workload pattern) of a bench artifact's last metric line;
    legacy lines read back perf.artifacts.TOPOLOGY_BANDED (the banded
    bench ring, recorded: false)."""
    from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines

    recs = load_bench_lines(path)
    for rec in reversed(recs):
        if rec.topology_recorded:
            return rec.topology
    return recs[-1].topology


def artifact_router(path: str) -> dict:
    """The ``router`` fingerprint block (round 24: which protocol
    generation cut the number — v1.1 | v1.2-IDONTWANT — plus the choke
    decision rule and latency ring depth) of a bench artifact's last
    metric line; legacy lines read back perf.artifacts.ROUTER_V11
    (plain v1.1 semantics, which every pre-round-24 build ran)."""
    from go_libp2p_pubsub_tpu.perf.artifacts import load_bench_lines

    recs = load_bench_lines(path)
    for rec in reversed(recs):
        if rec.router_on:
            return rec.router
    return recs[-1].router


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("tracefile")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--format", choices=("auto", "json", "pb"), default="auto")
    ap.add_argument("--artifact", metavar="RUN.json",
                    help="bench artifact of the same run: report its "
                         "schema-v3 invariants and adversary blocks "
                         "(legacy artifacts read back INVARIANTS_OFF / "
                         "ADVERSARY_OFF)")
    args = ap.parse_args()

    stats = summarize(read_events(args.tracefile, args.format))
    if args.artifact:
        stats["invariants"] = artifact_invariants(args.artifact)
        stats["adversary"] = artifact_adversary(args.artifact)
        stats["execution"] = artifact_execution(args.artifact)
        stats["params"] = artifact_params(args.artifact)
        stats["service"] = artifact_service(args.artifact)
        stats["topology"] = artifact_topology(args.artifact)
        stats["dynamics"] = artifact_dynamics(args.artifact)
        stats["router"] = artifact_router(args.artifact)
    if args.json:
        print(json.dumps(stats))
        return
    print(f"events: {stats['events']}   peers: {stats['peers']}")
    for name, c in stats["counts"].items():
        print(f"  {name:20s} {c}")
    print(
        f"published {stats['published']}  delivered {stats['delivered']}  "
        f"dup {stats['duplicates']}  rejected {stats['rejected']}  "
        f"deliveries/publish {stats['deliveries_per_publish']}"
    )
    d = stats["delay_ns"]
    ms = lambda v: None if v is None else round(v / 1e6, 3)
    print(
        f"propagation delay (ms): p50={ms(d['p50'])} p90={ms(d['p90'])} "
        f"p99={ms(d['p99'])} max={ms(d['max'])} (n={d['samples']})"
    )
    if "cadence" in stats:
        c = stats["cadence"]
        print(
            f"cadence: phase trace, ~{c['rounds_per_phase_estimate']} "
            f"rounds/phase — {c['note']}"
        )
    if stats.get("caveats"):
        print("caveats: " + ", ".join(stats["caveats"]))
    if "invariants" in stats:
        iv = stats["invariants"]
        if iv.get("enabled"):
            print(
                f"invariants: {iv['checked']} property evaluations, "
                f"{iv['violated']} violated, last checked round "
                f"{iv['last_checked_round']} "
                f"({len(iv.get('properties', []))} properties, engine "
                f"{iv.get('engine')})"
            )
        else:
            print("invariants: INVARIANTS_OFF (artifact predates the "
                  "oracle plane or the run checked nothing)")
    if "execution" in stats:
        ex = stats["execution"]
        if ex.get("scan") is None:
            print("execution: SCAN_OFF sentinel (artifact predates the "
                  "round-14 execution block — dispatch shape unrecorded)")
        else:
            print(
                f"execution: scan={ex['scan']}, "
                f"{ex.get('dispatches_per_window')} dispatch(es) per "
                f"{ex.get('segment_rounds')}-round window "
                f"(mesh {ex.get('mesh_shape')}, unroll {ex.get('unroll')}, "
                f"check_every {ex.get('check_every')})"
            )
    if "params" in stats:
        pm = stats["params"]
        if not pm.get("recorded"):
            print("params: PARAMS_STATIC sentinel (artifact predates the "
                  "round-16 score lift — every knob was a baked static)")
        elif pm.get("lifted"):
            print(
                f"params: LIFTED — {len(pm.get('traced', []))} score "
                "fields rode the traced ScoreParams plane "
                "(recompile-free sweeps; LIFT_AUDIT.json has the proof)"
            )
        else:
            print("params: all static (recorded; nothing lifted)")
    if "service" in stats:
        sv = stats["service"]
        if sv.get("enabled"):
            ret = sv.get("retention", {})
            print(
                f"service: SUPERVISED — {sv.get('segments')} segments of "
                f"{sv.get('segment_rounds')} rounds, retention keep_last="
                f"{ret.get('keep_last')} keep_every={ret.get('keep_every')}"
                f", probes {sv.get('probes')}, {sv.get('recoveries')} "
                f"recovery(ies), {sv.get('resumes')} resume(s)"
            )
        else:
            print("service: SERVICE_OFF (bare window/loop run, or the "
                  "artifact predates the supervised service loop)")
    if "topology" in stats:
        tp = stats["topology"]
        if tp.get("recorded"):
            print(
                f"topology: {tp.get('generator')} ({tp.get('family')}) — "
                f"E={tp.get('n_edges')}, mean degree "
                f"{tp.get('mean_degree')} / cap {tp.get('max_degree')} "
                f"(density {tp.get('density')}), "
                f"workload {tp.get('workload_pattern') or 'steady'}"
                + (f", link classes {tp.get('link_classes')}"
                   if tp.get("link_classes") else "")
            )
        else:
            print("topology: TOPOLOGY_BANDED sentinel (the banded bench "
                  "ring; artifact predates the round-18 topology block)")
    if "dynamics" in stats:
        dy = stats["dynamics"]
        if dy.get("enabled"):
            print(
                f"dynamics: MUTATING overlay — "
                f"{dy.get('mutation_dispatches')} mutation dispatch(es) "
                f"of <= {dy.get('writes_per_dispatch')} write rows, "
                f"{dy.get('kills')} kill(s) / {dy.get('joins')} join(s) "
                f"/ {dy.get('rewires')} rewire(s), schedule "
                f"{(dy.get('schedule_hash') or '')[:16]}"
            )
        else:
            print("dynamics: DYNAMICS_OFF (frozen overlay, or the "
                  "artifact predates the round-22 dynamic plane)")
    if "router" in stats:
        rt = stats["router"]
        if rt.get("enabled"):
            bits = [f"protocol {rt.get('protocol')}"]
            if rt.get("idontwant"):
                bits.append(f"idontwant<= {rt.get('idontwant_threshold')}")
            if rt.get("choke"):
                bits.append(
                    f"choke ema={rt.get('choke_ema_alpha')} "
                    f"[{rt.get('unchoke_threshold')}, "
                    f"{rt.get('choke_threshold')}] "
                    f"max/hb={rt.get('choke_max_per_hb')}")
            bits.append(f"latency ring L={rt.get('latency_rounds')}")
            print("router: " + ", ".join(bits))
        else:
            print("router: ROUTER_V11 (plain v1.1 semantics, or the "
                  "artifact predates the round-24 router plane)")
    if "adversary" in stats:
        av = stats["adversary"]
        if av.get("enabled"):
            print(
                f"adversary: {av['n_sybils']} sybils, behaviors "
                f"{av.get('behaviors')}, onset {av.get('onset')} "
                f"stop {av.get('stop')} (population "
                f"{av.get('population')}, scenario {av.get('scenario')})"
            )
        else:
            print("adversary: ADVERSARY_OFF (honest population, or the "
                  "artifact predates the adversary plane)")


if __name__ == "__main__":
    main()
