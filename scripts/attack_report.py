"""Adversary-plane attack runner + the ``make attack-smoke`` gate.

Reproduces the GossipSub v1.1 hardening paper's attack evaluation
(arXiv:2007.02754) as ensemble bands: vectorized attacker populations
(chaos/adversary.py, docs/DESIGN.md §13) run INSIDE the same jitted
steps as the honest network, S sims per cell as one vmapped program
(ensemble plane), with the invariant oracle hook (docs/DESIGN.md §12)
ENABLED — the paper's strongest claim is protocol conformance *under*
attack, so every cell asserts zero property violations.

  * **sybil-flood** — a 20% sybil faction running the full suite
    (drop-on-forward + lie-in-IHAVE + graft-spam + self-promotion) on
    a lossy wire (i.i.d. flap — the chaos plane composes), PAIRED per
    sim against an attack-free ablation on IDENTICAL fault/PRNG
    streams. Gates: honest delivery stays within band of the ablation
    in every sim; attacker-as-receiver delivery separates below honest
    delivery in every sim (graylisted peers stop being served); the
    honest population's median score of attacker edges lands below the
    graylist threshold while honest-edge medians stay >= 0 — the
    paper's score-isolation figure as a per-sim gate.
  * **eclipse** — a target set whose topology neighborhood is half
    sybil (AttackScenario surround placement): graft-spam toward the
    targets takes their meshes over, drop-on-forward starves them, and
    the scoring machinery (P3 deficit -> prune -> graylist -> spam
    rejected at ingress) must hand the meshes back — every sim's
    targets recover an all-honest mesh within a bounded tick count
    after onset, with the takeover actually observed first.

``--smoke`` additionally asserts the acceptance invariants plus the
CHAOS-OFF **and ADVERSARY-OFF** compiled HLO kernel census equality vs
the committed PERF_SMOKE baseline (the elision-when-off contract at
the compiler level — the adversary plane must cost literally nothing
when unarmed) and the one-compile cache sentinels, exiting non-zero on
any failure. CPU-only by contract, like the sibling gates.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

#: smoke-shape defaults (the chaos_report sizing logic: big enough for
#: real score dynamics and a recovery tail, small enough for tens of
#: seconds warm)
SYBIL_N = 128
SYBIL_FRACTION = 0.2
SYBIL_ONSET = 12
SYBIL_ROUNDS = 72
SYBIL_LOSS = 0.10
ECLIPSE_N = 96
ECLIPSE_ONSET = 20
ECLIPSE_ROUNDS = 88
ECLIPSE_TARGETS = (0, 1, 2)
#: ticks after onset within which every sim's targets must hold an
#: all-honest mesh again (scoring reacts at heartbeat cadence: P3
#: activation ~8 ticks, deficit prune, graylist, spam rejected)
ECLIPSE_RECOVER_BOUND = 56
SMOKE_SEEDS = 8

#: the measured-delivery window of the sybil cell: messages born while
#: the attack is fully active (post-onset, pre-tail) — delivery is read
#: at run end, so the window only needs to avoid slot recycling (the
#: publish schedule stays under msg_slots)
SYBIL_BORN = (SYBIL_ONSET + 4, SYBIL_ONSET + 24)
#: ablation tolerance: honest delivery under attack must stay within
#: this of the SAME sim's attack-free run (identical fault streams)
SYBIL_ABLATION_TOL = 0.05


def _attack_score_params():
    """P3 deficit + P2 credit + P7 behaviour penalty — the v1.1
    security plane with every attacker-catching term live (the
    weights the smoke's score-isolation gate prices)."""
    from go_libp2p_pubsub_tpu.config import PeerScoreParams, TopicScoreParams

    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0,
        first_message_deliveries_weight=0.5,
        first_message_deliveries_cap=50.0,
        first_message_deliveries_decay=0.9,
        mesh_message_deliveries_weight=-1.0,
        mesh_message_deliveries_decay=0.9,
        mesh_message_deliveries_cap=20.0,
        mesh_message_deliveries_threshold=0.5,
        mesh_message_deliveries_window=2.0,
        mesh_message_deliveries_activation=8.0,
        mesh_failure_penalty_weight=-1.0,
        mesh_failure_penalty_decay=0.9,
    )
    sp = PeerScoreParams(
        topics={0: tp},
        skip_app_specific=True,
        behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=0.0,
        behaviour_penalty_decay=0.9,
        ip_colocation_factor_weight=0.0,
    )
    return tp, sp


def _thresholds():
    from go_libp2p_pubsub_tpu.config import PeerScoreThresholds

    return PeerScoreThresholds(
        gossip_threshold=-2.0,
        publish_threshold=-4.0,
        graylist_threshold=-8.0,
        accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0,
    )


def _overlay_params():
    """Low-degree v1.1 overlay (the chaos-smoke shape): D=3 leaves
    non-mesh neighbors for gossip, K=8 keeps the cells fast."""
    from go_libp2p_pubsub_tpu.config import GossipSubParams

    return GossipSubParams(D=3, Dlo=2, Dhi=4, Dscore=2, Dout=1,
                           history_length=6, history_gossip=4)


def _score_weights_block(tp, sp):
    from go_libp2p_pubsub_tpu.perf.artifacts import score_weights_fingerprint

    return score_weights_fingerprint(
        mesh_message_deliveries_weight=tp.mesh_message_deliveries_weight,
        mesh_failure_penalty_weight=tp.mesh_failure_penalty_weight,
        invalid_message_deliveries_weight=tp.invalid_message_deliveries_weight,
        first_message_deliveries_weight=tp.first_message_deliveries_weight,
        time_in_mesh_weight=tp.time_in_mesh_weight,
        behaviour_penalty_weight=sp.behaviour_penalty_weight,
    )


def _edge_masks(net, is_sybil):
    """(honest->sybil, honest->honest) [N, K] bool edge masks."""
    nbr = np.clip(np.asarray(net.nbr), 0, None)
    ok = np.asarray(net.nbr_ok)
    att = ok & is_sybil[nbr] & ~is_sybil[:, None]
    hon = ok & ~is_sybil[nbr] & ~is_sybil[:, None]
    return att, hon


def _per_sim_medians(scores, edge_mask):
    """[S] medians of a batched [S, N, K] score plane over an edge
    mask."""
    sc = np.asarray(scores)
    return np.asarray([float(np.median(sc[i][edge_mask]))
                       for i in range(sc.shape[0])])


def _honest_publish_schedule(rng, honest_ids, rounds, pub_rounds, width=2):
    """Publish batches drawn from HONEST origins only (an attacker
    origin would withhold its own publish — the measured delivery
    window must start from honest sources, like the paper's)."""
    po = np.full((rounds, width), -1, np.int32)
    for t in range(*pub_rounds):
        po[t] = rng.choice(honest_ids, size=width)
    pt = np.zeros((rounds, width), np.int32)
    pv = np.ones((rounds, width), bool)
    return po, pt, pv


def run_sybil_flood(n=SYBIL_N, fraction=SYBIL_FRACTION, loss=SYBIL_LOSS,
                    onset=SYBIL_ONSET, rounds=SYBIL_ROUNDS, seed=0,
                    seeds=SMOKE_SEEDS, invariants=True):
    """The sybil-flood cell + its paired attack-free ablation.

    Both runs share the topology, subscriptions, publish schedule, sim
    keys (hence chaos fault streams and every sampler stream) — the
    per-sim honest-delivery delta is the ATTACK's causal effect, the
    chaos-smoke pairing discipline applied to an adversary."""
    from go_libp2p_pubsub_tpu import ensemble, graph
    from go_libp2p_pubsub_tpu.chaos import AttackScenario, ChaosConfig
    from go_libp2p_pubsub_tpu.ensemble import stats as estats
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.state import Net

    s = int(seeds)
    topo = graph.random_connect(n, d=4, seed=seed)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    tp, sp = _attack_score_params()
    cc = ChaosConfig(loss_rate=loss)
    cfg = GossipSubConfig.build(_overlay_params(), _thresholds(),
                                score_enabled=True, chaos=cc)
    scenario = AttackScenario(
        n_peers=n, sybil_fraction=fraction,
        behaviors=("drop_forward", "lie_ihave", "graft_spam", "self_promo"),
        onset=onset, seed=seed,
    )
    adv = scenario.build()
    is_sybil = adv.is_sybil
    honest_ids = np.flatnonzero(~is_sybil)
    rng = np.random.default_rng(seed)
    po, pt, pv = _honest_publish_schedule(
        rng, honest_ids, rounds, (2, SYBIL_BORN[1] + 4))
    assert 2 * (SYBIL_BORN[1] + 2) <= 128, "publish volume must not recycle"

    def run_one(adversary, hook):
        # round 14: each side of the pair is ONE scan-window dispatch
        # (S sims x all rounds), the invariant checks folded in
        st0 = GossipSubState.init(net, 128, cfg, score_params=sp, seed=seed)
        step = make_gossipsub_step(cfg, net, score_params=sp,
                                   adversary=adversary)
        ens = ensemble.lift_step(step)
        return ensemble.run_window(
            ens, ensemble.batch_states(st0, s),
            lambda i: (ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
                       ensemble.tile(pv[i], s)),
            rounds, invariants=hook,
        )

    hook = None
    if invariants:
        from go_libp2p_pubsub_tpu.oracle import invariants as oracle_inv

        # the flap generator is active for the whole run, so the
        # delivery-liveness clause is vacuous by the due contract (the
        # chaos flap cell's precedent); every safety property stays
        # live under the attack — the acceptance claim
        hook = oracle_inv.ScanInvariants(
            "gossipsub", net, cfg,
            oracle_inv.InvariantConfig(check_every=8, delivery_window=12),
        )
    arun = run_one(adv, hook)
    brun = run_one(None, None)  # the paired attack-free ablation

    core = arun.states.core
    honest_attack = np.asarray(estats.sim_delivery_ratios(
        core.dlv.first_round, core.msgs.birth, core.msgs.topic,
        core.msgs.origin, net.subscribed, born_in=SYBIL_BORN,
        receivers=~is_sybil))
    sybil_attack = np.asarray(estats.sim_delivery_ratios(
        core.dlv.first_round, core.msgs.birth, core.msgs.topic,
        core.msgs.origin, net.subscribed, born_in=SYBIL_BORN,
        receivers=is_sybil))
    bcore = brun.states.core
    honest_ablation = np.asarray(estats.sim_delivery_ratios(
        bcore.dlv.first_round, bcore.msgs.birth, bcore.msgs.topic,
        bcore.msgs.origin, net.subscribed, born_in=SYBIL_BORN,
        receivers=~is_sybil))
    att_edges, hon_edges = _edge_masks(net, is_sybil)
    att_scores = _per_sim_medians(arun.states.scores, att_edges)
    hon_scores = _per_sim_medians(arun.states.scores, hon_edges)
    out = {
        "n": n, "rounds": rounds, "seeds": s, "onset": onset,
        "born": SYBIL_BORN,
        "chaos": cc, "scenario": scenario, "adversary": adv,
        "score_weights": _score_weights_block(tp, sp),
        "graylist_threshold": _thresholds().graylist_threshold,
        "honest_attack": honest_attack,
        "honest_attack_band": estats.quantile_band(honest_attack),
        "sybil_attack": sybil_attack,
        "sybil_attack_band": estats.quantile_band(sybil_attack),
        "honest_ablation": honest_ablation,
        "honest_ablation_band": estats.quantile_band(honest_ablation),
        "attacker_score_medians": att_scores,
        "attacker_score_band": estats.quantile_band(att_scores),
        "honest_score_medians": hon_scores,
        "honest_score_band": estats.quantile_band(hon_scores),
        "events": np.asarray(core.events),
        "compiles": {"attack": arun.compiles, "ablation": brun.compiles},
    }
    if hook is not None:
        out["invariants"] = arun.invariant_report
        out["invariant_compiles"] = arun.compiles
        out["dispatches"] = arun.dispatches
    return out


def run_eclipse(n=ECLIPSE_N, targets=ECLIPSE_TARGETS, onset=ECLIPSE_ONSET,
                rounds=ECLIPSE_ROUNDS, seed=1, seeds=SMOKE_SEEDS,
                invariants=True):
    """The eclipse/mesh-takeover cell: half of each target's topology
    neighborhood is sybil; graft-spam (restricted to the targets)
    takes the victims' meshes over while drop-on-forward starves them.
    Per-round mesh snapshots measure the takeover and the scoring-
    driven recovery (P3 deficit -> prune -> graylist -> spam rejected
    at ingress -> honest re-graft)."""
    from go_libp2p_pubsub_tpu import ensemble, graph
    from go_libp2p_pubsub_tpu.chaos import AttackScenario
    from go_libp2p_pubsub_tpu.ensemble import stats as estats
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.state import Net

    s = int(seeds)
    topo = graph.random_connect(n, d=6, seed=seed)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    tp, sp = _attack_score_params()
    cfg = GossipSubConfig.build(_overlay_params(), _thresholds(),
                                score_enabled=True)
    scenario = AttackScenario(
        n_peers=n, targets=tuple(targets), surround_targets=True,
        surround_fraction=0.5,
        behaviors=("drop_forward", "graft_spam"),
        onset=onset, seed=seed,
    )
    adv = scenario.build(net)
    is_sybil = adv.is_sybil
    honest_ids = np.flatnonzero(~is_sybil)
    rng = np.random.default_rng(seed)
    po, pt, pv = _honest_publish_schedule(
        rng, honest_ids, rounds, (2, 62))

    st0 = GossipSubState.init(net, 128, cfg, score_params=sp, seed=seed)
    step = make_gossipsub_step(cfg, net, score_params=sp, adversary=adv)
    ens = ensemble.lift_step(step)

    tlist = list(targets)
    nbr = np.clip(np.asarray(net.nbr), 0, None)
    ok = np.asarray(net.nbr_ok)
    syb_edge_t = ok[tlist] & is_sybil[nbr[tlist]]   # [T, K]
    hon_edge_t = ok[tlist] & ~is_sybil[nbr[tlist]]

    # round 14: the per-round takeover series is observed ON DEVICE
    # inside the scan window — same masks, stacked as scan ys
    import jax.numpy as jnp

    t_idx = jnp.asarray(tlist)
    syb_edge_j = jnp.asarray(syb_edge_t)
    hon_edge_j = jnp.asarray(hon_edge_t)

    def observe(states):
        mesh_t = states.mesh[:, t_idx, 0, :]          # [S, T, K]
        return (jnp.sum(mesh_t & syb_edge_j[None], axis=(1, 2)),
                jnp.sum(mesh_t & hon_edge_j[None], axis=(1, 2)))

    hook = None
    if invariants:
        from go_libp2p_pubsub_tpu.oracle import invariants as oracle_inv

        # lossless wire: pre-onset publishes are due end-to-end (the
        # non-vacuous liveness leg); the takeover window gets the
        # fault-scoped grace the due contract defines for active
        # faults — the attack IS the fault here
        w = 12

        def due_fn(tick):
            return oracle_inv.due_vector(
                quiet=(0, onset),
                grace=onset <= tick < onset + ECLIPSE_RECOVER_BOUND,
            )

        hook = oracle_inv.ScanInvariants(
            "gossipsub", net, cfg,
            oracle_inv.InvariantConfig(check_every=8, delivery_window=w),
            due_fn=due_fn,
        )
    run = ensemble.run_window(
        ens, ensemble.batch_states(st0, s),
        lambda i: (ensemble.tile(po[i], s), ensemble.tile(pt[i], s),
                   ensemble.tile(pv[i], s)),
        rounds, observe=observe, invariants=hook,
    )
    syb_series, hon_series = run.observations
    series = [(t + 1, syb_series[t], hon_series[t]) for t in range(rounds)]

    # takeover depth: max sybil share of the targets' mesh edges after
    # onset; recovery: first tick at/after the takeover peak where the
    # targets' meshes are sybil-free AND hold at least one honest edge
    peak_share = np.zeros(s)
    recover_tick = np.full(s, np.nan)
    for i in range(s):
        peak = 0.0
        peak_t = onset
        for t, syb, hon in series:
            if t < onset:
                continue
            tot = syb[i] + hon[i]
            share = syb[i] / tot if tot else 0.0
            if share > peak:
                peak, peak_t = share, t
        peak_share[i] = peak
        for t, syb, hon in series:
            if t >= peak_t and syb[i] == 0 and hon[i] > 0:
                recover_tick[i] = t
                break
    recover_after_onset = recover_tick - onset

    core = run.states.core
    honest_final = np.asarray(estats.sim_delivery_ratios(
        core.dlv.first_round, core.msgs.birth, core.msgs.topic,
        core.msgs.origin, net.subscribed, born_in=(2, onset),
        receivers=~is_sybil))
    out = {
        "n": n, "rounds": rounds, "seeds": s, "onset": onset,
        "targets": tlist, "scenario": scenario, "adversary": adv,
        "score_weights": _score_weights_block(tp, sp),
        "peak_sybil_share": peak_share,
        "peak_band": estats.quantile_band(peak_share),
        "recover_ticks": recover_after_onset,
        "recover_band": estats.quantile_band(recover_after_onset),
        "pre_onset_honest_delivery": honest_final,
        "compiles": run.compiles,
        "events": np.asarray(core.events),
    }
    if hook is not None:
        out["invariants"] = run.invariant_report
        out["invariant_compiles"] = run.compiles
        out["dispatches"] = run.dispatches
    return out


def _emit(metric, value, unit="ratio", chaos=None, chaos_scenario=None,
          adversary=None, attack_scenario=None, score_weights=None,
          extras=None, n_sims=1, invariants=None):
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        BenchRecord,
        adversary_fingerprint,
        chaos_fingerprint,
        dump_record,
        ensemble_fingerprint,
    )

    fp = {
        "chaos": chaos_fingerprint(chaos, chaos_scenario),
        "ensemble": ensemble_fingerprint(n_sims),
        "adversary": adversary_fingerprint(adversary, attack_scenario),
    }
    if score_weights is not None:
        fp["score_weights"] = score_weights
    rec = BenchRecord(
        metric=metric, value=float(value), unit=unit, vs_baseline=0.0,
        schema=3, fingerprint=fp, extras=extras or {},
        invariants_raw=invariants,
    )
    print(dump_record(rec), flush=True)


def _band_extras(band: dict, per_sim) -> dict:
    out = {
        "iqr": [band.get("q25"), band.get("q75")],
        "min": band.get("min"),
        "max": band.get("max"),
        "n_sims": band["n"],
        "n_undefined": band["n_undefined"],
        "per_sim": [None if not np.isfinite(v) else round(float(v), 4)
                    for v in np.asarray(per_sim, np.float64)],
    }
    return out


def _check_invariants(failures, cell, out):
    rep = out.get("invariants")
    if rep is None:
        failures.append(f"{cell}: the invariant hook did not run")
        return None
    if not rep.all_ok:
        failures.append(
            f"{cell}: {rep.violated} invariant violation(s) under attack: "
            f"{rep.violations()}")
    if rep.checked == 0:
        failures.append(f"{cell}: the invariant hook checked nothing")
    if out.get("invariant_compiles") not in (-1, 1):
        failures.append(
            f"{cell}: the checked window ran {out['invariant_compiles']} "
            "compiles (expected exactly 1 — the checker is folded into "
            "the window program)")
    if out.get("dispatches") not in (None, 1):
        failures.append(
            f"{cell}: executed as {out['dispatches']} dispatches "
            "(expected ONE whole-run window)")
    return rep


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="assert the acceptance invariants; exit 1 on failure")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--seeds", type=int, default=SMOKE_SEEDS,
                    help="sims per cell (one vmapped program)")
    ap.add_argument("--no-census", action="store_true",
                    help="skip the adversary-off kernel-census gate")
    args = ap.parse_args(argv)
    if args.seeds < 1:
        ap.error("--seeds must be >= 1")

    # CPU-only by contract (the perf-smoke platform/PRNG pinning)
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    enable_persistent_cache(os.path.join(repo_root(), ".jax_cache"))

    failures = []

    # ---- sybil flood ----------------------------------------------------
    syb = run_sybil_flood(seed=args.seed, seeds=args.seeds)
    rep = _check_invariants(failures, "sybil-flood", syb)
    gray = syb["graylist_threshold"]
    _emit("attack_sybil_honest_delivery", syb["honest_attack_band"]["q50"],
          chaos=syb["chaos"], adversary=syb["adversary"],
          attack_scenario=syb["scenario"],
          score_weights=syb["score_weights"], n_sims=syb["seeds"],
          invariants=rep.artifact_block() if rep is not None else None,
          extras={
              "n_peers": syb["n"], "rounds": syb["rounds"],
              "onset": syb["onset"], "born_window": list(syb["born"]),
              "sybil_delivery_median":
                  round(float(syb["sybil_attack_band"]["q50"]), 4),
              "sybil_delivery_iqr": [syb["sybil_attack_band"].get("q25"),
                                     syb["sybil_attack_band"].get("q75")],
              "honest_ablation_median":
                  round(float(syb["honest_ablation_band"]["q50"]), 4),
              "attacker_score_median":
                  round(float(syb["attacker_score_band"]["q50"]), 4),
              "honest_score_median":
                  round(float(syb["honest_score_band"]["q50"]), 4),
              "graylist_threshold": gray,
              **_band_extras(syb["honest_attack_band"],
                             syb["honest_attack"]),
          })
    # (a) paired per-sim honest-vs-attacker separation + unharmed honest
    sep = syb["honest_attack"] - syb["sybil_attack"]
    if float(sep.min()) <= 0.0:
        failures.append(
            "sybil-flood: honest-vs-attacker delivery separation failed in "
            "at least one sim (per-sim honest-minus-attacker: "
            f"{[round(float(v), 4) for v in sep]})")
    harm = syb["honest_ablation"] - syb["honest_attack"]
    if float(harm.max()) > SYBIL_ABLATION_TOL:
        failures.append(
            "sybil-flood: honest delivery under attack fell more than "
            f"{SYBIL_ABLATION_TOL} below the attack-free ablation on the "
            "same fault stream in at least one sim (per-sim deltas: "
            f"{[round(float(v), 4) for v in harm]})")
    # score isolation, per sim: attackers below the graylist line,
    # honest edges unharmed
    if float(syb["attacker_score_medians"].max()) >= gray:
        failures.append(
            "sybil-flood: attacker median score failed to cross the "
            f"graylist threshold {gray} in at least one sim (per-sim: "
            f"{[round(float(v), 2) for v in syb['attacker_score_medians']]})")
    if float(syb["honest_score_medians"].min()) < 0.0:
        failures.append(
            "sybil-flood: an honest-edge median score went negative "
            "(per-sim: "
            f"{[round(float(v), 2) for v in syb['honest_score_medians']]})")
    for name, nc in sorted(syb["compiles"].items()):
        if nc not in (-1, 1):
            failures.append(
                f"sybil-flood: {name} ensemble ran {nc} compiles "
                "(expected exactly 1)")

    # ---- eclipse --------------------------------------------------------
    ecl = run_eclipse(seed=args.seed + 1, seeds=args.seeds)
    rep = _check_invariants(failures, "eclipse", ecl)
    _emit("attack_eclipse_recovery_ticks", ecl["recover_band"]["q50"],
          unit="rounds", adversary=ecl["adversary"],
          attack_scenario=ecl["scenario"],
          score_weights=ecl["score_weights"], n_sims=ecl["seeds"],
          invariants=rep.artifact_block() if rep is not None else None,
          extras={
              "n_peers": ecl["n"], "rounds": ecl["rounds"],
              "onset": ecl["onset"], "targets": ecl["targets"],
              "peak_sybil_share_median":
                  round(float(ecl["peak_band"]["q50"]), 4),
              "peak_sybil_share_min":
                  round(float(ecl["peak_band"]["min"]), 4),
              "recover_bound": ECLIPSE_RECOVER_BOUND,
              **_band_extras(ecl["recover_band"], ecl["recover_ticks"]),
          })
    # (b) the takeover must be observed, then recovered from — bounded,
    # in EVERY sim: every stream shows real sybil mesh presence at the
    # targets, the MEDIAN stream a sybil-majority mesh (takeover depth
    # varies with the random overlay draw; recovery is the hard gate)
    peak = ecl["peak_sybil_share"]
    if float(peak.min()) <= 0.25:
        failures.append(
            "eclipse: the attack never took a meaningful share of the "
            "targets' meshes in at least one sim (per-sim peak shares: "
            f"{[round(float(v), 3) for v in peak]})")
    if float(ecl["peak_band"]["q50"]) < 0.5:
        failures.append(
            "eclipse: the median stream never reached a sybil-majority "
            "mesh at the targets (median peak share "
            f"{ecl['peak_band']['q50']:.3f})")
    if ecl["recover_band"]["n_undefined"] > 0:
        failures.append(
            f"eclipse: the targets' meshes never recovered an all-honest "
            f"state in {ecl['recover_band']['n_undefined']}/{ecl['seeds']} "
            "sims")
    elif float(np.nanmax(ecl["recover_ticks"])) > ECLIPSE_RECOVER_BOUND:
        failures.append(
            "eclipse: mesh recovery exceeded the "
            f"{ECLIPSE_RECOVER_BOUND}-tick bound in at least one sim "
            "(per-sim ticks after onset: "
            f"{[round(float(v), 1) for v in ecl['recover_ticks']]})")
    if float(ecl["pre_onset_honest_delivery"].min()) < 1.0:
        failures.append(
            "eclipse: pre-onset publishes failed to fully deliver to the "
            "honest population in at least one sim")
    if ecl["compiles"] not in (-1, 1):
        failures.append(
            f"eclipse: ensemble ran {ecl['compiles']} compiles "
            "(expected exactly 1)")

    # ---- (d) adversary-off census + elision ----------------------------
    if not args.no_census:
        import chaos_report

        census = chaos_report.check_census()
        print(json.dumps({"adversary_off_kernel_census": census}),
              flush=True)
        if not census["equal"]:
            failures.append(
                f"adversary-off kernel census {census['total']} != "
                f"on-image baseline {census['on_image']} — the "
                "elision-when-off contract broke (committed pin "
                f"{census['committed']} is informational)")

    if args.smoke and failures:
        for f in failures:
            print(f"attack-smoke FAIL: {f}", file=sys.stderr)
        print(json.dumps({"attack_smoke": "FAIL", "errors": len(failures)}))
        return 1
    print(json.dumps({"attack_smoke": "PASS" if not failures else "REPORT",
                      "warnings": failures}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
