#!/usr/bin/env python
"""Whole-run-window gate (``make scan-smoke``; docs/DESIGN.md §14).

Builds the smoke-shape bench window — N=12.5k peers, phase engine at
r=16, 64 rounds — with ALL THREE observability planes enabled (i.i.d.
chaos link flaps, the telemetry panel recorder, the folded invariant
oracle) and asserts the round-14 whole-run-compilation contract:

  1. **one dispatch** — the entire window (4 phase dispatches' worth of
     rounds, checks included) executes as ONE XLA program invocation:
     the window jit's compile-cache grows by exactly 1 AND the window
     callable is entered exactly once, under
     ``jax.transfer_guard('disallow')`` (publish schedules and
     invariant due rows are materialized on device beforehand; the
     violation masks and telemetry panel ride the program).
  2. **observability intact** — zero invariant violations, and the
     telemetry panel reconciles against the drained counters
     bit-for-bit (the §11 anchor, now inside a scanned window).
  3. **measurably faster** — warm-vs-warm against the committed
     per-dispatch path (the same step driven phase-by-phase from
     Python with the per-dispatch InvariantHook): the scanned window
     must be at least SCAN_SMOKE_MIN_SPEEDUP (default 1.0) times the
     per-dispatch rate, and at least SCAN_SMOKE_TOL × the committed
     SCAN_SMOKE.json floor (both rates and the implied
     per-dispatch-overhead are recorded in the artifact).
  4. **projection refresh** — the v5e-8 projection recomputed from the
     committed BENCH_r05 shard rates with the new
     ``dispatch_overhead_ms`` term parameterized on the overhead this
     run measured, gated on the 2-D (sims × peers) multichip dryrun
     artifact (MULTICHIP_r06.json — scripts/mesh2d_dryrun.py).

``SCAN_SMOKE_UPDATE=1`` rewrites SCAN_SMOKE.json from this run.
CPU-only by contract, bench PRNG, persistent compile cache — the
perf-smoke gate policy. Shape knobs: SCAN_SMOKE_N / _R / _ROUNDS.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))

import numpy as np  # noqa: E402

BASELINE_NAME = "SCAN_SMOKE.json"
MULTICHIP_2D_NAME = "MULTICHIP_r06.json"
SMOKE_N = 12_500
SMOKE_R = 16
SMOKE_ROUNDS = 64
SMOKE_LOSS = 0.05
CHECK_EVERY = 2          # invariant checks per window: dispatches 2 and 4
TIMING_REPS = 3
#: floor: fraction of the committed scanned rate a fresh run must reach
DEFAULT_TOL = 0.4
#: the acceptance bar: scanned must beat the per-dispatch path
DEFAULT_MIN_SPEEDUP = 1.0


def build_cell(n: int, r: int, rounds: int, loss: float, seed: int = 0):
    """The bench workload (ring-lattice d=8, live scoring, honest-net
    weights) with chaos + telemetry enabled — build_bench's decision
    table plus the fault generator the bench build deliberately lacks."""
    import dataclasses as _dc

    from go_libp2p_pubsub_tpu import graph
    from go_libp2p_pubsub_tpu.chaos import ChaosConfig
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub_phase import (
        make_gossipsub_phase_step,
    )
    from go_libp2p_pubsub_tpu.perf.sweep import bench_score_params
    from go_libp2p_pubsub_tpu.state import Net
    from go_libp2p_pubsub_tpu.telemetry import TelemetryConfig

    topo = graph.ring_lattice(n, d=8)
    net = Net.build(topo, graph.subscribe_all(n, 1))
    _tp, sp = bench_score_params("default", 1)
    params = _dc.replace(GossipSubParams(), flood_publish=False)
    cfg = GossipSubConfig.build(
        params, PeerScoreThresholds(), score_enabled=True,
        heartbeat_every=r, chaos=ChaosConfig(loss_rate=loss),
    )
    # live counters: the telemetry reconciliation anchor needs them
    cfg = _dc.replace(cfg, count_events=True, fanout_slots=0)
    tcfg = TelemetryConfig(rows=rounds // r)
    st0 = GossipSubState.init(net, 64, cfg, score_params=sp, seed=seed,
                              telemetry=tcfg)
    step = make_gossipsub_phase_step(cfg, net, r, score_params=sp,
                                     telemetry=tcfg)

    def fresh():
        return GossipSubState.init(net, 64, cfg, score_params=sp,
                                   seed=seed, telemetry=tcfg)

    return net, cfg, st0, step, fresh


def run_gate(n: int, r: int, rounds: int, loss: float) -> dict:
    import jax
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.driver import make_window
    from go_libp2p_pubsub_tpu.oracle import invariants as oracle_inv
    from go_libp2p_pubsub_tpu.telemetry import reconcile

    assert rounds % r == 0
    d = rounds // r
    failures: list[str] = []
    net, cfg, st0, step, fresh = build_cell(n, r, rounds, loss)

    rng = np.random.default_rng(0)
    po = jnp.asarray(rng.integers(0, n, size=(d, r, 4)).astype(np.int32))
    pt = jnp.asarray(np.zeros((d, r, 4), np.int32))
    pv = jnp.asarray(np.ones((d, r, 4), bool))

    spec = oracle_inv.ScanInvariants(
        "phase", net, cfg,
        oracle_inv.InvariantConfig(check_every=CHECK_EVERY,
                                   delivery_window=24),
        batched=False, rounds_per_step=r,
    )
    due = spec.precompute(d)
    window = make_window(step, heartbeat=[True], check=spec.check,
                         check_every=CHECK_EVERY)

    def cache_size():
        try:
            return int(window._cache_size())
        except Exception:  # pragma: no cover
            return None

    # --- the acceptance run: ONE dispatch, guarded window ------------
    # (one INVOCATION is by construction — the whole run is the single
    # window call below; the compile-count sentinel is what verifies
    # the program really covers all of it)
    before = cache_size()
    st_guarded = fresh()
    with jax.transfer_guard("disallow"):
        st_fin, ys = window(st_guarded, (po, pt, pv), due)
        jax.block_until_ready((st_fin, ys))
    after = cache_size()
    compiles = -1 if before is None or after is None else after - before
    if compiles not in (-1, 1):
        failures.append(
            f"one-dispatch: the window compiled {compiles} times "
            "(expected exactly 1 — chaos + telemetry + checker are one "
            "program)")
    rep = spec.report(ys["ok"])
    if not rep.all_ok:
        failures.append(
            f"invariants: {rep.violated}/{rep.checked} property "
            f"evaluations failed inside the window: {rep.violations(8)}")
    if rep.n_checks != d // CHECK_EVERY:
        failures.append(
            f"invariants: {rep.n_checks} checks recorded, expected "
            f"{d // CHECK_EVERY}")
    panel = np.asarray(st_fin.core.telem.panel)
    mism = reconcile(panel, np.asarray(st_fin.core.events))
    if mism:
        failures.append(
            "telemetry: drain-vs-timeline reconciliation failed inside "
            "the scanned window: " + "; ".join(mism[:4]))

    # --- warm-vs-warm: scanned window vs the per-dispatch path -------
    # the committed pre-round-14 execution: one program per phase from
    # Python, the invariant checks as separate hook dispatches
    hook = oracle_inv.InvariantHook(
        "phase", net, cfg,
        oracle_inv.InvariantConfig(check_every=CHECK_EVERY,
                                   delivery_window=24),
        batched=False, rounds_per_step=r,
    )
    hook.precompute(d)

    def run_loop():
        st = fresh()
        hook.reset()
        t0 = time.perf_counter()
        for p in range(d):
            st = step(st, po[p], pt[p], pv[p], do_heartbeat=True)
            hook.on_step(p, st)
        jax.block_until_ready(st)
        return time.perf_counter() - t0

    def run_scan():
        st = fresh()
        t0 = time.perf_counter()
        st, ys_ = window(st, (po, pt, pv), due)
        jax.block_until_ready((st, ys_))
        return time.perf_counter() - t0

    run_loop()  # warm the per-dispatch program (+ hook checker jit)
    pairs = [(run_scan(), run_loop()) for _ in range(TIMING_REPS)]
    t_scan = min(p[0] for p in pairs)
    t_loop = min(p[1] for p in pairs)
    scan_rate = rounds / t_scan
    loop_rate = rounds / t_loop
    speedup = scan_rate / loop_rate if loop_rate else float("inf")
    # the measured per-dispatch overhead the projection's new term is
    # parameterized on: the warm time delta amortized over the loop's
    # extra dispatches (d phase programs + d/ce checker programs vs 1)
    extra_dispatches = d + d // CHECK_EVERY - 1
    overhead_ms = max(0.0, (t_loop - t_scan) * 1000.0 / extra_dispatches)
    return {
        "failures": failures,
        "n_peers": n,
        "rounds_per_phase": r,
        "rounds": rounds,
        "loss": loss,
        "check_every": CHECK_EVERY,
        "dispatches_per_window": 1,
        "window_compiles": compiles,
        "invariant_checks": rep.n_checks,
        "scanned_rounds_per_sec": round(scan_rate, 2),
        "per_dispatch_rounds_per_sec": round(loop_rate, 2),
        "speedup": round(speedup, 4),
        "dispatch_overhead_ms": round(overhead_ms, 4),
        "window_dispatches_per_sec": round(1.0 / t_scan, 4),
    }


def refresh_projection(root: str, res: dict) -> dict:
    """The v5e-8 projection recomputed with the dispatch term: the
    round-5 shard rates + the 2-D multichip dryrun gate + the overhead
    this run measured, for the scanned (1 dispatch/window) vs
    per-dispatch (1/r) execution shapes."""
    from go_libp2p_pubsub_tpu.perf.projection import project_from_artifacts

    bench = os.path.join(root, "BENCH_r05.json")
    multi2d = os.path.join(root, MULTICHIP_2D_NAME)
    if not os.path.exists(multi2d):
        multi2d = os.path.join(root, "MULTICHIP_r05.json")
    if not (os.path.exists(bench) and os.path.exists(multi2d)):
        return {"skipped": "no committed bench/multichip artifacts"}
    ov = res["dispatch_overhead_ms"]
    try:
        scanned = project_from_artifacts(
            bench, multi2d, dispatch_overhead_ms=ov,
            dispatches_per_round=1.0 / res["rounds"])
        # per-dispatch = one program per phase at the PROJECTION's own
        # cadence (the round-5 shard table is r=16), not this run's r
        per_dispatch = project_from_artifacts(
            bench, multi2d, dispatch_overhead_ms=ov,
            dispatches_per_round=1.0 / scanned.rounds_per_phase)
    except ValueError as e:
        # a committed-but-failed dryrun (ok=false) must surface as a
        # gate failure, not an unhandled traceback
        return {"error": str(e),
                "multichip_artifact": os.path.basename(multi2d)}
    return {
        "multichip_artifact": os.path.basename(multi2d),
        "dispatch_overhead_ms": ov,
        "scanned": scanned.summary(),
        "per_dispatch": per_dispatch.summary(),
    }


def emit_artifact(res: dict, projection: dict) -> None:
    from go_libp2p_pubsub_tpu.perf.artifacts import (
        BenchRecord,
        chaos_fingerprint,
        dump_record,
        execution_fingerprint,
    )
    from go_libp2p_pubsub_tpu.chaos import ChaosConfig

    rec = BenchRecord(
        metric=(f"scan_window_delivery_rounds_per_sec_"
                f"n{res['n_peers']}_phase{res['rounds_per_phase']}"),
        value=res["scanned_rounds_per_sec"],
        unit="delivery-rounds/s",
        vs_baseline=0.0,
        schema=3,
        fingerprint={
            "chaos": chaos_fingerprint(
                ChaosConfig(loss_rate=res["loss"])),
            "execution": execution_fingerprint(
                scan=True, segment_rounds=res["rounds"],
                dispatches_per_window=res["dispatches_per_window"],
                rounds_per_dispatch=res["rounds"],
                check_every=res["check_every"],
            ),
        },
        extras={
            "per_dispatch_rounds_per_sec":
                res["per_dispatch_rounds_per_sec"],
            "speedup": res["speedup"],
            "dispatch_overhead_ms": res["dispatch_overhead_ms"],
            "projection": projection,
        },
    )
    print(dump_record(rec), flush=True)


def check_baseline(root: str, res: dict) -> list[str]:
    path = os.path.join(root, BASELINE_NAME)
    if not os.path.exists(path) or os.environ.get("SCAN_SMOKE_UPDATE"):
        return []
    with open(path) as f:
        base = json.load(f)
    if (int(base.get("n_peers", res["n_peers"])) != res["n_peers"]
            or int(base.get("rounds", res["rounds"])) != res["rounds"]
            or int(base.get("rounds_per_phase", res["rounds_per_phase"]))
            != res["rounds_per_phase"]):
        return []  # reshape run: the committed rates are shape-specific
    tol = float(os.environ.get("SCAN_SMOKE_TOL", DEFAULT_TOL))
    committed = base.get("scanned_rounds_per_sec")
    out = []
    if committed and res["scanned_rounds_per_sec"] < tol * committed:
        out.append(
            f"scanned window rate regressed: "
            f"{res['scanned_rounds_per_sec']:.1f} < {tol:.2f} x committed "
            f"{committed:.1f} rounds/s ({BASELINE_NAME}; SCAN_SMOKE_TOL "
            "overrides, SCAN_SMOKE_UPDATE=1 rewrites)")
    return out


def write_baseline(root: str, res: dict, projection: dict) -> str:
    path = os.path.join(root, BASELINE_NAME)
    doc = {
        "schema": 1,
        "note": (
            "whole-run-window smoke baseline (scripts/scan_smoke.py); "
            "SCAN_SMOKE_UPDATE=1 rewrites. scanned_* is the ONE-dispatch "
            "window (chaos + telemetry + folded invariants), "
            "per_dispatch_* the same build driven phase-by-phase from "
            "Python with the hook — both warm, min over reps on the "
            "gate machine. dispatch_overhead_ms is the measured per-"
            "dispatch host cost the projection's round-14 term uses."),
        **{k: res[k] for k in (
            "n_peers", "rounds_per_phase", "rounds", "check_every",
            "scanned_rounds_per_sec", "per_dispatch_rounds_per_sec",
            "speedup", "dispatch_overhead_ms",
            "window_dispatches_per_sec")},
        "projection": projection,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="exit non-zero on any gate failure")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")
    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache
    from go_libp2p_pubsub_tpu.perf.regress import repo_root

    root = repo_root()
    enable_persistent_cache(os.path.join(root, ".jax_cache"))

    n = int(os.environ.get("SCAN_SMOKE_N", SMOKE_N))
    r = int(os.environ.get("SCAN_SMOKE_R", SMOKE_R))
    rounds = int(os.environ.get("SCAN_SMOKE_ROUNDS", SMOKE_ROUNDS))
    loss = float(os.environ.get("SCAN_SMOKE_LOSS", SMOKE_LOSS))

    res = run_gate(n, r, rounds, loss)
    failures = res.pop("failures")
    min_speedup = float(os.environ.get("SCAN_SMOKE_MIN_SPEEDUP",
                                       DEFAULT_MIN_SPEEDUP))
    if res["speedup"] < min_speedup:
        failures.append(
            f"scanned window is not faster than the per-dispatch path: "
            f"{res['scanned_rounds_per_sec']:.1f} vs "
            f"{res['per_dispatch_rounds_per_sec']:.1f} rounds/s "
            f"(speedup {res['speedup']:.3f} < {min_speedup}; warm-vs-warm"
            ", min over reps)")

    projection = refresh_projection(root, res)
    if "error" in projection:
        failures.append(
            f"projection refresh failed on "
            f"{projection['multichip_artifact']}: {projection['error']} "
            "(re-run scripts/mesh2d_dryrun.py --write)")
    elif "skipped" not in projection:
        mc = projection["multichip_artifact"]
        if mc != MULTICHIP_2D_NAME:
            failures.append(
                f"projection fell back to {mc} — the 2-D (sims x peers) "
                f"dryrun artifact {MULTICHIP_2D_NAME} is missing or not "
                "ok (run scripts/mesh2d_dryrun.py)")
    emit_artifact(res, projection)
    failures += check_baseline(root, res)
    if os.environ.get("SCAN_SMOKE_UPDATE") and not failures:
        print(f"wrote {write_baseline(root, res, projection)}")

    summary = {"scan_smoke": "PASS" if not failures else "FAIL", **res,
               "failures": failures}
    if args.smoke and failures:
        for f in failures:
            print(f"scan-smoke FAIL: {f}", file=sys.stderr)
        print(json.dumps(summary))
        return 1
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
