"""The ``make analyze`` tune leg: the search space's legality proof.

Asserts that every box constraint in ``tune/space.py`` decodes inside
``config.py``'s accepted region — the legality-by-construction claim
the evaluation loop relies on (an illegal candidate would abort a
generation mid-search). :func:`tune.space.check_space` materializes
every box corner (each knob pinned to lo/hi with the others mid, plus
the all-lo / all-hi / mid genomes) and a seeded uniform sweep through
the REAL validators (``GossipSubParams.validate()`` /
``PeerScoreParams.validate()`` / ``PeerScoreThresholds.validate()``),
and proves the defaults-as-candidate-0 round-trip.

Pure host-side config arithmetic — no jax import, no device, <1 s.
The doctored-space negative test (tests/test_tune.py) calls
check_space with an out-of-region box and asserts it fails.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_RANDOM = 64


def main(argv=None) -> int:
    from go_libp2p_pubsub_tpu.tune.fitness import sybil_profile
    from go_libp2p_pubsub_tpu.tune.space import (
        _corner_genomes,
        check_space,
        default_space,
    )

    space = default_space()
    profile = sybil_profile()
    failures = check_space(space, profile, n_random=N_RANDOM, seed=0)

    summary = {
        "tune_check": "FAIL" if failures else "PASS",
        "knobs": space.dim,
        "space": space.fingerprint(),
        "corners": int(_corner_genomes(space).shape[0]),
        "random_points": N_RANDOM,
    }
    if failures:
        for f in failures:
            print(f"tune-check FAIL: {f}", file=sys.stderr)
    print(json.dumps(summary))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
