"""`make choke-smoke`: the router-plane protocol A/B gate (round 24).

Four paired cells on ONE latency-classed power-law graph (identical
edge list, publish schedule, chaos and PRNG streams — the layout of
`make topo-smoke`'s pairing, applied to protocol generations), all as
S-sim ensemble runs so every gate is per-sim:

  * **A** — GossipSub v1.1 (``router=None``, the elision baseline);
  * **B** — v1.2 IDONTWANT suppression (docs/DESIGN.md §24a);
  * **D** — v1.2 + the depth-L latency ring (§24c): per-edge integer
    delays from ``topo.link_delay_plane`` make delivery order
    heterogeneous — the cell choking has something to learn on;
  * **C** — D plus the episub-style lazy-choke router (§24b), the
    invariant hook armed (the round-24 ``choke-wf`` /
    ``no-choke-below-dlo`` properties ride the standard catalog) —
    plus a CSR arm of C (the ring rides the CSR-resident tier flat
    as [E, L, W]).

The gates:

  1. **v1.2 exactness anchor** (B vs A, per sim): the delivery plane
     is BIT-IDENTICAL (equal deliveries, equal first_round stamps) and
     the duplicate count strictly drops on EVERY sim — suppression
     removes exactly the traffic that was going to be thrown away
     (``dontwant ⊆ have`` by construction). The committed
     ``dup_cut_floor`` pins the suppression depth.
  2. **choke latency-tail cut** (C vs D, per sim, at equal delivery):
     both cells drain to >= 99% coverage and the delivery-latency p95,
     pooled over the PAIRED common support (pairs both cells
     delivered, so neither cell's rare protocol-faithful holes censor
     the other's tail), drops — choking demotes consistently-late
     (high-delay-class) mesh links to IHAVE-only, and the gossip
     control path's fixed RTT beats the slow links' ring delay. The
     committed ``tail_cut_floor`` pins the win.
  3. **zero invariant violations** on the choked cell, with the two
     choke properties registered and checked (they are seeded-negative
     -tested in tests/test_invariants.py).
  4. **one compile per cell** + **layout parity**: C's CSR arm counts
     the same events bit-for-bit.
  5. **router-off census**: the chaos-off compiled kernel census still
     equals the on-image baseline (the chaos-report census leg,
     reused) — the router plane is opt-in, kernel-for-kernel; and the
     v1.1 cell's per-sim counters equal the COMMITTED pin bit-for-bit
     (router growth must never move router-off behavior).

CHOKE_SMOKE_UPDATE=1 rewrites CHOKE_SMOKE.json from a green run
(floors committed at half the measured margin).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

BASELINE_PATH = os.path.join(REPO, "CHOKE_SMOKE.json")

N = int(os.environ.get("CHOKE_SMOKE_N", 256))
MAX_DEGREE = int(os.environ.get("CHOKE_SMOKE_K", 16))
D_MIN = 3
CLUSTERS = 8
MSG_SLOTS = 64
ROUNDS = int(os.environ.get("CHOKE_SMOKE_ROUNDS", 84))
PUB_WIDTH = 4
#: sparse schedule: N_MSGS single publishes every 2 rounds from round
#: 3, then a long drain tail — BOTH latency cells must reach full
#: coverage (every live slot stamped at every peer) so the p95
#: comparison is uncensored; slot count stays under MSG_SLOTS (no
#: recycle, so first_round keeps every stamp)
N_MSGS = int(os.environ.get("CHOKE_SMOKE_MSGS", 12))
SIMS = int(os.environ.get("CHOKE_SMOKE_SIMS", 4))
SEED = 0
LOSS = 0.05

#: update-mode margin: floors commit at half the measured margin
MARGIN = 0.5

CHOKE = None  # RouterConfig knobs, filled in main (needs the import)


def _choke_knobs():
    from go_libp2p_pubsub_tpu.routers import RouterConfig

    return dict(choke_ema_alpha=0.4, choke_threshold=0.35,
                unchoke_threshold=0.1, choke_max_per_hb=2)


def _score_params():
    from go_libp2p_pubsub_tpu.config import (
        PeerScoreParams,
        TopicScoreParams,
    )

    return PeerScoreParams(
        topics={0: TopicScoreParams(mesh_message_deliveries_weight=0.0,
                                    mesh_failure_penalty_weight=0.0)},
        skip_app_specific=True,
    )


def run_cell(name: str, graphs, router=None, link_delay=None,
             edge_layout="dense", invariants=False):
    """One protocol cell: an S-sim ensemble run over the shared graph +
    schedule. Returns per-sim events, the delivery-latency plane, the
    compile sentinel and (optionally) the invariant report."""
    import jax
    import numpy as np

    from go_libp2p_pubsub_tpu import ensemble
    from go_libp2p_pubsub_tpu.chaos.faults import ChaosConfig
    from go_libp2p_pubsub_tpu.config import (
        GossipSubParams,
        PeerScoreThresholds,
    )
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSubConfig,
        GossipSubState,
        make_gossipsub_step,
    )
    from go_libp2p_pubsub_tpu.oracle import invariants as inv
    from go_libp2p_pubsub_tpu.state import Net

    topo_, subs, po, pt, pv = graphs
    net = Net.build(topo_, subs, edge_layout=edge_layout)
    sp = _score_params()
    # widened mcache window: with ring delays up to L rounds plus 5%
    # loss, a hole must still find a live IHAVE advertisement — the
    # default 3-heartbeat gossip window can expire first, leaving a
    # permanent (peer, msg) hole that would censor the p95 pairing
    cfg = GossipSubConfig.build(
        GossipSubParams(history_length=12, history_gossip=8),
        PeerScoreThresholds(), score_enabled=True,
        chaos=ChaosConfig(generator="iid", loss_rate=LOSS),
        router=router, edge_layout=edge_layout)
    st0 = GossipSubState.init(net, MSG_SLOTS, cfg, score_params=sp,
                              seed=SEED)
    step = make_gossipsub_step(cfg, net, score_params=sp,
                               link_delay=link_delay)
    ens = ensemble.lift_step(jax.jit(step, donate_argnums=0))
    states = ensemble.batch_states(st0, SIMS)

    hook = None
    if invariants:
        hook = inv.InvariantHook(
            "gossipsub", net, cfg,
            inv.InvariantConfig(check_every=8, delivery_window=48),
            due_fn=lambda tick: inv.due_vector(quiet=(0, ROUNDS)))

    xs_fn = lambda i: (ensemble.tile(po[i], SIMS),
                       ensemble.tile(pt[i], SIMS),
                       ensemble.tile(pv[i], SIMS))
    t0 = time.perf_counter()
    run = ensemble.run_rounds(ens, states, xs_fn, ROUNDS,
                              invariants=hook)
    wall = time.perf_counter() - t0

    core = run.states.core
    events = np.asarray(core.events)             # [S, N_EVENTS]
    fr = np.asarray(core.dlv.first_round)        # [S, N, M]
    birth = np.asarray(core.msgs.birth)          # [S, M]
    lat = fr - birth[:, None, :]
    lat_mask = (fr >= 0) & (birth[:, None, :] >= 0)
    out = {
        "name": name,
        "events": events,
        "lat": lat,
        "lat_mask": lat_mask,
        "first_round": fr,
        "wall_s": round(wall, 3),
        "compiles": int(run.compiles),
    }
    if hook is not None:
        out["invariants"] = hook.report()
    return out


def _per_sim(events, ev):
    return [int(x) for x in events[:, ev]]


def _lat_p95(cell):
    """Pooled delivery-latency p95 per sim (rounds from publish to
    first receipt, over every delivered (peer, msg) pair)."""
    import numpy as np

    out = []
    for s in range(cell["lat"].shape[0]):
        v = cell["lat"][s][cell["lat_mask"][s]]
        out.append(float(np.percentile(v, 95)) if v.size else -1.0)
    return out


def run_smoke() -> dict:
    import numpy as np

    from go_libp2p_pubsub_tpu import graph, topo
    from go_libp2p_pubsub_tpu.routers import RouterConfig
    from go_libp2p_pubsub_tpu.trace.events import EV

    el = topo.powerlaw(N, d_min=D_MIN, max_degree=MAX_DEGREE, seed=SEED)
    el = topo.attach_latency_classes(el, n_clusters=CLUSTERS)
    topo_ = topo.to_topology(el)
    subs = graph.subscribe_all(N, 1)
    delay, L = topo.link_delay_plane(el, topo_)
    rng = np.random.default_rng(1)
    po = np.full((ROUNDS, PUB_WIDTH), -1, np.int32)
    pt = np.zeros((ROUNDS, PUB_WIDTH), np.int32)
    pv = np.zeros((ROUNDS, PUB_WIDTH), bool)
    for i in range(N_MSGS):
        r = 3 + 2 * i
        po[r, 0] = rng.integers(0, N)
        pv[r, 0] = True
    graphs = (topo_, subs, po, pt, pv)

    knobs = _choke_knobs()
    r_b = RouterConfig(idontwant=True)
    r_d = RouterConfig(idontwant=True, latency_rounds=L)
    r_c = RouterConfig(idontwant=True, latency_rounds=L, choke=True,
                       **knobs)

    a = run_cell("v1.1", graphs)
    b = run_cell("v1.2_idontwant", graphs, router=r_b)
    d = run_cell("v1.2_ring", graphs, router=r_d, link_delay=delay)
    c = run_cell("v1.2_ring_choke", graphs, router=r_c, link_delay=delay,
                 invariants=True)
    c_csr = run_cell("v1.2_ring_choke_csr", graphs, router=r_c,
                     link_delay=delay, edge_layout="csr")

    dup_a = np.asarray(_per_sim(a["events"], EV.DUPLICATE_MESSAGE), float)
    dup_b = np.asarray(_per_sim(b["events"], EV.DUPLICATE_MESSAGE), float)
    dlv_a = np.asarray(_per_sim(a["events"], EV.DELIVER_MESSAGE), float)
    dup_ratio_a = (dup_a / np.maximum(dlv_a, 1)).round(4)
    dup_ratio_b = (dup_b / np.maximum(dlv_a, 1)).round(4)
    # paired comparison over the COMMON delivered support: a (peer, msg)
    # hole in one cell (all mesh pushes lost at a peer with no non-mesh
    # in-edges — no IHAVE can reach it; protocol-faithful) must not
    # censor the other cell's tail, so both p95s pool exactly the pairs
    # both cells delivered, and the coverage floors below keep that
    # support honest (>= 99% of every sim's (peer, msg) plane)
    common = c["lat_mask"] & d["lat_mask"]
    p95_c = _lat_p95({"lat": c["lat"], "lat_mask": common})
    p95_d = _lat_p95({"lat": d["lat"], "lat_mask": common})

    rep = c.pop("invariants")
    res = {
        "n_peers": N,
        "max_degree": MAX_DEGREE,
        "n_edges": int(len(el.edges)),
        "latency_classes": [int(x)
                            for x in np.bincount(el.link_class,
                                                 minlength=3)],
        "ring_depth": int(L),
        "rounds": ROUNDS,
        "n_sims": SIMS,
        "workload": f"sparse_{N_MSGS}_publishes",
        "loss_rate": LOSS,
        "choke_knobs": knobs,
        "cells": {},
        "dup_ratio_v11_per_sim": dup_ratio_a.tolist(),
        "dup_ratio_v12_per_sim": dup_ratio_b.tolist(),
        "dup_cut_per_sim": [round(float(x), 4)
                            for x in 1.0 - dup_b / np.maximum(dup_a, 1)],
        "p95_latency_choke_per_sim": p95_c,
        "p95_latency_nochoke_per_sim": p95_d,
        "tail_cut": round(1.0 - (float(np.mean(p95_c))
                                 / max(float(np.mean(p95_d)), 1e-9)), 4),
        "coverage_choke_per_sim": [
            round(float(m.sum()) / (N_MSGS * N), 4) for m in c["lat_mask"]],
        "coverage_nochoke_per_sim": [
            round(float(m.sum()) / (N_MSGS * N), 4) for m in d["lat_mask"]],
        "common_support_per_sim": [
            round(float(m.sum()) / (N_MSGS * N), 4) for m in common],
        "first_round_exact_v12": bool(
            np.array_equal(a["first_round"], b["first_round"])),
        "csr_counters_exact": bool(
            np.array_equal(c["events"], c_csr["events"])),
        "invariants": {
            "all_ok": bool(rep.all_ok),
            "checked": int(rep.checked),
            "violated": int(rep.violated),
            "properties": list(rep.names),
        },
    }
    for cell in (a, b, d, c, c_csr):
        res["cells"][cell["name"]] = {
            "wall_s": cell["wall_s"],
            "compiles": cell["compiles"],
            "delivered_per_sim": _per_sim(cell["events"],
                                          EV.DELIVER_MESSAGE),
            "duplicates_per_sim": _per_sim(cell["events"],
                                           EV.DUPLICATE_MESSAGE),
            "rpc_per_sim": _per_sim(cell["events"], EV.SEND_RPC),
            "idontwant_per_sim": _per_sim(cell["events"],
                                          EV.IDONTWANT_SENT),
            "suppressed_per_sim": _per_sim(cell["events"],
                                           EV.DUP_SUPPRESSED),
            "chokes_per_sim": _per_sim(cell["events"], EV.CHOKE),
            "unchokes_per_sim": _per_sim(cell["events"], EV.UNCHOKE),
        }
    return res


def gate(res: dict) -> list[str]:
    import numpy as np

    failures = []
    cells = res["cells"]
    a = cells["v1.1"]
    b = cells["v1.2_idontwant"]
    c = cells["v1.2_ring_choke"]
    d = cells["v1.2_ring"]

    # 1. v1.2 exactness anchor, per sim
    if a["delivered_per_sim"] != b["delivered_per_sim"]:
        failures.append(
            "v1.2 changed WHAT was delivered: per-sim deliveries "
            f"{b['delivered_per_sim']} != v1.1 {a['delivered_per_sim']}")
    if not res["first_round_exact_v12"]:
        failures.append("v1.2 moved a first_round stamp — suppression "
                        "must only remove duplicate traffic")
    dup_pairs = list(zip(a["duplicates_per_sim"], b["duplicates_per_sim"]))
    if not all(db < da for da, db in dup_pairs):
        failures.append(
            f"duplicate cut not strict on every sim: v1.1 vs v1.2 "
            f"duplicates {dup_pairs}")
    if not all(x > 0 for x in b["idontwant_per_sim"]):
        failures.append("a v1.2 sim announced nothing (IDONTWANT_SENT=0)")
    for da, db, sa, sb in zip(a["duplicates_per_sim"],
                              b["duplicates_per_sim"],
                              a["rpc_per_sim"], b["rpc_per_sim"]):
        if sa - sb != da - db:
            failures.append(
                f"RPC drop {sa - sb} != duplicate drop {da - db} — "
                "suppression removed non-duplicate traffic")
            break

    # 2. choke latency-tail cut at equal delivery
    for tag in ("coverage_choke_per_sim", "coverage_nochoke_per_sim",
                "common_support_per_sim"):
        if min(res[tag]) < 0.99:
            failures.append(
                f"{tag} {res[tag]} below 0.99 — a latency cell did not "
                "drain to (near-)full coverage; the paired p95 "
                "comparison would be censored (grow ROUNDS)")
    if not all(x > 0 for x in c["chokes_per_sim"]):
        failures.append(
            f"a sim choked nothing ({c['chokes_per_sim']}) — the "
            "lateness EMA never crossed the threshold; vacuous cell")
    if res["tail_cut"] <= 0.0:
        failures.append(
            f"choking did not cut the latency tail: p95 choke "
            f"{res['p95_latency_choke_per_sim']} vs no-choke "
            f"{res['p95_latency_nochoke_per_sim']}")

    # 3. invariants (choke properties armed, zero violations)
    iv = res["invariants"]
    for prop in ("choke-wf", "no-choke-below-dlo"):
        if prop not in iv["properties"]:
            failures.append(f"invariant hook ran without {prop}")
    if not iv["checked"]:
        failures.append("invariant hook checked nothing (vacuous gate)")
    if not iv["all_ok"]:
        failures.append(f"invariant violations on the choked cell: "
                        f"{iv['violated']}")

    # 4. one compile per cell + layout parity
    compiles = {k: v["compiles"] for k, v in cells.items()}
    if -1 in compiles.values():
        print("choke-smoke: one-compile sentinel UNAVAILABLE — gate "
              "skipped")
    elif any(v != 1 for v in compiles.values()):
        failures.append(f"one-compile sentinel: {compiles}")
    if not res["csr_counters_exact"]:
        failures.append("CSR arm counters differ from dense — the "
                        "layout changed WHAT, not just how")
    if any(x <= 0 for x in a["delivered_per_sim"]):
        failures.append("a v1.1 sim delivered nothing — dead wire")
    return failures


def check_census(failures: list) -> dict:
    """Router-off structural leg: the chaos-off compiled kernel census
    must still equal the on-image baseline (chaos_report leg, reused
    like churn-smoke does) — the router plane is opt-in."""
    from chaos_report import check_census as _chaos_census

    census = _chaos_census()
    if not census["equal"]:
        failures.append(
            f"census: router-off kernel census {census['total']} != "
            f"on-image baseline {census['on_image']} — the router "
            "plane leaked kernels into the off build")
    return census


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    # the census leg (and therefore every committed number here) is
    # defined under the bench PRNG, like churn-smoke
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")

    from go_libp2p_pubsub_tpu.compile_cache import enable_persistent_cache

    enable_persistent_cache(os.path.join(REPO, ".jax_cache"))

    res = run_smoke()
    failures = gate(res)
    if not os.environ.get("CHOKE_SMOKE_NO_CENSUS"):
        res["census"] = check_census(failures)
    print(json.dumps(res, indent=1, sort_keys=True))

    update = bool(os.environ.get("CHOKE_SMOKE_UPDATE"))
    shape_keys = ("n_peers", "max_degree", "rounds", "n_sims",
                  "workload", "loss_rate")
    if update or not os.path.exists(BASELINE_PATH):
        if failures:
            print("choke-smoke: FAIL (refusing to baseline a broken "
                  "run):")
            for f in failures:
                print("  -", f)
            return 1
        dup_cut = min(res["dup_cut_per_sim"])
        baseline = {
            "note": ("choke-smoke baseline (scripts/choke_smoke.py; "
                     "CHOKE_SMOKE_UPDATE=1 rewrites)"),
            **{k: res[k] for k in shape_keys},
            "ring_depth": res["ring_depth"],
            # the committed floors: half the measured margin
            "dup_cut_floor": round(dup_cut * MARGIN, 4),
            "tail_cut_floor": round(res["tail_cut"] * MARGIN, 4),
            # the v1.1 pin: router growth must never move router-off
            # behavior (bit-exact per-sim counters)
            "v11_pin": {k: res["cells"]["v1.1"][k]
                        for k in ("delivered_per_sim",
                                  "duplicates_per_sim", "rpc_per_sim")},
            "measured": {
                "dup_cut_per_sim": res["dup_cut_per_sim"],
                "tail_cut": res["tail_cut"],
                "p95_choke": res["p95_latency_choke_per_sim"],
                "p95_nochoke": res["p95_latency_nochoke_per_sim"],
            },
        }
        with open(BASELINE_PATH, "w") as f:
            json.dump(baseline, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"choke-smoke: wrote {BASELINE_PATH}")
        return 0

    with open(BASELINE_PATH) as f:
        base = json.load(f)
    mismatched = [k for k in shape_keys if res[k] != base.get(k)]
    if not mismatched:
        if min(res["dup_cut_per_sim"]) < base["dup_cut_floor"]:
            failures.append(
                f"duplicate cut {min(res['dup_cut_per_sim'])} below the "
                f"committed floor {base['dup_cut_floor']}")
        if res["tail_cut"] < base["tail_cut_floor"]:
            failures.append(
                f"latency tail cut {res['tail_cut']} below the "
                f"committed floor {base['tail_cut_floor']}")
        pin = base.get("v11_pin") or {}
        for k, v in pin.items():
            if res["cells"]["v1.1"][k] != v:
                failures.append(
                    f"v1.1 pin broke: {k} {res['cells']['v1.1'][k]} != "
                    f"committed {v} — router growth moved router-off "
                    "behavior")
    else:
        print("choke-smoke: NOTE — run shape differs from the committed "
              "baseline on %s; floor/pin gates SKIPPED (pairing + "
              "invariant + census gates still apply)" % mismatched)

    if failures:
        print("choke-smoke: FAIL")
        for f in failures:
            print("  -", f)
        return 1
    print("choke-smoke: PASS — v1.2 dup cut per sim %s at bit-exact "
          "delivery; choke p95 tail cut %.3f (%s -> %s); invariants "
          "green (%d checks); per-cell compiles %s; CSR parity exact"
          % (res["dup_cut_per_sim"], res["tail_cut"],
             res["p95_latency_nochoke_per_sim"],
             res["p95_latency_choke_per_sim"],
             res["invariants"]["checked"],
             {k: v["compiles"] for k, v in res["cells"].items()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
